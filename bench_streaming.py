"""Streaming micro-batch benchmark (BASELINE.json configs[4]).

Prints ONE JSON line: steady-state micro-batch throughput through
StreamingDBSCAN.update on the live backend, jit-cache reuse evidence
(XLA compile count per batch, via jax_log_compiles), and identity
stability (engineered persistent blobs must keep their stream ids
across every update).

Workload: K persistent hotspots + per-batch noise, all batches the same
size so the static bucket ladder (parallel/binning.py) repeats shapes —
steady-state updates must hit the jit cache (0 compiles) after the first
batch compiles the rungs.

Env knobs: BENCH_STREAM_BATCH (points per micro-batch, default 200k),
BENCH_STREAM_BATCHES (default 10), BENCH_STREAM_MAXPP (default 65536),
BENCH_STREAM_WINDOW (default 3).
"""

import json
import logging
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

EPS = 0.35
MIN_POINTS = 10
K = 64


def make_batch(rng, n: int):
    """One micro-batch: 90% points from the K persistent hotspots (known
    membership), 10% fresh uniform noise."""
    gx = int(np.ceil(np.sqrt(K)))
    centers = np.stack(
        np.meshgrid(np.arange(gx) * 4.0, np.arange(gx) * 4.0), -1
    ).reshape(-1, 2)[:K]
    n_blob = n * 9 // 10
    blob_of = rng.integers(0, K, n_blob)
    pts = np.concatenate(
        [
            centers[blob_of] + rng.normal(0, 0.1, (n_blob, 2)),
            rng.uniform(-2, gx * 4.0, (n - n_blob, 2)),
        ]
    )
    return pts, blob_of, n_blob


class _CompileCounter(logging.Handler):
    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if "Compiling" in record.getMessage():
            self.count += 1


def main() -> None:
    batch_n = int(os.environ.get("BENCH_STREAM_BATCH", "200000"))
    n_batches = int(os.environ.get("BENCH_STREAM_BATCHES", "10"))
    maxpp = int(os.environ.get("BENCH_STREAM_MAXPP", "65536"))
    window = int(os.environ.get("BENCH_STREAM_WINDOW", "3"))

    import jax

    jax.config.update("jax_log_compiles", True)
    counter = _CompileCounter()
    logging.getLogger("jax._src.dispatch").addHandler(counter)
    logging.getLogger("jax._src.interpreters.pxla").addHandler(counter)

    from dbscan_tpu.streaming import StreamingDBSCAN

    rng = np.random.default_rng(7)
    stream = StreamingDBSCAN(
        EPS, MIN_POINTS, max_points_per_partition=maxpp, window=window
    )

    walls, compiles, blob_ids = [], [], []
    stable = True
    for b in range(n_batches):
        pts, blob_of, n_blob = make_batch(rng, batch_n)
        c0 = counter.count
        t0 = time.perf_counter()
        upd = stream.update(pts)
        walls.append(time.perf_counter() - t0)
        compiles.append(counter.count - c0)
        # identity stability: each hotspot's majority stream id (resolved
        # through the union-find) must never change once assigned
        labels = stream.resolve(upd.clusters[:n_blob])
        ids_now = np.zeros(K, dtype=np.int64)
        for k in range(K):
            lk = labels[blob_of == k]
            lk = lk[lk > 0]
            if len(lk):
                ids_now[k] = np.bincount(lk).argmax()
        if blob_ids:
            prev = blob_ids[-1]
            both = (prev > 0) & (ids_now > 0)
            if not np.array_equal(
                stream.resolve(prev[both]), stream.resolve(ids_now[both])
            ):
                stable = False
        blob_ids.append(ids_now)

    # steady state = batches that hit the jit cache completely (the first
    # `window` batches keep growing the window skeleton, which changes
    # the padded N and compiles new ladder rungs until it saturates)
    steady = [w for w, c in zip(walls, compiles) if c == 0] or walls[-1:]
    steady_s = float(np.median(steady))
    out = {
        "metric": "dbscan_streaming_microbatch_throughput",
        "value": round(batch_n / steady_s / 1e6, 4),
        "unit": "Mpoints/s",
        "backend": jax.default_backend(),
        "batch_points": batch_n,
        "n_batches": n_batches,
        "window": window,
        "maxpp": maxpp,
        "batch_walls_s": [round(w, 3) for w in walls],
        "compiles_per_batch": compiles,
        "steady_state_compiles": int(sum(compiles[2:])),
        "identity_stable": bool(stable),
        "first_batch_s": round(walls[0], 3),
        "steady_batch_s": round(steady_s, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
