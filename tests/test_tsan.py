"""graftcheck runtime thread sanitizer (dbscan_tpu/lint/tsan.py).

Pins, per the PR acceptance bar:

- the sanitizer's detectors themselves: lockset races (two threads, no
  common lock, at least one write), lock-order inversions, condition
  wait/reacquire bookkeeping, and the strict disabled-path no-op;
- the races the static rules surfaced and this PR FIXED stay fixed:
  ``faults.get_registry`` / ``_native.lib`` / ``obs.memory.available``
  singletons hammered from many threads return one object each and
  record no race under the live sanitizer (regression tests);
- the static/dynamic contract: a real pipelined banded train run under
  the sanitizer records a worker access set CONTAINED IN the static
  worker-slice model (``lint.races.worker_tsan_sites``) — divergence
  means the static model went stale and IS the test failure;
- the tier-1 rerun: the pipeline + fault suites pass under
  ``DBSCAN_TSAN=1`` with an EMPTY race/inversion report
  (``DBSCAN_TSAN_REPORT`` JSON, asserted from outside the process).
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from dbscan_tpu import Engine, faults, obs, train
from dbscan_tpu.lint import tsan
from dbscan_tpu.parallel import pipeline as pipe_mod

pytestmark = pytest.mark.tsan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dbscan_tpu")


@pytest.fixture
def rt():
    """A fresh, enabled sanitizer runtime; always disabled after."""
    tsan.enable()
    tsan.reset()
    yield tsan
    tsan.disable()


def _in_threads(n, fn):
    errs = []

    def run(i):
        try:
            fn(i)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert errs == []


# --- detector unit tests ----------------------------------------------


def test_disabled_path_is_noop():
    tsan.disable()
    assert not tsan.enabled()
    tsan.access("nothing.recorded")  # must not raise or allocate state
    rep = tsan.report()
    assert rep["enabled"] is False
    assert rep["accesses"] == {} and rep["races"] == []
    tsan.assert_clean()  # empty report is clean


def test_unsynchronized_cross_thread_write_is_a_race(rt):
    _in_threads(2, lambda i: tsan.access("t.bare"))
    rep = tsan.report()
    assert [r["site"] for r in rep["races"]] == ["t.bare"]
    assert len(rep["races"][0]["threads"]) == 2
    with pytest.raises(AssertionError, match="t.bare"):
        tsan.assert_clean()


def test_lock_protected_access_is_clean(rt):
    lk = tsan.lock("t.lk")

    def body(i):
        with lk:
            tsan.access("t.guarded")

    _in_threads(4, body)
    rep = tsan.report()
    assert rep["races"] == []
    assert rep["accesses"]["t.guarded"]["lockset"] == ["t.lk"]
    assert len(rep["accesses"]["t.guarded"]["threads"]) == 4


def test_single_thread_unlocked_is_not_a_race(rt):
    for _ in range(5):
        tsan.access("t.solo")
    assert tsan.report()["races"] == []


def test_read_only_cross_thread_is_not_a_race(rt):
    _in_threads(3, lambda i: tsan.access("t.ro", write=False))
    assert tsan.report()["races"] == []


def test_broken_locked_suffix_convention_is_caught(rt):
    """The static rule trusts `_locked`-suffix helpers; the sanitizer
    is the layer that catches a caller breaking the convention — the
    access records an empty lockset and races once a second thread
    arrives."""
    lk = tsan.lock("t.outer")

    def good(i):
        with lk:
            tsan.access("t.conv")

    def bad(i):
        tsan.access("t.conv")  # forgot the lock

    _in_threads(2, good)
    assert tsan.report()["races"] == []
    _in_threads(1, bad)  # same (main-spawned) thread names differ
    rep = tsan.report()
    assert [r["site"] for r in rep["races"]] == ["t.conv"]


def test_lock_order_inversion_detected(rt):
    a, b = tsan.lock("t.A"), tsan.lock("t.B")
    with a:
        with b:
            pass
    assert tsan.report()["lock_inversions"] == []
    with b:
        with a:
            pass
    inv = tsan.report()["lock_inversions"]
    assert len(inv) == 1 and inv[0]["locks"] == ["t.A", "t.B"]
    with pytest.raises(AssertionError):
        tsan.assert_clean()


def test_condition_wait_releases_and_reacquires(rt):
    cv = tsan.condition("t.cv")
    hit = []

    def waiter(i):
        with cv:
            tsan.access("t.cv_state")
            cv.wait(timeout=5)
            tsan.access("t.cv_state")
            hit.append(i)

    t = threading.Thread(target=waiter, args=(0,))
    t.start()
    import time

    time.sleep(0.05)
    with cv:
        tsan.access("t.cv_state")
        cv.notify_all()
    t.join()
    rep = tsan.report()
    assert hit == [0]
    assert rep["races"] == []
    # both sides held the cv at every access
    assert rep["accesses"]["t.cv_state"]["lockset"] == ["t.cv"]
    assert rep["lock_inversions"] == []


def test_report_write_and_reset(rt, tmp_path):
    tsan.access("t.x")
    path = tsan.write_report(str(tmp_path / "rep.json"))
    rep = json.load(open(path))
    assert rep["enabled"] and "t.x" in rep["accesses"]
    tsan.reset()
    assert tsan.report()["accesses"] == {}


def test_emitted_telemetry_names_are_declared(rt):
    from dbscan_tpu.obs import schema

    _in_threads(2, lambda i: tsan.access("t.bad"))
    obs.disable()
    st = obs.enable()
    try:
        tsan.emit_telemetry()
        counters = st.metrics.counters()
        assert counters["tsan.races"] == 1
        assert counters["tsan.accesses"] >= 2
        for name in counters:
            if name.startswith("tsan."):
                assert schema.is_declared("counter", name), name
    finally:
        obs.disable()


# --- regression tests for the races the static rules surfaced ----------


def test_get_registry_is_one_object_across_threads(rt, monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:TRANSIENT")
    faults.reset_registry()
    got = []
    barrier = threading.Barrier(8)

    def grab(i):
        barrier.wait()
        got.append(faults.get_registry())

    _in_threads(8, grab)
    assert len({id(r) for r in got}) == 1
    assert got[0].active
    rep = tsan.report()
    assert rep["races"] == []
    assert rep["accesses"]["faults.registry_state"]["lockset"] == [
        "faults.registry_state"
    ]
    faults.reset_registry()


def test_native_lib_load_is_single_and_clean(rt):
    from dbscan_tpu import _native

    got = []
    barrier = threading.Barrier(8)

    def grab(i):
        barrier.wait()
        got.append(_native.lib())

    _in_threads(8, grab)
    assert len({id(x) for x in got}) == 1
    assert tsan.report()["races"] == []


def test_memory_available_latch_is_clean(rt):
    from dbscan_tpu.obs import memory as obs_memory

    obs_memory.reset_peak()
    barrier = threading.Barrier(8)

    def probe(i):
        barrier.wait()
        obs_memory.available()

    _in_threads(8, probe)
    assert tsan.report()["races"] == []
    obs_memory.reset_peak()


def test_fault_counters_concurrent_adds_exact_and_clean(rt):
    snap = faults.counters.snapshot()
    _in_threads(8, lambda i: [faults.counters.add("attempts")
                              for _ in range(250)])
    delta = faults.counters.delta(snap)
    assert delta["attempts"] == 2000
    rep = tsan.report()
    assert rep["races"] == []
    assert rep["accesses"]["faults.counters"]["lockset"] == [
        "faults.counters"
    ]


# --- the static/dynamic contract --------------------------------------


def _blobs(seed=0):
    rng = np.random.default_rng(seed)
    pts = np.concatenate(
        [
            rng.normal(c, 0.4, (s, 2))
            for c, s in zip(
                [(0, 0), (8, 8), (-7, 9), (9, -8)], [200, 500, 900, 400]
            )
        ]
    )
    rng.shuffle(pts)
    return pts


def test_worker_access_set_contained_in_static_model(rt, monkeypatch):
    """THE acceptance contract: run a real pipelined banded train under
    the sanitizer (obs enabled so the telemetry registries record too),
    then assert every site the pull worker touched is in the static
    worker-slice model. A new worker-side shared-state touch without a
    model update fails here."""
    from dbscan_tpu import lint as lint_mod
    from dbscan_tpu.lint import races
    from dbscan_tpu.lint.core import load_package, run_rules

    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    faults.reset_registry()
    pipe_mod.reset_engine()  # rebuild the engine under the live sanitizer
    obs.disable()
    obs.enable()
    try:
        out = train(
            _blobs(),
            eps=0.5,
            min_points=5,
            max_points_per_partition=256,
            engine=Engine.ARCHERY,
            neighbor_backend="banded",
        )
        assert out.stats["pull"]["jobs"] > 0, "run was not pipelined"
    finally:
        obs.disable()
        pipe_mod.reset_engine()
    observed = tsan.worker_sites()
    assert observed, "worker recorded no tsan sites"
    assert "pipeline.engine" in observed
    pkg = load_package([PKG])
    run_rules(pkg, (), lint_mod.RULES)
    model = races.worker_tsan_sites(pkg)
    assert observed <= model, (
        f"worker touched sites outside the static model: "
        f"{sorted(observed - model)} (model: {sorted(model)})"
    )
    tsan.assert_clean()


def test_pipeline_and_fault_suites_race_free_under_tsan(tmp_path):
    """Tier-1 rerun of the pipeline + fault suites with DBSCAN_TSAN=1:
    the suites must pass AND the atexit JSON report must show zero
    races and zero lock-order inversions."""
    report = tmp_path / "tsan_report.json"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_TSAN": "1",
        "DBSCAN_TSAN_REPORT": str(report),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(REPO, "tests", "test_pipeline.py"),
            os.path.join(REPO, "tests", "test_faults.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    rep = json.loads(report.read_text())
    assert rep["enabled"] is True
    assert rep["races"] == [], rep["races"]
    assert rep["lock_inversions"] == [], rep["lock_inversions"]
    # the suites exercised real cross-thread traffic, not a no-op run
    assert rep["naccesses"] > 100
    worker_threads = {
        t
        for site in rep["accesses"].values()
        for t in site["threads"]
        if t.startswith("dbscan-pull")
    }
    assert worker_threads, "no pull-worker activity recorded"
