"""Real multi-process (DCN-analog) execution of the distributed driver.

VERDICT r3 item 4: the single-process 8-virtual-device mesh exercises
GSPMD partitioning but NOT the multi-process data plane — per-process
addressable shards, cross-host gathers, replicated host phases. This
suite spawns TWO actual `jax.distributed` CPU processes (4 virtual
devices each, gloo TCP collectives), runs the full banded + dense
pipelines over the combined 8-device mesh, and pins label identity
against the single-process run — the reference's real executor fan-out
(DBSCAN.scala:150-154) exercised as processes, not threads.

The child re-executes THIS file (``python test_multihost.py <pid> ...``);
the pytest entry spawns both children and compares artifacts.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest


def _dataset():
    rng = np.random.default_rng(1234)
    return np.concatenate(
        [rng.normal(c, 0.5, (1200, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-9, 11, (600, 2))]
    )


TRAIN_KW = dict(eps=0.3, min_points=6, max_points_per_partition=600)


def _child_main(pid: int, port: int, out_path: str) -> None:
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from dbscan_tpu.parallel.mesh import initialize_multihost

    mesh = initialize_multihost(f"localhost:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert len(jax.local_devices()) == 4

    from dbscan_tpu import Engine, train

    pts = _dataset()
    results = {}
    for name, extra in [
        ("banded", {"neighbor_backend": "banded"}),
        ("dense", {"neighbor_backend": "dense"}),
    ]:
        m = train(pts, engine=Engine.NAIVE, mesh=mesh, **extra, **TRAIN_KW)
        results[f"{name}_clusters"] = m.clusters
        results[f"{name}_flags"] = m.flags
        results[f"{name}_nparts"] = np.int64(m.stats["n_partitions"])
        # collective-aware pulls (PR 12): the engine no longer disables
        # itself under multi-process — every pull rides it at its
        # submission point, so stats["pull"] exists PER SHARD here
        pull = m.stats.get("pull")
        assert pull is not None and pull["jobs"] > 0, (name, m.stats)
        results[f"{name}_pull_jobs"] = np.int64(pull["jobs"])
    if pid == 0:
        np.savez(out_path, **results)


def test_two_process_mesh_matches_single_process(tmp_path):
    import socket

    # let the OS pick a free port (a hardcoded one collides with
    # concurrent runs or stale children); the tiny close->reuse window
    # is the standard benign race
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    out_path = os.path.join(tmp_path, "mp.npz")
    env_base = dict(os.environ)
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # strip sitecustomize-bearing plugin paths (the tunneled-TPU plugin
    # initializes a PJRT client at import, which would pre-empt
    # jax.distributed.initialize in the children) — the same filter
    # bench.py's CPU re-exec applies
    keep = [
        p
        for p in env_base.get("PYTHONPATH", "").split(os.pathsep)
        if p
        and p != repo
        and not os.path.exists(os.path.join(p, "sitecustomize.py"))
    ]
    env_base["PYTHONPATH"] = os.pathsep.join([repo] + keep)
    procs = []
    for pid in range(2):
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, os.path.abspath(__file__),
                    str(pid), str(port), out_path,
                ],
                env=env_base,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            p.kill()
    assert all(p.returncode == 0 for p in procs), (
        f"rc={[p.returncode for p in procs]}\n"
        + "\n--- child ---\n".join(o[-4000:] for o in outs)
    )
    mp = np.load(out_path)

    # single-process reference over the default (8-virtual-device) mesh
    from dbscan_tpu import Engine, train
    from dbscan_tpu.parallel.mesh import make_mesh

    pts = _dataset()
    for name, extra in [
        ("banded", {"neighbor_backend": "banded"}),
        ("dense", {"neighbor_backend": "dense"}),
    ]:
        ref = train(
            pts, engine=Engine.NAIVE, mesh=make_mesh(), **extra, **TRAIN_KW
        )
        assert ref.stats["n_partitions"] == int(mp[f"{name}_nparts"])
        np.testing.assert_array_equal(
            ref.clusters, mp[f"{name}_clusters"], err_msg=name
        )
        np.testing.assert_array_equal(
            ref.flags, mp[f"{name}_flags"], err_msg=name
        )


if __name__ == "__main__":
    _child_main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
