"""graftfault runtime cross-check (dbscan_tpu/lint/faultcheck.py).

The static fault-surface rules (tests/test_lint.py) reason about what a
``faults.supervised`` callable MAY mutate; this suite pins the runtime
half that watches what one actually DOES:

- window mechanics: per-thread window stacks, nested windows each
  recording, shard-suffixed sites aggregating per base site, and the
  strictly-empty disabled path;
- mutation containment: the observed per-site write fingerprint must be
  a subset of the static effect model's reachable tsan sites (plus the
  FAULTS_BASELINE the supervision machinery itself touches) — judged
  against a controlled model in units, against the REAL parsed model on
  a live faulted train;
- retry idempotence: an injected-transient drill's fingerprint equals
  the no-fault run's (the runtime twin of ``fault-retry-unsafe``), and
  the serve-ingest restore-prologue regression: a transient ingest
  fault applies the batch exactly once;
- the tier-1 rerun: the fault + pipeline suites pass under
  ``DBSCAN_FAULTCHECK=1`` with an EMPTY violation report
  (``DBSCAN_FAULTCHECK_REPORT`` JSON, asserted from outside).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import Engine, faults, train
from dbscan_tpu.lint import faultcheck
from dbscan_tpu.lint import tsan

pytestmark = pytest.mark.faultcheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NO_BACKOFF = faults.RetryPolicy(max_retries=3, backoff_base_s=0.0)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Each test gets a virgin checker, fault registry, and no sleeps;
    the process-cached static model is preserved (it is content-pure)."""
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    faultcheck.disable()
    yield
    faultcheck.disable()
    faults.reset_registry()


def _model(monkeypatch, table):
    """Pin the static model the checker judges against (units stay
    independent of the real repo's effect analysis)."""
    monkeypatch.setattr(faultcheck, "_static_cache", dict(table))


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


# --- window mechanics --------------------------------------------------


def test_disabled_is_a_noop():
    assert not faultcheck.enabled()
    # hooks are safe without a runtime (the one-truthiness-check path)
    faultcheck.begin("dispatch")
    faultcheck.note_access("anything")
    faultcheck.end("dispatch")
    rep = faultcheck.report()
    assert rep == {
        "enabled": False, "checks": 0, "sites": {}, "violations": [],
    }
    assert faultcheck.fingerprint("dispatch") == ()
    faultcheck.assert_clean()  # never raises when disabled


def test_window_records_contained_mutations(monkeypatch):
    _model(monkeypatch, {"dispatch": frozenset({"a.site", "b.site"})})
    rt = faultcheck.enable()
    faultcheck.begin("dispatch")
    faultcheck.note_access("a.site")
    faultcheck.end("dispatch")
    assert rt.checks == 1
    assert faultcheck.fingerprint("dispatch") == ("a.site",)
    rep = faultcheck.report()
    assert rep["sites"]["dispatch"] == {
        "calls": 1, "mutations": ["a.site"], "modeled": True, "extra": [],
    }
    faultcheck.assert_clean()


def test_uncontained_mutation_is_a_violation(monkeypatch):
    _model(monkeypatch, {"dispatch": frozenset({"a.site"})})
    faultcheck.enable()
    faultcheck.begin("dispatch")
    faultcheck.note_access("rogue.state")
    faultcheck.end("dispatch")
    rep = faultcheck.report()
    (viol,) = rep["violations"]
    assert viol["kind"] == "mutation-containment"
    assert viol["site"] == "dispatch"
    assert viol["extra"] == ["rogue.state"]
    with pytest.raises(AssertionError, match="rogue.state"):
        faultcheck.assert_clean()
    # re-reporting must not duplicate the violation (atexit re-snapshots)
    assert len(faultcheck.report()["violations"]) == 1


def test_faults_baseline_sites_always_allowed(monkeypatch):
    """The supervision machinery's own registry/counter writes inside a
    window are never evidence of a callable-side effect."""
    _model(monkeypatch, {"dispatch": frozenset()})
    faultcheck.enable()
    faultcheck.begin("dispatch")
    for site in faultcheck.FAULTS_BASELINE:
        faultcheck.note_access(site)
    faultcheck.end("dispatch")
    assert faultcheck.report()["violations"] == []


def test_unmodeled_site_skips_containment(monkeypatch):
    """A site whose supervised callable is not statically resolvable
    maps to None: recorded but not judged (the static rules already
    force a drill, so the gap stays visible there)."""
    _model(monkeypatch, {"serve_replica": None})
    faultcheck.enable()
    faultcheck.begin("serve_replica")
    faultcheck.note_access("router.replicas")
    faultcheck.end("serve_replica")
    rep = faultcheck.report()
    assert rep["sites"]["serve_replica"]["modeled"] is False
    assert rep["violations"] == []


def test_nested_windows_each_record(monkeypatch):
    """An inner supervised call's mutations land in the outer window
    too — the outer model reaches the inner callable transitively, so
    outer fingerprints must stay complete."""
    _model(monkeypatch, {
        "serve": frozenset({"x"}), "dispatch": frozenset({"x"}),
    })
    faultcheck.enable()
    faultcheck.begin("serve")
    faultcheck.begin("dispatch")
    faultcheck.note_access("x")
    faultcheck.end("dispatch")
    faultcheck.end("serve")
    assert faultcheck.fingerprint("serve") == ("x",)
    assert faultcheck.fingerprint("dispatch") == ("x",)
    assert faultcheck.report()["checks"] == 2


def test_shard_suffixed_sites_aggregate_per_base(monkeypatch):
    _model(monkeypatch, {"serve_replica": frozenset({"a", "b"})})
    faultcheck.enable()
    for shard, site in enumerate(("serve_replica", "serve_replica@1")):
        faultcheck.begin(site)
        faultcheck.note_access("ab"[shard])
        faultcheck.end(site)
    assert faultcheck.fingerprint("serve_replica") == ("a", "b")
    rep = faultcheck.report()
    assert rep["sites"]["serve_replica"]["calls"] == 2


def test_supervised_drives_the_window_hooks(monkeypatch):
    """faults.supervised opens/closes windows itself (attempt AND
    fallback), and tsan write accesses inside land in them."""
    _model(monkeypatch, {"dispatch": frozenset({"probe.state"})})
    faultcheck.enable()
    faults.supervised(
        "dispatch", lambda b: tsan.access("probe.state", write=True),
        policy=NO_BACKOFF,
    )
    assert faultcheck.fingerprint("dispatch") == ("probe.state",)
    # fallback path: a persistent fault runs the fallback in a window
    _spec(monkeypatch, "dispatch#1:PERSISTENT")
    faults.supervised(
        "dispatch", lambda b: None, policy=NO_BACKOFF,
        fallback=lambda: tsan.access("probe.state", write=True),
    )
    rep = faultcheck.report()
    assert rep["sites"]["dispatch"]["calls"] >= 2
    faultcheck.assert_clean()


def test_reads_are_not_mutations(monkeypatch):
    _model(monkeypatch, {"dispatch": frozenset()})
    faultcheck.enable()
    faults.supervised(
        "dispatch", lambda b: tsan.access("probe.state", write=False),
        policy=NO_BACKOFF,
    )
    assert faultcheck.fingerprint("dispatch") == ()
    faultcheck.assert_clean()


def test_write_report_and_env_activation(tmp_path):
    """DBSCAN_FAULTCHECK=1 turns recording on at import and the REPORT
    path receives the atexit JSON (checked in a subprocess so the env
    init path itself is exercised)."""
    report = tmp_path / "fc.json"
    code = (
        "from dbscan_tpu.lint import faultcheck\n"
        "assert faultcheck.enabled()\n"
        "faultcheck.begin('dispatch'); faultcheck.end('dispatch')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={
            **os.environ, "JAX_PLATFORMS": "cpu",
            "DBSCAN_FAULTCHECK": "1",
            "DBSCAN_FAULTCHECK_REPORT": str(report),
        },
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(report.read_text())
    assert rep["enabled"] is True and rep["checks"] == 1


def test_telemetry_deltas(monkeypatch):
    """faultcheck.* counters/events are declared and emitted as deltas
    (periodic publication never double-counts)."""
    from dbscan_tpu import obs

    _model(monkeypatch, {"dispatch": frozenset()})
    faultcheck.enable()
    faultcheck.begin("dispatch")
    faultcheck.note_access("rogue.state")
    faultcheck.end("dispatch")
    was = obs.active()
    obs.enable()
    try:
        snap = obs.counters()
        faultcheck.emit_telemetry()
        d1 = obs.counters_delta(snap)
        faultcheck.emit_telemetry()  # no new activity: zero delta
        d2 = obs.counters_delta(snap)
    finally:
        if not was:
            obs.disable()
    assert d1.get("faultcheck.checks", 0) == 1
    assert d1.get("faultcheck.violations", 0) == 1
    assert d2 == d1


# --- the real static model on live runs --------------------------------


def _blobs():
    rng = np.random.default_rng(3)
    return np.concatenate([
        rng.normal((0, 0), 0.4, (300, 2)),
        rng.normal((8, 8), 0.4, (300, 2)),
    ])


KW = dict(
    eps=0.5, min_points=5, max_points_per_partition=128,
    engine=Engine.ARCHERY, neighbor_backend="dense",
)


def _clean_fingerprints():
    """Observed per-site mutation sets minus the supervision baseline
    (injection bookkeeping differs between faulted and clean runs)."""
    rep = faultcheck.report()
    return {
        site: frozenset(rec["mutations"]) - faultcheck.FAULTS_BASELINE
        for site, rec in rep["sites"].items()
    }


def test_real_train_is_contained_in_the_static_model(monkeypatch):
    """A live faulted train's observed mutations are explained by the
    REAL parsed effect model — the two halves cross-check each other."""
    faultcheck.enable()
    _spec(monkeypatch, "dispatch#0:TRANSIENT")
    out = train(_blobs(), **KW)
    assert out.stats["faults"]["retries"] == 1
    rep = faultcheck.report()  # parses the package effect model
    assert rep["checks"] > 0 and "dispatch" in rep["sites"]
    assert rep["sites"]["dispatch"]["modeled"] is True
    assert rep["violations"] == [], rep["violations"]


def test_transient_drill_fingerprint_matches_no_fault_run(monkeypatch):
    """Retry idempotence, measured: the faulted run's per-site mutation
    fingerprint equals the no-fault run's."""
    pts = _blobs()
    faultcheck.enable()
    clean_out = train(pts, **KW)
    clean = _clean_fingerprints()
    faultcheck.reset()
    _spec(monkeypatch, "dispatch#0:TRANSIENT*2")
    faulted_out = train(pts, **KW)
    assert faulted_out.stats["faults"]["retries"] == 2
    np.testing.assert_array_equal(
        clean_out.clusters, faulted_out.clusters
    )
    assert _clean_fingerprints() == clean
    faultcheck.assert_clean()


def test_serve_ingest_transient_applies_batch_once(monkeypatch):
    """Regression for the real fault-retry-unsafe finding: the serve
    ingest attempt re-enters from a snapshot, so a transient fault plus
    retry applies the batch EXACTLY once (epoch/update counters equal
    the no-fault run's, labels identical)."""
    from dbscan_tpu.serve import ClusterService

    rng = np.random.default_rng(5)
    batch = rng.normal((0, 0), 0.4, (200, 2))

    def run_service():
        svc = ClusterService(
            0.6, 5, window=2, max_points_per_partition=500
        )
        with svc:
            svc.submit(batch.copy())
            assert svc.drain(timeout=120)
            state = svc._stream.export_state()
            res = svc.query(batch[:20].copy())
        return state, res

    _spec(monkeypatch, "serve#0:TRANSIENT;serve#1:TRANSIENT")
    f_state, f_res = run_service()
    monkeypatch.delenv("DBSCAN_FAULT_SPEC")
    faults.reset_registry()
    c_state, c_res = run_service()
    assert f_state["scalars"] == c_state["scalars"]  # n_updates == 1
    for k, arr in c_state["arrays"].items():
        np.testing.assert_array_equal(f_state["arrays"][k], arr)
    np.testing.assert_array_equal(f_res.gids, c_res.gids)


# --- tier-1 rerun: the fault + pipeline suites under the checker -------


def test_fault_and_pipeline_suites_clean_under_faultcheck(tmp_path):
    """Tier-1 rerun of the fault + pipeline suites with
    DBSCAN_FAULTCHECK=1: the suites must pass AND the atexit JSON
    report must show zero containment violations. The nested
    distributed-suite smoke is deselected (it spawns its own
    subprocess sweeps; the drills here are the in-process ones)."""
    report = tmp_path / "faultcheck_report.json"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_FAULTCHECK": "1",
        "DBSCAN_FAULTCHECK_REPORT": str(report),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(REPO, "tests", "test_faults.py"),
            os.path.join(REPO, "tests", "test_pipeline.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
            "-k", "not distributed_suite",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    rep = json.loads(report.read_text())
    assert rep["enabled"] is True
    assert rep["violations"] == [], rep["violations"]
    # the suites exercised real supervised windows, not a no-op run
    assert rep["checks"] > 50
    assert "dispatch" in rep["sites"] and "stream" in rep["sites"]
    # drilled transient/persistent paths settled their windows too
    assert rep["sites"]["dispatch"]["calls"] > 10
