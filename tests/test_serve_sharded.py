"""dbscan_tpu/serve sharded+replicated: the distributed serving layer.

Pins the distributed serving contract (PARITY.md "Distributed serving
contract"):

- deterministic spatial shard routing (pure function of coordinates)
  and disjoint global id namespacing;
- direct sharded queries exactly matching the union-skeleton numpy
  oracle at the pinned consistent cut;
- the epoch-VECTOR consistent-cut property under genuinely concurrent
  multi-shard ingest (every pinned vector is a vector some publish
  actually produced — never a blend of two cuts) — fuzzed;
- bounded seqlock reads: a wedged publish starves readers into a
  DBSCAN_SERVE_READ_TIMEOUT_S error NAMING the stale shard, at both
  the per-shard and the cut level;
- shard-suffixed serve checkpoints: roundtrip, shard-count fingerprint
  refuse-and-warn, all-or-nothing partial-restore refusal;
- ``site@<shard>#N`` fault ordinal namespacing (bare = shard 0
  regression pin, ``*@N`` rejected, independent per-shard streams) and
  a shard-TARGETED ingest drill degrading only its shard;
- THE replica-kill acceptance drill: under a kill schedule taking every
  replica down, every accepted query completes oracle-exact for its
  pinned epoch vector — zero failed queries (failover chain ending in
  the host union oracle); transient faults heal without eviction;
- p99 load shedding via the declared serve.query family-model price
  (QueryShed as an admission refusal, shed_frac accounting);
- zero-recompile pin for the steady-state cut broadcast, and a
  DBSCAN_SHAPECHECK=1 live run validating serve.broadcast clean;
- the sharded SIGTERM subprocess drill: per-shard flight/checkpoint
  artifacts, then a replay()-resumed service answering byte-identical
  to an uninterrupted oracle run;
- registration/promotion/direction pins for the new telemetry,
  serve.broadcast family model, serve_shed_frac history promotion
  (unit ratio, regresses UP), and the committed BENCH_SERVE_r02.json
  gating green against bench/history.jsonl.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dbscan_tpu import faults
from dbscan_tpu.serve import (
    ClusterService,
    QueryRouter,
    QueryShed,
    ShardedClusterService,
    cut_query_host,
    shard_of,
)
from dbscan_tpu.serve import query as query_mod
from dbscan_tpu.serve import router as router_mod
from dbscan_tpu.serve import sharded as sharded_mod

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPS, MINPTS = 0.6, 5


@pytest.fixture(autouse=True)
def _fresh_faults(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    yield
    faults.reset_registry()


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


def _blob(rng, center, n=60, s=0.25):
    return rng.normal(center, s, size=(n, 2))


def _batches(seed, k=4, n=70):
    """k micro-batches spanning well-separated centers so every batch
    slices onto multiple shards."""
    rng = np.random.default_rng(seed)
    centers = [(0, 0), (5, 0), (0, 5), (5, 5), (-4, 2), (2, -4)]
    return [
        np.concatenate([_blob(rng, c, n=n) for c in centers])
        for _ in range(k)
    ]


def _svc(n_shards=2, **kw):
    kw.setdefault("window", 2)
    kw.setdefault("max_points_per_partition", 500)
    return ShardedClusterService(EPS, MINPTS, n_shards=n_shards, **kw)


# --- routing + namespacing --------------------------------------------


def test_shard_routing_deterministic_partition(rng):
    pts = rng.uniform(-20, 20, size=(5000, 2))
    for n in (1, 2, 3, 7):
        a = shard_of(pts, EPS, n)
        b = shard_of(pts, EPS, n)
        np.testing.assert_array_equal(a, b)  # pure function
        assert a.min() >= 0 and a.max() < n
        if n > 1:
            assert len(np.unique(a)) == n  # all shards actually used
    # cell-level: points in the same 8*eps cell always co-locate
    cell = np.floor(pts / (8.0 * EPS))
    sh = shard_of(pts, EPS, 3)
    for c in np.unique(cell, axis=0)[:20]:
        mask = (cell == c[None, :]).all(axis=1)
        assert len(np.unique(sh[mask])) == 1


def test_namespace_sids_disjoint_and_invertible():
    ns = sharded_mod.namespace_sids
    a = ns(np.array([1, 2, 3, 0]), 0, 3)
    b = ns(np.array([1, 2, 3, 0]), 1, 3)
    c = ns(np.array([1, 2, 3, 0]), 2, 3)
    pos = np.concatenate([a[:3], b[:3], c[:3]])
    assert len(set(pos.tolist())) == 9  # injective across shards
    assert a[3] == b[3] == c[3] == 0  # 0 maps to 0
    # invertible: shard = (g-1) % n, local = (g-1) // n + 1
    for g, (s, l) in zip(pos, [(0, 1), (0, 2), (0, 3),
                               (1, 1), (1, 2), (1, 3),
                               (2, 1), (2, 2), (2, 3)]):
        assert (int(g) - 1) % 3 == s and (int(g) - 1) // 3 + 1 == l
    # elder-id min-fold preserved per shard: striding is monotone
    assert a[0] < a[1] < a[2]
    with pytest.raises(ValueError, match="int32"):
        ns(np.array([2**30]), 1, 4)


def test_fault_spec_shard_namespacing_pins():
    # bare token: the pre-sharding grammar, pinned — and @0 NORMALIZES
    # to it, so existing specs keep their exact ordinal streams
    (c,) = faults.parse_fault_spec("serve#0:TRANSIENT")
    assert c.site == "serve" and c.ordinal == 0
    (c0,) = faults.parse_fault_spec("serve@0#0:TRANSIENT")
    assert c0.site == "serve"
    (c2,) = faults.parse_fault_spec("serve@2#1:PERSISTENT")
    assert c2.site == "serve@2"
    (cr,) = faults.parse_fault_spec("serve_replica@1#0:PERSISTENT")
    assert cr.site == "serve_replica@1"
    with pytest.raises(ValueError, match="cannot take an @shard"):
        faults.parse_fault_spec("*@1#0:TRANSIENT")
    with pytest.raises(ValueError):
        faults.parse_fault_spec("nosuchsite@1#0:TRANSIENT")
    # per-namespace ordinal streams are independent
    reg = faults.FaultRegistry("")
    assert reg.next_ordinal("serve@1")[0] == 0
    assert reg.next_ordinal("serve@1")[0] == 1
    assert reg.next_ordinal("serve")[0] == 0  # untouched by shard 1's
    assert reg.next_ordinal("serve@2")[0] == 0
    assert faults.shard_site("serve", None) == "serve"
    assert faults.shard_site("serve", 0) == "serve"
    assert faults.shard_site("serve", 3) == "serve@3"


# --- sharded query vs the union oracle --------------------------------


def test_sharded_query_matches_union_oracle(rng):
    log = []
    svc = _svc(n_shards=3, cut_log=log)
    with svc:
        for b in _batches(7):
            assert svc.submit(b)
        assert svc.drain(timeout=300)
        qpts = np.concatenate(
            [_blob(rng, (0, 0), 40), rng.uniform(-25, 25, (60, 2))]
        )
        res = svc.query(qpts)
        cut = svc.cut()
    assert res.epochs == cut.epochs
    want = cut_query_host(qpts, cut, EPS, MINPTS, "euclidean")
    np.testing.assert_array_equal(res.gids, want.gids)
    np.testing.assert_array_equal(res.core, want.core)
    np.testing.assert_array_equal(res.counts, want.counts)
    assert (res.gids > 0).any()  # the probe actually hit clusters
    # and the union answer is NOT degenerate sharding: >1 shard holds
    # skeleton mass at the final cut
    assert sum(1 for sc in cut.shards if sc.k > 0) > 1
    # resolve: global ids round-trip through the owning shard
    rr = svc.resolve(res.gids)
    assert ((rr > 0) == (res.gids > 0)).all()


def test_epoch_vector_consistent_cut_fuzz(rng):
    """THE consistent-cut property, fuzzed under concurrent multi-shard
    ingest: every vector a reader ever pins is exactly the vector of
    one published cut (cut_log is append-ordered under the cut lock),
    and a single reader's pinned cut ids never go backwards."""
    log = []
    svc = _svc(n_shards=3, cut_log=log)
    seen = [[] for _ in range(3)]
    stop = threading.Event()

    def reader(i):
        while not stop.is_set():
            c = svc.cut()
            seen[i].append((c.cut_id, c.epochs))

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(3)
    ]
    with svc:
        [t.start() for t in threads]
        for b in _batches(11, k=6, n=50):
            svc.submit(b)
        assert svc.drain(timeout=300)
        stop.set()
        [t.join(timeout=60) for t in threads]
    assert len(log) >= 6  # at least one publish per batch per shard
    # cut ids are dense, append-ordered, epoch vectors monotone
    for i, cut in enumerate(log):
        assert cut.cut_id == i + 1
        if i:
            prev = log[i - 1].epochs
            assert all(a >= b for a, b in zip(cut.epochs, prev))
            assert sum(cut.epochs) == sum(prev) + 1  # one shard stepped
    published = {c.cut_id: c.epochs for c in log}
    published[0] = (0,) * 3  # the pre-ingest empty cut
    for reads in seen:
        assert reads, "reader thread never pinned a cut"
        last = -1
        for cut_id, epochs in reads:
            assert epochs == published[cut_id], (cut_id, epochs)
            assert cut_id >= last  # a reader never observes regression
            last = cut_id


# --- seqlock starvation (bounded reads) -------------------------------


def test_shard_seqlock_starvation_names_stale_shard(monkeypatch):
    monkeypatch.setenv("DBSCAN_SERVE_READ_TIMEOUT_S", "0.2")
    svc = ClusterService(
        EPS, MINPTS, window=2, max_points_per_partition=500, shard=1,
        n_shards=2,
    )
    svc._seq = 1  # wedged writer: publish never completes
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match=r"shard 1's snapshot publish"):
        svc.query(np.zeros((4, 2)))
    assert 0.1 < time.monotonic() - t0 < 5.0  # bounded, not a hang
    svc._seq = 0
    assert svc.query(np.zeros((4, 2))).epoch == 0  # recovered


def test_cut_seqlock_starvation_names_stale_shard(monkeypatch):
    monkeypatch.setenv("DBSCAN_SERVE_READ_TIMEOUT_S", "0.2")
    svc = _svc(n_shards=3)
    svc._cut_seq = 1
    svc._publishing_shard = 2  # the wedged cut publisher
    with pytest.raises(RuntimeError, match=r"shard 2's cut publish"):
        svc.cut()
    svc._cut_seq = 0
    svc._publishing_shard = None
    assert svc.cut().cut_id == 0


# --- shard-suffixed checkpoints ---------------------------------------


def test_shard_checkpoint_suffix_roundtrip(rng, tmp_path):
    ck = str(tmp_path / "ck")
    svc = _svc(n_shards=2, checkpoint_dir=ck)
    with svc:
        for b in _batches(23, k=3):
            svc.submit(b)
        assert svc.drain(timeout=300)
        cut = svc.cut()
        qpts = rng.uniform(-6, 6, (80, 2))
        want = svc.query(qpts)
    # stop() checkpointed each shard under its suffix
    assert os.path.exists(os.path.join(ck, "serve_state.npz.0"))
    assert os.path.exists(os.path.join(ck, "serve_state.npz.1"))
    assert not os.path.exists(os.path.join(ck, "serve_state.npz"))
    svc2 = _svc(n_shards=2, checkpoint_dir=ck)
    assert svc2.cut().epochs == cut.epochs
    got = svc2.query(qpts)
    np.testing.assert_array_equal(got.gids, want.gids)
    np.testing.assert_array_equal(got.counts, want.counts)


def test_shard_checkpoint_mismatch_refused(rng, tmp_path, caplog):
    import logging

    ck = str(tmp_path / "ck")
    svc = _svc(n_shards=2, checkpoint_dir=ck)
    with svc:
        for b in _batches(29, k=2):
            svc.submit(b)
        assert svc.drain(timeout=300)
    # shard-count fingerprint: a 3-shard service must REFUSE the
    # 2-shard files (different routing = different per-shard streams)
    with caplog.at_level(logging.WARNING):
        svc3 = _svc(n_shards=3, checkpoint_dir=ck)
    assert svc3.cut().epochs == (0, 0, 0)
    assert any("refusing the restore" in r.message for r in caplog.records)
    caplog.clear()
    # all-or-nothing: remove one shard file -> the whole restore is
    # refused (a half-restored cut would relabel across the boundary)
    os.remove(os.path.join(ck, "serve_state.npz.1"))
    with caplog.at_level(logging.WARNING):
        svc4 = _svc(n_shards=2, checkpoint_dir=ck)
    assert svc4.cut().epochs == (0, 0)
    assert any("PARTIAL" in r.message for r in caplog.records)


# --- fault drills ------------------------------------------------------


def test_shard_targeted_ingest_fault_degrades_one_shard(
    rng, monkeypatch
):
    _spec(monkeypatch, "serve@1#0:PERSISTENT")
    svc = _svc(n_shards=2)
    with svc:
        for b in _batches(31, k=3):
            svc.submit(b)
        assert svc.drain(timeout=300)
        h = svc.health()
        assert h["degraded"] == [1]  # ONLY the targeted shard marked
        cut = svc.cut()
        # the faulted update was dropped on shard 1 (its epoch lags by
        # exactly the one killed ingest step); shard 0 is untouched —
        # and BOTH keep ingesting after the mark (degraded, not dead)
        assert cut.epochs[0] == 3 and cut.epochs[1] == 2
        assert cut.shards[0].k > 0 and cut.shards[1].k > 0
        qpts = rng.uniform(-6, 6, (50, 2))
        res = svc.query(qpts)  # queries keep serving the union
        want = cut_query_host(qpts, cut, EPS, MINPTS, "euclidean")
        np.testing.assert_array_equal(res.gids, want.gids)


def _oracle_for(res, cut_log, n_shards):
    """The union oracle at an answer's PINNED epoch vector (unique in
    the log: each publish steps exactly one coordinate)."""
    if res.epochs == (0,) * n_shards:
        return None  # pre-ingest empty cut: everything is noise
    return next(c for c in cut_log if c.epochs == res.epochs)


def test_replica_kill_drill_zero_failed_queries(rng, monkeypatch):
    """THE acceptance drill: a kill schedule that takes down EVERY
    replica (kill-on-first-touch), queries interleaved with live
    ingest. Every accepted query must complete and be oracle-exact for
    its pinned epoch vector — the failover chain drains each dying
    replica onto the next, ending at the host union oracle."""
    _spec(
        monkeypatch,
        "serve_replica@0#0:PERSISTENT;serve_replica@1#0:PERSISTENT;"
        "serve_replica@2#0:PERSISTENT",
    )
    log = []
    svc = _svc(n_shards=2, cut_log=log)
    batches = _batches(37, k=4)
    with svc:
        svc.submit(batches[0])
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=3) as router:
            answers = []
            for i, b in enumerate(batches[1:]):
                svc.submit(b)  # ingest stays live: cuts keep landing
                for j in range(3):
                    q = rng.uniform(-6, 6, (40 + 8 * j, 2))
                    answers.append((q, router.query(q)))  # must not raise
            assert svc.drain(timeout=300)
            h = router.health()
    # the schedule executed: every replica died, nothing failed
    assert h["live"] == []
    assert h["routed"] == len(answers) and h["shed"] == 0
    for q, res in answers:
        cut = _oracle_for(res, log, 2)
        if cut is None:
            assert not (res.gids > 0).any()
            continue
        want = cut_query_host(q, cut, EPS, MINPTS, "euclidean")
        np.testing.assert_array_equal(res.gids, want.gids)
        np.testing.assert_array_equal(res.core, want.core)
        np.testing.assert_array_equal(res.counts, want.counts)


def test_replica_transient_heals_no_eviction(rng, monkeypatch):
    _spec(monkeypatch, "serve_replica@0#0:TRANSIENT")
    svc = _svc(n_shards=2)
    with svc:
        for b in _batches(41, k=2):
            svc.submit(b)
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=2) as router:
            cut = svc.cut()
            for _ in range(4):
                q = rng.uniform(-6, 6, (30, 2))
                res = router.query(q)
                want = cut_query_host(q, cut, EPS, MINPTS, "euclidean")
                np.testing.assert_array_equal(res.gids, want.gids)
            h = router.health()
    assert h["live"] == [0, 1]  # healed in place: nobody evicted
    assert h["routed"] == 4


def test_router_shed_under_p99_pressure(rng, monkeypatch):
    svc = _svc(n_shards=2)
    with svc:
        for b in _batches(43, k=2):
            svc.submit(b)
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=2) as router:
            # warm the rolling-latency window while shedding is off
            for _ in range(10):
                router.query(rng.uniform(-6, 6, (16, 2)))
            assert router.shed_frac == 0.0
            # declare an unmeetable bound: every real latency is past
            # it, so the admission window shrinks toward zero
            monkeypatch.setenv("DBSCAN_SERVE_SHED_P99_MS", "1e-6")
            with pytest.raises(QueryShed) as exc:
                router.query(rng.uniform(-6, 6, (512, 2)))
            assert exc.value.price > exc.value.allowed
            assert exc.value.p99 > exc.value.bound
            h = router.health()
    assert h["shed"] == 1 and h["routed"] == 10
    assert 0.0 < h["shed_frac"] < 1.0
    assert router.shed_frac == pytest.approx(1.0 / 11.0)


# --- compile stability + shapecheck -----------------------------------


def test_broadcast_steady_state_zero_recompile(rng):
    svc = _svc(n_shards=2)
    with svc:
        for b in _batches(47, k=3, n=50):
            svc.submit(b)
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=2):
            fn = router_mod._broadcast_builder()
            misses0 = fn._cache_size()
            # more steady-state publishes inside the warmed rungs:
            # window retention keeps the skeleton in the same ladder
            # rung, so every further broadcast reuses the signature
            for b in _batches(53, k=3, n=50):
                svc.submit(b)
            assert svc.drain(timeout=300)
            assert fn._cache_size() == misses0


_SHAPECHECK_CHILD = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
from dbscan_tpu.lint import shapecheck
from dbscan_tpu.serve import QueryRouter, ShardedClusterService

rng = np.random.default_rng(3)
svc = ShardedClusterService(
    0.6, 5, n_shards=2, window=2, max_points_per_partition=500
)
with svc:
    for i in range(3):
        svc.submit(np.concatenate([
            rng.normal(c, 0.25, (70, 2))
            for c in [(0, 0), (5, 0), (0, 5)]
        ]))
    assert svc.drain(timeout=300)
    with QueryRouter(svc, replicas=2) as router:
        for n in (32, 200):
            router.query(rng.uniform(-6, 6, (n, 2)))
rep = shapecheck.report()
assert rep["enabled"], rep
assert "serve.broadcast" in rep["sites"], sorted(rep["sites"])
assert "serve.query" in rep["sites"], sorted(rep["sites"])
assert rep["violations"] == [], rep
print("SHAPECHECK_OK", sorted(rep["sites"]))
"""


def test_shapecheck_clean_on_sharded_serving(tmp_path):
    env = dict(os.environ)
    env.update(
        DBSCAN_SHAPECHECK="1",
        JAX_PLATFORMS="cpu",
        DBSCAN_FAULT_SPEC="",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, "-c", _SHAPECHECK_CHILD],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "SHAPECHECK_OK" in out.stdout


def test_sharded_tsan_rerun_race_free(tmp_path):
    """DBSCAN_TSAN=1 certification of the cut seqlock + router locks
    under genuinely concurrent shard publishes, broadcasts, and routed
    reads."""
    report = tmp_path / "tsan.json"
    code = (
        "import threading\n"
        "import numpy as np\n"
        "from dbscan_tpu.serve import QueryRouter, ShardedClusterService\n"
        "rng = np.random.default_rng(0)\n"
        "svc = ShardedClusterService(0.6, 5, n_shards=2, window=2,"
        " max_points_per_partition=500)\n"
        "stop = threading.Event()\n"
        "with svc:\n"
        "    router = QueryRouter(svc, replicas=2)\n"
        "    def reader():\n"
        "        q = rng.uniform(-6, 6, (24, 2))\n"
        "        while not stop.is_set():\n"
        "            router.query(q)\n"
        "    threads = [threading.Thread(target=reader, daemon=True)"
        " for _ in range(2)]\n"
        "    [t.start() for t in threads]\n"
        "    for i in range(4):\n"
        "        svc.submit(np.concatenate(["
        "rng.normal(c, 0.25, (60, 2))"
        " for c in [(0, 0), (5, 0), (0, 5)]]))\n"
        "    assert svc.drain(timeout=300)\n"
        "    stop.set()\n"
        "    [t.join(timeout=60) for t in threads]\n"
        "    router.close()\n"
        "print('OK')\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_TSAN="1",
        DBSCAN_TSAN_REPORT=str(report),
        DBSCAN_FAULT_SPEC="",
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
    rep = json.load(open(report))
    assert rep["races"] == []
    assert rep["lock_inversions"] == []


# --- SIGTERM drill -----------------------------------------------------


_DRILL_CHILD = r"""
import os, sys, time
import numpy as np

ck, data, out_dir, mode = sys.argv[1:5]

z = np.load(data)
batches = [z[f"b{i}"] for i in range(6)]
probe = z["probe"]

from dbscan_tpu.serve import ShardedClusterService

def save_answer(svc):
    res = svc.query(probe)
    np.savez(
        os.path.join(out_dir, "answer.npz"),
        gids=res.gids, core=res.core, counts=res.counts,
        epochs=np.asarray(res.epochs, np.int64),
    )

if mode == "oracle":
    svc = ShardedClusterService(
        0.6, 5, n_shards=2, window=2, max_points_per_partition=500
    )
    with svc:
        for b in batches:
            svc.submit(b)
        assert svc.drain(timeout=300)
        save_answer(svc)
    print("DONE", flush=True)
    sys.exit(0)

svc = ShardedClusterService(
    0.6, 5, n_shards=2, window=2, max_points_per_partition=500,
    checkpoint_dir=ck,
)
svc.start()
if mode == "victim":
    for i in range(3):
        svc.submit(batches[i])
        svc.drain()
        print(f"CUT {svc.cut().cut_id}", flush=True)
    # submit the 4th batch and DON'T drain: the parent SIGTERMs us
    # while both shard ingest threads are inside update #4
    svc.submit(batches[3])
    print("READY", flush=True)
    time.sleep(120)
    print("UNREACHABLE", flush=True)
else:
    print("RESUME", list(svc.cut().epochs), flush=True)
    sent = svc.replay(batches)
    assert sent > 0  # the kill left SOMETHING to replay
    assert svc.drain(timeout=300)
    save_answer(svc)
    svc.stop()
print("DONE", flush=True)
"""


def test_sharded_sigterm_drill_resumes_byte_identical(tmp_path):
    """The sharded robustness acceptance: SIGTERM mid-ingest dumps the
    flight recording, checkpoints EVERY shard under its suffix, and a
    replay()-resumed service converges to answers byte-identical to an
    uninterrupted run's — per-shard epochs included."""
    from dbscan_tpu.obs import flight

    batches = _batches(59, k=6, n=60)
    probe = np.random.default_rng(61).uniform(-6, 6, (120, 2))
    ck = tmp_path / "ck"
    out_dir = tmp_path / "out"
    oracle_dir = tmp_path / "oracle"
    out_dir.mkdir()
    oracle_dir.mkdir()
    data = tmp_path / "batches.npz"
    np.savez(
        data, probe=probe, **{f"b{i}": b for i, b in enumerate(batches)}
    )
    child = tmp_path / "child.py"
    child.write_text(_DRILL_CHILD)
    dump = tmp_path / "flight.json"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_FLIGHTREC_PATH=str(dump),
        DBSCAN_FAULT_SPEC="",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )

    proc0 = subprocess.run(
        [sys.executable, str(child), str(ck), str(data),
         str(oracle_dir), "oracle"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc0.returncode == 0, proc0.stderr

    proc = subprocess.Popen(
        [sys.executable, str(child), str(ck), str(data), str(out_dir),
         "victim"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env,
    )
    deadline = time.monotonic() + 300
    for line in proc.stdout:
        if line.startswith("READY"):
            break
        assert time.monotonic() < deadline
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    err = proc.stderr.read()
    assert rc == -signal.SIGTERM, err
    assert "UNREACHABLE" not in err

    rep = flight.load(str(dump))
    assert rep["reason"] == "SIGTERM"
    # EVERY shard checkpointed under its suffix on the signal path
    assert (ck / "serve_state.npz.0").exists()
    assert (ck / "serve_state.npz.1").exists()

    proc2 = subprocess.run(
        [sys.executable, str(child), str(ck), str(data), str(out_dir),
         "resume"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc2.returncode == 0, proc2.stderr
    resumed = json.loads(proc2.stdout.split("RESUME ", 1)[1].split("\n")[0])
    assert min(resumed) >= 3  # the drained epochs survived the kill

    got = np.load(out_dir / "answer.npz")
    want = np.load(oracle_dir / "answer.npz")
    for key in ("gids", "core", "counts", "epochs"):
        np.testing.assert_array_equal(got[key], want[key])


# --- registration / history / gate pins --------------------------------


def test_registration_pins_sharded():
    from dbscan_tpu import config
    from dbscan_tpu.lint.shapes import FAMILY_MODELS
    from dbscan_tpu.obs import schema

    assert "serve.broadcast" in schema.COMPILE_FAMILIES
    assert "serve.broadcast" in FAMILY_MODELS
    model = FAMILY_MODELS["serve.broadcast"]
    assert [a.dims for a in model.args] == [("K", "D"), ("K",)]
    for name in (
        "serve.router.routed", "serve.router.shed",
        "serve.router.failovers", "serve.router.host_fallbacks",
        "serve.replica.evictions", "serve.broadcast.casts",
        "serve.broadcast.bytes", "compiles.serve.broadcast",
    ):
        assert schema.is_declared("counter", name), name
    for name in (
        "serve.cut_id", "serve.router.replicas_live",
        "serve.router.p99_ms",
    ):
        assert schema.is_declared("gauge", name), name
    assert schema.is_declared("span", "serve.route")
    for name in (
        "serve.cut_publish", "serve.replica.evict",
        "serve.router.failover",
    ):
        assert schema.is_declared("event", name), name
    for knob in (
        "DBSCAN_SERVE_REPLICAS", "DBSCAN_SERVE_READ_TIMEOUT_S",
        "DBSCAN_SERVE_SHED_P99_MS",
    ):
        assert knob in config.ENV_VARS, knob
    assert faults.SITE_SERVE_REPLICA in faults._SITES


def test_shed_frac_promotion_and_direction():
    from dbscan_tpu.obs import analyze, bench_history, regress

    cap = {
        "metric": "serve",
        "backend": "cpu",
        "serve_r1_qps": 9.0,
        "serve_r4_qps": 26.0,
        "serve_r4_p99_ms": 310.0,
        "serve_shed_frac": 0.03,
        "serve_replicas": 4,  # not a perf key: must NOT promote
    }
    recs = bench_history.normalize_capture(cap, "t.json", "rev")
    by = {r["metric"]: r for r in recs}
    assert by["serve_shed_frac"]["unit"] == "ratio"
    assert by["serve_r1_qps"]["unit"] == "queries/s"
    assert "serve_replicas" not in by
    # shed fraction is capacity turned away: it regresses UP
    assert regress.direction("serve_shed_frac") == regress.LOWER_BETTER
    assert regress.direction("serve_r4_qps") == regress.HIGHER_BETTER
    hist = [
        {"metric": "serve_shed_frac", "value": v, "backend": "cpu",
         "resident_hot": None, "source": f"h{i}"}
        for i, v in enumerate((0.02, 0.03, 0.04))
    ]
    bad = [{"metric": "serve_shed_frac", "value": 0.5, "backend": "cpu",
            "resident_hot": None, "source": "f"}]
    result = regress.compare(bad, hist, threshold=0.25)
    assert {e["metric"] for e in result["regressions"]} == {
        "serve_shed_frac"
    }
    # analyze derives the same figure from the router counters
    out = analyze._serve_rollup(
        {"serve.router.shed": 3, "serve.router.routed": 97}, []
    )
    assert out["serve.shed_frac"] == pytest.approx(0.03)


def test_committed_serve_r02_capture_gates_green():
    """BENCH_SERVE_r02.json (the replicated-serving capture) is in
    bench/history.jsonl and gates green — and carries the acceptance
    inequalities: QPS grows with the replica ladder, p99 well under the
    ingest batch period."""
    from dbscan_tpu.obs import bench_history, regress

    cap_path = os.path.join(REPO, "BENCH_SERVE_r02.json")
    hist_path = os.path.join(REPO, "bench", "history.jsonl")
    assert os.path.exists(cap_path)
    cap = json.load(open(cap_path))
    row = (cap["runs"] if "runs" in cap else [cap])[0]
    ladder = sorted(
        int(k[len("serve_r"):-len("_qps")])
        for k in row if k.startswith("serve_r") and k.endswith("_qps")
    )
    assert len(ladder) >= 2 and ladder[0] == 1
    top = ladder[-1]
    assert row[f"serve_r{top}_qps"] > row["serve_r1_qps"]
    assert (
        row[f"serve_r{top}_p99_ms"] / 1e3
        < 0.5 * row["serve_rep_batch_period_s"]
    )
    assert 0.0 <= row["serve_shed_frac"] < 1.0
    recs = bench_history.parse_capture_file(cap_path)
    metrics = {r["metric"] for r in recs}
    assert {
        "serve_r1_qps", f"serve_r{top}_qps", "serve_shed_frac",
    } <= metrics
    history = bench_history.load_history(hist_path)
    assert [
        r for r in history if r["metric"] == f"serve_r{top}_qps"
    ], "r02 not ingested into the committed history"
    recs = [{**r, "source": "fresh-check"} for r in recs]
    result = regress.compare(recs, history, threshold=0.25)
    assert result["regressions"] == []
