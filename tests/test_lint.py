"""graftlint (dbscan_tpu/lint/): fixture pairs per rule family, the
repo-wide lint-clean pin, suppression semantics, and the CLI contract.

The repo-wide test is the enforcement point of this PR's contracts:
``python -m dbscan_tpu.lint dbscan_tpu/`` exits 0, so any emission of
an undeclared telemetry name, any direct ``DBSCAN_*`` environ read, and
any trace-reachable host sync fails tier-1 CI the moment it lands.
Every bad-snippet fixture asserts the exact rule id AND line so the
findings stay actionable; every good-snippet twin pins the rule's
false-positive boundary.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from dbscan_tpu import lint as lint_mod
from dbscan_tpu.lint import callgraph as cg_mod
from dbscan_tpu.lint.cli import main as lint_main

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dbscan_tpu")


def _lint_source(tmp_path, source, name="snippet.py", subdir=None):
    d = tmp_path if subdir is None else tmp_path / subdir
    d.mkdir(parents=True, exist_ok=True)
    p = d / name
    p.write_text(textwrap.dedent(source))
    findings, _ = lint_mod.lint_paths([str(p)])
    return findings, str(p)


def _rules(findings):
    return [f.rule for f in findings]


# --- host-sync family -------------------------------------------------


def test_hostsync_item_in_jit_root(tmp_path):
    findings, path = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            s = jnp.sum(x)
            return s.item()
        """,
    )
    assert _rules(findings) == ["host-sync-item"]
    assert findings[0].path == path and findings[0].line == 8


def test_hostsync_item_transitively_reachable(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def helper(v):
            return v.item()

        @jax.jit
        def root(x):
            return helper(jnp.sum(x))
        """,
    )
    assert _rules(findings) == ["host-sync-item"]
    assert findings[0].line == 6  # reported in the helper, not the root


def test_hostsync_item_clean_outside_jit(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        def host_pull(x):
            return jnp.sum(x).item()
        """,
    )
    assert findings == []  # not reachable from any jit site


def test_hostsync_cast_on_array_expression(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            return float(jnp.sum(x))
        """,
    )
    assert _rules(findings) == ["host-sync-cast"]


def test_hostsync_cast_shape_and_static_are_exempt(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n",))
        def root(x, n):
            pad = int(n) + int(x.shape[0])
            return jnp.pad(x, (0, pad))
        """,
    )
    assert findings == []  # static param + shape arithmetic stay clean


def test_hostsync_asarray_on_traced_value(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def root(x):
            return np.asarray(x)
        """,
    )
    assert _rules(findings) == ["host-sync-asarray"]


def test_hostsync_asarray_literal_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            return x + jnp.asarray(np.asarray([1.0, 2.0]))
        """,
    )
    assert findings == []


# --- recompile family -------------------------------------------------


def test_jit_in_loop_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        def run(xs):
            out = []
            for x in xs:
                f = jax.jit(lambda a: a + 1)
                out.append(f(x))
            return out
        """,
    )
    assert "jit-in-loop" in _rules(findings)


def test_jit_hoisted_out_of_loop_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        f = jax.jit(lambda a: a + 1)

        def run(xs):
            return [f(x) for x in xs]
        """,
    )
    assert findings == []


def test_jit_scalar_arg_without_statics(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        @jax.jit
        def g(x, n):
            return x * n

        def call(x):
            return g(x, 3)
        """,
    )
    assert _rules(findings) == ["jit-scalar-arg"]
    assert findings[0].line == 9


def test_jit_scalar_arg_with_statics_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            return x * n

        def call(x):
            return g(x, 3)
        """,
    )
    assert findings == []


def test_dtype_flow_drift_literal_in_kernel_path(tmp_path):
    """Parity with the superseded literal-only rule: a float64 dtype
    literal entering a jnp call in kernel code still flags — now under
    the successor id."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        def kern(x):
            return jnp.asarray(x, dtype="float64")
        """,
        name="kern.py",
        subdir="ops",
    )
    assert _rules(findings) == ["dtype-flow-drift"]


def test_dtype_flow_drift_through_value_flow(tmp_path):
    """The flow half the literal rule could not see: an explicit f64
    VALUE built host-side and later fed to a device op."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def kern(x):
            w = np.float64(2.5)
            return jnp.sum(x * w)
        """,
        name="kern_flow.py",
        subdir="ops",
    )
    assert _rules(findings) == ["dtype-flow-drift"]
    assert findings[0].line == 7


def test_dtype_f32_kernel_and_host_f64_are_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def kern(x):
            return jnp.asarray(x, dtype=jnp.float32)

        def host_grid(c):
            return np.asarray(c, dtype=np.float64)
        """,
        name="kern2.py",
        subdir="ops",
    )
    assert findings == []  # host np.* f64 is exempt by design


def test_dtype_flow_host_astype_f64_is_clean(tmp_path):
    """The geometry.py migration pin: host-provenance f64 (an astype on
    an np.concatenate result) no longer needs the suppression the
    literal rule required — the flow rule proves it never reaches a
    device op."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np

        def grid_corners(idx, cell):
            return np.concatenate([idx, idx + 1]).astype(np.float64) * cell
        """,
        name="kern3.py",
        subdir="ops",
    )
    assert findings == []


def test_dtype_drift_alias_suppression_still_works(tmp_path):
    """A suppression written against the RETIRED id keeps silencing the
    successor's findings (lint.ALIASES)."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        def kern(x):
            return jnp.asarray(x, dtype="float64")  # graftlint: disable=dtype-drift  parity fixture
        """,
        name="kern4.py",
        subdir="ops",
    )
    assert findings == []


# --- telemetry-schema family ------------------------------------------


def test_schema_undeclared_counter(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit():
            obs.count("nonexistent.counter")
        """,
    )
    assert _rules(findings) == ["schema-counter"]
    assert findings[0].line == 5


def test_schema_declared_names_are_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit(fam):
            obs.count("transfer.h2d_bytes", 128)
            obs.gauge("memory.bytes_in_use", 1)
            obs.event("fault.retry", site="dispatch")
            with obs.span("spill.pivots", node=3):
                pass
            obs.count(f"compiles.{fam}")
        """,
    )
    assert findings == []


def test_schema_dynamic_prefix_must_match(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit(fam):
            obs.count(f"zzz.{fam}")
        """,
    )
    assert _rules(findings) == ["schema-dynamic"]


def test_schema_family_literal_checked(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.obs import compile as obs_compile

        def dispatch(fn, x):
            return obs_compile.tracked_call("not.a.family", fn, x)
        """,
    )
    assert _rules(findings) == ["schema-family"]


def test_schema_known_family_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.obs import compile as obs_compile

        def dispatch(fn, x):
            return obs_compile.tracked_call("dispatch.dense", fn, x)
        """,
    )
    assert findings == []


def test_deleting_declared_counter_breaks_lint(tmp_path, monkeypatch):
    """The acceptance contract: remove an emitted counter from
    obs/schema.py and the linter flags the emission site."""
    from dbscan_tpu.obs import schema

    src = """
    from dbscan_tpu import obs

    def emit():
        obs.count("transfer.h2d_bytes", 128)
    """
    findings, _ = _lint_source(tmp_path, src)
    assert findings == []
    monkeypatch.delitem(schema.COUNTERS, "transfer.h2d_bytes")
    findings, _ = _lint_source(tmp_path, src, name="snippet2.py")
    assert _rules(findings) == ["schema-counter"]


# --- env-registry family ----------------------------------------------


def test_env_direct_read_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import os

        def knobs():
            a = os.environ.get("DBSCAN_SOMETHING", "1")
            b = os.getenv("DBSCAN_OTHER")
            c = os.environ["DBSCAN_THIRD"]
            return a, b, c
        """,
    )
    assert _rules(findings) == ["env-direct-read"] * 3
    assert [f.line for f in findings] == [5, 6, 7]


def test_env_direct_write_is_clean(tmp_path):
    """Setting a knob (os.environ["DBSCAN_X"] = ...) is not a registry
    bypass — drill CLIs (dbscan_tpu/campaign.py --fault-spec) and
    harnesses set knobs that are read back through config.env; only
    Load-context reads route around the registry."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import os

        def arm(spec):
            os.environ["DBSCAN_FAULT_SPEC"] = spec
            del os.environ["DBSCAN_FAULT_SPEC"]
        """,
    )
    assert _rules(findings) == []


def test_env_accessor_of_declared_name_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import config

        def knob():
            return config.env("DBSCAN_GROUP_SLOTS")
        """,
    )
    assert findings == []


def test_env_undeclared_name_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.config import env

        def knob():
            return env("DBSCAN_NOT_A_REAL_KNOB")
        """,
    )
    assert _rules(findings) == ["env-undeclared"]


def test_non_dbscan_env_reads_ignored(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import os

        def other():
            return os.environ.get("JAX_PLATFORMS", "")
        """,
    )
    assert findings == []


def test_every_declared_env_var_documented_in_parity():
    """Row-marker check, not substring: DBSCAN_TRACE inside the
    DBSCAN_TRACE_MAX_SPANS row (or a prose mention) must not satisfy
    the missing-row case."""
    from dbscan_tpu.config import ENV_VARS

    with open(os.path.join(REPO, "PARITY.md"), encoding="utf-8") as f:
        text = f.read()
    missing = [n for n in ENV_VARS if f"| `{n}` |" not in text]
    assert missing == []


def test_env_parity_detects_deleted_table_row(tmp_path):
    """Deleting one variable's table row from PARITY.md fires
    env-parity even though the name still appears elsewhere in the
    file (the substring false-negative the row marker exists for)."""
    import shutil

    pkg_copy = tmp_path / "dbscan_tpu"
    shutil.copytree(PKG, pkg_copy, ignore=shutil.ignore_patterns("__pycache__"))
    with open(os.path.join(REPO, "PARITY.md"), encoding="utf-8") as f:
        lines = f.read().splitlines(keepends=True)
    kept = [ln for ln in lines if not ln.startswith("| `DBSCAN_TRACE` |")]
    assert len(kept) == len(lines) - 1
    (tmp_path / "PARITY.md").write_text("".join(kept))
    findings, _ = lint_mod.lint_paths([str(pkg_copy / "config.py")])
    parity = [f for f in findings if f.rule == "env-parity"]
    assert [
        f for f in parity if "'DBSCAN_TRACE'" in f.message
    ], parity


# --- race family (graftcheck) -----------------------------------------

_RACE_PRELUDE = """
        import threading

        from dbscan_tpu.parallel.pipeline import get_engine

        TOTALS = {"n": 0}
        LOCK = threading.Lock()
        eng = get_engine()
"""


def test_race_unlocked_shared_on_worker_callable(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        def work():
            TOTALS["n"] += 1

        eng.submit(work)
        """,
    )
    assert _rules(findings) == ["race-unlocked-shared"]
    assert findings[0].line == 11


def test_race_unlocked_shared_clean_under_lock(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        def work():
            with LOCK:
                TOTALS["n"] += 1

        eng.submit(work)
        """,
    )
    assert findings == []


def test_race_thread_target_is_a_worker_root(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        N = 0

        def tick():
            global N
            N += 1

        t = threading.Thread(target=tick)
        """,
    )
    assert _rules(findings) == ["race-unlocked-shared"]


def test_race_closure_defined_under_lock_runs_unlocked(tmp_path):
    """A closure DEFINED inside a `with lock:` block does not run under
    that lock — its body is scanned with its own (empty) lock context,
    so the unlocked write still flags (exactly once)."""
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        def work():
            with LOCK:
                def cb():
                    TOTALS["n"] += 1
            cb()

        eng.submit(work)
        """,
    )
    assert _rules(findings) == ["race-unlocked-shared"]


def test_race_nested_def_local_does_not_shadow_exempt(tmp_path):
    """A nested def binding a local named like the module global must
    not exempt the ENCLOSING function's unlocked shared write (the
    binding scans are scope-bounded)."""
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        def work():
            def unrelated():
                TOTALS = {}
                return TOTALS

            TOTALS["n"] += 1

        eng.submit(work)
        """,
    )
    assert _rules(findings) == ["race-unlocked-shared"]


def test_race_nested_global_decl_does_not_leak_out(tmp_path):
    """A `global N` inside a nested def must not make the enclosing
    function's plain local write look like a module-global write."""
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        N = 0

        def work():
            def bump():
                global N
                with LOCK:
                    N += 1

            N = 1  # plain LOCAL in work: not the module global
            bump()

        eng.submit(work)
        """,
    )
    assert findings == []


def test_race_lock_order_closure_built_under_lock_is_clean(tmp_path):
    """Constructing (not running) a closure under a lock must not
    charge the closure's lock acquisitions to the builder — no
    invented lock-order cycle."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def helper_a():
            with A:
                pass

        def make_later():
            def cb():
                helper_a()

            return cb

        def f():
            with A:
                with B:
                    pass

        def clean():
            with B:
                cb = make_later()  # builds, never runs helper_a
            return cb
        """,
    )
    assert findings == []


def test_race_not_flagged_off_the_worker_slice(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        N = 0

        def main_thread_only():
            global N
            N += 1
        """,
    )
    assert findings == []  # same write, but nothing dispatches it


def test_race_param_rooted_writes_are_ownership_transfer(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        def work(rec):
            rec["out"] = 1  # handed-off record: exempt by design

        eng.submit(work)
        """,
    )
    assert findings == []


def test_race_lock_order_cycle(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with B:
                with A:
                    pass
        """,
    )
    assert set(_rules(findings)) == {"race-lock-order"}
    assert len(findings) == 2  # both edges of the cycle


def test_race_lock_order_cycle_through_a_call(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def helper():
            with B:
                pass

        def f():
            with A:
                helper()

        def g():
            with B:
                with A:
                    pass
        """,
    )
    assert "race-lock-order" in _rules(findings)


def test_race_lock_order_consistent_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def f():
            with A:
                with B:
                    pass

        def g():
            with A:
                with B:
                    pass
        """,
    )
    assert findings == []


def test_race_lock_order_self_deadlock(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()

        def f():
            with A:
                with A:
                    pass
        """,
    )
    assert _rules(findings) == ["race-lock-order"]
    assert "re-acquired" in findings[0].message


def test_race_lock_order_call_transitive_self_deadlock(tmp_path):
    """`with L: helper()` where helper itself takes non-reentrant L is
    the same guaranteed deadlock as lexical nesting and must flag."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.Lock()

        def helper():
            with A:
                pass

        def f():
            with A:
                helper()
        """,
    )
    assert _rules(findings) == ["race-lock-order"]


def test_race_annotated_local_shadows_module_global(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _RACE_PRELUDE
        + """
        cache = {}

        def work():
            cache: dict = {}
            cache["k"] = 1  # annotated LOCAL, not the module global

        eng.submit(work)
        """,
    )
    assert findings == []


def test_race_rlock_reacquire_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading

        A = threading.RLock()

        def f():
            with A:
                with A:
                    pass
        """,
    )
    assert findings == []


def test_race_sync_under_lock(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading
        import jax

        L = threading.Lock()

        def f(x):
            with L:
                jax.block_until_ready(x)
            return x
        """,
    )
    assert _rules(findings) == ["race-sync-under-lock"]


def test_race_sync_outside_lock_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import threading
        import jax

        L = threading.Lock()

        def f(x):
            with L:
                y = x
            jax.block_until_ready(y)
            return y
        """,
    )
    assert findings == []


# --- collective family (graftcheck) -----------------------------------


def test_collective_in_branch_on_traced_param(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        from jax import lax

        def block(x):
            if x[0] > 0:
                x = lax.psum(x, "i")
            return x

        f = jax.shard_map(block, mesh=None, in_specs=None, out_specs=None)
        """,
    )
    assert _rules(findings) == ["collective-in-branch"]
    assert findings[0].line == 7


def test_collective_under_uniform_host_config_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        from jax import lax

        def make(mesh):
            def block(x):
                y = x.sum()
                if mesh is not None:
                    y = lax.psum(y, "i")
                return y

            return jax.shard_map(
                block, mesh=mesh, in_specs=None, out_specs=None
            )
        """,
    )
    assert findings == []  # closure over the builder's mesh is uniform


def test_collective_axis_undeclared(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        AXIS = "parts"
        mesh = Mesh(np.empty(1, object), (AXIS,))

        def block(x):
            return lax.psum(x, "chips")

        f = jax.shard_map(block, mesh=mesh, in_specs=None, out_specs=None)
        """,
    )
    assert _rules(findings) == ["collective-axis-undeclared"]


def test_collective_axis_resolved_constant_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh

        AXIS = "parts"
        mesh = Mesh(np.empty(1, object), (AXIS,))

        def block(x):
            return lax.psum(x, AXIS)

        f = jax.shard_map(block, mesh=mesh, in_specs=None, out_specs=None)
        """,
    )
    assert findings == []


def test_pull_in_collective(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        def helper(x):
            return jax.device_get(x)

        def block(x):
            return helper(x)

        f = jax.shard_map(block, mesh=None, in_specs=None, out_specs=None)
        """,
    )
    assert _rules(findings) == ["pull-in-collective"]
    assert findings[0].line == 5  # in the helper, via the region walk


def test_pull_outside_collective_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        def block(x):
            return x + 1

        f = jax.shard_map(block, mesh=None, in_specs=None, out_specs=None)

        def driver(x):
            return jax.device_get(f(x))
        """,
    )
    assert findings == []


def test_worker_slice_model_covers_the_pull_paths():
    """Pin the callgraph's worker slice on the real package: the pull
    finalize, the sparse leaf lander, the engine loop, and fault
    supervision are all on it, and every tsan site they touch is in the
    static model the containment test consumes."""
    from dbscan_tpu.lint import races
    from dbscan_tpu.lint.core import load_package, run_rules

    pkg = load_package([PKG])
    run_rules(pkg, (), lint_mod.RULES)
    names = {f.qualname for f in pkg.callgraph.worker_funcs()}
    for expected in (
        "dbscan_tpu.parallel.driver.train_arrays._pull_record",
        "dbscan_tpu.ops.sparse._mesh_leaf_dispatch._land",
        "dbscan_tpu.parallel.pipeline.PullEngine._loop",
        "dbscan_tpu.faults.supervised",
        "dbscan_tpu.faults.get_registry",
        "dbscan_tpu.obs.metrics.MetricsRegistry.count",
        "dbscan_tpu._native.lib",
    ):
        assert expected in names, expected
    sites = races.worker_tsan_sites(pkg)
    assert {
        "faults.counters",
        "faults.registry",
        "faults.registry_state",
        "obs.metrics",
        "obs.trace",
        "pipeline.engine",
    } <= sites


# --- suppressions -----------------------------------------------------

_SUPPRESSIBLE = """
import jax
import jax.numpy as jnp

@jax.jit
def root(x):
    return float(jnp.sum(x)){comment}
"""


def test_suppression_with_reason_silences(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _SUPPRESSIBLE.format(
            comment="  # graftlint: disable=host-sync-cast  scalar loss"
        ),
    )
    assert findings == []


def test_suppression_without_reason_keeps_finding(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _SUPPRESSIBLE.format(
            comment="  # graftlint: disable=host-sync-cast"
        ),
    )
    assert sorted(_rules(findings)) == [
        "host-sync-cast",
        "suppress-no-reason",
    ]


def test_suppression_unknown_rule_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        x = 1  # graftlint: disable=not-a-rule  because reasons
        """,
    )
    assert _rules(findings) == ["suppress-unknown-rule"]


# --- shapes family (graftshape) ---------------------------------------


def test_shape_mismatch_broadcast(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            a = jnp.zeros((4, 8))
            b = jnp.ones((3, 8))
            return a + b
        """,
    )
    assert _rules(findings) == ["shape-mismatch"]
    assert findings[0].line == 9


def test_shape_mismatch_symbolic_dims_unify_clean(tmp_path):
    """A symbolic dim (x.shape[0]) against a concrete one is NOT a
    provable conflict — the interpreter must stay conservative."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            n = x.shape[0]
            a = jnp.zeros((n, 8))
            b = jnp.ones((128, 8))
            c = jnp.concatenate([a, b])
            return c * jnp.ones((1, 8))
        """,
    )
    assert findings == []


def test_shape_mismatch_concat_off_axis(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            a = jnp.zeros((4, 8))
            b = jnp.ones((4, 9))
            return jnp.concatenate([a, b], axis=0)
        """,
    )
    assert _rules(findings) == ["shape-mismatch"]


def test_shape_mismatch_dot_contraction(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            a = jnp.zeros((4, 8))
            b = jnp.ones((9, 5))
            return jnp.dot(a, b)
        """,
    )
    assert _rules(findings) == ["shape-mismatch"]


def test_shape_unratcheted_dim_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np
        import jax

        fn = jax.jit(lambda x: x)

        def drive(pts):
            n = len(pts)
            buf = np.zeros((n, 4), dtype=np.float32)
            return fn(buf)
        """,
    )
    assert _rules(findings) == ["shape-unratcheted-dim"]


def test_shape_ratcheted_dim_is_clean(tmp_path):
    """The repo idiom: a dim routed through a sanctioned padding
    function carries ratchet provenance and passes."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np
        import jax

        fn = jax.jit(lambda x: x)

        def _ratchet(floors, key, val, cap=None):
            return val

        def drive(pts):
            n = _ratchet(None, "k", len(pts))
            buf = np.zeros((n, 4), dtype=np.float32)
            return fn(buf)
        """,
    )
    assert findings == []


def test_hbm_over_budget_constructed_array(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            big = jnp.zeros((1 << 20, 1 << 20), dtype=jnp.float32)
            return big.sum()
        """,
    )
    assert _rules(findings) == ["hbm-over-budget"]


def test_hbm_within_budget_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            tile = jnp.zeros((4096, 4096), dtype=jnp.float32)
            return tile.sum()
        """,
    )
    assert findings == []


def test_hbm_over_budget_family_knobs(tmp_path, monkeypatch):
    """The knob-bound worst-case gate: a tracked_call dispatch family
    whose FAMILY_MODELS envelope exceeds the device budget under the
    LIVE env knobs fails lint before it OOMs a chip."""
    monkeypatch.setenv("DBSCAN_GROUP_SLOTS", str(1 << 34))
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.obs import compile as obs_compile

        def dispatch(fn, pts, mask):
            return obs_compile.tracked_call(
                "dispatch.dense", fn, pts, mask
            )
        """,
    )
    assert _rules(findings) == ["hbm-over-budget"]
    assert "DBSCAN_GROUP_SLOTS" in findings[0].message


def test_shard_indivisible_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def block(x):
            return x * 2

        def drive():
            mesh = jax.make_mesh((4, 2), ("i", "j"))
            fn = jax.jit(shard_map(
                block, mesh=mesh, in_specs=(P("i", None),),
                out_specs=P("i", None),
            ))
            return fn(jnp.zeros((6, 8)))
        """,
    )
    assert _rules(findings) == ["shard-indivisible"]


def test_shard_divisible_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def block(x):
            return x * 2

        def drive():
            mesh = jax.make_mesh((4, 2), ("i", "j"))
            fn = jax.jit(shard_map(
                block, mesh=mesh, in_specs=(P("i", None),),
                out_specs=P("i", None),
            ))
            return fn(jnp.zeros((8, 8)))
        """,
    )
    assert findings == []


# --- halo-merge kernel coverage (PR 12 mesh scale-out) -----------------
#
# Fixture pairs shaped like the collective halo-merge kernel
# (parallel/halo.py _compiled_halo_merge): a ppermute ring + scatter-min
# fixed point under shard_map. Each of the three collective rules gets a
# BAD variant (the hazard injected into the halo shape) and the GOOD
# variant is the real kernel shape, which must stay clean.

_HALO_KERNEL_GOOD = """
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
import numpy as np

PARTS_AXIS = "parts"
HALO_AXIS = "halo"
mesh = Mesh(np.empty((4, 2), object), (PARTS_AXIS, HALO_AXIS))

def build(n_pad, mesh):
    def ring_min(x):
        acc = x
        part = lax.ppermute(x, PARTS_AXIS, [(0, 1)])
        acc = jnp.minimum(acc, part)
        part = lax.ppermute(part, HALO_AXIS, [(0, 1)])
        acc = jnp.minimum(acc, part)
        return acc

    def block(ua, ub):
        def body(state):
            lab, _, it = state
            upd = lab.at[ua].min(lab[ub])
            new = ring_min(upd)
            return new, jnp.any(new != lab), it + 1

        def cond(state):
            _, changed, it = state
            return changed & (it < n_pad)

        init = jnp.arange(n_pad, dtype=jnp.int32)
        state = body((init, jnp.bool_(True), jnp.int32(0)))
        lab, _, iters = lax.while_loop(cond, body, state)
        return lab, iters

    return jax.jit(jax.shard_map(
        block, mesh=mesh,
        in_specs=(PartitionSpec(PARTS_AXIS), PartitionSpec(PARTS_AXIS)),
        out_specs=(PartitionSpec(), PartitionSpec()),
    ))
"""


def test_halo_kernel_shape_is_clean(tmp_path):
    findings, _ = _lint_source(tmp_path, _HALO_KERNEL_GOOD)
    assert findings == []


def test_halo_kernel_collective_in_branch(tmp_path):
    """A ring exchange gated on a traced value — some shards would skip
    their ppermute: the all-chips deadlock the rule exists for."""
    bad = _HALO_KERNEL_GOOD.replace(
        """        init = jnp.arange(n_pad, dtype=jnp.int32)
""",
        """        if ua[0] > 0:
            ub = lax.ppermute(ub, PARTS_AXIS, [(0, 1)])
        init = jnp.arange(n_pad, dtype=jnp.int32)
""",
    )
    assert bad != _HALO_KERNEL_GOOD
    findings, _ = _lint_source(tmp_path, bad)
    assert "collective-in-branch" in _rules(findings)


def test_halo_kernel_axis_undeclared(tmp_path):
    """A typo'd ring axis fails at trace time only on the multichip
    path nobody runs in CI — the static rule catches it here."""
    bad = _HALO_KERNEL_GOOD.replace(
        'part = lax.ppermute(part, HALO_AXIS, [(0, 1)])',
        'part = lax.ppermute(part, "chips", [(0, 1)])',
    )
    assert bad != _HALO_KERNEL_GOOD
    findings, _ = _lint_source(tmp_path, bad)
    assert "collective-axis-undeclared" in _rules(findings)


def test_halo_kernel_pull_in_collective(tmp_path):
    """A host pull reachable from the fixed-point body would interleave
    cross-host transfers inside the collective region."""
    bad = _HALO_KERNEL_GOOD.replace(
        """        init = jnp.arange(n_pad, dtype=jnp.int32)
""",
        """        init = jnp.arange(n_pad, dtype=jnp.int32)
        jax.device_get(init)
""",
    )
    assert bad != _HALO_KERNEL_GOOD
    findings, _ = _lint_source(tmp_path, bad)
    assert "pull-in-collective" in _rules(findings)


def test_halo_mesh_block_shapes_divide(tmp_path):
    """shard-indivisible pin for the halo kernel's mesh-axis block
    shapes: an edge table NOT divisible by the flattened mesh flags,
    and halo._pad_up (the width every live call goes through) always
    produces divisible widths."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def block(ua):
            return ua

        def drive():
            mesh = jax.make_mesh((8,), ("parts",))
            fn = jax.jit(shard_map(
                block, mesh=mesh, in_specs=(P("parts"),),
                out_specs=P("parts"),
            ))
            return fn(jnp.zeros((1001,), jnp.int32))
        """,
    )
    assert _rules(findings) == ["shard-indivisible"]
    from dbscan_tpu.parallel.halo import _pad_up

    for k in (1, 2, 3, 4, 7, 8):
        for n in (0, 1, 127, 128, 129, 5000):
            assert _pad_up(n, k) % k == 0
            assert _pad_up(n, k) >= max(1, n)


def test_halo_merge_family_registered():
    """The new compile family is declared end to end: obs/schema.py
    COMPILE_FAMILIES (counters/spans/devtime ride it automatically) and
    lint/shapes.py FAMILY_MODELS (the shapecheck runtime refuses
    undeclared families)."""
    from dbscan_tpu.lint.shapes import FAMILY_MODELS
    from dbscan_tpu.obs import schema

    assert "halo.merge" in schema.COMPILE_FAMILIES
    assert schema.is_declared("counter", "compiles.halo.merge")
    assert schema.is_declared("span", "devtime.halo.merge")
    model = FAMILY_MODELS["halo.merge"]
    assert [a.name for a in model.args] == ["ua", "ub"]


def test_rules_glob_matches_retired_alias(tmp_path, capsys):
    """--rules dtype-drift (the RETIRED id) still gates the successor's
    findings, so existing CI pipelines survive the rename."""
    bad = tmp_path / "ops"
    bad.mkdir()
    (bad / "k.py").write_text(
        "import jax.numpy as jnp\n\n"
        "def kern(x):\n"
        "    return jnp.asarray(x, dtype='float64')\n"
    )
    assert lint_main(["--rules", "dtype-drift", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "dtype-flow-drift" in out
    # and a disjoint real family still filters it out
    assert lint_main(["--rules", "race-*", str(bad)]) == 0


def test_baseline_written_under_old_rule_id_still_matches(
    tmp_path, capsys
):
    """A baseline row recorded under the retired id suppresses the
    successor's finding (canonicalized on read)."""
    bad = tmp_path / "ops"
    bad.mkdir()
    src = bad / "k.py"
    src.write_text(
        "import jax.numpy as jnp\n\n"
        "def kern(x):\n"
        "    return jnp.asarray(x, dtype='float64')\n"
    )
    base = tmp_path / "baseline.json"
    assert lint_main(["--write-baseline", str(base), str(bad)]) == 0
    capsys.readouterr()
    # rewrite the baseline rows as the OLD linter would have recorded
    # them: retired id AND its old message text — the successor's
    # messages differ by design, so retired-id rows must match
    # message-agnostically (rule+path only)
    payload = json.loads(base.read_text())
    for row in payload["findings"]:
        assert row["rule"] == "dtype-flow-drift"
        row["rule"] = "dtype-drift"
        row["message"] = (
            '"float64" dtype literal in kernel code: the device '
            "kernels are f32/bf16 (config.Precision); a float64 "
            "constant upcasts or retraces the kernel — use the "
            "configured dtype"
        )
    base.write_text(json.dumps(payload))
    assert lint_main(["--baseline", str(base), str(bad)]) == 0
    assert "(1 baselined)" in capsys.readouterr().err


def test_cli_sarif_output_schema(tmp_path, capsys):
    """SARIF 2.1.0 pin: the keys CI code-scanning ingestion reads are
    stable — version/$schema, the driver's rule catalog, and one
    result per finding with a 1-based region."""
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('DBSCAN_X')\n")
    assert lint_main(["--format", "sarif", str(bad)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    assert [r["id"] for r in driver["rules"]] == ["env-direct-read"]
    (result,) = run["results"]
    assert result["ruleId"] == "env-direct-read"
    assert result["level"] == "warning"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"] == {"startLine": 2, "startColumn": 5}
    # exit contract identical across formats: clean file, sarif, exit 0
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    capsys.readouterr()
    assert lint_main(["--format", "sarif", str(good)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


def test_cli_shape_table(capsys):
    assert lint_main(["--shape-table"]) == 0
    out = capsys.readouterr().out
    for family in ("dispatch.dense", "dispatch.banded_p1",
                   "cellcc.postpass", "spill.gather"):
        assert f"`{family}`" in out
    assert "runtime-gated" in out  # the data-scaled families
    from dbscan_tpu.obs import schema

    # every declared compile family has a row (model completeness pin)
    for family in schema.COMPILE_FAMILIES:
        assert f"`{family}`" in out


# --- fault-surface family (graftfault) --------------------------------


def test_fault_retry_unsafe_premature_mutation(tmp_path):
    """The attempt callable bumps a module global BEFORE its fallible
    device op: a transient-fault retry double-counts it."""
    findings, p = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        from dbscan_tpu import faults

        _progress = {"batches": 0}

        def _attempt(budget):
            _progress["batches"] += 1
            return jnp.sum(budget)

        def run():
            return faults.supervised("serve", _attempt)
        """,
    )
    assert _rules(findings) == ["fault-retry-unsafe"]
    assert "_progress" in findings[0].message
    assert findings[0].line == 13  # reported at the supervised call


def test_fault_retry_post_success_mutation_is_clean(tmp_path):
    """The same mutation AFTER the last fallible op is once-per-success
    bookkeeping — the safe shape the rule message prescribes."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        from dbscan_tpu import faults

        _progress = {"batches": 0}

        def _attempt(budget):
            out = jnp.sum(budget)
            _progress["batches"] += 1
            return out

        def run():
            return faults.supervised("serve", _attempt)
        """,
    )
    assert _rules(findings) == []


def test_fault_retry_restore_prologue_is_clean(tmp_path):
    """A callable whose FIRST statement restores a snapshot of the root
    it mutates re-enters idempotently (the serve-ingest fix idiom)."""
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        from dbscan_tpu import faults

        _stream = make_stream()
        _snap = None

        def _attempt(budget):
            _stream.restore_state(_snap)
            _stream.epoch += 1
            return jnp.sum(budget)

        def run():
            return faults.supervised("serve", _attempt)
        """,
    )
    assert _rules(findings) == []


def test_fault_site_undeclared(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        def run():
            return faults.supervised("nosuchsite", lambda b: b)
        """,
    )
    assert _rules(findings) == ["fault-site-undeclared"]
    assert "nosuchsite" in findings[0].message


def test_fault_site_resolved_through_constant_and_shard(tmp_path):
    """Site tokens resolve through module constants and shard_site()
    wraps; a declared site this way is clean (no undeclared finding)."""
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        SITE = "serve"

        def run(shard):
            faults.supervised(SITE, lambda b: b)
            return faults.supervised(
                faults.shard_site("serve", shard), lambda b: b
            )
        """,
    )
    assert _rules(findings) == []


def test_fault_site_undrilled(tmp_path):
    """A consumed declared site with no DBSCAN_FAULT_SPEC clause in
    tests/ is a retry path CI never exercises."""
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_other.py").write_text(
        'SPEC = "dispatch#0:TRANSIENT"\n'
    )
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        def run():
            return faults.supervised("serve", lambda b: b)
        """,
    )
    assert _rules(findings) == ["fault-site-undrilled"]
    assert "serve#0:TRANSIENT" in findings[0].message


def test_fault_site_drilled_is_clean(tmp_path):
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_other.py").write_text(
        '_spec(monkeypatch, "serve#1:TRANSIENT*2")\n'
    )
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        def run():
            return faults.supervised("serve", lambda b: b)
        """,
    )
    assert _rules(findings) == []


def test_fault_degrade_unreachable_without_fallback(tmp_path):
    """Site 'dispatch' declares handler mode fallback-arg; a supervised
    call without fallback= cannot reach the documented ladder."""
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        def run():
            return faults.supervised("dispatch", lambda b: b)
        """,
    )
    assert _rules(findings) == ["fault-degrade-unreachable"]
    assert "cpu-tier" in findings[0].message


def test_fault_degrade_fallback_arg_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import faults

        def run():
            return faults.supervised(
                "dispatch", lambda b: b, fallback=lambda: None
            )
        """,
    )
    assert _rules(findings) == []


def test_atomic_write_violation(tmp_path):
    findings, p = _lint_source(
        tmp_path,
        """
        import json

        def save(path, row):
            with open(path, "w") as f:
                json.dump(row, f)
        """,
    )
    assert _rules(findings) == ["atomic-write-violation"]
    assert findings[0].line == 5
    assert "os.replace" in findings[0].message


def test_atomic_write_tmp_then_replace_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import json
        import os

        def save(path, row):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(row, f)
            os.replace(tmp, path)
        """,
    )
    assert _rules(findings) == []


def test_atomic_write_append_mode_is_exempt(tmp_path):
    """Append is the other crash-tolerant idiom (JSONL ledgers)."""
    findings, _ = _lint_source(
        tmp_path,
        """
        def log(path, line):
            with open(path, "a") as f:
                f.write(line)
        """,
    )
    assert _rules(findings) == []


def test_fixed_persistence_writes_stay_atomic():
    """Regression pin for the real atomic-write-violation findings this
    family surfaced (the campaign --json row and the linter's own
    --write-baseline): the rule is per-file, so a single-file lint
    re-derives any regression."""
    for rel in ("campaign.py", os.path.join("lint", "cli.py")):
        findings, _ = lint_mod.lint_paths([os.path.join(PKG, rel)])
        assert [
            f.render() for f in findings
            if f.rule == "atomic-write-violation"
        ] == []


def test_serve_ingest_retry_safety_is_from_the_restore_prologue():
    """Regression pin for the real fault-retry-unsafe finding: the serve
    ingest attempt re-enters from an export_state() snapshot. Pin BOTH
    halves so the clean repo result is not vacuous: the effect model
    still sees StreamingDBSCAN.update mutating the stream before its
    success point (the hazard), while the service's _attempt wrapper is
    effect-free (the restore prologue exempts it)."""
    from dbscan_tpu.lint import effects as effects_mod
    from dbscan_tpu.lint.core import load_package

    pkg = load_package([PKG])
    pkg.callgraph = cg = cg_mod.build(pkg)
    model = effects_mod.EffectModel(cg)
    mods = {m.modname: m for m in cg.modules.values()}
    upd = mods["dbscan_tpu.streaming"].classes["StreamingDBSCAN"].methods[
        "update"
    ]
    hazards = effects_mod.unsafe_mutations(model, upd)
    assert any(e.root == "self" for e in hazards)  # the raw hazard
    attempts = [
        fi for fi in mods["dbscan_tpu.serve.service"].all_functions
        if fi.node.name == "_attempt"
    ]
    assert attempts  # the wrapper exists ...
    for fi in attempts:  # ... and is retry-safe
        assert effects_mod.unsafe_mutations(model, fi) == []


# --- repo-wide pins ---------------------------------------------------


def test_whole_package_is_lint_clean():
    """THE tier-1 gate: zero findings over dbscan_tpu/ (suppressions
    with reasons are the only allowed escape, and they are visible in
    the diff)."""
    findings, n_files = lint_mod.lint_paths([PKG])
    assert n_files > 40
    assert [f.render() for f in findings] == []


def test_lint_package_self_lints_the_linter():
    findings, n_files = lint_mod.lint_paths(
        [os.path.join(PKG, "lint")]
    )
    assert n_files >= 7
    assert [f.render() for f in findings] == []


def test_tracked_call_sites_metadata():
    sites = cg_mod.tracked_call_sites(PKG)
    assert "dispatch.dense" in sites
    files = {f for f, _ in sites["dispatch.dense"]}
    assert files == {os.path.join("parallel", "driver.py")}
    # every statically visible family is a declared one
    from dbscan_tpu.obs import schema

    assert set(sites) <= set(schema.COMPILE_FAMILIES)


# --- CLI contract -----------------------------------------------------


def test_cli_exit_codes_and_text_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\nv = os.environ.get('DBSCAN_X')\n"
    )
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint_main([str(good)]) == 0
    capsys.readouterr()
    assert lint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "env-direct-read" in out and "bad.py:2:" in out
    assert lint_main([str(tmp_path / "missing.py")]) == 2


def test_cli_json_output_schema(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('DBSCAN_X')\n")
    assert lint_main(["--format", "json", str(bad)]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert set(payload) == {"files_scanned", "baselined", "findings"}
    assert payload["files_scanned"] == 1
    assert payload["baselined"] == 0
    (finding,) = payload["findings"]
    assert set(finding) == {"rule", "path", "line", "col", "message"}
    assert finding["rule"] == "env-direct-read"
    assert finding["line"] == 2
    assert finding["rule"] in lint_mod.RULES


def test_cli_rules_filter(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('DBSCAN_X')\n")
    # matching family still fails ...
    assert lint_main(["--rules", "env-*", str(bad)]) == 1
    capsys.readouterr()
    # ... a disjoint family filter passes the same file ...
    assert lint_main(["--rules", "race-*,collective-*", str(bad)]) == 0
    capsys.readouterr()
    # ... and a glob matching no known rule is a usage error (a typo'd
    # filter must not silently gate nothing)
    assert lint_main(["--rules", "nope-*", str(bad)]) == 2


def test_cli_baseline_gates_new_findings_only(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('DBSCAN_X')\n")
    base = tmp_path / "baseline.json"
    # record the existing debt ...
    assert lint_main(["--write-baseline", str(base), str(bad)]) == 0
    capsys.readouterr()
    # ... baselined findings no longer fail ...
    assert lint_main(["--baseline", str(base), str(bad)]) == 0
    err = capsys.readouterr().err
    assert "(1 baselined)" in err
    # ... a NEW finding does (and baselined stays suppressed), even on
    # a shifted line (baseline matches rule+path+message, not line)
    bad.write_text(
        "import os\n# pushed down\nv = os.environ.get('DBSCAN_X')\n"
        "w = os.getenv('DBSCAN_Y')\n"
    )
    assert lint_main(["--baseline", str(base), str(bad)]) == 1
    out = capsys.readouterr().out
    assert "DBSCAN_Y" in out and "DBSCAN_X" not in out
    # a missing baseline file is exit 2, not a silent full run
    assert lint_main(["--baseline", str(tmp_path / "nope.json"),
                      str(bad)]) == 2


def test_cli_baseline_is_a_multiset(tmp_path, capsys):
    """One baselined occurrence must not absorb a NEWLY ADDED duplicate
    of the same finding (same rule+path+message, different line)."""
    bad = tmp_path / "dup.py"
    bad.write_text("import os\nv = os.getenv('DBSCAN_X')\n")
    base = tmp_path / "baseline.json"
    assert lint_main(["--write-baseline", str(base), str(bad)]) == 0
    capsys.readouterr()
    bad.write_text(
        "import os\nv = os.getenv('DBSCAN_X')\nw = os.getenv('DBSCAN_X')\n"
    )
    assert lint_main(["--baseline", str(base), str(bad)]) == 1
    err = capsys.readouterr().err
    assert "1 finding(s) (1 baselined)" in err


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("host-sync-item", "jit-scalar-arg", "schema-counter",
                 "env-direct-read"):
        assert rule in out


def test_console_entrypoint_gates_repo():
    """The CI command verbatim: python -m dbscan_tpu.lint dbscan_tpu/
    exits 0 on the repo — with EVERY rule family (old + shapes) active.
    The explicit ``--rules`` sweep pins that no family silently drops
    out of the default run: a glob per family, all gating the same
    invocation."""
    all_families = (
        "host-sync-*,jit-*,schema-*,env-*,race-*,collective-*,"
        "pull-in-collective,shape-*,dtype-flow-drift,hbm-over-budget,"
        "shard-indivisible,fault-*,atomic-write-violation,"
        "suppress-*,parse-error"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "dbscan_tpu.lint", "--rules",
         all_families, PKG],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # the filtered sweep and the default run gate identically
    proc = subprocess.run(
        [sys.executable, "-m", "dbscan_tpu.lint", PKG],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # every rule family id is registered (catalog completeness)
    from dbscan_tpu import lint as _lm

    for rule in ("shape-mismatch", "shape-unratcheted-dim",
                 "dtype-flow-drift", "hbm-over-budget",
                 "shard-indivisible", "fault-retry-unsafe",
                 "fault-site-undeclared", "fault-site-undrilled",
                 "fault-degrade-unreachable", "atomic-write-violation"):
        assert rule in _lm.RULES
    assert _lm.ALIASES == {"dtype-drift": "dtype-flow-drift"}
