"""graftlint (dbscan_tpu/lint/): fixture pairs per rule family, the
repo-wide lint-clean pin, suppression semantics, and the CLI contract.

The repo-wide test is the enforcement point of this PR's contracts:
``python -m dbscan_tpu.lint dbscan_tpu/`` exits 0, so any emission of
an undeclared telemetry name, any direct ``DBSCAN_*`` environ read, and
any trace-reachable host sync fails tier-1 CI the moment it lands.
Every bad-snippet fixture asserts the exact rule id AND line so the
findings stay actionable; every good-snippet twin pins the rule's
false-positive boundary.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from dbscan_tpu import lint as lint_mod
from dbscan_tpu.lint import callgraph as cg_mod
from dbscan_tpu.lint.cli import main as lint_main

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dbscan_tpu")


def _lint_source(tmp_path, source, name="snippet.py", subdir=None):
    d = tmp_path if subdir is None else tmp_path / subdir
    d.mkdir(parents=True, exist_ok=True)
    p = d / name
    p.write_text(textwrap.dedent(source))
    findings, _ = lint_mod.lint_paths([str(p)])
    return findings, str(p)


def _rules(findings):
    return [f.rule for f in findings]


# --- host-sync family -------------------------------------------------


def test_hostsync_item_in_jit_root(tmp_path):
    findings, path = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            s = jnp.sum(x)
            return s.item()
        """,
    )
    assert _rules(findings) == ["host-sync-item"]
    assert findings[0].path == path and findings[0].line == 8


def test_hostsync_item_transitively_reachable(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        def helper(v):
            return v.item()

        @jax.jit
        def root(x):
            return helper(jnp.sum(x))
        """,
    )
    assert _rules(findings) == ["host-sync-item"]
    assert findings[0].line == 6  # reported in the helper, not the root


def test_hostsync_item_clean_outside_jit(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        def host_pull(x):
            return jnp.sum(x).item()
        """,
    )
    assert findings == []  # not reachable from any jit site


def test_hostsync_cast_on_array_expression(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            return float(jnp.sum(x))
        """,
    )
    assert _rules(findings) == ["host-sync-cast"]


def test_hostsync_cast_shape_and_static_are_exempt(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n",))
        def root(x, n):
            pad = int(n) + int(x.shape[0])
            return jnp.pad(x, (0, pad))
        """,
    )
    assert findings == []  # static param + shape arithmetic stay clean


def test_hostsync_asarray_on_traced_value(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np

        @jax.jit
        def root(x):
            return np.asarray(x)
        """,
    )
    assert _rules(findings) == ["host-sync-asarray"]


def test_hostsync_asarray_literal_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax
        import numpy as np
        import jax.numpy as jnp

        @jax.jit
        def root(x):
            return x + jnp.asarray(np.asarray([1.0, 2.0]))
        """,
    )
    assert findings == []


# --- recompile family -------------------------------------------------


def test_jit_in_loop_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        def run(xs):
            out = []
            for x in xs:
                f = jax.jit(lambda a: a + 1)
                out.append(f(x))
            return out
        """,
    )
    assert "jit-in-loop" in _rules(findings)


def test_jit_hoisted_out_of_loop_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        f = jax.jit(lambda a: a + 1)

        def run(xs):
            return [f(x) for x in xs]
        """,
    )
    assert findings == []


def test_jit_scalar_arg_without_statics(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax

        @jax.jit
        def g(x, n):
            return x * n

        def call(x):
            return g(x, 3)
        """,
    )
    assert _rules(findings) == ["jit-scalar-arg"]
    assert findings[0].line == 9


def test_jit_scalar_arg_with_statics_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            return x * n

        def call(x):
            return g(x, 3)
        """,
    )
    assert findings == []


def test_dtype_drift_in_kernel_path(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import jax.numpy as jnp

        def kern(x):
            return jnp.asarray(x, dtype="float64")
        """,
        name="kern.py",
        subdir="ops",
    )
    assert _rules(findings) == ["dtype-drift"]


def test_dtype_f32_kernel_and_host_f64_are_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def kern(x):
            return jnp.asarray(x, dtype=jnp.float32)

        def host_grid(c):
            return np.asarray(c, dtype=np.float64)
        """,
        name="kern2.py",
        subdir="ops",
    )
    assert findings == []  # host np.* f64 is exempt by design


# --- telemetry-schema family ------------------------------------------


def test_schema_undeclared_counter(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit():
            obs.count("nonexistent.counter")
        """,
    )
    assert _rules(findings) == ["schema-counter"]
    assert findings[0].line == 5


def test_schema_declared_names_are_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit(fam):
            obs.count("transfer.h2d_bytes", 128)
            obs.gauge("memory.bytes_in_use", 1)
            obs.event("fault.retry", site="dispatch")
            with obs.span("spill.pivots", node=3):
                pass
            obs.count(f"compiles.{fam}")
        """,
    )
    assert findings == []


def test_schema_dynamic_prefix_must_match(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import obs

        def emit(fam):
            obs.count(f"zzz.{fam}")
        """,
    )
    assert _rules(findings) == ["schema-dynamic"]


def test_schema_family_literal_checked(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.obs import compile as obs_compile

        def dispatch(fn, x):
            return obs_compile.tracked_call("not.a.family", fn, x)
        """,
    )
    assert _rules(findings) == ["schema-family"]


def test_schema_known_family_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.obs import compile as obs_compile

        def dispatch(fn, x):
            return obs_compile.tracked_call("dispatch.dense", fn, x)
        """,
    )
    assert findings == []


def test_deleting_declared_counter_breaks_lint(tmp_path, monkeypatch):
    """The acceptance contract: remove an emitted counter from
    obs/schema.py and the linter flags the emission site."""
    from dbscan_tpu.obs import schema

    src = """
    from dbscan_tpu import obs

    def emit():
        obs.count("transfer.h2d_bytes", 128)
    """
    findings, _ = _lint_source(tmp_path, src)
    assert findings == []
    monkeypatch.delitem(schema.COUNTERS, "transfer.h2d_bytes")
    findings, _ = _lint_source(tmp_path, src, name="snippet2.py")
    assert _rules(findings) == ["schema-counter"]


# --- env-registry family ----------------------------------------------


def test_env_direct_read_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import os

        def knobs():
            a = os.environ.get("DBSCAN_SOMETHING", "1")
            b = os.getenv("DBSCAN_OTHER")
            c = os.environ["DBSCAN_THIRD"]
            return a, b, c
        """,
    )
    assert _rules(findings) == ["env-direct-read"] * 3
    assert [f.line for f in findings] == [5, 6, 7]


def test_env_accessor_of_declared_name_is_clean(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu import config

        def knob():
            return config.env("DBSCAN_GROUP_SLOTS")
        """,
    )
    assert findings == []


def test_env_undeclared_name_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        from dbscan_tpu.config import env

        def knob():
            return env("DBSCAN_NOT_A_REAL_KNOB")
        """,
    )
    assert _rules(findings) == ["env-undeclared"]


def test_non_dbscan_env_reads_ignored(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        import os

        def other():
            return os.environ.get("JAX_PLATFORMS", "")
        """,
    )
    assert findings == []


def test_every_declared_env_var_documented_in_parity():
    """Row-marker check, not substring: DBSCAN_TRACE inside the
    DBSCAN_TRACE_MAX_SPANS row (or a prose mention) must not satisfy
    the missing-row case."""
    from dbscan_tpu.config import ENV_VARS

    with open(os.path.join(REPO, "PARITY.md"), encoding="utf-8") as f:
        text = f.read()
    missing = [n for n in ENV_VARS if f"| `{n}` |" not in text]
    assert missing == []


def test_env_parity_detects_deleted_table_row(tmp_path):
    """Deleting one variable's table row from PARITY.md fires
    env-parity even though the name still appears elsewhere in the
    file (the substring false-negative the row marker exists for)."""
    import shutil

    pkg_copy = tmp_path / "dbscan_tpu"
    shutil.copytree(PKG, pkg_copy, ignore=shutil.ignore_patterns("__pycache__"))
    with open(os.path.join(REPO, "PARITY.md"), encoding="utf-8") as f:
        lines = f.read().splitlines(keepends=True)
    kept = [ln for ln in lines if not ln.startswith("| `DBSCAN_TRACE` |")]
    assert len(kept) == len(lines) - 1
    (tmp_path / "PARITY.md").write_text("".join(kept))
    findings, _ = lint_mod.lint_paths([str(pkg_copy / "config.py")])
    parity = [f for f in findings if f.rule == "env-parity"]
    assert [
        f for f in parity if "'DBSCAN_TRACE'" in f.message
    ], parity


# --- suppressions -----------------------------------------------------

_SUPPRESSIBLE = """
import jax
import jax.numpy as jnp

@jax.jit
def root(x):
    return float(jnp.sum(x)){comment}
"""


def test_suppression_with_reason_silences(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _SUPPRESSIBLE.format(
            comment="  # graftlint: disable=host-sync-cast  scalar loss"
        ),
    )
    assert findings == []


def test_suppression_without_reason_keeps_finding(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        _SUPPRESSIBLE.format(
            comment="  # graftlint: disable=host-sync-cast"
        ),
    )
    assert sorted(_rules(findings)) == [
        "host-sync-cast",
        "suppress-no-reason",
    ]


def test_suppression_unknown_rule_flags(tmp_path):
    findings, _ = _lint_source(
        tmp_path,
        """
        x = 1  # graftlint: disable=not-a-rule  because reasons
        """,
    )
    assert _rules(findings) == ["suppress-unknown-rule"]


# --- repo-wide pins ---------------------------------------------------


def test_whole_package_is_lint_clean():
    """THE tier-1 gate: zero findings over dbscan_tpu/ (suppressions
    with reasons are the only allowed escape, and they are visible in
    the diff)."""
    findings, n_files = lint_mod.lint_paths([PKG])
    assert n_files > 40
    assert [f.render() for f in findings] == []


def test_lint_package_self_lints_the_linter():
    findings, n_files = lint_mod.lint_paths(
        [os.path.join(PKG, "lint")]
    )
    assert n_files >= 7
    assert [f.render() for f in findings] == []


def test_tracked_call_sites_metadata():
    sites = cg_mod.tracked_call_sites(PKG)
    assert "dispatch.dense" in sites
    files = {f for f, _ in sites["dispatch.dense"]}
    assert files == {os.path.join("parallel", "driver.py")}
    # every statically visible family is a declared one
    from dbscan_tpu.obs import schema

    assert set(sites) <= set(schema.COMPILE_FAMILIES)


# --- CLI contract -----------------------------------------------------


def test_cli_exit_codes_and_text_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\nv = os.environ.get('DBSCAN_X')\n"
    )
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint_main([str(good)]) == 0
    capsys.readouterr()
    assert lint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "env-direct-read" in out and "bad.py:2:" in out
    assert lint_main([str(tmp_path / "missing.py")]) == 2


def test_cli_json_output_schema(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nv = os.environ.get('DBSCAN_X')\n")
    assert lint_main(["--format", "json", str(bad)]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert set(payload) == {"files_scanned", "findings"}
    assert payload["files_scanned"] == 1
    (finding,) = payload["findings"]
    assert set(finding) == {"rule", "path", "line", "col", "message"}
    assert finding["rule"] == "env-direct-read"
    assert finding["line"] == 2
    assert finding["rule"] in lint_mod.RULES


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("host-sync-item", "jit-scalar-arg", "schema-counter",
                 "env-direct-read"):
        assert rule in out


def test_console_entrypoint_gates_repo():
    """The CI command verbatim: python -m dbscan_tpu.lint dbscan_tpu/
    exits 0 on the repo."""
    proc = subprocess.run(
        [sys.executable, "-m", "dbscan_tpu.lint", PKG],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
