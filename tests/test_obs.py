"""Observability subsystem (dbscan_tpu/obs/): spans, counters, export.

Design constraints pinned here (obs/__init__.py module contract):

- the DISABLED path is a strict no-op — one truthiness check per call
  site, the shared NOOP_SPAN, no registry growth, no file ever touched
  — plus an overhead guard comparing a small train() against a build
  whose tracing hooks are monkeypatched away entirely;
- spans nest by thread-local stack and the Chrome-trace export is
  valid Perfetto-loadable JSON (ph/ts/dur fields, microsecond times);
- the fault-accounting bridge: under the deterministic injection suite
  (``DBSCAN_FAULT_SPEC``), the obs ``faults.*`` counter delta equals
  ``stats["faults"]`` field-for-field and the trace carries the retry
  events — the three views (stats, timings, trace) can never disagree,
  with stats["faults"] the documented authoritative per-run figure.
"""

import json
import os
import time

import numpy as np
import pytest

from dbscan_tpu import Engine, faults, obs, train
from dbscan_tpu.obs.trace import NOOP_SPAN

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Every test starts (and leaves) the process with observability
    disabled, no trace env, and the always-on flight recorder OFF (its
    ring would otherwise catch the hooks these tests pin as strict
    no-ops; the recorder has its own suite, tests/test_flight.py)."""
    from dbscan_tpu.obs import flight

    monkeypatch.delenv("DBSCAN_TRACE", raising=False)
    monkeypatch.setenv("DBSCAN_FLIGHTREC", "0")
    flight.reset()
    obs.disable()
    yield
    obs.disable()
    flight.reset()


def _blobs(n_per=300):
    rng = np.random.default_rng(0)
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (n_per, 2)) for c in centers]
    )
    rng.shuffle(pts)
    return pts


KW = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY,
)


# --- disabled path is a strict no-op ----------------------------------


def test_disabled_hooks_are_noops(tmp_path):
    assert obs.state() is None and not obs.active()
    sp = obs.span("anything", a=1)
    assert sp is NOOP_SPAN
    # the shared span swallows the whole protocol without allocating
    with sp as s:
        s.event("x", k=2)
        s.sync(object())
    sp.end()
    assert obs.add_span("x", 0.0, 1.0) is None
    obs.event("x", a=1)
    obs.count("c", 5)
    obs.gauge("g", 7)
    obs.timed_count("t", time.perf_counter())
    assert obs.counters() == {}
    assert obs.counters_delta({}) == {}
    assert obs.flush() is None
    assert obs.write(str(tmp_path / "never.json")) is None
    assert not list(tmp_path.iterdir())  # no file was ever touched
    assert obs.state() is None  # and no registry ever materialized
    summ = obs.summary()
    assert summ == {
        "enabled": False, "spans": [], "counters": {}, "gauges": {}
    }


def test_disabled_train_leaves_no_state(tmp_path):
    """A full pipeline run with observability off must not create the
    registry, and DBSCAN_TRACE unset must not create any file."""
    train(_blobs(100), **KW)
    assert obs.state() is None
    assert not list(tmp_path.iterdir())


def test_ensure_env_activates_only_when_set(monkeypatch, tmp_path):
    obs.ensure_env()
    assert obs.state() is None
    path = str(tmp_path / "t.json")
    monkeypatch.setenv("DBSCAN_TRACE", path)
    obs.ensure_env()
    st = obs.state()
    assert st is not None and st.trace_path == path


# --- span mechanics ---------------------------------------------------


def test_span_nesting_depth_and_finish_order():
    obs.enable()
    with obs.span("outer", level=0) as outer:
        with obs.span("inner") as inner:
            obs.event("mark", k=1)
        with obs.span("inner2"):
            pass
    spans = obs.state().tracer.snapshot_spans()
    by_name = {s.name: s for s in spans}
    assert by_name["outer"].depth == 0
    assert by_name["inner"].depth == 1
    assert by_name["inner2"].depth == 1
    # registry appends at END time: children land before their parent
    assert [s.name for s in spans] == ["inner", "inner2", "outer"]
    assert by_name["inner"].t0 >= by_name["outer"].t0
    assert by_name["outer"].t1 >= by_name["inner2"].t1
    # the instant attached to the innermost open span at event time
    assert [e[0] for e in by_name["inner"].events] == ["mark"]
    assert inner is by_name["inner"] and outer is by_name["outer"]


def test_span_end_idempotent_and_retroactive_spans():
    obs.enable()
    sp = obs.span("s")
    sp.end()
    t1 = sp.t1
    sp.end()  # second end must not move the boundary or re-register
    assert sp.t1 == t1
    assert len(obs.state().tracer.snapshot_spans()) == 1
    r = obs.add_span("retro", 1.0, 2.5, phase="merge")
    assert r.t0 == 1.0 and r.t1 == 2.5 and r.args == {"phase": "merge"}


def test_span_end_releases_sync_handle():
    """The sync handle must be dropped at end() even WITHOUT device-sync
    boundaries: finished spans live in the registry for the process
    lifetime, and a retained reference would pin the device buffers
    (the ~1 GB resident payload) against reclamation."""
    obs.enable()
    assert obs.state().tracer.device_sync is False
    payload = object()
    with obs.span("s") as sp:
        sp.sync(payload)
    assert sp._sync is None


def test_span_retention_bound(monkeypatch):
    """Past DBSCAN_TRACE_MAX_SPANS the oldest half is dropped and the
    drop is reported in the export — a long-lived traced stream must
    not grow memory or flush cost without bound."""
    from dbscan_tpu.obs import export

    obs.enable()
    tracer = obs.state().tracer
    tracer.max_spans = 1024  # floor enforced by Tracer.__init__
    for i in range(1024 + 1):
        obs.add_span(f"s{i}", float(i), float(i) + 0.5)
    assert len(tracer.spans) <= 1024
    assert tracer.dropped_spans > 0
    # the TAIL survives (the interesting part of a live process)
    assert tracer.spans[-1].name == "s1024"
    trace = export.chrome_trace(tracer)
    assert trace["otherData"]["dropped_spans"] == tracer.dropped_spans
    recs = list(export.jsonl_records(tracer))
    assert recs[-1] == {
        "type": "dropped_spans", "value": tracer.dropped_spans
    }


def test_process_level_instants_outside_spans():
    obs.enable()
    obs.event("free", a=1)
    assert [i[0] for i in obs.state().tracer.instants] == ["free"]


def test_counters_and_delta():
    obs.enable()
    obs.count("a")
    obs.count("a", 2)
    obs.count("b", 0.5)
    obs.gauge("g", 42)
    snap = obs.counters()
    assert snap == {"a": 3, "b": 0.5}
    obs.count("a", 4)
    assert obs.counters_delta(snap) == {"a": 4, "b": 0.0}
    assert obs.summary()["gauges"] == {"g": 42}


def test_enable_idempotent_adopts_trace_path(tmp_path):
    st = obs.enable()
    obs.count("k")
    path = str(tmp_path / "late.json")
    st2 = obs.enable(trace_path=path)
    assert st2 is st and st.trace_path == path
    assert obs.counters() == {"k": 1}  # registries survived the re-enable


# --- export -----------------------------------------------------------


def test_chrome_trace_is_valid_perfetto_json(tmp_path):
    path = str(tmp_path / "trace.json")
    obs.enable(trace_path=path)
    with obs.span("parent", n=3):
        with obs.span("child"):
            obs.event("retry", attempt=1)
    obs.count("transfer.h2d_bytes", 1024)
    out = obs.flush()
    assert out == path
    with open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        # "M" = the PR-9 process_name track metadata (shard identity)
        assert e["ph"] in ("X", "i", "C", "M")
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert "name" in e and "pid" in e
    # exactly one process_name metadata record names this track
    assert [e["args"]["name"] for e in evs if e["ph"] == "M"] == [
        f"dbscan pid {os.getpid()}"
    ]
    # the merge anchors ride otherData
    assert trace["otherData"]["pid"] == os.getpid()
    assert trace["otherData"]["epoch0"] > 0
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"parent", "child"}
    for e in xs:
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # events are start-time ordered: parent precedes child
    assert [e["name"] for e in xs] == ["parent", "child"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert instants and instants[0]["name"] == "retry"
    counters = [e for e in evs if e["ph"] == "C"]
    assert {c["name"] for c in counters} == {"transfer.h2d_bytes"}
    assert counters[0]["args"]["value"] == 1024


def test_jsonl_export(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(trace_path=path)
    with obs.span("a"):
        pass
    obs.count("c", 2)
    obs.flush()
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    kinds = {r["type"] for r in records}
    assert kinds == {"meta", "span", "counter"}
    # the leading meta record carries the clock anchor + track identity
    # the --merge mode aligns shards on
    assert records[0]["type"] == "meta"
    assert records[0]["pid"] == os.getpid()
    assert records[0]["epoch0"] > 0 and records[0]["shard"] is None
    span_rec = next(r for r in records if r["type"] == "span")
    assert span_rec["name"] == "a" and span_rec["dur_s"] >= 0


def test_trace_args_coerce_numpy(tmp_path):
    path = str(tmp_path / "np.json")
    obs.enable(trace_path=path)
    with obs.span(
        "np", n=np.int64(7), f=np.float32(0.5), shape=(np.int32(2), 3)
    ):
        pass
    obs.flush()
    with open(path) as f:
        trace = json.load(f)  # must not raise on numpy scalars
    args = trace["traceEvents"][0]["args"]
    assert args["n"] == 7 and args["shape"] == [2, 3]


# --- pipeline integration ---------------------------------------------


def test_small_train_writes_trace_via_env(monkeypatch, tmp_path):
    """DBSCAN_TRACE=path on a real train(): the file exists, loads as a
    Chrome trace, and carries the driver phase spans, the dispatch
    spans, and the root `train` span — the stats timings and the trace
    describe the same run."""
    path = str(tmp_path / "run.json")
    monkeypatch.setenv("DBSCAN_TRACE", path)
    out = train(_blobs(), **KW)
    assert os.path.exists(path)
    with open(path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs}
    assert "train" in names
    assert "driver.histogram" in names
    assert names & {"dispatch.banded", "dispatch.dense", "dispatch.resident"}
    # the phase spans carry the timings key they mirror
    hist = next(e for e in evs if e["name"] == "driver.histogram")
    assert hist["args"]["timings_key"] == "histogram_s"
    assert "histogram_s" in out.stats["timings"]
    # transfer accounting saw the dispatch uploads and the label pulls
    counters = {e["name"]: e["args"]["value"] for e in evs if e["ph"] == "C"}
    assert counters.get("transfer.h2d_bytes", 0) > 0
    assert counters.get("transfer.d2h_bytes", 0) > 0


def test_streaming_update_span(monkeypatch, tmp_path):
    from dbscan_tpu import StreamingDBSCAN

    path = str(tmp_path / "stream.json")
    monkeypatch.setenv("DBSCAN_TRACE", path)
    s = StreamingDBSCAN(eps=0.5, min_points=5, max_points_per_partition=128)
    s.update(_blobs(60))
    s.update(_blobs(60))
    with open(path) as f:
        trace = json.load(f)
    ups = [
        e for e in trace["traceEvents"] if e["name"] == "stream.update"
    ]
    assert len(ups) == 2
    assert [e["args"]["update"] for e in ups] == [1, 2]


# --- fault-accounting bridge (the consistency satellite) --------------


@pytest.mark.faults
def test_fault_counters_agree_with_stats(monkeypatch):
    """Under injected faults the obs counter delta, stats['faults'],
    and the trace's retry events all describe the same run —
    stats['faults'] being the authoritative per-run figure."""
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:TRANSIENT*2")
    faults.reset_registry()
    obs.enable()
    snap = obs.counters()
    pts = _blobs()
    out = train(pts, neighbor_backend="dense", **KW)
    delta = obs.counters_delta(snap)
    fa = out.stats["faults"]
    assert fa["retries"] == 2 and fa["injected"] == 2
    for field in (
        "attempts", "retries", "fallbacks", "budget_halvings", "injected"
    ):
        assert delta.get(f"faults.{field}", 0) == fa[field], field
    assert abs(delta.get("faults.backoff_s", 0.0) - fa["backoff_s"]) < 1e-9
    # timings mirrors the authoritative backoff figure exactly
    assert out.stats["timings"]["fault_backoff_s"] == fa["backoff_s"]
    # the retry events rode the trace (attached to the dispatch span)
    retries = [
        e
        for sp in obs.state().tracer.snapshot_spans()
        for e in sp.events
        if e[0] == "fault.retry"
    ] + [
        i for i in obs.state().tracer.instants if i[0] == "fault.retry"
    ]
    assert len(retries) == 2
    assert all(e[2]["site"] == "dispatch" for e in retries)
    # and the per-run delta instant matches stats["faults"]
    run_deltas = [
        i for i in obs.state().tracer.instants if i[0] == "faults.run_delta"
    ]
    assert run_deltas and run_deltas[-1][2] == fa
    faults.reset_registry()


@pytest.mark.faults
def test_fallback_event_present(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:PERSISTENT")
    faults.reset_registry()
    obs.enable()
    snap = obs.counters()
    out = train(_blobs(), neighbor_backend="dense", **KW)
    delta = obs.counters_delta(snap)
    assert out.stats["faults"]["fallbacks"] == 1
    assert delta.get("faults.fallbacks", 0) == 1
    evs = [
        e
        for sp in obs.state().tracer.snapshot_spans()
        for e in sp.events
        if e[0] == "fault.fallback"
    ]
    assert len(evs) == 1 and evs[0][2]["site"] == "dispatch"
    faults.reset_registry()


# --- bench integration -------------------------------------------------


def test_bench_rep_fields_split_upload_from_compute():
    import bench

    pts = _blobs(150)
    model, dt, rep_obs = bench.run_train(
        pts, 256, reps=1, eps=0.5, min_points=5
    )
    assert model is not None and dt > 0
    assert rep_obs["upload_s"] >= 0.0
    assert rep_obs["compute_s"] >= 0.0
    # fields are rounded to 1 ms, so allow that much slack
    assert rep_obs["upload_s"] + rep_obs["compute_s"] <= dt + 2e-3
    # euclidean never touches the resident cache: no hot/cold tag
    assert "resident_hot" not in rep_obs


def test_bench_rep_fields_tag_resident_cache(monkeypatch):
    """Cosine resident mode: a cold rep (miss) then a hot rep (hit) —
    the tag bench.py stamps on every timed rep."""
    import bench

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")  # resident on CPU
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(600, 16)).astype(np.float32)
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    obs.enable()
    kw = dict(
        eps=0.05, min_points=4, max_points_per_partition=128,
        metric="cosine",
    )
    snap = obs.counters()
    t0 = time.perf_counter()
    train(pts, **kw)  # cold: builds + caches the resident payload
    cold = bench._rep_obs_fields(
        obs.counters_delta(snap), time.perf_counter() - t0
    )
    snap = obs.counters()
    t0 = time.perf_counter()
    train(pts, **kw)  # hot: identity + checksum hit
    hot = bench._rep_obs_fields(
        obs.counters_delta(snap), time.perf_counter() - t0
    )
    assert cold["resident_hot"] is False
    assert cold["upload_bytes"] > 0
    assert hot["resident_hot"] is True
    assert hot["upload_bytes"] == 0 and hot["upload_s"] == 0.0


# --- memory watermarks (obs/memory.py) --------------------------------


_FAKE_STATS = {
    "tpu:0": {
        "bytes_in_use": 1_000_000,
        "peak_bytes_in_use": 3_000_000,
        "bytes_limit": 16_000_000_000,
    },
    "tpu:1": {"bytes_in_use": 500_000, "peak_bytes_in_use": 600_000},
}


@pytest.fixture
def fake_hbm(monkeypatch):
    from dbscan_tpu.obs import memory

    stats = {k: dict(v) for k, v in _FAKE_STATS.items()}
    monkeypatch.setattr(memory, "device_memory_stats", lambda: stats)
    memory.reset_peak()  # drop the availability latch + peak
    yield stats
    memory.reset_peak()


def test_memory_sample_disabled_is_noop(fake_hbm):
    from dbscan_tpu.obs import memory

    assert obs.state() is None
    assert memory.sample("anywhere") is None


def test_memory_sample_gauges_and_peak(fake_hbm):
    from dbscan_tpu.obs import memory

    obs.enable()
    assert memory.sample("dispatch.dense") == 1_500_000
    g = obs.summary()["gauges"]
    assert g["memory.bytes_in_use"] == 1_500_000
    # peak = max(allocator-reported peaks, observed in-use)
    assert g["memory.peak_bytes_in_use"] == 3_600_000
    assert g["memory.bytes_limit"] == 16_000_000_000
    assert g["memory.at.dispatch.dense"] == 1_500_000
    # the process watermark is monotone even when in-use drops
    fake_hbm["tpu:0"]["bytes_in_use"] = 100
    fake_hbm["tpu:0"]["peak_bytes_in_use"] = 0
    fake_hbm["tpu:1"]["peak_bytes_in_use"] = 0
    memory.sample("spill.payload_upload")
    g = obs.summary()["gauges"]
    assert g["memory.bytes_in_use"] == 500_100
    assert g["memory.peak_bytes_in_use"] == 3_600_000
    assert g["memory.at.spill.payload_upload"] == 500_100
    assert obs.counters()["memory.samples"] == 2


def test_memory_unavailable_backend_latches(monkeypatch):
    """CPU backends (memory_stats() -> None) degrade to a no-op after
    ONE probe: the sampler must not re-walk jax.devices() per dispatch."""
    from dbscan_tpu.obs import memory

    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        return {}

    monkeypatch.setattr(memory, "device_memory_stats", probe)
    memory.reset_peak()
    obs.enable()
    assert memory.sample("a") is None
    assert memory.sample("b") is None
    assert calls["n"] == 1  # second sample hit the latch
    assert "memory.bytes_in_use" not in obs.summary()["gauges"]
    memory.reset_peak()


@pytest.mark.faults
def test_budget_halving_records_hbm_occupancy(monkeypatch, fake_hbm):
    """A RESOURCE_EXHAUSTED halving event carries the observed HBM
    occupancy (the figure faults.py used to react to blindly)."""
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    monkeypatch.setenv(
        "DBSCAN_FAULT_SPEC", "dispatch#0:RESOURCE_EXHAUSTED*1"
    )
    faults.reset_registry()
    obs.enable()
    out = train(_blobs(), neighbor_backend="dense", **KW)
    assert out.stats["faults"]["budget_halvings"] == 1
    halved = [
        e
        for sp in obs.state().tracer.snapshot_spans()
        for e in sp.events
        if e[0] == "fault.budget_halved"
    ] + [
        i
        for i in obs.state().tracer.instants
        if i[0] == "fault.budget_halved"
    ]
    assert len(halved) == 1
    assert halved[0][2]["hbm_bytes_in_use"] == 1_500_000
    assert (
        obs.summary()["gauges"]["memory.at.fault.resource_exhausted"]
        == 1_500_000
    )
    faults.reset_registry()


# --- compile accounting (obs/compile.py) ------------------------------


def test_tracked_call_counts_cache_misses():
    import jax
    import jax.numpy as jnp

    from dbscan_tpu.obs import compile as obs_compile

    obs_compile.reset()
    fn = jax.jit(lambda x: x + 1)
    # disabled: strict pass-through, nothing counted
    assert obs.state() is None
    obs_compile.tracked_call("t.fam", fn, jnp.ones(3))
    obs.enable()
    snap = obs.counters()
    assert "compiles.total" not in snap
    # warm shape: cache hit, no compile recorded
    obs_compile.tracked_call("t.fam", fn, jnp.ones(3))
    assert "compiles.total" not in obs.counters()
    # fresh shape: cache miss -> counters + a compile-wall span
    obs_compile.tracked_call("t.fam", fn, jnp.ones(7))
    c = obs.counters()
    assert c["compiles.total"] == 1 and c["compiles.t.fam"] == 1
    assert c["compiles.wall_s"] > 0
    names = [s.name for s in obs.state().tracer.snapshot_spans()]
    assert "compile.t.fam" in names
    assert obs_compile.family_compiles()["t.fam"] == 1
    obs_compile.reset()


def test_recompile_storm_warns_once(monkeypatch, caplog):
    import jax
    import jax.numpy as jnp

    from dbscan_tpu.obs import compile as obs_compile

    monkeypatch.setenv("DBSCAN_COMPILE_STORM_THRESHOLD", "2")
    obs_compile.reset()
    obs.enable()
    fn = jax.jit(lambda x: x * 2)
    with caplog.at_level("WARNING", logger="dbscan_tpu.obs.compile"):
        for n in range(3, 8):  # 5 distinct shapes -> 5 compiles
            obs_compile.tracked_call("storm.fam", fn, jnp.ones(n))
    storms = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storms) == 1  # warned once, not per compile
    assert obs_compile.family_compiles()["storm.fam"] == 5
    assert obs_compile.warn_on_recompile_storm("storm.fam") is True
    assert obs_compile.warn_on_recompile_storm("quiet.fam") is False
    obs_compile.reset()


def test_storm_warning_names_call_site(monkeypatch, caplog):
    """The PR-4 bugfix: the recompile-storm warning names the offending
    call-site file:line (runtime frame of the tracked_call), so a storm
    points at the dispatch that mints signatures, not just a family."""
    import jax
    import jax.numpy as jnp

    from dbscan_tpu.obs import compile as obs_compile

    monkeypatch.setenv("DBSCAN_COMPILE_STORM_THRESHOLD", "2")
    obs_compile.reset()
    obs.enable()
    fn = jax.jit(lambda x: x * 3)
    with caplog.at_level("WARNING", logger="dbscan_tpu.obs.compile"):
        for n in range(3, 8):
            obs_compile.tracked_call("site.fam", fn, jnp.ones(n))
    storms = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storms) == 1
    assert "test_obs.py:" in storms[0].getMessage()
    obs_compile.reset()


def test_storm_site_falls_back_to_static_callgraph():
    """With no runtime miss observed for a family, the storm attribution
    uses the linter's static tracked_call metadata (file:line of the
    dispatch call sites in the package source)."""
    from dbscan_tpu.obs import compile as obs_compile

    obs_compile.reset()
    site = obs_compile._known_sites("dispatch.dense")
    assert "parallel" in site and "driver.py:" in site
    assert obs_compile._known_sites("no.such.family") == "unknown call site"
    obs_compile.reset()


def test_all_runtime_telemetry_names_are_declared(monkeypatch):
    """obs/schema.py is the single source of truth: every counter,
    gauge, span, and event name a real run (with fault retries
    injected, and with the PULL PIPELINE live so the pull.* family is
    exercised too) emits is declared there. Deleting an emitted name
    from the schema fails this test at runtime and the linter
    (tests/test_lint.py) statically."""
    from dbscan_tpu.obs import schema
    from dbscan_tpu.parallel import pipeline as pipe_mod

    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:TRANSIENT*1")
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    faults.reset_registry()
    pipe_mod.reset_engine()
    try:
        obs.enable()
        train(_blobs(), **KW)
        st = obs.state()
        counters = st.metrics.counters()
        # the pipelined train really emitted its pull telemetry (the
        # engine worker drains before train returns, so the counters
        # are complete by now)
        assert counters.get("pull.busy_s", 0) > 0
        assert "pull.inflight" in st.metrics.gauges()
        for name in counters:
            assert schema.is_declared("counter", name), name
        for name in st.metrics.gauges():
            assert schema.is_declared("gauge", name), name
        span_names = {sp.name for sp in st.tracer.spans}
        assert "pull.chunk" in span_names
        for name in span_names:
            assert schema.is_declared("span", name), name
        event_names = {
            ev[0] for sp in st.tracer.spans for ev in sp.events
        } | {name for (name, _t, _a) in st.tracer.instants}
        assert event_names  # the injected fault guarantees fault.retry
        for name in event_names:
            assert schema.is_declared("event", name), name
    finally:
        faults.reset_registry()
        pipe_mod.reset_engine()


def test_analyze_sections_map_to_declared_names():
    """Every section obs.analyze renders is wired to a DECLARED name
    family (analyze.SECTIONS): a consumer section whose producer names
    vanish from obs/schema.py must fail here (and at analyze import),
    never silently render empty — the drift the schema exists to stop."""
    from dbscan_tpu.obs import analyze, schema

    # one entry per rendered report section (keep SECTIONS honest: a
    # new analyze() section must register its name family here)
    report_keys = set(
        analyze.analyze(
            {"spans": [], "instants": [], "counters": {}, "gauges": {},
             "dropped_spans": 0}
        )
    )
    for key in analyze.SECTIONS:
        assert key in report_keys, key
    # and every registered family resolves against the schema
    for key, (kind, names) in analyze.SECTIONS.items():
        if names is None:
            continue  # spans section: unfiltered
        if isinstance(names, str):
            assert schema.prefix_declared(kind, names), (key, names)
        else:
            for name in names:
                assert schema.is_declared(kind, name), (key, name)
    # the merge/devtime consumers read declared span families
    assert schema.prefix_declared("span", "devtime.")
    assert schema.is_declared("span", "pull.chunk")


def test_small_train_records_compile_accounting():
    """A cold-cache train() under obs records at least one dispatch
    compile; an identical rerun records none (the lru_cache + jit cache
    reuse the signature)."""
    from dbscan_tpu.obs import compile as obs_compile
    from dbscan_tpu.parallel import driver

    driver.clear_compile_cache()
    obs_compile.reset()
    obs.enable()
    pts = _blobs(100)
    train(pts, **KW)
    c = obs.counters()
    assert c.get("compiles.total", 0) >= 1
    snap = obs.counters()
    train(pts, **KW)
    delta = obs.counters_delta(snap)
    assert delta.get("compiles.total", 0) == 0
    obs_compile.reset()


# --- export footers carry gauges (both formats) -----------------------


def test_gauges_in_both_export_footers(tmp_path):
    obs.enable()
    obs.count("some.counter", 3)
    obs.gauge("memory.peak_bytes_in_use", 12345)
    jl = str(tmp_path / "t.jsonl")
    ch = str(tmp_path / "t.json")
    obs.write(jl)
    obs.write(ch)
    with open(jl) as f:
        records = [json.loads(line) for line in f if line.strip()]
    gauges = [r for r in records if r["type"] == "gauge"]
    assert gauges == [
        {"type": "gauge", "name": "memory.peak_bytes_in_use",
         "value": 12345}
    ]
    with open(ch) as f:
        trace = json.load(f)
    assert trace["otherData"]["gauges"] == {
        "memory.peak_bytes_in_use": 12345
    }
    cs = {
        e["name"]: e["args"]["value"]
        for e in trace["traceEvents"]
        if e["ph"] == "C"
    }
    # gauges ride the counter track too (Perfetto visibility +
    # analyze.py can read watermarks from either format alone)
    assert cs["memory.peak_bytes_in_use"] == 12345
    assert cs["some.counter"] == 3


# --- cli enable/disable exception safety ------------------------------


def _write_csv(tmp_path):
    import numpy as np

    path = tmp_path / "pts.csv"
    pts = _blobs(60)
    np.savetxt(path, pts, delimiter=",")
    return str(path)


def test_cli_disables_obs_and_flushes_on_error(monkeypatch, tmp_path):
    """--trace/--metrics-summary enable obs; an exception in the run
    must still flush the partial trace AND disable — no live registry
    may leak into the caller's next run (the regression the try/finally
    exists for)."""
    import dbscan_tpu
    from dbscan_tpu import cli

    trace = str(tmp_path / "crash.json")

    def boom(*a, **k):
        obs.count("reached.train", 1)
        raise RuntimeError("injected train failure")

    monkeypatch.setattr(dbscan_tpu, "train", boom)
    with pytest.raises(RuntimeError, match="injected train failure"):
        cli.main(
            [
                "--input", _write_csv(tmp_path),
                "--eps", "0.5", "--min-points", "5",
                "--trace", trace,
            ]
        )
    assert obs.state() is None  # disabled on the error path
    with open(trace) as f:  # and the partial trace was flushed
        t = json.load(f)
    cs = {e["name"] for e in t["traceEvents"] if e["ph"] == "C"}
    assert "reached.train" in cs


def test_cli_leaves_harness_obs_state_alive(tmp_path, capsys):
    """cli.main must disable only a state IT created: a harness that
    enabled obs first keeps its registries (and accumulated counters)
    across a cli invocation — the no-clobber contract in
    obs/__init__.py."""
    from dbscan_tpu import cli

    st = obs.enable()
    obs.count("harness.counter", 7)
    rc = cli.main(
        [
            "--input", _write_csv(tmp_path),
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "128",
            "--metrics-summary",
        ]
    )
    assert rc == 0
    assert obs.state() is st  # same registry, still live
    assert obs.counters()["harness.counter"] == 7
    assert "== metrics summary ==" in capsys.readouterr().out


def test_cli_disables_obs_on_success(monkeypatch, tmp_path):
    from dbscan_tpu import cli

    trace = str(tmp_path / "ok.json")
    rc = cli.main(
        [
            "--input", _write_csv(tmp_path),
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "128",
            "--trace", trace,
        ]
    )
    assert rc == 0
    assert obs.state() is None
    with open(trace) as f:
        t = json.load(f)
    assert any(e["name"] == "train" for e in t["traceEvents"])


# --- overhead guard ---------------------------------------------------


def _min_wall(fn, reps=5):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def test_disabled_overhead_under_5pct(monkeypatch):
    """The disabled path must add <5% wall to a small train() versus a
    build with the tracing code absent (every module-level hook
    monkeypatched to a bare no-op). Min-of-reps on a warmed pipeline:
    the disabled hooks are single truthiness checks, so anything past
    noise indicates a hook doing real work while disabled."""
    pts = _blobs(150)

    def run():
        train(pts, **KW)

    run()  # warm the jit caches so neither side pays compilation
    assert obs.state() is None
    with_hooks = _min_wall(run)
    noop = lambda *a, **k: None  # noqa: E731
    for name in (
        "add_span", "event", "count", "gauge", "timed_count",
        "ensure_env", "flush",
    ):
        monkeypatch.setattr(obs, name, noop)
    # span stubs must still satisfy the with-statement protocol; the
    # shared NOOP_SPAN is exactly the allocation-free stand-in
    monkeypatch.setattr(obs, "span", lambda *a, **k: obs.NOOP_SPAN)
    monkeypatch.setattr(obs, "state", lambda: None)
    without_hooks = _min_wall(run)
    assert with_hooks <= without_hooks * 1.05 + 0.010, (
        f"disabled-path overhead: {with_hooks:.4f}s vs "
        f"{without_hooks:.4f}s hook-free"
    )
