"""End-to-end distributed pipeline tests on the 8-device CPU mesh (the
stand-in for the reference's local[2] integration suite, DBSCANSuite.scala).

Comparison semantics: with eps-halo decomposition, distributed output equals
the single-machine oracle exactly when clusters are separated by > 2*eps (no
cross-cluster border bridging, which the reference's merge would over-merge —
DBSCAN.scala:317-342 unions on any doubly-non-noise point). Tests use
separated data for exact checks, plus the reference's own 749-point fixture
with its exact hyperparameters."""

import numpy as np
import pytest

import conftest
import jax
from dbscan_tpu import DBSCANConfig, Engine, train
from dbscan_tpu.ops.labels import BORDER, CORE, NOISE
from dbscan_tpu.parallel.mesh import make_mesh
from dbscan_tpu.utils import reference_engines as oracle
from dbscan_tpu.utils.ari import adjusted_rand_index, exact_match_up_to_permutation


def separated_blobs(rng, n_per=400, centers=((0, 0), (8, 8), (-7, 9), (9, -6)), scale=0.5):
    pts = np.concatenate([rng.normal(c, scale, size=(n_per, 2)) for c in centers])
    noise = rng.uniform(-15, 15, size=(60, 2)) + 30  # far-away sparse noise
    pts = np.concatenate([pts, noise])
    rng.shuffle(pts)
    return pts


@pytest.mark.parametrize("engine", [Engine.ARCHERY, Engine.NAIVE])
def test_single_partition_exact_vs_oracle(engine):
    # max_points_per_partition large enough that everything lands in one
    # partition: buffer order == input order, so even the order-dependent
    # naive semantics must match the oracle EXACTLY.
    rng = np.random.default_rng(0)
    pts = separated_blobs(rng, n_per=150)
    model = train(pts, eps=0.4, min_points=8, max_points_per_partition=10**6,
                  engine=engine)
    assert model.stats["n_partitions"] == 1
    ofit = oracle.naive_fit if engine == Engine.NAIVE else oracle.archery_fit
    oc, of = ofit(pts, 0.4, 8)
    assert exact_match_up_to_permutation(model.clusters, oc)
    np.testing.assert_array_equal(model.flags, of)


def test_multi_partition_exact_vs_oracle_archery():
    rng = np.random.default_rng(1)
    pts = separated_blobs(rng)
    model = train(pts, eps=0.4, min_points=8, max_points_per_partition=300,
                  engine=Engine.ARCHERY)
    assert model.stats["n_partitions"] > 1
    oc, of = oracle.archery_fit(pts, 0.4, 8)
    assert exact_match_up_to_permutation(model.clusters, oc)
    # flags: core is partition-independent; border/noise equal here because
    # clusters are separated
    np.testing.assert_array_equal(model.flags == CORE, of == CORE)
    np.testing.assert_array_equal(model.flags, of)


def test_cluster_split_across_many_partitions():
    # one huge connected blob forced through many partitions must come back
    # as ONE global cluster (exercises halo adjacency + union-find chain)
    rng = np.random.default_rng(2)
    pts = rng.uniform(0, 10, size=(4000, 2))  # dense uniform square: 1 cluster
    model = train(pts, eps=0.5, min_points=5, max_points_per_partition=250,
                  engine=Engine.ARCHERY)
    assert model.stats["n_partitions"] > 4
    oc, _ = oracle.archery_fit(pts, 0.5, 5)
    assert oc.max() == 1  # sanity: oracle sees one cluster
    assert model.n_clusters == 1
    assert (model.clusters == 1).all()


@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_golden_fixture_end_to_end(engine):
    # The reference integration test: eps=0.3F, minPoints=10,
    # maxPointsPerPartition=250 on the 749-point fixture
    # (DBSCANSuite.scala:36), labels must match up to permutation (:28).
    if not conftest.reference_fixture_available():
        pytest.skip("reference fixture not mounted")
    pts, expected = conftest.load_reference_fixture()
    eps = float(np.float32(0.3))
    model = train(pts, eps=eps, min_points=10, max_points_per_partition=250,
                  engine=engine)
    assert model.stats["n_partitions"] > 1
    assert exact_match_up_to_permutation(model.clusters, expected.astype(int))
    assert adjusted_rand_index(model.clusters, expected) == 1.0
    assert model.n_clusters == 3


def test_mesh_matches_single_device():
    rng = np.random.default_rng(3)
    pts = separated_blobs(rng)
    kw = dict(eps=0.4, min_points=8, max_points_per_partition=200,
              engine=Engine.ARCHERY)
    m0 = train(pts, **kw)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    m1 = train(pts, mesh=mesh, **kw)
    np.testing.assert_array_equal(m0.clusters, m1.clusters)
    np.testing.assert_array_equal(m0.flags, m1.flags)


def test_extra_columns_ride_along():
    if not conftest.reference_fixture_available():
        pytest.skip("reference fixture not mounted")
    pts, expected = conftest.load_reference_fixture()
    data3 = np.concatenate([pts, expected[:, None]], axis=1)  # x,y,label
    model = train(data3, eps=float(np.float32(0.3)), min_points=10,
                  max_points_per_partition=250)
    lp = model.labeled_points
    assert lp.shape == (749, 5)  # x, y, orig label, cluster, flag
    np.testing.assert_array_equal(lp[:, 2], expected)


def test_predict_nearest_core():
    rng = np.random.default_rng(4)
    pts = separated_blobs(rng, n_per=200)
    model = train(pts, eps=0.4, min_points=8, max_points_per_partition=10**6)
    # core training points predict their own cluster
    core = model.flags == CORE
    pred = model.predict(pts[core][:50])
    np.testing.assert_array_equal(pred, model.clusters[core][:50])
    # far away -> noise
    assert model.predict(np.array([[999.0, 999.0]]))[0] == 0


def test_empty_and_tiny_inputs():
    m = train(np.empty((0, 2)), eps=0.5, min_points=3)
    assert m.n_clusters == 0 and len(m.clusters) == 0
    m = train(np.array([[0.0, 0.0]]), eps=0.5, min_points=3)
    assert m.clusters.tolist() == [0] and m.flags.tolist() == [int(NOISE)]
    m = train(np.array([[0.0, 0.0], [0.1, 0.0], [0.2, 0.0]]), eps=0.5, min_points=3)
    assert m.n_clusters == 1
    assert (m.clusters == 1).all()


def test_partitions_accessor_and_stats():
    rng = np.random.default_rng(5)
    pts = separated_blobs(rng)
    model = train(pts, eps=0.4, min_points=8, max_points_per_partition=300)
    assert model.stats["n_partitions"] == len(model.partitions)
    for pid, rect in model.partitions:
        assert rect.shape == (4,)
    assert model.stats["duplication_factor"] >= 1.0
