"""Pallas banded engine (ops/pallas_banded.py) vs the XLA banded engine:
bit-exact equality through the full pipeline.

The Pallas port consumes the identical packer contract (cell-sorted
points, run tables, slab origins) and feeds the identical compact
postpass + host cell-CC, so clusters, flags, AND the core-instance count
must match the XLA banded engine exactly on every geometry that stresses
the machinery (interpret mode on CPU; Mosaic lowering is exercised on
TPU by bench.py BENCH_PALLAS=1)."""

import numpy as np
import pytest

from dbscan_tpu import Engine, train

GEOMETRIES = {
    "blobs+noise": lambda rng: np.concatenate(
        [rng.normal(c, 0.5, (700, 2)) for c in [(0, 0), (5, 5), (-4, 6)]]
        + [rng.uniform(-8, 10, (300, 2))]
    ),
    "thin-chain": lambda rng: np.stack(
        [np.linspace(0, 40, 1500), rng.normal(0, 0.05, 1500)], axis=1
    ),
    "single-cell-pileup": lambda rng: rng.normal(0, 0.02, (1200, 2)),
    "boundary-points": lambda rng: np.concatenate(
        [
            np.stack(
                [
                    rng.integers(0, 12, 600) * 0.3,
                    rng.integers(0, 12, 600) * 0.3,
                ],
                axis=1,
            ),
            rng.uniform(0, 3.6, (600, 2)),
        ]
    ),
}


def _equal(pts, rng_unused, engine, mesh=None, maxpp=10**9):
    kw = dict(
        eps=0.3,
        min_points=6,
        max_points_per_partition=maxpp,
        engine=engine,
        neighbor_backend="banded",
        mesh=mesh,
    )
    mb = train(pts, **kw)
    mp = train(pts, use_pallas=True, **kw)
    assert mp.stats["n_banded_groups"] >= 1
    np.testing.assert_array_equal(mb.clusters, mp.clusters)
    np.testing.assert_array_equal(mb.flags, mp.flags)
    assert mb.stats["n_core_instances"] == mp.stats["n_core_instances"]
    return mp


@pytest.mark.parametrize("name", sorted(GEOMETRIES))
@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_pallas_banded_equals_xla_banded(name, engine, rng):
    _equal(GEOMETRIES[name](rng), rng, engine)


def test_pallas_banded_multi_partition(rng):
    pts = np.concatenate(
        [rng.normal(c, 0.6, (1500, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (500, 2))]
    )
    m = _equal(pts, rng, Engine.ARCHERY, maxpp=700)
    assert m.stats["n_partitions"] > 4


def test_pallas_banded_on_mesh(rng):
    from dbscan_tpu.parallel.mesh import make_mesh

    pts = np.concatenate(
        [rng.normal(c, 0.6, (1200, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
    )
    _equal(pts, rng, Engine.ARCHERY, mesh=make_mesh(), maxpp=600)


def test_pallas_slab_chunking_bit_identical(rng, monkeypatch):
    """Wide-slab runs walk the slab in ladder-divisor chunks on a third
    grid dimension (_PALLAS_SLAB_CHUNK; the ADVICE r3 VMEM fix). Forcing a
    tiny chunk target makes every test slab multi-chunk, and counts/bits
    accumulated across chunk steps must stay bit-identical to the XLA
    banded engine."""
    import jax

    from dbscan_tpu.ops import banded as banded_mod
    from dbscan_tpu.ops import pallas_banded as pb
    from dbscan_tpu.parallel import driver as driver_mod

    # 512 (not smaller): every forced chunk width stays a multiple of 128,
    # so the test also compiles under real Mosaic, not just interpret mode
    monkeypatch.setattr(pb, "_PALLAS_SLAB_CHUNK", 512)
    seen_ns = []
    real_chunks = banded_mod._slab_chunks

    def spy(slab, target=None):
        ns = real_chunks(slab, target)
        if target == 512:
            seen_ns.append(ns)
        return ns

    monkeypatch.setattr(pb, "_slab_chunks", spy)
    driver_mod.clear_compile_cache()
    jax.clear_caches()
    try:
        _equal(GEOMETRIES["blobs+noise"](rng), rng, Engine.ARCHERY)
        _equal(GEOMETRIES["single-cell-pileup"](rng), rng, Engine.ARCHERY)
    finally:
        driver_mod.clear_compile_cache()
        jax.clear_caches()
    # the chunked (ns > 1) accumulate path must actually have executed
    assert seen_ns and max(seen_ns) > 1, seen_ns


def test_pallas_auto_routes_banded_at_scale(rng, monkeypatch):
    """With neighbor_backend='auto', large buckets route the Pallas run
    through the banded structure (the round-3 reclassification) — not the
    O(diameter) streaming engine. The auto threshold
    (BANDED_ROUTE_BUCKET, 32768) is lowered so the test exercises the
    routing at CI-sized N."""
    from dbscan_tpu.parallel import binning, driver

    monkeypatch.setattr(binning, "BANDED_ROUTE_BUCKET", 2048)
    driver.clear_compile_cache()
    pts = np.concatenate(
        [rng.normal(c, 0.7, (4000, 2)) for c in [(0, 0), (9, 9)]]
    )
    mp = train(
        pts,
        eps=0.3,
        min_points=6,
        max_points_per_partition=10**9,
        engine=Engine.ARCHERY,
        use_pallas=True,
    )
    assert mp.stats["n_banded_groups"] >= 1
    mb = train(
        pts,
        eps=0.3,
        min_points=6,
        max_points_per_partition=10**9,
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
    )
    np.testing.assert_array_equal(mb.clusters, mp.clusters)


def test_pallas_banded_haversine_chord(rng):
    """The spherical route feeds the banded engines a 3-plane CHORD
    payload (ops/sphere.py) with the grid built from the equirectangular
    projection; the Pallas port's difference-form distance generalizes
    over D as a static unrolled sum and must stay bit-identical to the
    XLA engine there too (D=3 exercises the plane loop beyond the 2-D
    geometries above)."""
    # lon/lat degrees in a ~100 km box; eps in km
    lon0, lat0 = -74.0, 40.7
    pts = np.stack(
        [
            lon0 + rng.uniform(0, 0.9, 3000),
            lat0 + rng.uniform(0, 0.7, 3000),
        ],
        axis=1,
    )
    centers = np.stack(
        [lon0 + np.array([0.2, 0.6]), lat0 + np.array([0.2, 0.5])], axis=1
    )
    blobs = np.concatenate(
        [c + rng.normal(0, 0.01, (1500, 2)) for c in centers]
    )
    pts = np.concatenate([pts, blobs])
    kw = dict(
        eps=1.0,  # km
        min_points=8,
        max_points_per_partition=10**9,
        engine=Engine.ARCHERY,
        metric="haversine",
        neighbor_backend="banded",
    )
    mb = train(pts, **kw)
    mp = train(pts, use_pallas=True, **kw)
    assert mp.stats["n_banded_groups"] >= 1
    np.testing.assert_array_equal(mb.clusters, mp.clusters)
    np.testing.assert_array_equal(mb.flags, mp.flags)


@pytest.mark.parametrize("geometry", sorted(GEOMETRIES))
def test_scalar_prefetch_variant_bit_exact(geometry, rng, monkeypatch):
    """DBSCAN_PALLAS_SP=1 routes phase 1 through the scalar-prefetch
    kernels (ops/pallas_banded_sp.py — no XLA slab gather, origins read
    from SMEM inside the BlockSpec index maps). Labels, flags, and core
    counts must equal the XLA banded engine bit-for-bit: the alignment
    shift only widens the candidate window with positions the run test
    rejects."""
    monkeypatch.setenv("DBSCAN_PALLAS_SP", "1")
    # no cache clearing needed: pallas_sp is part of the executor cache
    # key, so SP and non-SP programs can never collide
    pts = GEOMETRIES[geometry](rng)
    _equal(pts, rng, Engine.ARCHERY)
