"""Randomized cross-metric parity fuzz: train() vs the f64 oracle over
random configurations of all three decomposition paths (2eps grid,
spherical embedding, metric spill). Trials whose data has any pair
within a hairline of the eps boundary are re-rolled — the engine
decides in f32, the oracle in f64, and a boundary-exact pair could flip
legitimately; everything else must match exactly."""

import numpy as np
import pytest

from dbscan_tpu import Engine, train
from dbscan_tpu.ops.distance import get_metric
from dbscan_tpu.utils.ari import adjusted_rand_index
from dbscan_tpu.utils.reference_engines import archery_fit, naive_fit


def _boundary_clear(data, metric, eps, rel=2e-5):
    # the engines' f32 arithmetic is good to ~1e-7 relative; a 2e-5
    # exclusion window is 100x that while still letting most random
    # datasets through
    m = get_metric(metric)
    d = np.asarray(m.pairwise(data, data), dtype=np.float64)
    thr = float(m.threshold(eps))
    return not (np.abs(d - thr) < rel * max(thr, 1e-12)).any()


def _gen(rng, metric):
    if metric == "euclidean":
        k = int(rng.integers(2, 6))
        centers = rng.uniform(-30, 30, (k, 2))
        data = np.concatenate(
            [rng.normal(c, rng.uniform(0.2, 0.6), (60, 2)) for c in centers]
            + [rng.uniform(-35, 35, (30, 2))]
        )
        eps = float(rng.uniform(0.3, 0.8))
    elif metric == "haversine":
        k = int(rng.integers(2, 5))
        lons = rng.uniform(-74.2, -73.6, k)
        lats = rng.uniform(40.5, 41.0, k)
        data = np.concatenate(
            [
                np.stack(
                    [
                        rng.normal(lo, 0.002, 60),
                        rng.normal(la, 0.002, 60),
                    ],
                    axis=1,
                )
                for lo, la in zip(lons, lats)
            ]
        )
        eps = float(rng.uniform(0.2, 0.5))
    else:  # cosine
        d = int(rng.integers(8, 48))
        k = int(rng.integers(2, 6))
        c = rng.normal(size=(k, d))
        c /= np.linalg.norm(c, axis=1, keepdims=True)
        data = np.repeat(c, 60, axis=0) + 0.02 * rng.normal(
            size=(k * 60, d)
        )
        eps = float(rng.uniform(0.02, 0.06))
    return data, eps


@pytest.mark.parametrize("metric", ["euclidean", "haversine", "cosine"])
def test_fuzz_parity(rng, metric):
    done = 0
    attempts = 0
    while done < 4 and attempts < 20:
        attempts += 1
        data, eps = _gen(rng, metric)
        if not _boundary_clear(data, metric, eps):
            continue
        min_points = int(rng.integers(3, 10))
        maxpp = int(rng.choice([64, 128, 256]))
        engine = rng.choice(["naive", "archery"])
        model = train(
            data, eps=eps, min_points=min_points,
            max_points_per_partition=maxpp, metric=metric,
            engine=Engine.NAIVE if engine == "naive" else Engine.ARCHERY,
        )
        oracle = naive_fit if engine == "naive" else archery_fit
        ocl, ofl = oracle(data, eps, min_points, metric=metric)
        assert adjusted_rand_index(model.clusters, ocl) == 1.0, (
            metric, eps, min_points, maxpp, engine, done, attempts
        )
        np.testing.assert_array_equal(model.flags, ofl)
        done += 1
    assert done == 4, f"only {done} boundary-clear trials in {attempts}"
