"""graftshape: the symbolic shape/dtype/HBM abstract-interpretation
core (dbscan_tpu/lint/absint.py), the dispatch-family models
(lint/shapes.py), and the runtime cross-check (lint/shapecheck.py).

Pins, per the PR acceptance bar:

- the dim algebra and unification edge cases: monomial solving (shard
  block division ``B == 512*NB``), ratchet floor raises (a GROWN
  observed dim still instantiates the per-call model), static-argnum
  specialization (a static param usable as a symbolic dim), and the
  conservative no-refutation rule for under-determined dims;
- the family models against REAL runs: dense, banded, resident, spill
  and streaming trains validate with zero violations on this backend,
  and the model constants mirror the packer's (BANDED_BLOCK);
- the HBM containment half: a dispatch whose observed allocator growth
  exceeds the static prediction is a violation (faked stats — the CPU
  backend has none);
- the bench gate: ``hbm_pred_ratio`` ingests with unit ``ratio`` and
  ``obs/regress.py`` hard-caps it at 1.0 with no history needed;
- the tier-1 rerun: a distributed + streaming train passes under
  ``DBSCAN_SHAPECHECK=1`` with an EMPTY violation report
  (``DBSCAN_SHAPECHECK_REPORT`` JSON, asserted from outside the
  process).

STRICT mode is on for every interpreter-driven test here so a modeling
crash fails the suite instead of being swallowed by the per-function
guard.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from dbscan_tpu.lint import absint, shapecheck, shapes
from dbscan_tpu.lint.absint import E, Sym, unify_dim

pytestmark = pytest.mark.shapecheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rt():
    """A fresh, enabled cross-check runtime; always disabled after."""
    shapecheck.enable()
    shapecheck.reset()
    yield shapecheck
    shapecheck.disable()


@pytest.fixture(autouse=True)
def strict_absint():
    absint.STRICT = True
    yield
    absint.STRICT = False


# --- dim algebra ------------------------------------------------------


def test_expr_normalization_and_algebra():
    P, B = Sym("P"), Sym("B")
    e = (E.of(P) + P) * 3 + 4  # 6P + 4
    assert e.evaluate({"P": 10}) == 64
    assert (E.of(P) * B).evaluate({"P": 3, "B": 5}) == 15
    assert (E.of(P) - P).const() == 0
    assert E(7).const() == 7
    assert (E.of(P) * 0).const() == 0
    # unbound symbols evaluate to None, partial substitution folds
    assert (E.of(P) * B).evaluate({"P": 3}) is None
    assert (E.of(P) * B).substitute({"P": 3}).evaluate({"B": 5}) == 15


def test_nbytes_symbolic():
    P = Sym("P")
    e = absint.nbytes((E.of(P), E(4)), "f32")
    assert e.evaluate({"P": 100}) == 1600
    assert absint.nbytes(None, "f32") is None
    assert absint.nbytes((E(2),), "nonsense") is None


def test_unify_concrete_and_symbolic():
    subst = {}
    assert unify_dim(E(8), 8, subst)
    assert not unify_dim(E(8), 9, subst)
    assert unify_dim(E.of(Sym("P")), 12, subst) and subst["P"] == 12
    # a bound symbol must stay consistent
    assert unify_dim(E.of(Sym("P")), 12, subst)
    assert not unify_dim(E.of(Sym("P")), 13, subst)


def test_unify_monomial_shard_block_division():
    """The shard-block edge case: 512*NB against an observed width
    solves NB when divisible and REFUTES when not."""
    subst = {}
    assert unify_dim(E(512) * Sym("NB"), 1024, subst)
    assert subst["NB"] == 2
    assert not unify_dim(E(512) * Sym("NB"), 1000, {})
    # and the solved binding participates in later constraints
    assert unify_dim(E.of(Sym("NB")) * 512, 1024, dict(subst))


def test_unify_under_determined_never_refutes():
    # two unbound symbols cannot be refuted by one observation
    assert unify_dim(E.of(Sym("A")) * Sym("B"), 7, {})


def test_ratchet_floor_raise_instantiates_per_call():
    """A streaming ratchet raise grows B between dispatches; each call
    unifies against a FRESH substitution, so the grown shape still
    instantiates the same symbolic model."""
    for b in (512, 1024, 1536):  # a raising rung sequence
        specs = [((8, b, 2), "f32"), ((8, b), "bool")]
        subst, problems = shapes.validate_args("dispatch.dense", specs)
        assert problems == []
        assert subst["B"] == b


def test_static_argnum_specialization_dim():
    """A static-argnums param is a compile-time int the kernel may use
    as a dimension: the interpreter binds it symbolically, so shapes
    built from it unify instead of going unknown (and provably
    conflicting concrete dims still flag)."""
    import dbscan_tpu.lint as lint_mod

    src = textwrap.dedent(
        """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("k",))
        def root(x, k):
            a = jnp.zeros((k, 8))
            b = jnp.ones((k, 8))
            return a + b
        """
    )
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "snippet.py")
        with open(p, "w") as f:
            f.write(src)
        findings, _ = lint_mod.lint_paths([p])
    assert findings == []


# --- family-model validation -----------------------------------------


def test_model_constants_mirror_the_packer():
    from dbscan_tpu.parallel import binning

    assert shapes.BANDED_BLOCK == binning.BANDED_BLOCK
    assert shapes.BANDED_ROWS == binning.BANDED_ROWS


def test_models_cover_every_declared_family():
    from dbscan_tpu.obs import schema

    assert set(shapes.FAMILY_MODELS) == set(schema.COMPILE_FAMILIES)


def test_banded_model_constraint():
    base = {
        "points": ((4, 1024, 2), "f32"),
        "mask": ((4, 1024), "bool"),
        "rel_starts": ((4, 1024, 5), "u16"),
        "spans": ((4, 1024, 5), "u16"),
        "slab_starts": ((4, 2, 5), "i32"),
        "cx": ((4, 1024), "i32"),
    }
    specs = list(base.values())
    subst, problems = shapes.validate_args("dispatch.banded_p1", specs)
    assert problems == []
    assert subst["NB"] == 2 and subst["B"] == 1024
    # an inconsistent block count violates B == 512*NB
    bad = dict(base, slab_starts=((4, 3, 5), "i32"))
    _, problems = shapes.validate_args(
        "dispatch.banded_p1", list(bad.values())
    )
    assert any("constraint" in p for p in problems)


def test_model_rejects_rank_dtype_and_binding_drift():
    # rank drift
    _, p1 = shapes.validate_args(
        "dispatch.dense", [((8, 512), "f32"), ((8, 512), "bool")]
    )
    assert any("rank" in p for p in p1)
    # dtype class drift (int points)
    _, p2 = shapes.validate_args(
        "dispatch.dense", [((8, 512, 2), "i32"), ((8, 512), "bool")]
    )
    assert any("dtype" in p for p in p2)
    # inconsistent P across args
    _, p3 = shapes.validate_args(
        "dispatch.dense", [((8, 512, 2), "f32"), ((9, 512), "bool")]
    )
    assert any("does not instantiate" in p for p in p3)
    # unknown family is itself a violation
    _, p4 = shapes.validate_args("dispatch.nope", [])
    assert p4 and "undeclared" in p4[0]


def test_postpass_tuple_coupling():
    cores = [((2, 512), "bool"), ((4, 512), "bool")]
    bitses_ok = [((2, 512), "i32"), ((4, 512), "i32")]
    segflags = [((1024,), "bool"), ((2048,), "bool")]
    or_idx = ((64,), "i32")
    _, problems = shapes.validate_args(
        "cellcc.postpass", [cores, bitses_ok, segflags, or_idx]
    )
    assert problems == []
    bitses_bad = [((2, 512), "i32"), ((3, 512), "i32")]
    _, problems = shapes.validate_args(
        "cellcc.postpass", [cores, bitses_bad, segflags, or_idx]
    )
    assert any("shape" in p for p in problems)


def test_scalar_passthrough_args_tolerated(rt):
    """Static-argnum passthrough: trailing non-array args beyond the
    declared model do not fail validation."""
    pts = np.zeros((8, 512, 2), np.float32)
    mask = np.zeros((8, 512), bool)
    h = rt.runtime().observe_call("dispatch.dense", (pts, mask, 7))
    rt.runtime().settle_call(h)
    assert rt.report()["violations"] == []


def test_undeclared_extra_array_arg_is_a_violation(rt):
    """A kernel signature growing an ARRAY the model does not declare
    must fail the cross-check — zip truncation would otherwise let new
    buffers ship unregistered."""
    pts = np.zeros((8, 512, 2), np.float32)
    mask = np.zeros((8, 512), bool)
    extra = np.zeros((8, 512), np.int32)
    rt.runtime().observe_call("dispatch.dense", (pts, mask, extra))
    rep = rt.report()
    assert len(rep["violations"]) == 1
    assert "undeclared extra array" in rep["violations"][0]["detail"]


# --- runtime cross-check against real runs -----------------------------


def test_runtime_clean_on_dense_and_banded_train(rt):
    from dbscan_tpu import train

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(4000, 2)) * 10
    train(pts, eps=0.5, min_points=5, max_points_per_partition=400)
    train(
        pts, eps=0.5, min_points=5, max_points_per_partition=1500,
        neighbor_backend="banded",
    )
    rep = rt.report()
    assert rep["enabled"] and rep["checks"] > 0
    assert rep["violations"] == [], rep["violations"]
    assert "dispatch.dense" in rep["sites"]
    assert "dispatch.banded_p1" in rep["sites"]
    assert "cellcc.postpass" in rep["sites"]
    rt.assert_clean()


def test_runtime_clean_on_streaming(rt):
    from dbscan_tpu.streaming import StreamingDBSCAN

    rng = np.random.default_rng(1)
    s = StreamingDBSCAN(eps=0.5, min_points=5, window=3000)
    for _ in range(3):
        s.update(rng.normal(size=(1200, 2)) * 10)
    rep = rt.report()
    assert rep["checks"] > 0
    assert rep["violations"] == [], rep["violations"]
    rt.assert_clean()


def test_runtime_clean_on_spill_gather(rt):
    from dbscan_tpu.parallel import spill_device

    ops = spill_device.DeviceNodeOps.from_host(
        np.random.default_rng(0).normal(size=(512, 16))
    )
    ops.take(np.arange(0, 256, 2))
    rep = rt.report()
    assert rep["sites"]["spill.gather"]["calls"] == 1
    assert rep["violations"] == []


def test_runtime_flags_model_drift(rt):
    """A dispatch whose real shapes the model cannot explain is a
    violation — the contract that forces model updates alongside
    kernel-signature changes."""
    pts = np.zeros((8, 512), np.float32)  # rank 2: model declares 3
    mask = np.zeros((8, 512), bool)
    rt.runtime().observe_call("dispatch.dense", (pts, mask))
    rep = rt.report()
    assert len(rep["violations"]) == 1
    assert rep["violations"][0]["kind"] == "shape-model"
    with pytest.raises(AssertionError):
        rt.assert_clean()


def test_runtime_hbm_over_prediction(rt, monkeypatch):
    """Faked allocator stats: growth past the static prediction across
    a dispatch is a violation; growth within it is not."""
    probes = iter([1000, 10**13, 1000, 2000])
    monkeypatch.setattr(
        shapecheck, "_bytes_in_use", lambda: next(probes)
    )
    pts = np.zeros((8, 512, 2), np.float32)
    mask = np.zeros((8, 512), bool)
    r = rt.runtime()
    h = r.observe_call("dispatch.dense", (pts, mask))
    assert h["predicted"] is not None
    r.settle_call(h)  # grew 10**13 - 1000 >> predicted
    rep = rt.report()
    assert any(
        v["kind"] == "hbm-over-prediction" for v in rep["violations"]
    )
    # a contained dispatch records no violation
    h = r.observe_call("dispatch.dense", (pts, mask))
    r.settle_call(h)
    assert (
        len([v for v in rt.report()["violations"]
             if v["kind"] == "hbm-over-prediction"]) == 1
    )
    # and both halves of the bench gate are tracked: the predicted
    # envelope and the PER-RUN observed peak (dispatch-boundary
    # samples, not the allocator's process-monotone figure)
    assert rt.predicted_peak() is not None
    assert rt.observed_peak() == 10**13
    # a fresh runtime resets the observed peak — the property that
    # keeps a second bench run's ratio independent of the first
    shapecheck.reset()
    assert rt.observed_peak() is None


def test_disabled_path_is_a_noop():
    shapecheck.disable()
    assert shapecheck.runtime() is None
    rep = shapecheck.report()
    assert rep == {
        "enabled": False,
        "checks": 0,
        "sites": {},
        "violations": [],
        "predicted_peak_bytes": None,
        "observed_peak_bytes": None,
    }
    shapecheck.assert_clean()  # no violations when disabled
    assert shapecheck.predicted_peak() is None
    assert shapecheck.observed_peak() is None


def test_enable_idempotent_reset_and_write_report(rt, tmp_path):
    r1 = shapecheck.enable()
    assert shapecheck.enable() is r1  # idempotent
    pts = np.zeros((8, 512, 2), np.float32)
    mask = np.zeros((8, 512), bool)
    r1.observe_call("dispatch.dense", (pts, mask))
    path = shapecheck.write_report(str(tmp_path / "sc.json"))
    rep = json.loads(open(path).read())
    assert rep["enabled"] is True and rep["checks"] == 1
    shapecheck.reset()
    assert shapecheck.report()["checks"] == 0
    assert shapecheck.enabled()


def test_telemetry_deltas_declared_and_exact(rt):
    from dbscan_tpu import obs

    st = obs.enable()
    try:
        pts = np.zeros((8, 512), np.float32)  # rank drift -> violation
        mask = np.zeros((8, 512), bool)
        rt.runtime().observe_call("dispatch.dense", (pts, mask))
        shapecheck.emit_telemetry()
        c = obs.counters()
        assert c.get("shapecheck.checks") == 1
        assert c.get("shapecheck.violations") == 1
        shapecheck.emit_telemetry()  # deltas: no double count
        c = obs.counters()
        assert c.get("shapecheck.checks") == 1
        ev = [
            i for i in st.tracer.instants
            if i[0] == "shapecheck.violation"
        ]
        assert len(ev) == 1 and ev[0][2]["family"] == "dispatch.dense"
    finally:
        obs.disable()


# --- bench gate --------------------------------------------------------


def test_hbm_pred_ratio_ingests_with_ratio_unit(tmp_path):
    from dbscan_tpu.obs import bench_history

    cap = tmp_path / "BENCH_X.json"
    cap.write_text(json.dumps({
        "metric": "tpu_1m_dense_mpts",
        "value": 0.7,
        "unit": "Mpoints/s",
        "backend": "tpu",
        "hbm_pred_ratio": 0.93,
        "anchor_hbm_pred_ratio": 0.88,
    }))
    recs = bench_history.parse_capture_file(str(cap))
    ratios = {
        r["metric"]: r for r in recs if r["metric"].endswith("_pred_ratio")
    }
    assert set(ratios) == {"hbm_pred_ratio", "anchor_hbm_pred_ratio"}
    for r in ratios.values():
        assert r["unit"] == "ratio"


def test_regress_hard_caps_pred_ratio():
    """<= 1.0 passes with NO history; above 1.0 regresses regardless of
    spread — a containment contract, not a noise-widened direction."""
    from dbscan_tpu.obs import regress

    fresh_ok = [{"metric": "anchor_hbm_pred_ratio", "value": 0.97,
                 "backend": "tpu", "source": "a.json"}]
    fresh_bad = [{"metric": "anchor_hbm_pred_ratio", "value": 1.08,
                  "backend": "tpu", "source": "a.json"}]
    res = regress.compare(fresh_ok, history=[])
    assert res["regressions"] == [] and len(res["ok"]) == 1
    res = regress.compare(fresh_bad, history=[])
    assert len(res["regressions"]) == 1
    e = res["regressions"][0]
    assert e["direction"] == "cap" and e["value"] == 1.08
    # the shared renderer handles the cap entry
    assert "anchor_hbm_pred_ratio" in regress.format_regression(e)


# --- the tier-1 rerun --------------------------------------------------


def test_distributed_and_streaming_under_shapecheck_env():
    """The acceptance gate from OUTSIDE the process: a distributed
    (dense + banded) and streaming train under DBSCAN_SHAPECHECK=1
    records observed shapes instantiating the static model at every
    tracked dispatch site, and an EMPTY violation report."""
    report = os.path.join(REPO, "bench", ".sc_report_test.json")
    if os.path.exists(report):
        os.remove(report)
    script = textwrap.dedent(
        """
        import numpy as np
        from dbscan_tpu import train
        from dbscan_tpu.streaming import StreamingDBSCAN

        import os

        rng = np.random.default_rng(0)
        pts = rng.normal(size=(5000, 2)) * 10
        train(pts, eps=0.5, min_points=5, max_points_per_partition=400)
        train(pts, eps=0.5, min_points=5,
              max_points_per_partition=1500, neighbor_backend="banded")
        # host-oracle finalize: covers the cellcc.gather border readout
        # the device path (the default, covered above as cellcc.unpack/
        # cellcc.cc) replaces
        os.environ["DBSCAN_CELLCC_DEVICE"] = "0"
        train(pts, eps=0.5, min_points=5,
              max_points_per_partition=1500, neighbor_backend="banded")
        del os.environ["DBSCAN_CELLCC_DEVICE"]
        s = StreamingDBSCAN(eps=0.5, min_points=5, window=3000)
        for _ in range(3):
            s.update(rng.normal(size=(1200, 2)) * 10)
        """
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_SHAPECHECK": "1",
        "DBSCAN_SHAPECHECK_REPORT": report,
    }
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            cwd=REPO,
            env=env,
            timeout=420,
        )
        assert proc.returncode == 0, (
            proc.stdout[-4000:] + proc.stderr[-2000:]
        )
        rep = json.loads(open(report).read())
        assert rep["enabled"] is True
        assert rep["violations"] == [], rep["violations"]
        assert rep["checks"] > 0
        # the run exercised both engines' dispatch sites, the device
        # cellcc finalize (the default), and the host oracle's gather
        for fam in ("dispatch.dense", "dispatch.banded_p1",
                    "cellcc.postpass", "cellcc.gather",
                    "cellcc.unpack", "cellcc.cc"):
            assert fam in rep["sites"], sorted(rep["sites"])
            assert rep["sites"][fam]["violations"] == 0
    finally:
        if os.path.exists(report):
            os.remove(report)
