"""Embed engine suite (``dbscan_tpu/embed/``): exact-path label parity
vs the numpy host oracle on fuzzed [N, D] inputs (D in {8, 64, 256,
768}), the canonical-gid renumbering contract (labels are a function
of the data alone — LSH seed, bucket layout, spill fallbacks, and the
metric-spill train() route all produce the identical vector),
multi-table LSH recall vs the Goemans-Williamson bound, the
subsampled-edge accuracy contract, zero-recompile ladder pins across
mixed N/D job streams, ``embed`` fault-site drills (transient heal,
persistent bucket degrade to the oracle, persistent hash degrade of
the whole run), the D=64 spill-tree fallback parity + rank-2 guard,
and a ``DBSCAN_TSAN=1`` concurrent rerun asserting a race-free report.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import embed_dbscan, faults, obs
from dbscan_tpu.embed import lsh, neighbors, oracle
from dbscan_tpu.utils.ari import adjusted_rand_index

pytestmark = pytest.mark.embed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _blobs(rng, d, k, per, noise, n_noise=0):
    """k tight unit-sphere blobs + optional random-direction noise."""
    c = rng.normal(size=(k, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    x = np.repeat(c, per, axis=0) + noise * rng.normal(size=(k * per, d))
    if n_noise:
        x = np.concatenate([x, rng.normal(size=(n_noise, d))])
    return x


def _boundary_clear(x, eps, rel=2e-5):
    unit, _ = oracle.normalize_rows(x)
    d = 1.0 - unit @ unit.T
    return not (np.abs(d - float(eps)) < rel).any()


@pytest.fixture(autouse=True)
def _fresh_embed_state(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    neighbors.reset_w_floors()
    yield
    faults.reset_registry()


# --- exact-path oracle parity ------------------------------------------


@pytest.mark.parametrize(
    "d,eps,maxpp",
    [(8, 0.002, 128), (64, 0.002, 128), (256, 0.003, 96), (768, 0.02, 64)],
    ids=["d8", "d64", "d256", "d768"],
)
def test_exact_parity_fuzz(rng, d, eps, maxpp):
    """Exact-path labels match the host oracle on fuzzed [N, D]
    inputs: ARI 1.0, byte-equal flags, and — via the shared canonical
    numbering — byte-equal label VALUES."""
    done = attempts = 0
    while done < 2 and attempts < 10:
        attempts += 1
        k = int(rng.integers(4, 9))
        per = int(rng.integers(30, 60))
        x = _blobs(rng, d, k, per, noise=0.1 * eps, n_noise=per // 3)
        if not _boundary_clear(x, eps):
            continue
        mp = int(rng.integers(3, 8))
        engine = ["naive", "archery"][int(rng.integers(2))]
        stats = {}
        cl, fl = embed_dbscan(
            x, eps, mp, engine=engine,
            max_points_per_partition=maxpp, stats_out=stats,
        )
        ocl, ofl = oracle.cosine_dbscan_oracle(x, eps, mp, engine)
        assert adjusted_rand_index(cl, ocl) == 1.0, (d, eps, mp, engine)
        np.testing.assert_array_equal(fl, ofl)
        np.testing.assert_array_equal(cl, ocl)
        assert stats["n_partitions"] >= 1
        done += 1
    assert done == 2, f"only {done} boundary-clear trials in {attempts}"


def test_exact_parity_straddling_neighborhoods(rng):
    """Adversarial regression for the duplication band: UNIFORM sphere
    points with eps at a low pair-distance quantile, so eps-balls
    routinely straddle hyperplane cuts with one endpoint OUT of band.
    A pair-sharing-only band (the reviewed-out halo/2 variant) loses
    out-of-band neighbors from home buckets, undercounts core tests,
    and fails the flag check on nearly every trial. The full-halo
    band's neighborhood-completeness invariant guarantees, on ANY
    input: byte-equal flags, no oracle cluster ever SPLIT, and merges
    only where a shared border point witnesses them (the reference's
    border-bridged merge semantic, PARITY.md — separated workloads
    have no witnesses, which is why the blob fuzz above gets full
    byte equality)."""
    done = attempts = 0
    while done < 2 and attempts < 10:
        attempts += 1
        d = int(rng.integers(4, 6))  # low D: dense straddling regime
        n = 2000
        x = rng.normal(size=(n, d))
        unit, _ = oracle.normalize_rows(x)
        dist = 1.0 - unit @ unit.T
        iu = np.triu_indices(n, k=1)
        flat = np.sort(dist[iu])
        # eps at the ~0.05% pair quantile, NUDGED into the widest gap
        # between consecutive pair distances nearby — dense pair
        # spectra always have SOME pair inside a fixed boundary
        # window, so reroll-until-clear would never terminate
        k0 = int(0.0005 * len(flat))
        lo, hi = max(1, k0 - 200), min(len(flat) - 1, k0 + 200)
        gaps = flat[lo + 1 : hi] - flat[lo : hi - 1]
        g = int(np.argmax(gaps))
        if gaps[g] < 2e-5:  # midpoint margin 1e-5 >> the f32 rounding
            continue
        eps = float((flat[lo + g] + flat[lo + g + 1]) / 2.0)
        mp = int(rng.integers(3, 6))
        stats = {}
        cl, fl = embed_dbscan(
            x, eps, mp, max_points_per_partition=256, stats_out=stats
        )
        assert stats["n_partitions"] > 1  # the decomposition engaged
        assert stats["embed_buckets"] >= 2  # ...including LSH cuts
        ocl, ofl = oracle.cosine_dbscan_oracle(x, eps, mp)
        # (1) core/border/noise decisions are EXACT — the invariant the
        # duplication band exists to protect
        np.testing.assert_array_equal(fl, ofl)
        # (2) completeness: the engine never splits an oracle cluster
        m = (cl > 0) & (ocl > 0)
        pairs = set(zip(ocl[m].tolist(), cl[m].tolist()))
        o2e: dict = {}
        for o, e in pairs:
            o2e.setdefault(o, set()).add(e)
        assert all(len(s) == 1 for s in o2e.values()), "oracle cluster split"
        # (3) soundness: engine-merged oracle clusters must share a
        # border-bridge witness class (reference merge semantics)
        adj = dist <= eps
        np.fill_diagonal(adj, True)
        core = ofl == oracle.CORE
        comp_of = np.where(core, ocl, 0)
        parent: dict = {}

        def find(a):
            while parent.get(a, a) != a:
                a = parent[a]
            return a

        for i in np.flatnonzero(ofl == oracle.BORDER):
            cs = sorted(set(comp_of[np.flatnonzero(adj[i] & core)]))
            for c in cs[1:]:
                ra, rb = find(cs[0]), find(c)
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)
        e2o: dict = {}
        for o, e in pairs:
            e2o.setdefault(e, set()).add(o)
        for merged in e2o.values():
            assert len({find(o) for o in merged}) == 1, merged
        done += 1
    assert done == 2, f"only {done} boundary-clear trials in {attempts}"


def test_canonical_gid_renumbering(rng):
    """The canonical-gid contract: different LSH seeds (different
    planes AND different spill pivot draws), different bucket caps, and
    the metric-spill train() route all produce the byte-identical label
    vector — cluster numbering is a function of the data alone."""
    from dbscan_tpu import Engine, train

    x = _blobs(rng, 64, 6, 50, noise=0.0005, n_noise=20)
    eps, mp = 0.002, 4
    base, base_f = embed_dbscan(x, eps, mp, max_points_per_partition=96)
    for seed, maxpp in ((1, 96), (7, 64), (0, 200)):
        cl, fl = embed_dbscan(
            x, eps, mp, seed=seed, max_points_per_partition=maxpp
        )
        np.testing.assert_array_equal(base, cl)
        np.testing.assert_array_equal(base_f, fl)
    # cross-engine: the spill-route train() numbers canonically too
    model = train(
        x, eps=eps, min_points=mp, metric="cosine",
        max_points_per_partition=96, engine=Engine.ARCHERY,
    )
    np.testing.assert_array_equal(base, model.clusters)


def test_zero_norm_rows_are_noise(rng):
    x = _blobs(rng, 16, 3, 40, noise=0.0005)
    x = np.concatenate([x, np.zeros((5, 16))])
    cl, fl = embed_dbscan(x, 0.01, 4, max_points_per_partition=64)
    assert (cl[-5:] == 0).all()
    assert (fl[-5:] == oracle.NOISE).all()
    assert (cl[:-5] > 0).any()


def test_empty_and_tiny_inputs():
    cl, fl = embed_dbscan(np.empty((0, 32)), 0.1, 3)
    assert len(cl) == 0 and len(fl) == 0
    cl, fl = embed_dbscan(np.ones((1, 32)), 0.1, 1)
    assert cl.tolist() == [1] and fl.tolist() == [int(oracle.CORE)]


# --- LSH front-end -----------------------------------------------------


def test_lsh_binning_engages_at_tight_eps(rng):
    """Tight-threshold (dedup-regime) workloads must actually split on
    hyperplanes — the front-end is not allowed to silently degrade to
    the spill tree everywhere."""
    x = _blobs(rng, 64, 12, 50, noise=0.0003)
    stats = {}
    cl, _ = embed_dbscan(
        x, 0.002, 4, max_points_per_partition=128, stats_out=stats
    )
    assert stats["embed_buckets"] >= 2
    # hyperplanes must do the bulk of the splitting (stray dense nodes
    # may still fall back — that composes, it must not dominate)
    assert stats["embed_spill_fallback_points"] < len(x) // 2
    assert len(np.unique(cl[cl > 0])) == 12


def test_lsh_recall_vs_brute_force_bound(rng):
    """Multi-table co-bucketing recall of eps-close pairs is at or
    above the Goemans-Williamson lower bound (minus sampling noise) —
    the diagnostic contract of the non-primary tables."""
    d, bits, tables = 64, 12, 6
    base = _blobs(rng, d, 40, 1, noise=0.0)
    pert = base + 0.01 * rng.normal(size=base.shape)  # eps-close pairs
    x = np.concatenate([base, pert])
    unit, _ = oracle.normalize_rows(x)
    eps = float(
        (1.0 - np.sum(unit[:40] * unit[40:], axis=1)).max()
    ) + 1e-9
    planes = lsh.make_planes(d, bits, tables, seed=3)
    codes, _proj = lsh.hash_points(
        unit.astype(np.float32), planes, bits, tables
    )
    ii = np.arange(40)
    jj = ii + 40
    recall = float(lsh.pair_covered(codes, ii, jj).mean())
    bound = lsh.collision_lower_bound(eps, bits, tables)
    assert recall >= bound - 0.17, (recall, bound)  # 3 sigma at n=40


def test_bin_points_coverage_is_exact(rng):
    """Every eps-pair shares at least one partition (the coverage
    contract) and every point has exactly one home leaf."""
    from dbscan_tpu.parallel.spill import chord_halo, spill_partition

    x = _blobs(rng, 32, 8, 40, noise=0.002)
    unit, _ = oracle.normalize_rows(x)
    u32 = unit.astype(np.float32)
    eps = 0.01
    halo = chord_halo(eps, 32 * 2.0**-23, dim=32)
    planes = lsh.make_planes(32, 16, 1, seed=0)
    proj0 = (u32 @ planes.T).astype(np.float32)[:, :16]
    part_ids, point_idx, n_parts, home_of = lsh.bin_points(
        proj0, halo, 64,
        lambda idx: spill_partition(u32[idx], 64, halo),
    )
    assert home_of.min() >= 0 and home_of.max() < n_parts
    # brute-force eps-pairs must co-reside somewhere
    dmat = 1.0 - unit @ unit.T
    ai, aj = np.nonzero(np.triu(dmat <= eps, k=1))
    parts_of = [set() for _ in range(len(x))]
    for p, i in zip(part_ids, point_idx):
        parts_of[i].add(int(p))
    for i, j in zip(ai, aj):
        assert parts_of[i] & parts_of[j], (i, j)


# --- subsampled-edge mode ---------------------------------------------


def test_subsampled_mode_ari_floor_and_determinism(rng):
    """The declared accuracy contract (PARITY.md): at frac 0.5 on a
    clusterable workload the sampled labels stay at or above the
    declared ARI floor vs the exact path, and the deterministic pair
    coin makes reruns byte-identical."""
    x = _blobs(rng, 64, 8, 60, noise=0.0005, n_noise=30)
    eps, mp = 0.002, 5
    exact, _ = embed_dbscan(x, eps, mp, max_points_per_partition=128)
    s1 = {}
    samp, _f = embed_dbscan(
        x, eps, mp, max_points_per_partition=128, sample_frac=0.5,
        stats_out=s1,
    )
    assert s1["sample_frac"] == 0.5
    ari = adjusted_rand_index(exact, samp)
    assert ari >= 0.95, ari  # the declared floor, PARITY.md
    samp2, _ = embed_dbscan(
        x, eps, mp, max_points_per_partition=128, sample_frac=0.5
    )
    np.testing.assert_array_equal(samp, samp2)


def test_sample_frac_env_knob(rng, monkeypatch):
    monkeypatch.setenv("DBSCAN_EMBED_SAMPLE_FRAC", "0.5")
    x = _blobs(rng, 16, 3, 40, noise=0.0005)
    s = {}
    embed_dbscan(x, 0.002, 4, max_points_per_partition=64, stats_out=s)
    assert s["sample_frac"] == 0.5
    with pytest.raises(ValueError):
        embed_dbscan(x, 0.002, 4, sample_frac=1.5)


def test_eff_min_points_scaling():
    assert neighbors.eff_min_points(10, 1.0) == 10
    assert neighbors.eff_min_points(10, 0.5) == 6  # ceil(0.5*9)+1
    assert neighbors.eff_min_points(1, 0.1) == 1
    assert neighbors.keep_threshold(1.0) == neighbors.SAMPLE_RES


# --- compiled-shape discipline ----------------------------------------


def test_zero_recompile_across_mixed_jobs(rng):
    """The ladder/ratchet pin: after one warm pass over a mixed N/D
    job stream, re-running the SAME stream compiles nothing and never
    escalates a W rung — the embed analog of the serve/spill
    steady-state pins."""
    jobs = []
    for d, k, per in ((16, 4, 40), (64, 6, 30), (16, 3, 55)):
        jobs.append(_blobs(rng, d, k, per, noise=0.0005))
    was = obs.active()
    obs.enable()
    try:
        for x in jobs:  # warm pass settles every rung
            embed_dbscan(x, 0.002, 4, max_points_per_partition=64)
        snap = obs.counters()
        for x in jobs:
            embed_dbscan(x, 0.002, 4, max_points_per_partition=64)
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.total", 0) == 0, delta
        assert delta.get("embed.neighbor_escalations", 0) == 0
    finally:
        if not was:
            obs.disable()


def test_w_escalation_is_exact(rng):
    """A bucket denser than the starting W rung re-runs at the rung
    its max degree needs; labels stay exact."""
    neighbors.reset_w_floors()
    x = _blobs(rng, 16, 2, 150, noise=0.0002)  # degree ~149 >> first rung
    stats = {}
    cl, fl = embed_dbscan(
        x, 0.002, 4, max_points_per_partition=512, stats_out=stats
    )
    assert stats["embed_escalations"] >= 1
    ocl, ofl = oracle.cosine_dbscan_oracle(x, 0.002, 4)
    np.testing.assert_array_equal(cl, ocl)
    np.testing.assert_array_equal(fl, ofl)


# --- fault-site drills -------------------------------------------------


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


def test_embed_transient_heals(rng, monkeypatch):
    x = _blobs(rng, 32, 4, 40, noise=0.0005)
    clean, clean_f = embed_dbscan(x, 0.002, 4, max_points_per_partition=64)
    _spec(monkeypatch, "embed#1:TRANSIENT*2")
    snap = faults.counters.snapshot()
    cl, fl = embed_dbscan(x, 0.002, 4, max_points_per_partition=64)
    delta = faults.counters.delta(snap)
    assert delta["retries"] >= 2 and delta["injected"] >= 2
    assert delta["fallbacks"] == 0
    np.testing.assert_array_equal(clean, cl)
    np.testing.assert_array_equal(clean_f, fl)


def test_embed_persistent_bucket_degrades_to_oracle(rng, monkeypatch):
    x = _blobs(rng, 32, 4, 40, noise=0.0005)
    clean, clean_f = embed_dbscan(x, 0.002, 4, max_points_per_partition=64)
    _spec(monkeypatch, "embed#2:PERSISTENT")
    stats = {}
    cl, fl = embed_dbscan(
        x, 0.002, 4, max_points_per_partition=64, stats_out=stats
    )
    assert stats["embed_oracle_buckets"] >= 1
    np.testing.assert_array_equal(clean, cl)
    np.testing.assert_array_equal(clean_f, fl)


def test_embed_persistent_hash_degrades_whole_run(rng, monkeypatch):
    x = _blobs(rng, 32, 4, 40, noise=0.0005)
    _spec(monkeypatch, "embed#0:PERSISTENT")  # ordinal 0 = the hash
    stats = {}
    cl, fl = embed_dbscan(
        x, 0.002, 4, max_points_per_partition=64, stats_out=stats
    )
    assert stats.get("embed_degraded") == "oracle"
    ocl, ofl = oracle.cosine_dbscan_oracle(x, 0.002, 4)
    np.testing.assert_array_equal(cl, ocl)
    np.testing.assert_array_equal(fl, ofl)


def test_embed_persistent_without_fallback_raises(rng, monkeypatch):
    x = _blobs(rng, 32, 4, 40, noise=0.0005)
    _spec(monkeypatch, "embed#0:PERSISTENT")
    with pytest.raises(faults.FatalDeviceFault):
        embed_dbscan(
            x, 0.002, 4, max_points_per_partition=64,
            oracle_fallback=False,
        )


# --- spill-tree fallback at D=64 ---------------------------------------


def test_spill_fallback_d64_device_host_parity(rng, monkeypatch):
    """The embed fallback reuses the dimension-agnostic spill tree
    unmodified: at D=64, forced device passes (level build on) and the
    host recursion produce byte-identical labels."""
    x = _blobs(rng, 64, 5, 60, noise=0.02)  # loose eps => fallback
    eps, mp = 0.05, 5
    stats_h = {}
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "0")
    host, host_f = embed_dbscan(
        x, eps, mp, max_points_per_partition=64, stats_out=stats_h
    )
    assert stats_h["embed_spill_fallbacks"] >= 1
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE_TREE", "1")
    dev, dev_f = embed_dbscan(x, eps, mp, max_points_per_partition=64)
    np.testing.assert_array_equal(host, dev)
    np.testing.assert_array_equal(host_f, dev_f)


def test_spill_device_rank_guard():
    from dbscan_tpu.parallel.spill_device import DeviceNodeOps

    with pytest.raises(ValueError, match=r"\[N, D\]"):
        DeviceNodeOps.from_host(np.ones(8, np.float32))
    with pytest.raises(ValueError, match=r"\[N, D\]"):
        DeviceNodeOps.from_host(np.ones((4, 2, 2), np.float32))
    ops = DeviceNodeOps.from_host(np.ones((4, 64), np.float32))
    assert ops.dim == 64 and ops.n == 4


# --- telemetry ---------------------------------------------------------


def test_embed_counters_declared_and_analyzed(rng, tmp_path):
    """Every embed.* emission is schema-declared and the analyzer's
    -- embed -- section derives the occupancy/fallback/sampling
    figures from them."""
    from dbscan_tpu.obs import analyze as obs_analyze
    from dbscan_tpu.obs import schema

    trace = tmp_path / "embed_trace.jsonl"
    was = obs.active()
    obs.enable(trace_path=str(trace))
    try:
        x = _blobs(rng, 64, 8, 40, noise=0.0005)
        embed_dbscan(
            x, 0.002, 4, max_points_per_partition=64, sample_frac=0.5
        )
        snap = obs.counters()
        for name in snap:
            assert schema.is_declared("counter", name), name
    finally:
        obs.flush()
        if not was:
            obs.disable()
    report = obs_analyze.analyze(obs_analyze.load_trace(str(trace)))
    emb = report["embed"]
    assert emb["embed.points"] == len(x)
    assert emb["embed.dup_factor"] >= 1.0
    assert emb["embed.sampled_edge_frac"] == 0.5
    assert "embed.spill_fallback_rate" in emb
    occ = sum(
        emb.get(k, 0)
        for k in (
            "embed.occ_le_64", "embed.occ_le_1024",
            "embed.occ_le_16384", "embed.occ_gt_16384",
        )
    )
    assert occ >= 1
    text = obs_analyze.render(report)
    assert "-- embed (LSH binning / cosine neighbors) --" in text


# --- concurrency -------------------------------------------------------


def test_embed_suite_race_free_under_tsan(tmp_path):
    """DBSCAN_TSAN=1 concurrent rerun: the PullEngine-overlapped land
    path and the W-floor ratchet must produce an empty race report."""
    report = tmp_path / "tsan_report.json"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_TSAN": "1",
        "DBSCAN_TSAN_REPORT": str(report),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(REPO, "tests", "test_embed.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
            "-k", "exact_parity_fuzz and (d8 or d64)",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    with open(report) as f:
        rep = json.load(f)
    assert rep["races"] == [], rep["races"]
    assert rep["lock_inversions"] == [], rep["lock_inversions"]
