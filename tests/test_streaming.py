"""Streaming micro-batch DBSCAN: identity persistence, merges, windowing."""

import numpy as np
import pytest

from dbscan_tpu.streaming import StreamingDBSCAN


def _blob(rng, center, n=60, s=0.25):
    return rng.normal(center, s, size=(n, 2))


def test_stable_identity_across_batches(rng):
    s = StreamingDBSCAN(eps=0.6, min_points=5, max_points_per_partition=500)
    u1 = s.update(_blob(rng, (0, 0)))
    assert u1.n_stream_clusters == 1
    sid = np.unique(u1.clusters[u1.clusters > 0])
    assert len(sid) == 1
    # same region next batch -> same stream id
    u2 = s.update(_blob(rng, (0.1, 0.1)))
    sid2 = np.unique(u2.clusters[u2.clusters > 0])
    np.testing.assert_array_equal(sid, sid2)
    # far-away new blob -> new id
    u3 = s.update(_blob(rng, (30, 30)))
    sid3 = np.unique(u3.clusters[u3.clusters > 0])
    assert len(sid3) == 1 and sid3[0] != sid[0]
    assert u3.n_stream_clusters == 2


def test_merge_unifies_ids(rng):
    s = StreamingDBSCAN(eps=0.6, min_points=5, max_points_per_partition=500)
    a = s.update(_blob(rng, (0, 0)))
    b = s.update(_blob(rng, (4, 0)))
    ida = int(np.unique(a.clusters[a.clusters > 0])[0])
    idb = int(np.unique(b.clusters[b.clusters > 0])[0])
    assert ida != idb
    # a bridge batch connecting both blobs
    bridge = np.stack(
        [np.linspace(-0.5, 4.5, 120), np.zeros(120)], axis=1
    ) + rng.normal(0, 0.05, (120, 2))
    u = s.update(bridge)
    merged = np.unique(u.clusters[u.clusters > 0])
    assert len(merged) == 1
    assert merged[0] == min(ida, idb)  # elder id wins
    assert u.n_stream_clusters == 1
    # previously-emitted labels resolve to the surviving id
    np.testing.assert_array_equal(
        s.resolve(np.array([ida, idb])), [min(ida, idb)] * 2
    )


def test_window_expiry_forgets_old_density(rng):
    s = StreamingDBSCAN(
        eps=0.6, min_points=5, max_points_per_partition=500, window=1
    )
    u1 = s.update(_blob(rng, (0, 0)))
    id1 = int(np.unique(u1.clusters[u1.clusters > 0])[0])
    # push two unrelated batches through the window=1 skeleton
    s.update(_blob(rng, (20, 20)))
    s.update(_blob(rng, (40, 40)))
    # back at the origin: old cores expired, so this is a NEW stream id
    u4 = s.update(_blob(rng, (0, 0)))
    id4 = int(np.unique(u4.clusters[u4.clusters > 0])[0])
    assert id4 != id1


def test_noise_batch(rng):
    s = StreamingDBSCAN(eps=0.3, min_points=8, max_points_per_partition=500)
    u = s.update(rng.uniform(-50, 50, size=(40, 2)))
    assert (u.clusters == 0).all()
    assert u.n_stream_clusters == 0


def test_buffer_reuse_no_recompile(rng):
    """Same-shaped micro-batches must hit the jit cache (the TPU
    partition-buffer-reuse contract): compiled-function count stays flat
    after the first update."""
    from dbscan_tpu.ops.local_dbscan import local_dbscan

    s = StreamingDBSCAN(eps=0.6, min_points=5, max_points_per_partition=500)
    s.update(_blob(rng, (0, 0), n=128))
    misses0 = local_dbscan._cache_size()
    for i in range(3):
        s.update(_blob(rng, (i * 0.2, 0), n=128))
    assert local_dbscan._cache_size() == misses0


def test_rejects_bad_batch(rng):
    s = StreamingDBSCAN(eps=0.5, min_points=3)
    with pytest.raises(ValueError, match=r"\[B, >=2\]"):
        s.update(np.zeros(5))


def test_stress_many_clusters_large_batch(rng):
    """>=100k points/batch, thousands of clusters: the identity-carry path
    must stay vectorized (no per-cluster masking over the batch). Two
    updates + a bulk resolve; wall time is the regression signal (the old
    per-id loops took minutes at this scale)."""
    import time

    side = 45  # 2025 blob centers
    centers = np.stack(
        np.meshgrid(np.arange(side) * 10.0, np.arange(side) * 10.0),
        axis=-1,
    ).reshape(-1, 2)
    per = 50  # 101_250 points per batch
    batch = (
        np.repeat(centers, per, axis=0)
        + rng.normal(0, 0.3, (len(centers) * per, 2))
    )
    s = StreamingDBSCAN(
        eps=1.5, min_points=5, max_points_per_partition=65536
    )
    t0 = time.perf_counter()
    u1 = s.update(batch)
    assert u1.n_stream_clusters == len(centers)
    # second batch over the same regions: every cluster keeps its id
    u2 = s.update(batch + rng.normal(0, 0.3, batch.shape))
    assert u2.n_stream_clusters == len(centers)
    assert set(np.unique(u2.clusters[u2.clusters > 0])) <= set(
        np.unique(u1.clusters[u1.clusters > 0])
    )
    # bulk resolve over the full emitted label array
    r = s.resolve(u1.clusters)
    assert (r[u1.clusters > 0] > 0).all()
    elapsed = time.perf_counter() - t0
    assert elapsed < 120, f"streaming stress took {elapsed:.0f}s"


def test_streaming_haversine_identity(rng):
    """Non-euclidean streaming: haversine micro-batches keep stream
    identity across updates (the window skeleton rides the spherical
    decomposition)."""
    from dbscan_tpu import DBSCANConfig

    s = StreamingDBSCAN(
        eps=0.3, min_points=5,
        config=DBSCANConfig(
            eps=0.3, min_points=5, max_points_per_partition=500,
            metric="haversine",
        ),
    )
    nyc = np.array([-73.98, 40.75])
    blob = nyc + rng.normal(0, 0.0008, (60, 2))
    u1 = s.update(blob)
    sid = np.unique(u1.clusters[u1.clusters > 0])
    assert len(sid) == 1
    u2 = s.update(nyc + rng.normal(0, 0.0008, (60, 2)))
    np.testing.assert_array_equal(
        np.unique(u2.clusters[u2.clusters > 0]), sid
    )


def test_streaming_cosine_uses_all_columns(rng):
    """Cosine streaming consumes every column: two batches identical in
    the first two dims but opposite in the third stay distinct ids."""
    from dbscan_tpu import DBSCANConfig

    s = StreamingDBSCAN(
        eps=0.05, min_points=5,
        config=DBSCANConfig(
            eps=0.05, min_points=5, max_points_per_partition=500,
            metric="cosine",
        ),
    )
    base = rng.normal(size=(50, 2)) * 0.01 + np.array([1.0, 1.0])
    up = np.concatenate([base, np.full((50, 1), 5.0)], axis=1)
    down = np.concatenate([base, np.full((50, 1), -5.0)], axis=1)
    u1 = s.update(up)
    u2 = s.update(down)
    id1 = set(np.unique(u1.clusters[u1.clusters > 0]))
    id2 = set(np.unique(u2.clusters[u2.clusters > 0]))
    assert id1 and id2 and not (id1 & id2)
