"""Elastic fault-priced campaign driver (dbscan_tpu/campaign.py).

The acceptance contract this suite pins:

- under a deterministic worker-kill fault spec (>= 2 kills across a
  multi-chunk campaign) the campaign COMPLETES with labels
  byte-identical to a fault-free run, and ``campaign_replay_frac``
  prices the wasted wall;
- a wedged worker's lease provably EXPIRES and is restolen by the rest
  of the fleet (the heartbeat-expiry steal path);
- a worker whose device path exhausts its retries DEGRADES to the CPU
  tier instead of aborting the campaign — labels unchanged;
- a campaign worker killed by SIGTERM between chunk flushes leaves a
  flightrec dump, its banked chunks intact, and a clean steal+resume
  by another worker (subprocess drill);
- the ``DBSCAN_TSAN=1`` rerun of this suite reports zero races on the
  shared queue state.

Plus the queue/lease/replay-pricing unit semantics, the fault-rate
lease-size ladder, frontier-mode subprocess campaigns (the m100 mold),
and the ``campaign_replay_frac`` history promotion + regress-up gate.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dbscan_tpu import campaign as camp
from dbscan_tpu import faults
from dbscan_tpu.parallel import checkpoint as ckpt_mod
from dbscan_tpu.parallel import driver

pytestmark = pytest.mark.campaign

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    yield
    faults.reset_registry()


def _pts():
    return camp.demo_points(3000, seed=0)


def _cfg():
    from dbscan_tpu.config import DBSCANConfig, Engine

    return DBSCANConfig(
        eps=0.5, min_points=5, max_points_per_partition=256,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )


@pytest.fixture
def small_chunks(monkeypatch):
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)


# --- ChunkQueue unit semantics -----------------------------------------


def test_queue_lease_complete_and_replay_pricing():
    q = camp.ChunkQueue(range(6), lease_s=60.0)
    a = q.lease("w0", 4, "device")
    assert a.chunks == (0, 1, 2, 3)
    b = q.lease("w1", 4, "device")
    assert b.chunks == (4, 5)
    assert q.lease("w2", 1, "device") is None  # nothing pending
    for ci in a.chunks:
        q.note_chunk(a, ci)
    q.release(a, wall_s=4.0, outcome="ok")
    # b fails having banked one of two chunks: half the wall is wasted
    q.note_chunk(b, 4)
    q.release(b, wall_s=2.0, outcome="error")
    snap = q.snapshot()
    assert snap["work_wall_s"] == pytest.approx(6.0)
    assert snap["replayed_wall_s"] == pytest.approx(1.0)
    assert snap["steals"] == 1
    assert not q.done()
    c = q.lease("w0", 4, "device")
    assert c.chunks == (5,)  # only the unfinished chunk re-leases
    q.note_chunk(c, 5)
    q.release(c, wall_s=1.0, outcome="ok")
    assert q.done()
    assert camp.replay_frac(7.0, 1.0) == pytest.approx(1.0 / 7.0, rel=1e-3)


def test_queue_expiry_steals_wedged_lease():
    q = camp.ChunkQueue(range(3), lease_s=0.05)
    lease = q.lease("wedged", 3, "device")
    assert q.lease("thief", 1, "device") is None
    time.sleep(0.08)
    stolen = q.expire_stale()
    assert [s.lease_id for s in stolen] == [lease.lease_id]
    assert lease.active is False and lease.outcome == "expired"
    # the stale holder's late report is ignored — no double pricing
    before = q.snapshot()
    q.note_chunk(lease, 0)
    q.release(lease, wall_s=99.0, outcome="ok")
    after = q.snapshot()
    assert after["work_wall_s"] == before["work_wall_s"]
    assert after["chunks_done"] == 0
    # the thief gets all three chunks back
    steal = q.lease("thief", 3, "device")
    assert steal.chunks == (0, 1, 2)
    assert after["expired"] == 1 and after["steals"] == 3


def test_queue_mark_done_excludes_banked_chunks():
    q = camp.ChunkQueue(range(4), lease_s=60.0)
    q.mark_done([1, 3])
    lease = q.lease("w0", 10, "device")
    assert lease.chunks == (0, 2)
    q.note_chunk(lease, 0)
    q.note_chunk(lease, 2)
    q.release(lease, 1.0, "ok")
    assert q.done()


# --- fault-rate-aware lease-size ladder --------------------------------


class _SyntheticJob:
    """Scripted lease outcomes: 'ok' completes every chunk, 'fail'
    raises after completing none, 'faulty' completes with a nonzero
    retry delta (device faults that healed)."""

    def __init__(self, script):
        self.script = list(script)
        self.leases = []

    def plan(self):
        return {"output": None, "chunks_total": 12, "banked": []}

    def run_lease(self, chunks, *, tier, kill_after=0, kill_ordinal=-1,
                  on_chunk=None, heartbeat=None, should_stop=None):
        if heartbeat is not None:
            heartbeat()
        mode = self.script.pop(0) if self.script else "ok"
        self.leases.append((tuple(chunks), tier, mode))
        if mode == "fail":
            raise RuntimeError("synthetic leg failure")
        for ci in chunks:
            if on_chunk is not None:
                on_chunk(ci)
        retries = 2 if mode == "faulty" else 0
        return {"faults": {"retries": retries, "fallbacks": 0}}

    def finalize(self):
        return "assembled"


def test_worker_repartitions_lease_size_by_fault_rate():
    job = _SyntheticJob(["faulty", "fail", "ok", "ok", "ok", "ok", "ok"])
    result = camp.Campaign(
        job, workers=1, lease_s=60.0, min_chunk=1, max_chunk=4,
        budget_s=30.0, poll_s=0.01,
    ).run()
    assert result.complete and result.output == "assembled"
    sizes = [len(c) for c, _t, _m in job.leases]
    # starts at 2; the faulty lease halves to 1; the failed lease keeps
    # it floored; two clean leases double back to 2, then toward 4
    assert sizes[0] == 2
    assert sizes[1] == 1  # halved after the faulty lease
    assert max(sizes) == 4  # sustained health grew it to the cap
    assert result.replay_frac > 0.0  # the failed lease was priced
    assert result.chunks_done == result.chunks_total == 12


class _SlowHeartbeatJob(_SyntheticJob):
    """A healthy leg whose first chunk takes several expiry windows —
    it must stay leased as long as it heartbeats (per-group progress),
    never be stolen mid-compute."""

    def __init__(self, beat_s, beats):
        super().__init__([])
        self.beat_s = beat_s
        self.beats = beats

    def run_lease(self, chunks, *, heartbeat=None, on_chunk=None, **kw):
        self.leases.append((tuple(chunks), kw.get("tier"), "slow"))
        for _ in range(self.beats):
            time.sleep(self.beat_s)
            if heartbeat is not None:
                heartbeat()
        for ci in chunks:
            if on_chunk is not None:
                on_chunk(ci)
        return {"faults": {"retries": 0, "fallbacks": 0}}


def test_healthy_slow_lease_heartbeats_instead_of_expiring():
    """Regression (review finding): a lease whose first chunk outlives
    DBSCAN_CAMPAIGN_LEASE_S must NOT be expired while it demonstrates
    per-group progress — only a leg with no progress for a whole
    window reads as wedged. Here every lease runs ~3 expiry windows
    while heartbeating twice per window: zero expiries, zero replay."""
    job = _SlowHeartbeatJob(beat_s=0.1, beats=6)  # 0.6s per lease
    result = camp.Campaign(
        job, workers=2, lease_s=0.2, min_chunk=4, max_chunk=4,
        budget_s=30.0, poll_s=0.02,
    ).run()
    assert result.complete, result.last_error
    assert result.expired == 0
    assert result.steals == 0
    assert result.replay_frac == 0.0


def test_all_wedged_campaign_terminates_without_budget():
    """Regression (review finding): every worker wedged (injected
    PERSISTENT) with budget_s=None must terminate incomplete — not
    spin forever on a queue nobody can drain."""
    os.environ["DBSCAN_FAULT_SPEC"] = "campaign#0:PERSISTENT"
    faults.reset_registry()
    try:
        job = _SyntheticJob([])
        t0 = time.monotonic()
        result = camp.Campaign(
            job, workers=1, lease_s=0.2, poll_s=0.02,
        ).run()
        assert not result.complete
        assert result.wedges == 1
        assert job.leases == []  # the wedged worker never ran a leg
        assert time.monotonic() - t0 < 30.0
    finally:
        os.environ.pop("DBSCAN_FAULT_SPEC", None)
        faults.reset_registry()


def test_worker_retires_after_repeated_errors():
    job = _SyntheticJob(["fail"] * 20)
    result = camp.Campaign(
        job, workers=1, lease_s=60.0, min_chunk=1, max_chunk=2,
        budget_s=10.0, poll_s=0.01,
    ).run()
    assert not result.complete
    assert result.output is None
    assert "synthetic leg failure" in result.last_error
    assert result.replay_frac == pytest.approx(1.0)  # nothing landed


# --- the acceptance drills (real clustering job) -----------------------


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


def test_two_kills_campaign_labels_byte_identical(
    tmp_path, monkeypatch, small_chunks
):
    """THE acceptance drill: >= 2 deterministic worker kills across a
    multi-chunk campaign; the campaign completes, labels are
    byte-identical to a fault-free run, and the replay budget priced
    the kills. Each kill goes through the driver's REAL abort path, so
    the abort site lands in the progress sidecar too."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    _spec(monkeypatch, "campaign#0:TRANSIENT;campaign#2:TRANSIENT")
    job = camp.TrainChunkJob(pts, _cfg(), str(tmp_path))
    result = camp.Campaign(
        job, workers=2, lease_s=30.0, budget_s=300.0, poll_s=0.05
    ).run()
    assert result.complete, result.last_error
    assert result.kills == 2
    assert result.chunks_total >= 3  # a real multi-chunk campaign
    assert result.replay_frac > 0.0  # the kills cost priced wall
    assert result.replay_frac <= 1.0
    np.testing.assert_array_equal(result.output.clusters, clean.clusters)
    np.testing.assert_array_equal(result.output.flags, clean.flags)
    # the kill drove the driver's real abort path: site recorded
    assert ckpt_mod.read_progress(str(tmp_path)).get(
        "aborted_site"
    ) == "campaign"


def test_wedged_worker_lease_expires_and_is_restolen(
    tmp_path, monkeypatch, small_chunks
):
    """A PERSISTENT campaign clause wedges a worker mid-campaign: its
    lease must heartbeat-expire and its chunks must be restolen by the
    other worker, completing the campaign with identical labels."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    _spec(monkeypatch, "campaign#1:PERSISTENT")
    job = camp.TrainChunkJob(pts, _cfg(), str(tmp_path))
    result = camp.Campaign(
        job, workers=2, lease_s=2.0, budget_s=300.0, poll_s=0.05
    ).run()
    assert result.complete, result.last_error
    assert result.wedges == 1
    assert result.expired >= 1  # the wedged lease provably expired
    assert result.steals >= 1  # and its chunks were restolen
    assert result.replay_frac > 0.0  # the wedge wall was priced
    np.testing.assert_array_equal(result.output.clusters, clean.clusters)
    np.testing.assert_array_equal(result.output.flags, clean.flags)


def test_exhausted_worker_degrades_to_cpu_tier(
    tmp_path, monkeypatch, small_chunks
):
    """An injected RESOURCE_EXHAUSTED at the campaign site degrades the
    worker's whole lease stream to the CPU tier (the per-group
    degradation machinery generalized to chunk leases) — the campaign
    completes instead of aborting, labels byte-identical."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    _spec(monkeypatch, "campaign#0:RESOURCE_EXHAUSTED")
    job = camp.TrainChunkJob(pts, _cfg(), str(tmp_path))
    result = camp.Campaign(
        job, workers=1, lease_s=30.0, budget_s=300.0, poll_s=0.05
    ).run()
    assert result.complete, result.last_error
    assert result.degrades == 1
    np.testing.assert_array_equal(result.output.clusters, clean.clusters)
    np.testing.assert_array_equal(result.output.flags, clean.flags)


def test_campaign_resumes_over_banked_chunks(
    tmp_path, monkeypatch, small_chunks
):
    """A campaign over a dir where an earlier (interrupted) campaign
    banked some chunks leases ONLY the holes, and the premerge-complete
    case short-circuits to a zero-lease result."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    job = camp.TrainChunkJob(pts, _cfg(), str(tmp_path))
    plan = job.plan()
    total = plan["chunks_total"]
    assert total >= 3
    # bank chunk 0 and the last chunk "by hand" (a dead campaign's legs)
    job.run_lease([0, total - 1], tier="device")
    leased = []

    class _Spy(camp.TrainChunkJob):
        def run_lease(self, chunks, **kw):
            leased.append(tuple(chunks))
            return super().run_lease(chunks, **kw)

    spy = _Spy(pts, _cfg(), str(tmp_path))
    result = camp.Campaign(
        spy, workers=1, lease_s=30.0, budget_s=300.0, poll_s=0.05
    ).run()
    assert result.complete
    got = sorted(c for ch in leased for c in ch)
    assert got == list(range(1, total - 1))  # only the holes leased
    np.testing.assert_array_equal(result.output.clusters, clean.clusters)
    # second campaign over the now-complete dir: premerge resume,
    # zero leases, zero replay
    again = camp.Campaign(
        camp.TrainChunkJob(pts, _cfg(), str(tmp_path)),
        workers=1, lease_s=30.0, poll_s=0.05,
    ).run()
    assert again.complete and again.leases == 0
    assert again.replay_frac == 0.0
    assert again.output.stats["resumed_from_checkpoint"] is True


def test_waiting_for_device_lease_heartbeats_instead_of_expiring(
    tmp_path, small_chunks
):
    """Regression (review finding): a worker queued BEHIND the
    in-process device lease is healthy — its lease must heartbeat
    through the wait (several expiry windows long here) instead of
    being expired and restolen into duplicate recompute."""
    pts = _pts()
    job = camp.TrainChunkJob(pts, _cfg(), str(tmp_path))
    total = job.plan()["chunks_total"]
    q = camp.ChunkQueue(range(total), lease_s=0.4)
    lease = q.lease("w0", total, "device")
    assert camp._DEVICE_LEASE.acquire()  # a peer's leg holds the device
    try:
        t = threading.Thread(
            target=lambda: job.run_lease(
                sorted(lease.chunks),
                tier="device",
                on_chunk=lambda ci: q.note_chunk(lease, ci),
                heartbeat=lambda: q.heartbeat(lease),
            ),
        )
        t.start()
        time.sleep(1.3)  # ~3 expiry windows spent blocked on the lock
        assert q.expire_stale() == []  # the wait heartbeats kept it alive
        assert lease.active
    finally:
        camp._DEVICE_LEASE.release()
    t.join(180)
    assert not t.is_alive()
    q.release(lease, 1.0, "ok")
    assert q.done()
    snap = q.snapshot()
    assert snap["expired"] == 0 and snap["replayed_wall_s"] == 0.0


# --- SIGTERM mid-leg subprocess drill (satellite) ----------------------


def _wait_for(pred, timeout_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def test_sigterm_mid_leg_leaves_dump_banked_chunks_and_resumes(
    tmp_path, monkeypatch, small_chunks
):
    """A campaign worker killed by SIGTERM between chunk flushes must
    leave a flightrec dump, its banked chunks intact, and a clean
    steal+resume by another worker with byte-identical labels."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    ck = tmp_path / "ck"
    dump = tmp_path / "flight.json"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_FLIGHTREC_PATH": str(dump),
        # serial per-flush pulls: chunks bank one by one, so the
        # SIGTERM window "between chunk flushes" is wide and real
        "DBSCAN_EAGER_PULL": "1",
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dbscan_tpu.campaign",
            "--leg", "--ckpt", str(ck),
            "--n", "3000", "--chunk-slots", "512",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        _wait_for(
            lambda: ckpt_mod.count_p1_chunks(str(ck)) >= 1,
            timeout_s=120,
            what="first banked chunk",
        )
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode != 0  # the leg really died
    # flightrec postmortem written by the SIGTERM handler
    _wait_for(lambda: dump.exists(), 10, "flightrec dump")
    rep = json.loads(dump.read_text())
    assert rep["reason"] == "SIGTERM"
    # banked chunks are intact restart points
    job = camp.TrainChunkJob(pts, _cfg(), str(ck))
    banked = ckpt_mod.p1_chunk_indices(
        str(ck), job._fingerprint(), budget=512
    )
    assert len(banked) >= 1
    # another worker steals the rest and the campaign completes
    result = camp.Campaign(
        job, workers=1, lease_s=30.0, budget_s=300.0, poll_s=0.05
    ).run()
    assert result.complete, result.last_error
    np.testing.assert_array_equal(result.output.clusters, clean.clusters)
    np.testing.assert_array_equal(result.output.flags, clean.flags)


# --- frontier mode (the m100 mold) -------------------------------------


def test_frontier_campaign_kill_drill_resumes_and_prices_replay(
    tmp_path, monkeypatch, small_chunks
):
    """Frontier campaign over subprocess legs: a TRANSIENT campaign
    clause kills leg 1 right after it banks a chunk; leg 2 steals the
    frontier, resumes from the banked chunks, and completes. The killed
    leg's unbanked wall is priced into replay_frac."""
    pts = _pts()
    clean = driver.train_arrays(pts, _cfg())
    ck = tmp_path / "ck"
    _spec(monkeypatch, "campaign#0:TRANSIENT")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_EAGER_PULL": "1",
    }
    env.pop("DBSCAN_FAULT_SPEC", None)  # the drill is the PARENT's
    fr = camp.run_frontier(
        str(ck),
        [
            sys.executable, "-m", "dbscan_tpu.campaign",
            "--leg", "--ckpt", str(ck),
            "--n", "3000", "--chunk-slots", "512",
        ],
        env=env,
        max_leases=3,
        budget_s=600.0,
        leg_timeout_s=300.0,
        rest_s=0.1,
        poll_s=0.05,
    )
    assert fr.complete, fr.last_error
    assert fr.kills == 1
    assert fr.legs == 2
    assert fr.replay_frac > 0.0
    assert fr.chunks_done == fr.chunks_total
    # the banked chunks merge into byte-identical labels
    out = driver.train_arrays(pts, _cfg(), checkpoint_dir=str(ck))
    np.testing.assert_array_equal(out.clusters, clean.clusters)
    assert out.stats["resumed_from_checkpoint"] is True


def test_frontier_resource_exhausted_degrades_leg_env(
    tmp_path, monkeypatch, small_chunks
):
    """A RESOURCE_EXHAUSTED campaign clause on a frontier campaign
    degrades the leg stream to the CPU backend (JAX_PLATFORMS=cpu in
    the child env) instead of being silently ignored — the documented
    grammar holds for both campaign shapes."""
    ck = tmp_path / "ck"
    _spec(monkeypatch, "campaign#0:RESOURCE_EXHAUSTED")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "DBSCAN_EAGER_PULL": "1"}
    env.pop("DBSCAN_FAULT_SPEC", None)
    fr = camp.run_frontier(
        str(ck),
        [
            sys.executable, "-m", "dbscan_tpu.campaign",
            "--leg", "--ckpt", str(ck),
            "--n", "3000", "--chunk-slots", "512",
        ],
        env=env,
        max_leases=2,
        budget_s=600.0,
        leg_timeout_s=300.0,
        rest_s=0.1,
        poll_s=0.1,
    )
    assert fr.complete, fr.last_error
    assert fr.degrades == 1
    assert fr.legs == 1  # degraded tier, not killed: one clean leg


def test_frontier_stall_breakout_on_progress_counter(tmp_path):
    """Two consecutive legs that bank nothing break the campaign out —
    signalled by the sidecar's monotone chunk-write counter, not file
    mtimes."""
    ck = tmp_path / "ck"
    ck.mkdir()
    fr = camp.run_frontier(
        str(ck),
        [sys.executable, "-c", "raise SystemExit(3)"],
        env={**os.environ},
        max_leases=5,
        budget_s=60.0,
        leg_timeout_s=30.0,
        rest_s=0.05,
        poll_s=0.05,
    )
    assert not fr.complete
    assert fr.stall_break is True
    assert fr.legs == 2  # broke out, did not burn all 5 leases
    assert fr.replay_frac == pytest.approx(1.0)  # pure waste
    assert "rc 3" in fr.last_error


# --- leg-progress signal + campaign key --------------------------------


def test_leg_progressed_counter_authoritative_with_mtime_fallback(
    tmp_path,
):
    ck = str(tmp_path)
    # no sidecar counter at all: mtime fallback
    assert camp.progress_counter(ck) == -1
    assert not camp.leg_progressed(ck, -1, time.time() + 60)
    (tmp_path / "p1chunk0000.npz").write_bytes(b"x")
    assert camp.leg_progressed(ck, -1, time.time() - 60)
    # once the counter exists it is authoritative: stale mtimes in the
    # window no longer count as progress
    ckpt_mod.write_progress(ck, **{ckpt_mod.PROGRESS_WRITE_COUNTER: 5})
    assert camp.progress_counter(ck) == 5
    assert camp.leg_progressed(ck, 4, time.time() + 60)
    assert not camp.leg_progressed(ck, 5, time.time() - 60)


def test_ensure_campaign_key_invalidates_on_change(tmp_path):
    ck = str(tmp_path)
    key = {"n": 1, "maxpp": 2}
    assert camp.ensure_campaign_key(ck, key) is False  # first write
    ckpt_mod.save_p1_chunk(
        ck, "fp", 0, "sig0",
        np.array([[4, 512, 8]], dtype=np.int64),
        {"combo": np.zeros(8, np.uint8)},
        budget=512,
    )
    ckpt_mod.write_progress(ck, chunks_total=9)
    assert camp.ensure_campaign_key(ck, key) is False  # unchanged: keep
    assert ckpt_mod.count_p1_chunks(ck) == 1
    assert camp.ensure_campaign_key(ck, {"n": 2, "maxpp": 2}) is True
    assert ckpt_mod.count_p1_chunks(ck) == 0  # wiped
    assert ckpt_mod.read_progress(ck) == {}


# --- replay-frac promotion + regress gate ------------------------------


def test_replay_frac_promoted_and_gated_regress_up():
    from dbscan_tpu.obs import bench_history, regress

    assert regress.direction("m100_campaign_replay_frac") == "lower"
    assert regress.direction("campaign_replay_frac") == "lower"
    recs = bench_history.normalize_capture(
        {"campaign_replay_frac": 0.12, "backend": "cpu"}, "t.json", "rev"
    )
    assert [
        (r["metric"], r["unit"]) for r in recs
    ] == [("campaign_replay_frac", "ratio")]
    history = [
        {"metric": "campaign_replay_frac", "value": v, "unit": "ratio",
         "backend": "cpu", "resident_hot": None, "rev": "r",
         "source": f"h{i}.json"}
        for i, v in enumerate((0.10, 0.12))
    ]
    bad = dict(history[0], value=0.55, source="fresh.json")
    res = regress.compare([bad], history)
    assert [e["metric"] for e in res["regressions"]] == [
        "campaign_replay_frac"
    ]
    good = dict(history[0], value=0.11, source="fresh.json")
    assert regress.compare([good], history)["regressions"] == []


def test_committed_history_gates_campaign_replay_frac():
    """The committed bench/history.jsonl carries enough
    campaign_replay_frac samples for the regress gate to actually gate
    (min_samples=2) — a future capture with doubled restart overhead
    fails CI."""
    from dbscan_tpu.obs import bench_history, regress

    history = bench_history.load_history(
        os.path.join(REPO, "bench", "history.jsonl")
    )
    samples = [
        h for h in history if h["metric"] == "campaign_replay_frac"
    ]
    assert len(samples) >= 2, "committed replay-frac baseline missing"
    worst = max(s["value"] for s in samples)
    bad = {
        "metric": "campaign_replay_frac",
        "value": max(worst * 4.0, 0.9),
        "unit": "ratio",
        "backend": samples[0]["backend"],
        "resident_hot": None,
        "source": "fresh.json",
    }
    res = regress.compare([bad], history)
    assert [e["metric"] for e in res["regressions"]] == [
        "campaign_replay_frac"
    ]


# --- concurrency: the tsan acceptance rerun ----------------------------


def test_campaign_queue_hammer_is_race_free():
    """Raw concurrent hammer on one ChunkQueue under the runtime
    sanitizer: every access must carry the queue monitor."""
    from dbscan_tpu.lint import tsan

    # under the DBSCAN_TSAN=1 rerun the sanitizer is already live for
    # the whole process — don't reset/disable the accumulated state the
    # atexit report asserts on
    was_enabled = tsan.enabled()
    if not was_enabled:
        tsan.reset()
        tsan.enable()
    try:
        q = camp.ChunkQueue(range(64), lease_s=60.0)

        def worker(name):
            while True:
                lease = q.lease(name, 3, "device")
                if lease is None:
                    if q.done():
                        return
                    q.wait(0.01)
                    continue
                for ci in lease.chunks:
                    q.note_chunk(lease, ci)
                q.release(lease, 0.01, "ok")

        threads = [
            threading.Thread(target=worker, args=(f"w{i}",))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert q.done()
        tsan.assert_clean()
    finally:
        if not was_enabled:
            tsan.disable()
            tsan.reset()


def test_campaign_suite_race_free_under_tsan(tmp_path):
    """DBSCAN_TSAN=1 rerun of the campaign drills: the suite passes AND
    the atexit report shows zero races / zero lock inversions across
    the shared queue state and the worker fleet."""
    report = tmp_path / "tsan_report.json"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_TSAN": "1",
        "DBSCAN_TSAN_REPORT": str(report),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(REPO, "tests", "test_campaign.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
            "-k", "kills or wedged or repartitions or hammer",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    rep = json.loads(report.read_text())
    assert rep["enabled"] is True
    assert rep["races"] == [], rep["races"]
    assert rep["lock_inversions"] == [], rep["lock_inversions"]
    worker_threads = {
        t
        for site in rep["accesses"].values()
        for t in site["threads"]
        if t.startswith("dbscan-campaign")
    }
    assert worker_threads, "no campaign-worker activity recorded"
