"""Native host-kernel parity: every ctypes wrapper must be bit-identical
to its numpy fallback (dbscan_tpu/_native.py builds native/hostops.cpp on
first use; when the toolchain is missing the wrappers fall back silently,
and these tests then assert the fallback against itself — still valid)."""

import numpy as np
import pytest

from dbscan_tpu import _native


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(1234)


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.uint32, np.uint64])
@pytest.mark.parametrize("n,hi", [(0, 10), (1, 1), (1000, 7), (100_000, 2**20)])
def test_argsort_matches_numpy_stable(rng, dtype, n, hi):
    keys = rng.integers(0, hi, size=n).astype(dtype)
    got = _native.argsort_ints(keys)
    want = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(got, want)


def test_argsort_wide_keys(rng):
    keys = rng.integers(0, 2**62, size=50_000).astype(np.int64)
    np.testing.assert_array_equal(
        _native.argsort_ints(keys), np.argsort(keys, kind="stable")
    )


def test_argsort_many_duplicates(rng):
    keys = rng.integers(0, 3, size=100_000).astype(np.int32)
    np.testing.assert_array_equal(
        _native.argsort_ints(keys), np.argsort(keys, kind="stable")
    )


@pytest.mark.parametrize("dtype", [np.int32, np.int64])
def test_group_by_matches_numpy(rng, dtype):
    keys = rng.integers(0, 5000, size=200_000).astype(dtype)
    res = _native.group_by_ints(keys)
    if res is None:
        pytest.skip("native library unavailable")
    uniq, inverse, counts, order = res
    w_uniq, w_inv, w_counts = np.unique(
        keys, return_inverse=True, return_counts=True
    )
    np.testing.assert_array_equal(uniq, w_uniq)
    np.testing.assert_array_equal(inverse, w_inv)
    np.testing.assert_array_equal(counts, w_counts)
    np.testing.assert_array_equal(order, np.argsort(keys, kind="stable"))


def test_group_by_int_key_uses_native(rng):
    from dbscan_tpu.ops import geometry as geo

    keys = rng.integers(0, 997, size=150_000)
    uniq, inverse, counts = geo.group_by_int_key(keys, max_key=1000)
    w_uniq, w_inv, w_counts = np.unique(
        keys, return_inverse=True, return_counts=True
    )
    np.testing.assert_array_equal(uniq, w_uniq)
    np.testing.assert_array_equal(inverse, w_inv)
    np.testing.assert_array_equal(counts, w_counts)


def test_classify_instances_matches_numpy(rng, monkeypatch):
    from dbscan_tpu.config import DBSCANConfig
    from dbscan_tpu.ops import geometry as geo
    from dbscan_tpu.parallel import binning, partitioner
    from dbscan_tpu.parallel.driver import _classify_instances

    pts = np.concatenate(
        [
            rng.normal(c, 0.6, size=(3000, 2))
            for c in rng.uniform(-8, 8, size=(6, 2))
        ]
    )
    cfg = DBSCANConfig(eps=0.4, min_points=5, max_points_per_partition=2000)
    cell = cfg.minimum_rectangle_size
    cells, counts, cell_inv = geo.cell_histogram_int(pts, cell)
    parts = partitioner.partition_cells(cells, counts, 2000)
    rects_int = np.stack([r for r, _ in parts])
    margins = binning.build_margins(rects_int, cell, cfg.eps)
    part_ids, point_idx = binning.duplicate_points(pts, margins.outer)

    got = _classify_instances(
        pts, cells, cell_inv, rects_int, margins, part_ids, point_idx
    )
    # numpy reference: force the fallback path
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_lib_failed", True)
    want = _classify_instances(
        pts, cells, cell_inv, rects_int, margins, part_ids, point_idx
    )
    monkeypatch.setattr(_native, "_lib_failed", False)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])
    assert got[1].any() and got[0].any()


def test_bucketize_banded_native_matches_numpy(rng, monkeypatch):
    from dbscan_tpu.ops import geometry as geo
    from dbscan_tpu.parallel import binning, partitioner

    pts = np.concatenate(
        [
            rng.normal(c, 0.5, size=(4000, 2))
            for c in rng.uniform(-6, 6, size=(4, 2))
        ]
    )
    eps = 0.35
    cell = 2 * eps
    cells, counts, cell_inv = geo.cell_histogram_int(pts, cell)
    parts = partitioner.partition_cells(cells, counts, 6000)
    rects_int = np.stack([r for r, _ in parts])
    margins = binning.build_margins(rects_int, cell, eps)
    part_ids, point_idx = binning.duplicate_points(pts, margins.outer)

    def run():
        return binning.bucketize_banded(
            pts, part_ids, point_idx, n_parts=len(parts), eps=eps,
            outer=margins.outer, dtype=np.float32, force=True,
        )

    g_nat, mb_nat, meta_nat = run()
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_lib_failed", True)
    g_np, mb_np, meta_np = run()
    monkeypatch.setattr(_native, "_lib_failed", False)

    assert mb_nat == mb_np and meta_nat.n_cells == meta_np.n_cells
    np.testing.assert_array_equal(meta_nat.wintab, meta_np.wintab)
    assert len(g_nat) == len(g_np)
    for a, b in zip(g_nat, g_np):
        np.testing.assert_array_equal(a.points, b.points)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.point_idx, b.point_idx)
        np.testing.assert_array_equal(a.part_ids, b.part_ids)
        assert (a.banded is None) == (b.banded is None)
        if a.banded is not None:
            for f in a.banded._fields:
                np.testing.assert_array_equal(
                    getattr(a.banded, f), getattr(b.banded, f), err_msg=f
                )


def test_band_dedup_matches_numpy(rng):
    """Fused band dedup vs the numpy packed-key argsort path."""
    for _ in range(20):
        m = int(rng.integers(1, 5000))
        p_true = int(rng.integers(1, 40))
        inst_pt = rng.integers(0, max(1, m // 3), m).astype(np.int64)
        inst_flag = rng.integers(1, 4, m).astype(np.int8)
        inst_part = rng.integers(0, p_true, m).astype(np.int64)
        ci = np.flatnonzero(rng.random(m) < 0.5).astype(np.int64)
        nat = _native.band_dedup(ci, inst_pt, inst_flag, inst_part, p_true)
        assert nat is not None
        if ci.size == 0:
            assert nat.size == 0
            continue
        key = (inst_pt[ci] * 4 + inst_flag[ci]) * np.int64(p_true) + inst_part[ci]
        order = np.argsort(key, kind="stable")
        cs = ci[order]
        keep = np.r_[True, inst_pt[cs][1:] != inst_pt[cs][:-1]]
        np.testing.assert_array_equal(nat, cs[keep])


def test_uf_assign_gids_matches_python_unionfind(rng):
    """Native union-find + global-id assignment vs the dict UnionFind on
    randomized rank-keyed edge sets: identical ids (not just identical
    partitions — the 1-based first-appearance numbering contract is part
    of parity, reference DBSCAN.scala:206-222)."""
    from dbscan_tpu.parallel.graph import UnionFind

    for _ in range(20):
        n_nodes = int(rng.integers(1, 400))
        n_edges = int(rng.integers(0, 3 * n_nodes))
        ei = rng.integers(0, n_nodes, size=(n_edges, 2)).astype(np.int64)

        nat = _native.uf_assign_gids(ei[:, 0], ei[:, 1], n_nodes)
        assert nat is not None
        nc_nat, gid_nat = nat

        uf = UnionFind()
        for a, b in ei:
            uf.union(int(a), int(b))
        nc_py, mapping = uf.assign_global_ids(list(range(n_nodes)))
        gid_py = np.array(
            [mapping[i] for i in range(n_nodes)], dtype=np.int64
        )

        assert nc_nat == nc_py
        np.testing.assert_array_equal(gid_nat, gid_py)

    # out-of-range endpoint -> fallback signal, not a wrong answer
    assert (
        _native.uf_assign_gids(
            np.array([7], np.int64), np.array([0], np.int64), 3
        )
        is None
    )


def test_full_train_native_matches_fallback(rng, monkeypatch):
    """End-to-end: the whole distributed pipeline must produce identical
    labels and flags with and without the native library (the strongest
    parity statement — every native call site's fallback branch is the
    same function of the same inputs)."""
    from dbscan_tpu import Engine, train

    pts = np.concatenate(
        [
            rng.normal(c, 0.5, size=(2500, 2))
            for c in rng.uniform(-7, 7, size=(5, 2))
        ]
        + [rng.uniform(-9, 9, size=(800, 2))]
    )
    kw = dict(
        eps=0.4, min_points=8, max_points_per_partition=1800,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    m_nat = train(pts, **kw)
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_lib_failed", True)
    m_np = train(pts, **kw)
    monkeypatch.setattr(_native, "_lib_failed", False)
    np.testing.assert_array_equal(m_nat.clusters, m_np.clusters)
    np.testing.assert_array_equal(m_nat.flags, m_np.flags)
    assert m_nat.n_clusters == m_np.n_clusters >= 1


def test_env_gate(monkeypatch, rng):
    monkeypatch.setenv("DBSCAN_TPU_NATIVE", "0")
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_lib_failed", False)
    assert _native.lib() is None
    keys = rng.integers(0, 100, size=1000).astype(np.int64)
    np.testing.assert_array_equal(
        _native.argsort_ints(keys), np.argsort(keys, kind="stable")
    )
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_lib_failed", False)
