"""Extended-metric tests: haversine and cosine through the full train() path
(haversine via the spherical embedding, cosine via metric spill partitioning),
plus precision handling."""

import numpy as np
import pytest

import jax
from dbscan_tpu import DBSCANConfig, Precision, train
from dbscan_tpu.ops.distance import EARTH_RADIUS_KM, get_metric


def test_cosine_uses_all_dimensions():
    # regression (code-review finding): two groups identical in the first two
    # coords but opposite in the third must NOT merge under cosine
    rng = np.random.default_rng(0)
    base = rng.normal(size=(40, 2))
    up = np.concatenate([base, np.full((40, 1), 1.0)], axis=1)
    down = np.concatenate([base, np.full((40, 1), -50.0)], axis=1)
    data = np.concatenate([up, down])
    model = train(data, eps=0.05, min_points=3, metric="cosine")
    assert model.stats["n_partitions"] == 1
    assert model.n_clusters >= 2
    # the two groups never share a cluster id
    assert not (set(model.clusters[:40]) & set(model.clusters[40:]) - {0})


def test_cosine_embeddings_clusters():
    rng = np.random.default_rng(1)
    d = 64
    c1, c2 = rng.normal(size=(2, d))
    a = c1 + 0.01 * rng.normal(size=(30, d))
    b = c2 + 0.01 * rng.normal(size=(30, d))
    data = np.concatenate([a, b])
    model = train(data, eps=0.01, min_points=3, metric="cosine")
    assert model.n_clusters == 2
    assert len(set(model.clusters[:30])) == 1
    assert len(set(model.clusters[30:])) == 1


def test_haversine_km_scale():
    # three points within ~150m around Manhattan + one in Brooklyn (~8 km)
    data = np.array(
        [
            [-73.9851, 40.7589],
            [-73.9855, 40.7593],
            [-73.9860, 40.7585],
            [-73.9442, 40.6782],
        ]
    )
    model = train(data, eps=0.5, min_points=3, metric="haversine")
    assert model.n_clusters == 1
    assert model.clusters[3] == 0  # Brooklyn point is noise at 0.5 km eps


def test_haversine_matches_known_distance():
    m = get_metric("haversine")
    # JFK (-73.7781, 40.6413) to LAX (-118.4085, 33.9416) ~ 3974-3983 km
    d = np.asarray(m.pairwise(
        np.array([[-73.7781, 40.6413]]), np.array([[-118.4085, 33.9416]])
    ))[0, 0]
    assert 3950 < d < 4010
    assert EARTH_RADIUS_KM > 6000


def test_f64_precision_requires_x64():
    # conftest enables x64, so F64 must work...
    pts = np.array([[0.0, 0.0], [0.1, 0.0], [0.2, 0.0]])
    model = train(
        pts, eps=0.5, min_points=3,
        config=DBSCANConfig(eps=0.5, min_points=3, precision=Precision.F64),
    )
    assert model.n_clusters == 1


def test_bf16_runs():
    rng = np.random.default_rng(2)
    pts = np.concatenate(
        [rng.normal((0, 0), 0.3, (50, 2)), rng.normal((20, 20), 0.3, (50, 2))]
    )
    model = train(
        pts, eps=1.0, min_points=3,
        config=DBSCANConfig(eps=1.0, min_points=3, precision=Precision.BF16),
    )
    assert model.n_clusters == 2


def test_use_pallas_rejects_f64():
    from dbscan_tpu.config import Precision

    with pytest.raises(ValueError, match="f32"):
        train(
            np.zeros((4, 2)), eps=0.5, min_points=2,
            config=DBSCANConfig(
                eps=0.5, min_points=2, use_pallas=True,
                precision=Precision.F64,
            ),
        )


def test_cosine_at_scale_fails_fast():
    """VERDICT r1 guard: a non-spatial metric at a scale whose dense
    [B, B] adjacency cannot fit HBM must raise a clear ValueError
    FAST (before packing or device work), naming the limit and the
    alternatives. Identical nonzero rows are the unsplittable worst
    case: the spill tree must detect the one-halo-ball node from a
    single exact pass, not via the leader-cover fallback."""
    import time

    from dbscan_tpu.parallel.driver import DENSE_WIDTH_LIMIT

    data = np.ones((4_000_000, 2))
    t0 = time.perf_counter()
    with pytest.raises(ValueError, match=str(DENSE_WIDTH_LIMIT)):
        train(data, eps=0.1, min_points=3, metric="cosine")
    # fails in seconds (degenerate bail), not the minutes a 4M-wide
    # pack / fallback walk would cost; margin absorbs cold-init +
    # co-running load
    assert time.perf_counter() - t0 < 15.0


def test_cosine_all_zero_rows_are_noise():
    """All-constant-zero input: every row is zero-norm, so (when eps + q
    cannot bridge zero-to-nonzero pairs) the whole dataset is noise by
    fiat — resolved through the zero-norm routing WITHOUT running the
    spill tree on all-equidistant zero vectors (which cannot split and
    would otherwise walk every fallback before failing)."""
    import time

    from dbscan_tpu.ops.labels import NOISE

    data = np.zeros((4_000_000, 2))
    t0 = time.perf_counter()
    model = train(data, eps=0.1, min_points=3, metric="cosine")
    # same margin rationale as the fails-fast test: cold-init +
    # co-running load must not flake a bound guarding a minutes-class
    # regression
    assert time.perf_counter() - t0 < 15.0
    assert model.n_clusters == 0
    assert (model.flags == NOISE).all()
    assert model.stats["n_zero_norm_noise"] == 4_000_000


def test_dense_width_boundary():
    """Widths under DENSE_WIDTH_LIMIT (incl. the 49152 ladder rung between
    the old ad-hoc limit and the banded threshold) stay allowed — they were
    dispatchable before the guard existed; the limit itself raises."""
    from dbscan_tpu.parallel.driver import (
        DENSE_WIDTH_LIMIT,
        _check_dense_width,
    )

    _check_dense_width(4096, 4096)  # no raise
    _check_dense_width(49152, 40000)  # no raise: ~9 GiB, previously worked
    _check_dense_width(DENSE_WIDTH_LIMIT - 1, 40000)  # no raise
    with pytest.raises(ValueError, match="subsample"):
        _check_dense_width(DENSE_WIDTH_LIMIT, 65536)


def test_cosine_rp_tree_matches_oracle():
    """Multi-leaf spill-tree cosine run reproduces the f64 cosine oracle
    (ARI 1.0) — the decomposition must be invisible in the labels."""
    from dbscan_tpu.utils.ari import adjusted_rand_index
    from dbscan_tpu.utils.reference_engines import naive_fit

    rng = np.random.default_rng(5)
    d = 64
    centers = rng.normal(size=(12, d))
    blobs = [
        c / np.linalg.norm(c) + 0.02 * rng.normal(size=(120, d))
        for c in centers
    ]
    noise = rng.normal(size=(60, d))
    data = np.concatenate(blobs + [noise])
    model = train(
        data, eps=0.02, min_points=8, max_points_per_partition=256,
        metric="cosine",
    )
    assert model.stats["spill_tree"]
    assert model.stats["n_partitions"] > 1
    assert model.partitions == []  # no rectangle representation
    ocl, ofl = naive_fit(data, 0.02, 8, metric="cosine")
    assert adjusted_rand_index(model.clusters, ocl) == 1.0
    np.testing.assert_array_equal(model.flags, ofl)


def test_cosine_rp_tree_equals_single_leaf():
    """Labels agree (ARI 1.0) between a forced-single-leaf run (huge
    maxpp) and a many-leaf run of the same data."""
    from dbscan_tpu.utils.ari import adjusted_rand_index

    rng = np.random.default_rng(6)
    d = 32
    centers = rng.normal(size=(6, d))
    data = np.concatenate(
        [c + 0.02 * rng.normal(size=(200, d)) for c in centers]
    )
    kw = dict(eps=0.03, min_points=6, metric="cosine")
    m1 = train(data, max_points_per_partition=100000, **kw)
    assert m1.stats["n_partitions"] == 1
    m2 = train(data, max_points_per_partition=128, **kw)
    assert m2.stats["n_partitions"] > 4
    assert adjusted_rand_index(m1.clusters, m2.clusters) == 1.0


def test_cosine_degenerate_data_unsplittable_leaf():
    """Identical points cannot be split (every cut spills everything):
    the tree emits one oversized leaf and small N still runs fine."""
    data = np.tile(np.array([[1.0, 2.0, 3.0]]), (500, 1))
    model = train(
        data, eps=0.1, min_points=3, max_points_per_partition=100,
        metric="cosine",
    )
    assert model.stats["n_partitions"] == 1
    assert model.n_clusters == 1
    assert (model.clusters == 1).all()


def test_cosine_zero_rows_spill():
    """Zero vectors in dense cosine input get a dedicated leaf (they are
    sim-0 to everything and would otherwise spill into every cell) and
    come out noise at eps < 1; real clusters are unaffected."""
    rng = np.random.default_rng(9)
    d = 16
    c = rng.normal(size=(6, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    data = np.concatenate(
        [
            np.repeat(c, 100, axis=0)
            + 0.01 * rng.normal(size=(600, d)),
            np.zeros((80, d)),
        ]
    )
    model = train(
        data, eps=0.03, min_points=5, max_points_per_partition=128,
        metric="cosine",
    )
    assert model.stats["n_partitions"] > 4
    assert (model.clusters[600:] == 0).all()
    assert model.n_clusters == 6


def test_cosine_f32_input_no_upcast_equivalence():
    """f32 embedding input keeps its dtype (no [N, D] f64 copy) and
    produces labels identical to the same values passed as f64."""
    rng = np.random.default_rng(11)
    d = 32
    c = rng.normal(size=(5, d))
    data32 = (
        np.repeat(c, 80, axis=0) + 0.02 * rng.normal(size=(400, d))
    ).astype(np.float32)
    kw = dict(
        eps=0.03, min_points=5, max_points_per_partition=128,
        metric="cosine",
    )
    snapshot = data32.copy()
    data64 = data32.astype(np.float64)
    m32 = train(data32, **kw)
    # the pass-through must never mutate the caller's array (the spill
    # path normalizes a copy, not the input)
    np.testing.assert_array_equal(data32, snapshot)
    m64 = train(data64, **kw)
    np.testing.assert_array_equal(m32.clusters, m64.clusters)
    np.testing.assert_array_equal(m32.flags, m64.flags)
    assert m32.stats["n_partitions"] > 1
