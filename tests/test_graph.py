"""DBSCANGraph + UnionFind tests. The four graph tests mirror the reference's
DBSCANGraphSuite.scala:22-64 one-for-one; the union-find tests pin the global
id numbering contract of DBSCAN.scala:206-222."""

from dbscan_tpu.parallel.graph import DBSCANGraph, UnionFind


def test_should_return_connected():
    graph = DBSCANGraph().connect(1, 3)
    assert graph.get_connected(1) == {3}


def test_should_return_doubly_connected():
    graph = DBSCANGraph().connect(1, 3).connect(3, 4)
    assert graph.get_connected(1) == {3, 4}


def test_should_return_none_for_vertex():
    graph = DBSCANGraph().add_vertex(5).connect(1, 3)
    assert graph.get_connected(5) == set()


def test_should_return_none_for_unknown():
    graph = DBSCANGraph().add_vertex(5).connect(1, 3)
    assert graph.get_connected(6) == set()


def test_graph_immutability():
    g0 = DBSCANGraph()
    g1 = g0.connect(1, 2)
    assert g0.get_connected(1) == set()
    assert g1.get_connected(1) == {2}


def test_union_find_transitive():
    uf = UnionFind()
    uf.union((0, 1), (1, 2))
    uf.union((1, 2), (2, 5))
    assert uf.find((0, 1)) == uf.find((2, 5))
    assert uf.find((3, 3)) != uf.find((0, 1))


def test_assign_global_ids_matches_reference_numbering():
    # Reference numbering (DBSCAN.scala:206-222): iterate cluster ids in
    # order; each unseen component gets the next id starting at 1, and the
    # whole component inherits it.
    uf = UnionFind()
    uf.union((0, 1), (1, 1))  # component A
    uf.union((2, 1), (3, 1))  # component B
    ordered = [(0, 1), (1, 1), (2, 1), (3, 1), (4, 7)]
    total, mapping = uf.assign_global_ids(ordered)
    assert total == 3
    assert mapping[(0, 1)] == 1 and mapping[(1, 1)] == 1
    assert mapping[(2, 1)] == 2 and mapping[(3, 1)] == 2
    assert mapping[(4, 7)] == 3


def test_assign_global_ids_order_dependence():
    uf = UnionFind()
    uf.union("a", "b")
    _, mapping = uf.assign_global_ids(["c", "a", "b"])
    assert mapping == {"c": 1, "a": 2, "b": 2}
