"""Fused Pallas unpack+fold+propagate (ops/pallas_banded.py
``compiled_cellcc_fused``, family ``cellcc.fused``): the per-chunk half
of the device cellcc finalize as ONE dispatch — unpack + scatter-fold +
the first propagation sweep — with the tail ``cellcc.cc`` starting one
sweep warm.

The parity contract is the device finalize's, EXACT: byte-identical
labels and flags against both the split unpack path and the host
oracle; interpreter mode is how this CPU suite pins the kernels
bit-for-bit (the module's established discipline). DBSCAN_CELLCC_DEVICE
semantics — fault site, degrade ladder, residency cap — are unchanged.
"""

import numpy as np
import pytest

from dbscan_tpu import Engine, obs, train

pytestmark = pytest.mark.cellcc


def _blobs(rng):
    return np.concatenate(
        [rng.normal(c, 0.6, (1500, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (500, 2))]
    )


def _kw(engine=Engine.ARCHERY, maxpp=700):
    return dict(
        eps=0.3, min_points=8, max_points_per_partition=maxpp,
        engine=engine, neighbor_backend="banded",
    )


def test_fused_mode_resolution(monkeypatch):
    from dbscan_tpu.ops import pallas_banded as pb

    monkeypatch.delenv("DBSCAN_CELLCC_FUSED", raising=False)
    # auto on this CPU suite = off (Pallas-capable backends only)
    assert pb.fused_mode() is False
    assert pb.fused_mode("1") is True
    assert pb.fused_mode("0") is False
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    assert pb.fused_mode() is True


def test_fused_unpack_bit_exact_vs_split(rng):
    """The fused dispatch's unpack/fold outputs are byte-identical to
    compiled_cellcc_unpack's, and its lab0 is exactly the chunk's
    first pull sweep from identity labels."""
    import jax.numpy as jnp

    from dbscan_tpu.ops.banded import compiled_cellcc_unpack
    from dbscan_tpu.ops.pallas_banded import compiled_cellcc_fused
    from dbscan_tpu.parallel.binning import BANDED_WIN

    cpad, m, k = 4096, 2048, 4096
    core = rng.random(m) < 0.4
    orv = rng.integers(0, 1 << 25, k).astype(np.int32)
    combo = np.concatenate([np.packbits(core), orv.view(np.uint8)])
    cell_flat = rng.integers(0, cpad - 1, m).astype(np.int32)
    cell_flat[rng.random(m) < 0.1] = cpad - 1
    fold_flat = rng.integers(0, 10**6, m).astype(np.int32)
    or_gid = rng.integers(0, cpad - 1, k).astype(np.int32)
    or_gid[k // 2:] = cpad - 1
    wintab = rng.integers(-1, cpad - 1, (cpad, BANDED_WIN)).astype(
        np.int32
    )
    args = tuple(
        jnp.asarray(a) for a in (combo, cell_flat, fold_flat, or_gid)
    )
    c0, o0, f0 = compiled_cellcc_unpack(cpad)(*args)
    c1, o1, f1, lab0 = compiled_cellcc_fused(cpad)(
        *args, jnp.asarray(wintab)
    )
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))
    np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))
    inf = 2**31 - 1
    cand = np.where(np.asarray(o0), np.clip(wintab, 0, cpad - 1), inf)
    ref = np.minimum(np.arange(cpad), cand.min(axis=1)).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(lab0), ref)


@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_fused_train_parity_both_engines(engine, rng, monkeypatch):
    """End-to-end: fused vs split vs host oracle, byte-identical, and
    the warm start saves a counted sweep on the iterated path (leg-1
    off isolates the fused contribution)."""
    pts = _blobs(rng)
    kw = _kw(engine)
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "0")
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "0")
    m_host = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "0")
    m_split = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    m_fused = train(pts, **kw)
    for m in (m_split, m_fused):
        np.testing.assert_array_equal(m_host.clusters, m.clusters)
        np.testing.assert_array_equal(m_host.flags, m.flags)
    assert m_split.stats["cellcc_cc_iters"] >= 2
    assert (
        m_fused.stats["cellcc_cc_iters"]
        < m_split.stats["cellcc_cc_iters"]
    ), "the folded first sweep must drop the counted tail sweeps"


def test_fused_family_dispatched_and_zero_recompile(rng, monkeypatch):
    """Compile pin: fused mode dispatches cellcc.fused (and never
    cellcc.unpack), and a second same-shaped train mints ZERO new
    kernels — the ladder/ratchet discipline extends to the fused
    family."""
    import jax

    from dbscan_tpu.ops.pallas_banded import compiled_cellcc_fused

    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    # an earlier test in this process may already have compiled the
    # fused rungs: start from a cold trace cache so the compile
    # accounting below is this test's own
    compiled_cellcc_fused.cache_clear()
    jax.clear_caches()
    obs.enable()
    try:
        snap0 = obs.counters()
        train(pts, **_kw())  # warm: compiles the fused rungs
        delta0 = obs.counters_delta(snap0)
        assert delta0.get("compiles.cellcc.fused", 0) >= 1
        assert delta0.get("compiles.cellcc.unpack", 0) == 0
        snap = obs.counters()
        m = train(pts, **_kw())
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.total", 0) == 0, delta
        assert delta.get("cellcc.cc_iters", 0) == m.stats[
            "cellcc_cc_iters"
        ]
    finally:
        obs.disable()


def test_fused_multi_chunk_parity(rng, monkeypatch):
    """Several compact chunks: per-chunk lab0 partials min-merge into
    the full first sweep, so labels AND the counted sweeps are
    chunk-layout-independent (the cc_iters contract extended to the
    warm start)."""
    from dbscan_tpu.parallel import driver

    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    m_one = train(pts, **_kw())
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 1 << 12)
    m_many = train(pts, **_kw())
    assert m_one.stats["cellcc_cc_iters"] >= 1
    assert (
        m_many.stats["cellcc_cc_iters"] == m_one.stats["cellcc_cc_iters"]
    )
    np.testing.assert_array_equal(m_one.clusters, m_many.clusters)
    np.testing.assert_array_equal(m_one.flags, m_many.flags)


def test_fused_fault_degrade_semantics_unchanged(rng, monkeypatch):
    """DBSCAN_CELLCC_DEVICE semantics are untouched by the fused path:
    a persistent cellcc_cc fault still degrades the WHOLE finalize to
    the host oracle with labels intact (the staged fused partials are
    dropped through the same _drop_staged path)."""
    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    m_ref = train(pts, **_kw())
    assert m_ref.stats["cellcc_cc_iters"] >= 1
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "cellcc_cc#0:PERSISTENT")
    m_p = train(pts, **_kw())
    assert m_p.stats["faults"]["fallbacks"] >= 1
    assert m_p.stats["cellcc_cc_iters"] == 0
    np.testing.assert_array_equal(m_p.clusters, m_ref.clusters)
    np.testing.assert_array_equal(m_p.flags, m_ref.flags)


def test_fused_residency_cap_unchanged(rng, monkeypatch):
    """The staged-residency degrade ladder applies to fused records the
    same way: a budget below one chunk degrades mid-run to the host
    oracle, labels identical."""
    from dbscan_tpu.parallel import driver

    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 1 << 12)
    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    m_ref = train(pts, **_kw())
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE_SLOTS", "1024")
    m_cap = train(pts, **_kw())
    assert m_cap.stats["cellcc_cc_iters"] == 0  # host oracle finished
    np.testing.assert_array_equal(m_cap.clusters, m_ref.clusters)
    np.testing.assert_array_equal(m_cap.flags, m_ref.flags)


def test_fused_registration_pins():
    """Cross-module contracts: the fused family is declared end to end
    — schema (counters/spans/devtime ride the generator), FAMILY_MODELS
    (the shapecheck runtime refuses undeclared families), and the
    cellcc.cc model's labs slot for the warm-start tuple."""
    from dbscan_tpu.lint.shapes import FAMILY_MODELS
    from dbscan_tpu.obs import schema

    assert "cellcc.fused" in schema.COMPILE_FAMILIES
    assert schema.is_declared("counter", "compiles.cellcc.fused")
    assert schema.is_declared("span", "devtime.cellcc.fused")
    model = FAMILY_MODELS["cellcc.fused"]
    assert [a.name for a in model.args] == [
        "combo", "cell_flat", "fold_flat", "or_gid", "wintab",
    ]
    cc = FAMILY_MODELS["cellcc.cc"]
    assert cc.args[-1].name == "labs" and cc.args[-1].tuple_of


def test_fused_under_shapecheck(rng, monkeypatch):
    """The runtime graftshape cross-check validates the fused family's
    observed shapes against the declared model (violation-free run,
    both cellcc.fused and cellcc.cc sites covered)."""
    from dbscan_tpu.lint import shapecheck

    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_CELLCC_FUSED", "1")
    was_on = shapecheck.enabled()
    shapecheck.enable()
    try:
        m = train(pts, **_kw())
        assert m.stats["cellcc_cc_iters"] >= 1
        rep = shapecheck.report()
        assert rep["violations"] == []
        assert "cellcc.fused" in rep["sites"]
        assert "cellcc.cc" in rep["sites"]
    finally:
        if not was_on:
            shapecheck.disable()
