"""Equivalence tests for the optimized host fast paths.

Each optimization here replaced a slower exact formulation and must be
BIT-IDENTICAL to it: grid-pruned halo duplication vs the brute-force
containment product, and the radix group-by-key vs np.unique.
"""

import numpy as np
import pytest

from dbscan_tpu.ops import geometry as geo
from dbscan_tpu.parallel import binning, partitioner


def _setup(pts, eps, maxpp):
    cell = 2 * eps
    cells, counts, inv = geo.cell_histogram_int(pts, cell)
    parts = partitioner.partition_cells(cells, counts, maxpp)
    rects_int = np.stack([r for r, _ in parts])
    margins = binning.build_margins(rects_int, cell, eps)
    return cells, inv, rects_int, margins


CASES = {
    "blobs": (0.3, 250),
    "tight-eps": (0.05, 100),
    "coarse": (1.0, 64),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_duplicate_points_grid_matches_bruteforce(name, rng):
    eps, maxpp = CASES[name]
    pts = np.concatenate(
        [rng.normal(rng.uniform(-15, 15, 2), rng.uniform(0.2, 1.5), (1500, 2))
         for _ in range(4)]
    )
    # exercise the snap quirk: exact negative multiples of the cell size
    pts[:20] = np.round(pts[:20] / (2 * eps)) * (2 * eps)
    cells, inv, rects_int, margins = _setup(pts, eps, maxpp)
    ref_p, ref_i = binning.duplicate_points(pts, margins.outer)
    got_p, got_i = binning.duplicate_points_grid(
        pts, cells, inv, rects_int, margins.outer
    )
    np.testing.assert_array_equal(ref_p, got_p)
    np.testing.assert_array_equal(ref_i, got_i)


def test_duplicate_points_grid_single_partition(rng):
    pts = rng.normal(0, 0.5, (500, 2))
    cells, inv, rects_int, margins = _setup(pts, 0.3, 10**9)
    got_p, got_i = binning.duplicate_points_grid(
        pts, cells, inv, rects_int, margins.outer
    )
    np.testing.assert_array_equal(got_p, np.zeros(len(pts), np.int64))
    np.testing.assert_array_equal(got_i, np.arange(len(pts)))


@pytest.mark.parametrize("name", sorted(CASES))
def test_classify_instances_matches_exact(name, rng):
    """The integer-cell interior shortcut must reproduce the exact
    band/inner formulation bit-for-bit (off-by-ones here misclassify only
    boundary-ring points, which end-to-end tests can miss)."""
    from dbscan_tpu.parallel.driver import _band_membership, _classify_instances

    eps, maxpp = CASES[name]
    pts = np.concatenate(
        [rng.normal(rng.uniform(-15, 15, 2), rng.uniform(0.2, 1.5), (1500, 2))
         for _ in range(4)]
    )
    pts[:20] = np.round(pts[:20] / (2 * eps)) * (2 * eps)
    cells, inv, rects_int, margins = _setup(pts, eps, maxpp)
    part_ids, point_idx = binning.duplicate_points_grid(
        pts, cells, inv, rects_int, margins.outer
    )
    band_fast, inner_fast = _classify_instances(
        pts, cells, inv, rects_int, margins, part_ids, point_idx
    )
    band_ref = _band_membership(pts, margins, part_ids, point_idx)
    inner_ref = geo.almost_contains(
        margins.inner[part_ids], pts[point_idx][:, :2]
    )
    np.testing.assert_array_equal(band_fast, band_ref)
    np.testing.assert_array_equal(inner_fast, inner_ref)


def test_group_by_int_key_matches_unique(rng):
    for max_key, dtype in [(10**4, np.int32), (10**4, np.int64), (2**40, np.int64)]:
        key = rng.integers(0, max_key, size=50_000).astype(dtype)
        uniq, inverse, counts = geo.group_by_int_key(key, max_key=max_key)
        ref_u, ref_inv, ref_c = np.unique(
            key, return_inverse=True, return_counts=True
        )
        np.testing.assert_array_equal(uniq, ref_u)
        np.testing.assert_array_equal(inverse, ref_inv)
        np.testing.assert_array_equal(counts, ref_c)
    # empty input
    u, i, c = geo.group_by_int_key(np.empty(0, np.int64))
    assert u.size == i.size == c.size == 0
