"""Level-synchronous device spill tree (spill_device.build_level_tree).

``DBSCAN_SPILL_DEVICE=1`` forces the jax spill passes on the CPU
backend (the device-path convention of tests/test_spill.py); the level
build engages by default (``DBSCAN_SPILL_DEVICE_TREE``) and must
produce IDENTICAL final labels to the host recursion — not just ARI
1.0. That is a real contract, not luck: cluster MEMBERSHIP is
decomposition-independent (every kernel-accepted pair shares a leaf, a
point's home leaf sees its whole neighborhood, and the merge unions
clusters across any doubly-labeled point), and spill runs number global
ids canonically by minimum member row (driver.finalize_merge
``canonical=True``), so two different trees — host and device pick
DIFFERENT pivots by design — yield the same label vector
(PARITY.md "Spill tree").
"""

import numpy as np
import pytest

pytestmark = pytest.mark.spill_tree


def _unit_blobs(rng, k, per, d, jitter=0.004):
    centers = rng.normal(size=(k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.repeat(centers, per, axis=0).astype(np.float32)
    pts += jitter * rng.normal(size=pts.shape).astype(np.float32)
    return pts


def _train_kw(maxpp=256):
    return dict(
        eps=0.02, min_points=5, max_points_per_partition=maxpp,
        metric="cosine",
    )


@pytest.fixture
def fresh_resident_cache():
    from dbscan_tpu.parallel import driver

    driver._RESIDENT_CACHE.clear()
    yield
    driver._RESIDENT_CACHE.clear()


def test_level_vs_host_labels_identical(rng, monkeypatch,
                                        fresh_resident_cache):
    """The tentpole parity contract: the level-synchronous device build
    and the pure-host recursion produce byte-identical labels AND flags
    through the full train pipeline, and the device run really took the
    level path (spill_levels >= 1)."""
    from dbscan_tpu import train

    pts = _unit_blobs(rng, 15, 140, 24)
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "0")
    m_host = train(pts, **_train_kw())
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    m_dev = train(pts, **_train_kw())
    assert m_dev.stats["spill_levels"] >= 1
    assert m_host.stats["spill_levels"] == 0
    assert m_dev.n_clusters == m_host.n_clusters == 15
    assert np.array_equal(m_dev.clusters, m_host.clusters)
    assert np.array_equal(m_dev.flags, m_host.flags)


def test_level_vs_node_recursive_device(rng, monkeypatch,
                                        fresh_resident_cache):
    """DBSCAN_SPILL_DEVICE_TREE=0 is the parity oracle: the
    node-recursive device path (same bf16 storage, different tree)
    matches the level build label-for-label."""
    from dbscan_tpu import train

    pts = _unit_blobs(rng, 12, 130, 20)
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE_TREE", "0")
    m_node = train(pts, **_train_kw())
    assert m_node.stats["spill_levels"] == 0

    from dbscan_tpu.parallel import driver

    driver._RESIDENT_CACHE.clear()
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE_TREE", "1")
    m_level = train(pts, **_train_kw())
    assert m_level.stats["spill_levels"] >= 1
    assert np.array_equal(m_level.clusters, m_node.clusters)
    assert np.array_equal(m_level.flags, m_node.flags)


def test_level_partition_contract_and_layout(rng, monkeypatch):
    """Direct spill_partition contract under the level build: exact
    home-leaf invariant, every kernel-accepted pair shares a leaf, and
    info_out carries the partition-major leaf layout (counts) without
    the caller re-deriving it."""
    from dbscan_tpu.parallel import spill

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    d = 24
    unit = _unit_blobs(rng, 12, 120, d)
    unit /= np.linalg.norm(unit, axis=1, keepdims=True)
    halo = spill.chord_halo(0.02, 1e-5, dim=d)
    info = {}
    pid, pidx, n_parts, home = spill.spill_partition(
        unit, 256, halo, info_out=info
    )
    assert info["levels"] >= 1
    assert info["level_dispatches"] <= info["levels"] + 1
    counts = info["counts"]
    assert len(counts) == n_parts and counts.sum() == len(pid)
    # partition-major: offsets = cumsum(counts) slice exact leaves
    offsets = np.r_[0, np.cumsum(counts)]
    for p in range(n_parts):
        assert (pid[offsets[p] : offsets[p + 1]] == p).all()
    # home invariant: exactly one home leaf, containing the point
    assert (home >= 0).all()
    inst = set(zip(pid.tolist(), pidx.tolist()))
    for p in range(0, len(unit), 89):
        assert (home[p], p) in inst
    # coverage: sampled accepted pairs share a leaf
    sims = unit @ unit.T
    acc = np.argwhere(np.triu(2.0 - 2.0 * sims <= halo * halo, k=1))
    from collections import defaultdict

    parts_of = defaultdict(set)
    for pp, pt in zip(pid.tolist(), pidx.tolist()):
        parts_of[pt].add(pp)
    step = max(1, len(acc) // 4000)
    for a, b in acc[::step]:
        assert parts_of[int(a)] & parts_of[int(b)]


def test_fault_on_level_dispatch(rng, monkeypatch, fresh_resident_cache):
    """The retry/degrade ladder covers the new spill_level site: a
    transient fault heals through supervised retries with identical
    labels; a persistent fault degrades the WHOLE build to the host
    recursion — also with identical labels (the point of the parity
    contract)."""
    from dbscan_tpu import train

    pts = _unit_blobs(rng, 12, 140, 24)
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    m_ref = train(pts, **_train_kw())
    assert m_ref.stats["spill_levels"] >= 1

    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "spill_level#0:TRANSIENT")
    m_t = train(pts, **_train_kw())
    assert m_t.stats["faults"]["injected"] >= 1
    assert m_t.stats["faults"]["retries"] >= 1
    assert np.array_equal(m_t.clusters, m_ref.clusters)

    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "spill_level#0:PERSISTENT")
    m_p = train(pts, **_train_kw())
    assert m_p.stats["spill_levels"] == 0  # degraded to host recursion
    assert np.array_equal(m_p.clusters, m_ref.clusters)

    # a LATER level's dispatch failing leaves level-1 leaf pulls already
    # submitted to the shared pull worker: the degrade path must drain
    # them (no orphaned jobs/banked errors) and still match labels
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "spill_level#1:PERSISTENT")
    m_p2 = train(pts, **_train_kw())
    assert m_p2.stats["spill_levels"] == 0
    assert np.array_equal(m_p2.clusters, m_ref.clusters)


def test_degenerate_inputs(rng, monkeypatch):
    """All-duplicate points (single halo ball), n below the leaf size,
    and a single-open-node tree all terminate with the host-identical
    layout invariants."""
    from dbscan_tpu.parallel import spill

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    d = 16
    halo = spill.chord_halo(0.02, 1e-5, dim=d)

    one = rng.normal(size=d).astype(np.float32)
    one /= np.linalg.norm(one)
    dup = np.tile(one, (700, 1))
    info = {}
    pid, pidx, n_parts, home = spill.spill_partition(
        dup, 256, halo, info_out=info
    )
    # unsplittable: one oversized leaf, zero duplication
    assert n_parts == 1 and len(pid) == 700
    assert (home == 0).all()

    # n <= maxpp: no tree at all
    small = _unit_blobs(rng, 4, 20, d)
    small /= np.linalg.norm(small, axis=1, keepdims=True)
    pid2, _pidx2, np2, _h2 = spill.spill_partition(small, 256, halo)
    assert np2 == 1

    # single open node, one level deep: n just over the leaf size
    unit = _unit_blobs(rng, 6, 60, d)
    unit /= np.linalg.norm(unit, axis=1, keepdims=True)
    info3 = {}
    pid3, pidx3, np3, home3 = spill.spill_partition(
        unit, 300, halo, info_out=info3
    )
    assert np3 >= 2 and (home3 >= 0).all()
    assert info3["levels"] >= 1


def test_recompile_stability_and_dispatch_count(rng, monkeypatch,
                                                fresh_resident_cache):
    """The level loop must not retrace per level: a second identical
    run mints ZERO new spill.level compiles, and the dispatch counter
    stays bounded by levels + 1 (one fused step per level + the closing
    compact) — the one-dispatch-per-level acceptance pin."""
    from dbscan_tpu import obs, train

    pts = _unit_blobs(rng, 12, 140, 24)
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    obs.enable()
    try:
        train(pts, **_train_kw())  # warm: compiles the level rungs
        from dbscan_tpu.parallel import driver

        driver._RESIDENT_CACHE.clear()
        snap = obs.counters()
        m = train(pts, **_train_kw())
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.spill.level", 0) == 0, delta
        assert delta.get("compiles.spill.level_final", 0) == 0, delta
        levels = int(delta.get("spill.levels", 0))
        dispatches = int(delta.get("spill.level_dispatches", 0))
        assert levels == m.stats["spill_levels"] >= 1
        assert dispatches <= levels + 1
    finally:
        obs.disable()


def test_sparse_labels_decomposition_independent(monkeypatch):
    """The sparse engine's leg of the parity contract: two DIFFERENT
    spill decompositions (maxpp values → different trees and layouts)
    yield byte-identical labels, because ids are canonical and
    membership is decomposition-independent. This is the property the
    device-vs-host toggle relies on for every engine that spills."""
    import scipy.sparse as sp

    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan

    rng = np.random.default_rng(7)
    k, per, vocab, nnz = 40, 60, 5000, 24
    feat = rng.integers(0, vocab, size=(k, nnz))
    val = rng.random((k, nnz)) + 0.1
    blob_of = np.repeat(np.arange(k), per)
    rows = np.repeat(np.arange(k * per), nnz)
    cols = feat[blob_of].ravel()
    vals = (val[blob_of] * rng.uniform(0.9, 1.1, (k * per, nnz))).ravel()
    x = sp.coo_matrix((vals, (rows, cols)), shape=(k * per, vocab)).tocsr()

    kw = dict(eps=0.05, min_points=5)
    c1, f1 = sparse_cosine_dbscan(x, max_points_per_partition=256, **kw)
    c2, f2 = sparse_cosine_dbscan(x, max_points_per_partition=700, **kw)
    assert np.array_equal(c1, c2)
    assert np.array_equal(f1, f2)


def test_level_model_pins():
    """Cross-module constants the lint model mirrors without imports,
    plus the fault-site registration for the new dispatch."""
    from dbscan_tpu import faults
    from dbscan_tpu.lint.shapes import FAMILY_MODELS, LEVEL_PIVOT_CAP
    from dbscan_tpu.parallel import spill

    assert LEVEL_PIVOT_CAP == spill._MAX_PIVOTS
    assert "spill.level" in FAMILY_MODELS
    assert "spill.level_final" in FAMILY_MODELS
    # the split policy is ONE implementation: the device build's pivot
    # request delegates to the host recursion's escalation formula, and
    # both read the same concentration-signature constants
    from dbscan_tpu.parallel import spill_device

    for count, attempt, maxpp in (
        (10_000, 0, 256), (10_000, 2, 256), (5_000_000, 1, 8192),
    ):
        assert spill_device._level_m_req(
            count, attempt, maxpp
        ) == spill.pivot_escalation(count, attempt, maxpp)
    assert spill.SCREEN_DUP_MARGIN == 1.15
    assert spill.CONCENTRATION_CELL_FRAC == 0.5
    assert faults.SITE_SPILL_LEVEL in faults._SITES
    (clause,) = faults.parse_fault_spec("spill_level#2:TRANSIENT*2")
    assert clause.site == "spill_level"
    assert clause.ordinal == 2 and clause.count == 2
