"""Sparse cosine DBSCAN: gram correctness vs dense math, clustering vs
sklearn (precomputed-cosine DBSCAN), and the feature-block scan on ragged
vocabularies."""

import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

from dbscan_tpu.ops.sparse import sparse_cosine_dbscan, sparse_cosine_gram
from dbscan_tpu.utils.ari import adjusted_rand_index


def _random_tfidf(rng, n, d, density=0.05):
    m = scipy_sparse.random(
        n, d, density=density, format="csr", random_state=np.random.RandomState(0),
        data_rvs=lambda k: rng.uniform(0.1, 2.0, k),
    )
    return m


def test_gram_matches_dense(rng):
    x = _random_tfidf(rng, 60, 500)
    gram = np.asarray(sparse_cosine_gram(x, feature_block=128))
    xd = x.toarray()
    norms = np.linalg.norm(xd, axis=1, keepdims=True)
    xn = np.divide(xd, norms, out=np.zeros_like(xd), where=norms > 0)
    np.testing.assert_allclose(gram, xn @ xn.T, atol=1e-5)


def test_gram_vocab_not_block_multiple(rng):
    x = _random_tfidf(rng, 40, 333)  # 333 % 128 != 0
    gram = np.asarray(sparse_cosine_gram(x, feature_block=128))
    xd = x.toarray()
    norms = np.linalg.norm(xd, axis=1, keepdims=True)
    xn = np.divide(xd, norms, out=np.zeros_like(xd), where=norms > 0)
    np.testing.assert_allclose(gram, xn @ xn.T, atol=1e-5)


def _topic_corpus(rng, docs_per_topic=40, n_topics=3, vocab=600, words=80):
    """Synthetic topic-separated sparse docs: each topic draws 80 word
    occurrences from its own 40-word keyword slice, giving within-topic
    cosine similarity ~0.67 (distance ~0.33) and zero cross-topic overlap —
    so eps=0.5 clusters = topics exactly."""
    labels = []
    n = docs_per_topic * n_topics
    mat = scipy_sparse.lil_matrix((n, vocab))
    slice_w = vocab // n_topics
    for t in range(n_topics):
        for i in range(docs_per_topic):
            r = t * docs_per_topic + i
            cols = rng.integers(t * slice_w, t * slice_w + 40, size=words)
            for c in cols:
                mat[r, int(c)] += 1.0
            labels.append(t + 1)
    return mat.tocsr(), np.array(labels)


def test_clusters_topics_vs_sklearn(rng):
    x, topics = _topic_corpus(rng)
    clusters, flags = sparse_cosine_dbscan(x, eps=0.5, min_points=5)
    # topic structure recovered
    assert adjusted_rand_index(clusters, topics) == 1.0

    sklearn_cluster = pytest.importorskip("sklearn.cluster")
    # sklearn on the exact precomputed cosine distances
    xd = x.toarray()
    xn = xd / np.linalg.norm(xd, axis=1, keepdims=True)
    dist = np.clip(1.0 - xn @ xn.T, 0.0, None)
    sk = sklearn_cluster.DBSCAN(eps=0.5, min_samples=5, metric="precomputed").fit(dist)
    assert adjusted_rand_index(clusters, sk.labels_) == 1.0


def test_empty_rows_are_noise(rng):
    x = _random_tfidf(rng, 30, 200, density=0.1).tolil()
    x[5, :] = 0
    x[17, :] = 0
    clusters, flags = sparse_cosine_dbscan(x.tocsr(), eps=0.3, min_points=3)
    assert clusters[5] == 0 and clusters[17] == 0


def test_sparse_spill_matches_single_gram(rng):
    """Spill-partitioned sparse run reproduces the single-gram labels
    (ARI 1.0) past the per-gram cap — the decomposition must be
    invisible."""
    import scipy.sparse as sp

    from dbscan_tpu import sparse_cosine_dbscan
    from dbscan_tpu.utils.ari import adjusted_rand_index

    # k topic blocks: every doc of topic t shares a strong anchor column
    # plus random terms from a t-specific vocabulary band -> same-topic
    # cosine distance ~0.1, cross-topic ~1.0. Anchors keep topics tight
    # so the spill bands (cell radius + chord(eps)) clear the
    # near-orthogonal topic separation and the tree actually splits.
    k, per, vocab, nnz = 10, 120, 5000, 30
    rows_l = []
    for t in range(k):
        base = t * (vocab // k)
        for _ in range(per):
            cols = base + 1 + rng.integers(0, vocab // k - 1, nnz)
            row = np.zeros(vocab)
            row[cols] = 1.0 + rng.random(nnz)
            row[base] = 20.0  # topic anchor
            rows_l.append(row)
    x = sp.csr_matrix(np.stack(rows_l))
    topic = np.repeat(np.arange(k), per)

    c1, f1 = sparse_cosine_dbscan(x, eps=0.3, min_points=5)
    stats: dict = {}
    c2, f2 = sparse_cosine_dbscan(
        x, eps=0.3, min_points=5, max_points_per_partition=256,
        stats_out=stats,
    )
    # the decomposition must actually engage — this test is about the
    # multi-leaf merge path, not a vacuous single-leaf fallback
    assert stats["n_partitions"] > 1, stats
    assert adjusted_rand_index(c1, topic) == 1.0
    assert adjusted_rand_index(c2, c1) == 1.0
    np.testing.assert_array_equal(f1, f2)


def test_sparse_spill_zero_rows(rng):
    """Zero rows (empty documents) stay noise through the spill path."""
    import scipy.sparse as sp

    from dbscan_tpu import sparse_cosine_dbscan

    dense = np.zeros((300, 200))
    dense[:250, :10] = 1.0 + rng.random((250, 10))  # one tight cluster
    x = sp.csr_matrix(dense)  # rows 250..299 are empty
    c, f = sparse_cosine_dbscan(
        x, eps=0.3, min_points=5, max_points_per_partition=64
    )
    assert (c[250:] == 0).all()
    assert len(set(c[:250]) - {0}) == 1


def test_spill_sparse_mesh_matches_sequential(rng):
    """The mesh-sharded leaf-batch dispatch (one leaf per device per
    batch, shard_map over 'parts') must reproduce the sequential stash
    loop's labels bit-for-bit — same leaves, same kernels, different
    fan-out."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel.mesh import make_mesh

    k, per, d = 10, 50, 40
    rows, cols, vals = [], [], []
    for c in range(k):
        feats = np.arange(c * 4, c * 4 + 4)
        for i in range(per):
            pick = rng.choice(feats, size=3, replace=False)
            ri = c * per + i
            rows += [ri] * 3
            cols += list(pick)
            vals += [1.0] * 3
    x = sp.csr_matrix(
        (vals, (rows, cols)), shape=(k * per, d), dtype=np.float32
    )
    from dbscan_tpu.parallel.mesh import mesh_size

    mesh = make_mesh()
    # guard against a vacuous pass: on a 1-device backend both runs
    # would take the sequential branch and compare nothing
    assert mesh_size(mesh) > 1, "mesh dispatch not exercised"
    seq_stats, mesh_stats = {}, {}
    c_seq, f_seq = sparse_cosine_dbscan(
        x, eps=0.4, min_points=5, max_points_per_partition=96,
        stats_out=seq_stats,
    )
    c_mesh, f_mesh = sparse_cosine_dbscan(
        x, eps=0.4, min_points=5, max_points_per_partition=96,
        stats_out=mesh_stats, mesh=mesh,
    )
    assert seq_stats["n_partitions"] > 1  # actually exercised the spill
    np.testing.assert_array_equal(c_seq, c_mesh)
    np.testing.assert_array_equal(f_seq, f_mesh)
