"""Geometry semantics tests, mirroring the reference's DBSCANRectangle
behaviors (inclusive contains vs strict almost_contains, shrink, grid
snapping quirks of DBSCAN.scala:345-356)."""

import numpy as np

from dbscan_tpu.ops import geometry as geo


def test_contains_point_inclusive_edges():
    r = geo.rect(0.0, 0.0, 1.0, 1.0)
    pts = np.array([[0.0, 0.0], [1.0, 1.0], [0.5, 0.5], [1.0001, 0.5], [-0.0001, 0.5]])
    got = geo.contains_point(r, pts)
    assert got.tolist() == [True, True, True, False, False]


def test_almost_contains_strict_interior():
    r = geo.rect(0.0, 0.0, 1.0, 1.0)
    pts = np.array([[0.0, 0.5], [1.0, 0.5], [0.5, 0.0], [0.5, 1.0], [0.5, 0.5]])
    got = geo.almost_contains(r, pts)
    assert got.tolist() == [False, False, False, False, True]


def test_contains_rect_inclusive():
    outer = geo.rect(0.0, 0.0, 2.0, 2.0)
    assert geo.contains_rect(outer, geo.rect(0.0, 0.0, 2.0, 2.0))
    assert geo.contains_rect(outer, geo.rect(0.5, 0.5, 1.5, 1.5))
    assert not geo.contains_rect(outer, geo.rect(-0.1, 0.0, 2.0, 2.0))
    assert not geo.contains_rect(outer, geo.rect(0.0, 0.0, 2.1, 2.0))


def test_shrink_grows_with_negative_amount():
    r = geo.rect(0.0, 0.0, 2.0, 2.0)
    inner = geo.shrink(r, 0.3)
    outer = geo.shrink(r, -0.3)
    np.testing.assert_allclose(inner, [0.3, 0.3, 1.7, 1.7])
    np.testing.assert_allclose(outer, [-0.3, -0.3, 2.3, 2.3])


def test_shrink_batched():
    rs = np.stack([geo.rect(0.0, 0.0, 2.0, 2.0), geo.rect(1.0, 1.0, 3.0, 3.0)])
    out = geo.shrink(rs, 0.5)
    np.testing.assert_allclose(out, [[0.5, 0.5, 1.5, 1.5], [1.5, 1.5, 2.5, 2.5]])


def test_snap_corner_positive():
    # cell = 0.6: 0.7 -> 0.6; 0.0 -> 0.0; 0.59 -> 0.0
    got = geo.snap_corner(np.array([0.7, 0.0, 0.59]), 0.6)
    np.testing.assert_allclose(got, [0.6, 0.0, 0.0])


def test_snap_corner_negative_shift_quirk():
    # Reference shiftIfNegative (DBSCAN.scala:352-356): negative coords are
    # shifted down one full cell before truncation. -0.1 -> trunc((-0.1-0.6)/0.6)
    # = trunc(-1.1667) = -1 -> -0.6. Exact negative multiple -0.6 ->
    # trunc((-0.6-0.6)/0.6) = -2 -> -1.2 (the quirk: it lands a cell below).
    got = geo.snap_corner(np.array([-0.1, -0.6]), 0.6)
    np.testing.assert_allclose(got, [-0.6, -1.2])


def test_points_to_cells_and_histogram():
    pts = np.array([[0.1, 0.1], [0.2, 0.3], [0.7, 0.1], [-0.1, 0.0]])
    cells, counts, inv = geo.cell_histogram(pts, 0.5)
    # cells: [0,0], [0.5,0] and [-0.5,0] corners
    assert cells.shape == (3, 4)
    assert counts.sum() == 4
    # the two points in the [0,0] cell map to the same row
    assert inv[0] == inv[1]
    # each cell is cell_size wide
    np.testing.assert_allclose(cells[:, 2] - cells[:, 0], 0.5)
    np.testing.assert_allclose(cells[:, 3] - cells[:, 1], 0.5)


def test_bounding_rect_of_cells():
    cells = np.array(
        [[0.0, 0.0, 1.0, 1.0], [2.0, -1.0, 3.0, 0.0], [-1.0, 2.0, 0.0, 3.0]]
    )
    np.testing.assert_allclose(geo.bounding_rect_of_cells(cells), [-1.0, -1.0, 3.0, 3.0])


def test_pairwise_sq_dists_uses_first_two_dims_only():
    # DBSCANPoint uses only dims 0,1 (DBSCANPoint.scala:23-24)
    a = np.array([[0.0, 0.0, 99.0], [1.0, 1.0, -5.0]])
    d2 = geo.pairwise_sq_dists(a, a)
    np.testing.assert_allclose(d2, [[0.0, 2.0], [2.0, 0.0]])
