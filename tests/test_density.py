"""Variable-density engine suite (``dbscan_tpu/density/``).

Pins the PARITY.md "Variable-density contract": device HDBSCAN*
labels match the pure-NumPy host oracle EXACTLY (two independent
condense constructions — the oracle's top-down dendrogram walk vs the
engine's single-sweep bottom-up build — agreeing label-for-label on
the same total-ordered MST), on 2-D euclidean and cosine embed inputs,
under both propagation modes, and under injected ``density_core`` /
``density_boruvka`` faults (transient heal; persistent chunk-fallback
and whole-run oracle degrade with labels intact). Also: the
zero-retrace second-run compile pin, the ceil(log2 n) + 2 Borůvka
round bound, MST total-weight property-fuzz vs SciPy, OPTICS
order/reachability parity, the eps='auto' knee probe, and the
``DBSCAN_SHAPECHECK=1`` subprocess rerun asserting an empty violation
report with all three density families covered.
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import faults, obs
from dbscan_tpu import density
from dbscan_tpu.density import boruvka, condense, core, oracle

pytestmark = pytest.mark.density

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _multi_density_blobs(rng, n_noise=20):
    """Two tight blobs + one loose blob + uniform noise: the payload a
    single global eps cannot label (the engine's reason to exist)."""
    a = rng.normal((0.0, 0.0), 0.05, (60, 2))
    b = rng.normal((1.5, 0.0), 0.05, (50, 2))
    c = rng.normal((0.0, 4.0), 0.6, (80, 2))
    noise = rng.uniform(-3.0, 7.0, (n_noise, 2))
    return np.concatenate([a, b, c, noise])


def _cosine_blobs(rng, d=16):
    e1 = rng.normal(0, 1, (1, d))
    e2 = rng.normal(0, 1, (1, d))
    p1 = e1 + rng.normal(0, 0.02, (70, d))
    p2 = e2 + rng.normal(0, 0.02, (60, d))
    p3 = rng.normal(0, 1, (30, d))
    return np.concatenate([p1, p2, p3])


def _payload(rng, metric):
    return _cosine_blobs(rng) if metric == "cosine" else (
        _multi_density_blobs(rng)
    )


def _oracle_input(pts, metric):
    """What the oracle must see to be the engine's exact reference: the
    engine's own f32 payload (cosine rows f32-normalized) upcast."""
    x32 = density._unit_payload(np.asarray(pts), metric)
    return np.asarray(x32, dtype=np.float64)


def _oracle_labels(pts, min_pts, metric, mcs=None):
    return oracle.hdbscan_labels(
        _oracle_input(pts, metric), min_pts, mcs or min_pts, metric
    )


@pytest.fixture(autouse=True)
def _fresh_density_state(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    yield
    faults.reset_registry()


# --- oracle-vs-device exact parity -------------------------------------


@pytest.mark.parametrize("metric", ["euclidean", "cosine"])
@pytest.mark.parametrize("prop", ["unionfind", "iterated"])
def test_hdbscan_device_oracle_parity(rng, metric, prop, monkeypatch):
    """Device labels == host-oracle labels, byte for byte, on
    multi-density payloads — under BOTH propagation modes of the shared
    union-find contraction."""
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", prop)
    pts = _payload(rng, metric)
    stats = {}
    lab = density.hdbscan(pts, min_pts=5, metric=metric, stats_out=stats)
    ref = _oracle_labels(pts, 5, metric)
    np.testing.assert_array_equal(lab, ref)
    assert lab.max() >= 2  # the payload really holds multiple clusters
    assert (lab == 0).any()  # and real noise
    assert stats["boruvka_rounds"] >= 1
    assert stats["n"] == len(pts)


@pytest.mark.parametrize("min_pts,mcs", [(3, 3), (5, 10), (8, 4)])
def test_hdbscan_parameter_sweep_parity(rng, min_pts, mcs):
    pts = _multi_density_blobs(rng)
    lab = density.hdbscan(pts, min_pts=min_pts, min_cluster_size=mcs)
    ref = _oracle_labels(pts, min_pts, "euclidean", mcs=mcs)
    np.testing.assert_array_equal(lab, ref)


def test_hdbscan_chunked_core_parity(rng, monkeypatch):
    """A chunk width smaller than the payload forces multiple
    density.core dispatches (incl. the clamped overlapping tail) —
    labels must not depend on the chunking."""
    pts = _multi_density_blobs(rng)
    whole = density.hdbscan(pts, min_pts=5)
    monkeypatch.setenv("DBSCAN_DENSITY_CHUNK", "96")
    stats = {}
    lab = density.hdbscan(pts, min_pts=5, stats_out=stats)
    assert stats["core_chunks"] >= 3
    np.testing.assert_array_equal(lab, whole)


def test_hdbscan_two_condense_constructions_agree(rng):
    """The single-sweep bottom-up condense (condense.py) and the
    oracle's top-down dendrogram condense produce identical labels from
    the SAME total-ordered MST — the two independent constructions the
    missing hdbscan library is compensated by."""
    pts = _multi_density_blobs(rng)
    x = _oracle_input(pts, "euclidean")
    n = len(x)
    d = oracle.pairwise_dists(x, "euclidean")
    edges = oracle.mst_edges(
        oracle.mutual_reachability(d, oracle.core_distances(d, 5))
    )
    lam = np.where(edges[:, 2] > 0, 1.0 / edges[:, 2], np.inf)
    for mcs in (3, 5, 12):
        sweep = oracle.canonical_raw(
            condense.condense_labels(edges, lam, n, mcs)
        )
        ref = oracle.canonical_raw(oracle.labels_from_mst(edges, n, mcs))
        np.testing.assert_array_equal(sweep, ref)


def test_degenerate_inputs():
    assert density.hdbscan(np.empty((0, 2)), min_pts=3).shape == (0,)
    np.testing.assert_array_equal(
        density.hdbscan(np.zeros((1, 2)), min_pts=3), [0]
    )
    # n < min_cluster_size: everything stays pending -> all noise
    pts = np.array([[0.0, 0.0], [0.1, 0.0], [0.0, 0.1]])
    np.testing.assert_array_equal(
        density.hdbscan(pts, min_pts=2, min_cluster_size=5), [0, 0, 0]
    )
    # all-duplicate rows: zero-weight chain MST, infinite lambdas
    dup = np.zeros((24, 2))
    lab = density.hdbscan(dup, min_pts=3)
    ref = oracle.hdbscan_labels(dup, 3, 3, "euclidean")
    np.testing.assert_array_equal(lab, ref)


def test_validation_errors():
    pts = np.zeros((10, 2))
    with pytest.raises(ValueError, match="metric"):
        density.hdbscan(pts, metric="manhattan")
    with pytest.raises(ValueError, match="min_pts"):
        density.hdbscan(pts, min_pts=0)
    with pytest.raises(ValueError, match="min_cluster_size"):
        density.hdbscan(pts, min_pts=3, min_cluster_size=1)
    with pytest.raises(ValueError, match="N, D"):
        density.hdbscan(np.zeros(10), min_pts=3)


def test_hdbscan_lib_cross_check(rng):
    """Cross-check the host oracle against scikit-learn-contrib
    ``hdbscan`` when importable (skip-marked otherwise — no new hard
    dependency): identical partitions up to canonical renumbering."""
    hdb = pytest.importorskip("hdbscan")
    pts = _multi_density_blobs(rng)
    ref = hdb.HDBSCAN(
        min_samples=5,
        min_cluster_size=5,
        allow_single_cluster=False,
        approx_min_span_tree=False,
    ).fit(pts)
    theirs = oracle.canonical_raw(np.asarray(ref.labels_, dtype=np.int64))
    ours = oracle.hdbscan_labels(pts, 5, 5, "euclidean")
    np.testing.assert_array_equal(ours, theirs)


# --- Borůvka MST: property-fuzz + round bound --------------------------


@pytest.mark.parametrize("seed,n,min_pts", [
    (1, 70, 3), (2, 150, 5), (3, 150, 3), (4, 260, 5), (5, 90, 8),
])
def test_boruvka_mst_weight_matches_scipy(seed, n, min_pts):
    """Property-fuzz: the device Borůvka MST total weight equals
    SciPy's ``minimum_spanning_tree`` over the f64 mutual-reachability
    graph (to f32 edge-weight rounding), and the oracle's Kruskal
    matches it to f64 precision."""
    sp = pytest.importorskip("scipy.sparse")
    from scipy.sparse.csgraph import minimum_spanning_tree

    g = np.random.default_rng(seed)
    pts = np.concatenate([
        g.normal((0, 0), 0.1, (n // 2, 2)),
        g.normal((3, 3), 0.5, (n - n // 2, 2)),
    ])
    x = _oracle_input(pts, "euclidean")
    d = oracle.pairwise_dists(x, "euclidean")
    mr = oracle.mutual_reachability(d, oracle.core_distances(d, min_pts))
    scipy_total = float(minimum_spanning_tree(sp.csr_matrix(mr)).sum())
    kruskal = oracle.mst_edges(mr)
    assert np.isclose(kruskal[:, 2].sum(), scipy_total, rtol=1e-9)
    stats = {"_oracle_fallback": True}
    dev_edges, rounds = density._device_mst(
        np.asarray(pts, dtype=np.float32), min_pts, "euclidean", stats
    )[0], None
    assert len(dev_edges) == len(pts) - 1
    assert np.isclose(dev_edges[:, 2].sum(), scipy_total, rtol=1e-5)
    # and edge-for-edge identity with the oracle under the total order
    dev_sorted = dev_edges[
        np.lexsort((dev_edges[:, 1], dev_edges[:, 0], dev_edges[:, 2]))
    ]
    np.testing.assert_array_equal(
        dev_sorted[:, :2].astype(np.int64),
        kruskal[
            np.lexsort((kruskal[:, 1], kruskal[:, 0], kruskal[:, 2]))
        ][:, :2].astype(np.int64),
    )


def test_boruvka_round_bound(rng):
    """Rounds are bounded by ceil(log2 n) + 2 — components at least
    halve per round because every live component selects an edge of the
    complete mutual-reachability graph."""
    pts = _multi_density_blobs(rng)
    stats = {}
    density.hdbscan(pts, min_pts=5, stats_out=stats)
    bound = int(math.ceil(math.log2(len(pts)))) + 2
    assert 1 <= stats["boruvka_rounds"] <= bound, stats


# --- OPTICS ------------------------------------------------------------


@pytest.mark.parametrize("metric", ["euclidean", "cosine"])
def test_optics_order_and_reach_parity(rng, metric):
    """Device OPTICS ordering is EXACTLY the oracle's (structural in
    the shared MST edge set); reachability/core values agree to f32
    edge-weight rounding."""
    pts = _payload(rng, metric)
    o_ord, o_reach, o_core = density.optics(pts, min_pts=5, metric=metric)
    r_ord, r_reach, r_core = oracle.optics_oracle(
        _oracle_input(pts, metric), 5, metric
    )
    np.testing.assert_array_equal(o_ord, r_ord)
    assert np.isinf(o_reach[o_ord[0]])
    fin = np.isfinite(r_reach)
    np.testing.assert_allclose(
        o_reach[fin], r_reach[fin], rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(o_core, r_core, rtol=1e-4, atol=1e-6)


def test_optics_reachability_separates_densities(rng):
    """The reachability plot's valleys are the clusters: within-blob
    reachability sits far below the ridge entering the noise."""
    pts = _multi_density_blobs(rng, n_noise=12)
    order, reach, _ = density.optics(pts, min_pts=5)
    lab = density.hdbscan(pts, min_pts=5)
    in_cluster = lab[order] > 0
    r = reach[order]
    fin = np.isfinite(r)
    assert np.median(r[in_cluster & fin]) < 0.5 * np.median(
        r[~in_cluster & fin]
    )


# --- fault-site drills -------------------------------------------------


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


@pytest.mark.faults
def test_density_core_transient_heals(rng, monkeypatch):
    pts = _multi_density_blobs(rng)
    clean = density.hdbscan(pts, min_pts=5)
    _spec(monkeypatch, "density_core#0:TRANSIENT*2")
    snap = faults.counters.snapshot()
    lab = density.hdbscan(pts, min_pts=5)
    delta = faults.counters.delta(snap)
    assert delta["retries"] >= 2 and delta["injected"] >= 2
    assert delta["fallbacks"] == 0
    np.testing.assert_array_equal(clean, lab)


@pytest.mark.faults
def test_density_core_persistent_degrades_chunk_to_host(
    rng, monkeypatch
):
    """A persistently failing core chunk degrades to the bitwise-
    identical numpy chunk (euclidean leg) — labels intact."""
    pts = _multi_density_blobs(rng)
    clean = density.hdbscan(pts, min_pts=5)
    _spec(monkeypatch, "density_core#0:PERSISTENT")
    snap = faults.counters.snapshot()
    lab = density.hdbscan(pts, min_pts=5)
    delta = faults.counters.delta(snap)
    assert delta["fallbacks"] >= 1
    np.testing.assert_array_equal(clean, lab)


@pytest.mark.faults
@pytest.mark.parametrize("metric", ["euclidean", "cosine"])
def test_density_boruvka_transient_heals(rng, metric, monkeypatch):
    pts = _payload(rng, metric)
    clean = density.hdbscan(pts, min_pts=5, metric=metric)
    _spec(monkeypatch, "density_boruvka#0:TRANSIENT*2")
    snap = faults.counters.snapshot()
    lab = density.hdbscan(pts, min_pts=5, metric=metric)
    delta = faults.counters.delta(snap)
    assert delta["retries"] >= 2 and delta["injected"] >= 2
    np.testing.assert_array_equal(clean, lab)


@pytest.mark.faults
@pytest.mark.parametrize("metric", ["euclidean", "cosine"])
def test_density_boruvka_persistent_degrades_whole_run(
    rng, metric, monkeypatch
):
    """A persistent Borůvka fault cannot degrade per round (the MST is
    global state): the WHOLE run degrades to the host oracle, labels
    intact."""
    pts = _payload(rng, metric)
    clean = density.hdbscan(pts, min_pts=5, metric=metric)
    _spec(monkeypatch, "density_boruvka#0:PERSISTENT")
    was = obs.active()
    obs.enable()
    try:
        snap = obs.counters()
        stats = {}
        lab = density.hdbscan(
            pts, min_pts=5, metric=metric, stats_out=stats
        )
        delta = obs.counters_delta(snap)
    finally:
        if not was:
            obs.disable()
    assert stats["density_degraded"] == "oracle"
    assert delta.get("density.oracle_fallbacks", 0) == 1
    np.testing.assert_array_equal(clean, lab)


@pytest.mark.faults
def test_density_persistent_without_fallback_raises(rng, monkeypatch):
    pts = _multi_density_blobs(rng)
    _spec(monkeypatch, "density_boruvka#0:PERSISTENT")
    with pytest.raises(faults.FatalDeviceFault):
        density.hdbscan(pts, min_pts=5, oracle_fallback=False)
    _spec(monkeypatch, "density_core#0:PERSISTENT")
    with pytest.raises(faults.FatalDeviceFault):
        density.hdbscan(pts, min_pts=5, oracle_fallback=False)


@pytest.mark.faults
def test_density_optics_persistent_degrades_whole_run(rng, monkeypatch):
    pts = _multi_density_blobs(rng)
    c_ord, c_reach, _ = density.optics(pts, min_pts=5)
    _spec(monkeypatch, "density_boruvka#0:PERSISTENT")
    stats = {}
    o_ord, o_reach, _ = density.optics(pts, min_pts=5, stats_out=stats)
    assert stats["density_degraded"] == "oracle"
    np.testing.assert_array_equal(c_ord, o_ord)
    fin = np.isfinite(c_reach)
    np.testing.assert_allclose(
        o_reach[fin], c_reach[fin], rtol=1e-4, atol=1e-6
    )


# --- zero-retrace + citizenship ----------------------------------------


def test_zero_retrace_second_run(rng):
    """The acceptance pin: a second same-shaped run (hdbscan AND
    optics, both metrics) compiles ZERO new kernels — chunk starts are
    traced, ladders are ratcheted, round kernels are shape-keyed."""
    jobs = [
        (_multi_density_blobs(rng), "euclidean"),
        (_cosine_blobs(rng), "cosine"),
    ]
    was = obs.active()
    obs.enable()
    try:
        for pts, metric in jobs:  # warm pass settles every ladder
            density.hdbscan(pts, min_pts=5, metric=metric)
            density.optics(pts, min_pts=5, metric=metric)
        snap = obs.counters()
        for pts, metric in jobs:
            density.hdbscan(pts, min_pts=5, metric=metric)
            density.optics(pts, min_pts=5, metric=metric)
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.total", 0) == 0, delta
        assert delta.get("compiles.ratchet_raises", 0) == 0, delta
    finally:
        if not was:
            obs.disable()


def test_density_counters_declared(rng):
    """Every density.* emission is schema-declared (the obs acceptance
    contract) and the run stamps the expected counters."""
    from dbscan_tpu.obs import schema

    was = obs.active()
    obs.enable()
    try:
        snap = obs.counters()
        pts = _multi_density_blobs(rng)
        density.hdbscan(pts, min_pts=5)
        delta = obs.counters_delta(snap)
        for name in obs.counters():
            assert schema.is_declared("counter", name), name
    finally:
        if not was:
            obs.disable()
    assert delta.get("density.points", 0) == len(pts)
    assert delta.get("density.edges", 0) == len(pts) - 1
    assert delta.get("density.rounds", 0) >= 1
    assert delta.get("density.core_dispatches", 0) >= 1
    assert delta.get("density.condense_dispatches", 0) == 1


def test_density_registry_citizenship():
    """The three dispatch families + both fault sites + all knobs are
    registered in their registries (the PR's citizenship checklist)."""
    from dbscan_tpu import config
    from dbscan_tpu.lint.shapes import FAMILY_MODELS
    from dbscan_tpu.obs import schema

    for fam in ("density.core", "density.boruvka", "density.condense"):
        assert fam in schema.COMPILE_FAMILIES
        assert fam in FAMILY_MODELS
        assert schema.is_declared("counter", f"compiles.{fam}")
    assert faults.SITE_DENSITY_CORE in faults._SITES
    assert faults.SITE_DENSITY_BORUVKA in faults._SITES
    for knob in (
        "DBSCAN_DENSITY_CHUNK",
        "DBSCAN_DENSITY_ORACLE_MAX",
        "DBSCAN_DENSITY_AUTO_SAMPLE",
        "DBSCAN_DENSITY_AUTO_PARTS",
    ):
        assert knob in config.ENV_VARS
    assert schema.is_declared("span", "density.run")
    assert schema.is_declared("gauge", "density.eps_auto")


def test_shapecheck_subprocess_clean(tmp_path):
    """DBSCAN_SHAPECHECK=1 rerun of hdbscan + optics in a fresh
    process: the atexit JSON report must be violation-free with ALL
    THREE density families covered."""
    report = tmp_path / "shapecheck.json"
    code = (
        "import numpy as np\n"
        "from dbscan_tpu import hdbscan, optics\n"
        "from dbscan_tpu.density import oracle\n"
        "rng = np.random.default_rng(0)\n"
        "pts = np.concatenate([rng.normal((0, 0), 0.05, (60, 2)),"
        " rng.normal((1.5, 0), 0.05, (50, 2)),"
        " rng.normal((0, 4), 0.6, (80, 2)),"
        " rng.uniform(-3, 7, (20, 2))])\n"
        "lab = hdbscan(pts, min_pts=5)\n"
        "ref = oracle.hdbscan_labels(pts.astype(np.float64), 5, 5)\n"
        "assert np.array_equal(lab, ref)\n"
        "order, reach, core = optics(pts, min_pts=5)\n"
        "assert len(order) == len(pts)\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_SHAPECHECK="1",
        DBSCAN_SHAPECHECK_REPORT=str(report),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, timeout=600, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    rep = json.loads(report.read_text())
    assert rep["violations"] == []
    assert "density.core" in rep["sites"]
    assert "density.boruvka" in rep["sites"]
    assert "density.condense" in rep["sites"]


# --- eps='auto' (plain-DBSCAN satellite) -------------------------------


def test_auto_eps_probe_deterministic(rng):
    pts = _multi_density_blobs(rng)
    stats = {}
    eps1 = core.auto_eps(pts, 5, stats_out=stats)
    eps2 = core.auto_eps(pts, 5)
    assert eps1 == eps2 > 0.0
    info = stats["eps_auto"]
    assert info["eps"] == eps1 and info["k"] == 5
    assert info["strips"] == len(info["strip_knees"]) >= 1
    # stamped knees are rounded to 9 decimals for the stats record
    assert np.isclose(eps1, np.median(info["strip_knees"]), atol=1e-8)


def test_knee_index_picks_the_elbow():
    flat = np.linspace(0.1, 0.1001, 50)
    assert 0 <= core.knee_index(flat) < 50
    hockey = np.concatenate([np.full(40, 0.05), np.linspace(0.05, 2.0, 10)])
    assert core.knee_index(hockey) >= 38  # at the bend, not the blade
    assert core.knee_index(np.array([1.0])) == 0
    assert core.knee_index(np.empty(0)) == 0


def test_train_eps_auto_recovers_blobs(rng):
    """train(eps='auto') resolves the knob from the k-distance knee and
    stamps the probe statistics; the two same-density blobs come back
    as the two dominant clusters."""
    import dbscan_tpu

    a = rng.normal((0.0, 0.0), 0.08, (120, 2))
    b = rng.normal((4.0, 4.0), 0.08, (120, 2))
    pts = np.concatenate([a, b, rng.uniform(-2.0, 6.0, (15, 2))])
    m = dbscan_tpu.train(pts, "auto", 5)
    info = m.stats["eps_auto"]
    assert m.config.eps == info["eps"] > 0.0
    la, lb = m.clusters[:120], m.clusters[120:240]
    da = np.bincount(la[la > 0]).max()
    db = np.bincount(lb[lb > 0]).max()
    assert da >= 96 and db >= 96  # >= 80% of each blob in one cluster
    assert (
        np.bincount(la[la > 0]).argmax() != np.bincount(lb[lb > 0]).argmax()
    )


def test_train_eps_auto_validation(rng):
    import dbscan_tpu
    from dbscan_tpu.config import DBSCANConfig

    pts = rng.normal(0, 1, (50, 2))
    with pytest.raises(ValueError, match="'auto'"):
        dbscan_tpu.train(pts, "bogus", 5)
    with pytest.raises(ValueError, match="euclidean"):
        dbscan_tpu.train(pts, "auto", 5, metric="haversine")
    with pytest.raises(ValueError, match="config"):
        dbscan_tpu.train(
            pts, "auto", 5, config=DBSCANConfig(eps=0.1, min_points=5)
        )
    with pytest.raises(ValueError, match=">= 2"):
        core.auto_eps(pts[:1], 5)
