"""Trace analyzer + bench-history regression gate (dbscan_tpu/obs/).

Two consumer surfaces pinned here:

- `obs/analyze.py` on a HAND-BUILT synthetic trace with known
  self-times and byte counters: the critical-path and bandwidth tables
  must come out exactly (no tolerance — the fixture's arithmetic is the
  spec);
- `obs/bench_history.py` + `obs/regress.py`: every historical capture
  shape normalizes, ingest is append-only/dedup-on-reingest, the gate
  flags an injected 2x slowdown (exit 1) and stays green on identical
  numbers (exit 0), hot/cold resident populations never mix, and the
  noise-aware threshold widens to the history's own spread;
- the `python -m` console entry points run as subprocesses on the
  fixture trace and the committed `bench/history.jsonl` — the tier-1
  smoke keeping the CLIs from rotting.
"""

import json
import os
import subprocess
import sys

import pytest

from dbscan_tpu.obs import analyze, bench_history, regress

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- synthetic trace fixture ------------------------------------------


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


def _span(name, t0, dur, tid=1, depth=0, args=None, events=None):
    return {
        "type": "span", "name": name, "t0_s": t0, "dur_s": dur,
        "depth": depth, "tid": tid, "args": args or {},
        "events": events or [],
    }


@pytest.fixture
def synthetic_trace(tmp_path):
    """Nested spans with known self-times:

    tid 1: root [0, 10]
             phase.a [1, 4]  (contains phase.a.inner [2, 3])
             phase.b [5, 7]
             transfer.pull [7.5, 8.0] bytes=5e6
    tid 2: worker [0, 2]  (separate thread: no nesting vs tid 1)

    Exact self-times: root 4.5, phase.a 2.0, phase.b 2.0,
    phase.a.inner 1.0, transfer.pull 0.5, worker 2.0.
    """
    records = [
        _span("root", 0.0, 10.0),
        _span("phase.a", 1.0, 3.0, depth=1),
        _span("phase.a.inner", 2.0, 1.0, depth=2),
        _span("phase.b", 5.0, 2.0, depth=1),
        _span("transfer.pull", 7.5, 0.5, depth=1,
              args={"bytes": 5_000_000}),
        _span("worker", 0.0, 2.0, tid=2),
        {"type": "instant", "name": "resident_cache.miss", "t_s": 0.5,
         "args": {}},
        {"type": "counter", "name": "transfer.h2d_bytes",
         "value": 1_000_000},
        {"type": "counter", "name": "transfer.payload_upload_bytes",
         "value": 2_000_000},
        {"type": "counter", "name": "transfer.payload_upload_s",
         "value": 1.0},
        {"type": "counter", "name": "transfer.d2h_bytes",
         "value": 5_000_000},
        {"type": "counter", "name": "transfer.d2h_s", "value": 0.5},
        {"type": "counter", "name": "compiles.total", "value": 3},
        {"type": "gauge", "name": "memory.peak_bytes_in_use",
         "value": 1_234_567},
        {"type": "gauge", "name": "memory.at.dispatch.dense",
         "value": 1_000_000},
    ]
    return _write_jsonl(tmp_path / "trace.jsonl", records)


def test_critical_path_table_exact(synthetic_trace):
    report = analyze.analyze(analyze.load_trace(synthetic_trace))
    rows = {r["name"]: r for r in report["phases"]}
    assert rows["root"]["self_s"] == 4.5
    assert rows["root"]["total_s"] == 10.0
    assert rows["phase.a"]["self_s"] == 2.0
    assert rows["phase.a.inner"]["self_s"] == 1.0
    assert rows["phase.b"]["self_s"] == 2.0
    assert rows["transfer.pull"]["self_s"] == 0.5
    # the second thread's span never nests under tid 1's root
    assert rows["worker"]["self_s"] == 2.0
    # ordered by self-time descending; ties keep first-seen order
    assert [r["name"] for r in report["phases"]] == [
        "root", "phase.a", "phase.b", "worker", "phase.a.inner",
        "transfer.pull",
    ]
    assert rows["phase.a"]["count"] == 1
    assert rows["phase.a"]["mean_s"] == 3.0
    assert rows["phase.a"]["max_s"] == 3.0


def test_bandwidth_table_exact(synthetic_trace):
    report = analyze.analyze(analyze.load_trace(synthetic_trace))
    bw = {r["name"]: r for r in report["bandwidth"]}
    assert bw["h2d (dispatch inputs, async)"]["bytes"] == 1_000_000
    assert bw["h2d (dispatch inputs, async)"]["mb_per_s"] is None
    up = bw["h2d payload upload"]
    assert (up["bytes"], up["seconds"], up["mb_per_s"]) == (
        2_000_000, 1.0, 2.0,
    )
    d2h = bw["d2h pulls (incl. device wait)"]
    assert (d2h["bytes"], d2h["seconds"], d2h["mb_per_s"]) == (
        5_000_000, 0.5, 10.0,
    )
    pulls = bw["d2h pull spans"]
    assert (pulls["bytes"], pulls["seconds"], pulls["mb_per_s"]) == (
        5_000_000, 0.5, 10.0,
    )


def test_memory_and_compiles_sections(synthetic_trace):
    report = analyze.analyze(analyze.load_trace(synthetic_trace))
    assert report["memory"] == {
        "memory.at.dispatch.dense": 1_000_000,
        "memory.peak_bytes_in_use": 1_234_567,
    }
    assert report["compiles"] == {"compiles.total": 3}


def test_resident_hot_cold_split(tmp_path):
    """Two train runs in one trace: the one whose window holds a miss
    mark is cold, the one holding a hit mark is hot."""
    records = [
        _span("train", 0.0, 60.0),
        _span("train", 100.0, 5.0),
        {"type": "instant", "name": "resident_cache.miss", "t_s": 0.1,
         "args": {}},
        {"type": "instant", "name": "resident_cache.hit", "t_s": 100.1,
         "args": {}},
        {"type": "counter", "name": "resident_cache.hits", "value": 1},
        {"type": "counter", "name": "resident_cache.misses", "value": 1},
    ]
    path = _write_jsonl(tmp_path / "hc.jsonl", records)
    res = analyze.analyze(analyze.load_trace(path))["resident"]
    assert res["hits"] == 1 and res["misses"] == 1
    assert res["cold_walls_s"] == [60.0]
    assert res["hot_walls_s"] == [5.0]
    assert res["cold_mean_s"] == 60.0 and res["hot_mean_s"] == 5.0


def test_chrome_and_jsonl_loaders_agree(tmp_path):
    """The same run exported in both formats analyzes identically."""
    from dbscan_tpu import obs

    obs.disable()
    obs.enable()
    try:
        with obs.span("outer"):
            with obs.span("inner"):
                pass
        obs.count("transfer.h2d_bytes", 4096)
        obs.gauge("memory.peak_bytes_in_use", 777)
        jl = str(tmp_path / "t.jsonl")
        ch = str(tmp_path / "t.json")
        obs.write(jl)
        obs.write(ch)
    finally:
        obs.disable()
    rep_j = analyze.analyze(analyze.load_trace(jl))
    rep_c = analyze.analyze(analyze.load_trace(ch))
    names_j = [r["name"] for r in rep_j["phases"]]
    names_c = [r["name"] for r in rep_c["phases"]]
    assert set(names_j) == set(names_c) == {"outer", "inner"}
    assert rep_j["memory"] == rep_c["memory"] == {
        "memory.peak_bytes_in_use": 777
    }
    assert (
        rep_j["bandwidth"][0]["bytes"]
        == rep_c["bandwidth"][0]["bytes"]
        == 4096
    )


def test_analyze_cli_smoke(synthetic_trace):
    """Tier-1 smoke for the console entry point: the module must run
    as `python -m dbscan_tpu.obs.analyze` on a fixture trace."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "dbscan_tpu.obs.analyze", synthetic_trace],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "critical path" in proc.stdout
    assert "root" in proc.stdout
    assert "memory.peak_bytes_in_use" in proc.stdout
    proc = subprocess.run(
        [
            sys.executable, "-m", "dbscan_tpu.obs.analyze",
            synthetic_trace, "--json",
        ],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["n_spans"] == 6


def test_analyze_missing_file_exits_2(tmp_path):
    assert analyze.main([str(tmp_path / "nope.json")]) == 2


# --- bench history: normalization + ingest ----------------------------


def _capture(tmp_path, name, obj):
    path = tmp_path / name
    path.write_text(json.dumps(obj))
    return str(path)


BASE_CAPTURE = {
    "metric": "dbscan_2d_euclidean_throughput",
    "value": 0.75,
    "unit": "Mpoints/s",
    "backend": "tpu",
    "seconds": 1.3,
    "anchor_seconds": 32.0,
    "n_clusters": 48,  # not a perf key: must NOT become a record
    "cosine_seconds": 5.1,
    "cosine_resident_hot": True,
}


def test_resident_hot_false_is_preserved(tmp_path):
    """resident_hot=False (a COLD rep) is a tag, not a missing tag: a
    falsy-coalescing bug here would gate cold walls against the
    untagged population."""
    path = _capture(
        tmp_path, "BENCH_COLDTAG.json",
        {"backend": "tpu", "seconds": 55.0, "resident_hot": False,
         "cosine_seconds": 60.0, "cosine_resident_hot": False},
    )
    recs = {r["metric"]: r for r in bench_history.parse_capture_file(path)}
    assert recs["seconds"]["resident_hot"] is False
    assert recs["cosine_seconds"]["resident_hot"] is False


def test_resident_tag_covers_all_row_metrics(tmp_path):
    """One {prefix}_resident_hot tag covers EVERY metric of that row —
    vs_baseline and the upload/compute splits are derived from the same
    bimodal wall as the seconds figure."""
    path = _capture(
        tmp_path, "BENCH_ROWTAG.json",
        {"backend": "tpu", "cosine_seconds": 8.6,
         "cosine_vs_baseline": 24.1, "cosine_compute_s": 8.4,
         "cosine_resident_hot": True, "anchor_seconds": 32.0},
    )
    recs = {r["metric"]: r for r in bench_history.parse_capture_file(path)}
    assert recs["cosine_seconds"]["resident_hot"] is True
    assert recs["cosine_vs_baseline"]["resident_hot"] is True
    assert recs["cosine_compute_s"]["resident_hot"] is True
    assert recs["anchor_seconds"]["resident_hot"] is None


def test_normalize_metric_capture(tmp_path):
    path = _capture(tmp_path, "BENCH_X.json", BASE_CAPTURE)
    recs = bench_history.parse_capture_file(path, rev="abc123")
    by_metric = {r["metric"]: r for r in recs}
    assert set(by_metric) == {
        "dbscan_2d_euclidean_throughput", "seconds", "anchor_seconds",
        "cosine_seconds",
    }
    head = by_metric["dbscan_2d_euclidean_throughput"]
    assert head["value"] == 0.75 and head["unit"] == "Mpoints/s"
    assert head["backend"] == "tpu" and head["rev"] == "abc123"
    assert head["source"] == "BENCH_X.json"
    assert by_metric["anchor_seconds"]["unit"] == "s"
    assert by_metric["anchor_seconds"]["resident_hot"] is None
    # the hot/cold tag rides the metric it covers
    assert by_metric["cosine_seconds"]["resident_hot"] is True


def test_normalize_wrapper_and_multichip(tmp_path):
    wrapper = {
        "n": 2, "cmd": "python bench.py", "rc": 0,
        "parsed": {"metric": "m", "value": 2.0, "unit": "Mpoints/s",
                   "backend": "tpu", "seconds": 3.5},
        "tail": 'noise\n{"metric": "m", "value": 1.0, "unit": '
                '"Mpoints/s", "backend": "tpu", "seconds": 4.0}\n'
                "not json {\n",
    }
    path = _capture(tmp_path, "BENCH_W.json", wrapper)
    recs = bench_history.parse_capture_file(path)
    vals = sorted(
        r["value"] for r in recs if r["metric"] == "seconds"
    )
    assert vals == [3.5, 4.0]  # parsed record + embedded tail line
    mc = _capture(
        tmp_path, "MULTICHIP_X.json",
        {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
         "tail": "..."},
    )
    recs = bench_history.parse_capture_file(mc)
    assert recs == [
        {
            "metric": "multichip_ok", "value": 1.0, "unit": None,
            "backend": "multichip8", "resident_hot": None,
            "rev": "unknown", "source": "MULTICHIP_X.json",
        }
    ]


def test_normalize_multichip_real_capture(tmp_path):
    """The PR-12 multichip shape (dryrun keys + real flat metrics)
    promotes its perf keys under the multichip backend — _mpts,
    walls, and the per-shard busy/overlap ratios — while the legacy
    dryrun shape (previous test) stays one multichip_ok record."""
    mc = _capture(
        tmp_path, "MULTICHIP_Y.json",
        {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
         "multichip_n": 120000, "multichip_seconds": 14.7,
         "multichip_mpts": 0.00815,
         "multichip_all_busy_frac": 0.9998,
         "multichip_pull_overlap_ratio": 0.0,
         "multichip_shard_dispatches": [7, 7],
         "multichip_recompiles": 0},
    )
    recs = bench_history.parse_capture_file(mc)
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["multichip_ok"]["value"] == 1.0
    assert by_metric["multichip_mpts"]["unit"] == "Mpoints/s"
    assert by_metric["multichip_seconds"]["unit"] == "s"
    assert by_metric["multichip_all_busy_frac"]["value"] == 0.9998
    assert "multichip_pull_overlap_ratio" in by_metric
    # every promoted record rides the multichip backend so sharded
    # trends never mix with single-chip rows
    assert {r["backend"] for r in recs} == {"multichip8"}
    # list/bool/count keys are not perf metrics
    assert "multichip_shard_dispatches" not in by_metric
    assert "multichip_recompiles" not in by_metric
    # and the regress gate reads the ratios HIGHER-better
    from dbscan_tpu.obs import regress

    assert regress.direction("multichip_all_busy_frac") == regress.HIGHER_BETTER
    assert regress.direction("multichip_mpts") == regress.HIGHER_BETTER
    assert regress.direction("multichip_seconds") != regress.HIGHER_BETTER


def test_ingest_append_only_dedup(tmp_path):
    cap = _capture(tmp_path, "BENCH_A.json", BASE_CAPTURE)
    hist = str(tmp_path / "history.jsonl")
    added, skipped = bench_history.ingest([cap], hist, rev="r1")
    assert added == 4 and skipped == 0
    # re-ingest: nothing appended, nothing rewritten
    before = open(hist).read()
    added, skipped = bench_history.ingest([cap], hist, rev="r1")
    assert added == 0 and skipped == 4
    assert open(hist).read() == before
    assert not bench_history.check_schema(bench_history.load_history(hist))


# --- regression gate --------------------------------------------------


def _mk_history(tmp_path, sources):
    """History from several capture dicts (one source each)."""
    hist = str(tmp_path / "history.jsonl")
    for i, obj in enumerate(sources):
        cap = _capture(tmp_path, f"BENCH_H{i}.json", obj)
        bench_history.ingest([cap], hist, rev=f"r{i}")
    return hist


def test_regress_green_on_identical_red_on_2x(tmp_path):
    hist = _mk_history(
        tmp_path,
        [
            {"backend": "tpu", "anchor_seconds": 32.0, "value": 0.75,
             "metric": "thr", "unit": "Mpoints/s"},
            {"backend": "tpu", "anchor_seconds": 33.0, "value": 0.73,
             "metric": "thr", "unit": "Mpoints/s"},
            {"backend": "tpu", "anchor_seconds": 31.5, "value": 0.76,
             "metric": "thr", "unit": "Mpoints/s"},
        ],
    )
    same = _capture(
        tmp_path, "BENCH_FRESH.json",
        {"backend": "tpu", "anchor_seconds": 32.2, "value": 0.75,
         "metric": "thr", "unit": "Mpoints/s"},
    )
    assert regress.main(["--history", hist, "--capture", same]) == 0
    slow = _capture(
        tmp_path, "BENCH_SLOW.json",
        {"backend": "tpu", "anchor_seconds": 64.0, "value": 0.75,
         "metric": "thr", "unit": "Mpoints/s"},
    )
    assert regress.main(["--history", hist, "--capture", slow]) == 1
    # throughput regressing DOWN flags too
    slow_thr = _capture(
        tmp_path, "BENCH_SLOWTHR.json",
        {"backend": "tpu", "anchor_seconds": 32.0, "value": 0.3,
         "metric": "thr", "unit": "Mpoints/s"},
    )
    assert regress.main(["--history", hist, "--capture", slow_thr]) == 1


def test_regress_gates_pull_overlap_ratio(tmp_path):
    """The pull-pipeline overlap ratio rides the history like the walls
    (suffix `_overlap_ratio`, unit `ratio`) and regresses DOWN: a
    capture whose pulls fell back onto the critical path flags."""
    rows = [
        {"backend": "tpu", "anchor_pull_overlap_ratio": v}
        for v in (0.82, 0.78, 0.85)
    ]
    hist = _mk_history(tmp_path, rows)
    recs = bench_history.load_history(hist)
    mine = [r for r in recs if r["metric"] == "anchor_pull_overlap_ratio"]
    assert len(mine) == 3 and all(r["unit"] == "ratio" for r in mine)
    assert regress.direction("anchor_pull_overlap_ratio") == "higher"
    same = _capture(
        tmp_path, "BENCH_OV_OK.json",
        {"backend": "tpu", "anchor_pull_overlap_ratio": 0.80},
    )
    assert regress.main(["--history", hist, "--capture", same]) == 0
    lost = _capture(
        tmp_path, "BENCH_OV_BAD.json",
        {"backend": "tpu", "anchor_pull_overlap_ratio": 0.15},
    )
    assert regress.main(["--history", hist, "--capture", lost]) == 1


def test_regress_hot_cold_populations_never_mix(tmp_path):
    """A cold cosine wall ~10x the hot wall is NOT a regression when
    the history's cold population says so — and a 2x slowdown within
    the cold population still flags."""
    hot = {"backend": "tpu", "cosine_seconds": 5.0,
           "cosine_resident_hot": True}
    cold = {"backend": "tpu", "cosine_seconds": 55.0,
            "cosine_resident_hot": False}
    hist = _mk_history(
        tmp_path,
        [hot, cold,
         {**hot, "cosine_seconds": 5.2},
         {**cold, "cosine_seconds": 58.0}],
    )
    fresh_cold = _capture(
        tmp_path, "BENCH_COLD.json",
        {"backend": "tpu", "cosine_seconds": 56.0,
         "cosine_resident_hot": False},
    )
    assert regress.main(["--history", hist, "--capture", fresh_cold]) == 0
    slow_cold = _capture(
        tmp_path, "BENCH_COLDSLOW.json",
        {"backend": "tpu", "cosine_seconds": 113.0,
         "cosine_resident_hot": False},
    )
    assert regress.main(["--history", hist, "--capture", slow_cold]) == 1


def test_regress_noise_aware_threshold(tmp_path):
    """A metric whose history already swings 2x cannot flag at 25%: the
    effective threshold widens to the observed spread."""
    hist = _mk_history(
        tmp_path,
        [{"backend": "tpu", "cosine_seconds": 10.0},
         {"backend": "tpu", "cosine_seconds": 30.0},
         {"backend": "tpu", "cosine_seconds": 20.0}],
    )
    fresh = _capture(
        tmp_path, "BENCH_N.json",
        {"backend": "tpu", "cosine_seconds": 29.0},  # +45% over median
    )
    assert regress.main(["--history", hist, "--capture", fresh]) == 0
    way_out = _capture(
        tmp_path, "BENCH_N2.json",
        {"backend": "tpu", "cosine_seconds": 80.0},  # past spread too
    )
    assert regress.main(["--history", hist, "--capture", way_out]) == 1


def test_regress_min_samples_and_backend_isolation(tmp_path):
    hist = _mk_history(
        tmp_path, [{"backend": "tpu", "anchor_seconds": 32.0}]
    )
    # one sample < min 2 -> skipped, not gated
    fresh = _capture(
        tmp_path, "BENCH_S.json",
        {"backend": "tpu", "anchor_seconds": 500.0},
    )
    assert regress.main(["--history", hist, "--capture", fresh]) == 0
    # a cpu capture never gates against tpu history
    cpu = _capture(
        tmp_path, "BENCH_CPU.json",
        {"backend": "cpu", "anchor_seconds": 500.0},
    )
    assert regress.main(["--history", hist, "--capture", cpu]) == 0


def test_regress_check_schema_catches_bad_records(tmp_path):
    hist = str(tmp_path / "history.jsonl")
    with open(hist, "w") as f:
        f.write(json.dumps({"metric": "m", "value": 1.0, "source": "s"}))
        f.write("\n")
        f.write(json.dumps({"metric": "m", "value": "fast"}))
        f.write("\n")
    assert regress.main(["--history", hist, "--check-schema"]) == 2
    assert regress.main(
        ["--history", str(tmp_path / "absent.jsonl"), "--check-schema"]
    ) == 2


def test_regress_cli_smoke_on_committed_history():
    """Tier-1 smoke: the committed bench/history.jsonl (ingested from
    the root BENCH_*/MULTICHIP_* captures) passes --check-schema via
    the real console entry point."""
    hist = os.path.join(REPO, "bench", "history.jsonl")
    assert os.path.exists(hist), (
        "bench/history.jsonl missing — re-ingest with "
        "python -m dbscan_tpu.obs.bench_history BENCH_*.json "
        "MULTICHIP_*.json"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "dbscan_tpu.obs.regress",
            "--check-schema", "--history", hist,
        ],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "schema ok" in proc.stdout


def test_bench_history_gate_before_append(tmp_path):
    """bench.py's BENCH_HISTORY hook gates against the PRIOR history
    and refuses to ingest a regressed capture — one bad run must not
    enter its own baseline and widen the noise threshold over itself."""
    import bench

    hist = _mk_history(
        tmp_path,
        [{"backend": "tpu", "anchor_seconds": 32.0},
         {"backend": "tpu", "anchor_seconds": 33.0}],
    )
    before = open(hist).read()
    assert (
        bench._history_gate_append(
            {"backend": "tpu", "anchor_seconds": 64.0}, hist
        )
        is False
    )
    assert open(hist).read() == before  # nothing ingested
    assert (
        bench._history_gate_append(
            {"backend": "tpu", "anchor_seconds": 32.5}, hist
        )
        is True
    )
    new = bench_history.load_history(hist)
    assert any(
        r["metric"] == "anchor_seconds" and r["value"] == 32.5
        for r in new
    )


def test_regress_gate_against_committed_history(tmp_path):
    """Acceptance criterion end-to-end: against the INGESTED history, a
    real capture's numbers pass and a synthetic 2x slowdown of the same
    capture exits nonzero."""
    hist = os.path.join(REPO, "bench", "history.jsonl")
    src = os.path.join(REPO, "BENCH_TPU_r05.json")
    if not (os.path.exists(hist) and os.path.exists(src)):
        pytest.skip("committed history/captures not present")
    assert regress.main(["--history", hist, "--capture", src]) == 0
    obj = json.loads(open(src).readline())
    for k in list(obj):
        if k.endswith("_seconds") or k == "seconds":
            obj[k] = obj[k] * 2
    slow = _capture(tmp_path, "BENCH_2X.json", obj)
    assert regress.main(["--history", hist, "--capture", slow]) == 1


# --- devtime schema coverage (device-timeline rollup) ------------------


def test_devtime_rollup_covers_every_compile_family(tmp_path):
    """Schema-coverage pin for the device-timeline section: EVERY
    declared compile family — explicitly including the PR-13/14
    additions (serve.query/serve.jobs/embed.hash/embed.neighbors) —
    has its ``devtime.<family>`` span generated in the schema, reaches
    the per-family rollup, and survives the --merge path into the
    merged report. A family added to COMPILE_FAMILIES can never
    silently drop out of the device timeline again."""
    from dbscan_tpu.obs import schema

    for fam in (
        "serve.query", "serve.jobs", "embed.hash", "embed.neighbors",
        "cellcc.fused",
    ):
        assert fam in schema.COMPILE_FAMILIES, fam
    for fam in schema.COMPILE_FAMILIES:
        assert schema.is_declared("span", f"devtime.{fam}"), fam

    fams = list(schema.COMPILE_FAMILIES)
    records = [
        {"type": "meta", "epoch0": 100.0, "pid": 1, "shard": 0},
        _span("train", 0.0, float(len(fams) + 1)),
    ] + [
        _span(f"devtime.{fam}", float(i), 0.5, depth=1,
              args={"host_s": 0.1, "sync_s": 0.05})
        for i, fam in enumerate(fams)
    ]
    path = _write_jsonl(tmp_path / "dev.jsonl", records)
    report = analyze.analyze(analyze.load_trace(path))
    rolled = {r["family"] for r in report["devtime"]["families"]}
    assert rolled == set(fams)
    assert report["devtime"]["device_busy_frac"] > 0

    # the merged (--merge) view rolls the same families up
    records2 = [dict(r) for r in records]
    records2[0] = {"type": "meta", "epoch0": 101.0, "pid": 2, "shard": 1}
    path2 = _write_jsonl(tmp_path / "dev2.jsonl", records2)
    merged = analyze.merge_shards([path, path2])
    mreport = analyze.analyze(merged["data"])
    mreport["merge"] = merged["merge"]
    mrolled = {r["family"] for r in mreport["devtime"]["families"]}
    assert mrolled == set(fams)
    text = analyze.render(mreport)
    assert "-- device timeline (ready-sync brackets) --" in text
    for fam in ("serve.query", "serve.jobs", "embed.hash",
                "embed.neighbors", "cellcc.fused"):
        assert fam in text, fam
