"""Mesh scale-out suite (ROADMAP item 1): the collective halo-merge and
the sharded campaign path.

What is pinned here:

- the collective fixed point (parallel/halo.py) is BYTE-IDENTICAL to
  the host union-find (``graph.uf_components``) on random graphs —
  numbering included, not just component sets;
- 1/2/4/8-device forced-host-device runs of the banded, haversine, and
  sparse engines produce byte-identical labels to the single-device
  engine, with the collective merge demonstrably ACTIVE (halo.rounds
  counters) and the driver-side union-find demonstrably replaced;
- the 2-D ('parts', 'halo') mesh gives the same labels as the 1-D mesh
  (the dimension-ordered ring schedule is pure layout);
- a second same-shaped sharded run compiles ZERO new kernels (the
  ladder padding of the halo kernel's node/edge widths);
- a chip dropping out degrades to RE-SHARDING (campaign.train_resharded
  + the ``campaign`` fault site), not a dead run, with labels intact —
  the ROADMAP item 1+5 composition;
- multi-process checkpoint requests degrade gracefully (warning naming
  the campaign driver, un-checkpointed run, identical labels) instead
  of the historical hard raise;
- DBSCAN_SHAPECHECK=1 validates the halo.merge dispatch family clean
  on a live sharded run (subprocess rerun).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import Engine, obs, train
from dbscan_tpu.parallel import halo
from dbscan_tpu.parallel.graph import uf_components
from dbscan_tpu.parallel.mesh import make_mesh, make_mesh2d

pytestmark = pytest.mark.multichip


def _blobs(rng, n_per=800):
    return np.concatenate(
        [rng.normal(c, 0.5, (n_per, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-9, 11, (n_per // 2, 2))]
    )


def _geo_blobs(rng, centers, per, spread_km):
    out = []
    for lon, lat in centers:
        dlat = spread_km / 111.0
        dlon = spread_km / (111.0 * np.cos(np.deg2rad(lat)))
        out.append(
            np.stack(
                [rng.normal(lon, dlon, per), rng.normal(lat, dlat, per)],
                axis=1,
            )
        )
    return np.concatenate(out)


def _sparse_corpus(rng, k=8, per=50):
    import scipy.sparse as sp

    rows, cols, vals = [], [], []
    for c in range(k):
        feats = np.arange(c * 5, c * 5 + 5)
        for i in range(per):
            pick = rng.choice(feats, size=4, replace=False)
            ri = c * per + i
            rows += [ri] * 4
            cols += list(pick)
            vals += [1.0] * 4
    return sp.csr_matrix(
        (vals, (rows, cols)), shape=(k * per, k * 5), dtype=np.float32
    )


def _devices(k):
    import jax

    return jax.devices()[:k]


# --- unit: collective fixed point == host union-find -------------------


def test_collective_merge_matches_uf_components_random_graphs():
    """Exact (n_clusters, gid) equality on 25 random edge sets across
    1-D and 2-D meshes — numbering included (first-appearance order ==
    component-min-rank order, the halo.py docstring argument)."""
    rng = np.random.default_rng(42)
    meshes = [make_mesh(_devices(4)), make_mesh2d(_devices(8))]
    for trial in range(25):
        n = int(rng.integers(1, 400))
        e = int(rng.integers(0, 600))
        ua = rng.integers(0, n, e).astype(np.int64)
        ub = rng.integers(0, n, e).astype(np.int64)
        ref_n, ref_gid = uf_components(ua, ub, n)
        mesh = meshes[trial % len(meshes)]
        got_n, got_gid = halo.collective_merge(ua, ub, n, mesh)
        assert got_n == ref_n, trial
        np.testing.assert_array_equal(got_gid, ref_gid, err_msg=str(trial))


def test_collective_merge_empty_and_edgeless():
    mesh = make_mesh(_devices(2))
    n_c, gid = halo.collective_merge(
        np.empty(0, np.int64), np.empty(0, np.int64), 0, mesh
    )
    assert n_c == 0 and len(gid) == 0
    # edgeless nodes: every node is its own 1-based component in order
    n_c, gid = halo.collective_merge(
        np.empty(0, np.int64), np.empty(0, np.int64), 5, mesh
    )
    assert n_c == 5
    np.testing.assert_array_equal(gid, np.arange(1, 6))


# --- end-to-end label parity: 1/2/4/8 devices, three engines -----------


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
def test_banded_sharded_labels_byte_identical(ndev, rng):
    pts = _blobs(rng)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=600,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    mesh = make_mesh(_devices(ndev))
    got = train(pts, mesh=mesh, **kw)
    np.testing.assert_array_equal(ref.clusters, got.clusters)
    np.testing.assert_array_equal(ref.flags, got.flags)


def test_haversine_sharded_labels_byte_identical(rng):
    geo = _geo_blobs(
        rng,
        [(-74.0, 40.7), (-73.95, 40.75), (-73.9, 40.8), (-74.05, 40.65)],
        per=120,
        spread_km=0.25,
    )
    kw = dict(
        eps=0.3, min_points=5, max_points_per_partition=300,
        metric="haversine", neighbor_backend="banded",
    )
    ref = train(geo, **kw)
    for ndev in (2, 8):
        got = train(geo, mesh=make_mesh(_devices(ndev)), **kw)
        np.testing.assert_array_equal(ref.clusters, got.clusters, err_msg=str(ndev))
        np.testing.assert_array_equal(ref.flags, got.flags)


def test_sparse_sharded_labels_byte_identical(rng):
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan

    x = _sparse_corpus(rng)
    kw = dict(eps=0.35, min_points=5, max_points_per_partition=96)
    ref_c, ref_f = sparse_cosine_dbscan(x, **kw)
    for ndev in (2, 4, 8):
        got_c, got_f = sparse_cosine_dbscan(
            x, mesh=make_mesh(_devices(ndev)), **kw
        )
        np.testing.assert_array_equal(ref_c, got_c, err_msg=str(ndev))
        np.testing.assert_array_equal(ref_f, got_f)


def test_mesh2d_matches_mesh1d_and_single(rng):
    """The 2-D ('parts','halo') mesh is pure layout: same labels as the
    1-D mesh and the single-device run, on the banded engine."""
    pts = _blobs(rng, n_per=500)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=400,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    m1 = train(pts, mesh=make_mesh(_devices(8)), **kw)
    m2 = train(pts, mesh=make_mesh2d(_devices(8)), **kw)
    m2b = train(pts, mesh=make_mesh2d(_devices(8), shape=(2, 4)), **kw)
    for m in (m1, m2, m2b):
        np.testing.assert_array_equal(ref.clusters, m.clusters)
        np.testing.assert_array_equal(ref.flags, m.flags)


def test_mesh2d_shape_validation():
    with pytest.raises(ValueError):
        make_mesh2d(_devices(8), shape=(3, 2))


# --- the merge really is collective ------------------------------------


def test_halo_merge_active_and_counted(rng, tmp_path):
    """The sharded run routes the union through the mesh (halo.rounds/
    edges/nodes counters move), and DBSCAN_MESH_MERGE=0 restores the
    host union-find (counters still) with identical labels."""
    pts = _blobs(rng, n_per=400)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=300,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    mesh = make_mesh(_devices(8))
    obs.enable(str(tmp_path / "t.jsonl"))
    try:
        st = obs.state()
        snap = st.metrics.snapshot()
        on = train(pts, mesh=mesh, **kw)
        d1 = st.metrics.delta(snap)
        assert d1.get("halo.rounds", 0) > 0
        assert d1.get("halo.nodes", 0) > 0
        snap = st.metrics.snapshot()
        os.environ["DBSCAN_MESH_MERGE"] = "0"
        try:
            off = train(pts, mesh=mesh, **kw)
        finally:
            os.environ.pop("DBSCAN_MESH_MERGE", None)
        d2 = st.metrics.delta(snap)
        assert d2.get("halo.rounds", 0) == 0
    finally:
        obs.disable()
    np.testing.assert_array_equal(on.clusters, off.clusters)
    np.testing.assert_array_equal(on.flags, off.flags)


def test_sharded_second_run_zero_new_compiles(rng, tmp_path):
    """Compile-count pin: a second same-shaped sharded run (fresh data,
    same shapes) compiles ZERO new kernels — the halo widths ride the
    ladder like every other dispatch family."""
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=400,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    mesh = make_mesh(_devices(8))
    pts = _blobs(rng, n_per=500)
    obs.enable(str(tmp_path / "c.jsonl"))
    try:
        st = obs.state()
        train(pts, mesh=mesh, **kw)
        snap = st.metrics.snapshot()
        # same-shaped second run: jitter the values, keep the layout
        train(pts + 1e-9, mesh=mesh, **kw)
        delta = st.metrics.delta(snap)
        assert delta.get("compiles.total", 0) == 0, delta
    finally:
        obs.disable()


# --- chip drop degrades to re-sharding ---------------------------------


def test_chip_drop_resharding_labels_identical(rng, monkeypatch, tmp_path):
    """A campaign-site fault on the sharded attempt re-shards (8 -> 4
    devices) instead of killing the run; labels stay byte-identical and
    mesh.reshards counts the event."""
    from dbscan_tpu.campaign import train_resharded
    from dbscan_tpu import faults

    pts = _blobs(rng, n_per=400)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=300,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "campaign#0:TRANSIENT")
    faults.reset_registry()
    obs.enable(str(tmp_path / "r.jsonl"))
    try:
        st = obs.state()
        snap = st.metrics.snapshot()
        got = train_resharded(pts, make_mesh(_devices(8)), **kw)
        delta = st.metrics.delta(snap)
        assert delta.get("mesh.reshards", 0) == 1, delta
    finally:
        obs.disable()
        monkeypatch.delenv("DBSCAN_FAULT_SPEC", raising=False)
        faults.reset_registry()
    np.testing.assert_array_equal(ref.clusters, got.clusters)
    np.testing.assert_array_equal(ref.flags, got.flags)


def test_chip_drop_resharding_to_single_device(rng, monkeypatch):
    """Two consecutive faults walk the ladder 4 -> 2 -> 1 device; the
    single-device (mesh=None) rerun still lands identical labels."""
    from dbscan_tpu.campaign import train_resharded
    from dbscan_tpu import faults

    pts = _blobs(rng, n_per=300)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=300,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    monkeypatch.setenv(
        "DBSCAN_FAULT_SPEC", "campaign#0:TRANSIENT;campaign#1:TRANSIENT"
    )
    faults.reset_registry()
    try:
        got = train_resharded(pts, make_mesh(_devices(4)), **kw)
    finally:
        monkeypatch.delenv("DBSCAN_FAULT_SPEC", raising=False)
        faults.reset_registry()
    np.testing.assert_array_equal(ref.clusters, got.clusters)


def test_reshard_disabled_propagates(rng, monkeypatch):
    from dbscan_tpu.campaign import train_resharded
    from dbscan_tpu import faults

    pts = _blobs(rng, n_per=200)
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "campaign#0:TRANSIENT")
    monkeypatch.setenv("DBSCAN_MESH_RESHARD", "0")
    faults.reset_registry()
    try:
        with pytest.raises(faults.FatalDeviceFault):
            train_resharded(
                pts, make_mesh(_devices(2)),
                eps=0.3, min_points=6, max_points_per_partition=300,
            )
    finally:
        monkeypatch.delenv("DBSCAN_FAULT_SPEC", raising=False)
        monkeypatch.delenv("DBSCAN_MESH_RESHARD", raising=False)
        faults.reset_registry()


# --- multi-process checkpoint degrade ----------------------------------


class _MeshModProxy:
    """Proxy of parallel.mesh that reports multiprocess=True to the
    DRIVER's gates only: the real mesh helpers (shard_host_array,
    pull_to_host) keep consulting the genuine single-process state, so
    the run itself stays healthy — this isolates exactly the driver's
    multi-process control flow, the way the historical raise fired."""

    def __init__(self, real):
        self._real = real

    def multiprocess(self):
        return True

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_multiprocess_checkpoint_degrades_gracefully(
    rng, tmp_path, monkeypatch, caplog
):
    """checkpoint_dir in a (simulated) multi-process run no longer
    raises: the run completes un-checkpointed BEFORE any partition work,
    the warning names the campaign-driver alternative, and labels equal
    the plain run."""
    import logging

    from dbscan_tpu.parallel import driver as drv
    from dbscan_tpu.parallel import mesh as mesh_mod

    pts = _blobs(rng, n_per=300)
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=300,
        engine=Engine.NAIVE, neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    ck = tmp_path / "ckpt"
    ck.mkdir()
    monkeypatch.setattr(drv, "mesh_mod", _MeshModProxy(mesh_mod))
    with caplog.at_level(logging.WARNING, logger="dbscan_tpu.parallel.driver"):
        got = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(ref.clusters, got.clusters)
    np.testing.assert_array_equal(ref.flags, got.flags)
    assert not got.stats.get("resumed_from_checkpoint")
    msgs = [r.getMessage() for r in caplog.records]
    assert any("campaign" in m and "checkpoint" in m.lower() for m in msgs), msgs
    # nothing was written: the degrade happened before any partition work
    assert list(ck.iterdir()) == []


# --- collective-aware pulls under (simulated) multi-process ------------


def test_collective_engine_active_under_multiprocess(rng, monkeypatch):
    """get_engine() no longer returns None under multiprocess: the
    collective engine runs every pull at its submission point (one
    issuing thread, deterministic sequence) and stats['pull'] exists —
    the per-shard pull_overlap_ratio source the MULTICHIP capture
    stamps."""
    from dbscan_tpu.parallel import mesh as mesh_mod
    from dbscan_tpu.parallel import pipeline as pipe_mod

    monkeypatch.setattr(mesh_mod, "multiprocess", lambda: True)
    pipe_mod.reset_engine()
    try:
        eng = pipe_mod.get_engine()
        assert eng is not None and eng.collective
        order = []
        jobs = [
            eng.submit(lambda i=i: order.append(i) or i, label=f"j{i}")
            for i in range(5)
        ]
        # inline-at-submit: already done, strict submission order
        assert order == list(range(5))
        assert [eng.wait(j) for j in jobs] == list(range(5))
        # quiesce cancels nothing in collective mode
        assert eng.quiesce() == 0
        t = eng.totals()
        assert t["jobs"] == 5 and t["overlap_s"] == 0.0
    finally:
        pipe_mod.reset_engine()
        monkeypatch.undo()
        pipe_mod.reset_engine()


def test_collective_engine_fault_surfaces_at_settle(monkeypatch):
    from dbscan_tpu.parallel import mesh as mesh_mod
    from dbscan_tpu.parallel import pipeline as pipe_mod

    monkeypatch.setattr(mesh_mod, "multiprocess", lambda: True)
    pipe_mod.reset_engine()
    try:
        eng = pipe_mod.get_engine()

        def boom():
            raise RuntimeError("pull died")

        job = eng.submit(boom, label="bad")
        with pytest.raises(RuntimeError, match="pull died"):
            eng.settle(job)
    finally:
        pipe_mod.reset_engine()
        monkeypatch.undo()
        pipe_mod.reset_engine()


# --- shapecheck coverage for the new family ----------------------------


_SHAPECHECK_CHILD = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
from dbscan_tpu import Engine, train
from dbscan_tpu.lint import shapecheck
from dbscan_tpu.parallel.mesh import make_mesh
rng = np.random.default_rng(5)
pts = np.concatenate(
    [rng.normal(c, 0.5, (400, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
)
m = train(
    pts, eps=0.3, min_points=6, max_points_per_partition=300,
    engine=Engine.NAIVE, neighbor_backend="banded", mesh=make_mesh(),
)
rep = shapecheck.report()
assert rep["enabled"], rep
assert "halo.merge" in rep["sites"], sorted(rep["sites"])
assert rep["violations"] == [], rep
print("SHAPECHECK_OK", sorted(rep["sites"]))
"""


def test_shapecheck_clean_on_sharded_run(tmp_path):
    env = dict(os.environ)
    env["DBSCAN_SHAPECHECK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", _SHAPECHECK_CHILD],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "SHAPECHECK_OK" in out.stdout
