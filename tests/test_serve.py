"""dbscan_tpu/serve: resident ClusterService + multi-tenant JobBatcher.

Pins the serving contract (PARITY.md "Serving contract"):

- ingest/query label consistency vs a serial numpy oracle, at rest AND
  under genuinely concurrent ingest (every answer must exactly match
  the oracle evaluated on the epoch it reports — the seqlock pin);
- SIGTERM-mid-ingest subprocess drill: flight dump, then serve
  checkpoint, then chain — and a resumed service continues the stream
  with BYTE-IDENTICAL labels (no relabeling drift);
- the flight-recorder SIGTERM composition bugfix (dump before the
  service hook, exactly one dump, previous disposition preserved);
- admission-controller rejection at an inflated (tiny) headroom knob,
  batch splitting, and tenancy results exactly matching the per-job
  local_dbscan oracle;
- zero-recompile pins for the query path and a mixed tenant job
  stream (the ladder/ratchet discipline);
- `serve` fault-site drills (transient heals, persistent query
  degrades to the host oracle, persistent ingest marks the service
  degraded while queries keep serving);
- graftcheck worker-slice coverage of the new ingest thread and the
  DBSCAN_TSAN=1 concurrent rerun asserting a race-free report;
- serve_qps / serve_p50_ms / serve_p99_ms / tenancy_jobs_s history
  promotion + regression-gate directions, incl. the committed
  BENCH_SERVE_r01.json against the committed history.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dbscan_tpu import faults, obs
from dbscan_tpu.serve import (
    AdmissionController,
    AdmissionRejected,
    ClusterService,
    JobBatcher,
)
from dbscan_tpu.serve import query as query_mod

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_faults(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    yield
    faults.reset_registry()


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


def _blob(rng, center, n=60, s=0.25):
    return rng.normal(center, s, size=(n, 2))


def _oracle(snapshot, qpts, eps, min_points, metric="euclidean"):
    """Independent serial oracle over one published snapshot: for each
    query point, neighbors = valid skeleton rows within eps; gid = min
    neighbor id (0 if none); core = self-inclusive count reaches
    min_points."""
    spts = snapshot.spts[: snapshot.k]
    sids = snapshot.sids[: snapshot.k].astype(np.int64)
    gids = np.zeros(len(qpts), np.int64)
    core = np.zeros(len(qpts), np.int8)
    for i, q in enumerate(np.asarray(qpts, np.float64)):
        if snapshot.k:
            d2 = ((spts - q[None, :]) ** 2).sum(axis=1)
            nbr = d2 <= eps * eps
        else:
            nbr = np.zeros(0, bool)
        core[i] = np.int8(1 + int(nbr.sum()) >= min_points)
        if nbr.any():
            gids[i] = sids[nbr].min()
    return gids, core


# --- ingest/query consistency -----------------------------------------


def test_query_matches_serial_oracle(rng):
    log = []
    svc = ClusterService(
        0.6, 5, window=3, max_points_per_partition=500, snapshot_log=log
    )
    with svc:
        for c in [(0, 0), (4, 0), (0.2, 0.1)]:
            svc.submit(_blob(rng, c))
        assert svc.drain(timeout=300)
        qpts = np.concatenate(
            [_blob(rng, (0, 0), 30), rng.uniform(-30, 30, (30, 2))]
        )
        res = svc.query(qpts)
    assert res.epoch == 3
    snap = next(s for s in log if s.epoch == res.epoch)
    gids, core = _oracle(snap, qpts, 0.6, 5)
    np.testing.assert_array_equal(res.gids, gids)
    np.testing.assert_array_equal(res.core, core)
    # dense region queries actually resolve to a live cluster
    assert (res.gids[:30] > 0).all()


def test_concurrent_ingest_query_epoch_consistency(rng):
    """Queries racing a live ingest thread must each be EXACTLY the
    oracle answer for the epoch they report — the seqlock's
    never-a-half-merged-update contract."""
    log = []
    svc = ClusterService(
        0.6, 5, window=3, max_points_per_partition=500, snapshot_log=log
    )
    recorded = []
    rec_lock = threading.Lock()
    stop = threading.Event()
    qsets = [rng.uniform(-2, 6, (40, 2)) for _ in range(4)]

    def reader(qpts):
        while not stop.is_set():
            r = svc.query(qpts)
            with rec_lock:
                recorded.append((qpts, r))

    threads = [
        threading.Thread(target=reader, args=(q,), daemon=True)
        for q in qsets[:2]
    ]
    with svc:
        for t in threads:
            t.start()
        for i in range(5):
            svc.submit(_blob(rng, (i * 0.3, 0), n=120))
        assert svc.drain(timeout=300)
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert recorded
    by_epoch = {s.epoch: s for s in log}
    epochs_seen = set()
    for qpts, r in recorded:
        epochs_seen.add(r.epoch)
        if r.epoch == 0:
            assert (r.gids == 0).all()
            continue
        snap = by_epoch[r.epoch]
        gids, core = _oracle(snap, qpts, 0.6, 5)
        np.testing.assert_array_equal(r.gids, gids)
        np.testing.assert_array_equal(r.core, core)
    # the drill actually exercised concurrency: answers span epochs
    assert len(epochs_seen) > 1


def test_query_semantics_handcrafted():
    """core_flag / gid algebra on a hand-built skeleton."""
    svc = ClusterService(1.0, 3, window=2, max_points_per_partition=500)
    with svc:
        # one tight 6-point cluster at the origin
        svc.submit(
            np.array(
                [[0.0, 0.0], [0.1, 0], [0, 0.1], [0.1, 0.1], [0.05, 0],
                 [0, 0.05]]
            )
        )
        assert svc.drain(timeout=120)
        res = svc.query(
            np.array([[0.05, 0.05], [0.9, 0.0], [10.0, 10.0]])
        )
    assert res.epoch == 1
    sid = res.gids[0]
    assert sid > 0
    assert res.core[0] == 1  # 6 skeleton neighbors + self >= 3
    assert res.gids[1] == sid  # within eps of the cluster edge
    assert res.gids[2] == 0 and res.core[2] == 0  # far away: noise
    # empty-service behavior: fresh service answers epoch 0 noise
    svc2 = ClusterService(1.0, 3, max_points_per_partition=500)
    r2 = svc2.query(np.array([[0.0, 0.0]]))
    assert r2.epoch == 0 and r2.gids[0] == 0 and r2.core[0] == 0


def test_backpressure_refusal(rng):
    """A full ingest queue refuses block=False submits and counts
    them; the queue bound is the DBSCAN_SERVE_QUEUE knob surface."""
    svc = ClusterService(
        0.6, 5, max_points_per_partition=500, queue_depth=1
    )
    # NOT started: the queue can only fill
    assert svc.submit(_blob(rng, (0, 0)), block=False)
    assert not svc.submit(_blob(rng, (0, 0)), block=False)
    assert not svc.submit(_blob(rng, (0, 0)), block=True, timeout=0.05)
    with svc:
        assert svc.drain(timeout=120)
        h = svc.health()
    assert h["epoch"] == 1 and h["queue_depth"] == 0
    assert h["faults"]["attempts"] >= 0  # health shape smoke
    assert "pull" in h and "hbm_bytes_in_use" in h


# --- zero-recompile pins ----------------------------------------------


def test_query_steady_state_zero_recompile(rng):
    svc = ClusterService(0.6, 5, window=2, max_points_per_partition=500)
    with svc:
        svc.submit(_blob(rng, (0, 0), n=100))
        svc.submit(_blob(rng, (0.2, 0), n=100))
        assert svc.drain(timeout=300)
        fn = query_mod._query_builder(5, "euclidean")
        svc.query(rng.uniform(-1, 1, (64, 2)))  # warm the rung
        misses0 = fn._cache_size()
        for n in (10, 64, 37, 128, 1):  # all inside the warm rung
            svc.query(rng.uniform(-1, 1, (n, 2)))
        assert fn._cache_size() == misses0


def test_tenancy_zero_recompile_mixed_job_stream(rng):
    from dbscan_tpu.serve.tenancy import _jobs_builder

    b = JobBatcher(engine="archery", metric="euclidean")
    for n in (300, 500):
        b.submit(rng.normal(0, 1, (n, 2)), eps=0.4, min_points=4)
    b.flush()  # warm: pins the (J, S) rungs
    fn = _jobs_builder("archery", "euclidean")
    misses0 = fn._cache_size()
    # mixed sizes, eps, and min_points inside the warmed rungs
    for n, eps, mp in ((120, 0.3, 3), (480, 0.7, 6), (33, 0.2, 2)):
        b.submit(rng.normal(0, 1, (n, 2)), eps=eps, min_points=mp)
    out = b.flush()
    assert len(out) == 3
    assert fn._cache_size() == misses0


# --- tenancy: oracle parity + admission --------------------------------


def test_tenancy_results_match_local_oracle(rng):
    import jax.numpy as jnp

    from dbscan_tpu.ops.labels import seed_to_local_ids
    from dbscan_tpu.ops.local_dbscan import local_dbscan

    jobs = []
    for i in range(7):
        n = int(rng.integers(20, 200))
        c = rng.uniform(-5, 5, 2)
        pts = np.concatenate(
            [rng.normal(c, 0.2, (n // 2, 2)),
             rng.uniform(-20, 20, (n - n // 2, 2))]
        )
        jobs.append((pts, float(rng.uniform(0.3, 0.8)), int(rng.integers(2, 6))))
    b = JobBatcher(engine="archery", metric="euclidean", max_jobs=3)
    ids = [b.submit(p, eps=e, min_points=m) for p, e, m in jobs]
    results = {r.job_id: r for r in b.flush()}
    assert sorted(results) == sorted(ids)
    for jid, (pts, eps, mp) in zip(ids, jobs):
        ref = local_dbscan(
            jnp.asarray(pts), jnp.ones(len(pts), bool), eps, mp,
            engine="archery",
        )
        np.testing.assert_array_equal(
            results[jid].clusters, seed_to_local_ids(np.asarray(ref.seed_labels))
        )
        np.testing.assert_array_equal(
            results[jid].flags, np.asarray(ref.flags)
        )


def test_admission_rejects_at_inflated_knob(rng, monkeypatch):
    """The acceptance drill: a job whose FAMILY_MODELS HBM prediction
    exceeds the configured headroom is provably rejected BEFORE any
    dispatch."""
    monkeypatch.setenv("DBSCAN_SERVE_HEADROOM_BYTES", "100000")
    b = JobBatcher()
    assert b.admission.headroom == 100000
    with pytest.raises(AdmissionRejected) as ei:
        b.submit(rng.normal(0, 1, (500, 2)), eps=0.4, min_points=4)
    assert ei.value.predicted_bytes > 100000
    assert b.pending == 0  # nothing queued, nothing dispatched
    # the same job sails through at the default headroom
    b2 = JobBatcher(admission=AdmissionController(1 << 34))
    b2.submit(rng.normal(0, 1, (500, 2)), eps=0.4, min_points=4)
    assert b2.pending == 1
    # oversized-point-count rejection is admission too
    with pytest.raises(AdmissionRejected, match="DBSCAN_SERVE_JOB_SLOTS"):
        JobBatcher(max_job_points=64).submit(
            rng.normal(0, 1, (65, 2)), eps=0.4, min_points=4
        )


def test_admission_prices_the_post_ratchet_shape(rng):
    """Review regression: the ratchet floors are monotone across
    flushes, so a later tiny batch pads up to the combined (max-J,
    max-S) floor — admission must price THAT shape. When the ratcheted
    shape would breach the headroom, the batch dispatches at its own
    un-ratcheted rungs (a recompile, never un-admitted HBM), and the
    floors stay where they were."""
    adm = AdmissionController()
    # headroom fits [8, 2048] (one wide job) and [48, 128] (many tiny
    # jobs) but NOT the combined ratchet floor [48, 2048]
    headroom = max(adm.price(8, 2048, 2), adm.price(48, 128, 2))
    assert adm.price(48, 2048, 2) > headroom
    b = JobBatcher(admission=AdmissionController(headroom))
    was = obs.active()
    if not was:
        obs.enable()
    try:
        snap = obs.counters()
        # batch A: 40 tiny jobs -> ratchets serve_jobs_j to 48
        for _ in range(40):
            b.submit(rng.normal(0, 1, (60, 2)), eps=0.4, min_points=3)
        b.flush()
        # batch B: one 2000-point job -> ratchets serve_jobs_s to 2048
        b.submit(rng.normal(0, 1, (2000, 2)), eps=0.4, min_points=3)
        b.flush()
        floors_after_wide = dict(b._floors)
        # batch C: 40 tiny jobs again — the OLD bug dispatched this at
        # the never-admitted [48, 2048] combined floor
        ids = [
            b.submit(rng.normal(0, 1, (60, 2)), eps=0.4, min_points=3)
            for _ in range(40)
        ]
        out = b.flush()
        delta = obs.counters_delta(snap)
        st = obs.state()
        shapes = [
            (s.args["padded_jobs"], s.args["slots"])
            for s in st.tracer.snapshot_spans()
            if s.name == "serve.job_batch"
        ]
    finally:
        if not was:
            obs.disable()
    assert sorted(r.job_id for r in out) == sorted(ids)
    # every shape that actually dispatched was priced within headroom
    assert shapes
    for jp, sp in shapes:
        assert adm.price(jp, sp, 2) <= headroom, (jp, sp)
    # the breaching combination never ratcheted the floors further
    assert dict(b._floors) == floors_after_wide
    assert delta.get("serve.jobs_done", 0) == 81


def test_admission_price_matches_family_model():
    from dbscan_tpu.lint.shapes import FAMILY_MODELS

    adm = AdmissionController(headroom_bytes=1 << 34)
    model = FAMILY_MODELS["serve.jobs"]
    binding = {"J": 8, "S": 256, "D": 2}
    expr = model.input_expr() + model.overhead
    assert adm.price(8, 256, 2) == int(
        expr.substitute(binding).evaluate(binding)
    )
    assert adm.admit(8, 256, 2)


def test_admission_splits_batches_and_results_survive(rng):
    """A headroom that fits only small stacks: flush splits the stream
    into several admitted dispatches (serve.admit_splits) and every
    job still gets its exact result."""
    # headroom = exactly one J=8-rung stack of 256-slot jobs: the 9th
    # job would bump the J ladder to 16, doubling the price — split
    one_rung = AdmissionController().price(8, 256, 2)
    b = JobBatcher(admission=AdmissionController(one_rung))
    was = obs.active()
    if not was:
        obs.enable()
    try:
        snap = obs.counters()
        ids = [
            b.submit(rng.normal(0, 1, (150, 2)), eps=0.4, min_points=4)
            for _ in range(10)
        ]
        out = b.flush()
        delta = obs.counters_delta(snap)
    finally:
        if not was:
            obs.disable()
    assert sorted(r.job_id for r in out) == sorted(ids)
    assert delta.get("serve.job_batches", 0) == 2  # 8-job + 2-job stacks
    assert delta.get("serve.admit_splits", 0) >= 1
    assert delta.get("serve.jobs_done", 0) == 10


# --- fault drills ------------------------------------------------------


def test_serve_site_transient_query_heals(rng, monkeypatch):
    svc = ClusterService(0.6, 5, window=2, max_points_per_partition=500)
    with svc:
        svc.submit(_blob(rng, (0, 0)))
        assert svc.drain(timeout=120)
        # arm AFTER ingest so the query consumes serve#0
        _spec(monkeypatch, "serve#0:TRANSIENT")
        qpts = _blob(rng, (0, 0), 20)
        snap = faults.counters.snapshot()
        res = svc.query(qpts)
        delta = faults.counters.delta(snap)
        faults.reset_registry()
        monkeypatch.delenv("DBSCAN_FAULT_SPEC")
        ref = svc.query(qpts)
    assert delta["injected"] == 1 and delta["retries"] == 1
    np.testing.assert_array_equal(res.gids, ref.gids)
    np.testing.assert_array_equal(res.core, ref.core)


def test_serve_site_persistent_query_degrades_to_host(rng, monkeypatch):
    svc = ClusterService(0.6, 5, window=2, max_points_per_partition=500)
    with svc:
        svc.submit(_blob(rng, (0, 0)))
        assert svc.drain(timeout=120)
        _spec(monkeypatch, "serve#0:PERSISTENT")
        qpts = _blob(rng, (0, 0), 20)
        snap = faults.counters.snapshot()
        res = svc.query(qpts)  # degrades to query_host, labels intact
        delta = faults.counters.delta(snap)
        faults.reset_registry()
        monkeypatch.delenv("DBSCAN_FAULT_SPEC")
        ref = svc.query(qpts)
    assert delta["fallbacks"] == 1
    np.testing.assert_array_equal(res.gids, ref.gids)
    np.testing.assert_array_equal(res.core, ref.core)


def test_serve_site_persistent_ingest_marks_degraded(rng, monkeypatch):
    """A retries-exhausted ingest fault must not kill the server: the
    health endpoint reports the degradation, queries keep answering
    the last good epoch, and the NEXT ingest (new ordinal) heals."""
    _spec(monkeypatch, "serve#1:PERSISTENT")
    svc = ClusterService(0.6, 5, window=2, max_points_per_partition=500)
    with svc:
        svc.submit(_blob(np.random.default_rng(0), (0, 0)))  # serve#0: ok
        assert svc.drain(timeout=120)
        good = svc.health()
        svc.submit(_blob(np.random.default_rng(1), (0, 0)))  # serve#1: dies
        assert svc.drain(timeout=120)
        h = svc.health()
        res = svc.query(np.zeros((3, 2)))
        svc.submit(_blob(np.random.default_rng(2), (0, 0)))  # serve#2: ok
        assert svc.drain(timeout=120)
        h3 = svc.health()
    assert good["epoch"] == 1 and good["degraded"] is None
    assert h["epoch"] == 1  # the faulted update never published
    assert "serve#1" in h["degraded"]
    assert res.epoch == 1  # queries kept serving the last good epoch
    assert h3["epoch"] == 2  # the stream healed on the next batch


# --- checkpoint / SIGTERM ----------------------------------------------


def test_stop_checkpoint_restore_byte_identical(rng, tmp_path):
    """Orderly-shutdown resume: labels for post-restore batches are
    byte-identical to an uninterrupted stream's."""
    from dbscan_tpu.streaming import StreamingDBSCAN

    batches = [
        _blob(np.random.default_rng(100 + i), (i * 0.25, 0), n=90)
        for i in range(6)
    ]
    oracle = StreamingDBSCAN(
        0.6, 5, max_points_per_partition=500, window=2
    )
    want = [oracle.update(b) for b in batches]

    ck = str(tmp_path / "serve_ck")
    svc = ClusterService(
        0.6, 5, window=2, max_points_per_partition=500,
        checkpoint_dir=ck,
    )
    with svc:
        for b in batches[:3]:
            svc.submit(b)
        assert svc.drain(timeout=300)
    # stop() checkpointed; a NEW service resumes the identity state
    svc2 = ClusterService(
        0.6, 5, window=2, max_points_per_partition=500,
        checkpoint_dir=ck,
    )
    log = []
    svc2._snapshot_log = log
    with svc2:
        h = svc2.health()
        assert h["epoch"] == 3 and h["n_updates"] == 3
        for b in batches[3:]:
            svc2.submit(b)
        assert svc2.drain(timeout=300)
    got = [s.update for s in log if s.update is not None]
    assert len(got) == 3
    for w, g in zip(want[3:], got):
        np.testing.assert_array_equal(w.clusters, g.clusters)
        np.testing.assert_array_equal(w.flags, g.flags)
    assert got[-1].n_stream_clusters == want[-1].n_stream_clusters
    # a config change must NOT adopt the checkpoint (fingerprint gate)
    svc3 = ClusterService(
        0.7, 5, window=2, max_points_per_partition=500,
        checkpoint_dir=ck,
    )
    assert svc3.health()["epoch"] == 0


_DRILL_CHILD = r"""
import os, sys, time
import numpy as np

ck, data, out_dir, mode = sys.argv[1:5]

z = np.load(data)
batches = [z[f"b{i}"] for i in range(6)]
if mode == "oracle":
    # the uninterrupted reference stream, in the SAME subprocess
    # regime (platform/x64) as the drill legs
    from dbscan_tpu.streaming import StreamingDBSCAN

    s = StreamingDBSCAN(0.6, 5, max_points_per_partition=500, window=2)
    for i, b in enumerate(batches):
        upd = s.update(b)
        np.save(
            os.path.join(out_dir, f"labels{i}.npy"),
            np.concatenate([upd.clusters, upd.flags.astype(np.int64)]),
        )
    st = s.export_state()
    np.savez(
        os.path.join(out_dir, "final_state.npz"),
        **st["arrays"],
        n_stream=np.int64(st["scalars"]["next_id"]),
    )
    print("DONE", flush=True)
    sys.exit(0)

from dbscan_tpu.serve import ClusterService

svc = ClusterService(
    0.6, 5, window=2, max_points_per_partition=500, checkpoint_dir=ck
)
svc.start()
done = svc.health()["n_updates"]
print(f"RESUME {done}", flush=True)
if mode == "victim":
    for i in range(done, 3):
        svc.submit(batches[i])
        svc.drain()
        print(f"EPOCH {svc.health()['epoch']}", flush=True)
    # submit the 4th batch and DON'T drain: the parent SIGTERMs us
    # mid-ingest (the ingest thread is inside update #4 right now)
    svc.submit(batches[3])
    print("READY", flush=True)
    time.sleep(120)
    print("UNREACHABLE", flush=True)
else:
    for i in range(done, 6):
        svc.submit(batches[i])
        svc.drain()
        upd = svc.last_update()
        np.save(
            os.path.join(out_dir, f"labels{i}.npy"),
            np.concatenate([upd.clusters, upd.flags.astype(np.int64)]),
        )
    st = svc._stream.export_state()
    np.savez(
        os.path.join(out_dir, "final_state.npz"),
        **st["arrays"],
        n_stream=np.int64(st["scalars"]["next_id"]),
    )
    svc.stop()
print("DONE", flush=True)
"""


def test_sigterm_mid_ingest_drill_resumes_byte_identical(tmp_path):
    """THE acceptance drill: SIGTERM lands mid-ingest; the flight
    recorder dumps, the service checkpoints the last completed epoch,
    the process dies with the standard SIGTERM status — and a resumed
    service replays the remaining batches to BYTE-IDENTICAL labels and
    final identity state vs an uninterrupted oracle stream."""
    from dbscan_tpu.obs import flight

    rngs = [np.random.default_rng(200 + i) for i in range(6)]
    batches = [
        _blob(rngs[i], (i * 0.25, 0), n=90) for i in range(6)
    ]

    ck = tmp_path / "ck"
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    oracle_dir = tmp_path / "oracle"
    oracle_dir.mkdir()
    data = tmp_path / "batches.npz"
    np.savez(data, **{f"b{i}": b for i, b in enumerate(batches)})
    child = tmp_path / "child.py"
    child.write_text(_DRILL_CHILD)
    dump = tmp_path / "flight.json"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_FLIGHTREC_PATH=str(dump),
        DBSCAN_FAULT_SPEC="",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )

    # leg 0: the uninterrupted oracle stream, same subprocess regime
    proc0 = subprocess.run(
        [sys.executable, str(child), str(ck), str(data),
         str(oracle_dir), "oracle"],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env=env,
    )
    assert proc0.returncode == 0, proc0.stderr

    # leg 1: the victim — killed mid-ingest of batch #4
    proc = subprocess.Popen(
        [sys.executable, str(child), str(ck), str(data), str(out_dir),
         "victim"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env=env,
    )
    deadline = time.monotonic() + 300
    for line in proc.stdout:
        if line.startswith("READY"):
            break
        assert time.monotonic() < deadline
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    err = proc.stderr.read()
    assert rc == -signal.SIGTERM, err
    assert "UNREACHABLE" not in err

    # the recorder dumped (reason SIGTERM), THEN the service hook
    # checkpointed — both artifacts exist
    rep = flight.load(str(dump))
    assert rep["reason"] == "SIGTERM"
    assert (ck / "serve_state.npz").exists()

    # leg 2: resume — must adopt epoch >= 3 and replay the rest
    proc2 = subprocess.run(
        [sys.executable, str(child), str(ck), str(data), str(out_dir),
         "resume"],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env=env,
    )
    assert proc2.returncode == 0, proc2.stderr
    resumed_from = int(proc2.stdout.split("RESUME ", 1)[1].split()[0])
    assert resumed_from >= 3  # at least the drained epochs survived

    for i in range(resumed_from, 6):
        got = np.load(out_dir / f"labels{i}.npy")
        want = np.load(oracle_dir / f"labels{i}.npy")
        np.testing.assert_array_equal(got, want)
    final = np.load(out_dir / "final_state.npz")
    want_final = np.load(oracle_dir / "final_state.npz")
    for key in ("window_pts", "window_ids", "window_lens", "uf_parent",
                "n_stream"):
        np.testing.assert_array_equal(final[key], want_final[key])


def test_flight_sigterm_hook_composition(tmp_path):
    """The satellite bugfix pinned end to end: on SIGTERM the recorder
    dumps FIRST, the registered service hook runs SECOND (it must see
    the dump already on disk), the previous disposition still chains
    (standard -SIGTERM exit) — and exactly ONE dump is written even
    though the hook itself is on the signal path."""
    dump = tmp_path / "order.json"
    marker = tmp_path / "marker.json"
    code = (
        "import os, json, signal\n"
        f"os.environ['DBSCAN_FLIGHTREC_PATH'] = {str(dump)!r}\n"
        "from dbscan_tpu.obs import flight\n"
        "flight.ensure_env()\n"
        "calls = []\n"
        "def hook():\n"
        f"    seen = os.path.exists({str(dump)!r})\n"
        "    calls.append(seen)\n"
        f"    json.dump({{'dump_seen': seen, 'calls': len(calls)}}, "
        f"open({str(marker)!r}, 'w'))\n"
        "un = flight.on_sigterm(hook)\n"
        "un2 = flight.on_sigterm(lambda: None)\n"
        "un2()  # unregistering one hook must not lose the other\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "print('UNREACHABLE')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
    assert b"UNREACHABLE" not in proc.stdout
    m = json.load(open(marker))
    assert m == {"dump_seen": True, "calls": 1}  # dump first, hook once
    from dbscan_tpu.obs import flight

    assert flight.load(str(dump))["reason"] == "SIGTERM"


# --- graftcheck / tsan certification ----------------------------------


def test_worker_slice_models_the_serve_threads():
    """The ingest thread is a real worker root: the static model walks
    Thread(target=self._ingest_loop) into the streaming update and the
    seqlock publish, and the serve tsan sites are on the slice."""
    import dbscan_tpu.lint as lint_mod
    from dbscan_tpu.lint import races
    from dbscan_tpu.lint.core import load_package, run_rules

    pkg = load_package([os.path.join(REPO, "dbscan_tpu")])
    run_rules(pkg, (), lint_mod.RULES)
    names = {f.qualname for f in pkg.callgraph.worker_funcs()}
    for expected in (
        "dbscan_tpu.serve.service.ClusterService._ingest_loop",
        "dbscan_tpu.serve.service.ClusterService._ingest_one",
        "dbscan_tpu.serve.service.ClusterService._publish",
        "dbscan_tpu.streaming.StreamingDBSCAN.update",
        "dbscan_tpu.parallel.driver.train_arrays",
    ):
        assert expected in names, expected
    sites = races.worker_tsan_sites(pkg)
    assert {"serve.queue", "serve.state", "driver.resident_cache"} <= sites


def test_serve_tsan_rerun_race_free(tmp_path):
    """DBSCAN_TSAN=1 certification of the concurrent ingest/query
    paths: a real concurrent drive leaves an empty race report."""
    report = tmp_path / "tsan.json"
    code = (
        "import threading\n"
        "import numpy as np\n"
        "from dbscan_tpu.serve import ClusterService, JobBatcher\n"
        "rng = np.random.default_rng(0)\n"
        "svc = ClusterService(0.6, 5, window=2,"
        " max_points_per_partition=500)\n"
        "stop = threading.Event()\n"
        "def reader():\n"
        "    q = rng.uniform(-1, 3, (24, 2))\n"
        "    while not stop.is_set():\n"
        "        svc.query(q)\n"
        "threads = [threading.Thread(target=reader, daemon=True)"
        " for _ in range(2)]\n"
        "with svc:\n"
        "    [t.start() for t in threads]\n"
        "    for i in range(4):\n"
        "        svc.submit(rng.normal((i * 0.2, 0), 0.25, (80, 2)))\n"
        "    assert svc.drain(timeout=300)\n"
        "    stop.set()\n"
        "    [t.join(timeout=60) for t in threads]\n"
        "b = JobBatcher()\n"
        "for _ in range(4):\n"
        "    b.submit(rng.normal(0, 1, (64, 2)), eps=0.4, min_points=3)\n"
        "assert len(b.flush()) == 4\n"
        "print('OK')\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_TSAN="1",
        DBSCAN_TSAN_REPORT=str(report),
        DBSCAN_FAULT_SPEC="",
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
    rep = json.load(open(report))
    assert rep["races"] == []
    assert rep["lock_inversions"] == []


# --- registration / history / gate pins --------------------------------


def test_registration_pins():
    from dbscan_tpu import config
    from dbscan_tpu.lint.shapes import FAMILY_MODELS
    from dbscan_tpu.obs import schema

    assert "serve.query" in schema.COMPILE_FAMILIES
    assert "serve.jobs" in schema.COMPILE_FAMILIES
    assert "serve.query" in FAMILY_MODELS
    assert "serve.jobs" in FAMILY_MODELS
    assert "serve.health" in schema.MEMORY_SITES
    for name in (
        "serve.updates", "serve.queries", "serve.jobs_done",
        "serve.jobs_rejected", "serve.admit_splits",
        "checkpoint.serve_saves", "checkpoint.serve_loads",
    ):
        assert schema.is_declared("counter", name), name
    for name in ("serve.queue_depth", "serve.epoch",
                 "serve.resident_points"):
        assert schema.is_declared("gauge", name), name
    for name in ("serve.update", "serve.query", "serve.job_batch",
                 "checkpoint.save_serve"):
        assert schema.is_declared("span", name), name
    for name in ("serve.epoch_publish", "serve.admit_reject"):
        assert schema.is_declared("event", name), name
    for knob in (
        "DBSCAN_SERVE_QUEUE", "DBSCAN_SERVE_QUERY_SLOTS",
        "DBSCAN_SERVE_JOB_SLOTS", "DBSCAN_SERVE_BATCH_JOBS",
        "DBSCAN_SERVE_HEADROOM_BYTES",
    ):
        assert knob in config.ENV_VARS, knob


def test_serve_metric_promotion_and_directions():
    from dbscan_tpu.obs import bench_history, regress

    cap = {
        "metric": "serve",
        "backend": "cpu",
        "serve_qps": 11.5,
        "serve_p50_ms": 165.7,
        "serve_p99_ms": 375.9,
        "tenancy_jobs_s": 580.3,
        "serve_batch_period_s": 24.3,
        "serve_queries": 1400,  # not a perf key: must NOT promote
    }
    recs = bench_history.normalize_capture(cap, "t.json", "rev")
    by = {r["metric"]: r for r in recs}
    assert by["serve_qps"]["unit"] == "queries/s"
    assert by["serve_p50_ms"]["unit"] == "ms"
    assert by["tenancy_jobs_s"]["unit"] == "jobs/s"
    assert by["serve_batch_period_s"]["unit"] == "s"
    assert "serve_queries" not in by
    assert regress.direction("serve_qps") == regress.HIGHER_BETTER
    assert regress.direction("serve_p50_ms") == regress.LOWER_BETTER
    assert regress.direction("serve_p99_ms") == regress.LOWER_BETTER
    # the trap: jobs PER second must not gate as a wall
    assert regress.direction("tenancy_jobs_s") == regress.HIGHER_BETTER
    assert regress.direction("serve_batch_period_s") == regress.LOWER_BETTER

    # gate arithmetic: a halved QPS and a doubled p99 both flag
    hist = [
        {"metric": "serve_qps", "value": v, "backend": "cpu",
         "resident_hot": None, "source": f"h{i}"}
        for i, v in enumerate((10.0, 11.0, 12.0))
    ] + [
        {"metric": "serve_p99_ms", "value": v, "backend": "cpu",
         "resident_hot": None, "source": f"h{i}"}
        for i, v in enumerate((300.0, 360.0, 400.0))
    ]
    fresh = [
        {"metric": "serve_qps", "value": 4.0, "backend": "cpu",
         "resident_hot": None, "source": "f"},
        {"metric": "serve_p99_ms", "value": 1200.0, "backend": "cpu",
         "resident_hot": None, "source": "f"},
    ]
    result = regress.compare(fresh, hist, threshold=0.25)
    flagged = {e["metric"] for e in result["regressions"]}
    assert flagged == {"serve_qps", "serve_p99_ms"}
    good = [
        {"metric": "serve_qps", "value": 12.5, "backend": "cpu",
         "resident_hot": None, "source": "g"},
        {"metric": "serve_p99_ms", "value": 310.0, "backend": "cpu",
         "resident_hot": None, "source": "g"},
    ]
    assert regress.compare(good, hist, threshold=0.25)["regressions"] == []


def test_committed_serve_capture_gates_green():
    """BENCH_SERVE_r01.json is ingested into bench/history.jsonl and
    gates green against it — the committed acceptance capture."""
    from dbscan_tpu.obs import bench_history, regress

    cap_path = os.path.join(REPO, "BENCH_SERVE_r01.json")
    hist_path = os.path.join(REPO, "bench", "history.jsonl")
    assert os.path.exists(cap_path)
    recs = bench_history.parse_capture_file(cap_path)
    metrics = {r["metric"] for r in recs}
    assert {
        "serve_qps", "serve_p50_ms", "serve_p99_ms", "tenancy_jobs_s",
    } <= metrics
    history = bench_history.load_history(hist_path)
    hist_serve = [r for r in history if r["metric"] == "serve_qps"]
    assert len(hist_serve) >= 2  # enough samples for the gate to arm
    # the gate excludes same-source records — re-tag the capture as a
    # fresh run so the committed history is its baseline (exactly what
    # a post-merge `bench.py --serve` capture would see)
    recs = [{**r, "source": "fresh-check"} for r in recs]
    result = regress.compare(recs, history, threshold=0.25)
    assert result["regressions"] == []
    gated = {e["metric"] for e in result["ok"]}
    assert "serve_qps" in gated and "serve_p99_ms" in gated
    # and the acceptance inequality itself: query p50 well under the
    # streaming batch period, in the committed capture
    cap = json.load(open(cap_path))
    rows = cap["runs"] if "runs" in cap else [cap]
    for row in rows:
        assert row["serve_p50_ms"] / 1e3 < 0.5 * row["serve_batch_period_s"]


def test_analyze_serve_section_exact():
    from dbscan_tpu.obs import analyze

    spans = [
        {"name": "serve.query", "t0": 0.0, "dur": 0.010},
        {"name": "serve.query", "t0": 0.5, "dur": 0.020},
        {"name": "serve.query", "t0": 1.0, "dur": 0.030},
        {"name": "serve.query", "t0": 1.5, "dur": 0.500},
    ]
    counters = {"serve.queries": 4, "serve.updates": 2, "other": 1}
    out = analyze._serve_rollup(counters, spans)
    assert out["serve.queries"] == 4 and out["serve.updates"] == 2
    assert "other" not in out
    assert out["serve.qps"] == round(4 / 2.0, 3)  # window [0, 2.0]
    assert out["serve.query_p50_ms"] == 30.0  # nearest-rank over walls
    assert out["serve.query_p99_ms"] == 500.0
    assert "serve" in analyze.SECTIONS
    rendered = analyze.render(
        {
            "n_spans": 4,
            "dropped_spans": 0,
            "phases": [],
            "bandwidth": [],
            "resident": {"hits": 0, "misses": 0, "hot_walls_s": [],
                         "cold_walls_s": []},
            "memory": {},
            "compiles": {},
            "faults": {},
            "campaign": {},
            "serve": out,
            "devtime": {},
            "pull_check": {},
        }
    )
    assert "-- serve (resident service / tenancy) --" in rendered
    assert "serve.qps" in rendered
