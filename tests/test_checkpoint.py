"""Pre-merge checkpoint/resume (parallel/checkpoint.py).

The reference has no checkpoint of its own — it relies on Spark lineage
to recompute lost partitions (DBSCAN.scala:59-60). Our story: the flat
instance tables are persisted once the device phase completes, and a
killed run resumes straight at the merge. These tests pin:

- round-trip: second run resumes (flag in stats) and reproduces labels;
- kill/resume: a crash AFTER the checkpoint is written resumes WITHOUT
  re-running decomposition or the device phase (both are monkeypatched
  to explode on the resume run);
- fingerprint safety: changed config or data ignores the checkpoint.
"""

import numpy as np
import pytest

from dbscan_tpu import Engine, train
from dbscan_tpu.parallel import checkpoint as ckpt
from dbscan_tpu.parallel import driver


def _blobs(rng, n_per=200):
    centers = [(0, 0), (7, 7), (-6, 8), (8, -7)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (n_per, 2)) for c in centers]
    )
    rng.shuffle(pts)
    return pts


KW = dict(
    eps=0.5, min_points=5, max_points_per_partition=128,
    engine=Engine.ARCHERY,
)


def test_checkpoint_roundtrip(rng, tmp_path):
    pts = _blobs(rng)
    clean = train(pts, **KW)
    first = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert "resumed_from_checkpoint" not in first.stats
    assert (tmp_path / "premerge.npz").exists()
    assert (tmp_path / "manifest.json").exists()
    second = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert second.stats["resumed_from_checkpoint"] is True
    np.testing.assert_array_equal(second.clusters, clean.clusters)
    np.testing.assert_array_equal(second.flags, clean.flags)
    assert second.n_clusters == clean.n_clusters == 4
    # partition rectangles survive the round-trip
    assert len(second.partitions) == len(clean.partitions)
    for (i, r), (j, s) in zip(second.partitions, clean.partitions):
        assert i == j
        np.testing.assert_array_equal(r, s)


def test_kill_after_device_phase_resumes_at_merge(rng, tmp_path, monkeypatch):
    pts = _blobs(rng)
    clean = train(pts, **KW)

    # crash the first run INSIDE the merge — after the checkpoint write
    real_merge = driver.finalize_merge

    def dying_merge(*a, **kw):
        raise KeyboardInterrupt("simulated kill during merge")

    monkeypatch.setattr(driver, "finalize_merge", dying_merge)
    with pytest.raises(KeyboardInterrupt):
        train(pts, checkpoint_dir=str(tmp_path), **KW)
    monkeypatch.setattr(driver, "finalize_merge", real_merge)

    # the resume run must not touch decomposition or the device phase
    from dbscan_tpu.parallel import binning

    def explode(*a, **kw):  # pragma: no cover - failure path
        raise AssertionError("resume re-ran a pre-merge phase")

    monkeypatch.setattr(binning, "bucketize_grouped", explode)
    monkeypatch.setattr(binning, "bucketize_banded", explode)
    monkeypatch.setattr(binning, "duplicate_points", explode)
    monkeypatch.setattr(binning, "duplicate_points_grid", explode)

    resumed = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert resumed.stats["resumed_from_checkpoint"] is True
    np.testing.assert_array_equal(resumed.clusters, clean.clusters)
    np.testing.assert_array_equal(resumed.flags, clean.flags)


def test_config_change_invalidates_checkpoint(rng, tmp_path):
    pts = _blobs(rng)
    train(pts, checkpoint_dir=str(tmp_path), **KW)
    kw2 = dict(KW, eps=0.45)
    other = train(pts, checkpoint_dir=str(tmp_path), **kw2)
    assert "resumed_from_checkpoint" not in other.stats
    # and the new run OVERWROTE the checkpoint with its own state
    fp2 = ckpt.run_fingerprint(
        np.asarray(pts, dtype=np.float64),
        driver.DBSCANConfig(
            eps=0.45, min_points=5, max_points_per_partition=128,
            engine=Engine.ARCHERY,
        ).validate(),
    )
    assert ckpt.load_premerge(str(tmp_path), fp2) is not None


def test_data_change_invalidates_checkpoint(rng, tmp_path):
    pts = _blobs(rng)
    train(pts, checkpoint_dir=str(tmp_path), **KW)
    pts2 = pts.copy()
    pts2[0] += 0.001  # first row is always hashed
    other = train(pts2, checkpoint_dir=str(tmp_path), **KW)
    assert "resumed_from_checkpoint" not in other.stats


def test_torn_checkpoint_ignored(rng, tmp_path):
    pts = _blobs(rng)
    train(pts, checkpoint_dir=str(tmp_path), **KW)
    # corrupt the npz: loader must fall back to a full recompute
    (tmp_path / "premerge.npz").write_bytes(b"not a zipfile")
    clean = train(pts, **KW)
    redone = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert "resumed_from_checkpoint" not in redone.stats
    np.testing.assert_array_equal(redone.clusters, clean.clusters)


def test_truncated_npz_ignored(rng, tmp_path):
    """Truncation can keep the zip magic intact (np.load then raises
    BadZipFile, not ValueError) — still a silent recompute, not a crash."""
    pts = _blobs(rng)
    clean = train(pts, **KW)
    train(pts, checkpoint_dir=str(tmp_path), **KW)
    raw = (tmp_path / "premerge.npz").read_bytes()
    (tmp_path / "premerge.npz").write_bytes(raw[: len(raw) // 2])
    redone = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert "resumed_from_checkpoint" not in redone.stats
    np.testing.assert_array_equal(redone.clusters, clean.clusters)


def test_cross_file_torn_checkpoint_ignored(rng, tmp_path):
    """rename is atomic per FILE: a crash between the npz replace and the
    manifest replace can pair run B's arrays with run A's manifest. The
    npz-embedded fingerprint must catch the mismatch."""
    import numpy as np_

    pts = _blobs(rng)
    train(pts, checkpoint_dir=str(tmp_path), **KW)
    # simulate run B's npz landing without its manifest: rewrite the npz
    # with a different embedded fingerprint but keep A's manifest
    with np_.load(tmp_path / "premerge.npz") as z:
        arrays = {k: z[k] for k in z.files}
    arrays["_fingerprint"] = np_.array("deadbeef")
    with open(tmp_path / "premerge.npz", "wb") as f:
        np_.savez(f, **arrays)
    redone = train(pts, checkpoint_dir=str(tmp_path), **KW)
    assert "resumed_from_checkpoint" not in redone.stats


def test_checkpoint_spill_cosine(rng, tmp_path):
    """The spill-tree front-end checkpoints too (no rectangles)."""
    d = 24
    c = rng.normal(size=(6, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    data = np.repeat(c, 150, axis=0) + 0.01 * rng.normal(size=(900, d))
    kw = dict(
        eps=0.02, min_points=5, max_points_per_partition=200,
        metric="cosine",
    )
    clean = train(data, **kw)
    train(data, checkpoint_dir=str(tmp_path), **kw)
    resumed = train(data, checkpoint_dir=str(tmp_path), **kw)
    assert resumed.stats["resumed_from_checkpoint"] is True
    assert resumed.stats["spill_tree"] is True
    assert resumed.partitions == []
    np.testing.assert_array_equal(resumed.clusters, clean.clusters)


def _varied_blobs(rng):
    """Blobs at very different densities: partitions land on several
    bucket-ladder rungs, so the packer emits MULTIPLE groups (chunking
    is group-granular — one uniform group can never split)."""
    sizes = [80, 200, 500, 1200, 300, 900]
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8), (-9, -9), (16, 2)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (s, 2)) for c, s in zip(centers, sizes)]
    )
    rng.shuffle(pts)
    return pts


def test_device_phase_chunks_resume_without_redispatch(
    rng, tmp_path, monkeypatch
):
    """Resumable DEVICE phase: a run killed mid-device-work leaves its
    pulled compact chunks on disk; the resumed run re-packs, skips
    device dispatch for every group a saved chunk covers, and produces
    identical labels. (The premerge checkpoint only helps once ALL
    device work finished — chunks close the gap for worker deaths
    during it, the failure mode of the tunneled TPU at 100M points.)"""
    pts = _varied_blobs(rng)
    kw = dict(
        eps=0.5, min_points=5, max_points_per_partition=256,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    clean = train(pts, **kw)

    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)  # many chunks
    ck = tmp_path / "ck"
    first = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, first.clusters)
    chunk_files = sorted(ck.glob("p1chunk*.npz"))
    assert len(chunk_files) >= 2  # the tiny budget really chunked

    # simulate "killed before premerge was written": drop premerge so the
    # resume path must come from the chunks
    for f in ck.glob("premerge.npz"):
        f.unlink()
    for f in ck.glob("manifest.json"):
        f.unlink()

    calls = []
    real = driver._dispatch_banded_p1

    def counting(group, *a, **k):
        calls.append(group.points.shape)
        return real(group, *a, **k)

    monkeypatch.setattr(driver, "_dispatch_banded_p1", counting)
    resumed = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, resumed.clusters)
    np.testing.assert_array_equal(clean.flags, resumed.flags)
    assert calls == []  # every banded group came from a saved chunk

    # partial coverage: drop the LAST chunk -> only its groups re-dispatch
    # (the resumed run above wrote a fresh premerge — remove it again so
    # this resume exercises the chunk path, not the premerge shortcut)
    for f in ck.glob("premerge.npz"):
        f.unlink()
    for f in ck.glob("manifest.json"):
        f.unlink()
    chunk_files = sorted(ck.glob("p1chunk*.npz"))
    chunk_files[-1].unlink()
    calls.clear()
    partial = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, partial.clusters)
    assert len(calls) >= 1  # the uncovered tail really recomputed


def test_device_phase_chunk_budget_change_recomputes(
    rng, tmp_path, monkeypatch
):
    """A changed chunk budget re-forms different chunk compositions; the
    saved chunks must not be misapplied — skipped groups re-dispatch and
    labels stay exact."""
    pts = _varied_blobs(rng)
    kw = dict(
        eps=0.5, min_points=5, max_points_per_partition=256,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    clean = train(pts, **kw)
    ck = tmp_path / "ck"
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    train(pts, checkpoint_dir=str(ck), **kw)
    for f in ck.glob("premerge.npz"):
        f.unlink()
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 2048)  # new shape
    resumed = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, resumed.clusters)


def test_device_phase_eager_pull_mode(rng, tmp_path, monkeypatch):
    """DBSCAN_EAGER_PULL=1 (pull each chunk at its own flush — the
    resilience-first mode for retry loops) produces identical labels,
    and chunks do get saved."""
    pts = _varied_blobs(rng)
    kw = dict(
        eps=0.5, min_points=5, max_points_per_partition=256,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    clean = train(pts, **kw)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_EAGER_PULL", "1")
    ck = tmp_path / "ck"
    eager = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, eager.clusters)
    np.testing.assert_array_equal(clean.flags, eager.flags)
    assert len(list(ck.glob("p1chunk*.npz"))) >= 2


def _dummy_chunk(ck, fp, ci, budget=512):
    """Minimal well-formed p1chunk file at index ``ci``."""
    ckpt.save_p1_chunk(
        str(ck), fp, ci, f"sig{ci}",
        np.array([[4, 512, 8]], dtype=np.int64),
        {"combo": np.zeros(8, np.uint8), "bbits": np.zeros((1, 2), np.uint64)},
        budget=budget,
    )


def test_p1_chunk_truncated_mid_prefix_stops_load(rng, tmp_path):
    """A torn (truncated, zip magic intact) chunk file mid-prefix must
    truncate the loadable prefix THERE — never crash, never skip past
    the tear: chunks are only usable in consecutive emission order."""
    ck = tmp_path / "ck"
    for ci in range(3):
        _dummy_chunk(ck, "fp", ci)
    raw = (ck / "p1chunk0001.npz").read_bytes()
    (ck / "p1chunk0001.npz").write_bytes(raw[: len(raw) // 2])
    loaded = ckpt.load_p1_chunks(str(ck), "fp", budget=512)
    assert len(loaded) == 1  # chunk 0 only; 2 is unreachable behind the tear
    assert loaded[0]["sig"] == "sig0"
    # count_p1_chunks counts FILES (restart-point estimate for the
    # campaign harness); the verified load is the stricter gate
    assert ckpt.count_p1_chunks(str(ck)) == 3


def test_p1_chunk_budget_mismatch_rejected_outright(rng, tmp_path):
    """Chunks formed under a different slot budget cannot re-form the
    same compositions — the loader must reject the whole set, not hand
    back per-group skips that then redispatch serially."""
    ck = tmp_path / "ck"
    for ci in range(2):
        _dummy_chunk(ck, "fp", ci, budget=512)
    assert len(ckpt.load_p1_chunks(str(ck), "fp", budget=512)) == 2
    assert ckpt.load_p1_chunks(str(ck), "fp", budget=2048) == []
    # fingerprint mismatch: same outright rejection
    assert ckpt.load_p1_chunks(str(ck), "other-fp", budget=512) == []


def test_invalidate_p1_chunk_gap_semantics(rng, tmp_path):
    """invalidate_p1_chunk(ci) removes ci AND everything above it — a
    gap would make higher-index files unreachable now and actively
    dangerous later (a future leg's saves filling the gap would let
    stale survivors load as signature-mismatched placeholders)."""
    ck = tmp_path / "ck"
    for ci in range(4):
        _dummy_chunk(ck, "fp", ci)
    ckpt.invalidate_p1_chunk(str(ck), 1)
    assert sorted(p.name for p in ck.glob("p1chunk*.npz")) == [
        "p1chunk0000.npz"
    ]
    assert ckpt.count_p1_chunks(str(ck)) == 1
    # pre-existing gap: invalidation still clears every file >= ci
    _dummy_chunk(ck, "fp", 1)
    _dummy_chunk(ck, "fp", 3)  # gap at 2
    ckpt.invalidate_p1_chunk(str(ck), 1)
    assert sorted(p.name for p in ck.glob("p1chunk*.npz")) == [
        "p1chunk0000.npz"
    ]
    # invalidating a missing dir is a no-op, not a crash
    ckpt.invalidate_p1_chunk(str(tmp_path / "nope"), 0)


def test_progress_merge_survives_concurrent_writers(tmp_path):
    """The lost-update race note_abort had: progress.json is a
    read-modify-write shared by the driver's plan write, the abort
    merge, the chunk-save counter bump, and (now) N campaign workers.
    All writes merge under the progress file lock, so concurrent
    writers with disjoint fields can never silently drop each other's
    updates."""
    import threading

    ck = str(tmp_path)
    n_threads, n_rounds = 8, 25
    errors = []

    def writer(i):
        try:
            for r in range(n_rounds):
                ckpt.write_progress(ck, **{f"field_{i}": r})
                ckpt.bump_progress(ck, "counter")
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(i,))
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    prog = ckpt.read_progress(ck)
    # no field lost to a concurrent read-modify-write
    for i in range(n_threads):
        assert prog[f"field_{i}"] == n_rounds - 1
    # and the shared counter saw every single bump
    assert prog["counter"] == n_threads * n_rounds


def test_note_abort_merges_with_plan_fields(tmp_path):
    """note_abort must never drop the plan totals a prior write_progress
    landed (and vice versa) — the driver writes chunks_total minutes
    before an abort merges its site in."""
    ck = str(tmp_path)
    ckpt.write_progress(ck, chunks_total=7, planned_groups=12)
    ckpt.note_abort(ck, aborted_site="banded", aborted_ordinal=3)
    prog = ckpt.read_progress(ck)
    assert prog["chunks_total"] == 7
    assert prog["planned_groups"] == 12
    assert prog["aborted_site"] == "banded"
    # a later plan write keeps the abort breadcrumb too (merge, not
    # replace — readers treat aborted_* as "most recent abort")
    ckpt.write_progress(ck, chunks_total=7)
    assert ckpt.read_progress(ck)["aborted_site"] == "banded"


def test_save_p1_chunk_bumps_monotone_write_counter(tmp_path):
    """Every chunk save bumps the sidecar's chunks_written counter —
    including in-place OVERWRITES of an existing index, which is
    exactly the resumed-leg progress a bare file count cannot see (the
    stall detector's signal, bench.py/campaign.py)."""
    ck = str(tmp_path)
    assert ckpt.read_progress(ck).get(ckpt.PROGRESS_WRITE_COUNTER) is None
    for _ in range(2):  # second save overwrites chunk 0 in place
        _dummy_chunk(ck, "fp", 0)
    _dummy_chunk(ck, "fp", 1)
    prog = ckpt.read_progress(ck)
    assert prog[ckpt.PROGRESS_WRITE_COUNTER] == 3
    assert ckpt.count_p1_chunks(ck) == 2


def test_p1_chunk_indices_gaps_and_validation(tmp_path):
    """p1_chunk_indices (the campaign lease queue's banked-chunk scan)
    returns ALL matching indices — gaps allowed — and skips files from
    a different fingerprint/budget or torn files."""
    ck = str(tmp_path)
    _dummy_chunk(ck, "fp", 0)
    _dummy_chunk(ck, "fp", 3)  # gap at 1, 2
    _dummy_chunk(ck, "other", 1)  # wrong fingerprint
    _dummy_chunk(ck, "fp", 2, budget=2048)  # wrong budget
    raw = (tmp_path / "p1chunk0003.npz").read_bytes()
    (tmp_path / "p1chunk0004.npz").write_bytes(raw[: len(raw) // 2])
    assert ckpt.p1_chunk_indices(ck, "fp", budget=512) == [0, 3]
    assert ckpt.p1_chunk_indices(str(tmp_path / "nope"), "fp") == []


def test_device_phase_sig_divergence_rechunks(rng, tmp_path, monkeypatch):
    """A saved chunk whose composition signature no longer matches (a
    stale/corrupt checkpoint) must NOT be adopted: its groups re-enter
    the normal budgeted chunking (r4 rotation machinery), labels stay
    exact, and the stale file is invalidated so future legs' prefix
    load truncates instead of re-diverging every resume."""
    pts = _varied_blobs(rng)
    kw = dict(
        eps=0.5, min_points=5, max_points_per_partition=256,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    clean = train(pts, **kw)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    ck = tmp_path / "ck"
    train(pts, checkpoint_dir=str(ck), **kw)
    for f in ck.glob("premerge.npz"):
        f.unlink()
    for f in ck.glob("manifest.json"):
        f.unlink()
    n_chunks = len(list(ck.glob("p1chunk*.npz")))
    assert n_chunks >= 2

    # poison every saved sig: each placeholder must take the divergence
    # path (re-chunk + invalidate), never adopt stale artifacts
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    real_load = ckpt_mod.load_p1_chunks

    def poisoned(*a, **k):
        out = real_load(*a, **k)
        for lc in out:
            lc["sig"] = "poisoned-" + lc["sig"][:8]
        return out

    monkeypatch.setattr(ckpt_mod, "load_p1_chunks", poisoned)
    resumed = train(pts, checkpoint_dir=str(ck), **kw)
    np.testing.assert_array_equal(clean.clusters, resumed.clusters)
    np.testing.assert_array_equal(clean.flags, resumed.flags)
