"""Pipelined pull engine (dbscan_tpu/parallel/pipeline.py).

Pins, per the PR acceptance bar:

- pipeline/serial LABEL-FOR-LABEL equivalence on every engine family
  (banded, dense, cosine spill, streaming) — ``DBSCAN_PULL_PIPELINE=0``
  restores the serial pull paths, so both settings must produce exactly
  the same clusters and flags;
- bounded inflight: the engine never starts more jobs than
  ``DBSCAN_PULL_INFLIGHT`` (and never exceeds the
  ``DBSCAN_PULL_INFLIGHT_BYTES`` budget beyond one job), pinned as a
  property of the engine itself;
- fault injection mid-pull (``pull#N`` clauses in ``DBSCAN_FAULT_SPEC``):
  a transient pull fault retries ON the worker and keeps label parity; a
  persistent one aborts with completed chunks' artifacts banked and
  ``checkpoint.note_abort`` recording the ``pull`` site — and the healed
  resume completes from them;
- determinism: chunk completion order (pipeline depth) does not affect
  the merged labels.
"""

import threading
import time

import numpy as np
import pytest

from dbscan_tpu import Engine, faults, train
from dbscan_tpu.parallel import driver
from dbscan_tpu.parallel import pipeline as pipe_mod

pytestmark = pytest.mark.pipeline


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    """Every test starts with a virgin fault registry and no process
    engine left over from another test's env (the engine is keyed on
    the pull knobs; dropping it forces a clean rebuild)."""
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    pipe_mod.reset_engine()
    yield
    faults.reset_registry()
    pipe_mod.reset_engine()


def _blobs(seed=0):
    rng = np.random.default_rng(seed)
    sizes = [80, 200, 500, 1200, 300, 900]
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8), (-9, -9), (16, 2)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (s, 2)) for c, s in zip(centers, sizes)]
    )
    rng.shuffle(pts)
    return pts


def _cosine_rows(seed=3, k=6, per=150, d=24):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    x = np.repeat(centers, per, axis=0)
    x += 0.01 * rng.normal(size=x.shape).astype(np.float32)
    return x


KW_BANDED = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="banded",
)
KW_DENSE = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="dense",
)


def _assert_parity(a, b):
    np.testing.assert_array_equal(a.clusters, b.clusters)
    np.testing.assert_array_equal(a.flags, b.flags)


# --- engine-level properties ------------------------------------------


def test_engine_runs_jobs_in_order_and_returns_results():
    eng = pipe_mod.PullEngine(inflight=3)
    try:
        seen = []
        jobs = [
            eng.submit(lambda i=i: seen.append(i) or i * i, label=f"j{i}")
            for i in range(16)
        ]
        out = [eng.wait(j) for j in jobs]
        assert out == [i * i for i in range(16)]
        assert seen == list(range(16))  # strict submission order
        t = eng.totals()
        assert t["jobs"] == 16 and t["busy_s"] >= 0.0
    finally:
        eng.close()


def test_engine_reraises_at_wait_site():
    eng = pipe_mod.PullEngine(inflight=2)
    try:
        ok = eng.submit(lambda: "fine")
        boom = eng.submit(lambda: (_ for _ in ()).throw(ValueError("x")))
        after = eng.submit(lambda: "still runs")
        assert eng.wait(ok) == "fine"
        with pytest.raises(ValueError, match="x"):
            eng.wait(boom)
        # a failed job never blocks later jobs (ordering, not fate,
        # is what the pipeline guarantees)
        assert eng.wait(after) == "still runs"
    finally:
        eng.close()


def test_engine_bounded_inflight_depth():
    """Property: started-but-unfinished jobs never exceed the depth.
    The first job blocks, so everything the worker is ALLOWED to start
    ahead gets started; the peak must be exactly the configured depth."""
    eng = pipe_mod.PullEngine(inflight=2, inflight_bytes=1 << 40)
    gate = threading.Event()
    started = []

    def mk(i):
        return eng.submit(
            lambda: gate.wait(5),
            on_start=lambda i=i: started.append(i),
            bytes_hint=10,
            label=f"b{i}",
        )

    jobs = [mk(i) for i in range(8)]
    try:
        deadline = time.time() + 5
        while len(started) < 2 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)  # give the worker a chance to (wrongly) overrun
        assert len(started) == 2  # depth bound: job 0 executing, 1 ahead
        gate.set()
        for j in jobs:
            eng.wait(j)
        assert eng.totals()["inflight_peak"] <= 2
        assert started == list(range(8))  # starts follow submission order
    finally:
        gate.set()
        eng.close()


def test_engine_bounded_inflight_bytes():
    """Byte budget: a second job whose hint would exceed the budget is
    not started while the first is in flight — but a single oversized
    job always runs (alone), so no budget can deadlock the pipeline."""
    eng = pipe_mod.PullEngine(inflight=8, inflight_bytes=100)
    gate = threading.Event()
    started = []
    jobs = [
        eng.submit(
            lambda: gate.wait(5),
            on_start=lambda i=i: started.append(i),
            bytes_hint=60,
            label=f"b{i}",
        )
        for i in range(4)
    ]
    try:
        deadline = time.time() + 5
        while not started and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)
        assert started == [0]  # 60 + 60 > 100: second not started
        gate.set()
        for j in jobs:
            eng.wait(j)
        # oversized single job: runs alone despite hint > budget
        big = eng.submit(lambda: "ran", bytes_hint=10**9)
        assert eng.wait(big) == "ran"
    finally:
        gate.set()
        eng.close()


def test_engine_drain_settles_all_jobs_without_consuming():
    """drain() blocks until every submitted job finished, but does NOT
    consume results or swallow errors — wait() after a drain returns
    instantly with the stored result/exception."""
    eng = pipe_mod.PullEngine(inflight=2)
    try:
        jobs = [eng.submit(lambda i=i: i + 1) for i in range(6)]
        bad = eng.submit(lambda: (_ for _ in ()).throw(RuntimeError("kept")))
        eng.drain()
        assert all(j.done for j in jobs) and bad.done
        assert [eng.wait(j) for j in jobs] == list(range(1, 7))
        with pytest.raises(RuntimeError, match="kept"):
            eng.wait(bad)
    finally:
        eng.close()


def test_engine_quiesce_cancels_pending_jobs():
    eng = pipe_mod.PullEngine(inflight=1)
    gate = threading.Event()
    entered = threading.Event()
    ran = []

    def first_work():
        entered.set()
        gate.wait(5)
        ran.append(0)

    first = eng.submit(first_work)
    rest = [eng.submit(lambda i=i: ran.append(i)) for i in range(1, 6)]
    assert entered.wait(5)  # the first job is executing (and blocked)
    # quiesce cancels everything not yet executing, then waits for the
    # executing job — release the gate from a side thread so the wait
    # can complete
    dropped = [None]
    t = threading.Thread(target=lambda: dropped.__setitem__(
        0, eng.quiesce()))
    t.start()
    deadline = time.time() + 5
    while not all(j.cancelled for j in rest) and time.time() < deadline:
        time.sleep(0.01)
    assert all(j.cancelled for j in rest)  # none of them ever ran
    gate.set()
    t.join(timeout=5)
    assert dropped[0] == len(rest)
    for j in rest:
        assert eng.wait(j) is None  # record untouched, no error
    eng.wait(first)
    assert ran == [0]  # the executing job always finishes; cancelled
    eng.close()  # jobs never run


def test_get_engine_respects_off_switch(monkeypatch):
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "0")
    assert pipe_mod.get_engine() is None
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    monkeypatch.setenv("DBSCAN_PULL_INFLIGHT", "3")
    eng = pipe_mod.get_engine()
    assert eng is not None and eng.inflight == 3
    # same knobs -> same engine; changed knobs -> rebuilt
    assert pipe_mod.get_engine() is eng
    monkeypatch.setenv("DBSCAN_PULL_INFLIGHT", "5")
    eng2 = pipe_mod.get_engine()
    assert eng2 is not eng and eng2.inflight == 5


# --- pipeline/serial label equivalence, all engine families -----------


@pytest.mark.parametrize(
    "kw",
    [KW_BANDED, KW_DENSE],
    ids=["banded", "dense"],
)
def test_pipeline_serial_label_parity(monkeypatch, kw):
    pts = _blobs()
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "0")
    serial = train(pts, **kw)
    assert "pull" not in serial.stats  # serial path reports no engine
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    piped = train(pts, **kw)
    _assert_parity(piped, serial)
    assert piped.stats["pull"]["jobs"] > 0


def test_pipeline_serial_label_parity_cosine(monkeypatch):
    x = _cosine_rows()
    kw = dict(
        eps=0.02, min_points=5, max_points_per_partition=128,
        metric="cosine",
    )
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "0")
    serial = train(x, **kw)
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    piped = train(x, **kw)
    _assert_parity(piped, serial)


def test_pipeline_serial_label_parity_streaming(monkeypatch):
    from dbscan_tpu.streaming import StreamingDBSCAN

    def batches():
        r = np.random.default_rng(7)
        for i in range(3):
            c = np.array([[0.0, 0.0], [5.0, 5.0]]) + i * 0.1
            yield np.concatenate(
                [r.normal(c[0], 0.3, (150, 2)), r.normal(c[1], 0.3, (150, 2))]
            )

    def run():
        s = StreamingDBSCAN(
            eps=0.5, min_points=5, max_points_per_partition=128
        )
        return [s.update(b) for b in batches()]

    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "0")
    serial = run()
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    piped = run()
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a.clusters, b.clusters)
        np.testing.assert_array_equal(a.flags, b.flags)
    # the per-update stats carry the whole-update pull delta
    assert all("pull" in u.stats for u in piped)
    assert sum(u.stats["pull"]["jobs"] for u in piped) > 0


def test_chunk_completion_order_does_not_affect_labels(monkeypatch):
    """Determinism: pipeline depth (how far transfers run ahead, hence
    chunk COMPLETION order vs the host algebra) must not change merged
    labels. Small chunk budget -> many chunks so depth matters."""
    pts = _blobs()
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "0")
    ref = train(pts, **KW_BANDED)
    for depth in ("1", "3", "8"):
        monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
        monkeypatch.setenv("DBSCAN_PULL_INFLIGHT", depth)
        out = train(pts, **KW_BANDED)
        _assert_parity(out, ref)
        assert out.stats["pull"]["jobs"] >= 3  # many chunks really rode it


def test_inflight_gauge_bounded_in_real_run(monkeypatch):
    """End-to-end property: the pull.inflight gauge a pipelined train
    leaves behind never exceeded the configured depth (the engine peak
    is recorded continuously, so the peak pin covers the whole run)."""
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    monkeypatch.setenv("DBSCAN_PULL_INFLIGHT", "2")
    train(_blobs(), **KW_BANDED)
    eng = pipe_mod.get_engine()
    assert eng is not None
    assert 1 <= eng.totals()["inflight_peak"] <= 2


# --- fault injection mid-pull -----------------------------------------


def test_transient_pull_fault_retries_on_worker(monkeypatch):
    """A pull#N TRANSIENT clause fires inside the pipelined pull job:
    faults.supervised retries it ON the worker (the job re-enters the
    pipeline, not the raw call) and the run completes with labels equal
    to the fault-free run."""
    pts = _blobs()
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    clean = train(pts, **KW_BANDED)
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "pull#1:TRANSIENT*2")
    faults.reset_registry()
    faulted = train(pts, **KW_BANDED)
    _assert_parity(faulted, clean)
    fa = faulted.stats["faults"]
    assert fa["retries"] == 2 and fa["injected"] == 2


def test_persistent_pull_fault_banks_chunks_and_resumes(
    tmp_path, monkeypatch
):
    """A persistent mid-pull fault aborts the run, but chunks whose
    pipelined pulls completed are banked (persisted) and the abort site
    is recorded as ``pull`` — then a healed resume completes from them
    with full label parity."""
    pts = _blobs()
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    clean = train(pts, **KW_BANDED)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    ck = tmp_path / "ck"
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "pull#1:PERSISTENT")
    faults.reset_registry()
    with pytest.raises(faults.FatalDeviceFault) as ei:
        train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    assert ei.value.site == "pull"
    assert len(list(ck.glob("p1chunk*.npz"))) >= 1  # chunk 0 banked

    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    prog = ckpt_mod.read_progress(str(ck))
    assert prog["aborted_site"] == "pull"

    monkeypatch.delenv("DBSCAN_FAULT_SPEC")
    faults.reset_registry()
    resumed = train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    _assert_parity(resumed, clean)


def test_pull_site_supervision_is_opt_in(monkeypatch):
    """Specs that do not name the pull site must not have their global
    (``*``) ordinal stream shifted by pull jobs: the pipelined pull
    wraps in faults.supervised only when a pull clause is active."""
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:TRANSIENT")
    faults.reset_registry()
    assert not faults.pull_site_active()
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "pull#0:TRANSIENT")
    faults.reset_registry()
    assert faults.pull_site_active()
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "banded#1:TRANSIENT")
    faults.reset_registry()
    snap = faults.counters.snapshot()
    out = train(_blobs(), **KW_BANDED)
    # no pull ordinals were consumed: the run's supervised attempts are
    # exactly the dispatch-site ones (1 injected retry), so the global
    # ordinal stream existing * specs rely on is unchanged
    assert out.stats["faults"]["injected"] == 1
    assert faults.counters.delta(snap)["attempts"] == out.stats[
        "faults"
    ]["attempts"]


# --- stats surface ----------------------------------------------------


def test_pull_stats_shape(monkeypatch):
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    out = train(_blobs(), **KW_BANDED)
    p = out.stats["pull"]
    assert set(p) == {
        "jobs", "wait_s", "busy_s", "overlap_s", "bytes", "overlap_ratio",
    }
    assert p["jobs"] > 0 and p["busy_s"] >= 0.0
    assert 0.0 <= p["overlap_ratio"] <= 1.0
