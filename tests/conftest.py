"""Test fixture: force a virtual 8-device CPU mesh BEFORE jax is imported.

This is our stand-in for the reference's `local[2]` in-process SparkContext
(MLlibTestSparkContext.scala:28-41): real shardings and collectives, one host,
no TPU pod needed. Must run before any test module imports jax."""

import os
import sys
import tempfile

# The always-on flight recorder (dbscan_tpu/obs/flight.py) dumps a
# postmortem on every retries-exhausted abort — which the fault-injection
# suites trigger on purpose, dozens of times. Point the default dump path
# at a per-process temp file so test runs never litter the working tree;
# tests that assert on dumps set their own DBSCAN_FLIGHTREC_PATH.
os.environ.setdefault(
    "DBSCAN_FLIGHTREC_PATH",
    os.path.join(tempfile.gettempdir(), f"dbscan_flightrec_{os.getpid()}.json"),
)

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# This environment ships a sitecustomize that force-registers the axon TPU
# plugin and sets JAX_PLATFORMS=axon; the env var alone cannot win, so pin the
# platform through jax.config before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")
# Parity tests compare eps-boundary decisions against the reference's float64
# JVM arithmetic; enable x64 so CPU test runs can use f64.
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


REFERENCE_CSV = "/root/reference/src/test/resources/labeled_data.csv"


def reference_fixture_available() -> bool:
    return os.path.exists(REFERENCE_CSV)


def load_reference_fixture():
    """Load the reference's 749-point golden fixture (x, y, label) at test
    time from the read-only reference mount — never copied into this repo."""
    data = np.loadtxt(REFERENCE_CSV, delimiter=",", dtype=np.float64)
    return data[:, :2], data[:, 2]
