"""Metric spill partitioning (parallel/spill.py): the coverage contract,
pivot hygiene, and degradation behavior — unit-level, no kernels."""

import numpy as np

from dbscan_tpu.parallel.spill import spill_partition


def _leaf_sets(part_ids, point_idx, n_parts):
    return [
        set(point_idx[part_ids == p].tolist()) for p in range(n_parts)
    ]


def test_coverage_contract_fuzz(rng):
    """THE correctness property: every pair within halo chord distance
    shares at least one leaf — fuzzed over random cluster layouts."""
    for trial in range(5):
        d = int(rng.integers(4, 40))
        k = int(rng.integers(3, 10))
        c = rng.normal(size=(k, d))
        c /= np.linalg.norm(c, axis=1, keepdims=True)
        pts = np.repeat(c, 80, axis=0) + 0.05 * rng.normal(
            size=(k * 80, d)
        )
        pts /= np.linalg.norm(pts, axis=1, keepdims=True)
        halo = 0.25
        part_ids, point_idx, n_parts, home_of = spill_partition(
            pts, maxpp=60, halo=halo, seed=trial
        )
        leaves = _leaf_sets(part_ids, point_idx, n_parts)
        # membership per point for the pair check
        member = [set() for _ in range(len(pts))]
        for li, s in enumerate(leaves):
            for p in s:
                member[p].add(li)
        chord = np.linalg.norm(
            pts[:, None, :] - pts[None, :, :], axis=-1
        )
        close_i, close_j = np.nonzero(chord <= halo)
        for i, j in zip(close_i, close_j):
            assert member[i] & member[j], (
                f"trial {trial}: pair ({i},{j}) at chord "
                f"{chord[i, j]:.3f} <= {halo} shares no leaf"
            )
        # every point homed exactly once, in a leaf that contains it
        assert (home_of >= 0).all()
        for p, h in enumerate(home_of):
            assert p in leaves[h]


def test_instance_layout_partition_major(rng):
    pts = rng.normal(size=(500, 8))
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    part_ids, point_idx, n_parts, _ = spill_partition(
        pts, maxpp=100, halo=0.2, seed=0
    )
    assert (np.diff(part_ids) >= 0).all()  # partition-major
    for p in range(n_parts):  # point-sorted within each partition
        sl = point_idx[part_ids == p]
        assert (np.diff(sl) > 0).all()


def test_degenerate_identical_points():
    pts = np.tile([[0.6, 0.8]], (300, 1))
    part_ids, point_idx, n_parts, home_of = spill_partition(
        pts, maxpp=50, halo=0.1, seed=0
    )
    assert n_parts == 1  # unsplittable: one oversized leaf
    assert len(point_idx) == 300
    assert (home_of == 0).all()


def test_empty():
    part_ids, point_idx, n_parts, home_of = spill_partition(
        np.empty((0, 4)), maxpp=10, halo=0.1
    )
    assert n_parts == 0 and len(part_ids) == 0 and len(home_of) == 0


def test_cosine_spill_on_mesh(rng):
    """Spill-partitioned cosine fans out over the device mesh like the
    grid path: labels identical to the single-device run."""
    from dbscan_tpu import train
    from dbscan_tpu.parallel.mesh import make_mesh

    d = 24
    c = rng.normal(size=(8, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    data = np.repeat(c, 150, axis=0) + 0.01 * rng.normal(size=(1200, d))
    kw = dict(
        eps=0.02, min_points=6, max_points_per_partition=200,
        metric="cosine",
    )
    m0 = train(data, **kw)
    assert m0.stats["n_partitions"] >= 8
    m1 = train(data, mesh=make_mesh(), **kw)
    np.testing.assert_array_equal(m0.clusters, m1.clusters)
    np.testing.assert_array_equal(m0.flags, m1.flags)
    assert m0.n_clusters == 8


def _topic_csr(rng, n, d, k, nnz_center=40, noise_density=3):
    """Concentrated sparse topics: k random centers, n/k docs each, tiny
    per-doc random noise — the regime where every cross distance is
    ~equal and the pivot tree cannot split (clusters >> pivots)."""
    import scipy.sparse as sp

    centers = sp.random(
        k, d, density=nnz_center / d, random_state=int(rng.integers(1e6)),
        format="csr", dtype=np.float64,
    )
    rows = centers[np.repeat(np.arange(k), n // k)]
    noise = sp.random(
        n, d, density=noise_density / d,
        random_state=int(rng.integers(1e6)), format="csr",
        dtype=np.float64,
    )
    return (rows + 0.05 * noise).tocsr(), np.repeat(np.arange(k), n // k)


def test_prefix_components_split_concentration_regime(rng):
    """clusters >> _MAX_PIVOTS on concentrated sparse data: the verified
    prefix-filter components split what the pivot tree cannot — exact
    recovery with ZERO duplication (components are exact covers)."""
    import scipy.sparse as sp

    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan
    from dbscan_tpu.parallel.spill import (
        _MAX_PIVOTS,
        chord_halo,
        prefix_components,
        spill_partition,
    )
    from dbscan_tpu.utils.ari import adjusted_rand_index

    k = _MAX_PIVOTS + 58  # 250 clusters > 192 pivots
    n, d = 5000, 8000
    x, truth = _topic_csr(rng, n, d, k)
    norms = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
    xu = (sp.diags(1.0 / norms) @ x).tocsr()
    halo = chord_halo(0.05, 1e-4, dim=50)

    pc = prefix_components(xu, 1.0 - halo * halo / 2.0)
    assert pc is not None
    comp, n_comp = pc
    assert n_comp == k
    # components = topics exactly
    for c in range(n_comp):
        assert len(np.unique(truth[comp == c])) == 1

    pid, pidx, n_parts, home = spill_partition(xu, 512, halo)
    assert len(pid) == n  # zero duplication
    assert n_parts >= 2

    c_out, f_out = sparse_cosine_dbscan(
        x, eps=0.05, min_points=5, max_points_per_partition=512
    )
    assert len(np.unique(c_out[c_out > 0])) == k
    assert adjusted_rand_index(c_out, truth) == 1.0


def test_prefix_components_verified_edges_only():
    """A shared feature INSIDE both prefixes must still not union docs
    whose actual dot is below t — the percolation hazard the
    verification pass exists for (a blind share-a-prefix-feature union
    would merge these)."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel.spill import prefix_components

    # shared feature 9 (df=2) carries v^2 = 0.5 in both docs; unique
    # features (df=1, rarer -> earlier in the global order) carry the
    # rest. At t = 0.6: tail at feature 9 is 0.5 >= t^2*(1-eps) in both
    # docs, so 9 sits in BOTH prefixes and the candidate pair IS
    # generated — but dot = 0.5 < 0.6, so verification rejects it.
    d = 10
    row_a = np.zeros(d)
    row_a[0] = np.sqrt(0.3)
    row_a[1] = np.sqrt(0.2)
    row_a[9] = np.sqrt(0.5)
    row_b = np.zeros(d)
    row_b[2] = np.sqrt(0.3)
    row_b[3] = np.sqrt(0.2)
    row_b[9] = np.sqrt(0.5)
    x = sp.csr_matrix(np.stack([row_a, row_b]))
    pc = prefix_components(x, 0.6)
    assert pc is not None
    comp, n_comp = pc
    assert n_comp == 2  # candidate generated, verification rejected
    # sanity: at a threshold the pair DOES meet, they union
    pc2 = prefix_components(x, 0.4)
    assert pc2 is not None and pc2[1] == 1


def test_prefix_components_budget_bail(rng):
    """Stopword-heavy prefixes exceed the pair budget -> None (pivot-tree
    fallback), never a blowup."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel import spill

    n, d = 400, 50
    dense = 0.9 * rng.random((n, d)) + 0.1  # every feature ~every doc
    x = sp.csr_matrix(dense)
    x = sp.diags(1.0 / np.linalg.norm(dense, axis=1)) @ x
    old = spill._PREFIX_PAIR_BUDGET
    try:
        spill._PREFIX_PAIR_BUDGET = 4
        assert spill.prefix_components(x.tocsr(), 0.5) is None
    finally:
        spill._PREFIX_PAIR_BUDGET = old


def test_prefix_components_blocked_expansion_matches(rng, monkeypatch):
    """Row-band pair expansion (oversized groups + tiny chunk budget)
    must reach the same components as the one-shot triu path."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel import spill

    x, _ = _topic_csr(rng, 600, 2000, 20)
    norms = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
    xu = (sp.diags(1.0 / norms) @ x).tocsr()
    t = 1.0 - spill.chord_halo(0.05, 1e-4, dim=40) ** 2 / 2.0
    ref = spill.prefix_components(xu, t)
    monkeypatch.setattr(spill, "_PREFIX_CHUNK", 64)  # forces banding
    blk = spill.prefix_components(xu, t)
    assert ref is not None and blk is not None
    assert ref[1] == blk[1]
    np.testing.assert_array_equal(ref[0], blk[0])


def test_prefix_retry_inside_pivot_tree(rng, monkeypatch):
    """When the cheap-budget pre-split bails AND the pivot tree cannot
    split (concentration regime), the tree retries prefix components at
    the elevated budget instead of emitting an oversized leaf."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel import spill

    k = spill._MAX_PIVOTS + 58
    n, d = 5000, 8000
    x, truth = _topic_csr(rng, n, d, k)
    norms = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
    xu = (sp.diags(1.0 / norms) @ x).tocsr()
    halo = spill.chord_halo(0.05, 1e-4, dim=50)

    monkeypatch.setattr(spill, "_PREFIX_PAIR_BUDGET", 0)  # force the bail
    pid, pidx, n_parts, home = spill.spill_partition(xu, 512, halo)
    assert n_parts >= 2  # retry split it — no oversized leaf
    assert len(pid) == n  # components: zero duplication


def _dense_blobs(rng, k, per, d, sigma, n_noise=0):
    """k tight unit-sphere blobs at random directions (+ optional
    random-direction noise rows): the dense concentration regime —
    every cross-blob chord ~sqrt(2)."""
    c = rng.normal(size=(k, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    truth = np.repeat(np.arange(k), per)
    pts = c[truth] + sigma * rng.normal(size=(k * per, d))
    if n_noise:
        pts = np.concatenate([pts, rng.normal(size=(n_noise, d))])
        truth = np.concatenate([truth, np.full(n_noise, -1)])
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    return pts.astype(np.float32), truth


def test_leader_components_split_dense_concentration(rng):
    """clusters >> _MAX_PIVOTS on concentrated DENSE data: leader-cover
    components split what the pivot tree cannot (the dense counterpart
    of the sparse prefix pre-split) — exact covers, ~zero duplication."""
    from dbscan_tpu.parallel.spill import (
        _MAX_PIVOTS,
        _DenseOps,
        chord_halo,
        leader_components,
        spill_partition,
    )

    k = _MAX_PIVOTS + 58  # 250 blobs > 192 pivots
    pts, truth = _dense_blobs(rng, k, 16, 64, 0.005, n_noise=40)
    halo = chord_halo(0.02, 1e-4, dim=64)

    pc = leader_components(_DenseOps(pts), halo, np.random.default_rng(0))
    assert pc is not None
    comp, n_comp = pc
    assert n_comp >= k  # blobs + noise singletons
    for c in range(n_comp):  # no component mixes two blobs
        t = truth[comp == c]
        assert len(np.unique(t[t >= 0])) <= 1
    for b in range(k):  # no blob splits across components
        assert len(np.unique(comp[truth == b])) == 1

    pid, pidx, n_parts, home = spill_partition(pts, 512, halo, seed=0)
    assert n_parts >= 2  # the pivot tree alone cannot split this
    assert len(pid) <= 1.05 * len(pts)  # components: ~zero duplication
    assert (home >= 0).all()


def test_leader_components_end_to_end_cosine(rng):
    """Full train() through the dense leader pre-split: exact blob
    recovery in the concentration regime (the BENCH_COSINE shape)."""
    from dbscan_tpu import train
    from dbscan_tpu.parallel.spill import _MAX_PIVOTS
    from dbscan_tpu.utils.ari import adjusted_rand_index

    k = _MAX_PIVOTS + 8
    pts, truth = _dense_blobs(rng, k, 16, 64, 0.005, n_noise=30)
    model = train(
        pts,
        eps=0.02,
        min_points=5,
        max_points_per_partition=512,
        metric="cosine",
    )
    blob = truth >= 0
    assert model.n_clusters == k, model.stats
    assert adjusted_rand_index(model.clusters[blob], truth[blob]) == 1.0
    assert model.stats["duplication_factor"] <= 1.05


def test_leader_components_bails_on_connected_data(rng):
    """A halo-connected cloud (uniform sphere, NN distance << halo) is
    one component — leader_components returns None and the pivot tree
    keeps the node."""
    from dbscan_tpu.parallel.spill import _DenseOps, leader_components

    pts = rng.normal(size=(3000, 3))
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    out = leader_components(
        _DenseOps(pts.astype(np.float32)), 0.25, np.random.default_rng(0)
    )
    assert out is None


def test_prefix_components_cross_flush_chain_converges(monkeypatch):
    """Regression (ADVICE r4 high): a component merged ACROSS verify
    flushes leaves a depth-2 parent chain (3->2 in flush one, then
    2->0 in flush two); _roots must walk it to the root instead of
    spinning forever on the unadvanced frontier."""
    import queue
    import threading

    import scipy.sparse as sp

    from dbscan_tpu.parallel import spill

    # rows: x0={f1}, x1={f2} (singleton), x2={f0,f1}/sqrt2, x3={f0}.
    # feature f0's prefix list -> pair (2,3) in the FIRST flush;
    # f1's -> pair (0,2) in the SECOND (chunk=1 flushes per group).
    s = 1.0 / np.sqrt(2.0)
    x = sp.csr_matrix(
        (
            np.array([1.0, 1.0, s, s, 1.0]),
            (np.array([0, 1, 2, 2, 3]), np.array([1, 2, 0, 1, 0])),
        ),
        shape=(4, 3),
    )
    monkeypatch.setattr(spill, "_PREFIX_CHUNK", 1)
    out = queue.Queue()
    # daemon thread, not an executor: on regression the worker spins
    # forever, and an executor's shutdown/atexit join would hang the
    # whole suite instead of letting this assertion fail
    th = threading.Thread(
        target=lambda: out.put(spill.prefix_components(x, 0.5)),
        daemon=True,
    )
    th.start()
    th.join(timeout=60)
    assert not th.is_alive(), "prefix_components hung (pre-fix _roots spin)"
    comp, n_comp = out.get_nowait()
    assert n_comp == 2
    assert comp[0] == comp[2] == comp[3]
    assert comp[1] != comp[0]


def test_spill_device_passes_match_host(rng, monkeypatch):
    """DBSCAN_SPILL_DEVICE=1 routes pivot selection, the rejection
    screen, full-node membership, and the leader cover through the
    accelerated (jax) implementations with bf16 storage + slack-inflated
    bands. The trees may differ in copy-sets (slack only ADDS copies),
    so assert the CONTRACT, not the layout: same final labels through
    the full pipeline on a blobs workload, and a valid exact cover."""
    from dbscan_tpu import train

    d = 24
    centers = rng.normal(size=(12, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.repeat(centers, 120, axis=0).astype(np.float32)
    pts += 0.004 * rng.normal(size=pts.shape).astype(np.float32)

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "0")
    m_host = train(pts, eps=0.02, min_points=5,
                   max_points_per_partition=256, metric="cosine")
    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    m_dev = train(pts, eps=0.02, min_points=5,
                  max_points_per_partition=256, metric="cosine")
    assert m_dev.n_clusters == m_host.n_clusters == 12
    # identical labels up to renumbering: ARI exactly 1
    from dbscan_tpu.utils.ari import adjusted_rand_index

    assert adjusted_rand_index(m_host.clusters, m_dev.clusters) == 1.0


def test_spill_device_concentration_regime(rng, monkeypatch):
    """The device leader-cover fallback must split the concentration
    regime (cluster count >> pivots) exactly like the host's, with zero
    duplication."""
    from dbscan_tpu.parallel import spill

    d = 32
    k, per = 250, 12  # clusters >> _MAX_PIVOTS: pivot tree cannot split
    centers = rng.normal(size=(k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    unit = np.repeat(centers, per, axis=0).astype(np.float32)
    unit += 0.002 * rng.normal(size=unit.shape).astype(np.float32)
    unit /= np.linalg.norm(unit, axis=1, keepdims=True)
    halo = spill.chord_halo(0.02, 1e-5, dim=d)

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    part_ids, point_idx, n_parts, home_of = spill.spill_partition(
        unit, 256, halo
    )
    # components are bin-packed into maxpp-sized leaves — split happened
    # iff the leaf count is ~n/maxpp, not one oversized leaf
    assert n_parts >= len(unit) // 256
    assert len(part_ids) == len(unit)  # zero duplication (exact cover)
    # exact cover: same-blob rows always share their home partition
    blob = np.repeat(np.arange(k), per)
    for b in range(0, k, 7):
        homes = home_of[blob == b]
        assert len(np.unique(homes)) == 1


def test_resident_payload_cache_reuse_and_mutation(rng, monkeypatch):
    """The device-resident spill payload is reused across train() calls
    on the SAME (unmutated) input array — the upload is the measured
    wall floor of the cosine route on a remote-attached chip — and a
    mutated array re-uploads (results must track the new data)."""
    from dbscan_tpu import train
    from dbscan_tpu.parallel import driver, spill_device

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    driver._RESIDENT_CACHE.clear()
    uploads = {"n": 0}
    orig = spill_device.DeviceNodeOps.from_host.__func__

    def counting(cls, x):
        uploads["n"] += 1
        return orig(cls, x)

    monkeypatch.setattr(
        spill_device.DeviceNodeOps, "from_host", classmethod(counting)
    )

    d, k, per = 16, 8, 400
    centers = rng.normal(size=(k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.repeat(centers, per, axis=0).astype(np.float32)
    pts += 0.002 * rng.normal(size=pts.shape).astype(np.float32)

    kw = dict(eps=0.05, min_points=5, metric="cosine",
              max_points_per_partition=512)
    m1 = train(pts, **kw)
    first = uploads["n"]
    assert first >= 1
    m2 = train(pts, **kw)  # same array object, unchanged: reuse
    assert uploads["n"] == first
    assert np.array_equal(m1.clusters, m2.clusters)

    # in-place mutation must be detected (full-coverage checksum):
    # fresh upload — mutate rows AWAY from the start so a sparse
    # sampling scheme could not have caught it by luck
    pts[per + 3 : per + 7] = centers[1] + 0.002 * rng.normal(
        size=(4, d)
    ).astype(np.float32)
    train(pts, **kw)
    second = uploads["n"]
    assert second > first

    # a DIFFERENT array object (equal content) also re-uploads
    pts2 = pts.copy()
    m3 = train(pts2, **kw)
    third = uploads["n"]
    assert third > second
    m4 = train(pts2, **kw)  # and then reuses ITS entry
    assert uploads["n"] == third
    assert np.array_equal(m3.clusters, m4.clusters)
    driver._RESIDENT_CACHE.clear()


def test_fingerprint_in_window_row_swap_misses(rng, monkeypatch):
    """Resident-cache checksum must be POSITION-sensitive inside each
    64 KiB window (ADVICE r5 medium): swapping two rows that share a
    window is value-preserving for an order-insensitive xor/sum
    reduction, and a silent hit would reuse stale unit rows — labels
    would map to the OLD row order. Pins both the raw fingerprint and
    the cache-lookup miss."""
    from dbscan_tpu.parallel import driver

    # 16-col f64 rows are 128 B: 512 rows per 64 KiB window, so rows 3
    # and 7 share the FIRST window
    pts = rng.normal(size=(1024, 16))
    fp0 = driver._pts_fingerprint(pts)
    swapped = pts.copy()
    swapped[[3, 7]] = swapped[[7, 3]]
    assert driver._pts_fingerprint(swapped) != fp0
    swapped[[3, 7]] = swapped[[7, 3]]  # swap back: fingerprint restores
    assert driver._pts_fingerprint(swapped) == fp0

    # cache level: an entry built for the array must MISS after an
    # in-place in-window swap (and the miss returns the new fingerprint)
    monkeypatch.setenv("DBSCAN_RESIDENT_CACHE", "1")
    driver._RESIDENT_CACHE.clear()
    import weakref

    driver._RESIDENT_CACHE[id(pts)] = (
        weakref.ref(pts), fp0, pts, object(), False,
    )
    hit, _fp = driver._resident_payload_lookup(pts)
    assert hit is not None
    pts[[3, 7]] = pts[[7, 3]]
    miss, fp_new = driver._resident_payload_lookup(pts)
    assert miss is None
    assert fp_new is not None and fp_new != fp0
    driver._RESIDENT_CACHE.clear()


def test_device_greedy_cover_radius_units():
    """The device greedy cover stores SQUARED chords; coverage must
    compare them against t^2, not the linear t — the latter silently
    regresses the cover radius to sqrt(t) and under-mints leaders on
    any data whose spread falls in (t, sqrt(t)), voiding the canopy
    exact-cover proof. Points on an arc with consecutive chords just
    over t (t chosen above the bf16 slack so measurement noise cannot
    flip the test) are the sharp probe: every point must become a
    leader; the old chord^2 > t compare kept roughly every other."""
    import jax.numpy as jnp
    import ml_dtypes

    from dbscan_tpu.parallel import spill_device as sdev

    t = 0.2
    assert t > sdev.BF16_CHORD_SLACK
    th = np.arange(12) * 0.2525  # consecutive chords ~0.252 > t
    x = np.zeros((12, 8), np.float32)
    x[:, 0] = np.cos(th)
    x[:, 1] = np.sin(th)
    fn = sdev._greedy_leaders_fn(8, 4096)
    perm = np.arange(12, dtype=np.int32)  # identity: walk the arc
    xb = jnp.asarray(x.astype(ml_dtypes.bfloat16))
    buf, nb, overflow = fn(xb, jnp.asarray(perm), jnp.float32(t))
    assert not bool(overflow)
    # host-reference greedy walk at LINEAR radius t over the same order
    kept = [x[0]]
    for i in range(1, 12):
        ch = np.sqrt(
            np.clip(2.0 - 2.0 * (x[i] @ np.stack(kept).T), 0.0, None)
        )
        if float(ch.min()) > t:
            kept.append(x[i])
    assert int(nb) == len(kept) == 12


def test_device_greedy_cover_bf16_floor_terminates(rng):
    """A minting radius below the bf16 slack could never terminate (a
    covered point's measured self-chord is not 0 under bf16):
    leader_components_device must floor the radius at the slack and
    return a valid cover instead of spinning to the cap."""
    from dbscan_tpu.parallel import spill_device as sdev

    d = 8
    c = rng.normal(size=(3, d)).astype(np.float32)
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    x = np.repeat(c, 200, axis=0)
    x += 0.001 * rng.normal(size=x.shape).astype(np.float32)
    x /= np.linalg.norm(x, axis=1, keepdims=True)
    ops = sdev.DeviceNodeOps.from_host(x)
    # halo far below the slack: the unfixed radius never terminates
    r = sdev.leader_components_device(
        ops, 0.004, np.random.default_rng(0), 32
    )
    assert r is not None
    comp, n_comp = r
    assert n_comp == 3
    for blob in range(3):
        assert len(np.unique(comp[blob * 200 : (blob + 1) * 200])) == 1


def test_resident_cache_reapplies_zero_norm_screen(rng, monkeypatch):
    """The zero-norm noise screen is CONFIG-dependent (fires only when
    eps + q < 1), so a cache entry built under a screen-bypassing
    config must not let a later small-eps call on the same array skip
    it: zero rows must still route to noise with the stat recorded."""
    from dbscan_tpu import train
    from dbscan_tpu.ops.labels import NOISE
    from dbscan_tpu.parallel import driver

    monkeypatch.setenv("DBSCAN_SPILL_DEVICE", "1")
    driver._RESIDENT_CACHE.clear()
    d, k, per = 16, 4, 300
    centers = rng.normal(size=(k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.repeat(centers, per, axis=0).astype(np.float32)
    pts += 0.002 * rng.normal(size=pts.shape).astype(np.float32)
    pts[:17] = 0.0  # zero-norm rows

    # call 1: eps large enough that eps + q >= 1 bypasses the screen
    # (zero rows legitimately join clusters at that radius), building
    # a cache entry WITH zero rows present
    m1 = train(pts, eps=0.999, min_points=5, metric="cosine",
               max_points_per_partition=512)
    assert len(driver._RESIDENT_CACHE) == 1
    assert "n_zero_norm_noise" not in m1.stats

    # call 2, same array, small eps: the screen applies — the cache
    # hit must NOT skip it
    m2 = train(pts, eps=0.05, min_points=5, metric="cosine",
               max_points_per_partition=512)
    assert m2.stats.get("n_zero_norm_noise") == 17
    assert (m2.clusters[:17] == 0).all()
    assert (m2.flags[:17] == NOISE).all()
    assert m2.n_clusters == k
    driver._RESIDENT_CACHE.clear()
