"""Metric spill partitioning (parallel/spill.py): the coverage contract,
pivot hygiene, and degradation behavior — unit-level, no kernels."""

import numpy as np

from dbscan_tpu.parallel.spill import spill_partition


def _leaf_sets(part_ids, point_idx, n_parts):
    return [
        set(point_idx[part_ids == p].tolist()) for p in range(n_parts)
    ]


def test_coverage_contract_fuzz(rng):
    """THE correctness property: every pair within halo chord distance
    shares at least one leaf — fuzzed over random cluster layouts."""
    for trial in range(5):
        d = int(rng.integers(4, 40))
        k = int(rng.integers(3, 10))
        c = rng.normal(size=(k, d))
        c /= np.linalg.norm(c, axis=1, keepdims=True)
        pts = np.repeat(c, 80, axis=0) + 0.05 * rng.normal(
            size=(k * 80, d)
        )
        pts /= np.linalg.norm(pts, axis=1, keepdims=True)
        halo = 0.25
        part_ids, point_idx, n_parts, home_of = spill_partition(
            pts, maxpp=60, halo=halo, seed=trial
        )
        leaves = _leaf_sets(part_ids, point_idx, n_parts)
        # membership per point for the pair check
        member = [set() for _ in range(len(pts))]
        for li, s in enumerate(leaves):
            for p in s:
                member[p].add(li)
        chord = np.linalg.norm(
            pts[:, None, :] - pts[None, :, :], axis=-1
        )
        close_i, close_j = np.nonzero(chord <= halo)
        for i, j in zip(close_i, close_j):
            assert member[i] & member[j], (
                f"trial {trial}: pair ({i},{j}) at chord "
                f"{chord[i, j]:.3f} <= {halo} shares no leaf"
            )
        # every point homed exactly once, in a leaf that contains it
        assert (home_of >= 0).all()
        for p, h in enumerate(home_of):
            assert p in leaves[h]


def test_instance_layout_partition_major(rng):
    pts = rng.normal(size=(500, 8))
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    part_ids, point_idx, n_parts, _ = spill_partition(
        pts, maxpp=100, halo=0.2, seed=0
    )
    assert (np.diff(part_ids) >= 0).all()  # partition-major
    for p in range(n_parts):  # point-sorted within each partition
        sl = point_idx[part_ids == p]
        assert (np.diff(sl) > 0).all()


def test_degenerate_identical_points():
    pts = np.tile([[0.6, 0.8]], (300, 1))
    part_ids, point_idx, n_parts, home_of = spill_partition(
        pts, maxpp=50, halo=0.1, seed=0
    )
    assert n_parts == 1  # unsplittable: one oversized leaf
    assert len(point_idx) == 300
    assert (home_of == 0).all()


def test_empty():
    part_ids, point_idx, n_parts, home_of = spill_partition(
        np.empty((0, 4)), maxpp=10, halo=0.1
    )
    assert n_parts == 0 and len(part_ids) == 0 and len(home_of) == 0


def test_cosine_spill_on_mesh(rng):
    """Spill-partitioned cosine fans out over the device mesh like the
    grid path: labels identical to the single-device run."""
    from dbscan_tpu import train
    from dbscan_tpu.parallel.mesh import make_mesh

    d = 24
    c = rng.normal(size=(8, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    data = np.repeat(c, 150, axis=0) + 0.01 * rng.normal(size=(1200, d))
    kw = dict(
        eps=0.02, min_points=6, max_points_per_partition=200,
        metric="cosine",
    )
    m0 = train(data, **kw)
    assert m0.stats["n_partitions"] >= 8
    m1 = train(data, mesh=make_mesh(), **kw)
    np.testing.assert_array_equal(m0.clusters, m1.clusters)
    np.testing.assert_array_equal(m0.flags, m1.flags)
    assert m0.n_clusters == 8
