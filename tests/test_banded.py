"""Banded (block-slab) engine vs the dense engine: bit-exact equality.

The banded engine (dbscan_tpu/ops/banded.py) must reproduce the dense
engine's output EXACTLY — same difference-form f32 arithmetic, same
border/noise algebra — for every geometry that stresses its machinery:
cell-row straddles, empty cell rows, single-cell pileups, points on cell
boundaries, multi-partition halo interplay, and both reference engines'
border semantics. The packer invariants (every run fits its slab, inverse
permutation consistency) are checked directly.
"""

import numpy as np
import pytest

from dbscan_tpu import Engine, train
from dbscan_tpu.parallel import binning


def _equal_models(pts, eps, min_points, maxpp, engine, mesh=None):
    kw = dict(
        eps=eps,
        min_points=min_points,
        max_points_per_partition=maxpp,
        engine=engine,
        mesh=mesh,
    )
    md = train(pts, neighbor_backend="dense", **kw)
    mb = train(pts, neighbor_backend="banded", **kw)
    np.testing.assert_array_equal(md.clusters, mb.clusters)
    np.testing.assert_array_equal(md.flags, mb.flags)
    assert mb.stats["n_banded_groups"] >= 1
    return mb


GEOMETRIES = {
    "blobs+noise": lambda rng: np.concatenate(
        [rng.normal(c, 0.5, (700, 2)) for c in [(0, 0), (5, 5), (-4, 6)]]
        + [rng.uniform(-8, 10, (300, 2))]
    ),
    "thin-horizontal-chain": lambda rng: np.stack(
        [np.linspace(0, 40, 1500), rng.normal(0, 0.05, 1500)], axis=1
    ),
    "single-cell-pileup": lambda rng: rng.normal(0, 0.02, (1200, 2)),
    "grid-boundary-points": lambda rng: np.concatenate(
        [
            # points exactly on multiples of eps (cell boundaries)
            np.stack(
                [
                    rng.integers(0, 12, 600) * 0.3,
                    rng.integers(0, 12, 600) * 0.3,
                ],
                axis=1,
            ),
            rng.uniform(0, 3.6, (600, 2)),
        ]
    ),
    "sparse-rows": lambda rng: np.concatenate(
        [
            rng.normal((0, 0), 0.4, (800, 2)),
            rng.normal((0, 7), 0.4, (800, 2)),  # empty cell rows between
        ]
    ),
}


@pytest.mark.parametrize("name", sorted(GEOMETRIES))
@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_banded_equals_dense_single_partition(name, engine, rng):
    pts = GEOMETRIES[name](rng)
    _equal_models(pts, 0.3, 6, 10**9, engine)


@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_banded_equals_dense_multi_partition(engine, rng):
    pts = np.concatenate(
        [rng.normal(c, 0.6, (1500, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (500, 2))]
    )
    m = _equal_models(pts, 0.3, 8, 700, engine)
    assert m.stats["n_partitions"] > 4


def test_banded_equals_dense_on_mesh(rng):
    from dbscan_tpu.parallel.mesh import make_mesh

    pts = np.concatenate(
        [rng.normal(c, 0.5, (900, 2)) for c in [(0, 0), (7, 7), (-6, 8), (9, -7)]]
    )
    m = _equal_models(pts, 0.35, 8, 600, Engine.ARCHERY, mesh=make_mesh())
    assert m.stats["n_partitions"] >= 8


def test_banded_handles_empty_and_tiny():
    m = train(
        np.empty((0, 2)), eps=0.3, min_points=3,
        max_points_per_partition=100, neighbor_backend="banded",
    )
    assert m.n_clusters == 0
    m = train(
        np.array([[0.0, 0.0], [0.05, 0.0], [10.0, 10.0]]),
        eps=0.3, min_points=2, max_points_per_partition=100,
        neighbor_backend="banded",
    )
    assert m.n_clusters == 1
    assert (m.clusters > 0).sum() == 2


def test_auto_routes_large_buckets_banded(rng):
    """auto must choose banded where dense cannot fit HBM (B > 64k)."""
    # 70k points in one partition -> bucket width > DENSE_MAX_BUCKET
    pts = rng.uniform(0, 100, (70000, 2))
    m = train(
        pts, eps=0.5, min_points=4, max_points_per_partition=10**9,
        neighbor_backend="auto",
    )
    assert m.stats["n_banded_groups"] == m.stats["n_bucket_groups"] == 1


def test_packer_invariants(rng):
    """Every run fits its slab; permutations are inverse pairs; every
    instance lands exactly once."""
    pts = np.concatenate(
        [rng.normal(c, 0.5, (3000, 2)) for c in [(0, 0), (4, 4)]]
    )
    outer = np.array(
        [[pts[:, 0].min() - 1, pts[:, 1].min() - 1,
          pts[:, 0].max() + 1, pts[:, 1].max() + 1]]
    )
    part_ids = np.zeros(len(pts), np.int64)
    point_idx = np.arange(len(pts), dtype=np.int64)
    groups, _, meta = binning.bucketize_banded(
        pts, part_ids, point_idx, 1, 0.3, outer, force=True
    )
    (g,) = groups
    b = g.points.shape[1]
    assert b % binning.BANDED_BLOCK == 0
    ext = g.banded
    nb = b // binning.BANDED_BLOCK
    assert ext.slab_starts.shape == (g.points.shape[0], nb, binning.BANDED_ROWS)
    # slab bounds
    assert (ext.slab_starts >= 0).all()
    assert (ext.slab_starts + ext.slab <= b).all()
    # runs fit their slabs
    assert (ext.rel_starts >= 0).all()
    assert (ext.rel_starts + ext.spans <= ext.slab).all()
    # fold indices are a permutation on each row
    np.testing.assert_array_equal(np.sort(ext.fold_idx[0]), np.arange(b))
    # instances: valid slots carry each original index exactly once
    got = np.sort(g.point_idx[g.point_idx >= 0])
    np.testing.assert_array_equal(got, point_idx)
    # window table: every occupied cell sees itself at the center slot
    assert meta.n_cells == int(ext.cell_gid.max()) + 1
    np.testing.assert_array_equal(
        meta.wintab[:, binning.BANDED_WIN // 2], np.arange(meta.n_cells)
    )
    # every true eps-pair is covered by some run of the query row
    # (spot-check: counts from a brute-force subset against phase 1)
    sub = rng.choice(len(pts), 64, replace=False)
    d2 = ((pts[sub, None, :] - pts[None, :, :]) ** 2).sum(-1)
    want = (d2 <= 0.3 * 0.3).sum(axis=1)
    from dbscan_tpu.ops.banded import banded_phase1
    import jax.numpy as jnp

    counts_dev, core_dev, bits_dev = banded_phase1(
        jnp.asarray(g.points[0]), jnp.asarray(g.mask[0]),
        jnp.asarray(ext.rel_starts[0]), jnp.asarray(ext.spans[0]),
        jnp.asarray(ext.slab_starts[0]), jnp.asarray(ext.cx[0]),
        0.3, 6, slab=ext.slab,
    )
    counts = np.zeros(len(pts), np.int64)
    valid = g.point_idx[0] >= 0
    counts[g.point_idx[0][valid]] = np.asarray(counts_dev)[valid]
    np.testing.assert_array_equal(counts[sub], want)
    # a core point always reports its own cell in the edge bitmask
    bits = np.asarray(bits_dev)
    core = np.asarray(core_dev)
    center = 1 << (binning.BANDED_WIN // 2)
    assert ((bits[core] & center) == center).all()
    # bits are computed for every valid row (non-core rows feed the border
    # algebra): a row reports a nonzero mask iff it has an eps-adjacent core
    full = np.zeros(len(pts), np.int64)
    full[g.point_idx[0][valid]] = bits[valid]
    core_full = np.zeros(len(pts), bool)
    core_full[g.point_idx[0][valid]] = core[valid]
    has_core_nbr = (d2 <= 0.3 * 0.3) @ core_full > 0
    assert ((full[sub] != 0) == has_core_nbr).all()


def test_compact_postpass_chunking_matches_single_chunk(rng, monkeypatch):
    """The compact postpass splits its groups into slot-budgeted chunks
    (single device buffers are capped at 2^31 bytes on TPU); a tiny cap
    forcing many chunks must reproduce the one-chunk labels exactly —
    the host-side layout merge is bit-transparent."""
    from dbscan_tpu.parallel import driver

    pts = np.concatenate(
        [rng.normal(c, 0.5, (2500, 2)) for c in [(0, 0), (7, 7), (-6, 8), (8, -7)]]
        + [rng.uniform(-10, 12, (1000, 2))]
    )
    kw = dict(
        eps=0.35,
        min_points=8,
        max_points_per_partition=2048,
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 4096)  # many chunks
    chunked = train(pts, **kw)
    np.testing.assert_array_equal(ref.clusters, chunked.clusters)
    np.testing.assert_array_equal(ref.flags, chunked.flags)
    assert chunked.stats["n_banded_groups"] >= 2  # several groups split up


def test_slab_chunked_sweeps_match_unchunked(rng, monkeypatch):
    """Wide slabs are consumed in bounded chunks (transients at ~200k-wide
    slabs hit the TPU per-buffer ceiling); a tiny chunk target must
    reproduce the unchunked labels bit-for-bit, including runs that span
    chunk boundaries."""
    from dbscan_tpu.ops import banded as banded_mod

    pts = np.concatenate(
        [rng.normal(c, 0.4, (3000, 2)) for c in [(0, 0), (4, 4)]]
        + [rng.uniform(-3, 7, (800, 2))]
    )
    kw = dict(
        eps=0.35,
        min_points=8,
        max_points_per_partition=8192,
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
    )
    from dbscan_tpu.parallel import driver as driver_mod

    import jax

    ref = train(pts, **kw)
    # Both cache layers would replay the unchunked program: the driver's
    # lru-cached executors AND banded_phase1's own jax.jit trace cache
    # (same shapes + static slab -> cache hit even through a fresh
    # driver executor). Clear everything so the monkeypatched target is
    # actually read at retrace.
    monkeypatch.setattr(banded_mod, "_SLAB_CHUNK_TARGET", 128)
    driver_mod.clear_compile_cache()
    jax.clear_caches()
    try:
        chunked = train(pts, **kw)
    finally:
        driver_mod.clear_compile_cache()
        jax.clear_caches()
    np.testing.assert_array_equal(ref.clusters, chunked.clusters)
    np.testing.assert_array_equal(ref.flags, chunked.flags)


def test_group_slot_cap_label_transparent(rng, monkeypatch):
    """DBSCAN_GROUP_SLOTS splits a (width, win) class into slot-bounded
    groups (the restart-granularity lever the 100M campaign needs); the
    batching must be invisible to results — same labels, flags, core
    count — while producing strictly more banded groups."""
    from dbscan_tpu import Engine, train
    from dbscan_tpu.parallel import driver as driver_mod

    pts = np.concatenate(
        [rng.normal(c, 0.6, (1200, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (600, 2))]
    )
    kw = dict(
        eps=0.3,
        min_points=6,
        max_points_per_partition=700,
        engine=Engine.ARCHERY,
        neighbor_backend="banded",
    )
    ref = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_GROUP_SLOTS", "1024")  # ~1 partition/group
    driver_mod.clear_compile_cache()
    try:
        split = train(pts, **kw)
    finally:
        driver_mod.clear_compile_cache()
    assert split.stats["n_banded_groups"] > ref.stats["n_banded_groups"]
    np.testing.assert_array_equal(ref.clusters, split.clusters)
    np.testing.assert_array_equal(ref.flags, split.flags)
    assert ref.stats["n_core_instances"] == split.stats["n_core_instances"]
