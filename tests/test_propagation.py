"""Propagation contract (ops/propagation.py): the single-pass
union-find variant (DBSCAN_PROP_UNIONFIND) vs the iterated min-label
fixed point.

The contract is EXACT (PARITY.md "Propagation contract"): both modes
are monotone decreasing sequences on the same lattice, bounded below by
the per-component minimum, with a decreasing move available at any
label above it — so the fixed point (and every label) is byte-identical
under the documented SYMMETRIC-relation contract of ``window_cc``. Only
the counted sweeps differ, and the union-find mode must never need
MORE sweeps: pull+push is a two-hop relaxation per sweep and the
aggressive jumps strictly extend the iterated path's single jump.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dbscan_tpu.ops import propagation


def _run(adj, tab, mode, init=None):
    comp, it = propagation.window_cc(
        jnp.asarray(adj), jnp.asarray(tab), mode=mode, init=init
    )
    return np.asarray(comp), int(it)


def _sym_window(n, edges, w):
    """Edge list -> symmetric [n, w] window table + mask; an edge is
    kept only when BOTH endpoints have a free slot (window_cc's
    symmetric-relation contract)."""
    tab = np.zeros((n, w), np.int32)
    adj = np.zeros((n, w), bool)
    deg = np.zeros(n, np.int64)
    for u, v in edges:
        if u == v or deg[u] >= w or deg[v] >= w:
            continue
        tab[u, deg[u]] = v
        adj[u, deg[u]] = True
        deg[u] += 1
        tab[v, deg[v]] = u
        adj[v, deg[v]] = True
        deg[v] += 1
    return adj, tab


def _scipy_minlabels(n, adj, tab):
    sp = pytest.importorskip("scipy.sparse")
    from scipy.sparse.csgraph import connected_components

    uu, vv = np.nonzero(adj)
    g = sp.coo_matrix(
        (np.ones(len(uu)), (uu, tab[uu, vv])), shape=(n, n)
    )
    _, lab = connected_components(g, directed=False)
    ref = np.empty(n, np.int64)
    for c in range(lab.max() + 1):
        mem = np.flatnonzero(lab == c)
        ref[mem] = mem.min()
    return ref


def test_mode_resolution(monkeypatch):
    monkeypatch.delenv("DBSCAN_PROP_UNIONFIND", raising=False)
    assert propagation.prop_mode() == "unionfind"  # auto default
    for raw in ("0", "off", "iterated", "false"):
        assert propagation.prop_mode(raw) == "iterated"
    for raw in ("1", "auto", "unionfind", "on"):
        assert propagation.prop_mode(raw) == "unionfind"
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "0")
    assert propagation.prop_mode() == "iterated"


@pytest.mark.parametrize(
    "shape",
    ["long-chain", "star-forest", "torus", "two-rings"],
)
def test_pathological_shapes_parity_and_collapse(shape):
    """The sweep-count-maximizing shapes: byte-identical labels, and
    the union-find mode strictly collapses the sweep count wherever the
    iterated path needs more than the trivial 2 sweeps."""
    if shape == "long-chain":
        n, w = 4096, 2
        edges = [(i, i + 1) for i in range(n - 1)]
    elif shape == "star-forest":
        # many small stars chained at the hubs: mixes degree-w hubs
        # with chains (the hub fan-in is where scatter-min pays)
        n, w = 2048, 8
        edges = []
        for hub in range(0, n - 8, 8):
            edges += [(hub, hub + k) for k in range(1, 8)]
            if hub + 8 < n:
                edges.append((hub + 7, hub + 8))
    elif shape == "torus":
        s = 48
        n, w = s * s, 4
        idx = np.arange(n).reshape(s, s)
        edges = []
        for a, b in (
            (idx, np.roll(idx, 1, 0)),
            (idx, np.roll(idx, 1, 1)),
        ):
            edges += list(zip(a.reshape(-1), b.reshape(-1)))
        edges = [(int(u), int(v)) for u, v in edges]
    else:  # two-rings
        n, w = 2048, 2
        half = n // 2
        edges = [(i, (i + 1) % half) for i in range(half)]
        edges += [
            (half + i, half + (i + 1) % half) for i in range(half)
        ]
    adj, tab = _sym_window(n, edges, w)
    c_it, s_it = _run(adj, tab, "iterated")
    c_uf, s_uf = _run(adj, tab, "unionfind")
    np.testing.assert_array_equal(c_it, c_uf)
    np.testing.assert_array_equal(c_it, _scipy_minlabels(n, adj, tab))
    assert s_uf <= s_it
    if s_it > 2:
        assert s_uf < s_it, (shape, s_it, s_uf)


def test_property_fuzz_random_graphs(rng):
    """Property-based parity fuzz: random symmetric graphs across a
    density range — labels byte-identical between the modes AND equal
    to scipy's min-index components; union-find never needs more
    sweeps."""
    for trial in range(8):
        n = int(rng.integers(200, 1200))
        w = int(rng.integers(2, 12))
        m = int(rng.integers(n // 4, 2 * n))
        u = rng.integers(0, n, m)
        v = rng.integers(0, n, m)
        adj, tab = _sym_window(n, list(zip(u, v)), w)
        c_it, s_it = _run(adj, tab, "iterated")
        c_uf, s_uf = _run(adj, tab, "unionfind")
        np.testing.assert_array_equal(c_it, c_uf)
        np.testing.assert_array_equal(
            c_it, _scipy_minlabels(n, adj, tab)
        )
        assert s_uf <= s_it, (trial, s_it, s_uf)


def test_warm_init_preserves_fixed_point():
    """A monotone warm start (the fused path's first-sweep partial)
    changes the counted sweeps, never the labels."""
    n, w = 1024, 2
    edges = [(i, i + 1) for i in range(n - 1)]
    adj, tab = _sym_window(n, edges, w)
    cold, s_cold = _run(adj, tab, "unionfind")
    # the exact first pull sweep, as ops/pallas_banded.py folds it
    nbr = np.where(adj, tab, 2**31 - 1).min(axis=1)
    lab0 = np.minimum(np.arange(n), nbr).astype(np.int32)
    warm, s_warm = _run(adj, tab, "unionfind", init=jnp.asarray(lab0))
    np.testing.assert_array_equal(cold, warm)
    assert s_warm <= s_cold


def test_dense_engine_parity_across_modes(rng):
    """The dense (materialized-adjacency) consumer: eager
    cluster_from_adjacency under both modes, byte-identical
    labels/flags (the [N, N] path has no scatter table — it rides the
    pull + aggressive jumps half of the variant)."""
    from dbscan_tpu.ops.local_dbscan import cluster_from_adjacency

    pts = np.concatenate(
        [rng.normal(c, 0.5, (120, 2)) for c in [(0, 0), (4, 4)]]
        + [rng.uniform(-2, 6, (40, 2))]
    )
    d2 = ((pts[:, None] - pts[None, :]) ** 2).sum(-1)
    adj = jnp.asarray(d2 <= 0.36)
    mask = jnp.ones(len(pts), bool)
    outs = {}
    for mode in ("0", "1"):
        import os

        prev = os.environ.get("DBSCAN_PROP_UNIONFIND")
        os.environ["DBSCAN_PROP_UNIONFIND"] = mode
        try:
            res = cluster_from_adjacency(adj, mask, 6, "archery")
            outs[mode] = (
                np.asarray(res.seed_labels),
                np.asarray(res.flags),
            )
        finally:
            if prev is None:
                os.environ.pop("DBSCAN_PROP_UNIONFIND", None)
            else:
                os.environ["DBSCAN_PROP_UNIONFIND"] = prev
    np.testing.assert_array_equal(outs["0"][0], outs["1"][0])
    np.testing.assert_array_equal(outs["0"][1], outs["1"][1])


def test_banded_train_parity_and_strictly_fewer_sweeps(rng, monkeypatch):
    """End-to-end banded anchor-style shape: byte-identical labels and
    flags across the knob, the gated cellcc_cc_iters / prop_sweeps
    STRICTLY lower in union-find mode, and the telemetry funnel live
    (prop.sweeps counter == the stats figure, prop.mode gauge set)."""
    from dbscan_tpu import Engine, obs, train

    pts = np.concatenate(
        [rng.normal(c, 0.6, (1500, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (500, 2))]
    )
    kw = dict(
        eps=0.3, min_points=8, max_points_per_partition=700,
        engine=Engine.ARCHERY, neighbor_backend="banded",
    )
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "0")
    m_it = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "1")
    obs.enable()
    try:
        snap = obs.counters()
        m_uf = train(pts, **kw)
        delta = obs.counters_delta(snap)
        gauges = obs.state().metrics.gauges()
    finally:
        obs.disable()
    np.testing.assert_array_equal(m_it.clusters, m_uf.clusters)
    np.testing.assert_array_equal(m_it.flags, m_uf.flags)
    assert m_it.stats["cellcc_cc_iters"] >= 1
    assert (
        m_uf.stats["cellcc_cc_iters"] < m_it.stats["cellcc_cc_iters"]
    )
    assert m_it.stats["prop_mode"] == "iterated"
    assert m_uf.stats["prop_mode"] == "unionfind"
    assert m_uf.stats["prop_sweeps"] == m_uf.stats["cellcc_cc_iters"]
    assert delta.get("prop.sweeps") == m_uf.stats["prop_sweeps"]
    assert gauges.get("prop.mode") == 1.0


def test_embed_parity_across_modes(rng, monkeypatch):
    """The embed consumer: bucket window_cc under both modes, labels
    identical (the mode is part of the kernel cache key, so an
    in-process flip really flips the compiled path)."""
    from dbscan_tpu import embed_dbscan
    from dbscan_tpu.embed import neighbors

    d, k = 16, 4
    centers = rng.standard_normal((k, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    blob_of = rng.integers(0, k, 400)
    pts = centers[blob_of] + 0.001 * rng.standard_normal(
        (400, d)
    ).astype(np.float32)
    neighbors.reset_w_floors()
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "0")
    c0, f0 = embed_dbscan(pts, 0.01, 4, max_points_per_partition=256)
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "1")
    c1, f1 = embed_dbscan(pts, 0.01, 4, max_points_per_partition=256)
    np.testing.assert_array_equal(c0, c1)
    np.testing.assert_array_equal(f0, f1)


def test_sparse_parity_across_modes(monkeypatch):
    """The sparse front-end (cluster_from_adjacency consumer) under
    both modes: identical ids/flags."""
    sp = pytest.importorskip("scipy.sparse")
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan

    srng = np.random.default_rng(7)
    k, per, vocab, nnz = 12, 40, 2000, 12
    feat = srng.integers(0, vocab, size=(k, nnz))
    val = srng.random((k, nnz)) + 0.1
    blob_of = np.repeat(np.arange(k), per)
    rows = np.repeat(np.arange(k * per), nnz)
    cols = feat[blob_of].ravel()
    vals = (val[blob_of] * srng.uniform(0.9, 1.1, (k * per, nnz))).ravel()
    x = sp.coo_matrix((vals, (rows, cols)), shape=(k * per, vocab)).tocsr()
    kw = dict(max_points_per_partition=256, eps=0.05, min_points=5)
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "0")
    c0, f0 = sparse_cosine_dbscan(x, **kw)
    monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", "1")
    c1, f1 = sparse_cosine_dbscan(x, **kw)
    np.testing.assert_array_equal(c0, c1)
    np.testing.assert_array_equal(f0, f1)


def test_halo_merge_parity_across_modes(rng, monkeypatch):
    """The collective halo-merge consumer: the union-find rounds reach
    the same gids as the iterated rounds AND the host union-find, with
    no more rounds."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    from dbscan_tpu import obs
    from dbscan_tpu.parallel import graph as graph_mod
    from dbscan_tpu.parallel import halo, mesh as mesh_mod

    mesh = mesh_mod.make_mesh(jax.devices()[:4])
    n = 600
    m = 900
    ua = rng.integers(0, n, m).astype(np.int64)
    ub = rng.integers(0, n, m).astype(np.int64)
    n_ref, gid_ref = graph_mod.uf_components(ua, ub, n)
    results = {}
    obs.enable()
    try:
        for mode in ("0", "1"):
            monkeypatch.setenv("DBSCAN_PROP_UNIONFIND", mode)
            snap = obs.counters()
            n_got, gid = halo.collective_merge(
                ua.astype(np.int32), ub.astype(np.int32), n, mesh
            )
            rounds = obs.counters_delta(snap).get("halo.rounds", 0)
            results[mode] = (n_got, gid, rounds)
    finally:
        obs.disable()
    for mode, (n_got, gid, rounds) in results.items():
        assert n_got == n_ref, mode
        np.testing.assert_array_equal(gid, gid_ref)
        assert rounds >= 1
    assert results["1"][2] <= results["0"][2]
