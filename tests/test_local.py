"""Local-kernel tests: the vectorized TPU engine must match the sequential
numpy oracles EXACTLY (ids and flags), for both reference semantics, plus the
golden 749-point fixture from the reference tree (loaded read-only at test
time, never copied)."""

import numpy as np
import pytest

import conftest
from dbscan_tpu.ops import local_dbscan as ld
from dbscan_tpu.ops.labels import (
    BORDER,
    CORE,
    NOISE,
    NOT_FLAGGED,
    SEED_NONE,
    seed_to_local_ids,
)
from dbscan_tpu.utils import reference_engines as oracle
from dbscan_tpu.utils.ari import adjusted_rand_index, exact_match_up_to_permutation


def run_kernel(points, eps, min_points, engine, mask=None):
    points = np.asarray(points, dtype=np.float64)
    if mask is None:
        mask = np.ones(len(points), dtype=bool)
    res = ld.local_dbscan(
        points, mask, eps, min_points, engine=engine
    )
    return (
        np.asarray(res.seed_labels),
        np.asarray(res.flags),
        np.asarray(res.counts),
    )


def make_blobs(rng, n=300, centers=((0, 0), (5, 5), (-4, 6)), scale=0.6):
    pts = np.concatenate(
        [rng.normal(c, scale, size=(n // len(centers), 2)) for c in centers]
    )
    rng.shuffle(pts)
    return pts


@pytest.mark.parametrize("engine", ["naive", "archery"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_exact_match_vs_oracle_random_blobs(engine, seed):
    rng = np.random.default_rng(seed)
    pts = make_blobs(rng)
    eps, min_points = 0.5, 5
    seeds, flags, counts = run_kernel(pts, eps, min_points, engine)
    ofit = oracle.naive_fit if engine == "naive" else oracle.archery_fit
    ocluster, oflags = ofit(pts, eps, min_points)
    # seed labels densified to fold-order numbering == oracle's sequential ids
    np.testing.assert_array_equal(seed_to_local_ids(seeds), ocluster)
    np.testing.assert_array_equal(flags, oflags)
    # counts: self-inclusive neighborhood sizes
    from dbscan_tpu.ops.geometry import pairwise_sq_dists

    d2 = pairwise_sq_dists(pts, pts)
    np.testing.assert_array_equal(counts, (d2 <= eps * eps).sum(1))


@pytest.mark.parametrize("engine", ["naive", "archery"])
def test_exact_match_vs_oracle_uniform_noise(engine):
    rng = np.random.default_rng(7)
    pts = rng.uniform(-10, 10, size=(400, 2))
    seeds, flags, _ = run_kernel(pts, 0.8, 4, engine)
    ofit = oracle.naive_fit if engine == "naive" else oracle.archery_fit
    ocluster, oflags = ofit(pts, 0.8, 4)
    np.testing.assert_array_equal(seed_to_local_ids(seeds), ocluster)
    np.testing.assert_array_equal(flags, oflags)


def test_naive_vs_archery_divergence_exists():
    # A point visited as noise before its cluster's seed is processed stays
    # Noise under naive but becomes Border under archery. Construct: border
    # candidate at index 0, core cluster after it.
    #   index 0: non-core point eps-adjacent to the core at x=0.2; the
    #   cluster's seed (its first core, x=0.0) has index 1 > 0, so the
    #   expansion reaches index 0 only after its own fold visit marked it
    #   Noise -> naive keeps Noise, archery adopts it as Border
    pts = np.array([[0.35, 0.0], [0.0, 0.0], [0.1, 0.0], [0.2, 0.0]])
    eps, min_points = 0.2, 3
    sn, fn, _ = run_kernel(pts, eps, min_points, "naive")
    sa, fa, _ = run_kernel(pts, eps, min_points, "archery")
    onc, onf = oracle.naive_fit(pts, eps, min_points)
    oac, oaf = oracle.archery_fit(pts, eps, min_points)
    # oracle divergence sanity
    assert onf[0] == NOISE and oaf[0] == BORDER
    np.testing.assert_array_equal(fn, onf)
    np.testing.assert_array_equal(fa, oaf)
    np.testing.assert_array_equal(seed_to_local_ids(sn), onc)
    np.testing.assert_array_equal(seed_to_local_ids(sa), oac)


def test_padding_mask_is_inert():
    rng = np.random.default_rng(3)
    pts = make_blobs(rng, n=120)
    eps, min_points = 0.5, 5
    s1, f1, _ = run_kernel(pts, eps, min_points, "naive")
    # pad with garbage rows that would otherwise join clusters
    pad = np.tile(pts[:7], (3, 1))
    padded = np.concatenate([pts, pad])
    mask = np.concatenate([np.ones(len(pts), bool), np.zeros(len(pad), bool)])
    s2, f2, _ = run_kernel(padded, eps, min_points, "naive", mask=mask)
    np.testing.assert_array_equal(s1, s2[: len(pts)])
    np.testing.assert_array_equal(f1, f2[: len(pts)])
    assert (f2[len(pts):] == NOT_FLAGGED).all()
    assert (s2[len(pts):] == SEED_NONE).all()


def test_min_points_one_all_core():
    pts = np.array([[0.0, 0.0], [10.0, 10.0]])
    seeds, flags, counts = run_kernel(pts, 0.1, 1, "naive")
    assert (flags == CORE).all()
    np.testing.assert_array_equal(seed_to_local_ids(seeds), [1, 2])
    np.testing.assert_array_equal(counts, [1, 1])


def test_all_noise():
    pts = np.array([[0.0, 0.0], [10.0, 10.0], [20.0, 0.0]])
    seeds, flags, _ = run_kernel(pts, 0.5, 2, "naive")
    assert (flags == NOISE).all()
    assert (seeds == SEED_NONE).all()


def test_duplicate_points():
    pts = np.concatenate([np.zeros((5, 2)), np.full((4, 2), 9.0)])
    seeds, flags, counts = run_kernel(pts, 0.1, 4, "archery")
    ocluster, oflags = oracle.archery_fit(pts, 0.1, 4)
    np.testing.assert_array_equal(seed_to_local_ids(seeds), ocluster)
    np.testing.assert_array_equal(flags, oflags)


def test_chain_cluster_long_diameter():
    # a single long chain: stresses label-propagation convergence (pointer
    # jumping must collapse the O(n) diameter quickly)
    n = 257
    pts = np.stack([np.arange(n) * 0.1, np.zeros(n)], axis=1)
    seeds, flags, _ = run_kernel(pts, 0.15, 2, "naive")
    assert (flags == CORE).all()
    assert (seeds == 0).all()
    onc, onf = oracle.naive_fit(pts, 0.15, 2)
    np.testing.assert_array_equal(seed_to_local_ids(seeds), onc)


@pytest.mark.parametrize("engine", ["naive", "archery"])
def test_golden_fixture_749(engine):
    if not conftest.reference_fixture_available():
        pytest.skip("reference fixture not mounted")
    pts, expected = conftest.load_reference_fixture()
    eps = float(np.float32(0.3))  # the reference suite passes 0.3F
    seeds, flags, _ = run_kernel(pts, eps, 10, engine)
    got = seed_to_local_ids(seeds)
    # cluster structure must match the fixture labels exactly up to
    # permutation, with noise mapping to noise (the reference's own
    # end-to-end suite needs a correspondence map, DBSCANSuite.scala:28)
    assert exact_match_up_to_permutation(got, expected.astype(int))
    assert adjusted_rand_index(got, expected) == 1.0
    # fixture composition pinned in BASELINE.md: 18 noise, clusters of
    # 243/245/243
    sizes = sorted(np.bincount(got)[1:].tolist())
    assert (got == 0).sum() == 18
    assert sizes == [243, 243, 245]


def test_oracles_agree_on_fixture():
    if not conftest.reference_fixture_available():
        pytest.skip("reference fixture not mounted")
    pts, expected = conftest.load_reference_fixture()
    eps = float(np.float32(0.3))
    for ofit in (oracle.naive_fit, oracle.archery_fit):
        ocluster, _ = ofit(pts, eps, 10)
        assert exact_match_up_to_permutation(ocluster, expected.astype(int))
