"""Knob autotuner (dbscan_tpu/bench.py ``--tune``), the config.Profile
surface, and their gates: the HBM pre-dispatch constraint (never run a
config predicted to breach), the tuned-vs-default hard floor in
obs/regress, the history promotion of the new metrics, and the
``env-tunable-undeclared`` lint rule.
"""

import json
import os

import numpy as np
import pytest

from dbscan_tpu import config
from dbscan_tpu import bench as tune_mod


@pytest.fixture(autouse=True)
def _clean_profile():
    config.clear_profile()
    yield
    config.clear_profile()


# --- search space / constraint ----------------------------------------


def test_tunables_are_declared_registry_rows():
    declared = config.ENV_VARS
    for t in config.TUNABLES:
        assert t.name in declared, t.name
        assert declared[t.name].kind == t.kind, t.name
        assert len(t.choices) >= 2, t.name
        # every choice round-trips through the typed env reader
        for c in t.choices:
            os.environ[t.name] = str(c)
            try:
                got = config.env(t.name)
            finally:
                os.environ.pop(t.name, None)
            assert got == c, (t.name, c, got)


def test_hbm_ok_rejects_predicted_breach():
    fits, breaches = tune_mod.hbm_ok({})
    assert fits and breaches == []
    # shrink the budget until the knob-bounded families breach: the
    # constraint is graftshape's FAMILY_MODELS envelope itself
    fits, breaches = tune_mod.hbm_ok({}, budget=1 << 20)
    assert not fits and breaches


def test_sample_candidates_never_proposes_breaching_config():
    cands = tune_mod.sample_candidates(16, seed=3)
    assert cands[0] == {}  # the default is always entrant 0
    declared = {t.name: t for t in config.TUNABLES}
    for cand in cands:
        fits, breaches = tune_mod.hbm_ok(cand)
        assert fits, breaches
        for name, value in cand.items():
            assert value in declared[name].choices
    # deterministic: the same seed reproduces the tournament field
    assert cands == tune_mod.sample_candidates(16, seed=3)
    # a tiny budget filters the slot-heavy combos BEFORE evaluation —
    # sampled candidates that would breach are resampled, never run
    # (entrant 0, the operator's current defaults, is the baseline and
    # is not re-filtered: it is what already runs today)
    small = tune_mod.sample_candidates(16, seed=3, budget=1 << 33)
    for cand in small[1:]:
        fits, _ = tune_mod.hbm_ok(cand, budget=1 << 33)
        assert fits


# --- profile object ----------------------------------------------------


def test_profile_roundtrip_validation_and_precedence(tmp_path, monkeypatch):
    values = {
        "DBSCAN_PULL_INFLIGHT": 3,
        "DBSCAN_PROP_UNIONFIND": "1",
    }
    prof = config.Profile("cpu", "headline", values, {"rev": "x"})
    path = str(tmp_path / "p.json")
    prof.save(path)
    loaded = config.Profile.load(path)
    assert loaded.values == values
    assert loaded.meta == {"rev": "x"}
    monkeypatch.delenv("DBSCAN_PULL_INFLIGHT", raising=False)
    loaded.apply()
    assert config.env("DBSCAN_PULL_INFLIGHT") == 3
    # an explicit export still wins: profiles are tuned DEFAULTS
    monkeypatch.setenv("DBSCAN_PULL_INFLIGHT", "2")
    assert config.env("DBSCAN_PULL_INFLIGHT") == 2
    config.clear_profile()
    monkeypatch.delenv("DBSCAN_PULL_INFLIGHT", raising=False)
    assert config.env("DBSCAN_PULL_INFLIGHT") == 2  # table default


def test_profile_rejects_undeclared_knob_and_value(tmp_path):
    with pytest.raises(ValueError, match="not a declared Tunable"):
        config.Profile("cpu", "w", {"DBSCAN_NOT_A_KNOB": 1}).validate()
    with pytest.raises(ValueError, match="outside the declared"):
        config.Profile(
            "cpu", "w", {"DBSCAN_PULL_INFLIGHT": 999}
        ).validate()


# --- the --tune smoke ---------------------------------------------------


def test_tune_smoke_and_cli_profile_roundtrip(tmp_path, capsys):
    """Tiny-budget tournament: a committed profile whose speedup is
    >= 1.0 by construction (the default is a tournament entrant), the
    history gate/append runs green, and the written profile round-trips
    through ``cli.py --profile`` into a real run."""
    out_dir = str(tmp_path / "profiles")
    hist = str(tmp_path / "history.jsonl")
    rc = tune_mod.main(
        [
            "--tune", "--n", "3000", "--candidates", "3",
            "--rounds", "1", "--budget-s", "180",
            "--out-dir", out_dir, "--history", hist, "--seed", "1",
        ]
    )
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["tuned_vs_default_speedup"] >= 1.0
    prof_path = result["profile"]
    prof = config.Profile.load(prof_path)
    assert prof.meta["tuned_vs_default_speedup"] >= 1.0
    # the tune capture landed in the history with the gated metric
    recs = [json.loads(l) for l in open(hist) if l.strip()]
    metrics = {r["metric"] for r in recs}
    assert "tuned_vs_default_speedup" in metrics
    config.clear_profile()

    # round-trip: cli.py --profile applies the committed profile
    from dbscan_tpu import cli as cli_mod

    rng = np.random.default_rng(0)
    pts = np.concatenate(
        [rng.normal(c, 0.4, (150, 2)) for c in [(0, 0), (5, 5)]]
    )
    in_csv = str(tmp_path / "in.csv")
    out_csv = str(tmp_path / "out.csv")
    np.savetxt(in_csv, pts, delimiter=",")
    rc = cli_mod.main(
        [
            "--input", in_csv, "--output", out_csv,
            "--eps", "0.5", "--min-points", "5",
            "--profile", prof_path, "--stats",
        ]
    )
    assert rc == 0
    assert config.active_profile_values() == prof.values
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["n_clusters"] == 2
    assert os.path.exists(out_csv)


def test_tune_under_tiny_hbm_budget_only_fielded_safe_configs():
    """The pre-dispatch constraint reaches the tournament field: with a
    tight HBM budget every sampled candidate prices under it."""
    budget = 1 << 34
    cands = tune_mod.sample_candidates(8, seed=0, budget=budget)
    assert len(cands) >= 2  # the space is not empty under the budget
    for cand in cands:
        fits, breaches = tune_mod.hbm_ok(cand, budget=budget)
        assert fits, (cand, breaches)


# --- gates --------------------------------------------------------------


def test_regress_floor_tuned_vs_default_speedup():
    from dbscan_tpu.obs import regress

    def rec(v):
        return {
            "metric": "tuned_vs_default_speedup",
            "value": v,
            "backend": "cpu",
            "resident_hot": None,
            "source": "x",
        }

    out = regress.compare([rec(1.2)], [])
    assert not out["regressions"] and out["ok"][0]["direction"] == "floor"
    out = regress.compare([rec(0.93)], [])
    (bad,) = out["regressions"]
    assert bad["direction"] == "floor"
    # exactly 1.0 (the default winning its own tournament) is green
    assert not regress.compare([rec(1.0)], [])["regressions"]


def test_regress_direction_prop_sweeps():
    from dbscan_tpu.obs import regress

    assert regress.direction("anchor_prop_sweeps") == regress.LOWER_BETTER
    assert regress.direction("headline_prop_sweeps") == regress.LOWER_BETTER


def test_bench_history_promotes_new_metrics():
    from dbscan_tpu.obs import bench_history

    cap = {
        "metric": "tune",
        "backend": "cpu",
        "tuned_vs_default_speedup": 1.07,
        "anchor_prop_sweeps": 3,
        "anchor_prop_mode": "unionfind",  # a label, NOT promoted
    }
    recs = bench_history.normalize_capture(cap, "t.json", "rev")
    by = {r["metric"]: r for r in recs}
    assert by["tuned_vs_default_speedup"]["unit"] == "ratio"
    assert by["anchor_prop_sweeps"]["unit"] == "iters"
    assert "anchor_prop_mode" not in by


# --- lint rule ----------------------------------------------------------


def test_lint_env_tunable_undeclared(monkeypatch):
    import dbscan_tpu
    from dbscan_tpu import lint as lint_mod

    pkg_dir = os.path.dirname(os.path.abspath(dbscan_tpu.__file__))
    cfg_py = os.path.join(pkg_dir, "config.py")

    findings, _ = lint_mod.lint_paths([cfg_py])
    assert [f for f in findings if f.rule == "env-tunable-undeclared"] == []

    bad = config.TUNABLES + (
        config.Tunable("DBSCAN_NOT_DECLARED", "int", (1, 2), "bad"),
        config.Tunable("DBSCAN_PULL_INFLIGHT", "str", ("1",), "kind"),
        config.Tunable("DBSCAN_GROUP_SLOTS", "int", (), "empty"),
    )
    monkeypatch.setattr(config, "TUNABLES", bad)
    findings, _ = lint_mod.lint_paths([cfg_py])
    msgs = [
        f.message for f in findings if f.rule == "env-tunable-undeclared"
    ]
    assert len(msgs) == 3
    assert any("DBSCAN_NOT_DECLARED" in m for m in msgs)
    assert any("kind" in m and "DBSCAN_PULL_INFLIGHT" in m for m in msgs)
    assert any("empty" in m and "DBSCAN_GROUP_SLOTS" in m for m in msgs)
    # the rule is in the catalog (a finding under an unlisted id would
    # crash the --rules/--list-rules contract)
    assert "env-tunable-undeclared" in lint_mod.RULES
