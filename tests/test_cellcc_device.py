"""Device-resident cellcc finalize (parallel/cellgraph.py
``finalize_device`` + ops/banded.py ``compiled_cellcc_unpack`` /
``compiled_cellcc_cc`` + ops/propagation.py ``window_cc``).

The parity contract is EXACT: the device finalize must produce
byte-identical labels AND flags to the host oracle
(``DBSCAN_CELLCC_DEVICE=0``) — not just ARI 1.0. That is a real
contract, not luck: seeds are component-MINIMUM fold indices, so the
CC algorithm's component NUMBERING (scipy's arbitrary ids vs the
device's min-index representatives) never reaches a label, and every
other step is the same int32 algebra (PARITY.md "Cellcc finalize").
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import Engine, train

pytestmark = pytest.mark.cellcc


def _blobs(rng, scale=1):
    return np.concatenate(
        [rng.normal(c, 0.6, (1500 * scale, 2)) for c in [(0, 0), (6, 6), (-5, 7)]]
        + [rng.uniform(-10, 12, (500 * scale, 2))]
    )


def _kw(engine=Engine.ARCHERY, maxpp=700):
    return dict(
        eps=0.3, min_points=8, max_points_per_partition=maxpp,
        engine=engine, neighbor_backend="banded",
    )


def _toggle(monkeypatch, pts, kw):
    """(host model, device model) for one dataset/config pair."""
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "0")
    m_host = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_dev = train(pts, **kw)
    return m_host, m_dev


@pytest.mark.parametrize("engine", [Engine.NAIVE, Engine.ARCHERY])
def test_device_vs_host_banded_exact(engine, rng, monkeypatch):
    """Tentpole parity pin, both border semantics: multi-partition
    banded run, byte-identical labels/flags, and the device run really
    took the device path (cc sweeps >= 1) while the host run did not."""
    pts = _blobs(rng)
    m_host, m_dev = _toggle(monkeypatch, pts, _kw(engine))
    assert m_host.stats["n_partitions"] > 4
    assert m_dev.stats["cellcc_cc_iters"] >= 1
    assert m_host.stats["cellcc_cc_iters"] == 0
    np.testing.assert_array_equal(m_host.clusters, m_dev.clusters)
    np.testing.assert_array_equal(m_host.flags, m_dev.flags)
    # the whole-finalize wall is stamped on both modes (the bench key)
    for m in (m_host, m_dev):
        assert m.stats["timings"]["cellcc_finalize_s"] >= 0


def test_device_vs_host_haversine(rng, monkeypatch):
    """The spherical-chord banded payload (3-D points, projected grid)
    goes through the same finalize: exact parity, device path taken."""
    lat = np.concatenate([rng.normal(45.0, 0.01, 1200) for _ in range(3)])
    lon = np.concatenate(
        [rng.normal(c, 0.015, 1200) for c in (-74.0, -73.8, -73.6)]
    )
    pts = np.stack([lat, lon], axis=1)
    kw = dict(
        eps=0.5, min_points=6, max_points_per_partition=1500,
        metric="haversine", neighbor_backend="banded",
    )
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "0")
    m_host = train(pts, **kw)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_dev = train(pts, **kw)
    assert m_dev.stats["cellcc_cc_iters"] >= 1, (
        "banded route not taken — widen the geometry margins"
    )
    np.testing.assert_array_equal(m_host.clusters, m_dev.clusters)
    np.testing.assert_array_equal(m_host.flags, m_dev.flags)


def test_dense_and_sparse_paths_unaffected(rng, monkeypatch):
    """Engines with no banded finalize must be bit-for-bit unaffected
    by the knob: the dense backend and the sparse-cosine front-end."""
    pts = _blobs(rng)[:2000]
    kw = dict(eps=0.3, min_points=8, max_points_per_partition=700,
              neighbor_backend="dense")
    m_host, m_dev = _toggle(monkeypatch, pts, kw)
    assert m_dev.stats["cellcc_cc_iters"] == 0
    np.testing.assert_array_equal(m_host.clusters, m_dev.clusters)
    np.testing.assert_array_equal(m_host.flags, m_dev.flags)

    sp = pytest.importorskip("scipy.sparse")
    from dbscan_tpu.ops.sparse import sparse_cosine_dbscan

    srng = np.random.default_rng(7)
    k, per, vocab, nnz = 20, 50, 3000, 16
    feat = srng.integers(0, vocab, size=(k, nnz))
    val = srng.random((k, nnz)) + 0.1
    blob_of = np.repeat(np.arange(k), per)
    rows = np.repeat(np.arange(k * per), nnz)
    cols = feat[blob_of].ravel()
    vals = (val[blob_of] * srng.uniform(0.9, 1.1, (k * per, nnz))).ravel()
    x = sp.coo_matrix((vals, (rows, cols)), shape=(k * per, vocab)).tocsr()
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "0")
    c0, f0 = sparse_cosine_dbscan(x, max_points_per_partition=256,
                                  eps=0.05, min_points=5)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    c1, f1 = sparse_cosine_dbscan(x, max_points_per_partition=256,
                                  eps=0.05, min_points=5)
    np.testing.assert_array_equal(c0, c1)
    np.testing.assert_array_equal(f0, f1)


def test_streaming_parity_and_steady_state(monkeypatch):
    """Streaming micro-batches: per-update ids identical across the
    toggle, and the cellcc shapes ratchet — steady-state updates mint
    ZERO new cellcc compiles (the shape_floors contract extended to
    cpad / out_slots / the or-gid pad)."""
    from dbscan_tpu import obs
    from dbscan_tpu.config import DBSCANConfig
    from dbscan_tpu.streaming import StreamingDBSCAN

    def run(dev):
        monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", dev)
        rng = np.random.default_rng(5)
        cfg = DBSCANConfig(
            eps=0.3, min_points=6, max_points_per_partition=10**9,
            neighbor_backend="banded", static_partition_pad=True,
        )
        s = StreamingDBSCAN(eps=0.3, min_points=6, config=cfg)
        outs = []
        for i in range(4):
            b = np.concatenate(
                [rng.normal(c, 0.4, (900 + 40 * i, 2)) for c in [(0, 0), (5, 5)]]
            )
            outs.append(np.asarray(s.update(b).clusters).copy())
        return outs

    o_host = run("0")
    o_dev = run("1")
    for a, b in zip(o_host, o_dev):
        np.testing.assert_array_equal(a, b)

    obs.enable()
    try:
        monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
        rng = np.random.default_rng(9)
        cfg = DBSCANConfig(
            eps=0.3, min_points=6, max_points_per_partition=10**9,
            neighbor_backend="banded", static_partition_pad=True,
        )
        s = StreamingDBSCAN(eps=0.3, min_points=6, config=cfg)
        snap = None
        for i in range(5):
            b = np.concatenate(
                [rng.normal(c, 0.4, (900 + 40 * i, 2)) for c in [(0, 0), (5, 5)]]
            )
            if i == 3:
                snap = obs.counters()
            s.update(b)
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.cellcc.unpack", 0) == 0, delta
        assert delta.get("compiles.cellcc.cc", 0) == 0, delta
        assert delta.get("cellcc.cc_iters", 0) >= 1  # path stayed live
    finally:
        obs.disable()


def test_fault_transient_heals(rng, monkeypatch):
    """cellcc_cc#0:TRANSIENT: the supervised retry re-dispatches the
    fused CC from intact inputs and the run heals with device labels."""
    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_ref = train(pts, **_kw())
    assert m_ref.stats["cellcc_cc_iters"] >= 1
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "cellcc_cc#0:TRANSIENT")
    m_t = train(pts, **_kw())
    assert m_t.stats["faults"]["injected"] >= 1
    assert m_t.stats["faults"]["retries"] >= 1
    assert m_t.stats["cellcc_cc_iters"] >= 1  # healed ON the device
    np.testing.assert_array_equal(m_t.clusters, m_ref.clusters)
    np.testing.assert_array_equal(m_t.flags, m_ref.flags)


def test_fault_persistent_degrades_to_host(rng, monkeypatch):
    """cellcc_cc#0:PERSISTENT: the WHOLE finalize degrades to the host
    oracle (the records' combo/bits handles were never consumed) with
    labels intact — the acceptance shape of the fault surface."""
    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_ref = train(pts, **_kw())
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "cellcc_cc#0:PERSISTENT")
    m_p = train(pts, **_kw())
    assert m_p.stats["faults"]["fallbacks"] >= 1
    assert m_p.stats["cellcc_cc_iters"] == 0  # host oracle produced them
    np.testing.assert_array_equal(m_p.clusters, m_ref.clusters)
    np.testing.assert_array_equal(m_p.flags, m_ref.flags)


def test_zero_retrace_and_thin_pull(rng, monkeypatch):
    """Compile pin: a second same-shaped train mints ZERO new cellcc
    kernels (shapes are ratcheted/laddered), runs ZERO per-chunk combo
    pulls (the finalize's only D2H is the thin label pull), and the
    cc_iters counter delta equals the stats figure."""
    from dbscan_tpu import obs

    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    obs.enable()
    try:
        train(pts, **_kw())  # warm: compiles the cellcc rungs
        snap = obs.counters()
        m = train(pts, **_kw())
        delta = obs.counters_delta(snap)
        assert delta.get("compiles.cellcc.unpack", 0) == 0, delta
        assert delta.get("compiles.cellcc.cc", 0) == 0, delta
        assert delta.get("checkpoint.chunk_pulls", 0) == 0, (
            "device finalize must not pull per-chunk combo buffers"
        )
        assert delta.get("cellcc.cc_iters", 0) == m.stats["cellcc_cc_iters"]
    finally:
        obs.disable()


def test_multi_chunk_fused_cc(rng, monkeypatch):
    """Several compact chunks feed ONE fused cc dispatch: shrink the
    chunk budget so the run flushes >= 2 chunks, then pin exact parity
    (cells never cross chunks, partials merge elementwise)."""
    from dbscan_tpu import obs
    from dbscan_tpu.parallel import driver

    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 1 << 12)
    pts = _blobs(rng)
    obs.enable()
    try:
        monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "0")
        m_host = train(pts, **_kw())
        snap = obs.counters()
        monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
        m_dev = train(pts, **_kw())
        delta = obs.counters_delta(snap)
        assert delta.get("checkpoint.chunk_flushes", 0) >= 2, delta
        assert delta.get("compiles.cellcc.cc", 0) >= 1
    finally:
        obs.disable()
    assert m_dev.stats["cellcc_cc_iters"] >= 1
    np.testing.assert_array_equal(m_host.clusters, m_dev.clusters)
    np.testing.assert_array_equal(m_host.flags, m_dev.flags)


def test_cc_iters_independent_of_chunking(rng, monkeypatch):
    """cellcc.cc_iters is a property of the merged cell graph, not of
    the chunk/padding layout: the same data at different chunk budgets
    (different or-gather pads, different partial counts) must converge
    in the SAME sweep count — the regress gate trends graph diameter,
    and a padding-dependent count (the sentinel-row phantom-adjacency
    bug) would false-flag across ladder boundaries."""
    from dbscan_tpu.parallel import driver

    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_one = train(pts, **_kw())
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 1 << 12)
    m_many = train(pts, **_kw())
    assert m_one.stats["cellcc_cc_iters"] >= 1
    assert (
        m_many.stats["cellcc_cc_iters"] == m_one.stats["cellcc_cc_iters"]
    )
    np.testing.assert_array_equal(m_one.clusters, m_many.clusters)


def test_residency_budget_degrades_to_host_midrun(rng, monkeypatch):
    """DBSCAN_CELLCC_DEVICE_SLOTS: a run whose chunks exceed the staged
    budget degrades the finalize to the host oracle MID-RUN — the
    staged partials are dropped, already-flushed chunks re-enter the
    pipelined pulls, and labels stay identical (the review finding:
    device mode must not pin unbounded chunk metadata on HBM)."""
    from dbscan_tpu import obs
    from dbscan_tpu.parallel import driver

    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 1 << 12)
    pts = _blobs(rng)
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    m_ref = train(pts, **_kw())
    assert m_ref.stats["cellcc_cc_iters"] >= 1
    # budget below one chunk: the first flush already overflows
    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE_SLOTS", "1024")
    obs.enable()
    try:
        snap = obs.counters()
        m_cap = train(pts, **_kw())
        delta = obs.counters_delta(snap)
    finally:
        obs.disable()
    assert m_cap.stats["cellcc_cc_iters"] == 0  # host oracle finished it
    assert delta.get("checkpoint.chunk_pulls", 0) >= 2  # pulls resumed
    np.testing.assert_array_equal(m_cap.clusters, m_ref.clusters)
    np.testing.assert_array_equal(m_cap.flags, m_ref.flags)


def test_unpack_combo_shared_helper():
    """The one host unpack implementation (driver._pull_record, the
    tail merge, and the degrade path all route here): packed bits +
    validity mask -> (core bools, border-candidate positions), exactly
    np.unpackbits/np.flatnonzero semantics."""
    from dbscan_tpu.parallel import cellgraph

    rng = np.random.default_rng(3)
    total = 1024
    core = rng.random(total) < 0.4
    valid = rng.random(total) < 0.8
    combo = np.concatenate(
        [np.packbits(core), np.arange(12, dtype=np.uint8)]  # scan tail
    )
    layout = {"total": total, "validflat": valid}
    got_core, got_bpos = cellgraph.unpack_combo(combo, layout)
    np.testing.assert_array_equal(got_core, core)
    np.testing.assert_array_equal(got_bpos, np.flatnonzero(valid & ~core))


def test_or_gid_positions_repeats_runs():
    """Per-position cell ids expand the run-compressed readout plan: a
    cell spanning scan blocks repeats once per gather position."""
    from dbscan_tpu.parallel import cellgraph

    layout = {
        "or_pos": np.arange(6),
        "or_starts": np.array([0, 1, 4]),
        "or_gid": np.array([7, 3, 9]),
    }
    np.testing.assert_array_equal(
        cellgraph.or_gid_positions(layout),
        np.array([7, 3, 3, 3, 9, 9], dtype=np.int32),
    )


def test_registration_pins():
    """Cross-module contracts: the fault site, the compile families,
    the declared telemetry, and the lint models all name the new path."""
    from dbscan_tpu import faults
    from dbscan_tpu.lint.shapes import FAMILY_MODELS, TUPLE_COUPLED
    from dbscan_tpu.obs import schema

    assert faults.SITE_CELLCC in faults._SITES
    (clause,) = faults.parse_fault_spec("cellcc_cc#1:TRANSIENT*2")
    assert clause.site == "cellcc_cc"
    assert clause.ordinal == 1 and clause.count == 2
    assert "cellcc.unpack" in schema.COMPILE_FAMILIES
    assert "cellcc.cc" in schema.COMPILE_FAMILIES
    assert schema.is_declared("counter", "cellcc.cc_iters")
    assert schema.is_declared("span", "cellcc.finalize")
    # devtime coverage rides the family registry
    assert schema.is_declared("span", "devtime.cellcc.cc")
    assert "cellcc.unpack" in FAMILY_MODELS
    assert "cellcc.cc" in FAMILY_MODELS
    assert ("cores", "bitses") in TUPLE_COUPLED["cellcc.cc"]


def test_shapecheck_subprocess_clean(tmp_path):
    """DBSCAN_SHAPECHECK=1 rerun of a banded device-finalize train in a
    fresh process: the atexit JSON report must be violation-free with
    both cellcc families covered (the runtime model cross-check)."""
    report = tmp_path / "shapecheck.json"
    code = (
        "import numpy as np\n"
        "from dbscan_tpu import train\n"
        "rng = np.random.default_rng(1)\n"
        "pts = np.concatenate([rng.normal(c, 0.6, (1200, 2))"
        " for c in [(0, 0), (6, 6)]])\n"
        "m = train(pts, eps=0.3, min_points=8,"
        " max_points_per_partition=700, neighbor_backend='banded')\n"
        "assert m.stats['cellcc_cc_iters'] >= 1, m.stats\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_CELLCC_DEVICE="1",
        DBSCAN_SHAPECHECK="1",
        DBSCAN_SHAPECHECK_REPORT=str(report),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    rep = json.loads(report.read_text())
    assert rep["violations"] == []
    assert "cellcc.unpack" in rep["sites"]
    assert "cellcc.cc" in rep["sites"]
