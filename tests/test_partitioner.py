"""EvenSplitPartitioner tests.

The two fixture tests reproduce reference EvenSplitPartitionerSuite.scala:22-61
EXACTLY — same cell sets, same max-points/min-size, same expected rectangles in
the same output order — pinning our deterministic candidate order (x-cuts
ascending then y-cuts, first-win ties) as the reference order made explicit."""

import numpy as np

from dbscan_tpu.parallel import partitioner


def _sections(rows):
    cells = np.array([r[:4] for r in rows], dtype=np.float64)
    counts = np.array([r[4] for r in rows], dtype=np.int64)
    return cells, counts


def test_should_find_partitions():
    # EvenSplitPartitionerSuite.scala:23-49
    cells, counts = _sections(
        [
            (0, 0, 1, 1, 3),
            (0, 2, 1, 3, 6),
            (1, 1, 2, 2, 7),
            (1, 0, 2, 1, 2),
            (2, 0, 3, 1, 5),
            (2, 2, 3, 3, 4),
        ]
    )
    got = partitioner.partition(cells, counts, 9, 1.0)
    expected = [
        ((1, 2, 3, 3), 4),
        ((0, 2, 1, 3), 6),
        ((0, 1, 3, 2), 7),
        ((2, 0, 3, 1), 5),
        ((0, 0, 2, 1), 5),
    ]
    assert len(got) == len(expected)
    for (rect, count), (erect, ecount) in zip(got, expected):
        np.testing.assert_allclose(rect, erect)
        assert count == ecount


def test_should_find_two_splits():
    # EvenSplitPartitionerSuite.scala:51-60
    cells, counts = _sections(
        [
            (0, 0, 1, 1, 3),
            (2, 2, 3, 3, 4),
            (0, 1, 1, 2, 2),
        ]
    )
    got = partitioner.partition(cells, counts, 4, 1.0)
    np.testing.assert_allclose(got[0][0], (1, 0, 3, 3))
    assert got[0][1] == 4
    np.testing.assert_allclose(got[1][0], (0, 1, 1, 3))
    assert got[1][1] == 2


def test_respects_max_points_where_splittable(rng):
    pts = rng.uniform(-5, 5, size=(2000, 2))
    from dbscan_tpu.ops import geometry as geo

    cells, counts, _ = geo.cell_histogram(pts, 0.5)
    parts = partitioner.partition(cells, counts, 300, 0.5)
    assert sum(c for _, c in parts) == 2000
    # every partition either fits the bound or is a minimal unsplittable cell
    for rect, count in parts:
        splittable = (rect[2] - rect[0] > 1.0) or (rect[3] - rect[1] > 1.0)
        assert count <= 300 or not splittable


def test_empty_partitions_dropped(rng):
    # two far-apart blobs force empty middle partitions to appear and be cut
    pts = np.concatenate(
        [
            rng.normal(0, 0.1, size=(400, 2)),
            rng.normal(20, 0.1, size=(400, 2)),
        ]
    )
    from dbscan_tpu.ops import geometry as geo

    cells, counts, _ = geo.cell_histogram(pts, 0.5)
    parts = partitioner.partition(cells, counts, 100, 0.5)
    assert all(c > 0 for _, c in parts)
    assert sum(c for _, c in parts) == 800


def test_no_points_lost_to_fp_drift(rng):
    # Regression: with eps=0.3 (cell 0.6, not exactly representable) the
    # reference's all-double formulation drifts cut positions away from
    # trunc-derived cell corners by ulps, dropping cells from counts and
    # leaving coverage holes. The integer-domain partitioner must keep the
    # exact-count invariant and tile the bounding box.
    from dbscan_tpu.ops import geometry as geo

    pts = np.concatenate(
        [rng.normal(0, 1, (3000, 2)), rng.normal(8, 0.5, (2000, 2))]
    )
    cells, counts, _ = geo.cell_histogram_int(pts, 0.6)
    parts = partitioner.partition_cells(cells, counts, 250)
    assert sum(c for _, c in parts) == 5000
    # partitions tile the bounding box: every cell in exactly one partition
    rects = np.stack([r for r, _ in parts])
    cx, cy = cells[:, 0], cells[:, 1]
    owners = (
        (rects[:, None, 0] <= cx[None, :])
        & (cx[None, :] + 1 <= rects[:, None, 2])
        & (rects[:, None, 1] <= cy[None, :])
        & (cy[None, :] + 1 <= rects[:, None, 3])
    ).sum(axis=0)
    assert (owners == 1).all()
    # every point is inside its own partition's float main rect
    fr = geo.int_rects_to_float(rects, 0.6)
    covered = geo.contains_point(fr[:, None, :], pts[None, :, :]).any(axis=0)
    assert covered.all()


def test_unsplittable_overfull_cell_emitted_as_is():
    # one cell with more points than the bound cannot be split
    cells = np.array([[0.0, 0.0, 1.0, 1.0]])
    counts = np.array([50])
    parts = partitioner.partition(cells, counts, 10, 1.0)
    assert len(parts) == 1
    assert parts[0][1] == 50


def test_candidate_counts_matches_broadcast_oracle(rng):
    """The O(C + extent) histogram/prefix-sum candidate evaluation must agree
    with the direct [K, C] containment broadcast (_points_in over
    _possible_splits) for every candidate of random rects — the oracle is the
    reference's pointsInRectangle semantics made literal."""
    for _ in range(50):
        w, h = rng.integers(2, 30, size=2)
        x0, y0 = rng.integers(-40, 40, size=2)
        rect = np.array([x0, y0, x0 + w, y0 + h], dtype=np.int64)
        n_cells = int(rng.integers(1, 80))
        cells = np.stack(
            [
                rng.integers(x0, x0 + w, size=n_cells),
                rng.integers(y0, y0 + h, size=n_cells),
            ],
            axis=1,
        ).astype(np.int64)
        cells = np.unique(cells, axis=0)
        counts = rng.integers(1, 1000, size=cells.shape[0]).astype(np.int64)
        fast = partitioner._candidate_counts(
            rect, cells[:, 0], cells[:, 1], counts
        )
        oracle = partitioner._points_in(
            cells, counts, partitioner._possible_splits(rect)
        )
        np.testing.assert_array_equal(fast, oracle)


def test_effective_maxpp_heuristic():
    """auto_maxpp (VERDICT r3 item 7): when the densest 2eps cell
    under-fits the requested bound, the effective bound rises to
    K x pileup (capped) under auto_maxpp=True and stays put (warned)
    under the default."""
    from dbscan_tpu.config import DBSCANConfig
    from dbscan_tpu.parallel import driver

    counts = np.array([10, 25000, 300], dtype=np.int64)
    base = dict(eps=0.3, min_points=10)
    off = DBSCANConfig(max_points_per_partition=32768, **base)
    assert driver._effective_maxpp(off, counts) == 32768
    on = DBSCANConfig(
        max_points_per_partition=32768, auto_maxpp=True, **base
    )
    assert driver._effective_maxpp(on, counts) == 4 * 25000
    # already-fitting bound: untouched either way
    big = DBSCANConfig(
        max_points_per_partition=200000, auto_maxpp=True, **base
    )
    assert driver._effective_maxpp(big, counts) == 200000
    # cap: a monster pileup cannot push the bound past the known-good
    # production bucket width
    huge = np.array([1_000_000], dtype=np.int64)
    assert driver._effective_maxpp(on, huge) == driver._MAXPP_AUTO_CAP
    assert driver._effective_maxpp(off, np.empty(0, np.int64)) == 32768


def test_auto_maxpp_labels_unchanged(rng):
    """Raising the effective bound only changes the partition layout:
    the cluster STRUCTURE must match the default run exactly (global ids
    renumber with partition enumeration order, as in the reference's
    localClusterIds fold — so equality is up to label permutation).
    NAIVE engine: its order-free algebra is exactly partitioning-
    invariant; Archery's visited-noise adoption is order-dependent near
    seams (a border point adjacent to two clusters may be adopted by
    either), so it only agrees up to those adoptions."""
    from dbscan_tpu import Engine, train
    from dbscan_tpu.utils.ari import exact_match_up_to_permutation

    pts = np.concatenate(
        [rng.normal(c, 0.05, (1500, 2)) for c in [(0, 0), (3, 3), (6, 0)]]
        + [rng.uniform(-1, 7, (500, 2))]
    )
    kw = dict(eps=0.3, min_points=6, engine=Engine.NAIVE)
    m_off = train(pts, max_points_per_partition=400, **kw)
    m_on = train(
        pts, max_points_per_partition=400, auto_maxpp=True, **kw
    )
    assert m_on.stats["effective_maxpp"] > 400
    assert m_on.stats["n_partitions"] <= m_off.stats["n_partitions"]
    assert exact_match_up_to_permutation(m_off.clusters, m_on.clusters)
    np.testing.assert_array_equal(m_off.flags, m_on.flags)
    assert (
        m_on.stats["duplication_factor"]
        <= m_off.stats["duplication_factor"]
    )
