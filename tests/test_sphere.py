"""Spherical (haversine) spatial decomposition: projection bounds, chord
equivalence, end-to-end oracle parity, engine equality, and fallbacks.

The reference has no haversine support at all (euclidean only,
DBSCANPoint.scala:26-30); these tests pin the metric-aware decomposition
(ops/sphere.py + driver wiring) VERDICT r1 ranked first.
"""

import numpy as np
import pytest

from dbscan_tpu import train
from dbscan_tpu.ops import sphere
from dbscan_tpu.ops.distance import EARTH_RADIUS_KM, get_metric
from dbscan_tpu.utils.ari import adjusted_rand_index
from dbscan_tpu.utils.reference_engines import archery_fit, naive_fit


def _hav(a, b):
    """[N] f64 great-circle km between paired (lon, lat) degree rows."""
    m = get_metric("haversine")
    return np.asarray(m.pairwise(a, b)).diagonal()


def _geo_blobs(rng, centers, per=60, spread_km=0.12):
    """Gaussian lon/lat blobs of ~spread_km around (lon, lat) centers."""
    out = []
    for lon, lat in centers:
        dlat = spread_km / 111.0
        dlon = spread_km / (111.0 * np.cos(np.deg2rad(lat)))
        out.append(
            np.stack(
                [
                    rng.normal(lon, dlon, per),
                    rng.normal(lat, dlat, per),
                ],
                axis=1,
            )
        )
    return np.concatenate(out)


def test_chord_threshold_equivalence(rng):
    """hav <= eps iff chord <= chord_threshold(eps), checked on random
    near-threshold pairs (the embedding's exactness claim)."""
    eps = 0.5  # km
    base = np.array([-73.98, 40.75])
    # pairs spanning 0..2 eps separations
    a = base + rng.normal(0, 0.005, (4000, 2))
    b = a + rng.normal(0, 0.004, (4000, 2))
    both = np.concatenate([a, b])
    emb = sphere.embed(both, eps)
    assert emb is not None
    hav = _hav(a, b)
    ca = emb.chord[: len(a)]
    cb = emb.chord[len(a) :]
    chord = np.linalg.norm(ca - cb, axis=1)
    lhs = hav <= eps
    rhs = chord <= emb.eps_chord
    # exact equivalence up to f64 rounding: exclude a hairline band
    clear = np.abs(hav - eps) > 1e-9
    np.testing.assert_array_equal(lhs[clear], rhs[clear])


def test_projection_bounds(rng):
    """proj <= hav * (1 + slack) and hav <= ratio * proj * (1 + slack) for
    every pair — the two inequalities the halo and clique margins rest on."""
    eps = 1.0
    pts = _geo_blobs(
        rng,
        [(-74.0, 40.7), (-73.9, 41.3), (-73.5, 40.9), (-74.2, 41.1)],
        per=150,
        spread_km=20.0,
    )
    emb = sphere.embed(pts, eps)
    assert emb is not None
    i = rng.integers(0, len(pts), 3000)
    j = rng.integers(0, len(pts), 3000)
    hav = _hav(pts[i], pts[j])
    proj = np.linalg.norm(emb.proj[i] - emb.proj[j], axis=1)
    s = 1.0 + emb.slack
    assert (proj <= hav * s + 1e-9).all()
    assert (hav <= emb.cos_ratio * proj * s + 1e-9).all()


def test_embed_refuses_wrap_and_pole():
    eps = 1.0
    wrap = np.array([[179.9999, 10.0], [-179.9999, 10.0], [0.0, 10.0]])
    assert sphere.embed(wrap, eps) is None
    pole = np.array([[10.0, 89.0], [11.0, 89.0]])
    assert sphere.embed(pole, eps) is None
    # clear of both: fine
    ok = np.array([[179.0, 10.0], [178.0, 10.0]])
    assert sphere.embed(ok, eps) is not None


def test_lon_normalization_equivalence(rng):
    """Longitudes offset by 360 degrees produce identical labels."""
    pts = _geo_blobs(rng, [(-74.0, 40.7), (-73.9, 40.9)], per=50)
    shifted = pts.copy()
    shifted[:, 0] += 360.0
    m1 = train(pts, eps=0.5, min_points=5, metric="haversine")
    m2 = train(shifted, eps=0.5, min_points=5, metric="haversine")
    np.testing.assert_array_equal(m1.clusters, m2.clusters)
    np.testing.assert_array_equal(m1.flags, m2.flags)


@pytest.mark.parametrize("engine", ["naive", "archery"])
def test_haversine_spatial_matches_oracle(rng, engine):
    """End-to-end: multi-partition haversine run reproduces the f64
    haversine oracle exactly (ARI 1.0 + flag equality) — the projection
    and chord embedding must be invisible in the labels."""
    from dbscan_tpu import Engine

    centers = [
        (-74.0 + 0.04 * k, 40.6 + 0.05 * ((k * 7) % 5)) for k in range(12)
    ]
    pts = _geo_blobs(rng, centers, per=55, spread_km=0.1)
    noise = np.stack(
        [rng.uniform(-74.05, -73.5, 80), rng.uniform(40.5, 40.95, 80)],
        axis=1,
    )
    data = np.concatenate([pts, noise])
    eps = 0.35
    model = train(
        data, eps=eps, min_points=8, max_points_per_partition=128,
        metric="haversine",
        engine=Engine.NAIVE if engine == "naive" else Engine.ARCHERY,
    )
    assert model.stats["projected"]
    assert model.stats["n_partitions"] > 1
    oracle_fit = naive_fit if engine == "naive" else archery_fit
    ocl, ofl = oracle_fit(data, eps, 8, metric="haversine")
    assert adjusted_rand_index(model.clusters, ocl) == 1.0
    np.testing.assert_array_equal(model.flags, ofl)


def test_haversine_banded_equals_dense(rng):
    """Forced-banded and dense backends agree bit-for-bit on spherical
    data (same f32 chord difference-form arithmetic on both paths)."""
    pts = _geo_blobs(
        rng, [(-74.0, 40.7), (-73.95, 40.75), (-73.9, 40.8)], per=400,
        spread_km=0.4,
    )
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=512,
        metric="haversine",
    )
    m_b = train(pts, neighbor_backend="banded", **kw)
    m_d = train(pts, neighbor_backend="dense", **kw)
    assert m_b.stats["n_banded_groups"] > 0
    assert m_d.stats["n_banded_groups"] == 0
    np.testing.assert_array_equal(m_b.clusters, m_d.clusters)
    np.testing.assert_array_equal(m_b.flags, m_d.flags)


def test_haversine_wrap_fallback_still_correct(rng):
    """Antimeridian-spanning data refuses the projection and keeps the
    single-partition path — labels still match the oracle."""
    a = _geo_blobs(rng, [(179.98, -20.0)], per=40, spread_km=0.1)
    b = _geo_blobs(rng, [(-179.98, -20.0)], per=40, spread_km=0.1)
    data = np.concatenate([a, b])
    model = train(data, eps=6.0, min_points=5, metric="haversine")
    assert not model.stats["projected"]
    assert model.stats["n_partitions"] == 1
    ocl, ofl = naive_fit(data, 6.0, 5, metric="haversine")
    assert adjusted_rand_index(model.clusters, ocl) == 1.0
    # the two sides of the seam are one cluster (only ~4.4 km apart)
    assert model.n_clusters == 1


def test_haversine_wide_latitude_span_spatial_dense(rng):
    """A ~55-degree latitude span fails the banded reach margin
    (cos_ratio > sqrt(2)) but must still decompose spatially and match
    the oracle via the per-partition dense kernel."""
    centers = [(-70.0, lat) for lat in (2.0, 15.0, 30.0, 45.0, 57.0)]
    pts = _geo_blobs(rng, centers, per=50, spread_km=0.1)
    emb = sphere.embed(pts, 0.35)
    assert emb is not None and not emb.banded_ok
    model = train(
        pts, eps=0.35, min_points=8, max_points_per_partition=64,
        metric="haversine",
    )
    assert model.stats["projected"]
    assert model.stats["n_partitions"] > 1
    assert model.stats["n_banded_groups"] == 0
    ocl, _ = naive_fit(pts, 0.35, 8, metric="haversine")
    assert adjusted_rand_index(model.clusters, ocl) == 1.0


def test_haversine_banded_equals_dense_on_mesh(rng):
    """Spherical chord payloads (3 coordinate planes) through the banded
    engine + compact postpass, sharded over the mesh, agree bit-for-bit
    with the dense path — the D-plane generalization must hold under
    sharding too."""
    from dbscan_tpu.parallel.mesh import make_mesh

    pts = _geo_blobs(
        rng, [(-74.0, 40.7), (-73.95, 40.75), (-73.9, 40.8)], per=400,
        spread_km=0.4,
    )
    kw = dict(
        eps=0.3, min_points=6, max_points_per_partition=512,
        metric="haversine",
    )
    mesh = make_mesh()
    m_b = train(pts, neighbor_backend="banded", mesh=mesh, **kw)
    m_d = train(pts, neighbor_backend="dense", mesh=mesh, **kw)
    assert m_b.stats["n_banded_groups"] > 0
    assert "cellcc_pull_core_s" in m_b.stats["timings"]  # compact ran
    np.testing.assert_array_equal(m_b.clusters, m_d.clusters)
    np.testing.assert_array_equal(m_b.flags, m_d.flags)
    # and equal to the unsharded run
    m_s = train(pts, neighbor_backend="banded", **kw)
    np.testing.assert_array_equal(m_b.clusters, m_s.clusters)
