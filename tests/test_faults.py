"""Supervised device dispatch (dbscan_tpu/faults.py).

The reference delegates ALL fault tolerance to Spark lineage — a lost
executor silently replays the same expensive work (DBSCAN.scala:59-60).
Our in-process story is the supervised-dispatch shape parallel-DBSCAN
systems assume from their runtime (Wang et al., arXiv:1912.06255):
transient device faults retry with bounded backoff, RESOURCE_EXHAUSTED
halves the dispatch's batch budget, and a persistent failure degrades
THAT group to the CPU ``local_dbscan`` engine instead of aborting.

These tests pin, with deterministic injection (``DBSCAN_FAULT_SPEC``):

- the spec grammar, fault classification, and the retry/halve/degrade
  state machine of :func:`faults.supervised` in isolation;
- label parity: a run with injected faults mid-device-phase produces
  labels EXACTLY equal to the fault-free run (exact equality implies
  ARI == 1.0), across the banded, dense, and streaming dispatch
  families, for transient, budget, and persistent faults;
- the abort path: a retries-exhausted fault with CPU fallback disabled
  flushes the current compact chunk and records the abort site before
  raising, so the resumed leg restarts after the last completed group;
- the whole distributed suite once under a nonzero fault spec (the
  tier-1 smoke target: ``pytest -m faults``), so parity under injected
  faults stays in CI forever.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from dbscan_tpu import Engine, train
from dbscan_tpu import faults
from dbscan_tpu.parallel import driver

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# zero backoff everywhere: the tests pin the retry/degrade decisions,
# not the sleeps (backoff determinism has its own test below)
NO_BACKOFF = faults.RetryPolicy(max_retries=3, backoff_base_s=0.0)


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    """Every test starts with virgin per-site ordinal counters and no
    sleeping between retries; monkeypatch restores the env after."""
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    yield
    faults.reset_registry()


def _spec(monkeypatch, spec):
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", spec)
    faults.reset_registry()


# --- spec grammar and classification ----------------------------------


def test_parse_fault_spec_grammar():
    clauses = faults.parse_fault_spec(
        "dispatch#3:RESOURCE_EXHAUSTED*2; banded#0:TRANSIENT ;"
        "*#7:PERSISTENT;"
    )
    assert clauses == (
        faults.FaultClause("dispatch", 3, faults.RESOURCE_EXHAUSTED, 2),
        faults.FaultClause("banded", 0, faults.TRANSIENT, 1),  # count defaults
        faults.FaultClause("*", 7, faults.PERSISTENT, 1),
    )
    assert faults.parse_fault_spec("") == ()


@pytest.mark.parametrize(
    "bad",
    [
        "dispatch:TRANSIENT",  # no ordinal
        "dispatch#1:BOGUS_KIND",  # unknown kind
        "dispatch#x:TRANSIENT",  # non-numeric ordinal
        "garbage",
    ],
)
def test_parse_fault_spec_rejects_bad_clauses(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_spec(bad)


def test_classify_mapping():
    # programming errors are never supervised — retrying can't succeed
    assert faults.classify(ValueError("bad shape")) is None
    assert faults.classify(TypeError("not a tracer")) is None
    assert faults.classify(RuntimeError("plain host error")) is None
    # device-runtime errors are recognized structurally
    XlaErr = type("XlaRuntimeError", (RuntimeError,), {})
    assert faults.classify(XlaErr("INTERNAL: device halted")) == faults.TRANSIENT
    assert (
        faults.classify(XlaErr("RESOURCE_EXHAUSTED: 3.2G > 2.9G free"))
        == faults.RESOURCE_EXHAUSTED
    )
    JaxlibErr = type(
        "RuntimeError", (RuntimeError,), {"__module__": "jaxlib.xla_extension"}
    )
    assert faults.classify(JaxlibErr("UNAVAILABLE: socket closed")) == faults.TRANSIENT
    # injected faults carry their kind; an already-supervised fatal never re-wraps
    inj = faults.FaultInjected("dispatch", 0, faults.PERSISTENT)
    assert faults.classify(inj) == faults.PERSISTENT
    fatal = faults.FatalDeviceFault("dispatch", 0, 1, inj)
    assert faults.classify(fatal) is None


# --- the supervised() state machine in isolation ----------------------


def test_supervised_transient_retries_then_succeeds(monkeypatch):
    _spec(monkeypatch, "dispatch#0:TRANSIENT*2")
    snap = faults.counters.snapshot()
    calls = []
    out = faults.supervised(
        "dispatch", lambda b: calls.append(b) or "ok", policy=NO_BACKOFF
    )
    assert out == "ok"
    assert calls == [None]  # injection fires BEFORE the attempt body
    d = faults.counters.delta(snap)
    assert d["attempts"] == 3 and d["retries"] == 2 and d["injected"] == 2
    assert d["fallbacks"] == 0


def test_supervised_retries_real_device_errors():
    XlaErr = type("XlaRuntimeError", (RuntimeError,), {})
    n = [0]

    def attempt(_b):
        n[0] += 1
        if n[0] < 3:
            raise XlaErr("INTERNAL: channel reset")
        return "done"

    assert faults.supervised("dispatch", attempt, policy=NO_BACKOFF) == "done"
    assert n[0] == 3


def test_supervised_resource_exhausted_halves_budget(monkeypatch):
    _spec(monkeypatch, "dispatch#0:RESOURCE_EXHAUSTED*2")
    snap = faults.counters.snapshot()
    budgets = []
    out = faults.supervised(
        "dispatch",
        lambda b: budgets.append(b) or b,
        policy=NO_BACKOFF,
        budget=8,
    )
    assert budgets == [2] and out == 2  # 8 -> 4 -> 2, never below 1
    assert faults.counters.delta(snap)["budget_halvings"] == 2


def test_supervised_persistent_goes_straight_to_fallback(monkeypatch):
    _spec(monkeypatch, "spill#0:PERSISTENT")
    snap = faults.counters.snapshot()
    ran = []
    out = faults.supervised(
        "spill", lambda b: ran.append(1), policy=NO_BACKOFF, fallback=lambda: "cpu"
    )
    assert out == "cpu"
    assert ran == []  # every attempt would fail identically: no retry burn
    d = faults.counters.delta(snap)
    assert d["fallbacks"] == 1 and d["retries"] == 0


def test_supervised_exhaustion_without_fallback_raises_fatal(monkeypatch):
    _spec(monkeypatch, "stream#0:PERSISTENT")
    with pytest.raises(faults.FatalDeviceFault) as ei:
        faults.supervised("stream", lambda b: "never", policy=NO_BACKOFF)
    assert ei.value.site == "stream"
    assert ei.value.ordinal == 0
    assert ei.value.attempts == 1
    assert isinstance(ei.value.cause, faults.FaultInjected)


def test_supervised_programming_errors_not_retried():
    n = [0]

    def attempt(_b):
        n[0] += 1
        raise ValueError("trace-time shape error")

    with pytest.raises(ValueError):
        faults.supervised("dispatch", attempt, policy=NO_BACKOFF)
    assert n[0] == 1  # re-raised immediately, no retries, no fallback


def test_wildcard_clause_matches_global_ordinal(monkeypatch):
    _spec(monkeypatch, "*#2:TRANSIENT")
    deltas = []
    for site in ("dispatch", "banded", "spill"):
        snap = faults.counters.snapshot()
        faults.supervised(site, lambda b: "ok", policy=NO_BACKOFF)
        deltas.append(faults.counters.delta(snap)["retries"])
    # per-site ordinals are all 0; only the THIRD supervised call overall
    # (global ordinal 2) takes the injected fault
    assert deltas == [0, 0, 1]


def test_backoff_deterministic_and_bounded():
    pol = faults.RetryPolicy(
        max_retries=5, backoff_base_s=0.1, backoff_max_s=1.0, jitter=0.25, seed=7
    )
    d1 = [pol.backoff(k, faults._site_seed(pol, "banded", 3)) for k in range(5)]
    d2 = [pol.backoff(k, faults._site_seed(pol, "banded", 3)) for k in range(5)]
    assert d1 == d2  # same (seed, site, ordinal) -> same jitter stream
    for k, d in enumerate(d1):
        base = min(1.0, 0.1 * 2.0**k)
        assert base <= d <= base * 1.25


def test_retry_policy_env_overrides(monkeypatch):
    class Cfg:
        fault_max_retries = 3
        fault_backoff_base_s = 0.05
        fault_backoff_max_s = 2.0

    monkeypatch.setenv("DBSCAN_FAULT_RETRIES", "7")
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0.5")
    pol = faults.RetryPolicy.from_config(Cfg())
    assert pol.max_retries == 7 and pol.backoff_base_s == 0.5


def test_sync_mode_env(monkeypatch):
    monkeypatch.delenv("DBSCAN_FAULT_SPEC", raising=False)
    monkeypatch.delenv("DBSCAN_FAULT_SYNC", raising=False)
    faults.reset_registry()
    assert not faults.sync_mode()
    monkeypatch.setenv("DBSCAN_FAULT_SYNC", "1")
    assert faults.sync_mode()
    monkeypatch.delenv("DBSCAN_FAULT_SYNC")
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:TRANSIENT")
    faults.reset_registry()
    assert faults.sync_mode()


# --- the declared site registry (faults.SITES) ------------------------


def test_sites_self_check_clean():
    """The registry's own invariants hold (the schema.self_check()
    idiom applied to the fault plane)."""
    assert faults.sites_self_check() == []


def test_sites_registry_covers_every_site_constant():
    """Every SITE_* constant has a registry row and vice versa, and the
    spec grammar's site vocabulary is exactly the registry plus '*'."""
    consts = {
        v for k, v in vars(faults).items()
        if k.startswith("SITE_") and isinstance(v, str)
    }
    assert consts == set(faults.SITES)
    assert set(faults._SITES) == consts | {"*"}
    for site, spec in faults.SITES.items():
        assert spec.site == site
        assert spec.degrade and spec.handler and spec.owner


def test_parse_fault_spec_rejects_undeclared_site():
    """A drill clause naming a site outside the registry is a spec
    error, not a silently-never-firing clause — declaring the SITES row
    IS the registration step."""
    with pytest.raises(ValueError, match="nosuchsite"):
        faults.parse_fault_spec("nosuchsite#0:TRANSIENT")


# --- end-to-end label parity under injection --------------------------


def _varied_blobs():
    """Blobs at very different densities so the packer emits multiple
    groups — faults can then hit one group while others stay healthy."""
    rng = np.random.default_rng(0)
    sizes = [80, 200, 500, 1200, 300, 900]
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8), (-9, -9), (16, 2)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (s, 2)) for c, s in zip(centers, sizes)]
    )
    rng.shuffle(pts)
    return pts


KW_BANDED = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="banded",
)
KW_DENSE = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="dense",
)


def _assert_label_parity(faulted, clean):
    """Exact label equality — strictly stronger than the ARI == 1.0 the
    acceptance bar asks for (asserted too, for the stated criterion)."""
    np.testing.assert_array_equal(faulted.clusters, clean.clusters)
    np.testing.assert_array_equal(faulted.flags, clean.flags)
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(clean.clusters, faulted.clusters) == 1.0


def test_clean_run_reports_zero_fault_stats():
    out = train(_varied_blobs(), **KW_BANDED)
    fa = out.stats["faults"]
    assert set(fa) == {
        "attempts", "retries", "fallbacks", "budget_halvings",
        "injected", "backoff_s",
    }
    assert fa["attempts"] > 0  # every dispatch runs supervised
    assert fa["retries"] == 0 and fa["fallbacks"] == 0
    assert out.stats["timings"]["fault_backoff_s"] == 0.0


def test_transient_fault_banded_label_parity(monkeypatch):
    """Acceptance: an injected transient fault mid-device-phase produces
    labels exactly equal to the fault-free run (ARI == 1.0)."""
    pts = _varied_blobs()
    clean = train(pts, **KW_BANDED)
    _spec(monkeypatch, "banded#1:TRANSIENT*2")
    faulted = train(pts, **KW_BANDED)
    _assert_label_parity(faulted, clean)
    fa = faulted.stats["faults"]
    assert fa["retries"] == 2 and fa["injected"] == 2 and fa["fallbacks"] == 0


def test_transient_fault_dense_label_parity(monkeypatch):
    pts = _varied_blobs()
    clean = train(pts, **KW_DENSE)
    _spec(monkeypatch, "dispatch#0:TRANSIENT")
    faulted = train(pts, **KW_DENSE)
    _assert_label_parity(faulted, clean)
    assert faulted.stats["faults"]["retries"] == 1


def test_resource_exhausted_halves_batch_and_keeps_parity(monkeypatch):
    """A RESOURCE_EXHAUSTED retry re-dispatches the group at half the
    lax.map batch budget — a narrower peak-HBM schedule, same labels."""
    pts = _varied_blobs()
    clean = train(pts, **KW_DENSE)
    _spec(monkeypatch, "dispatch#0:RESOURCE_EXHAUSTED")
    faulted = train(pts, **KW_DENSE)
    _assert_label_parity(faulted, clean)
    fa = faulted.stats["faults"]
    assert fa["budget_halvings"] == 1 and fa["retries"] == 1


@pytest.mark.parametrize(
    "kw,site",
    [(KW_BANDED, "banded"), (KW_DENSE, "dispatch")],
    ids=["banded", "dense"],
)
def test_persistent_fault_degrades_group_to_cpu(monkeypatch, caplog, kw, site):
    """Acceptance: a forced persistent device failure on one group
    completes via CPU degradation with a logged fallback count instead
    of raising."""
    pts = _varied_blobs()
    clean = train(pts, **kw)
    _spec(monkeypatch, f"{site}#1:PERSISTENT")
    with caplog.at_level("WARNING", logger="dbscan_tpu.faults"):
        faulted = train(pts, **kw)
    _assert_label_parity(faulted, clean)
    assert faulted.stats["faults"]["fallbacks"] == 1
    assert any("degrading this group to the CPU engine" in r.message
               for r in caplog.records)


def test_fatal_fault_flushes_chunks_and_resume_completes(
    tmp_path, monkeypatch
):
    """CPU fallback off: a retries-exhausted fault must still not waste
    the healthy groups' work — the abort path closes the open compact
    chunk, persists every live chunk, and records the abort site, so the
    resumed leg restarts after the last completed group."""
    pts = _varied_blobs()
    clean = train(pts, **KW_BANDED)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)  # many chunks
    ck = tmp_path / "ck"
    _spec(monkeypatch, "banded#2:PERSISTENT")
    with pytest.raises(faults.FatalDeviceFault):
        train(pts, checkpoint_dir=str(ck), fault_cpu_fallback=False,
              **KW_BANDED)
    assert len(list(ck.glob("p1chunk*.npz"))) >= 1  # groups 0-1 banked

    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    prog = ckpt_mod.read_progress(str(ck))
    assert prog["aborted_site"] == "banded"
    assert prog["aborted_ordinal"] == 2

    # heal the fault and resume: saved chunks must skip real dispatches
    monkeypatch.delenv("DBSCAN_FAULT_SPEC")
    faults.reset_registry()
    calls = []
    real = driver._dispatch_banded_p1

    def counting(group, *a, **k):
        calls.append(1)
        return real(group, *a, **k)

    monkeypatch.setattr(driver, "_dispatch_banded_p1", counting)
    resumed = train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    _assert_label_parity(resumed, clean)
    assert len(calls) < prog["planned_groups"]


def test_async_pull_fault_banks_restart_point(tmp_path, monkeypatch):
    """jax dispatch is async: a REAL device fault surfaces at the
    consuming pull as a raw device-runtime error, not at the supervised
    dispatch site. The abort guard must still record the abort site and
    leave every already-persisted chunk usable by the next leg."""
    pts = _varied_blobs()
    clean = train(pts, **KW_BANDED)
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_EAGER_PULL", "1")  # persist at each flush
    ck = tmp_path / "ck"

    from dbscan_tpu.parallel import mesh as mesh_mod

    XlaErr = type("XlaRuntimeError", (RuntimeError,), {})
    real_pull = mesh_mod.pull_to_host
    calls = [0]

    def dying_pull(x):
        # each chunk pull is two pull_to_host calls (combo, bbits): let
        # the first chunk persist, then the worker "dies" for good
        calls[0] += 1
        if calls[0] > 2:
            raise XlaErr("UNAVAILABLE: TPU worker died")
        return real_pull(x)

    monkeypatch.setattr(mesh_mod, "pull_to_host", dying_pull)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    monkeypatch.setattr(mesh_mod, "pull_to_host", real_pull)

    assert len(list(ck.glob("p1chunk*.npz"))) >= 1  # banked before death
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    assert ckpt_mod.read_progress(str(ck))["aborted_site"] == "pull"
    resumed = train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    _assert_label_parity(resumed, clean)


def test_streaming_update_fault_parity(monkeypatch):
    """Per-batch supervision: stream identities survive both a transient
    pull-site fault (whole-batch retry — train_arrays is a pure function
    of host state) and a persistently dead device (batch re-runs pinned
    to the CPU backend)."""
    from dbscan_tpu.streaming import StreamingDBSCAN

    def batches():
        r = np.random.default_rng(7)
        for i in range(3):
            c = np.array([[0.0, 0.0], [5.0, 5.0]]) + i * 0.1
            yield np.concatenate(
                [r.normal(c[0], 0.3, (120, 2)), r.normal(c[1], 0.3, (120, 2))]
            )

    def run_stream():
        s = StreamingDBSCAN(eps=0.5, min_points=5, max_points_per_partition=128)
        return [s.update(b) for b in batches()]

    clean = run_stream()

    _spec(monkeypatch, "stream#1:TRANSIENT")
    transient = run_stream()
    for a, b in zip(clean, transient):
        np.testing.assert_array_equal(a.clusters, b.clusters)
    assert transient[1].stats["faults"]["retries"] == 1

    _spec(monkeypatch, "stream#1:PERSISTENT")
    degraded = run_stream()
    for a, b in zip(clean, degraded):
        np.testing.assert_array_equal(a.clusters, b.clusters)
    assert degraded[1].stats["faults"]["fallbacks"] == 1


def test_cli_fault_summary_surfaces_counts(tmp_path, monkeypatch, capsys):
    """The CLI summary exposes the structured failure accounting — a
    degraded-but-complete run is invisible from the labels alone."""
    from dbscan_tpu import cli

    csv = tmp_path / "pts.csv"
    np.savetxt(csv, _varied_blobs(), delimiter=",")
    _spec(monkeypatch, "*#0:PERSISTENT")
    rc = cli.main(
        [
            "--input", str(csv), "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "256", "--engine", "archery",
            "--stats",
        ]
    )
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["faults"]["fallbacks"] >= 1


# --- tier-1 smoke: the whole distributed suite under injection --------


def _distributed_suite_failures(extra_env):
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("DBSCAN_FAULT")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env)
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/test_distributed.py",
            "-q", "-p", "no:cacheprovider", "-p", "no:randomly",
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    return proc, set(re.findall(r"^FAILED (\S+)", proc.stdout, re.MULTILINE))


def test_distributed_suite_survives_injected_faults():
    """Run the distributed suite ONCE with a nonzero DBSCAN_FAULT_SPEC:
    every parity assertion in it must hold under injected transient and
    budget faults (compared against a spec-less control run, so a
    pre-existing environmental failure can't mask a supervision bug)."""
    _ctrl, base_failed = _distributed_suite_failures({})
    spec = (
        "dispatch#0:TRANSIENT;banded#0:TRANSIENT*2;"
        "*#6:RESOURCE_EXHAUSTED;*#11:TRANSIENT"
    )
    proc, inj_failed = _distributed_suite_failures(
        {"DBSCAN_FAULT_SPEC": spec, "DBSCAN_FAULT_BACKOFF_S": "0"}
    )
    assert inj_failed <= base_failed, (
        f"injection broke: {sorted(inj_failed - base_failed)}\n"
        + proc.stdout[-2000:]
    )
    assert re.search(r"\d+ passed", proc.stdout)  # the suite really ran
