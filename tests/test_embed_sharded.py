"""Sharded embed campaigns suite (ISSUE 20): byte-identical labels
across 1/2/4/8-device meshes x LSH seeds x quantizer front-ends
(``srp`` | ``ivf``), the IVF route's ARI >= 0.95 gate vs the exact
spill route, bucket-band checkpoint banking with a mid-campaign
SIGTERM drill resuming byte-identical, the frontier campaign kill
drill over bucket-band chunks (``count_done=count_banked_bands``), the
knob/telemetry/family registrations, the ``DBSCAN_SHAPECHECK=1``
subprocess drill covering embed.hash/embed.neighbors/embed.quantize,
the exact-arithmetic per-shard busy-share rollup, and the
embed1b_mpts/embed1b_replay_frac history promotion + gate directions.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from dbscan_tpu import config, embed_dbscan, faults
from dbscan_tpu.embed import engine as embed_engine
from dbscan_tpu.embed import neighbors
from dbscan_tpu.utils.ari import adjusted_rand_index

pytestmark = pytest.mark.embed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_embed_state(monkeypatch):
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    faults.reset_registry()
    neighbors.reset_w_floors()
    yield
    faults.reset_registry()


def _blobs(rng, d, k, per, noise, n_noise=0):
    c = rng.normal(size=(k, d))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    x = np.repeat(c, per, axis=0) + noise * rng.normal(size=(k * per, d))
    if n_noise:
        x = np.concatenate([x, rng.normal(size=(n_noise, d))])
    return x


def _mesh(k):
    import jax

    from dbscan_tpu.parallel import mesh as mesh_mod

    return mesh_mod.make_mesh(jax.devices()[:k])


# --- tentpole: byte-identity across meshes x seeds x quantizers --------


@pytest.mark.parametrize("quantizer", ["srp", "ivf"])
def test_labels_byte_identical_across_mesh_shapes(quantizer):
    """THE sharding contract: the label vector is a function of the
    data alone — 1/2/4/8-device meshes all produce the unsharded run's
    exact bytes, on both binning front-ends."""
    rng = np.random.default_rng(7)
    x = _blobs(rng, 24, 6, 40, 0.01, n_noise=12)
    kw = dict(max_points_per_partition=64, quantizer=quantizer)
    base_c, base_f = embed_dbscan(x, 0.05, 5, **kw)
    assert len(np.unique(base_c[base_c > 0])) == 6
    for k in (2, 4, 8):
        stats: dict = {}
        c, f = embed_dbscan(x, 0.05, 5, mesh=_mesh(k), stats_out=stats, **kw)
        np.testing.assert_array_equal(c, base_c)
        np.testing.assert_array_equal(f, base_f)
        assert stats["embed_shards"] == k


def test_labels_byte_identical_across_lsh_seeds_on_mesh():
    """Sharded runs keep the canonical renumbering contract: the LSH
    seed moves buckets and bucket owners, never a label."""
    rng = np.random.default_rng(11)
    x = _blobs(rng, 16, 5, 36, 0.01, n_noise=10)
    kw = dict(max_points_per_partition=64)
    base_c, _bf = embed_dbscan(x, 0.05, 5, seed=0, **kw)
    for seed in (0, 1, 5):
        c, _f = embed_dbscan(x, 0.05, 5, seed=seed, mesh=_mesh(4), **kw)
        np.testing.assert_array_equal(c, base_c)


def test_shard_knob_off_disables_mesh_dispatch(monkeypatch):
    """DBSCAN_EMBED_SHARD=0 is the escape hatch: a passed mesh is
    ignored (shard_active False) and labels are unchanged."""
    monkeypatch.setenv("DBSCAN_EMBED_SHARD", "0")
    rng = np.random.default_rng(3)
    x = _blobs(rng, 16, 4, 30, 0.01)
    base_c, _ = embed_dbscan(x, 0.05, 5, max_points_per_partition=48)
    assert not embed_engine.shard_active(_mesh(4))
    stats: dict = {}
    c, _ = embed_dbscan(
        x, 0.05, 5, max_points_per_partition=48, mesh=_mesh(4),
        stats_out=stats,
    )
    np.testing.assert_array_equal(c, base_c)
    assert stats["embed_shards"] == 1


def test_bucket_owner_contiguous_and_balanced():
    """Bucket bands are contiguous (owners monotone nondecreasing) and
    instance-balanced: equal-weight buckets split evenly."""
    counts = np.full(8, 100, dtype=np.int64)
    owner = embed_engine._bucket_owner(counts, 4)
    assert (np.diff(owner) >= 0).all()
    np.testing.assert_array_equal(np.bincount(owner, minlength=4), [2, 2, 2, 2])
    # a dominant bucket pulls the band boundaries around it
    skew = np.array([1000, 1, 1, 1, 1, 1, 1, 1], dtype=np.int64)
    owner = embed_engine._bucket_owner(skew, 4)
    assert (np.diff(owner) >= 0).all()
    assert owner.min() >= 0 and owner.max() <= 3
    # degenerate shapes never index out of range
    assert len(embed_engine._bucket_owner(np.empty(0, np.int64), 4)) == 0
    assert (embed_engine._bucket_owner(counts, 1) == 0).all()


# --- IVF coarse-quantizer front-end ------------------------------------


def test_ivf_route_meets_declared_ari_floor():
    """The PARITY-declared accuracy gate: IVF labels vs the exact spill
    route score ARI >= 0.95 (byte-identical on bridge-free workloads,
    so the gate holds with margin)."""
    rng = np.random.default_rng(19)
    x = _blobs(rng, 32, 8, 50, 0.01, n_noise=20)
    exact_c, _ = embed_dbscan(x, 0.05, 5, max_points_per_partition=96)
    stats: dict = {}
    ivf_c, _ = embed_dbscan(
        x, 0.05, 5, max_points_per_partition=96, quantizer="ivf",
        stats_out=stats,
    )
    assert stats["embed_quantizer"] == "ivf"
    assert stats["embed_ivf_cells"] >= 2
    assert float(adjusted_rand_index(ivf_c, exact_c)) >= 0.95


def test_ivf_knob_routes_and_bad_value_raises(monkeypatch):
    rng = np.random.default_rng(2)
    x = _blobs(rng, 16, 4, 40, 0.01)
    monkeypatch.setenv("DBSCAN_EMBED_QUANTIZER", "ivf")
    stats: dict = {}
    embed_dbscan(x, 0.05, 5, max_points_per_partition=64, stats_out=stats)
    assert stats["embed_quantizer"] == "ivf"
    with pytest.raises(ValueError, match="quantizer"):
        embed_dbscan(x, 0.05, 5, quantizer="kd")
    monkeypatch.setenv("DBSCAN_EMBED_QUANTIZER", "kd")
    with pytest.raises(ValueError, match="DBSCAN_EMBED_QUANTIZER"):
        embed_dbscan(x, 0.05, 5)


def test_ivf_cells_knob_and_auto_sizing(monkeypatch):
    from dbscan_tpu.embed import quantize

    monkeypatch.setenv("DBSCAN_EMBED_IVF_CELLS", "32")
    assert quantize.default_cells(10000, 100) == 32
    monkeypatch.setenv("DBSCAN_EMBED_IVF_CELLS", "0")
    # auto: ~2x the payload/maxpp ratio, clamped to the ladder range
    assert quantize.default_cells(1000, 100) == 20
    assert quantize.default_cells(50, 100) == 2
    assert quantize.default_cells(10**9, 100) == 192


# --- knob / telemetry / family registrations ---------------------------


def test_shard_knobs_registered():
    ev = config.ENV_VARS
    assert ev["DBSCAN_EMBED_SHARD"].kind == "bool"
    assert ev["DBSCAN_EMBED_SHARD"].default is True
    assert ev["DBSCAN_EMBED_QUANTIZER"].default == "srp"
    assert ev["DBSCAN_EMBED_IVF_CELLS"].kind == "int"
    assert ev["DBSCAN_EMBED_BAND"].kind == "int"
    tu = {t.name: t for t in config.TUNABLES}
    assert tu["DBSCAN_EMBED_QUANTIZER"].choices == ("srp", "ivf")
    assert 0 in tu["DBSCAN_EMBED_IVF_CELLS"].choices


def test_quantize_family_and_telemetry_declared():
    from dbscan_tpu.lint import shapes
    from dbscan_tpu.obs import schema

    assert "embed.quantize" in schema.COMPILE_FAMILIES
    fam = set(shapes.FAMILY_MODELS)
    assert {"embed.quantize", "embed.hash", "embed.neighbors"} <= fam
    for counter in (
        "embed.quantize_dispatches",
        "embed.bands_banked",
        "embed.bands_loaded",
    ):
        assert schema.is_declared("counter", counter), counter
    assert schema.is_declared("gauge", "embed.ivf_cells")
    assert schema.is_declared("gauge", "embed.shards")
    assert schema.is_declared("span", "embed.quantize")
    # the generator loop gave the new family its compile/devtime names
    assert schema.is_declared("counter", "compiles.embed.quantize")
    assert schema.is_declared("span", "devtime.embed.quantize")


def test_shapecheck_subprocess_covers_all_embed_families(tmp_path):
    """DBSCAN_SHAPECHECK=1 rerun of a srp + ivf embed run in a fresh
    process: the atexit JSON report must be violation-free with ALL
    THREE embed families covered."""
    report = tmp_path / "shapecheck.json"
    code = (
        "import numpy as np\n"
        "from dbscan_tpu import embed_dbscan\n"
        "rng = np.random.default_rng(0)\n"
        "c = rng.normal(size=(5, 16))\n"
        "c /= np.linalg.norm(c, axis=1, keepdims=True)\n"
        "x = np.repeat(c, 40, axis=0)"
        " + 0.01 * rng.normal(size=(200, 16))\n"
        "a, _ = embed_dbscan(x, 0.05, 5, max_points_per_partition=64)\n"
        "b, _ = embed_dbscan(x, 0.05, 5, max_points_per_partition=64,"
        " quantizer='ivf')\n"
        "assert np.array_equal(a, b)\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_SHAPECHECK="1",
        DBSCAN_SHAPECHECK_REPORT=str(report),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, timeout=600, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr.decode(errors="replace")[-2000:]
    rep = json.loads(report.read_text())
    assert rep["violations"] == []
    assert "embed.hash" in rep["sites"]
    assert "embed.neighbors" in rep["sites"]
    assert "embed.quantize" in rep["sites"]


# --- bucket-band checkpoints -------------------------------------------


def _campaign_payload():
    rng = np.random.default_rng(23)
    return _blobs(rng, 16, 6, 60, 0.01, n_noise=20)


def test_checkpoint_bank_and_resume_byte_identical(tmp_path, monkeypatch):
    """A checkpointed run banks one band file per bucket band; a resume
    loads them all (zero re-dispatches of settled bands) and finalizes
    byte-identically — including a partial resume after losing bands."""
    monkeypatch.setenv("DBSCAN_EMBED_BAND", "2")
    x = _campaign_payload()
    kw = dict(max_points_per_partition=64)
    clean_c, clean_f = embed_dbscan(x, 0.05, 5, **kw)
    ck = str(tmp_path / "ck")
    s1: dict = {}
    c1, f1 = embed_dbscan(x, 0.05, 5, checkpoint_dir=ck, stats_out=s1, **kw)
    np.testing.assert_array_equal(c1, clean_c)
    n_bands = s1["campaign_chunks_total"]
    assert embed_engine.count_banked_bands(ck) == n_bands >= 2
    assert s1["campaign_bands_loaded"] == 0
    s2: dict = {}
    c2, f2 = embed_dbscan(x, 0.05, 5, checkpoint_dir=ck, stats_out=s2, **kw)
    np.testing.assert_array_equal(c2, clean_c)
    np.testing.assert_array_equal(f2, clean_f)
    assert s2["campaign_bands_loaded"] == n_bands
    assert s2["resumed_from_checkpoint"] is True
    # lose a band: the next run recomputes exactly the missing one
    os.unlink(os.path.join(ck, embed_engine._BAND_FILE.format(0)))
    s3: dict = {}
    c3, _ = embed_dbscan(x, 0.05, 5, checkpoint_dir=ck, stats_out=s3, **kw)
    np.testing.assert_array_equal(c3, clean_c)
    assert s3["campaign_bands_loaded"] == n_bands - 1


def test_stale_fingerprint_rejects_banked_band(tmp_path, monkeypatch):
    """A banked band from DIFFERENT knobs (here: another seed) must be
    recomputed, never spliced in — the fingerprint is the gate."""
    monkeypatch.setenv("DBSCAN_EMBED_BAND", "2")
    x = _campaign_payload()
    kw = dict(max_points_per_partition=64)
    ck = str(tmp_path / "ck")
    embed_dbscan(x, 0.05, 5, seed=0, checkpoint_dir=ck, **kw)
    stats: dict = {}
    c, _ = embed_dbscan(
        x, 0.05, 5, seed=1, checkpoint_dir=ck, stats_out=stats, **kw
    )
    assert stats["campaign_bands_loaded"] == 0
    base_c, _ = embed_dbscan(x, 0.05, 5, seed=1, **kw)
    np.testing.assert_array_equal(c, base_c)


def _wait_for(pred, timeout_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


_CHILD_CODE = (
    "import sys\n"
    "import numpy as np\n"
    "from dbscan_tpu import embed_dbscan\n"
    "x = np.load(sys.argv[1])\n"
    "c, f = embed_dbscan(x, 0.05, 5, max_points_per_partition=96,"
    " checkpoint_dir=sys.argv[2])\n"
    "np.save(sys.argv[3] + '.tmp.npy', c)\n"
    "import os; os.replace(sys.argv[3] + '.tmp.npy', sys.argv[3])\n"
)


def test_sigterm_mid_campaign_resumes_byte_identical(tmp_path, monkeypatch):
    """The mid-campaign SIGTERM drill: a worker killed between band
    banks leaves its bands as intact restart points; the resume loads
    them and finalizes byte-identical to a clean run."""
    monkeypatch.setenv("DBSCAN_EMBED_BAND", "1")
    rng = np.random.default_rng(31)
    # enough buckets (~20+) that banking spans real wall time after the
    # first band lands — the SIGTERM window is wide and real
    x = _blobs(rng, 32, 20, 70, 0.01, n_noise=40)
    clean_c, clean_f = embed_dbscan(x, 0.05, 5, max_points_per_partition=96)
    pts_path = str(tmp_path / "pts.npy")
    np.save(pts_path, np.asarray(x))
    ck = str(tmp_path / "ck")
    out = str(tmp_path / "labels.npy")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_EMBED_BAND": "1",
    }
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_CODE, pts_path, ck, out],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        _wait_for(
            lambda: embed_engine.count_banked_bands(ck) >= 1,
            timeout_s=300,
            what="first banked band",
        )
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    if proc.returncode == 0:  # pragma: no cover - tiny-machine race
        pytest.skip("leg finished before SIGTERM landed")
    banked = embed_engine.count_banked_bands(ck)
    assert banked >= 1  # the kill left durable restart points
    stats: dict = {}
    c, f = embed_dbscan(
        x, 0.05, 5, max_points_per_partition=96,
        checkpoint_dir=ck, stats_out=stats,
    )
    np.testing.assert_array_equal(c, clean_c)
    np.testing.assert_array_equal(f, clean_f)
    assert stats["campaign_bands_loaded"] >= 1
    assert stats["resumed_from_checkpoint"] is True


def test_frontier_kill_drill_over_bucket_bands(tmp_path, monkeypatch):
    """campaign.run_frontier over embed legs with
    ``count_done=count_banked_bands``: a TRANSIENT campaign clause
    kills leg 1 right after it banks a band; leg 2 resumes from the
    banked bands and completes with byte-identical labels, and the
    killed leg's unbanked wall is priced into replay_frac."""
    from dbscan_tpu import campaign as camp

    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "campaign#0:TRANSIENT")
    faults.reset_registry()
    rng = np.random.default_rng(37)
    x = _blobs(rng, 32, 20, 70, 0.01, n_noise=40)
    clean_c, _cf = embed_dbscan(x, 0.05, 5, max_points_per_partition=96)
    pts_path = str(tmp_path / "pts.npy")
    np.save(pts_path, np.asarray(x))
    ck = str(tmp_path / "ck")
    out = str(tmp_path / "labels.npy")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DBSCAN_EMBED_BAND": "1",
    }
    env.pop("DBSCAN_FAULT_SPEC", None)  # the drill is the PARENT's
    fr = camp.run_frontier(
        ck,
        [sys.executable, "-c", _CHILD_CODE, pts_path, ck, out],
        env=env,
        max_leases=3,
        budget_s=600.0,
        leg_timeout_s=300.0,
        rest_s=0.1,
        poll_s=0.05,
        success_path=out,
        count_done=embed_engine.count_banked_bands,
    )
    assert fr.complete, fr.last_error
    assert fr.kills == 1
    assert fr.legs == 2
    assert fr.replay_frac > 0.0
    assert fr.chunks_done == fr.chunks_total >= 2
    np.testing.assert_array_equal(np.load(out), clean_c)


# --- per-shard busy-share rollup (exact arithmetic) --------------------


def test_embed_shard_rollup_exact_arithmetic():
    """The --merge busy-share section is exact interval-union
    arithmetic: overlapping same-shard windows union (never double-
    count), shares normalize over total busy seconds."""
    from dbscan_tpu.obs import analyze

    def sp(t0, dur, shard):
        return {
            "name": "embed.bucket", "t0": t0, "dur": dur, "tid": 1,
            "depth": 1, "args": {"p": 0, "b": 128, "w": 16, "shard": shard},
        }

    spans = [sp(0.0, 1.0, 0), sp(0.5, 1.0, 0), sp(0.0, 2.0, 1)]
    roll = analyze._embed_shard_rollup(spans)
    assert roll["busy_s"] == 3.5
    rows = {r["shard"]: r for r in roll["shards"]}
    assert rows[0]["busy_s"] == 1.5 and rows[0]["buckets"] == 2
    assert rows[1]["busy_s"] == 2.0 and rows[1]["buckets"] == 1
    assert rows[0]["busy_share"] == round(1.5 / 3.5, 6)
    assert rows[1]["busy_share"] == round(2.0 / 3.5, 6)
    # merge-assigned process shard is the fallback id
    merged = [dict(sp(0.0, 1.0, 0), shard=3) for _ in range(1)]
    del merged[0]["args"]["shard"]
    assert analyze._embed_shard_rollup(merged)["shards"][0]["shard"] == 3
    # unsharded spans roll up empty (section renders nothing)
    un = [sp(0.0, 1.0, 0)]
    del un[0]["args"]["shard"]
    un[0].pop("shard", None)
    assert analyze._embed_shard_rollup(un) == {}


def test_sharded_run_renders_busy_share_section(tmp_path):
    """A real mesh run records shard-stamped bucket spans and the
    analyzer renders the busy-share section with every shard's row."""
    from dbscan_tpu import obs
    from dbscan_tpu.obs import analyze

    rng = np.random.default_rng(13)
    x = _blobs(rng, 16, 6, 40, 0.01)
    trace = tmp_path / "shard_trace.jsonl"
    was = obs.active()
    obs.enable(trace_path=str(trace))
    try:
        embed_dbscan(
            x, 0.05, 5, max_points_per_partition=48, mesh=_mesh(4)
        )
    finally:
        obs.flush()
        if not was:
            obs.disable()
    report = analyze.analyze(analyze.load_trace(str(trace)))
    shards = {r["shard"] for r in report["embed_shards"]["shards"]}
    assert shards == {0, 1, 2, 3}
    assert abs(
        sum(r["busy_share"] for r in report["embed_shards"]["shards"]) - 1.0
    ) < 1e-3
    text = analyze.render(report)
    assert "embed shards (bucket-band busy share)" in text


# --- embed1b history promotion + gate directions -----------------------


def test_embed1b_metrics_promote_and_gate(tmp_path):
    """The two flagship figures promote into bench/history.jsonl with
    the right units and regress directions: embed1b_mpts a throughput
    (regress-down), embed1b_replay_frac a ratio (regress-up)."""
    from dbscan_tpu.obs import bench_history, regress

    cap = tmp_path / "BENCH_EMBED1B_r9.json"
    cap.write_text(json.dumps({
        "metric": "embed1b", "backend": "cpu",
        "embed1b_mpts": 1.25, "embed1b_replay_frac": 0.05,
        "embed1b_ari": 1.0, "embed1b_wall_s": 10.0,
        "embed1b_kills": 1, "embed1b_complete": True,
    }))
    hist = tmp_path / "history.jsonl"
    added, _skipped = bench_history.ingest([str(cap)], str(hist), rev="t")
    recs = {
        r["metric"]: r
        for r in map(json.loads, hist.read_text().splitlines())
    }
    assert recs["embed1b_mpts"]["unit"] == "Mpoints/s"
    assert recs["embed1b_replay_frac"]["unit"] == "ratio"
    assert "embed1b_ari" in recs and "embed1b_wall_s" in recs
    assert regress.direction("embed1b_mpts") == regress.HIGHER_BETTER
    assert regress.direction("embed1b_replay_frac") == regress.LOWER_BETTER
    assert regress.direction("embed1b_ari") == regress.HIGHER_BETTER
    # the committed capture's figures are in the committed history
    hist_live = os.path.join(REPO, "bench", "history.jsonl")
    metrics = {
        json.loads(line)["metric"]
        for line in open(hist_live)
    }
    assert {"embed1b_mpts", "embed1b_replay_frac"} <= metrics
