"""obs/live + obs/slo + request tracing: the live telemetry plane.

Pins the PR's acceptance contract (PARITY.md "SLO contract"):

- log-bucketed sliding-window histograms: bucket geometry, the
  declared QUANTILE_REL_ERROR bound on every reported quantile, epoch-
  ring expiry (observations older than the window stop counting),
  windowed counter rates, and the declared bytes_bound memory ceiling;
- DBSCAN_OBS_LIVE=0 is a STRICT no-op (no state, hooks return their
  empty values, health dicts keep the pre-PR shape) and the enabled
  plane adds < 1% to the serve query path (min-of-reps, the flight-
  recorder guard's discipline);
- undeclared series names are rejected (the schema stays the single
  registry: you cannot observe into a window the linter cannot see);
- the Prometheus-style exposition file: render/parse round-trip,
  atomic rewrite, the DBSCAN_OBS_EXPO_PERIOD_S throttle, and the
  ``python -m dbscan_tpu.obs.live`` console smoke;
- the SLO engine: multi-window burn-rate evaluation with ticket ->
  page escalation (page dumps the flight recorder WHILE the incident
  runs), recovery events, all four declared SLO keys' burn arithmetic,
  and maybe_evaluate's throttle + live-off single-check no-op;
- service/router health() carrying the windowed figures, router
  shedding driven by the LIVE windowed p99 with the refusal event
  NAMING the SLO, and recovery once the window drains;
- request-scoped tracing: ids minted at the router ingress ride every
  span the request touches (route -> shard reads -> pull hops), across
  the ingest queue hop and the PullEngine workers, stay coherent
  through a mid-query replica failover (no orphan spans), and feed
  ``obs.analyze --requests`` per-request critical paths;
- live-vs-offline agreement: the windowed p99 matches the offline
  client-side percentile within the declared tolerance;
- the DBSCAN_TSAN=1 sharded rerun stays race-free with the live
  aggregators, the SLO engine, and the expo writer all hot.
"""

import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dbscan_tpu import faults
from dbscan_tpu import obs
from dbscan_tpu.obs import analyze as analyze_mod
from dbscan_tpu.obs import flight
from dbscan_tpu.obs import live
from dbscan_tpu.obs import slo as slo_mod
from dbscan_tpu.serve import (
    ClusterService,
    QueryRouter,
    QueryShed,
    ShardedClusterService,
)

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPS, MINPTS = 0.6, 5

#: live-vs-offline agreement tolerance (relative) on the windowed p99
#: vs the client-side offline percentile over the same query
#: population — declared in PARITY.md "SLO contract" next to the
#: histogram's QUANTILE_REL_ERROR (~9.1%) it subsumes.
AGREEMENT_RTOL = 0.25

_ENV_KNOBS = (
    "DBSCAN_TRACE",
    "DBSCAN_OBS_LIVE",
    "DBSCAN_OBS_WINDOW_S",
    "DBSCAN_OBS_SLICES",
    "DBSCAN_OBS_EXPO",
    "DBSCAN_OBS_EXPO_PERIOD_S",
    "DBSCAN_SLO_QUERY_P99_MS",
    "DBSCAN_SLO_OBJECTIVE",
    "DBSCAN_SLO_SHED_FRAC",
    "DBSCAN_SLO_STALENESS_S",
    "DBSCAN_SLO_FAULT_RATE",
    "DBSCAN_SLO_BURN_PAGE",
    "DBSCAN_SLO_BURN_TICKET",
    "DBSCAN_SLO_EVAL_PERIOD_S",
    "DBSCAN_SERVE_SHED_P99_MS",
    "DBSCAN_FAULT_SPEC",
)


@pytest.fixture(autouse=True)
def _fresh_plane(monkeypatch, tmp_path):
    for var in _ENV_KNOBS:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv(
        "DBSCAN_FLIGHTREC_PATH", str(tmp_path / "flightrec.json")
    )
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    obs.disable()
    live.reset()
    slo_mod.reset_engine()
    flight.reset()
    faults.reset_registry()
    yield
    obs.disable()
    live.reset()
    slo_mod.reset_engine()
    flight.reset()
    faults.reset_registry()


def _batch(seed=7, n=60):
    rng = np.random.default_rng(seed)
    centers = [(0, 0), (5, 0), (0, 5), (5, 5)]
    return np.concatenate(
        [rng.normal(c, 0.25, size=(n, 2)) for c in centers]
    )


def _svc(**kw):
    kw.setdefault("window", 2)
    kw.setdefault("max_points_per_partition", 500)
    return ClusterService(EPS, MINPTS, **kw)


# --- bucket geometry + window arithmetic ------------------------------


def test_bucket_geometry_within_declared_error():
    # every representable value maps to a bucket whose reported
    # midpoint is within the declared relative error
    assert live.QUANTILE_REL_ERROR == pytest.approx(
        math.sqrt(live.GROWTH) - 1.0
    )
    v = live.LO_MS * 1.5
    while v < live.LO_MS * live.GROWTH ** (live.NBUCKETS - 3):
        mid = live.bucket_mid_ms(live.bucket_of(v))
        assert abs(mid - v) / v <= live.QUANTILE_REL_ERROR + 1e-9, v
        v *= 1.07
    # clamp edges: underflow to bucket 0, overflow to the top bucket
    assert live.bucket_of(0.0) == 0
    assert live.bucket_of(-5.0) == 0
    assert live.bucket_of(1e12) == live.NBUCKETS - 1
    assert live.bucket_of(live.LO_MS * 0.5) == 0


def test_quantile_within_declared_error_vs_numpy():
    live.ensure_env()
    rng = np.random.default_rng(11)
    vals = np.exp(rng.normal(2.5, 1.0, size=800))  # lognormal ms
    for v in vals:
        live.observe("serve.query_ms", float(v))
    ordered = np.sort(vals)
    for q in (0.5, 0.9, 0.99):
        got = live.quantile("serve.query_ms", q)
        # the exact empirical quantile at the histogram's own rank
        # convention: the bucket-midpoint guarantee is the ONLY error
        want = float(ordered[min(len(vals) - 1, int(q * len(vals)))])
        assert got is not None
        assert abs(got - want) / want <= live.QUANTILE_REL_ERROR + 1e-9, (
            q, got, want,
        )


def test_window_expiry_epoch_ring():
    # direct epoch control on one histogram window: no clock
    # monkeypatching, the ring arithmetic is the contract
    w = live._HistWindow(4, 0.0)
    w.observe(10.0, epoch=100)
    w.observe(20.0, epoch=101)
    total, _s, _b = w.merged(epoch=101)
    assert total == 2
    # 3 epochs later the first slice has rolled out of the window
    total, _s, _b = w.merged(epoch=104)
    assert total == 1
    # far future: everything expired, quantile says "no data"
    total, _s, _b = w.merged(epoch=300)
    assert total == 0
    assert w.quantile(0.99, epoch=300) is None
    # rate windows expire the same way
    r = live._RateWindow(4, 0.0)
    r.bump(3.0, epoch=100)
    assert r.total(epoch=100) == 3.0
    assert r.total(epoch=300) == 0.0


def test_rates_and_window_totals():
    monkeypatch_window = 60.0  # default window; test runs in < 1 s
    live.ensure_env()
    st = live.state()
    assert st is not None and st.window_s == monkeypatch_window
    for _ in range(12):
        live.bump("serve.queries")
    assert live.window_total("serve.queries") == 12.0
    # the rate denominator is the plane's age (>= one slice), never
    # the full window before it has lived that long
    assert live.rate("serve.queries") > 0.0
    assert live.window_total("serve.router.shed") == 0.0
    assert live.seconds_since("serve.epoch_publish") is None
    live.bump("serve.epoch_publish")
    age = live.seconds_since("serve.epoch_publish")
    assert age is not None and 0.0 <= age < 5.0


def test_undeclared_series_rejected():
    live.ensure_env()
    with pytest.raises(ValueError, match="not declared"):
        live.observe("serve.mystery_ms", 1.0)
    with pytest.raises(ValueError, match="not declared"):
        live.bump("serve.mystery_events")


def test_bytes_bound_matches_declared_formula():
    from dbscan_tpu.obs import schema

    st = live.LiveState(window_s=60.0, n_slices=12)
    per_hist = 12 * (live.NBUCKETS + 2) * 8
    per_rate = 12 * 2 * 8
    want = (
        len(schema.LIVE_HISTOGRAMS) * per_hist
        + len(schema.LIVE_RATES) * per_rate
    )
    assert st.bytes_bound() == want
    assert want < 512 * 1024  # the "bounded memory" claim is real


# --- disabled path: strict no-op --------------------------------------


def test_disabled_plane_is_strict_noop(monkeypatch):
    monkeypatch.setenv("DBSCAN_OBS_LIVE", "0")
    live.reset()
    live.ensure_env()
    assert live.state() is None and not live.active()
    # every hook returns its empty value without allocating state
    live.observe("serve.query_ms", 5.0)
    live.bump("serve.queries")
    assert live.quantile("serve.query_ms", 0.99) is None
    assert live.frac_above("serve.query_ms", 1.0) is None
    assert live.rate("serve.queries") == 0.0
    assert live.window_total("serve.queries") == 0.0
    assert live.seconds_since("serve.epoch_publish") is None
    assert live.snapshot() is None
    assert live.state() is None
    # SLO layer: one module-global check, no engine built
    monkeypatch.setenv("DBSCAN_SLO_QUERY_P99_MS", "10")
    assert slo_mod.maybe_evaluate() is None
    assert slo_mod._engine is None
    # health dicts keep the pre-PR shape
    assert slo_mod.windowed_health() == {}
    svc = _svc()
    with svc:
        svc.submit(_batch())
        assert svc.drain(timeout=300)
        h = svc.health()
    assert "windowed" not in h


def test_live_plane_overhead_under_1pct_on_query_path(monkeypatch):
    """The overhead pin at the flight-recorder guard's discipline:
    the live aggregators (histogram observe + rate bumps + the
    windowed health rollup) add < 1% to the steady-state serve query
    path versus DBSCAN_OBS_LIVE=0, min-of-reps on a warmed service,
    with absolute slack for timer noise."""
    svc = _svc()
    rng = np.random.default_rng(0)
    qpts = rng.uniform(-1, 6, size=(48, 2))

    with svc:
        svc.submit(_batch())
        assert svc.drain(timeout=300)

        def run():
            for _ in range(6):
                svc.query(qpts)

        def min_wall(reps=5):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                run()
                best = min(best, time.perf_counter() - t0)
            return best

        run()  # warm the jit caches
        monkeypatch.setenv("DBSCAN_OBS_LIVE", "0")
        live.reset()
        live.ensure_env()
        run()
        without = min_wall()
        assert live.state() is None
        monkeypatch.delenv("DBSCAN_OBS_LIVE")
        live.reset()
        live.ensure_env()
        assert live.state() is not None
        run()
        with_live = min_wall()
    assert with_live <= without * 1.01 + 0.015, (
        f"live-plane overhead: {with_live:.4f}s vs {without:.4f}s off"
    )


# --- exposition file + console ----------------------------------------


def test_expo_render_parse_roundtrip_atomic(tmp_path):
    live.ensure_env()
    for v in (1.0, 2.0, 4.0, 80.0):
        live.observe("serve.query_ms", v)
    for _ in range(4):
        live.bump("serve.queries")
    path = tmp_path / "live.prom"
    assert live.write_expo(str(path)) == str(path)
    text = path.read_text()
    assert "dbscan_live_window_seconds" in text
    parsed = live.parse_expo(text)
    assert parsed["window_s"] == live.state().window_s
    q = parsed["series"]["serve.query_ms"]
    assert q["count"] == 4.0
    assert q["p99_ms"] == pytest.approx(
        live.quantile("serve.query_ms", 0.99)
    )
    assert parsed["series"]["serve.queries"]["count"] == 4.0
    # atomic: no temp litter beside the file
    assert [p.name for p in tmp_path.iterdir()] == ["live.prom"]


def test_expo_throttle_and_console_once(
    tmp_path, monkeypatch, capsys
):
    path = tmp_path / "live.prom"
    monkeypatch.setenv("DBSCAN_OBS_EXPO", str(path))
    monkeypatch.setenv("DBSCAN_OBS_EXPO_PERIOD_S", "3600")
    live.reset()
    live.ensure_env()
    live.observe("serve.query_ms", 7.0)
    assert live.expo_path() == str(path)
    assert live.maybe_write_expo() == str(path)  # first write lands
    assert live.maybe_write_expo() is None  # throttled
    assert path.exists()
    # the top-style console, one frame
    assert live.main([str(path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "dbscan live" in out and "serve.query_ms" in out
    # no exposition file configured and none passed: exit 2
    monkeypatch.delenv("DBSCAN_OBS_EXPO")
    assert live.main(["--once"]) == 2


# --- SLO engine --------------------------------------------------------


def test_slo_burn_ticket_page_recover_and_flight_dump(
    monkeypatch, tmp_path
):
    """The full alert lifecycle on the query-latency SLO: a saturated
    bad-event window trips ticket then page (both windows burning),
    the page dumps the flight recorder mid-incident, and a drained
    window recovers with the declared event."""
    monkeypatch.setenv("DBSCAN_SLO_QUERY_P99_MS", "100")
    obs.enable()  # in-memory: events land in tracer.instants
    live.ensure_env()
    for _ in range(20):
        live.observe("serve.query_ms", 500.0)  # every obs is bad
    # budget 0.01 -> fast burn 100; a small engine window makes the
    # slow EMA track it within a few evaluation passes
    eng = slo_mod.SLOEngine(window_s=0.05)
    for _ in range(50):
        eng.evaluate()
        if eng.alerting().get("query_p99") == "page":
            break
        time.sleep(0.05)
    assert eng.alerting() == {"query_p99": "page"}
    burns = [
        (a["severity"], a["slo"])
        for n, _t, a in obs.state().tracer.instants
        if n == "slo.burn"
    ]
    assert burns == [("ticket", "query_p99"), ("page", "query_p99")]
    counters = obs.counters()
    assert counters["slo.tickets"] == 1
    assert counters["slo.pages"] == 1
    # the page wrote the postmortem WHILE the incident runs
    dump = json.load(open(tmp_path / "flightrec.json"))
    assert dump["reason"] == "slo_burn"
    assert dump["note"]["slo"] == "query_p99"
    # drain the window: flood with good observations, burn collapses
    for _ in range(5000):
        live.observe("serve.query_ms", 1.0)
    for _ in range(100):
        eng.evaluate()
        if not eng.alerting():
            break
        time.sleep(0.05)
    assert eng.alerting() == {}
    recovers = [
        a["slo"]
        for n, _t, a in obs.state().tracer.instants
        if n == "slo.recover"
    ]
    assert recovers == ["query_p99"]


def test_all_four_slo_keys_burn_arithmetic(monkeypatch):
    monkeypatch.setenv("DBSCAN_SLO_QUERY_P99_MS", "100")
    monkeypatch.setenv("DBSCAN_SLO_SHED_FRAC", "0.1")
    monkeypatch.setenv("DBSCAN_SLO_STALENESS_S", "10")
    monkeypatch.setenv("DBSCAN_SLO_FAULT_RATE", "1000")
    live.ensure_env()
    slos = {s.key: s for s in slo_mod.declared_slos()}
    assert set(slos) == {
        "query_p99", "shed_frac", "staleness", "fault_rate",
    }
    # empty windows neither burn nor recover
    assert slo_mod.fast_burn(slos["query_p99"]) is None
    assert slo_mod.fast_burn(slos["shed_frac"]) is None
    assert slo_mod.fast_burn(slos["staleness"]) is None
    # query_p99: 1 bad of 4 over a 0.01 budget -> burn 25
    for v in (1.0, 1.0, 1.0, 500.0):
        live.observe("serve.query_ms", v)
    assert slo_mod.fast_burn(slos["query_p99"]) == pytest.approx(
        0.25 / 0.01
    )
    # shed_frac: 1 shed / 4 total over the 0.1 bound -> burn 2.5
    for _ in range(3):
        live.bump("serve.router.routed")
    live.bump("serve.router.shed")
    assert slo_mod.fast_burn(slos["shed_frac"]) == pytest.approx(2.5)
    # staleness: a fresh publish burns ~0
    live.bump("serve.epoch_publish")
    burn = slo_mod.fast_burn(slos["staleness"])
    assert burn is not None and burn < 0.1
    # fault_rate: rate / bound
    live.bump("faults.events")
    assert slo_mod.fast_burn(slos["fault_rate"]) == pytest.approx(
        live.rate("faults.events") / 1000.0
    )


def test_maybe_evaluate_throttle(monkeypatch):
    monkeypatch.setenv("DBSCAN_SLO_QUERY_P99_MS", "100")
    monkeypatch.setenv("DBSCAN_SLO_EVAL_PERIOD_S", "5")
    live.ensure_env()
    live.observe("serve.query_ms", 1.0)
    first = slo_mod.maybe_evaluate()
    assert first is not None and first[0]["slo"] == "query_p99"
    assert slo_mod.maybe_evaluate() is None  # within the period


def test_classified_fault_feeds_fault_rate_window(monkeypatch):
    live.ensure_env()
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "serve#0:TRANSIENT")
    faults.reset_registry()
    svc = _svc()
    with svc:
        svc.submit(_batch())
        assert svc.drain(timeout=300)  # transient heals via retry
        h = svc.health()
    assert live.window_total("faults.events") >= 1.0
    assert not h["degraded"]


# --- windowed health + shed recovery ----------------------------------


def test_service_health_carries_windowed_figures():
    obs.enable()
    svc = _svc()
    rng = np.random.default_rng(1)
    with svc:
        svc.submit(_batch())
        assert svc.drain(timeout=300)
        for _ in range(5):
            svc.query(rng.uniform(-1, 6, size=(32, 2)))
        h = svc.health()
    win = h["windowed"]
    assert win["window_s"] == 60.0
    assert win["windowed_p99_ms"] > 0.0
    assert win["windowed_qps"] > 0.0
    assert win["windowed_shed_frac"] == 0.0
    assert win["slo_alerting"] == {}
    gauges = obs.state().metrics.gauges()
    assert gauges["serve.windowed_p99_ms"] == win["windowed_p99_ms"]
    assert gauges["serve.windowed_qps"] == win["windowed_qps"]


def test_router_shed_names_slo_and_recovers(monkeypatch):
    """The burn-driven refusal is attributable AND transient: the
    shed event names the SLO whose windowed figure drove it with
    source "window" (the LIVE plane, not the rolling fallback), and a
    drained window readmits the same query."""
    obs.enable()
    rng = np.random.default_rng(5)
    svc = ShardedClusterService(
        EPS, MINPTS, n_shards=2, window=2, max_points_per_partition=500
    )
    with svc:
        svc.submit(_batch(seed=3, n=70))
        assert svc.drain(timeout=300)
        # a small headroom so the burn-shrunk admission window is
        # smaller than the drill batch's price
        monkeypatch.setenv("DBSCAN_SERVE_HEADROOM_BYTES", str(1 << 22))
        with QueryRouter(svc, replicas=2) as router:
            for _ in range(10):
                router.query(rng.uniform(-1, 6, size=(16, 2)))
            assert live.state().window_count("serve.query_ms") >= 10
            # a latency incident the WINDOW sees: the sliding window
            # fills with observations far past a meetable bound
            monkeypatch.setenv("DBSCAN_SERVE_SHED_P99_MS", "5000")
            for _ in range(20):
                live.observe("serve.query_ms", 500_000.0)
            with pytest.raises(QueryShed):
                router.query(rng.uniform(-1, 6, size=(512, 2)))
            # the refusal mark rides the open serve.route span (the
            # shed request's own trace line)
            sheds = [
                a
                for sp in obs.state().tracer.snapshot_spans()
                for n, _t, a in sp.events
                if n == "serve.router.shed"
            ]
            assert len(sheds) == 1
            assert sheds[0]["slo"] == "query_p99"
            assert sheds[0]["source"] == "window"
            assert sheds[0]["p99_ms"] > sheds[0]["bound_ms"]
            assert live.window_total("serve.router.shed") == 1.0
            h = router.health()
            assert h["windowed"]["windowed_shed_frac"] == pytest.approx(
                1.0 / 11.0
            )
            # recovery: the incident's observations age out (reset
            # stands in for the sliding window draining) — the p99
            # the check reads is back under the bound, so the SAME
            # query readmits without any knob change
            live.reset()
            live.ensure_env()
            res = router.query(rng.uniform(-1, 6, size=(512, 2)))
            assert len(res.gids) == 512


# --- request-scoped tracing -------------------------------------------


def test_router_mints_rid_and_spans_are_coherent():
    obs.enable()
    rng = np.random.default_rng(9)
    svc = ShardedClusterService(
        EPS, MINPTS, n_shards=2, window=2, max_points_per_partition=500
    )
    with svc:
        svc.submit(_batch(seed=3, n=70))
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=2) as router:
            for _ in range(3):
                router.query(rng.uniform(-1, 6, size=(24, 2)))
    spans = obs.state().tracer.snapshot_spans()
    routes = [s for s in spans if s.name == "serve.route"]
    assert len(routes) == 3
    rids = [s.rid for s in routes]
    assert all(r and r.startswith(f"r{os.getpid():x}-") for r in rids)
    assert len(set(rids)) == 3  # one id per request, process-unique
    # every span the request produced carries ITS id: the pull-engine
    # chunk hops (worker thread!) ride inside the routed extent
    for rid in rids:
        names = {s.name for s in spans if s.rid == rid}
        assert "serve.route" in names
        assert "pull.chunk" in names, names


def test_rid_crosses_ingest_queue_hop():
    obs.enable()
    svc = _svc()
    with svc:
        with obs.request_scope("r-ingest-1"):
            svc.submit(_batch())  # capture-at-submit
        assert svc.drain(timeout=300)  # restore-around-work
    updates = [
        s
        for s in obs.state().tracer.snapshot_spans()
        if s.name == "serve.update"
    ]
    assert updates and all(s.rid == "r-ingest-1" for s in updates)


def test_rid_rides_pull_engine_workers():
    """The PullEngine queue hop: jobs capture the ambient id at
    construction and the worker restores it around the whole
    execution, so the retroactive pull.chunk spans are stamped."""
    from dbscan_tpu.parallel import pipeline as pipe_mod

    obs.enable()
    pipe_mod.reset_engine()
    eng = pipe_mod.get_engine()
    assert eng is not None
    with obs.request_scope("r-pull-7"):
        jobs = [
            eng.submit(lambda i=i: i * i, bytes_hint=8)
            for i in range(4)
        ]
    for j in jobs:
        eng.wait(j)
    assert [j.result for j in jobs] == [0, 1, 4, 9]
    assert all(j.rid == "r-pull-7" for j in jobs)
    pipe_mod.reset_engine()
    chunk_spans = [
        s
        for s in obs.state().tracer.snapshot_spans()
        if s.name == "pull.chunk"
    ]
    assert chunk_spans
    assert all(s.rid == "r-pull-7" for s in chunk_spans)


def test_rid_coherent_through_replica_failover(monkeypatch):
    """A replica dies mid-query: the failover event and the re-routed
    dispatch stay inside the SAME request scope — one id, no orphan
    spans, the trace reads as one request."""
    monkeypatch.setenv(
        "DBSCAN_FAULT_SPEC", "serve_replica@0#0:PERSISTENT"
    )
    faults.reset_registry()
    obs.enable()
    rng = np.random.default_rng(13)
    svc = ShardedClusterService(
        EPS, MINPTS, n_shards=2, window=2, max_points_per_partition=500
    )
    with svc:
        svc.submit(_batch(seed=3, n=70))
        assert svc.drain(timeout=300)
        with QueryRouter(svc, replicas=2) as router:
            res = router.query(rng.uniform(-1, 6, size=(30, 2)))
            assert len(res.gids) == 30
            h = router.health()
    assert h["live"] == [1]  # replica 0 evicted mid-query
    assert obs.counters()["serve.router.failovers"] == 1
    spans = obs.state().tracer.snapshot_spans()
    route = next(s for s in spans if s.name == "serve.route")
    rid = route.rid
    assert rid
    # the failover mark rides a span of THIS request
    fo = [
        (s, e)
        for s in spans
        for e in s.events
        if e[0] == "serve.router.failover"
    ]
    assert len(fo) == 1 and fo[0][0].rid == rid
    # no orphans: every serve-layer span this trace recorded belongs
    # to the request (single query -> single id)
    serve_spans = [
        s for s in spans if s.name in ("serve.route", "serve.query")
    ]
    assert serve_spans and all(s.rid == rid for s in serve_spans)


# --- analyze --requests ------------------------------------------------


def test_analyze_requests_rollup_and_render(tmp_path, capsys):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(trace_path=path)
    with obs.request_scope("r-slow-1"):
        with obs.span("serve.route", points=4):
            time.sleep(0.03)
        obs.event("fault.retry", site="serve_query")  # orphan instant
    with obs.request_scope("r-fast-2"):
        with obs.span("serve.route", points=4):
            time.sleep(0.005)
    with obs.span("serve.update", epoch=1):  # rid-less background work
        pass
    obs.flush()
    data = analyze_mod.load_trace(path)
    report = analyze_mod.analyze(data)
    req = report["requests"]
    assert req["n_requests"] == 2
    assert [r["rid"] for r in req["rows"]] == ["r-slow-1", "r-fast-2"]
    slow = req["rows"][0]
    assert slow["wall_ms"] >= 25.0
    assert slow["busy_ms"] <= slow["wall_ms"] + 1e-6
    assert slow["top_span"] == "serve.route"
    assert slow["faults"] == 1
    assert req["rows"][1]["faults"] == 0
    text = analyze_mod.render_requests(report)
    assert "r-slow-1" in text and "slowest requests" in text
    # console smoke: the --requests section alone
    assert analyze_mod.main([path, "--requests"]) == 0
    assert "r-slow-1" in capsys.readouterr().out


def test_analyze_requests_empty_on_old_traces(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(trace_path=path)
    with obs.span("serve.update", epoch=1):
        pass
    obs.flush()
    report = analyze_mod.analyze(analyze_mod.load_trace(path))
    assert report["requests"] == {}  # pre-tracing captures unchanged
    assert "no rid-stamped spans" in analyze_mod.render_requests(report)


# --- live-vs-offline agreement ----------------------------------------


def test_live_windowed_p99_agrees_with_offline():
    """THE agreement pin (the bench stamps both figures): the live
    windowed p99 over a query population matches the offline client-
    side percentile of the same population within AGREEMENT_RTOL."""
    svc = _svc()
    rng = np.random.default_rng(2)
    qpts = rng.uniform(-1, 6, size=(48, 2))
    lats = []
    with svc:
        svc.submit(_batch())
        assert svc.drain(timeout=300)
        svc.query(qpts)  # warm the jit caches outside the population
        live.reset()
        live.ensure_env()
        for _ in range(40):
            t0 = time.perf_counter()
            svc.query(qpts)
            lats.append((time.perf_counter() - t0) * 1e3)
        got = live.quantile("serve.query_ms", 0.99)
        qps_live = live.rate("serve.queries")
        assert live.state().window_count("serve.query_ms") == 40
    # the offline figure at the histogram's rank convention (at bench
    # scale — hundreds of samples — interpolation flavors converge;
    # AGREEMENT_RTOL covers the bucket error plus scheduling jitter)
    ordered = np.sort(np.asarray(lats))
    want = float(ordered[min(len(lats) - 1, int(0.99 * len(lats)))])
    assert got is not None
    assert abs(got - want) / want <= AGREEMENT_RTOL, (got, want)
    assert qps_live > 0.0


# --- TSAN: the live plane is certified race-free ----------------------


def test_sharded_tsan_rerun_race_free_with_live_plane_hot(tmp_path):
    """DBSCAN_TSAN=1 rerun of the concurrent sharded serving shape
    with every new lock hot: live aggregators (reader threads
    observing + the health rollup), the SLO engine evaluating, and
    the throttled expo writer — the report must stay empty."""
    report = tmp_path / "tsan.json"
    code = (
        "import threading\n"
        "import numpy as np\n"
        "from dbscan_tpu.serve import QueryRouter, ShardedClusterService\n"
        "rng = np.random.default_rng(0)\n"
        "svc = ShardedClusterService(0.6, 5, n_shards=2, window=2,"
        " max_points_per_partition=500)\n"
        "stop = threading.Event()\n"
        "with svc:\n"
        "    router = QueryRouter(svc, replicas=2)\n"
        "    def reader():\n"
        "        q = rng.uniform(-6, 6, (24, 2))\n"
        "        while not stop.is_set():\n"
        "            router.query(q)\n"
        "            router.health()\n"
        "    threads = [threading.Thread(target=reader, daemon=True)"
        " for _ in range(2)]\n"
        "    [t.start() for t in threads]\n"
        "    for i in range(4):\n"
        "        svc.submit(np.concatenate(["
        "rng.normal(c, 0.25, (60, 2))"
        " for c in [(0, 0), (5, 0), (0, 5)]]))\n"
        "    assert svc.drain(timeout=300)\n"
        "    stop.set()\n"
        "    [t.join(timeout=60) for t in threads]\n"
        "    router.close()\n"
        "from dbscan_tpu.obs import live\n"
        "assert live.active()\n"
        "assert live.state().window_count('serve.query_ms') > 0\n"
        "print('OK')\n"
    )
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        DBSCAN_TSAN="1",
        DBSCAN_TSAN_REPORT=str(report),
        DBSCAN_FAULT_SPEC="",
        DBSCAN_OBS_EXPO=str(tmp_path / "live.prom"),
        DBSCAN_OBS_EXPO_PERIOD_S="0.05",
        DBSCAN_SLO_QUERY_P99_MS="50",
        DBSCAN_SLO_EVAL_PERIOD_S="0.05",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
    rep = json.load(open(report))
    assert rep["races"] == []
    assert rep["lock_inversions"] == []
    assert (tmp_path / "live.prom").exists()  # the writer ran hot


def test_committed_serve_r03_capture_gates_green():
    """BENCH_SERVE_r03.json (the first capture stamped by the live
    plane) is in bench/history.jsonl and gates green — and pins the
    live-vs-offline agreement on a COMMITTED artifact: the windowed
    p99 the live aggregators reported during the run matches the
    offline client-side top-rung p99 within AGREEMENT_RTOL."""
    from dbscan_tpu.obs import bench_history, regress

    cap_path = os.path.join(REPO, "BENCH_SERVE_r03.json")
    hist_path = os.path.join(REPO, "bench", "history.jsonl")
    assert os.path.exists(cap_path)
    cap = json.load(open(cap_path))
    row = (cap["runs"] if "runs" in cap else [cap])[0]
    ladder = sorted(
        int(k[len("serve_r"):-len("_qps")])
        for k in row if k.startswith("serve_r") and k.endswith("_qps")
    )
    top = ladder[-1]
    # the live plane's stamps ride beside the offline percentiles
    assert row["serve_windowed_qps"] > 0
    live_p99 = row["serve_windowed_p99_ms"]
    offline_p99 = row[f"serve_r{top}_p99_ms"]
    assert (
        abs(live_p99 - offline_p99) / offline_p99 <= AGREEMENT_RTOL
    ), (live_p99, offline_p99)
    assert 0.0 <= row["serve_shed_frac"] < 1.0
    recs = bench_history.parse_capture_file(cap_path)
    metrics = {r["metric"] for r in recs}
    assert {
        f"serve_r{top}_qps", "serve_windowed_p99_ms", "serve_shed_frac",
    } <= metrics
    history = bench_history.load_history(hist_path)
    assert [
        r for r in history if r["metric"] == "serve_windowed_p99_ms"
    ], "r03 not ingested into the committed history"
    # gate the LIVE-plane metrics this PR introduced. The offline
    # serve_r*_qps/_p99_ms family now spans two capture boxes (r02:
    # multi-core, r03: single-core, where readers starve behind the
    # ingest thread) — that population is gated by the r02 test
    # through compare's spread widening; re-gating it here would just
    # pin the box bimodality twice.
    live_keys = {
        "serve_windowed_p99_ms", "serve_windowed_qps", "serve_shed_frac",
    }
    recs = [
        {**r, "source": "fresh-check"}
        for r in recs if r["metric"] in live_keys
    ]
    assert len(recs) == len(live_keys)
    result = regress.compare(recs, history, threshold=0.25)
    assert result["regressions"] == []
