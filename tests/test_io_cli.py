"""io loaders/writers + CLI driver (the parameterized DBSCANSample,
reference DBSCANSample.scala:13-38)."""

import json
import os

import numpy as np
import pytest

from dbscan_tpu import io as io_mod
from dbscan_tpu.cli import main as cli_main


@pytest.fixture
def blob_csv(tmp_path, rng):
    pts = np.concatenate(
        [rng.normal(c, 0.3, (80, 2)) for c in [(0, 0), (6, 6), (-5, 5)]]
    )
    rng.shuffle(pts)
    path = tmp_path / "pts.csv"
    np.savetxt(path, pts, delimiter=",")
    return str(path), pts


def test_csv_roundtrip(tmp_path, rng):
    pts = rng.normal(size=(50, 3))
    p = tmp_path / "a.csv"
    np.savetxt(p, pts, delimiter=",")
    loaded = io_mod.load_points(str(p))
    np.testing.assert_allclose(loaded, pts, rtol=1e-6)

    out = tmp_path / "out.csv"
    clusters = np.arange(50, dtype=np.int32)
    flags = np.ones(50, dtype=np.int8)
    io_mod.save_labeled(str(out), pts, clusters, flags)
    back = np.loadtxt(out, delimiter=",")
    assert back.shape == (50, 5)  # 3 coords + cluster + flag
    np.testing.assert_allclose(back[:, :3], pts, rtol=1e-12)
    np.testing.assert_array_equal(back[:, 3].astype(int), clusters)


def test_parquet_roundtrip(tmp_path, rng):
    pytest.importorskip("pyarrow")
    pts = rng.normal(size=(40, 2))
    out = tmp_path / "out.parquet"
    io_mod.save_labeled(str(out), pts, np.zeros(40, np.int32))
    loaded = io_mod.load_points(str(out))
    # columns come back as c0, c1, cluster — first two are the coords
    np.testing.assert_allclose(loaded[:, :2], pts, rtol=1e-12)


def test_numpy_roundtrip(tmp_path, rng):
    pts = rng.normal(size=(30, 2))
    p = tmp_path / "a.npy"
    np.save(p, pts)
    np.testing.assert_array_equal(io_mod.load_points(str(p)), pts)


def test_load_rejects_1d(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1.0\n2.0\n")
    with pytest.raises(ValueError, match=r"\[N, >=2\]"):
        io_mod.load_points(str(p))


def test_unknown_extension_rejected(tmp_path):
    with pytest.raises(ValueError, match="cannot infer"):
        io_mod.load_points(str(tmp_path / "a.weird"))


def test_cli_end_to_end(tmp_path, blob_csv, capsys):
    inp, pts = blob_csv
    out = str(tmp_path / "labeled.csv")
    rc = cli_main(
        [
            "--input", inp, "--output", out,
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "100",
            "--engine", "archery", "--stats",
        ]
    )
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["n_points"] == len(pts)
    assert stats["n_clusters"] == 3
    back = np.loadtxt(out, delimiter=",")
    assert back.shape == (len(pts), 4)  # x, y, cluster, flag
    assert set(np.unique(back[:, 2].astype(int))) <= {0, 1, 2, 3}
    # clusters are spatially coherent: points of one input blob share a label
    np.testing.assert_allclose(back[:, :2], pts, rtol=1e-9)


def test_cli_mesh_devices(tmp_path, blob_csv):
    inp, pts = blob_csv
    rc = cli_main(
        [
            "--input", inp,
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "60",
            "--mesh-devices", "4",
        ]
    )
    assert rc == 0


def test_cli_too_many_devices(blob_csv):
    inp, _ = blob_csv
    rc = cli_main(
        [
            "--input", inp, "--eps", "0.5", "--min-points", "5",
            "--mesh-devices", "4096",
        ]
    )
    assert rc == 2


def test_cli_serve_smoke(capsys):
    """--serve runs the resident ClusterService demo (synthetic stream,
    concurrent queries, tenancy leg) and prints the serve summary JSON
    (--stats routes to JSON-only output)."""
    rc = cli_main(
        [
            "--serve", "--serve-updates", "2", "--serve-batch", "300",
            "--eps", "0.6", "--min-points", "5", "--stats",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["metric"] == "serve"
    assert summary["serve_epoch"] == 2
    assert summary["serve_queries"] > 0
    assert summary["serve_qps"] > 0
    assert summary["tenancy_jobs_s"] > 0
    assert summary["degraded"] is None


def test_serve_main_watch_renders_live_console(capsys, monkeypatch):
    """``python -m dbscan_tpu.serve --watch`` interleaves the live-
    telemetry console frame (obs/live.py windows, rendered through the
    same expo round-trip the file poller uses) with the health lines,
    and the health line itself carries the windowed p99."""
    from dbscan_tpu.obs import live
    from dbscan_tpu.serve.__main__ import main as serve_main

    monkeypatch.delenv("DBSCAN_OBS_LIVE", raising=False)
    live.reset()
    rc = serve_main(
        [
            "--updates", "1", "--batch", "200", "--jobs", "0",
            "--query-batch", "64", "--readers", "1", "--watch",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "dbscan live" in out  # the console frame rendered
    assert "serve.query_ms" in out or "serve.update_ms" in out
    assert "wp99=" in out  # the health line shows the windowed p99
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["metric"] == "serve"
    live.reset()


def test_cli_requires_input_unless_serve(capsys):
    with pytest.raises(SystemExit) as ei:
        cli_main(["--eps", "0.5", "--min-points", "5"])
    assert ei.value.code == 2
    assert "--input" in capsys.readouterr().err
