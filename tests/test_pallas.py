"""Pallas streaming-sweep kernels: bit-parity with the materialized XLA path.

On CPU the kernels run in interpreter mode (same program, pure-JAX
semantics); the real Mosaic lowering is exercised on TPU via
``BENCH_PALLAS=1 python bench.py`` and the driver harness's bench runs.
Parity here is exact — both paths make identical f32
eps-boundary decisions, so labels/flags/counts must match elementwise, not
just up to permutation.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dbscan_tpu import Engine, train
from dbscan_tpu.ops.labels import SEED_NONE
from dbscan_tpu.ops.local_dbscan import local_dbscan
from dbscan_tpu.ops.pallas_kernel import TILE, neighbor_counts, neighbor_min_label


def _blobs(rng, n, spread=8.0):
    centers = rng.uniform(-spread, spread, size=(max(2, n // 200), 2))
    per = n // len(centers)
    pts = np.concatenate(
        [rng.normal(c, 0.5, size=(per, 2)) for c in centers]
        + [rng.uniform(-spread, spread, size=(n - per * len(centers), 2))]
    )
    rng.shuffle(pts)
    return pts.astype(np.float32)


def test_neighbor_counts_matches_bruteforce(rng):
    n = 300  # deliberately not a TILE multiple
    pts = _blobs(rng, n)
    mask = np.ones(n, dtype=bool)
    mask[::17] = False
    eps = 0.7
    got = np.asarray(neighbor_counts(jnp.asarray(pts), jnp.asarray(mask), eps**2))
    d2 = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
    want = ((d2 <= eps**2) & mask[None, :] & mask[:, None]).sum(1)
    assert got.shape == (n,)
    np.testing.assert_array_equal(got, want)


def test_neighbor_min_label_matches_bruteforce(rng):
    n = TILE + 37
    pts = _blobs(rng, n)
    mask = np.ones(n, dtype=bool)
    col_mask = rng.random(n) < 0.4
    labels = rng.integers(0, n, size=n).astype(np.int32)
    eps = 0.5
    got = np.asarray(
        neighbor_min_label(
            jnp.asarray(pts),
            jnp.asarray(mask),
            jnp.asarray(col_mask),
            jnp.asarray(labels),
            eps**2,
        )
    )
    d2 = ((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1)
    adj = (d2 <= eps**2) & col_mask[None, :] & mask[:, None]
    want = np.where(adj, labels[None, :], SEED_NONE).min(1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("engine", ["naive", "archery"])
@pytest.mark.parametrize("n", [100, 256, 777])
def test_local_kernel_parity(rng, engine, n):
    pts = jnp.asarray(_blobs(rng, n))
    mask_np = np.ones(n, dtype=bool)
    mask_np[rng.random(n) < 0.1] = False
    mask = jnp.asarray(mask_np)
    ref = local_dbscan(pts, mask, 0.6, 6, engine=engine)
    got = local_dbscan(pts, mask, 0.6, 6, engine=engine, use_pallas=True)
    np.testing.assert_array_equal(np.asarray(got.counts), np.asarray(ref.counts))
    np.testing.assert_array_equal(np.asarray(got.flags), np.asarray(ref.flags))
    np.testing.assert_array_equal(
        np.asarray(got.seed_labels), np.asarray(ref.seed_labels)
    )


def test_train_end_to_end_parity(rng):
    pts = _blobs(rng, 3000, spread=25.0).astype(np.float64)
    kw = dict(eps=0.5, min_points=8, max_points_per_partition=400)
    ref = train(pts, engine=Engine.ARCHERY, **kw)
    got = train(pts, engine=Engine.ARCHERY, use_pallas=True, **kw)
    np.testing.assert_array_equal(got.clusters, ref.clusters)
    np.testing.assert_array_equal(got.flags, ref.flags)
    assert got.n_clusters == ref.n_clusters


def test_pallas_rejects_3d_points(rng):
    pts = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    mask = jnp.ones(64, dtype=bool)
    with pytest.raises(ValueError, match="2-D"):
        local_dbscan(pts, mask, 0.5, 4, use_pallas=True)


def test_pallas_rejects_bf16_precision(rng):
    from dbscan_tpu.config import Precision

    pts = _blobs(rng, 64).astype(np.float64)
    with pytest.raises(ValueError, match="f32"):
        train(
            pts, eps=0.5, min_points=5,
            precision=Precision.BF16, use_pallas=True,
        )


def test_pallas_rejects_non_euclidean(rng):
    pts = _blobs(rng, 64)
    with pytest.raises(ValueError, match="euclidean"):
        train(
            pts.astype(np.float64),
            eps=0.5,
            min_points=5,
            metric="cosine",
            use_pallas=True,
        )
