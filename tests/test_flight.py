"""flightrec + devtime + multi-shard merge (PR 9, dbscan_tpu/obs/).

Pins, per the acceptance bar:

- a fault-injected train with tracing DISABLED leaves a flight-recorder
  dump containing the abort site and >= the last 64 spans (the ring is
  cross-run by design — a campaign's healthy legs stay in the tail);
- the always-on recorder's overhead is < 1% on the dense bench shape
  (min-of-reps, absolute slack for timer noise — the PR-2 guard's
  discipline at the tighter bound);
- ``obs.analyze --merge`` over two process shards emits ONE
  Perfetto-valid trace with disjoint track ids and a cross-process
  critical path whose arithmetic is pinned exactly on hand-built
  shards;
- ``DBSCAN_PROFILE_WINDOW`` opens and closes without leaking a
  profiler session under tier-1 CPU;
- ``pull.stall`` / ``pull.queue_depth`` make a wedged pull engine
  visible from the blocked consumer;
- ``cli.py --metrics-summary`` reports gauges (HBM watermarks,
  ``pull.inflight``) next to the counters.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from dbscan_tpu import Engine, faults, obs, train
from dbscan_tpu.obs import analyze as analyze_mod
from dbscan_tpu.obs import devtime
from dbscan_tpu.obs import export as export_mod
from dbscan_tpu.obs import flight
from dbscan_tpu.obs.trace import NOOP_SPAN
from dbscan_tpu.parallel import driver
from dbscan_tpu.parallel import pipeline as pipe_mod

pytestmark = pytest.mark.flight


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch, tmp_path):
    """Every test starts with a fresh flight ring (default-on), a
    test-local dump path, devtime off, obs off, and a virgin fault
    registry/pull engine."""
    monkeypatch.delenv("DBSCAN_TRACE", raising=False)
    monkeypatch.delenv("DBSCAN_FLIGHTREC", raising=False)
    monkeypatch.setenv(
        "DBSCAN_FLIGHTREC_PATH", str(tmp_path / "flightrec.json")
    )
    monkeypatch.setenv("DBSCAN_FAULT_BACKOFF_S", "0")
    obs.disable()
    flight.reset()
    devtime.reset()
    faults.reset_registry()
    pipe_mod.reset_engine()
    yield
    obs.disable()
    flight.reset()
    devtime.reset()
    faults.reset_registry()
    pipe_mod.reset_engine()


def _blobs(seed=0):
    rng = np.random.default_rng(seed)
    sizes = [80, 200, 500, 1200, 300, 900]
    centers = [(0, 0), (8, 8), (-7, 9), (9, -8), (-9, -9), (16, 2)]
    pts = np.concatenate(
        [rng.normal(c, 0.4, (s, 2)) for c, s in zip(centers, sizes)]
    )
    rng.shuffle(pts)
    return pts


KW_BANDED = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="banded",
)
KW_DENSE = dict(
    eps=0.5, min_points=5, max_points_per_partition=256,
    engine=Engine.ARCHERY, neighbor_backend="dense",
)


# --- the always-on ring -----------------------------------------------


def test_ring_records_with_tracing_disabled(tmp_path):
    """A plain train() with observability OFF fills the flight ring —
    spans, counters, per-thread track ids — and creates neither an obs
    registry nor any file."""
    train(_blobs(), **KW_BANDED)
    assert obs.state() is None  # full observability stayed off
    fs = flight.state()
    assert fs is not None
    spans = fs.tracer.snapshot_spans()
    assert len(spans) >= 20
    names = {sp.name for sp in spans}
    assert "driver.histogram" in names and "pull.chunk" in names
    # the pull-engine worker's spans carry their own thread track
    assert len({sp.tid for sp in spans}) >= 2
    assert fs.metrics.counters().get("transfer.d2h_bytes", 0) > 0
    assert not (tmp_path / "flightrec.json").exists()  # no dump yet


def test_flightrec_off_restores_strict_noop(monkeypatch):
    monkeypatch.setenv("DBSCAN_FLIGHTREC", "0")
    flight.reset()
    train(_blobs(), **KW_BANDED)
    assert flight.state() is None
    assert obs.span("x") is NOOP_SPAN
    assert obs.add_span("x", 0.0, 1.0) is None


def test_default_dump_path_is_tmp_scoped_never_cwd(monkeypatch):
    """The CWD-littering regression pin: with DBSCAN_FLIGHTREC_PATH
    unset, dumps land under the system tmp dir as a run-scoped
    ``dbscan-flightrec.<pid>.json`` — never a bare ``flightrec.json``
    in whatever directory the process was cwd'd into (the repo root,
    for a tier-1 run). The stray file is also .gitignore'd in case an
    older artifact survives somewhere."""
    import tempfile

    monkeypatch.delenv("DBSCAN_FLIGHTREC_PATH", raising=False)
    path = flight._default_path()
    assert os.path.isabs(path)
    assert os.path.dirname(path) == tempfile.gettempdir()
    assert os.path.basename(path) == (
        f"dbscan-flightrec.{os.getpid()}.json"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert not os.path.exists(os.path.join(repo, "flightrec.json"))
    gitignore = open(os.path.join(repo, ".gitignore")).read()
    assert "flightrec.json" in gitignore
    # a real dump honors the default: writes tmp, not the cwd
    flight.ensure_env()
    with obs.span("x"):
        pass
    out = flight.dump(reason="default_path_pin")
    try:
        assert out == path and os.path.exists(out)
    finally:
        if out and os.path.exists(out):
            os.remove(out)


def test_dump_on_demand_shape(tmp_path):
    train(_blobs(), **KW_BANDED)
    path = flight.dump(reason="operator_poke", extra="context")
    d = flight.load(path)
    assert d["flightrec"] == 1
    assert d["reason"] == "operator_poke"
    assert d["note"] == {"extra": "context"}
    assert d["source"] == "flightrec"
    assert d["pid"] == os.getpid() and d["shard"] is None
    assert d["capacity"] >= 64
    assert len(d["spans"]) >= 20
    for sp in d["spans"]:
        assert {"name", "t0_s", "dur_s", "depth", "tid", "args"} <= set(sp)
    assert d["counters"].get("flightrec.dumps") == 1
    # the dump records itself as the ring's final instant
    ev_names = [e["name"] for s in d["spans"] for e in s["events"]]
    ev_names += [i["name"] for i in d["instants"]]
    assert "flightrec.dump" in ev_names


def test_dump_reads_live_obs_registries_when_enabled(tmp_path):
    """An obs-enabled run records once: the dump reads the live obs
    tail instead of the (idle) flight ring."""
    obs.enable()
    train(_blobs(), **KW_DENSE)
    path = flight.dump(reason="obs_backed")
    d = flight.load(path)
    assert d["source"] == "obs"
    assert any(sp["name"] == "train" for sp in d["spans"])
    # and the dump marked itself in the obs registries
    assert obs.counters().get("flightrec.dumps") == 1


def test_fault_dump_contains_abort_site_and_last_64_spans(
    tmp_path, monkeypatch
):
    """THE acceptance pin: a campaign runs two healthy legs, then a
    persistent mid-pull fault kills the third — all with tracing
    disabled. The abort leaves (a) the banked chunks + abort note the
    PR-5 path already guaranteed, and (b) a flight-recorder dump whose
    note names the ``pull`` site and whose ring tail holds >= 64 spans
    of the runs leading up to the death."""
    pts = _blobs()
    monkeypatch.setattr(driver, "_COMPACT_CHUNK_SLOTS", 512)
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    train(pts, **KW_BANDED)  # healthy legs: the ring keeps their tail
    train(pts, **KW_BANDED)
    ck = tmp_path / "ck"
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "pull#1:PERSISTENT")
    faults.reset_registry()
    with pytest.raises(faults.FatalDeviceFault) as ei:
        train(pts, checkpoint_dir=str(ck), **KW_BANDED)
    assert ei.value.site == "pull"
    assert obs.state() is None  # tracing really was off throughout

    d = flight.load(str(tmp_path / "flightrec.json"))
    assert d["reason"] == "fatal_fault"
    assert d["note"]["site"] == "pull"
    assert len(d["spans"]) >= 64
    names = [sp["name"] for sp in d["spans"]]
    assert "dispatch.banded" in names and "pull.chunk" in names
    ev_names = {e["name"] for s in d["spans"] for e in s["events"]}
    ev_names |= {i["name"] for i in d["instants"]}
    assert "fault.fatal" in ev_names and "flightrec.dump" in ev_names
    # the PR-5 abort guarantees still hold next to the new dump
    assert len(list(ck.glob("p1chunk*.npz"))) >= 1
    from dbscan_tpu.parallel import checkpoint as ckpt_mod

    assert ckpt_mod.read_progress(str(ck))["aborted_site"] == "pull"


def test_fatal_dispatch_fault_dumps_site(monkeypatch, tmp_path):
    """Fatal faults that never reach the driver's abort guard path
    with a checkpoint (plain dispatch, no fallback) still dump — the
    wiring sits in faults.supervised itself."""
    monkeypatch.setenv("DBSCAN_FAULT_SPEC", "dispatch#0:PERSISTENT")
    faults.reset_registry()
    with pytest.raises(faults.FatalDeviceFault):
        train(_blobs(), fault_cpu_fallback=False, **KW_DENSE)
    d = flight.load(str(tmp_path / "flightrec.json"))
    assert d["reason"] == "fatal_fault"
    assert d["note"]["site"] == "dispatch"
    assert d["note"]["ordinal"] == 0


def test_sigusr1_dumps_and_process_continues(tmp_path):
    """SIGUSR1 = poke a live process for a postmortem: the handler
    dumps and execution continues (the streaming-service debug lever)."""
    train(_blobs(), **KW_BANDED)  # installs the handlers via ensure_env
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.time() + 5
    path = tmp_path / "flightrec.json"
    while not path.exists() and time.time() < deadline:
        time.sleep(0.01)
    d = flight.load(str(path))
    assert d["reason"] == "SIGUSR1"
    assert len(d["spans"]) >= 20


def test_sigterm_dumps_then_terminates(tmp_path):
    """SIGTERM (the preemption signal): dump, then die with the
    standard SIGTERM status. Exercised in a subprocess — the recorder
    needs no jax, so the child is import-light."""
    dump = tmp_path / "term.json"
    code = (
        "import os, signal\n"
        f"os.environ['DBSCAN_FLIGHTREC_PATH'] = {str(dump)!r}\n"
        "from dbscan_tpu.obs import flight\n"
        "import dbscan_tpu.obs as obs\n"
        "flight.ensure_env()\n"
        "with obs.span('child.work', step=1):\n"
        "    obs.count('child.counter', 3)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "print('UNREACHABLE')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
    assert b"UNREACHABLE" not in proc.stdout
    d = flight.load(str(dump))
    assert d["reason"] == "SIGTERM"
    assert [sp["name"] for sp in d["spans"]] == ["child.work"]
    assert d["counters"]["child.counter"] == 3


def test_signal_safe_dump_cannot_deadlock_on_held_locks():
    """CPython signal handlers run on the main thread between
    bytecodes: the interrupted frame may already HOLD the tracer or
    metrics lock. The signal-path dump must therefore never acquire
    them — pinned by dumping WHILE this thread holds both locks (the
    locked path would deadlock right here)."""
    flight.ensure_env()
    fs = flight.state()
    obs.count("pre.lock", 1)
    with obs.span("held"):
        pass
    with fs.metrics._lock:
        with fs.tracer._lock:
            path = flight.dump(reason="SIGTERM", _signal_safe=True)
    d = flight.load(path)
    assert d["reason"] == "SIGTERM"
    assert d["counters"]["pre.lock"] == 1
    assert any(sp["name"] == "held" for sp in d["spans"])
    # the signal-safe path emits no telemetry of its own (no locks)
    assert "flightrec.dumps" not in d["counters"]


def test_sigterm_with_ignored_disposition_keeps_handler(tmp_path):
    """A harness that set SIGTERM to SIG_IGN before the recorder
    installed: the prior disposition is honored (the process survives)
    AND the handler stays installed — the SECOND SIGTERM still dumps."""
    dump = tmp_path / "ign.json"
    code = (
        "import os, signal, json\n"
        f"os.environ['DBSCAN_FLIGHTREC_PATH'] = {str(dump)!r}\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "from dbscan_tpu.obs import flight\n"
        "import dbscan_tpu.obs as obs\n"
        "flight.ensure_env()\n"
        "obs.count('c', 1)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "obs.count('c', 1)  # survived: prior disposition was ignore\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "d = json.load(open(os.environ['DBSCAN_FLIGHTREC_PATH']))\n"
        "print('second dump counters', d['counters']['c'])\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr.decode()
    # the dump on disk reflects the SECOND signal (c == 2): the handler
    # survived the first one
    assert b"second dump counters 2" in proc.stdout


def test_ring_is_bounded(monkeypatch):
    monkeypatch.setenv("DBSCAN_FLIGHTREC_EVENTS", "100")
    flight.reset()
    flight.ensure_env()
    fs = flight.state()
    assert fs.capacity == 100 and fs.tracer.max_spans == 200
    for i in range(500):
        obs.add_span(f"s{i}", float(i), float(i) + 0.5)
        obs.event(f"e{i}", i=i)
    assert len(fs.tracer.spans) <= 200
    assert len(fs.tracer.instants) <= 200  # instants bounded too (ring)
    assert fs.tracer.dropped_spans > 0
    path = flight.dump(reason="bounded")
    d = flight.load(path)
    # the TAIL survives: the newest span is present, >= capacity kept
    assert d["spans"][-1]["name"] == "s499"
    assert len(d["spans"]) >= 100


def test_flight_overhead_under_1pct_on_dense_shape(monkeypatch):
    """The acceptance overhead pin: the always-on ring (flight ON, obs
    OFF — the default production state) adds < 1% to the dense bench
    shape versus DBSCAN_FLIGHTREC=0, min-of-reps on a warmed pipeline,
    with absolute slack for timer noise (the PR-2 guard's discipline
    at the tighter bound)."""
    pts = _blobs(1)[:600]

    def run():
        train(pts, **KW_DENSE)

    def min_wall(reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        return best

    run()  # warm the jit caches
    monkeypatch.setenv("DBSCAN_FLIGHTREC", "0")
    flight.reset()
    run()
    without = min_wall()
    assert flight.state() is None
    monkeypatch.setenv("DBSCAN_FLIGHTREC", "1")
    flight.reset()
    run()
    assert flight.state() is not None
    with_ring = min_wall()
    assert with_ring <= without * 1.01 + 0.015, (
        f"flight-recorder overhead: {with_ring:.4f}s vs "
        f"{without:.4f}s with the ring off"
    )


# --- pull-engine health (pull.stall / pull.queue_depth) ---------------


def test_pull_stall_event_from_blocked_consumer(monkeypatch):
    """A consumer blocked past DBSCAN_PULL_STALL_S on one job emits
    pull.stall (once) with the queue depth — into the live obs
    registries here, into the flight ring when tracing is off."""
    import threading

    monkeypatch.setenv("DBSCAN_PULL_STALL_S", "0.1")
    obs.enable()
    eng = pipe_mod.PullEngine(inflight=1)
    gate = threading.Event()
    wedged = eng.submit(lambda: gate.wait(10), label="wedged")
    eng.submit(lambda: "queued", label="queued")
    releaser = threading.Timer(0.4, gate.set)
    releaser.start()
    try:
        t0 = time.perf_counter()
        eng.wait(wedged)
        assert time.perf_counter() - t0 >= 0.3
        stalls = [
            i for i in obs.state().tracer.instants
            if i[0] == "pull.stall"
        ]
        assert len(stalls) == 1
        args = stalls[0][2]
        assert args["label"] == "wedged"
        assert args["queue_depth"] == 2  # wedged (executing) + queued
        assert args["waited_s"] >= 0.1
        assert obs.counters()["pull.stalls"] == 1
    finally:
        releaser.cancel()
        gate.set()
        eng.close()


def test_pull_stall_lands_in_flight_ring(monkeypatch):
    import threading

    monkeypatch.setenv("DBSCAN_PULL_STALL_S", "0.05")
    flight.ensure_env()
    assert obs.state() is None and flight.active()
    eng = pipe_mod.PullEngine(inflight=1)
    gate = threading.Event()
    job = eng.submit(lambda: gate.wait(10), label="wedged")
    releaser = threading.Timer(0.2, gate.set)
    releaser.start()
    try:
        eng.wait(job)
        fs = flight.state()
        stalled = [i for i in fs.tracer.instants if i[0] == "pull.stall"]
        assert len(stalled) == 1
        assert fs.metrics.counters()["pull.stalls"] == 1
    finally:
        releaser.cancel()
        gate.set()
        eng.close()


def test_queue_depth_gauge_tracks_backlog(monkeypatch):
    import threading

    monkeypatch.setenv("DBSCAN_PULL_STALL_S", "0")  # disabled: no event
    obs.enable()
    eng = pipe_mod.PullEngine(inflight=1)
    gate = threading.Event()
    entered = threading.Event()
    first = eng.submit(lambda: (entered.set(), gate.wait(10)))
    rest = [eng.submit(lambda i=i: i) for i in range(4)]
    try:
        assert entered.wait(5)
        # 1 executing + 4 backlogged, observed while wedged
        assert obs.summary()["gauges"]["pull.queue_depth"] == 5
        gate.set()
        for j in rest:
            eng.wait(j)
        eng.wait(first)
        eng.drain()
        assert obs.summary()["gauges"]["pull.queue_depth"] == 0
        # stall disabled: the blocked waits above emitted no event
        assert "pull.stalls" not in obs.counters()
    finally:
        gate.set()
        eng.close()


def test_stall_knob_declared_and_typed():
    from dbscan_tpu import config

    assert config.ENV_VARS["DBSCAN_PULL_STALL_S"].kind == "float"
    assert config.env("DBSCAN_PULL_STALL_S") == 30.0


# --- device timeline (obs/devtime.py) ---------------------------------


def test_devtime_brackets_emit_counters_and_family_spans():
    devtime.enable()
    obs.enable()
    snap = obs.counters()
    train(_blobs(), **KW_DENSE)
    delta = obs.counters_delta(snap)
    assert delta.get("devtime.samples", 0) >= 1
    assert delta["devtime.device_s"] > 0
    # device window >= host dispatch wall, and = dispatch + sync exactly
    assert delta["devtime.device_s"] >= delta["devtime.dispatch_s"]
    assert delta["devtime.device_s"] == pytest.approx(
        delta["devtime.dispatch_s"] + delta["devtime.sync_s"]
    )
    spans = obs.state().tracer.snapshot_spans()
    dev = [s for s in spans if s.name.startswith("devtime.")]
    assert dev and all(
        s.name == f"devtime.{s.args['family']}" for s in dev
    )
    # every devtime span names a declared compile family
    from dbscan_tpu.obs import schema

    for s in dev:
        assert s.args["family"] in schema.COMPILE_FAMILIES


def test_devtime_disabled_is_default_noop():
    obs.enable()
    train(_blobs(), **KW_DENSE)
    assert "devtime.samples" not in obs.counters()


def test_analyze_devtime_rollup_and_busy_frac(tmp_path):
    """The devtime section's arithmetic, pinned exactly on a hand-built
    trace: per-family device seconds, the counter totals, and
    device_busy_frac = device_s / train wall."""
    obs.enable()
    obs.add_span("train", 0.0, 10.0)
    obs.add_span(
        "devtime.dispatch.dense", 1.0, 4.0,
        family="dispatch.dense", host_s=1.0, sync_s=2.0,
    )
    obs.add_span(
        "devtime.spill.level", 5.0, 7.0,
        family="spill.level", host_s=0.5, sync_s=1.5,
    )
    obs.count("devtime.samples", 2)
    obs.count("devtime.dispatch_s", 1.5)
    obs.count("devtime.sync_s", 3.5)
    obs.count("devtime.device_s", 5.0)
    path = str(tmp_path / "t.json")
    obs.write(path)
    rep = analyze_mod.analyze(analyze_mod.load_trace(path))
    dev = rep["devtime"]
    assert dev["samples"] == 2
    assert dev["device_s"] == 5.0
    assert dev["train_wall_s"] == 10.0
    assert dev["device_busy_frac"] == 0.5
    rows = {r["family"]: r for r in dev["families"]}
    assert rows["dispatch.dense"]["device_s"] == 3.0
    assert rows["dispatch.dense"]["host_s"] == 1.0
    assert rows["spill.level"]["sync_s"] == 1.5
    # families sort by device seconds descending
    assert [r["family"] for r in dev["families"]] == [
        "dispatch.dense", "spill.level",
    ]


def test_analyze_pull_check_measures_device_overlap(tmp_path):
    """The measured pull_overlap_ratio check: device-side overlap is
    the exact intersection of pull.chunk windows with the devtime
    union — 1.5s of the 2s pull busy here, vs the host's claimed 1.8s."""
    obs.enable()
    obs.add_span("pull.chunk", 1.0, 2.0, label="c0", bytes=10)
    obs.add_span("pull.chunk", 3.0, 4.0, label="c1", bytes=10)
    obs.add_span(
        "devtime.dispatch.banded_p1", 0.0, 2.5,
        family="dispatch.banded_p1", host_s=0.1, sync_s=2.4,
    )
    obs.add_span(
        "devtime.dispatch.banded_p1", 3.5, 6.0,
        family="dispatch.banded_p1", host_s=0.1, sync_s=2.4,
    )
    obs.count("pull.busy_s", 2.0)
    obs.count("pull.overlap_s", 1.8)
    path = str(tmp_path / "t.json")
    obs.write(path)
    rep = analyze_mod.analyze(analyze_mod.load_trace(path))
    pc = rep["pull_check"]
    assert pc["pull_busy_s"] == 2.0
    assert pc["host_overlap_s"] == 1.8
    assert pc["host_overlap_ratio"] == 0.9
    assert pc["device_overlap_s"] == 1.5  # [1,2] full + [3.5,4] half
    assert pc["device_overlap_ratio"] == 0.75


def test_bench_stamps_device_busy_frac():
    import bench

    delta = {
        "devtime.samples": 3,
        "devtime.device_s": 0.6,
        "transfer.payload_upload_s": 0.0,
    }
    fields = bench._rep_obs_fields(delta, 1.2)
    assert fields["device_busy_frac"] == 0.5
    # absent when no bracketed dispatch ran
    assert "device_busy_frac" not in bench._rep_obs_fields({}, 1.2)


def test_history_promotes_and_gates_device_busy_frac(tmp_path):
    """bench_history promotes *_device_busy_frac at unit `ratio`;
    obs.regress gates it HIGHER-better — mirroring pull_overlap_ratio."""
    from dbscan_tpu.obs import bench_history, regress

    cap = {
        "backend": "cpu",
        "anchor_seconds": 10.0,
        "anchor_device_busy_frac": 0.8,
    }
    recs = bench_history.normalize_capture(cap, "CAP_new.json", "r9")
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["anchor_device_busy_frac"]["unit"] == "ratio"
    assert regress.direction("anchor_device_busy_frac") == "higher"
    history = [
        dict(by_metric["anchor_device_busy_frac"],
             value=v, source=f"CAP_{i}.json")
        for i, v in enumerate((0.8, 0.82, 0.78))
    ]
    fresh_bad = [dict(by_metric["anchor_device_busy_frac"], value=0.3)]
    res = regress.compare(fresh_bad, history, threshold=0.25)
    assert [e["metric"] for e in res["regressions"]] == [
        "anchor_device_busy_frac"
    ]
    fresh_ok = [dict(by_metric["anchor_device_busy_frac"], value=0.79)]
    res = regress.compare(fresh_ok, history, threshold=0.25)
    assert not res["regressions"]


# --- profiler capture window ------------------------------------------


def test_profile_window_opens_and_closes_without_leak(
    monkeypatch, tmp_path
):
    """DBSCAN_PROFILE_WINDOW=1 under tier-1 CPU: the window opens at
    the first tracked dispatch, closes after the n-th, and leaves NO
    live profiler session (a fresh start_trace/stop_trace cycle must
    succeed afterwards). One window per process: the latch holds."""
    import jax

    monkeypatch.setenv("DBSCAN_PROFILE_WINDOW", "1")
    monkeypatch.setenv("DBSCAN_PROFILE_DIR", str(tmp_path / "prof"))
    devtime.reset()
    train(_blobs(), **KW_DENSE)
    ws = devtime.window_state()
    assert ws["done"] and not ws["active"]
    assert ws["seen"] >= 1
    # no leaked session: a fresh profiler cycle succeeds
    jax.profiler.start_trace(str(tmp_path / "prof2"))
    jax.profiler.stop_trace()
    # latch: a second train opens no second window
    train(_blobs(), **KW_DENSE)
    assert devtime.window_state()["seen"] == ws["seen"]


def test_profile_window_events_and_conversion(monkeypatch, tmp_path):
    monkeypatch.setenv("DBSCAN_PROFILE_WINDOW", "1")
    prof = str(tmp_path / "prof")
    monkeypatch.setenv("DBSCAN_PROFILE_DIR", prof)
    devtime.reset()
    obs.enable()
    train(_blobs(), **KW_DENSE)
    evs = [i[0] for i in obs.state().tracer.instants] + [
        e[0]
        for sp in obs.state().tracer.snapshot_spans()
        for e in sp.events
    ]
    assert "profile.window_open" in evs
    assert "profile.window_close" in evs
    assert obs.counters().get("profile.windows") == 1
    # conversion: where this jaxlib emits trace.json.gz, the converted
    # file is a loadable Chrome trace; where it emits only xplane.pb,
    # convert returns None (documented degradation) — both accepted,
    # but the call itself must never raise
    out = devtime.convert_profile(prof, str(tmp_path / "conv.json"))
    if out is not None:
        data = analyze_mod.load_trace(out)
        assert isinstance(data["spans"], list)
    assert devtime.convert_profile(str(tmp_path / "empty")) is None


# --- multi-shard trace merge ------------------------------------------


def _write_shard(path, epoch0, pid, shard, spans):
    """Hand-built JSONL shard: exact numbers for the merge arithmetic."""
    lines = [
        json.dumps(
            {"type": "meta", "epoch0": epoch0, "pid": pid, "shard": shard}
        )
    ]
    for name, t0, dur, tid in spans:
        lines.append(
            json.dumps(
                {
                    "type": "span", "name": name, "t0_s": t0,
                    "dur_s": dur, "depth": 0, "tid": tid, "args": {},
                    "events": [],
                }
            )
        )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_merge_aligns_clocks_and_pins_critical_path(tmp_path):
    """Exact-arithmetic pin of the cross-process critical path: shard B
    starts 2s after A (epoch offset); A busy [0,4]+[6,8] in merged
    time, B busy [3,7] — exclusive stretches A:[0,3]+[7,8]=4s,
    B:[4,6]=2s, all-busy [3,4]+[6,7]=2s, idle [pause]=0."""
    a = str(tmp_path / "s.0")
    b = str(tmp_path / "s.1")
    _write_shard(
        a, epoch0=1000.0, pid=7, shard=0,
        spans=[("work", 0.0, 4.0, 11), ("work", 6.0, 2.0, 11)],
    )
    _write_shard(
        b, epoch0=1002.0, pid=7, shard=1,  # SAME os pid on purpose
        spans=[("work", 1.0, 4.0, 11)],  # merged: [3, 7]
    )
    merged = analyze_mod.merge_shards([a, b])
    mg = merged["merge"]
    assert mg["n_shards"] == 2
    assert mg["wall_s"] == 8.0
    assert mg["all_busy_s"] == 2.0
    assert mg["idle_s"] == 0.0
    sh = {s["index"]: s for s in mg["shards"]}
    assert sh[0]["offset_s"] == 0.0 and sh[1]["offset_s"] == 2.0
    assert sh[0]["busy_s"] == 6.0 and sh[0]["exclusive_s"] == 4.0
    assert sh[1]["busy_s"] == 4.0 and sh[1]["exclusive_s"] == 2.0
    segs = sorted(
        ((g["shard"], g["t0_s"], g["t1_s"]) for g in mg["serial_segments"])
    )
    assert segs == [(0, 0.0, 3.0), (0, 7.0, 8.0), (1, 4.0, 6.0)]
    # disjoint track ids BY CONSTRUCTION, even with colliding os pids
    trace = merged["trace"]
    pids = {
        e["pid"] for e in trace["traceEvents"] if e["ph"] != "M"
    }
    assert pids == {1, 2}
    # the same-tid spans of different shards landed on different tracks
    tids = {
        (e["pid"], e["tid"])
        for e in trace["traceEvents"]
        if e["ph"] == "X"
    }
    assert len({t for _, t in tids}) == 2


def test_merge_real_two_shard_trace_is_perfetto_valid(tmp_path):
    """Two real runs exported as shards -> --merge emits one
    Perfetto-valid trace (ph/ts/dur/pid on every event) with disjoint
    per-shard pids, and the console entry point round-trips."""
    pts = _blobs()
    s0 = str(tmp_path / "run.json.0")
    s1 = str(tmp_path / "run.json.1")
    obs.enable(trace_path=s0)
    train(pts, **KW_BANDED)
    obs.flush()
    obs.disable()
    obs.enable(trace_path=s1)
    train(pts, **KW_DENSE)
    obs.flush()
    obs.disable()
    out = str(tmp_path / "merged.json")
    rc = analyze_mod.main(["--merge", s0, s1, "-o", out])
    assert rc == 0
    with open(out) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert evs
    by_shard_pid = {}
    for e in evs:
        assert e["ph"] in ("X", "i", "C", "M")
        assert isinstance(e.get("ts"), (int, float))
        assert "pid" in e and "name" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0
            by_shard_pid.setdefault(e["pid"], set()).add(e["tid"])
    assert set(by_shard_pid) == {1, 2}  # one disjoint pid per shard
    # no merged tid is shared across the two shard pids
    assert not (by_shard_pid[1] & by_shard_pid[2])
    assert trace["otherData"]["merged"] is True
    assert len(trace["otherData"]["shards"]) == 2
    # both shards' spans survived into one timeline
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert "train" in names and "driver.histogram" in names
    # the merged report carries a cross-process critical path
    merged = analyze_mod.merge_shards([s0, s1])
    assert merged["merge"]["n_shards"] == 2
    assert merged["merge"]["wall_s"] > 0
    assert sum(s["busy_s"] for s in merged["merge"]["shards"]) > 0


def test_single_trace_cli_rejects_multiple_without_merge(tmp_path):
    with pytest.raises(SystemExit):
        analyze_mod.main(["a.json", "b.json"])


def test_trace_flush_shards_path_under_multiprocess(
    monkeypatch, tmp_path
):
    """DBSCAN_TRACE under a multi-process job: flush writes
    <path>.<process_index> (and JSONL shards keep the JSONL format
    despite the suffix hiding the extension)."""
    monkeypatch.setattr(export_mod, "shard_index", lambda: 1)
    for name, want_jsonl in (("t.json", False), ("t.jsonl", True)):
        obs.disable()
        obs.enable(trace_path=str(tmp_path / name))
        with obs.span("x"):
            pass
        written = obs.flush()
        assert written == str(tmp_path / name) + ".1"
        with open(written) as f:
            text = f.read()
        if want_jsonl:
            assert text.splitlines()[0].startswith('{"type": "meta"')
        else:
            assert json.loads(text)["traceEvents"]
        # and the shard id rides the export metadata
        data = analyze_mod.load_trace(written)
        assert data["meta"]["shard"] == 1


# --- cli gauges regression (satellite) --------------------------------


def _write_csv(tmp_path):
    path = tmp_path / "pts.csv"
    np.savetxt(path, _blobs()[:800], delimiter=",")
    return str(path)


def test_cli_metrics_summary_includes_gauges(monkeypatch, tmp_path, capsys):
    """--metrics-summary reports GAUGES (HBM watermarks, pull.inflight)
    next to the counters — pinned with fake allocator stats so the
    memory.* watermarks appear under tier-1 CPU too."""
    from dbscan_tpu import cli
    from dbscan_tpu.obs import memory

    stats = {
        "tpu:0": {
            "bytes_in_use": 123_000,
            "peak_bytes_in_use": 456_000,
            "bytes_limit": 16_000_000,
        }
    }
    monkeypatch.setattr(memory, "device_memory_stats", lambda: stats)
    memory.reset_peak()
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    rc = cli.main(
        [
            "--input", _write_csv(tmp_path),
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "256",
            "--metrics-summary",
        ]
    )
    memory.reset_peak()
    assert rc == 0
    out = capsys.readouterr().out
    assert "== metrics summary ==" in out
    assert "gauges:" in out
    gauge_block = out.split("gauges:", 1)[1]
    assert "pull.inflight" in gauge_block
    assert "memory.bytes_in_use" in gauge_block
    assert "memory.peak_bytes_in_use" in gauge_block
    assert "flight recorder: on" in out


def test_cli_metrics_summary_pins_cellcc_counters(monkeypatch, tmp_path,
                                                  capsys):
    """The PR-10 extension of the summary regression: a banded run
    (--neighbor-backend banded forces the route at any size) must
    surface the device cellcc finalize's convergence counter in the
    counters block next to the gauges the base test pins (the compile
    counters only appear on cache-cold processes, so the always-emitted
    cc_iters is the pinned name)."""
    from dbscan_tpu import cli

    monkeypatch.setenv("DBSCAN_CELLCC_DEVICE", "1")
    rc = cli.main(
        [
            "--input", _write_csv(tmp_path),
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "256",
            "--neighbor-backend", "banded",
            "--metrics-summary",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "== metrics summary ==" in out
    assert "cellcc.cc_iters" in out
    assert "gauges:" in out


def test_cli_trace_plus_summary_gauges_in_both(monkeypatch, tmp_path, capsys):
    """--trace + --metrics-summary together: the summary carries the
    gauges AND the flushed trace file carries them on the counter
    track (the satellite's regression shape)."""
    from dbscan_tpu import cli

    trace = str(tmp_path / "t.json")
    monkeypatch.setenv("DBSCAN_PULL_PIPELINE", "1")
    rc = cli.main(
        [
            "--input", _write_csv(tmp_path),
            "--eps", "0.5", "--min-points", "5",
            "--max-points-per-partition", "256",
            "--trace", trace,
            "--metrics-summary",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "gauges:" in out and "pull.inflight" in out
    with open(trace) as f:
        t = json.load(f)
    counter_names = {
        e["name"] for e in t["traceEvents"] if e["ph"] == "C"
    }
    assert "pull.inflight" in counter_names
    assert "pull.inflight" in t["otherData"]["gauges"]
