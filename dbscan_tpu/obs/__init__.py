"""Observability for the distributed pipeline: spans, counters, export.

VERDICT round 5 rejected the cosine >=10x bar largely on observability
grounds: the official wall swings 5-60 s across same-day captures
because the resident-payload cache makes the timed rep
nondeterministically hot or cold with respect to a ~1 GB upload, and
nobody could say WHERE the time went. This package is the first-class
telemetry layer that answers that question — the host/device phase
split GPU DBSCAN papers report when defending speedups (arXiv
2103.05162 build vs. query vs. transfer; arXiv 1912.06255).

Three modules, one process-global state:

- :mod:`dbscan_tpu.obs.trace` — nested wall-clock spans with optional
  device-sync boundaries (the ``DBSCAN_TIME_DEVICE=1`` convention);
- :mod:`dbscan_tpu.obs.metrics` — dotted-name counters/gauges
  (transfer bytes, resident-cache hits/misses, chunk flushes, fault
  retries);
- :mod:`dbscan_tpu.obs.export` — JSONL + Chrome-trace
  (chrome://tracing / Perfetto) writers.

Activation:

- ``DBSCAN_TRACE=path.json`` in the environment — picked up by
  :func:`ensure_env` at the pipeline entry points (driver, streaming);
  the trace file is (re)written at the end of every run;
- :func:`enable` — explicit, from ``cli.py --trace/--metrics-summary``
  or a harness (bench.py enables an in-memory registry around its
  timed reps; no file unless a path is given).

THE DISABLED PATH IS (NEARLY) A STRICT NO-OP (pinned by
tests/test_obs.py and the overhead-guard tests): with full
observability off, every module-level hook performs one truthiness
check of the process-global state plus one of the flight recorder's
(:mod:`dbscan_tpu.obs.flight` — the always-on bounded postmortem ring,
``DBSCAN_FLIGHTREC``, default on). With the recorder live the hook
appends to its bounded ring (<1% on the dense bench shape, pinned by
tests/test_flight.py); with ``DBSCAN_FLIGHTREC=0`` the original strict
no-op path is restored — no allocation, no registry, no file is ever
touched. When observability is ENABLED the hooks record once, into the
live registries only (the flight dump then reads their tail), so the
enabled path pays nothing new.
"""

from __future__ import annotations

import time
from typing import Optional

from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import export as export_mod
from dbscan_tpu.obs import flight
from dbscan_tpu.obs import live
_flight = flight  # internal alias: hot hooks read _flight._state directly
from dbscan_tpu.obs.metrics import MetricsRegistry
from dbscan_tpu.obs.trace import (  # noqa: F401
    NOOP_SPAN,
    Span,
    Tracer,
    current_request,
    mint_request_id,
    request_scope,
    reset_request,
    set_request,
)

__all__ = [
    "NOOP_SPAN",
    "flight",
    "live",
    "Span",
    "Tracer",
    "MetricsRegistry",
    "active",
    "add_span",
    "count",
    "counters",
    "counters_delta",
    "current_request",
    "disable",
    "enable",
    "ensure_env",
    "event",
    "flush",
    "gauge",
    "mint_request_id",
    "request_scope",
    "reset_request",
    "set_request",
    "span",
    "state",
    "summary",
]


class ObsState:
    """The process-global observability state: one tracer, one metrics
    registry, and the optional export path."""

    __slots__ = ("tracer", "metrics", "trace_path")

    def __init__(
        self,
        tracer: Tracer,
        metrics: MetricsRegistry,
        trace_path: Optional[str],
    ):
        self.tracer = tracer
        self.metrics = metrics
        self.trace_path = trace_path


_state: Optional[ObsState] = None
_lock = _tsan.lock("obs.state")


def state() -> Optional[ObsState]:
    """The live state, or None when disabled — the one value every
    hook truth-checks."""
    return _state


def active() -> bool:
    return _state is not None


def enable(
    trace_path: Optional[str] = None,
    device_sync: Optional[bool] = None,
) -> ObsState:
    """Turn observability on (idempotent). ``trace_path``: where
    :func:`flush` writes the trace (None = in-memory only — counters
    and spans still accumulate for harnesses to snapshot).
    ``device_sync``: spans that registered device outputs block on them
    at exit (defaults to the ``DBSCAN_TIME_DEVICE=1`` convention).

    IDEMPOTENCE / RESET SEMANTICS (the contract cli.py and the
    harnesses rely on): re-enabling an already-live state is a no-op
    that only ADOPTS a trace path it did not have — the registries
    persist, so a harness's in-memory enable and a later env activation
    share one timeline. The ONLY reset is an explicit
    :func:`disable` followed by :func:`enable`: that starts a fresh
    timeline (new tracer time base, empty counter/gauge registries,
    no trace path). Nothing resets implicitly — nested enables from a
    CLI flag, an env activation, and a test harness can interleave in
    any order without clobbering each other's spans."""
    global _state
    with _lock:
        _tsan.access("obs.state")
        if _state is None:
            if device_sync is None:
                device_sync = bool(config.env("DBSCAN_TIME_DEVICE"))
            _state = ObsState(
                Tracer(device_sync=bool(device_sync)),
                MetricsRegistry(),
                trace_path,
            )
        elif trace_path and not _state.trace_path:
            _state.trace_path = trace_path
        st = _state
    # the flight recorder's env latch (and its signal handlers) must be
    # live for obs-enabled runs too: the dump then reads THESE registries
    _flight.ensure_env()
    return st


def disable() -> None:
    """Drop the state WITHOUT writing (symmetric with enable; callers
    that want the trace must :func:`flush` first — cli.py's finally
    block does exactly that). A later :func:`enable` starts a FRESH
    timeline: disable+enable is the documented reset."""
    global _state
    with _lock:
        _tsan.access("obs.state")
        _state = None


def ensure_env() -> None:
    """Activate from ``DBSCAN_TRACE=path`` when set — called at the
    pipeline entry points — and (re)apply the always-on subsystems'
    env knobs: the flight recorder (``DBSCAN_FLIGHTREC``) and the
    device-timeline hooks (``DBSCAN_DEVTIME`` /
    ``DBSCAN_PROFILE_WINDOW``). A few env lookups per train entry;
    each subsystem latches its value, so steady-state updates pay no
    state churn."""
    if _state is None:
        path = config.env("DBSCAN_TRACE")
        if path:
            enable(trace_path=path)
    _flight.ensure_env()
    live.ensure_env()
    from dbscan_tpu.obs import devtime as _devtime

    _devtime.ensure_env()


# --- hot-path hooks ---------------------------------------------------
#
# Each hook truth-checks the obs state, then — only when obs is off —
# the flight recorder's (a plain module-global read, no call). The
# recorder reuses the same Tracer/MetricsRegistry machinery, so the
# two destinations behave identically; a run records into exactly ONE.


def span(name: str, **args):
    """Open a nested span (context manager); NOOP_SPAN when both
    observability and the flight recorder are off."""
    st = _state
    if st is not None:
        return st.tracer.span(name, args)
    fs = _flight._state
    if fs is None:
        return NOOP_SPAN
    return fs.tracer.span(name, args)


def add_span(name: str, t0: float, t1: float, **args):
    """Register a retroactive span from perf_counter bounds — the
    bridge for phases that already time themselves (driver timings)."""
    st = _state
    if st is not None:
        return st.tracer.add_span(name, t0, t1, args)
    fs = _flight._state
    if fs is None:
        return None
    return fs.tracer.add_span(name, t0, t1, args)


def event(name: str, **args) -> None:
    """Instant event: attaches to the innermost open span on this
    thread, else to the process-level list."""
    st = _state
    if st is not None:
        st.tracer.instant(name, args)
        return
    fs = _flight._state
    if fs is None:
        return
    fs.tracer.instant(name, args)


def count(name: str, value=1) -> None:
    st = _state
    if st is not None:
        st.metrics.count(name, value)
        return
    fs = _flight._state
    if fs is None:
        return
    fs.metrics.count(name, value)


def gauge(name: str, value) -> None:
    st = _state
    if st is not None:
        st.metrics.gauge(name, value)
        return
    fs = _flight._state
    if fs is None:
        return
    fs.metrics.gauge(name, value)


# --- snapshots / export -----------------------------------------------


def counters() -> dict:
    """Counter snapshot ({} when disabled) — harnesses diff two of
    these around a timed region (see :func:`counters_delta`)."""
    st = _state
    if st is None:
        return {}
    return st.metrics.snapshot()


def counters_delta(snap: dict) -> dict:
    st = _state
    if st is None:
        return {}
    return st.metrics.delta(snap)


def flush() -> Optional[str]:
    """Write the accumulated trace to the configured path (full
    rewrite — atomic, cumulative across runs in this process); returns
    the path, or None when disabled or path-less. Multi-process runs
    write per-process shards — ``<path>.<process_index>`` — so the
    workers of one job never clobber a shared trace path; merge them
    with ``python -m dbscan_tpu.obs.analyze --merge <shards>``."""
    st = _state
    if st is None or not st.trace_path:
        return None
    suffix = export_mod.shard_suffix()
    path = st.trace_path + suffix
    if suffix and st.trace_path.endswith(".jsonl"):
        # the shard suffix hides the extension from write()'s
        # format-by-extension rule; keep the configured format
        return export_mod.write_jsonl(path, st.tracer, st.metrics)
    return export_mod.write(path, st.tracer, st.metrics)


def write(path: str) -> Optional[str]:
    """One-off export to an explicit path (format by extension)."""
    st = _state
    if st is None:
        return None
    return export_mod.write(path, st.tracer, st.metrics)


def summary(top: int = 10) -> dict:
    """Condensed human-facing view: top spans by total wall + all
    counters — the body of ``cli.py --metrics-summary``."""
    st = _state
    if st is None:
        return {"enabled": False, "spans": [], "counters": {}, "gauges": {}}
    return {
        "enabled": True,
        "spans": export_mod.span_summary(st.tracer, top=top),
        "counters": st.metrics.counters(),
        "gauges": st.metrics.gauges(),
    }


def timed_count(name: str, t0: float) -> None:
    """Accumulate elapsed-since-``t0`` seconds into counter ``name``
    (one perf_counter call, only when a destination is live)."""
    st = _state
    if st is not None:
        st.metrics.count(name, time.perf_counter() - t0)
        return
    fs = _flight._state
    if fs is None:
        return
    fs.metrics.count(name, time.perf_counter() - t0)
