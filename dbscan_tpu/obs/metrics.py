"""Process-global counters and gauges for the distributed pipeline.

The reference's only numbers are the driver-side println taps it ships
commented-in (DBSCAN.scala:139,202); Spark's real accounting lives in
executor metrics. Our analog is one flat registry of dotted-name
counters (monotone adds) and gauges (set-last-wins), shared by every
subsystem so one snapshot describes a whole run:

- ``transfer.*`` — host<->device traffic: payload/dispatch upload bytes
  and the measured upload/pull walls (mesh.pull_to_host, the spill
  payload upload, the dispatch fan-outs);
- ``resident_cache.*`` — hits/misses of the driver's resident-payload
  cache (the hot/cold split behind the 5-60 s cosine capture swing);
- ``checkpoint.*`` — compact chunk flushes/saves/loads and their bytes;
- ``faults.*`` — the supervised-dispatch accounting, field-for-field
  the same names as :class:`dbscan_tpu.faults.FaultCounters` (which
  stays the AUTHORITATIVE per-run figure via ``stats["faults"]``; these
  counters are process-cumulative and exist so the trace, the stats
  dict, and the metrics summary can be cross-checked).

Callers never talk to this class directly — the ``dbscan_tpu.obs``
module-level hooks (``obs.count`` / ``obs.gauge``) carry the single
disabled-path truthiness check; the registry only exists while
observability is enabled.
"""

from __future__ import annotations

from dbscan_tpu.lint import tsan as _tsan


class MetricsRegistry:
    """Flat dotted-name counters + gauges, lock-protected (the driver's
    pulls and the packer callbacks can run from different threads)."""

    def __init__(self):
        self._lock = _tsan.lock("obs.metrics")
        self._counters: dict = {}
        self._gauges: dict = {}

    def count(self, name: str, value=1) -> None:
        """Add ``value`` (int or float) to counter ``name``."""
        with self._lock:
            _tsan.access("obs.metrics")
            self._counters[name] = self._counters.get(name, 0) + value

    def gauge(self, name: str, value) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        with self._lock:
            _tsan.access("obs.metrics")
            self._gauges[name] = value

    def counters(self) -> dict:
        with self._lock:
            _tsan.access("obs.metrics", write=False)
            return dict(self._counters)

    def gauges(self) -> dict:
        with self._lock:
            _tsan.access("obs.metrics", write=False)
            return dict(self._gauges)

    def snapshot(self) -> dict:
        """Counter snapshot for delta accounting (gauges excluded:
        set-last-wins values have no meaningful delta)."""
        return self.counters()

    def delta(self, snap: dict) -> dict:
        """Per-run counter delta against a prior :meth:`snapshot`
        (counters are monotone, so every delta is >= 0)."""
        cur = self.counters()
        return {k: v - snap.get(k, 0) for k, v in cur.items()}
