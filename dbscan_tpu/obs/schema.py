"""Declared telemetry schema: the single source of truth for every
counter, gauge, span, and event name the package emits.

PR 2/3 coupled producers (driver/spill/faults/checkpoint emit sites)
and consumers (`obs/analyze.py` rollup sections, `obs/regress.py`,
`obs/bench_history.py`, PARITY.md's trace-schema table) through
free-form dotted strings — a renamed counter silently emptied an
analyzer section, exactly the cross-component contract drift the MR-
DBSCAN merge phase cannot afford between partition producers and the
global merge. This module pins the contract in one importable place:

- producers are checked STATICALLY: ``dbscan_tpu.lint`` extracts every
  emitted name from the AST and fails on any name not declared here
  (rule family ``schema-*``);
- consumers import the names/prefixes they read back, so a deletion
  here breaks them at import/test time rather than silently;
- ``tests/test_obs.py`` asserts every name observed at RUNTIME is
  declared, so deleting an emitted name from this file fails both the
  linter and the test suite (the acceptance contract).

Dynamic names are declared through their generator sets: compile
accounting emits ``compiles.<family>`` / ``compile.<family>`` for
``family`` in :data:`COMPILE_FAMILIES`, memory sampling emits
``memory.at.<site>`` for ``site`` in :data:`MEMORY_SITES`, and the
driver's ``_mark`` bridge emits ``driver.<phase>`` for ``phase`` in
:data:`DRIVER_PHASES`. The linter cross-checks the literal family/site
arguments at the ``tracked_call``/``note_compile``/``memory.sample``
call sites against these tuples, so the expansion is just as pinned as
the exact names.

Import-light on purpose (stdlib only): the linter and the offline
analyzers import this without touching jax.
"""

from __future__ import annotations

# --- generator sets for dynamic name families -------------------------

#: jit dispatch families tracked by obs/compile.py `tracked_call`:
#: each emits counter ``compiles.<family>`` and span ``compile.<family>``.
COMPILE_FAMILIES = (
    "dispatch.dense",
    "dispatch.resident",
    "dispatch.banded_p1",
    "cellcc.postpass",
    "cellcc.gather",
    "cellcc.unpack",
    "cellcc.fused",
    "cellcc.cc",
    "spill.gather",
    "spill.level",
    "spill.level_final",
    "halo.merge",
    "serve.query",
    "serve.jobs",
    "serve.broadcast",
    "embed.hash",
    "embed.neighbors",
    "embed.quantize",
    "density.core",
    "density.boruvka",
    "density.condense",
)

#: HBM watermark sample sites (obs/memory.py `sample`): each emits
#: gauge ``memory.at.<site>``.
MEMORY_SITES = (
    "dispatch.dense",
    "dispatch.resident",
    "dispatch.banded",
    "spill.payload_upload",
    "fault.resource_exhausted",
    "serve.health",
)

#: declared SLO keys (obs/slo.py): each emits gauge ``slo.burn.<key>``
#: (the last evaluated fast-window burn rate) and rides the ``slo``
#: argument of the ``slo.burn``/``slo.recover`` events.
SLO_KEYS = (
    "query_p99",
    "shed_frac",
    "staleness",
    "fault_rate",
)

#: live sliding-window HISTOGRAM series (obs/live.py observe): ms-valued
#: observations into the shared log-bucketed window geometry. These are
#: windowed series, not cumulative counters — they surface through
#: health()/the expo file/the live console, never through obs.count.
LIVE_HISTOGRAMS = (
    "serve.query_ms",
    "serve.update_ms",
)

#: live sliding-window RATE series (obs/live.py bump): windowed event
#: counts read back as events/second over the declared window.
LIVE_RATES = (
    "serve.router.routed",
    "serve.router.shed",
    "serve.queries",
    "serve.updates",
    "serve.epoch_publish",
    "faults.events",
)

#: driver `_mark` phases (timings keys sans ``_s``): each emits span
#: ``driver.<phase>`` over the exact window ``stats["timings"]`` reports.
DRIVER_PHASES = (
    "spill_partition",
    "histogram",
    "partition",
    "duplicate",
    "postdispatch",
    "overlap_host",
    "cellcc_pull_rest",
    "cellcc_host",
    "cellcc",
    "device",
)

# --- exact names ------------------------------------------------------

COUNTERS = {
    "transfer.h2d_bytes": "host->device bytes fanned out by the dispatches",
    "transfer.d2h_bytes": "device->host bytes pulled (mesh.pull_to_host)",
    "transfer.d2h_s": "measured d2h pull wall (includes device wait)",
    "transfer.payload_upload_bytes": "spill resident-payload upload bytes",
    "transfer.payload_upload_s": "measured payload-upload wall",
    "resident_cache.hits": "resident-payload cache hits (hot runs)",
    "resident_cache.misses": "resident-payload cache misses (cold runs)",
    "checkpoint.chunk_flushes": "compact p1 chunks flushed by the driver",
    "checkpoint.chunk_pulls": "compact p1 chunks pulled back to host",
    "checkpoint.chunks_saved": "p1 chunks written by checkpoint.save",
    "checkpoint.chunks_loaded": "p1 chunks read back on resume",
    "checkpoint.chunk_bytes": "bytes across saved p1 chunk arrays",
    "checkpoint.premerge_bytes": "bytes across saved pre-merge arrays",
    "faults.attempts": "supervised dispatch attempts started",
    "faults.retries": "attempts re-run after a supervised failure",
    "faults.fallbacks": "groups/steps degraded to the CPU path",
    "faults.budget_halvings": "RESOURCE_EXHAUSTED budget reductions",
    "faults.injected": "injected (vs real) faults observed",
    "faults.backoff_s": "total backoff slept between retries",
    "compiles.total": "jit trace-cache misses observed (all families)",
    "compiles.wall_s": "summed wall of the cache-miss calls",
    "compiles.ratchet_raises": "streaming shape-floor raises post-warm-up",
    "memory.samples": "HBM watermark samples taken",
    "cellcc.cc_iters": "neighbor-min sweeps the device cell "
    "connected-components ran to its fixed point (data-dependent "
    "convergence depth; labels are iteration-count-independent)",
    "prop.sweeps": "window_cc-family fixed-point sweeps across every "
    "consumer (cellcc finalize, halo merge, embed buckets) — the "
    "shared convergence-depth figure the DBSCAN_PROP_UNIONFIND "
    "single-pass union-find mode exists to collapse "
    "(ops/propagation.py note_sweeps; labels are count-independent)",
    "spill.levels": "level-synchronous spill-tree build rounds run",
    "spill.level_dispatches": "fused level-build dispatches issued "
    "(one per level + the closing compact; bounded by tree depth, "
    "vs one-per-node on the host recursion)",
    "halo.rounds": "collective halo-merge neighbor-min sweeps to the "
    "union fixed point (data-dependent convergence depth; labels are "
    "round-count-independent, like cellcc.cc_iters)",
    "halo.edges": "border-union edges merged collectively (doubly-"
    "labeled halo seeds, the paper's executor-merge currency)",
    "halo.nodes": "per-partition cluster nodes entering the collective "
    "halo-merge",
    "mesh.reshards": "sharded runs re-sharded onto a smaller mesh "
    "after a chip-drop fault (campaign.train_resharded)",
    "pull.wait_s": "consumer seconds actually blocked on pipelined pulls",
    "pull.overlap_s": "pull/finalize seconds hidden behind other work",
    "pull.busy_s": "total pipelined pull+finalize wall (worker seconds)",
    "pull.bytes": "bytes routed through the pull pipeline (size hints)",
    "pull.stalls": "pull-pipeline stall warnings emitted (a consumer "
    "blocked past DBSCAN_PULL_STALL_S on one job)",
    "campaign.leases": "campaign chunk/frontier leases granted",
    "campaign.chunks_done": "campaign chunks banked across leases",
    "campaign.steals": "chunks requeued from failed/expired leases "
    "(available to be restolen by the fleet)",
    "campaign.expired": "leases expired by the heartbeat window "
    "(DBSCAN_CAMPAIGN_LEASE_S) — the wedged-worker steal path",
    "campaign.kills": "injected campaign worker kills (TRANSIENT "
    "clauses at the campaign fault site)",
    "campaign.wedges": "injected campaign worker wedges (PERSISTENT "
    "clauses: the lease must expire and be restolen)",
    "campaign.degrades": "campaign workers degraded to the CPU tier "
    "(real retries-exhausted device faults, or injected "
    "RESOURCE_EXHAUSTED)",
    "campaign.repartitions": "fault-rate-aware lease-size changes "
    "(halved while faults run hot, doubled back under health)",
    "campaign.work_wall_s": "summed campaign lease wall (the replay "
    "pricing denominator)",
    "campaign.replayed_wall_s": "summed pro-rata wall of chunks that "
    "had to be recomputed after a lease failed/expired "
    "(campaign_replay_frac numerator)",
    "flightrec.dumps": "flight-recorder postmortem dumps written",
    "serve.updates": "completed ClusterService ingest steps (each "
    "publishes a new query snapshot epoch)",
    "serve.ingest_points": "points ingested across completed serve "
    "updates",
    "serve.ingest_rejects": "micro-batches refused at the full ingest "
    "queue (block=False backpressure refusals)",
    "serve.queries": "query batches answered against the resident "
    "snapshot",
    "serve.query_points": "points across answered query batches",
    "serve.degraded": "serve ingest steps that died un-degradable "
    "(FatalDeviceFault surfaced to the service health state)",
    "serve.checkpoints": "serve state checkpoints written (explicit, "
    "shutdown, or SIGTERM)",
    "serve.restores": "serve state checkpoints restored at service "
    "construction",
    "serve.jobs_done": "small tenant jobs completed by batched "
    "serve.jobs dispatches",
    "serve.job_batches": "batched serve.jobs dispatches issued "
    "(pad-and-stack fan-ins, not per-job dispatches)",
    "serve.jobs_rejected": "tenant jobs rejected at admission (HBM "
    "price over DBSCAN_SERVE_HEADROOM_BYTES, or oversized)",
    "serve.router.routed": "query batches the router accepted and "
    "answered (replica dispatch or host fallback) — the shed-fraction "
    "denominator's accepted leg",
    "serve.router.shed": "query batches refused under p99 shed "
    "pressure (rolling p99 past DBSCAN_SERVE_SHED_P99_MS and the "
    "batch's priced cost over the shrunk admission window)",
    "serve.router.failovers": "in-flight queries re-routed to a "
    "surviving replica after a persistent replica fault (the pinned "
    "cut re-dispatched, never re-pinned)",
    "serve.router.host_fallbacks": "router queries answered by the "
    "numpy union oracle because no live replica remained",
    "serve.replica.evictions": "query replicas evicted from the live "
    "set after a persistent serve_replica fault",
    "serve.broadcast.casts": "per-replica cut broadcasts completed "
    "(one per live replica per published cut)",
    "serve.broadcast.bytes": "host bytes of skeleton state shipped by "
    "cut broadcasts (pre-padding payload, summed over shards)",
    "serve.admit_splits": "job batches split because the stacked "
    "HBM price would breach the admission headroom",
    "checkpoint.serve_saves": "serve state checkpoints written by "
    "checkpoint.save_serve",
    "checkpoint.serve_loads": "serve state checkpoints read back by "
    "checkpoint.load_serve",
    "checkpoint.serve_bytes": "bytes across saved serve state arrays",
    "embed.points": "points entering embed-engine runs",
    "embed.instances": "embed instances after LSH/spill duplication "
    "(duplication factor = this / embed.points)",
    "embed.buckets": "LSH leaf buckets emitted by the boundary-spill "
    "binning (spill-fallback sub-leaves not included)",
    "embed.spill_fallbacks": "binning nodes no hyperplane could split "
    "within the band/progress budget, routed to the pivot spill tree",
    "embed.spill_fallback_points": "points across those fallback nodes "
    "(spill-fallback rate = this / embed.points)",
    "embed.hash_dispatches": "embed.hash device dispatches issued",
    "embed.neighbor_dispatches": "embed.neighbors bucket dispatches "
    "issued (escalation re-runs included)",
    "embed.neighbor_escalations": "bucket re-runs at a wider W rung "
    "after the neighbor table overflowed (steady state: zero — the "
    "per-width ratchet pins the settled rung)",
    "embed.edges": "self-inclusive adjacency entries observed across "
    "bucket dispatches (sampled-edge mode counts the SAMPLED graph)",
    "embed.oracle_fallbacks": "embed dispatches degraded to the numpy "
    "host oracle after persistent faults (per bucket, or one for a "
    "whole-run hash degradation)",
    "embed.quantize_dispatches": "embed.quantize IVF coarse-quantizer "
    "dispatches issued (fp seeding + Lloyd + chord matrix, one per "
    "ivf-routed run)",
    "embed.bands_banked": "bucket-band checkpoint files banked by "
    "checkpointed embed runs (the campaign restart-point grain)",
    "embed.bands_loaded": "bucket-band checkpoint files restored on "
    "resume (fingerprint-verified; loaded bands skip their dispatches)",
    "embed.occ_le_64": "embed buckets holding <= 64 points "
    "(occupancy-histogram edge)",
    "embed.occ_le_1024": "embed buckets holding 65..1024 points",
    "embed.occ_le_16384": "embed buckets holding 1025..16384 points",
    "embed.occ_gt_16384": "embed buckets holding > 16384 points",
    "density.points": "points entering density-engine (HDBSCAN*/"
    "OPTICS) runs",
    "density.core_dispatches": "density.core chunk dispatches issued "
    "(packing-window core-distance slabs)",
    "density.boruvka_dispatches": "density.boruvka round dispatches "
    "issued (retries included; = density.rounds when fault-free)",
    "density.rounds": "completed Borůvka MST contraction rounds "
    "(data-dependent, bounded by ceil(log2 n) + 2; labels are "
    "round-count-independent — the unique-MST total-order invariant)",
    "density.edges": "mutual-reachability MST edges banked across "
    "runs (= n - 1 per run)",
    "density.condense_dispatches": "density.condense sort/compact "
    "dispatches issued (one per run)",
    "density.oracle_fallbacks": "density runs degraded whole to the "
    "numpy host oracle after a persistent density_boruvka fault "
    "(labels intact — the PARITY.md variable-density contract)",
    "devtime.samples": "dispatches bracketed by the ready-sync "
    "device-timeline hooks (DBSCAN_DEVTIME)",
    "devtime.dispatch_s": "summed host wall of the bracketed dispatch "
    "calls (trace/lower + enqueue)",
    "devtime.sync_s": "summed residual ready-wait after the host call "
    "returned (lower bound on device work still running)",
    "devtime.device_s": "summed issue->ready windows (upper bound on "
    "device occupancy; device_busy_frac = this / train wall)",
    "profile.windows": "jax.profiler capture windows completed "
    "(DBSCAN_PROFILE_WINDOW)",
    "shapecheck.checks": "dispatch shape/footprint validations run "
    "by the graftshape runtime cross-check",
    "shapecheck.violations": "model-instantiation or HBM-containment "
    "violations the cross-check recorded",
    "faultcheck.checks": "supervised windows fingerprinted by the "
    "graftfault runtime cross-check",
    "faultcheck.violations": "mutation-containment violations the "
    "cross-check recorded (observed write outside the static model)",
    "tsan.accesses": "shared-state accesses the thread sanitizer saw",
    "tsan.acquires": "registered-lock acquisitions the sanitizer saw",
    "tsan.races": "lockset races detected (empty-intersection, "
    "multi-thread, written sites)",
    "tsan.lock_inversions": "lock-acquisition-order inversions observed",
    "slo.pages": "page-severity SLO burn alerts fired (fast AND slow "
    "window burn past DBSCAN_SLO_BURN_PAGE; each triggers an on-demand "
    "flight-recorder dump)",
    "slo.tickets": "ticket-severity SLO burn alerts fired (burn past "
    "DBSCAN_SLO_BURN_TICKET but below the page threshold)",
}

GAUGES = {
    "memory.bytes_in_use": "summed live allocator bytes at last sample",
    "memory.peak_bytes_in_use": "process high-water mark (monotone)",
    "memory.bytes_limit": "summed allocator capacity when reported",
    "pull.inflight": "pull-pipeline jobs started and not yet finished",
    "pull.queue_depth": "pull-pipeline jobs submitted and not yet "
    "executed (pending + started-ahead; a wedged engine shows a "
    "frozen nonzero depth in the flight dump)",
    "campaign.queue_depth": "campaign chunks not yet banked (pending "
    "+ leased; a stalled campaign freezes it nonzero)",
    "campaign.workers_active": "campaign worker threads currently "
    "started (0 once the fleet joined)",
    "serve.queue_depth": "micro-batches submitted to the ClusterService "
    "and not yet ingested (the backpressure figure; bounded by "
    "DBSCAN_SERVE_QUEUE)",
    "serve.epoch": "the service's last PUBLISHED snapshot epoch — "
    "queries are answered against exactly this state, never a "
    "half-merged update",
    "serve.resident_points": "skeleton core points in the published "
    "query snapshot",
    "serve.cut_id": "the sharded service's last published consistent-"
    "cut id (each shard publish folds a new epoch VECTOR; readers pin "
    "one cut, never a blend of two)",
    "serve.router.replicas_live": "query replicas currently in the "
    "router's live set (drops on eviction — the read mesh re-sharding "
    "over the survivors)",
    "serve.router.p99_ms": "rolling p99 of answered router queries at "
    "the last shed-pressure evaluation (only sampled while past the "
    "declared bound)",
    "embed.sample_frac": "sampled-edge keep probability of the last "
    "embed run (1.0 = exact path) — the declared accuracy knob the "
    "analyzer's sampled-edge fraction reads back",
    "embed.ivf_cells": "IVF coarse-quantizer cell count (post-ladder) "
    "of the last embed.quantize dispatch",
    "embed.shards": "device count of the last sharded embed run "
    "(mesh size; unsharded runs never set this)",
    "prop.mode": "resolved propagation mode of the last settled "
    "window_cc-family fixed point (1.0 = unionfind, 0.0 = iterated — "
    "DBSCAN_PROP_UNIONFIND, ops/propagation.py note_sweeps)",
    "density.eps_auto": "eps selected by the last eps='auto' "
    "k-distance knee probe (median of the per-strip knees)",
    "serve.windowed_p99_ms": "live sliding-window query p99 (the "
    "serve.query_ms log-bucketed window, obs/live.py) at the last "
    "health/shed evaluation — the figure shed decisions actually read",
    "serve.windowed_qps": "live sliding-window query rate at the last "
    "health evaluation (windowed count / elapsed window)",
    "serve.windowed_shed_frac": "live sliding-window shed fraction "
    "(windowed shed / (shed + routed)) at the last health evaluation",
}

SPANS = {
    "train": "root span over one distributed train run",
    "train.resume": "checkpoint-resume short-circuit of a train run",
    "dispatch.dense": "dense kernel group fan-out (host dispatch wall)",
    "dispatch.resident": "resident kernel group fan-out",
    "dispatch.banded": "banded phase-1 group fan-out",
    "spill.payload_upload": "spill resident payload upload",
    "spill.partition": "spill-tree build over one (sub)dataset",
    "spill.pivots": "spill-tree pivot selection pass",
    "spill.screen": "spill-tree rejection screen pass",
    "spill.membership": "spill-tree full-node membership pass",
    "spill.leader_cover": "spill-tree leader cover pass",
    "spill.child_gather": "spill-tree child row gather",
    "spill.level": "one level-synchronous tree build round (all open "
    "nodes, one fused dispatch)",
    "spill.leaf_pull": "retiring leaf/fallback region pull of one "
    "level (PullEngine-overlapped)",
    "compact.flush_chunk": "compact p1 chunk flush to device",
    "compact.pull_chunk": "compact p1 chunk pull to host",
    "cellcc.finalize": "whole cellcc finalize window (device CC + "
    "label pull, or the host-oracle merge; mode/cc_iters attached — "
    "prior overlapped chunk-pull seconds ride the pull_prior_s attr, "
    "timings['cellcc_finalize_s'] adds them to this span's wall)",
    "pull.chunk": "one pull-pipeline job (transfer + host finalize)",
    "campaign.run": "root span over one campaign (chunk-leased or "
    "frontier)",
    "campaign.lease": "one lease execution window (worker, chunk "
    "count, tier, outcome attached)",
    "campaign.finalize": "the campaign's assembly run over the "
    "fully-banked checkpoint dir",
    "checkpoint.save_premerge": "pre-merge checkpoint write",
    "checkpoint.save_p1_chunk": "p1 chunk checkpoint write",
    "checkpoint.save_serve": "serve state checkpoint write",
    "serve.update": "one ClusterService ingest step (stream update + "
    "snapshot publish; epoch attached)",
    "serve.query": "one query batch answered against the resident "
    "snapshot (epoch + point count attached)",
    "serve.job_batch": "one pad-and-stack serve.jobs dispatch window "
    "(job count + padded shape attached)",
    "serve.route": "one routed query batch end-to-end (pin cut, pick "
    "replica, dispatch, failovers included; point count attached)",
    "transfer.pull": "device->host pull (bytes in args)",
    "stream.update": "streaming micro-batch update step",
    "embed.run": "root span over one embed-engine run",
    "embed.hash": "embed SRP hash dispatch window (one matmul over "
    "the padded payload)",
    "embed.bin": "host boundary-spill binning over the primary-table "
    "projections (spill-tree fallbacks nest inside)",
    "embed.bucket": "one embed bucket neighbor dispatch window "
    "(partition id, width, W rung attached; sharded runs attach the "
    "owning shard — the per-shard busy-share section's input)",
    "embed.quantize": "embed IVF coarse-quantizer dispatch window "
    "(fp seeding + Lloyd + chord matrix; n, d, cells attached)",
    "embed.merge": "embed instance-table merge (shared finalize_merge)",
    "density.run": "root span over one density-engine run (n, metric, "
    "kind=hdbscan/optics attached)",
    "density.core_chunk": "one density.core chunk dispatch window "
    "(chunk start + width attached)",
    "density.round": "one Borůvka round window (dispatch + the thin "
    "synchronous selection pull; round index attached)",
    "density.condense": "the density.condense sort/compact dispatch "
    "window (edge count attached)",
    "density.condense_pull": "the ONE PullEngine pull riding the "
    "sorted-MST arrays back (the final-labels pull)",
    "density.auto_eps": "the eps='auto' probe window (sample size + "
    "strip count attached)",
}

EVENTS = {
    "resident_cache.hit": "resident cache hit mark (hot/cold split)",
    "resident_cache.miss": "resident cache miss mark (hot/cold split)",
    "binning.ratchet_raise": "streaming shape floor moved post-warm-up",
    "compiles.storm": "recompile-storm threshold crossed for a family",
    "fault.retry": "supervised dispatch retry scheduled",
    "fault.budget_halved": "RESOURCE_EXHAUSTED halved a dispatch budget",
    "fault.fallback": "group degraded to the CPU engine",
    "fault.fatal": "supervised dispatch exhausted retries, aborting",
    "fault.degrade_host": "caller-counted host degradation (spill tree)",
    "faults.run_delta": "per-run fault-counter delta (= stats['faults'])",
    "shapecheck.violation": "graftshape cross-check violation record "
    "(family + detail)",
    "faultcheck.violation": "graftfault cross-check violation record "
    "(site + detail)",
    "tsan.race": "thread sanitizer race record (site + thread roles)",
    "tsan.lock_inversion": "thread sanitizer lock-order inversion record",
    "pull.stall": "a pull-pipeline consumer blocked past "
    "DBSCAN_PULL_STALL_S on one job (label + queue depth attached) — "
    "the wedged-engine mark the flight recorder exists to capture",
    "campaign.steal": "unfinished chunks of a failed lease returned "
    "to the queue (lease, worker, outcome, count attached)",
    "campaign.expire": "a lease's heartbeat window lapsed — its "
    "chunks were requeued for the fleet to steal",
    "campaign.kill": "injected campaign worker kill fired (the leg "
    "died through the driver's real abort path)",
    "campaign.wedge": "injected campaign worker wedge fired (the "
    "worker parks holding its lease until it expires)",
    "campaign.degrade": "a campaign worker degraded to the CPU tier",
    "campaign.repartition": "a worker's lease size adapted to its "
    "fault rate (old/new size attached)",
    "campaign.leg": "one frontier subprocess leg ended (rc, banked "
    "chunk count, wall attached)",
    "mesh.reshard": "a chip-drop fault degraded a sharded run to a "
    "smaller mesh (old/new device counts attached) — re-sharding, "
    "not a dead campaign (ROADMAP items 1+5 composition)",
    "flightrec.dump": "flight-recorder dump written (reason + abort "
    "site attached); the ring's final instant says why the file exists",
    "serve.epoch_publish": "a completed ingest step published a new "
    "query snapshot (epoch + skeleton size attached)",
    "serve.admit_reject": "the admission controller rejected a tenant "
    "job (predicted bytes + headroom attached)",
    "serve.cut_publish": "a shard publish folded a new consistent cut "
    "(publishing shard, cut id, epoch vector attached)",
    "serve.replica.evict": "a query replica left the live set after a "
    "persistent fault (replica, survivor count, error attached)",
    "serve.router.failover": "an in-flight query re-routed its pinned "
    "cut to a surviving replica (replica + cut id attached)",
    "profile.window_open": "jax.profiler capture window opened at a "
    "tracked dispatch (DBSCAN_PROFILE_WINDOW)",
    "profile.window_close": "jax.profiler capture window closed "
    "(dispatch count + log dir attached)",
    "serve.router.shed": "a query batch was refused at the router "
    "(the SLO driving the refusal, the live windowed p99, the bound, "
    "and the priced/allowed costs attached) — the event NAMES the SLO "
    "so a shed is attributable to windowed burn, not ad-hoc stats",
    "slo.burn": "an SLO's multi-window burn rate crossed an alerting "
    "threshold (slo key, severity=page/ticket, fast/slow burns, bound "
    "attached); page severity also writes a flight-recorder dump",
    "slo.recover": "a previously-alerting SLO's burn dropped back "
    "below the ticket threshold (slo key + final burns attached)",
}

for _f in COMPILE_FAMILIES:
    COUNTERS[f"compiles.{_f}"] = f"cache misses of the {_f} dispatch"
    SPANS[f"compile.{_f}"] = f"trace+lower+compile wall of a {_f} miss"
    SPANS[f"devtime.{_f}"] = (
        f"issue->ready device-time window of one {_f} dispatch "
        "(DBSCAN_DEVTIME ready-sync bracket)"
    )
for _s in MEMORY_SITES:
    GAUGES[f"memory.at.{_s}"] = f"HBM occupancy at the last {_s} sample"
for _p in DRIVER_PHASES:
    SPANS[f"driver.{_p}"] = f"driver phase window (timings['{_p}_s'])"
for _k in SLO_KEYS:
    GAUGES[f"slo.burn.{_k}"] = (
        f"last evaluated fast-window burn rate of the {_k} SLO "
        "(bad fraction / error budget; obs/slo.py)"
    )
del _f, _s, _p, _k

KINDS = {
    "counter": COUNTERS,
    "gauge": GAUGES,
    "span": SPANS,
    "event": EVENTS,
}

# --- consumer-side groupings (imported by obs/analyze.py et al.) ------

#: analyzer report sections keyed by counter/gauge name prefix
PREFIX_MEMORY = "memory."
PREFIX_COMPILES = "compiles."
PREFIX_FAULTS = "faults."
PREFIX_DEVTIME = "devtime."
PREFIX_CAMPAIGN = "campaign."
PREFIX_SERVE = "serve."
PREFIX_EMBED = "embed."

#: the hot/cold classification marks obs/analyze.py reads back
RESIDENT_MARKS = ("resident_cache.hit", "resident_cache.miss")

#: counter-delta keys that LOOK like perf walls but are not
#: run-comparable (bench_history's suffix rule must not promote them):
#: ``backoff_s`` is fault-retry sleep, a robustness figure, not a wall.
BENCH_EXCLUDE_SUFFIXES = ("backoff_s",)


def names(kind: str) -> frozenset:
    """All declared names of ``kind`` ('counter'/'gauge'/'span'/'event')."""
    return frozenset(KINDS[kind])


def is_declared(kind: str, name: str) -> bool:
    """Exact-name membership check for one telemetry kind."""
    return name in KINDS[kind]


def prefix_declared(kind: str, prefix: str) -> bool:
    """True when some declared name of ``kind`` starts with ``prefix`` —
    the check the linter applies to dynamic emissions (f-strings /
    concatenations) whose literal head is all it can see."""
    return any(n.startswith(prefix) for n in KINDS[kind])


def self_check() -> list:
    """Structural validation of the registry itself; returns error
    strings (empty = ok). Run by ``obs.regress --check-schema`` so the
    CI gate also covers a malformed registry edit."""
    errors = []
    for kind, table in KINDS.items():
        for name, doc in table.items():
            if not isinstance(name, str) or not name:
                errors.append(f"{kind} {name!r}: names must be strings")
            elif name != name.strip() or " " in name:
                errors.append(f"{kind} {name!r}: no whitespace in names")
            if not doc or not isinstance(doc, str):
                errors.append(f"{kind} {name!r}: missing doc string")
    overlap = set(COUNTERS) & set(GAUGES)
    if overlap:
        errors.append(f"counter/gauge name collision: {sorted(overlap)}")
    for fam in COMPILE_FAMILIES:
        if "." not in fam:
            errors.append(f"compile family {fam!r}: must be dotted")
    for series in LIVE_HISTOGRAMS:
        if not series.endswith("_ms"):
            errors.append(
                f"live histogram {series!r}: windows observe "
                "milliseconds; the name must say so (_ms suffix)"
            )
    live_overlap = set(LIVE_HISTOGRAMS) & set(LIVE_RATES)
    if live_overlap:
        errors.append(
            f"live histogram/rate name collision: {sorted(live_overlap)}"
        )
    return errors
