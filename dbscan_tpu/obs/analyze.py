"""Offline trace analyzer: per-phase rollups, critical-path (self-time)
attribution, transfer-bandwidth tables, hot/cold resident-cache splits,
memory watermarks, device-timeline rollups, and the multi-shard merge —
from trace files alone.

`obs/export.py` writes two formats (Chrome-trace JSON and JSONL) and
until now nothing in the repo CONSUMED them: answering "where did the
time go" meant loading the file into Perfetto by hand, and questions
Perfetto cannot answer from our schema (self-time per span name across
the run, upload bandwidth, hot-vs-cold `train` walls) went unanswered.
This module reads either format back and prints the rollups the VERDICT
rounds kept asking for::

    python -m dbscan_tpu.obs.analyze trace.json [--top N] [--json]
    python -m dbscan_tpu.obs.analyze --merge shard.0 shard.1 \
        [-o merged.json] [--json]

Self-time model: spans are nested intervals per thread (the tracer's
thread-local stack guarantees proper nesting for live spans;
retroactive `driver.*` bridges enclose the dispatch spans emitted
inside their window). A span's self time is its wall minus the wall of
spans nested strictly inside it on the same thread — the quantity that
makes "cellcc_s is 70% of the run" actionable by splitting the pull
wait from the host algebra. A span that OVERLAPS but is not contained
(possible only for hand-built traces; the tracer never emits one)
charges its full wall to the span it starts inside.

Device timeline (PR 9): when a capture carries ``devtime.*`` telemetry
(obs/devtime.py ready-sync brackets, or the converted profiler window),
the report adds a per-family device-time rollup — device-busy vs
host-busy vs the train wall — and a MEASURED cross-check of the pull
pipeline's host-inferred ``pull.overlap_s``: the device-side overlap is
the exact interval intersection of the ``pull.chunk`` windows with the
union of ``devtime.<family>`` windows.

Multi-shard merge (``--merge``): per-process shards
(``DBSCAN_TRACE=<path>`` writes ``<path>.<i>`` under multi-process
runs) are clock-aligned on their ``epoch0`` wall anchors, given
disjoint track ids (pid = shard index + 1; every (shard, tid) pair maps
to a distinct merged tid), written as ONE Perfetto-loadable trace, and
rolled into a cross-process critical path: per-shard busy/exclusive
seconds plus the longest single-shard-busy stretches — the stretches
where that one process WAS the job's critical path.

Programmatic API: :func:`load_trace` -> :func:`analyze` -> report dict
(exact numbers, test surface) -> :func:`render` -> text;
:func:`merge_shards` for the merge leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from dbscan_tpu.obs import schema

# consumer-side names come from the declared schema — deleting one
# there breaks this module at import, not silently at report time
_RESIDENT_MARKS = schema.RESIDENT_MARKS
_TRANSFER_KEYS = (
    "transfer.h2d_bytes",
    "transfer.payload_upload_bytes",
    "transfer.payload_upload_s",
    "transfer.d2h_bytes",
    "transfer.d2h_s",
)
_DEVTIME_KEYS = (
    "devtime.samples",
    "devtime.dispatch_s",
    "devtime.sync_s",
    "devtime.device_s",
)
_PULL_CHECK_KEYS = ("pull.busy_s", "pull.overlap_s")

#: every section this module renders, mapped to the declared name
#: family it reads — the schema-coverage contract `tests/test_obs.py`
#: asserts (a section whose names vanish from obs/schema.py breaks at
#: import/test time, never silently renders empty)
SECTIONS = {
    "phases": ("span", None),  # all spans; no name filter
    "bandwidth": ("counter", _TRANSFER_KEYS),
    "resident": ("event", _RESIDENT_MARKS),
    "memory": ("gauge", schema.PREFIX_MEMORY),
    "compiles": ("counter", schema.PREFIX_COMPILES),
    "faults": ("counter", schema.PREFIX_FAULTS),
    "campaign": ("counter", schema.PREFIX_CAMPAIGN),
    "serve": ("counter", schema.PREFIX_SERVE),
    "embed": ("counter", schema.PREFIX_EMBED),
    "embed_shards": ("span", ("embed.bucket",)),
    "devtime": ("counter", _DEVTIME_KEYS),
    "pull_check": ("counter", _PULL_CHECK_KEYS),
    "requests": ("span", None),  # rid-stamped spans; no name filter
}
for _kind, _names in SECTIONS.values():
    if isinstance(_names, tuple):
        for _k in _names:
            assert schema.is_declared(_kind, _k), (_kind, _k)
    elif isinstance(_names, str):
        assert schema.prefix_declared(_kind, _names), (_kind, _names)
assert schema.is_declared("counter", "resident_cache.hits")
assert schema.is_declared("counter", "resident_cache.misses")
assert schema.is_declared("span", "transfer.pull")
assert schema.is_declared("span", "pull.chunk")
assert schema.prefix_declared("span", schema.PREFIX_DEVTIME)
# the device-timeline section is keyed per family off the
# ``devtime.<family>`` span names: EVERY declared compile family must
# have its devtime span generated, or a family added to
# COMPILE_FAMILIES without the schema generator loop would silently
# never reach the rollup (and the --merge report) — the PR-13/14
# families (serve.query/serve.jobs/embed.hash/embed.neighbors) are
# exactly what this pin was added for (tests/test_obs_analyze.py pins
# the rollup end-to-end per family)
for _f in schema.COMPILE_FAMILIES:
    assert schema.is_declared("span", f"devtime.{_f}"), _f
del _f, _k, _kind, _names


def load_trace(path: str) -> dict:
    """Read a trace file (format by content, not extension: a JSON
    object with ``traceEvents`` is a Chrome trace, anything else is
    tried as JSONL) into the normalized form :func:`analyze` consumes:
    ``{"spans", "instants", "counters", "gauges", "dropped_spans"}``
    with span times in SECONDS relative to the tracer base."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and "traceEvents" in obj:
        return _from_chrome(obj)
    return _from_jsonl(text)


def _from_chrome(obj: dict) -> dict:
    spans, instants, counters = [], [], {}
    for e in obj.get("traceEvents", []):
        ph = e.get("ph")
        if ph == "X":
            args = dict(e.get("args") or {})
            depth = args.pop("depth", 0)
            # the request id rides Chrome args (export.py); lift it
            # back to a first-class field for the --requests rollup
            rid = args.pop("rid", None)
            spans.append(
                {
                    "name": e["name"],
                    "t0": float(e["ts"]) / 1e6,
                    "dur": float(e.get("dur", 0.0)) / 1e6,
                    "depth": depth,
                    "tid": e.get("tid", 0),
                    "rid": rid,
                    "args": args,
                    "events": [],
                }
            )
        elif ph == "i":
            instants.append(
                {
                    "name": e["name"],
                    "t": float(e["ts"]) / 1e6,
                    "args": dict(e.get("args") or {}),
                }
            )
        elif ph == "C":
            counters[e["name"]] = (e.get("args") or {}).get("value", 0)
    other = obj.get("otherData") or {}
    return {
        "spans": spans,
        "instants": instants,
        "counters": counters,
        "gauges": dict(other.get("gauges") or {}),
        "dropped_spans": int(other.get("dropped_spans", 0)),
        # clock anchor + track identity for --merge (absent on pre-PR-9
        # traces: they merge with offset 0 and a synthetic pid)
        "meta": {
            k: other[k] for k in ("epoch0", "pid", "shard") if k in other
        },
    }


def _from_jsonl(text: str) -> dict:
    spans, instants, counters, gauges = [], [], {}, {}
    dropped = 0
    meta: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        r = json.loads(line)
        t = r.get("type")
        if t == "meta":
            meta = {
                k: r[k] for k in ("epoch0", "pid", "shard") if k in r
            }
        elif t == "span":
            spans.append(
                {
                    "name": r["name"],
                    "t0": float(r["t0_s"]),
                    "dur": float(r["dur_s"]),
                    "depth": r.get("depth", 0),
                    "tid": r.get("tid", 0),
                    "rid": r.get("rid"),
                    "args": r.get("args") or {},
                    "events": r.get("events") or [],
                }
            )
        elif t == "instant":
            instants.append(
                {
                    "name": r["name"],
                    "t": float(r["t_s"]),
                    "args": r.get("args") or {},
                }
            )
        elif t == "counter":
            counters[r["name"]] = r["value"]
        elif t == "gauge":
            gauges[r["name"]] = r["value"]
        elif t == "dropped_spans":
            dropped = int(r["value"])
    return {
        "spans": spans,
        "instants": instants,
        "counters": counters,
        "gauges": gauges,
        "dropped_spans": dropped,
        "meta": meta,
    }


def _annotate_self_times(spans: list) -> None:
    """Set ``self_s`` on every span: wall minus walls nested strictly
    inside it on the same thread (stack sweep over start-sorted
    intervals; ties open the longer span first so a parent sharing its
    child's start still encloses it)."""
    by_tid: dict = {}
    for sp in spans:
        by_tid.setdefault(sp["tid"], []).append(sp)
    for sps in by_tid.values():
        sps.sort(key=lambda s: (s["t0"], -s["dur"]))
        stack: list = []
        for sp in sps:
            sp["_child_s"] = 0.0
            while stack and sp["t0"] >= (
                stack[-1]["t0"] + stack[-1]["dur"] - 1e-9
            ):
                stack.pop()
            if stack:
                stack[-1]["_child_s"] += sp["dur"]
            stack.append(sp)
    for sp in spans:
        sp["self_s"] = round(
            max(0.0, sp["dur"] - sp.pop("_child_s", 0.0)), 9
        )


def _phase_rollup(spans: list) -> list:
    agg: dict = {}
    for sp in spans:
        row = agg.setdefault(
            sp["name"],
            {"name": sp["name"], "count": 0, "total_s": 0.0,
             "self_s": 0.0, "max_s": 0.0},
        )
        row["count"] += 1
        row["total_s"] += sp["dur"]
        row["self_s"] += sp["self_s"]
        row["max_s"] = max(row["max_s"], sp["dur"])
    rows = sorted(agg.values(), key=lambda r: -r["self_s"])
    for r in rows:
        r["total_s"] = round(r["total_s"], 6)
        r["self_s"] = round(r["self_s"], 6)
        r["max_s"] = round(r["max_s"], 6)
        r["mean_s"] = round(r["total_s"] / r["count"], 6)
    return rows


def _bandwidth(counters: dict, spans: list) -> list:
    """Transfer table rows: (direction, bytes, seconds or None, MB/s or
    None). h2d dispatch bytes have no measured wall of their own (the
    dispatch is async); the payload upload and the d2h pulls carry
    walls, so those rows get a rate."""

    def row(name, nbytes, secs):
        mbps = (
            round(nbytes / secs / 1e6, 3)
            if secs and nbytes
            else None
        )
        return {
            "name": name,
            "bytes": int(nbytes),
            "seconds": round(float(secs), 6) if secs else None,
            "mb_per_s": mbps,
        }

    rows = []
    h2d = counters.get("transfer.h2d_bytes", 0)
    if h2d:
        rows.append(row("h2d (dispatch inputs, async)", h2d, None))
    up_b = counters.get("transfer.payload_upload_bytes", 0)
    up_s = counters.get("transfer.payload_upload_s", 0.0)
    if up_b or up_s:
        rows.append(row("h2d payload upload", up_b, up_s))
    d2h = counters.get("transfer.d2h_bytes", 0)
    d2h_s = counters.get("transfer.d2h_s", 0.0)
    if d2h or d2h_s:
        rows.append(row("d2h pulls (incl. device wait)", d2h, d2h_s))
    pull_b = pull_s = 0.0
    for sp in spans:
        if sp["name"] == "transfer.pull":
            pull_b += sp["args"].get("bytes", 0)
            pull_s += sp["dur"]
    if pull_b:
        rows.append(row("d2h pull spans", pull_b, pull_s))
    return rows


def _resident_split(data: dict) -> dict:
    """Hot/cold `train` walls: classify each root train span by the
    resident-cache hit/miss marks inside its window (a miss anywhere in
    the window = cold — that run paid the payload upload)."""
    marks = [
        (i["t"], i["name"])
        for i in data["instants"]
        if i["name"] in _RESIDENT_MARKS
    ]
    for sp in data["spans"]:
        for ev in sp["events"]:
            name = ev["name"] if isinstance(ev, dict) else ev[0]
            t = ev["t_s"] if isinstance(ev, dict) else ev[1]
            if name in _RESIDENT_MARKS:
                marks.append((t, name))
    hot, cold = [], []
    for sp in data["spans"]:
        if sp["name"] != "train":
            continue
        t0, t1 = sp["t0"], sp["t0"] + sp["dur"]
        window = [n for t, n in marks if t0 - 1e-9 <= t <= t1 + 1e-9]
        if "resident_cache.miss" in window:
            cold.append(round(sp["dur"], 6))
        elif "resident_cache.hit" in window:
            hot.append(round(sp["dur"], 6))
    out = {
        "hits": int(data["counters"].get("resident_cache.hits", 0)),
        "misses": int(data["counters"].get("resident_cache.misses", 0)),
        "hot_walls_s": sorted(hot),
        "cold_walls_s": sorted(cold),
    }
    for key, walls in (("hot", hot), ("cold", cold)):
        if walls:
            out[f"{key}_mean_s"] = round(sum(walls) / len(walls), 6)
            out[f"{key}_min_s"] = round(min(walls), 6)
    return out


def _union_intervals(intervals: list) -> list:
    """Sorted disjoint union of (t0, t1) intervals."""
    out: list = []
    for t0, t1 in sorted(intervals):
        if t1 <= t0:
            continue
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _intersection_s(a: list, b: list) -> float:
    """Total overlap seconds between two interval lists (each is
    union-ed first) — exact arithmetic, the measured-overlap primitive."""
    a, b = _union_intervals(a), _union_intervals(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _devtime_rollup(counters: dict, spans: list) -> dict:
    """Device-timeline section: per-family issue->ready windows from
    the ``devtime.<family>`` spans plus the counter totals, and the
    device-busy share of the train wall (the figure bench stamps as
    ``device_busy_frac``). Empty dict when the capture carries no
    devtime telemetry (the DBSCAN_DEVTIME brackets were off)."""
    dev_spans = [
        sp for sp in spans
        if sp["name"].startswith(schema.PREFIX_DEVTIME)
    ]
    if not dev_spans and not counters.get("devtime.samples"):
        return {}
    fams: dict = {}
    for sp in dev_spans:
        fam = sp["name"][len(schema.PREFIX_DEVTIME):]
        row = fams.setdefault(
            fam,
            {"family": fam, "count": 0, "device_s": 0.0,
             "host_s": 0.0, "sync_s": 0.0},
        )
        row["count"] += 1
        row["device_s"] += sp["dur"]
        row["host_s"] += float(sp["args"].get("host_s", 0.0))
        row["sync_s"] += float(sp["args"].get("sync_s", 0.0))
    rows = sorted(fams.values(), key=lambda r: -r["device_s"])
    for r in rows:
        for k in ("device_s", "host_s", "sync_s"):
            r[k] = round(r[k], 6)
    train_wall = sum(
        sp["dur"] for sp in spans if sp["name"] == "train"
    )
    device_s = float(counters.get("devtime.device_s", 0.0)) or sum(
        r["device_s"] for r in rows
    )
    out = {
        "families": rows,
        "samples": int(counters.get("devtime.samples", 0)),
        "device_s": round(device_s, 6),
        "dispatch_s": round(
            float(counters.get("devtime.dispatch_s", 0.0)), 6
        ),
        "sync_s": round(float(counters.get("devtime.sync_s", 0.0)), 6),
    }
    if train_wall > 0:
        out["train_wall_s"] = round(train_wall, 6)
        out["device_busy_frac"] = round(
            min(1.0, device_s / train_wall), 4
        )
    return out


def _pull_device_check(counters: dict, spans: list) -> dict:
    """The measured check of ``pull_overlap_ratio``: the host-side
    figure claims pull/finalize seconds were hidden behind other work;
    the device side corroborates by intersecting the ``pull.chunk``
    windows with the union of the ``devtime.<family>`` device windows.
    Empty when the capture has no pull jobs or no devtime spans (there
    is nothing to check against)."""
    pulls = [
        (sp["t0"], sp["t0"] + sp["dur"])
        for sp in spans
        if sp["name"] == "pull.chunk"
    ]
    devs = [
        (sp["t0"], sp["t0"] + sp["dur"])
        for sp in spans
        if sp["name"].startswith(schema.PREFIX_DEVTIME)
    ]
    busy = float(counters.get("pull.busy_s", 0.0))
    if not pulls or not devs or busy <= 0:
        return {}
    measured = _intersection_s(pulls, devs)
    host_overlap = float(counters.get("pull.overlap_s", 0.0))
    return {
        "pull_busy_s": round(busy, 6),
        "host_overlap_s": round(host_overlap, 6),
        "host_overlap_ratio": round(min(1.0, host_overlap / busy), 4),
        "device_overlap_s": round(measured, 6),
        "device_overlap_ratio": round(min(1.0, measured / busy), 4),
    }


def analyze(data: dict, top: Optional[int] = None) -> dict:
    """Full report from normalized trace data (see module doc). Exact
    and deterministic — the test surface asserts on these numbers."""
    spans = data["spans"]
    _annotate_self_times(spans)
    phases = _phase_rollup(spans)
    counters = data["counters"]
    return {
        "n_spans": len(spans),
        "dropped_spans": data["dropped_spans"],
        "phases": phases[:top] if top else phases,
        "bandwidth": _bandwidth(counters, spans),
        "resident": _resident_split(data),
        "memory": {
            k: v for k, v in sorted(data["gauges"].items())
            if k.startswith(schema.PREFIX_MEMORY)
        },
        "compiles": {
            k: v for k, v in sorted(counters.items())
            if k.startswith(schema.PREFIX_COMPILES)
        },
        "faults": {
            k: v for k, v in sorted(counters.items())
            if k.startswith(schema.PREFIX_FAULTS)
        },
        "campaign": _campaign_rollup(counters),
        "serve": _serve_rollup(counters, spans),
        "embed": _embed_rollup(counters, data["gauges"]),
        "embed_shards": _embed_shard_rollup(spans),
        "devtime": _devtime_rollup(counters, spans),
        "pull_check": _pull_device_check(counters, spans),
        "requests": _requests_rollup(data, top=top or 10),
    }


def _requests_rollup(data: dict, top: int = 10) -> dict:
    """Per-request critical paths from the rid-stamped spans: group by
    request id (minted at the router's ingress, obs/trace.py; carried
    across the ingest queue, the pull worker, and every shard's read
    dispatch), and report the slowest-N requests by wall — request
    extent (first span start to last span end, across EVERY thread and
    shard the request touched), busy seconds (union of its span
    intervals — the request's own critical path: wall minus busy is
    time the request sat in queues), the shard set, the longest single
    span, and any fault events that named the rid. Empty ({}) on
    captures with no rid-stamped spans — pre-tracing traces render
    identically to before."""
    by_rid: dict = {}
    for sp in data["spans"]:
        rid = sp.get("rid")
        if rid:
            by_rid.setdefault(rid, []).append(sp)
    if not by_rid:
        return {}
    faults_by_rid: dict = {}
    for inst in data["instants"]:
        rid = (inst.get("args") or {}).get("rid")
        if rid and inst["name"].startswith("fault."):
            faults_by_rid[rid] = faults_by_rid.get(rid, 0) + 1
    rows = []
    for rid, sps in by_rid.items():
        t0 = min(s["t0"] for s in sps)
        t1 = max(s["t0"] + s["dur"] for s in sps)
        busy = _union_intervals(
            [(s["t0"], s["t0"] + s["dur"]) for s in sps]
        )
        shards = sorted({s["shard"] for s in sps if "shard" in s})
        top_sp = max(sps, key=lambda s: s["dur"])
        rows.append(
            {
                "rid": rid,
                "n_spans": len(sps),
                "shards": shards,
                "t0_s": round(t0, 6),
                "wall_ms": round((t1 - t0) * 1e3, 3),
                "busy_ms": round(
                    sum(b - a for a, b in busy) * 1e3, 3
                ),
                "top_span": top_sp["name"],
                "top_span_ms": round(top_sp["dur"] * 1e3, 3),
                "faults": faults_by_rid.get(rid, 0),
            }
        )
    rows.sort(key=lambda r: -r["wall_ms"])
    return {
        "n_requests": len(by_rid),
        "rows": rows[:top] if top else rows,
    }


def render_requests(report: dict) -> str:
    """The ``--requests`` table alone (also embedded in render())."""
    req = report.get("requests") or {}
    if not req:
        return "no rid-stamped spans in this capture"
    out = [
        f"-- slowest requests ({len(req['rows'])} of "
        f"{req['n_requests']}; wall = cross-shard extent, busy = "
        "union of the request's spans) --",
        f"{'rid':<18} {'spans':>5} {'shards':<8} {'wall_ms':>9} "
        f"{'busy_ms':>9} {'top span':<22} {'faults':>6}",
    ]
    for r in req["rows"]:
        shards = (
            ",".join(str(s) for s in r["shards"]) if r["shards"] else "-"
        )
        top_span = f"{r['top_span']} ({r['top_span_ms']:.1f})"
        out.append(
            f"{r['rid']:<18} {r['n_spans']:>5} {shards:<8} "
            f"{r['wall_ms']:>9.3f} {r['busy_ms']:>9.3f} "
            f"{top_span:<22} {r['faults']:>6}"
        )
    return "\n".join(out)


def _campaign_rollup(counters: dict) -> dict:
    """The campaign section: every campaign.* counter plus the derived
    ``campaign.replay_frac`` (replayed/work wall — the figure the bench
    row stamps and obs/regress gates; dbscan_tpu/campaign.py)."""
    out = {
        k: v
        for k, v in sorted(counters.items())
        if k.startswith(schema.PREFIX_CAMPAIGN)
    }
    work = out.get("campaign.work_wall_s", 0.0)
    if work > 0:
        out["campaign.replay_frac"] = round(
            min(1.0, out.get("campaign.replayed_wall_s", 0.0) / work), 4
        )
    return out


def _embed_rollup(counters: dict, gauges: dict) -> dict:
    """The embed section: every embed.* counter plus the derived
    figures the ROADMAP-item-3 capture reads — the bucket-occupancy
    histogram (the fixed-edge ``embed.occ_*`` counters), the
    spill-fallback rate (fallback points / points), the duplication
    factor (instances / points), and the sampled-edge fraction (the
    ``embed.sample_frac`` gauge; 1.0 = exact path)."""
    out = {
        k: v
        for k, v in sorted(counters.items())
        if k.startswith(schema.PREFIX_EMBED)
    }
    pts = out.get("embed.points", 0)
    if pts > 0:
        out["embed.spill_fallback_rate"] = round(
            out.get("embed.spill_fallback_points", 0) / pts, 4
        )
        out["embed.dup_factor"] = round(
            out.get("embed.instances", 0) / pts, 4
        )
    frac = gauges.get("embed.sample_frac")
    if frac is not None:
        out["embed.sampled_edge_frac"] = round(float(frac), 6)
    return out


def _embed_shard_rollup(spans: list) -> dict:
    """Per-shard busy share of a sharded embed run: EXACT interval
    union of each shard's ``embed.bucket`` dispatch windows (the
    ``_union_intervals`` primitive, so a shard's overlapping
    escalation re-runs never double-count), with shares normalized
    over the total busy seconds — near-equal shares across the mesh is
    the bucket-band balance evidence ROADMAP item 1 asks --merge to
    show. The shard id prefers the span-arg ``shard`` (the owning chip
    the engine stamps) and falls back to the merge-assigned process
    shard, so both a single-process mesh capture and an
    ``obs.analyze --merge`` of per-process traces roll up. Empty ({})
    when no bucket span carries a shard — unsharded captures render
    identically to before."""
    by_shard: dict = {}
    for sp in spans:
        if sp.get("name") != "embed.bucket":
            continue
        shard = (sp.get("args") or {}).get("shard", sp.get("shard"))
        if shard is None:
            continue
        by_shard.setdefault(int(shard), []).append(
            (sp["t0"], sp["t0"] + sp["dur"])
        )
    if not by_shard:
        return {}
    rows = []
    busies = {}
    for shard in sorted(by_shard):
        iv = _union_intervals(by_shard[shard])
        busies[shard] = sum(t1 - t0 for t0, t1 in iv)
        rows.append(
            {
                "shard": shard,
                "buckets": len(by_shard[shard]),
                "busy_s": round(busies[shard], 6),
            }
        )
    total = sum(busies.values())
    for r in rows:
        r["busy_share"] = (
            round(busies[r["shard"]] / total, 6) if total > 0 else 0.0
        )
    return {"shards": rows, "busy_s": round(total, 6)}


def _serve_rollup(counters: dict, spans: list) -> dict:
    """The serve section: every serve.* counter plus rates derived
    from the recorded ``serve.query`` spans — ``serve.qps`` (answered
    query batches over the span WINDOW, min t0 to max t1, the honest
    sustained figure under concurrent readers) and
    ``serve.query_p50_ms`` / ``serve.query_p99_ms`` (nearest-rank
    percentiles of the span walls, the same definition the bench row
    stamps)."""
    out = {
        k: v
        for k, v in sorted(counters.items())
        if k.startswith(schema.PREFIX_SERVE)
    }
    walls = sorted(
        s["dur"] for s in spans if s.get("name") == "serve.query"
    )
    if walls:
        qspans = [s for s in spans if s.get("name") == "serve.query"]
        t0 = min(s["t0"] for s in qspans)
        t1 = max(s["t0"] + s["dur"] for s in qspans)
        window = t1 - t0
        if window > 0:
            out["serve.qps"] = round(len(walls) / window, 3)

        def _pct(p: float) -> float:
            i = min(len(walls) - 1, int(p * (len(walls) - 1) + 0.5))
            return walls[i]

        out["serve.query_p50_ms"] = round(_pct(0.50) * 1e3, 3)
        out["serve.query_p99_ms"] = round(_pct(0.99) * 1e3, 3)
    shed = out.get("serve.router.shed", 0)
    routed = out.get("serve.router.routed", 0)
    if shed or routed:
        # the router's admission figure: refused / offered — the same
        # arithmetic the bench row stamps as serve_shed_frac
        out["serve.shed_frac"] = round(shed / (shed + routed), 6)
    return out


# --- multi-shard merge ------------------------------------------------


def merge_shards(paths: List[str]) -> dict:
    """Load per-process trace shards, align their clocks, and build the
    merged view: ``{"data": <normalized, analyze()-ready>,
    "trace": <one Perfetto-loadable Chrome object>,
    "merge": <cross-process critical-path section>}``.

    Clock alignment: every shard's span times are relative to its own
    tracer base; the export's ``epoch0`` anchors that base to wall
    clock, so shard i's offset is ``epoch0_i - min(epoch0)``. A shard
    without an anchor (pre-PR-9 capture, converted profiler trace)
    merges at offset 0.

    Track ids are made disjoint BY CONSTRUCTION: merged pid = shard
    index + 1 (the original pid moves into the process_name metadata
    and ``otherData.shards``), and every distinct (shard, tid) pair
    maps to a fresh small merged tid — two processes that happened to
    share an OS pid/thread id can never interleave on one track."""
    shards = []
    for i, p in enumerate(paths):
        d = load_trace(p)
        meta = d.get("meta") or {}
        shards.append(
            {
                "index": i,
                "source": os.path.basename(p),
                "data": d,
                "epoch0": meta.get("epoch0"),
                "orig_pid": meta.get("pid"),
                "shard_id": meta.get("shard"),
            }
        )
    anchors = [s["epoch0"] for s in shards if s["epoch0"] is not None]
    base = min(anchors) if anchors else 0.0
    for s in shards:
        s["offset"] = (
            float(s["epoch0"]) - base if s["epoch0"] is not None else 0.0
        )

    # merged normalized data: offset times, disjoint (shard, tid) tracks
    tid_map: dict = {}

    def _tid(i, tid):
        key = (i, tid)
        if key not in tid_map:
            tid_map[key] = len(tid_map) + 1
        return tid_map[key]

    m_spans, m_instants, m_counters = [], [], {}
    trace_events = []
    for s in shards:
        i, off, d = s["index"], s["offset"], s["data"]
        pid = i + 1
        label = f"shard{i}"
        trace_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0.0,
                "pid": pid,
                "args": {
                    "name": f"{label} ({s['source']}"
                    + (
                        f", pid {s['orig_pid']}"
                        if s["orig_pid"] is not None
                        else ""
                    )
                    + ")"
                },
            }
        )
        shard_spans = []
        for sp in d["spans"]:
            msp = dict(
                sp, t0=sp["t0"] + off, tid=_tid(i, sp["tid"]),
                shard=i,
            )
            m_spans.append(msp)
            shard_spans.append(msp)
            margs = dict(sp["args"], depth=sp["depth"], shard=i)
            if sp.get("rid"):
                margs["rid"] = sp["rid"]
            trace_events.append(
                {
                    "name": sp["name"],
                    "cat": "dbscan",
                    "ph": "X",
                    "ts": msp["t0"] * 1e6,
                    "dur": sp["dur"] * 1e6,
                    "pid": pid,
                    "tid": msp["tid"],
                    "args": margs,
                }
            )
        for inst in d["instants"]:
            m_instants.append(
                dict(inst, t=inst["t"] + off, shard=i)
            )
            trace_events.append(
                {
                    "name": inst["name"],
                    "cat": "dbscan",
                    "ph": "i",
                    "s": "p",
                    "ts": (inst["t"] + off) * 1e6,
                    "pid": pid,
                    "tid": 0,
                    "args": dict(inst["args"], shard=i),
                }
            )
        for name, value in sorted(d["counters"].items()):
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                m_counters[name] = m_counters.get(name, 0) + value
            trace_events.append(
                {
                    "name": name,
                    "cat": "dbscan",
                    "ph": "C",
                    "ts": 0.0,
                    "pid": pid,
                    "args": {"value": value},
                }
            )
        s["busy_intervals"] = _union_intervals(
            [(sp["t0"], sp["t0"] + sp["dur"]) for sp in shard_spans]
        )
    trace_events.sort(key=lambda e: (e.get("ts", 0.0), e["ph"] != "M"))
    merged_trace = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged": True,
            "epoch_base": base,
            "shards": [
                {
                    "index": s["index"],
                    "pid": s["index"] + 1,
                    "source": s["source"],
                    "orig_pid": s["orig_pid"],
                    "shard": s["shard_id"],
                    "offset_s": round(s["offset"], 9),
                }
                for s in shards
            ],
        },
    }
    data = {
        "spans": m_spans,
        "instants": m_instants,
        "counters": m_counters,
        "gauges": {},  # set-last-wins values do not merge meaningfully
        "dropped_spans": sum(s["data"]["dropped_spans"] for s in shards),
        "meta": {"merged": True},
    }
    return {
        "data": data,
        "trace": merged_trace,
        "merge": _merge_critical_path(shards),
    }


def _merge_critical_path(shards: list, top_segments: int = 10) -> dict:
    """Cross-process critical path over the merged wall: sweep the
    union of every shard's busy intervals; an instant where exactly ONE
    shard is busy means that shard IS the job's critical path there
    (everyone else idles on it — the merge-barrier shape the reference
    paper's driver-side merge forces, DBSCAN.scala:171-178). Reports
    per-shard busy/exclusive seconds, the all-busy (truly parallel) and
    idle shares, and the longest exclusive stretches with the span that
    was running."""
    if not shards:
        return {}
    bounds = [
        iv for s in shards for iv in s["busy_intervals"]
    ]
    if not bounds:
        return {}
    t_min = min(iv[0] for iv in bounds)
    t_max = max(iv[1] for iv in bounds)
    edges = sorted(
        {t for s in shards for iv in s["busy_intervals"] for t in iv}
    )
    per_shard = {
        s["index"]: {"busy_s": 0.0, "exclusive_s": 0.0} for s in shards
    }
    all_busy = idle = 0.0
    segments: list = []
    # one advancing cursor per shard: its busy intervals are sorted and
    # disjoint, and every interval endpoint is an edge, so an interval
    # that covers a segment's start covers the whole segment — the sweep
    # is O(edges * shards), not O(edges * intervals) (a fragmented
    # 200k-span shard would otherwise make the merge quadratic)
    cursors = {s["index"]: 0 for s in shards}
    for a, b in zip(edges, edges[1:]):
        if b <= a:
            continue
        busy_here = []
        for s in shards:
            ivs = s["busy_intervals"]
            i = cursors[s["index"]]
            while i < len(ivs) and ivs[i][1] <= a:
                i += 1
            cursors[s["index"]] = i
            if i < len(ivs) and ivs[i][0] <= a:
                busy_here.append(s["index"])
        dur = b - a
        for i in busy_here:
            per_shard[i]["busy_s"] += dur
        if len(busy_here) == 0:
            idle += dur
        elif len(busy_here) == len(shards):
            all_busy += dur
        if len(busy_here) == 1:
            i = busy_here[0]
            per_shard[i]["exclusive_s"] += dur
            # coalesce adjacent exclusive segments of the same shard
            if segments and segments[-1]["shard"] == i and abs(
                segments[-1]["t1_s"] - a
            ) < 1e-9:
                segments[-1]["t1_s"] = b
            else:
                segments.append({"shard": i, "t0_s": a, "t1_s": b})
    for seg in segments:
        seg["dur_s"] = round(seg["t1_s"] - seg["t0_s"], 6)
        seg["t0_s"] = round(seg["t0_s"], 6)
        seg["t1_s"] = round(seg["t1_s"], 6)
    segments.sort(key=lambda g: -g["dur_s"])
    return {
        "n_shards": len(shards),
        "wall_s": round(t_max - t_min, 6),
        "all_busy_s": round(all_busy, 6),
        "idle_s": round(idle, 6),
        "shards": [
            {
                "index": s["index"],
                "source": s["source"],
                "offset_s": round(s["offset"], 6),
                "busy_s": round(per_shard[s["index"]]["busy_s"], 6),
                "exclusive_s": round(
                    per_shard[s["index"]]["exclusive_s"], 6
                ),
            }
            for s in shards
        ],
        "serial_segments": segments[:top_segments],
    }


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1000 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1000.0
    return f"{n:.1f}GB"


def render(report: dict) -> str:
    out = []
    out.append(
        f"== trace: {report['n_spans']} spans"
        + (
            f" (oldest {report['dropped_spans']} dropped by retention)"
            if report["dropped_spans"]
            else ""
        )
    )
    out.append("")
    out.append("-- critical path (self-time attribution) --")
    out.append(
        f"{'span':<28} {'count':>6} {'self_s':>10} {'total_s':>10} "
        f"{'mean_s':>10} {'max_s':>10}"
    )
    for r in report["phases"]:
        out.append(
            f"{r['name']:<28} {r['count']:>6} {r['self_s']:>10.3f} "
            f"{r['total_s']:>10.3f} {r['mean_s']:>10.3f} "
            f"{r['max_s']:>10.3f}"
        )
    if report["bandwidth"]:
        out.append("")
        out.append("-- transfers --")
        out.append(
            f"{'direction':<32} {'bytes':>10} {'seconds':>10} "
            f"{'MB/s':>8}"
        )
        for r in report["bandwidth"]:
            secs = f"{r['seconds']:.3f}" if r["seconds"] else "-"
            rate = f"{r['mb_per_s']:.1f}" if r["mb_per_s"] else "-"
            out.append(
                f"{r['name']:<32} {_fmt_bytes(r['bytes']):>10} "
                f"{secs:>10} {rate:>8}"
            )
    res = report["resident"]
    if res["hits"] or res["misses"] or res["hot_walls_s"] or res["cold_walls_s"]:
        out.append("")
        out.append("-- resident cache (hot/cold train walls) --")
        out.append(f"hits={res['hits']} misses={res['misses']}")
        if res["hot_walls_s"]:
            out.append(
                f"hot  runs: n={len(res['hot_walls_s'])} "
                f"mean={res['hot_mean_s']:.3f}s "
                f"min={res['hot_min_s']:.3f}s"
            )
        if res["cold_walls_s"]:
            out.append(
                f"cold runs: n={len(res['cold_walls_s'])} "
                f"mean={res['cold_mean_s']:.3f}s "
                f"min={res['cold_min_s']:.3f}s"
            )
    if report["memory"]:
        out.append("")
        out.append("-- memory watermarks --")
        for k, v in report["memory"].items():
            out.append(f"{k:<36} {_fmt_bytes(v):>12}")
    if report["compiles"]:
        out.append("")
        out.append("-- compiles --")
        for k, v in report["compiles"].items():
            v = round(v, 3) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    if report["faults"]:
        out.append("")
        out.append("-- faults --")
        for k, v in report["faults"].items():
            v = round(v, 6) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    if report.get("campaign"):
        out.append("")
        out.append("-- campaign (priced replay budget) --")
        for k, v in report["campaign"].items():
            v = round(v, 6) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    if report.get("serve"):
        out.append("")
        out.append("-- serve (resident service / tenancy) --")
        for k, v in report["serve"].items():
            v = round(v, 6) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    if report.get("embed"):
        out.append("")
        out.append("-- embed (LSH binning / cosine neighbors) --")
        for k, v in report["embed"].items():
            v = round(v, 6) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    es = report.get("embed_shards") or {}
    if es:
        out.append("")
        out.append("-- embed shards (bucket-band busy share) --")
        out.append(
            f"{'shard':<8} {'buckets':>8} {'busy_s':>10} {'share':>8}"
        )
        for r in es["shards"]:
            out.append(
                f"{r['shard']:<8} {r['buckets']:>8} "
                f"{r['busy_s']:>10.3f} {r['busy_share']:>8.3f}"
            )
        out.append(f"total busy {es['busy_s']:.3f}s")
    dev = report.get("devtime") or {}
    if dev:
        out.append("")
        out.append("-- device timeline (ready-sync brackets) --")
        out.append(
            f"{'family':<24} {'count':>6} {'device_s':>10} "
            f"{'host_s':>10} {'sync_s':>10}"
        )
        for r in dev["families"]:
            out.append(
                f"{r['family']:<24} {r['count']:>6} "
                f"{r['device_s']:>10.3f} {r['host_s']:>10.3f} "
                f"{r['sync_s']:>10.3f}"
            )
        line = (
            f"device busy {dev['device_s']:.3f}s"
            f" (dispatch {dev['dispatch_s']:.3f}s"
            f" + sync {dev['sync_s']:.3f}s)"
        )
        if "device_busy_frac" in dev:
            line += (
                f" / train wall {dev['train_wall_s']:.3f}s"
                f" = device_busy_frac {dev['device_busy_frac']:.3f}"
            )
        out.append(line)
    if report.get("requests"):
        out.append("")
        out.append(render_requests(report))
    pc = report.get("pull_check") or {}
    if pc:
        out.append("")
        out.append("-- pull overlap, device-measured --")
        out.append(
            f"host-inferred: {pc['host_overlap_s']:.3f}s of "
            f"{pc['pull_busy_s']:.3f}s pull busy "
            f"(ratio {pc['host_overlap_ratio']:.3f})"
        )
        out.append(
            f"device-measured: {pc['device_overlap_s']:.3f}s of pull "
            f"windows overlapped device work "
            f"(ratio {pc['device_overlap_ratio']:.3f})"
        )
    mg = report.get("merge") or {}
    if mg:
        out.append("")
        out.append("-- cross-process critical path --")
        out.append(
            f"{mg['n_shards']} shard(s), merged wall "
            f"{mg['wall_s']:.3f}s: all-busy {mg['all_busy_s']:.3f}s, "
            f"idle {mg['idle_s']:.3f}s"
        )
        out.append(
            f"{'shard':<28} {'offset_s':>10} {'busy_s':>10} "
            f"{'exclusive_s':>12}"
        )
        for s in mg["shards"]:
            label = f"{s['index']}: {s['source']}"[:28]
            out.append(
                f"{label:<28} {s['offset_s']:>10.3f} "
                f"{s['busy_s']:>10.3f} {s['exclusive_s']:>12.3f}"
            )
        if mg["serial_segments"]:
            out.append("longest single-shard (critical-path) stretches:")
            for seg in mg["serial_segments"][:5]:
                out.append(
                    f"  shard{seg['shard']} "
                    f"[{seg['t0_s']:.3f}, {seg['t1_s']:.3f}] "
                    f"{seg['dur_s']:.3f}s"
                )
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.obs.analyze",
        description="Analyze a DBSCAN_TRACE capture (Chrome JSON or "
        "JSONL): phase rollups, self-time attribution, bandwidth, "
        "hot/cold splits, memory watermarks, device-timeline rollups; "
        "--merge aligns per-process shards into one trace + a "
        "cross-process critical path.",
    )
    p.add_argument(
        "traces", nargs="+",
        help="trace file(s) written by obs (--trace / DBSCAN_TRACE; "
        "multi-process runs write <path>.<i> shards)",
    )
    p.add_argument(
        "--merge", action="store_true",
        help="treat the inputs as per-process shards of ONE run: align "
        "their epoch0 clocks, write a single merged Perfetto trace "
        "(--out), and report the cross-process critical path",
    )
    p.add_argument(
        "-o", "--out",
        help="with --merge: path for the merged Chrome trace "
        "(default <first shard>.merged.json)",
    )
    p.add_argument(
        "--top", type=int, default=20,
        help="rows in the self-time table (default 20; 0 = all)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the full report as JSON instead of tables",
    )
    p.add_argument(
        "--requests", action="store_true",
        help="print ONLY the slowest-requests table: per-request "
        "cross-shard critical paths from the rid-stamped spans the "
        "serving path records (router ingress mints the id; ingest "
        "queue, pull worker, and shard reads carry it)",
    )
    args = p.parse_args(argv)
    if not args.merge and len(args.traces) > 1:
        p.error("multiple traces require --merge")
    try:
        if args.merge:
            merged = merge_shards(args.traces)
            out_path = args.out or args.traces[0] + ".merged.json"
            from dbscan_tpu.obs import export as export_mod

            export_mod._atomic_write(
                out_path, json.dumps(merged["trace"])
            )
            report = analyze(merged["data"], top=args.top or None)
            report["merge"] = merged["merge"]
            report["merged_trace"] = out_path
        else:
            data = load_trace(args.traces[0])
            report = analyze(data, top=args.top or None)
    except (OSError, ValueError) as e:
        print(f"analyze: cannot read input: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    elif args.requests:
        print(render_requests(report))
    else:
        if args.merge:
            print(f"merged trace written to {report['merged_trace']}")
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
