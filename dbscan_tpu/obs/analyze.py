"""Offline trace analyzer: per-phase rollups, critical-path (self-time)
attribution, transfer-bandwidth tables, hot/cold resident-cache splits,
and memory watermarks — from a PR-2 trace file alone.

`obs/export.py` writes two formats (Chrome-trace JSON and JSONL) and
until now nothing in the repo CONSUMED them: answering "where did the
time go" meant loading the file into Perfetto by hand, and questions
Perfetto cannot answer from our schema (self-time per span name across
the run, upload bandwidth, hot-vs-cold `train` walls) went unanswered.
This module reads either format back and prints the rollups the VERDICT
rounds kept asking for::

    python -m dbscan_tpu.obs.analyze trace.json [--top N] [--json]

Self-time model: spans are nested intervals per thread (the tracer's
thread-local stack guarantees proper nesting for live spans;
retroactive `driver.*` bridges enclose the dispatch spans emitted
inside their window). A span's self time is its wall minus the wall of
spans nested strictly inside it on the same thread — the quantity that
makes "cellcc_s is 70% of the run" actionable by splitting the pull
wait from the host algebra. A span that OVERLAPS but is not contained
(possible only for hand-built traces; the tracer never emits one)
charges its full wall to the span it starts inside.

Programmatic API: :func:`load_trace` -> :func:`analyze` -> report dict
(exact numbers, test surface) -> :func:`render` -> text.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from dbscan_tpu.obs import schema

# consumer-side names come from the declared schema — deleting one
# there breaks this module at import, not silently at report time
_RESIDENT_MARKS = schema.RESIDENT_MARKS
_TRANSFER_KEYS = (
    "transfer.h2d_bytes",
    "transfer.payload_upload_bytes",
    "transfer.payload_upload_s",
    "transfer.d2h_bytes",
    "transfer.d2h_s",
)
for _k in _TRANSFER_KEYS:
    assert schema.is_declared("counter", _k), _k
for _k in _RESIDENT_MARKS:
    assert schema.is_declared("event", _k), _k
assert schema.is_declared("counter", "resident_cache.hits")
assert schema.is_declared("counter", "resident_cache.misses")
assert schema.is_declared("span", "transfer.pull")
del _k


def load_trace(path: str) -> dict:
    """Read a trace file (format by content, not extension: a JSON
    object with ``traceEvents`` is a Chrome trace, anything else is
    tried as JSONL) into the normalized form :func:`analyze` consumes:
    ``{"spans", "instants", "counters", "gauges", "dropped_spans"}``
    with span times in SECONDS relative to the tracer base."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and "traceEvents" in obj:
        return _from_chrome(obj)
    return _from_jsonl(text)


def _from_chrome(obj: dict) -> dict:
    spans, instants, counters = [], [], {}
    for e in obj.get("traceEvents", []):
        ph = e.get("ph")
        if ph == "X":
            args = dict(e.get("args") or {})
            depth = args.pop("depth", 0)
            spans.append(
                {
                    "name": e["name"],
                    "t0": float(e["ts"]) / 1e6,
                    "dur": float(e.get("dur", 0.0)) / 1e6,
                    "depth": depth,
                    "tid": e.get("tid", 0),
                    "args": args,
                    "events": [],
                }
            )
        elif ph == "i":
            instants.append(
                {
                    "name": e["name"],
                    "t": float(e["ts"]) / 1e6,
                    "args": dict(e.get("args") or {}),
                }
            )
        elif ph == "C":
            counters[e["name"]] = (e.get("args") or {}).get("value", 0)
    other = obj.get("otherData") or {}
    return {
        "spans": spans,
        "instants": instants,
        "counters": counters,
        "gauges": dict(other.get("gauges") or {}),
        "dropped_spans": int(other.get("dropped_spans", 0)),
    }


def _from_jsonl(text: str) -> dict:
    spans, instants, counters, gauges = [], [], {}, {}
    dropped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        r = json.loads(line)
        t = r.get("type")
        if t == "span":
            spans.append(
                {
                    "name": r["name"],
                    "t0": float(r["t0_s"]),
                    "dur": float(r["dur_s"]),
                    "depth": r.get("depth", 0),
                    "tid": r.get("tid", 0),
                    "args": r.get("args") or {},
                    "events": r.get("events") or [],
                }
            )
        elif t == "instant":
            instants.append(
                {
                    "name": r["name"],
                    "t": float(r["t_s"]),
                    "args": r.get("args") or {},
                }
            )
        elif t == "counter":
            counters[r["name"]] = r["value"]
        elif t == "gauge":
            gauges[r["name"]] = r["value"]
        elif t == "dropped_spans":
            dropped = int(r["value"])
    return {
        "spans": spans,
        "instants": instants,
        "counters": counters,
        "gauges": gauges,
        "dropped_spans": dropped,
    }


def _annotate_self_times(spans: list) -> None:
    """Set ``self_s`` on every span: wall minus walls nested strictly
    inside it on the same thread (stack sweep over start-sorted
    intervals; ties open the longer span first so a parent sharing its
    child's start still encloses it)."""
    by_tid: dict = {}
    for sp in spans:
        by_tid.setdefault(sp["tid"], []).append(sp)
    for sps in by_tid.values():
        sps.sort(key=lambda s: (s["t0"], -s["dur"]))
        stack: list = []
        for sp in sps:
            sp["_child_s"] = 0.0
            while stack and sp["t0"] >= (
                stack[-1]["t0"] + stack[-1]["dur"] - 1e-9
            ):
                stack.pop()
            if stack:
                stack[-1]["_child_s"] += sp["dur"]
            stack.append(sp)
    for sp in spans:
        sp["self_s"] = round(
            max(0.0, sp["dur"] - sp.pop("_child_s", 0.0)), 9
        )


def _phase_rollup(spans: list) -> list:
    agg: dict = {}
    for sp in spans:
        row = agg.setdefault(
            sp["name"],
            {"name": sp["name"], "count": 0, "total_s": 0.0,
             "self_s": 0.0, "max_s": 0.0},
        )
        row["count"] += 1
        row["total_s"] += sp["dur"]
        row["self_s"] += sp["self_s"]
        row["max_s"] = max(row["max_s"], sp["dur"])
    rows = sorted(agg.values(), key=lambda r: -r["self_s"])
    for r in rows:
        r["total_s"] = round(r["total_s"], 6)
        r["self_s"] = round(r["self_s"], 6)
        r["max_s"] = round(r["max_s"], 6)
        r["mean_s"] = round(r["total_s"] / r["count"], 6)
    return rows


def _bandwidth(counters: dict, spans: list) -> list:
    """Transfer table rows: (direction, bytes, seconds or None, MB/s or
    None). h2d dispatch bytes have no measured wall of their own (the
    dispatch is async); the payload upload and the d2h pulls carry
    walls, so those rows get a rate."""

    def row(name, nbytes, secs):
        mbps = (
            round(nbytes / secs / 1e6, 3)
            if secs and nbytes
            else None
        )
        return {
            "name": name,
            "bytes": int(nbytes),
            "seconds": round(float(secs), 6) if secs else None,
            "mb_per_s": mbps,
        }

    rows = []
    h2d = counters.get("transfer.h2d_bytes", 0)
    if h2d:
        rows.append(row("h2d (dispatch inputs, async)", h2d, None))
    up_b = counters.get("transfer.payload_upload_bytes", 0)
    up_s = counters.get("transfer.payload_upload_s", 0.0)
    if up_b or up_s:
        rows.append(row("h2d payload upload", up_b, up_s))
    d2h = counters.get("transfer.d2h_bytes", 0)
    d2h_s = counters.get("transfer.d2h_s", 0.0)
    if d2h or d2h_s:
        rows.append(row("d2h pulls (incl. device wait)", d2h, d2h_s))
    pull_b = pull_s = 0.0
    for sp in spans:
        if sp["name"] == "transfer.pull":
            pull_b += sp["args"].get("bytes", 0)
            pull_s += sp["dur"]
    if pull_b:
        rows.append(row("d2h pull spans", pull_b, pull_s))
    return rows


def _resident_split(data: dict) -> dict:
    """Hot/cold `train` walls: classify each root train span by the
    resident-cache hit/miss marks inside its window (a miss anywhere in
    the window = cold — that run paid the payload upload)."""
    marks = [
        (i["t"], i["name"])
        for i in data["instants"]
        if i["name"] in _RESIDENT_MARKS
    ]
    for sp in data["spans"]:
        for ev in sp["events"]:
            name = ev["name"] if isinstance(ev, dict) else ev[0]
            t = ev["t_s"] if isinstance(ev, dict) else ev[1]
            if name in _RESIDENT_MARKS:
                marks.append((t, name))
    hot, cold = [], []
    for sp in data["spans"]:
        if sp["name"] != "train":
            continue
        t0, t1 = sp["t0"], sp["t0"] + sp["dur"]
        window = [n for t, n in marks if t0 - 1e-9 <= t <= t1 + 1e-9]
        if "resident_cache.miss" in window:
            cold.append(round(sp["dur"], 6))
        elif "resident_cache.hit" in window:
            hot.append(round(sp["dur"], 6))
    out = {
        "hits": int(data["counters"].get("resident_cache.hits", 0)),
        "misses": int(data["counters"].get("resident_cache.misses", 0)),
        "hot_walls_s": sorted(hot),
        "cold_walls_s": sorted(cold),
    }
    for key, walls in (("hot", hot), ("cold", cold)):
        if walls:
            out[f"{key}_mean_s"] = round(sum(walls) / len(walls), 6)
            out[f"{key}_min_s"] = round(min(walls), 6)
    return out


def analyze(data: dict, top: Optional[int] = None) -> dict:
    """Full report from normalized trace data (see module doc). Exact
    and deterministic — the test surface asserts on these numbers."""
    spans = data["spans"]
    _annotate_self_times(spans)
    phases = _phase_rollup(spans)
    counters = data["counters"]
    return {
        "n_spans": len(spans),
        "dropped_spans": data["dropped_spans"],
        "phases": phases[:top] if top else phases,
        "bandwidth": _bandwidth(counters, spans),
        "resident": _resident_split(data),
        "memory": {
            k: v for k, v in sorted(data["gauges"].items())
            if k.startswith(schema.PREFIX_MEMORY)
        },
        "compiles": {
            k: v for k, v in sorted(counters.items())
            if k.startswith(schema.PREFIX_COMPILES)
        },
        "faults": {
            k: v for k, v in sorted(counters.items())
            if k.startswith(schema.PREFIX_FAULTS)
        },
    }


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1000 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1000.0
    return f"{n:.1f}GB"


def render(report: dict) -> str:
    out = []
    out.append(
        f"== trace: {report['n_spans']} spans"
        + (
            f" (oldest {report['dropped_spans']} dropped by retention)"
            if report["dropped_spans"]
            else ""
        )
    )
    out.append("")
    out.append("-- critical path (self-time attribution) --")
    out.append(
        f"{'span':<28} {'count':>6} {'self_s':>10} {'total_s':>10} "
        f"{'mean_s':>10} {'max_s':>10}"
    )
    for r in report["phases"]:
        out.append(
            f"{r['name']:<28} {r['count']:>6} {r['self_s']:>10.3f} "
            f"{r['total_s']:>10.3f} {r['mean_s']:>10.3f} "
            f"{r['max_s']:>10.3f}"
        )
    if report["bandwidth"]:
        out.append("")
        out.append("-- transfers --")
        out.append(
            f"{'direction':<32} {'bytes':>10} {'seconds':>10} "
            f"{'MB/s':>8}"
        )
        for r in report["bandwidth"]:
            secs = f"{r['seconds']:.3f}" if r["seconds"] else "-"
            rate = f"{r['mb_per_s']:.1f}" if r["mb_per_s"] else "-"
            out.append(
                f"{r['name']:<32} {_fmt_bytes(r['bytes']):>10} "
                f"{secs:>10} {rate:>8}"
            )
    res = report["resident"]
    if res["hits"] or res["misses"] or res["hot_walls_s"] or res["cold_walls_s"]:
        out.append("")
        out.append("-- resident cache (hot/cold train walls) --")
        out.append(f"hits={res['hits']} misses={res['misses']}")
        if res["hot_walls_s"]:
            out.append(
                f"hot  runs: n={len(res['hot_walls_s'])} "
                f"mean={res['hot_mean_s']:.3f}s "
                f"min={res['hot_min_s']:.3f}s"
            )
        if res["cold_walls_s"]:
            out.append(
                f"cold runs: n={len(res['cold_walls_s'])} "
                f"mean={res['cold_mean_s']:.3f}s "
                f"min={res['cold_min_s']:.3f}s"
            )
    if report["memory"]:
        out.append("")
        out.append("-- memory watermarks --")
        for k, v in report["memory"].items():
            out.append(f"{k:<36} {_fmt_bytes(v):>12}")
    if report["compiles"]:
        out.append("")
        out.append("-- compiles --")
        for k, v in report["compiles"].items():
            v = round(v, 3) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    if report["faults"]:
        out.append("")
        out.append("-- faults --")
        for k, v in report["faults"].items():
            v = round(v, 6) if isinstance(v, float) else v
            out.append(f"{k:<36} {v:>12}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.obs.analyze",
        description="Analyze a DBSCAN_TRACE capture (Chrome JSON or "
        "JSONL): phase rollups, self-time attribution, bandwidth, "
        "hot/cold splits, memory watermarks.",
    )
    p.add_argument("trace", help="trace file written by obs (--trace / DBSCAN_TRACE)")
    p.add_argument(
        "--top", type=int, default=20,
        help="rows in the self-time table (default 20; 0 = all)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the full report as JSON instead of tables",
    )
    args = p.parse_args(argv)
    try:
        data = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"analyze: cannot read {args.trace}: {e}", file=sys.stderr)
        return 2
    report = analyze(data, top=args.top or None)
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
