"""Trace export: Chrome-trace (chrome://tracing / Perfetto) and JSONL.

One exporter consumes the span registry (obs/trace.py) and the metrics
registry (obs/metrics.py) and writes either format, decided by the
target path's extension (``.jsonl`` -> JSONL records, anything else ->
a Chrome-trace JSON object). Writes are atomic (tmp + rename) so a run
killed mid-flush leaves the previous trace intact — the same discipline
as the checkpoint writers (parallel/checkpoint.py).

Chrome-trace schema (the subset Perfetto's JSON importer consumes):

- complete spans: ``{"ph": "X", "name", "cat", "ts", "dur", "pid",
  "tid", "args"}`` with ``ts``/``dur`` in MICROSECONDS relative to the
  tracer's time base;
- instant events (fault retries, cache decisions): ``{"ph": "i",
  "s": "t"}`` attached to the thread that observed them;
- counters: one final ``{"ph": "C"}`` sample per counter name (the
  registry keeps totals, not a time series — the trace shows the run's
  end state, the spans show where the time went).
"""

from __future__ import annotations

import json
import os
from typing import Optional


def shard_index() -> Optional[int]:
    """This process's shard id in a multi-process (DCN) job, or None
    for single-process runs / before jax initializes. The id keys the
    per-process trace/flight-dump shards and the merged trace's track
    ids (``obs.analyze --merge``)."""
    try:
        import jax

        if jax.process_count() > 1:
            return int(jax.process_index())
    except Exception:  # noqa: BLE001 — no jax / uninitialized runtime
        pass
    return None


def shard_suffix() -> str:
    """``.<process_index>`` under multi-process runs, else ``""`` —
    the sharding rule every per-process artifact path follows
    (``DBSCAN_TRACE`` -> ``<path>.<i>``, ``DBSCAN_FLIGHTREC_PATH``
    likewise), so concurrent workers never clobber one file."""
    idx = shard_index()
    return "" if idx is None else f".{idx}"


def _jsonable(v):
    """Coerce numpy scalars/arrays and other exotica into JSON types —
    span args come straight from hot loops that pass whatever they have."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return round(v, 9)
    try:  # numpy scalars
        import numpy as np

        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return round(float(v), 9)
        if isinstance(v, np.ndarray):
            return v.tolist()
    except Exception:  # noqa: BLE001 — exporter must never raise on args
        pass
    return str(v)


def chrome_trace(tracer, metrics=None) -> dict:
    """Build the Chrome-trace object from a tracer (+ optional metrics
    registry). Events are ordered by start time — the span registry
    appends at END time (obs/trace.py), so the export layer re-sorts."""
    pid = os.getpid()
    base = tracer.t0
    shard = shard_index()
    events = []
    t_last = 0.0
    for sp in tracer.snapshot_spans():
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        ts = (sp.t0 - base) * 1e6
        t_last = max(t_last, (t1 - base) * 1e6)
        args = dict(sp.args, depth=sp.depth)
        rid = getattr(sp, "rid", None)
        if rid is not None:
            # request id rides the args (Perfetto has no first-class
            # request field); analyze --requests reads it back
            args["rid"] = rid
        events.append(
            {
                "name": sp.name,
                "cat": "dbscan",
                "ph": "X",
                "ts": ts,
                "dur": max(0.0, (t1 - sp.t0) * 1e6),
                "pid": pid,
                "tid": sp.tid,
                "args": _jsonable(args),
            }
        )
        for name, t, args in sp.events:
            events.append(
                {
                    "name": name,
                    "cat": "dbscan",
                    "ph": "i",
                    "s": "t",
                    "ts": (t - base) * 1e6,
                    "pid": pid,
                    "tid": sp.tid,
                    "args": _jsonable(args),
                }
            )
    for name, t, args in getattr(tracer, "instants", ()):
        events.append(
            {
                "name": name,
                "cat": "dbscan",
                "ph": "i",
                "s": "p",
                "ts": (t - base) * 1e6,
                "pid": pid,
                "tid": 0,
                "args": _jsonable(args),
            }
        )
    events.sort(key=lambda e: e["ts"])
    if metrics is not None:
        # gauges ride as counter samples too (not just otherData): the
        # memory watermarks must be visible in the Perfetto counter
        # track AND readable by obs/analyze.py from either format alone
        for name, value in sorted(metrics.counters().items()) + sorted(
            metrics.gauges().items()
        ):
            events.append(
                {
                    "name": name,
                    "cat": "dbscan",
                    "ph": "C",
                    "ts": t_last,
                    "pid": pid,
                    "args": {"value": _jsonable(value)},
                }
            )
    # track identity: Perfetto groups by pid, so name the process track
    # after this shard — merged multi-shard traces stay tellable apart.
    # Appended last (metadata has no timeline position of its own).
    events.append(
        {
            "name": "process_name",
            "ph": "M",
            "ts": t_last,
            "pid": pid,
            "args": {
                "name": "dbscan"
                + (f" shard {shard}" if shard is not None else f" pid {pid}")
            },
        }
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            # epoch anchor: ts are perf_counter-relative; this pins the
            # trace to wall-clock time for cross-process correlation
            # (obs.analyze --merge aligns shard clocks on it)
            "epoch0": tracer.epoch0,
            # >0 means the retention bound (DBSCAN_TRACE_MAX_SPANS)
            # dropped the oldest spans — the trace is a tail, not a whole
            "dropped_spans": getattr(tracer, "dropped_spans", 0),
            "gauges": _jsonable(metrics.gauges()) if metrics else {},
            # per-process track identity for the multi-shard merge
            "pid": pid,
            "shard": shard,
        },
    }


def jsonl_records(tracer, metrics=None):
    """Yield one flat JSON-able dict per span / instant / counter —
    the grep-able format for harnesses that don't want a trace UI.
    The leading ``meta`` record carries the clock anchor + track
    identity the Chrome format keeps in ``otherData`` (without it a
    JSONL shard could not participate in ``obs.analyze --merge``)."""
    base = tracer.t0
    yield {
        "type": "meta",
        "epoch0": tracer.epoch0,
        "pid": os.getpid(),
        "shard": shard_index(),
    }
    for sp in tracer.snapshot_spans():
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        rec = {
            "type": "span",
            "name": sp.name,
            "t0_s": round(sp.t0 - base, 9),
            "dur_s": round(max(0.0, t1 - sp.t0), 9),
            "depth": sp.depth,
            "tid": sp.tid,
            "args": _jsonable(sp.args),
            "events": [
                {
                    "name": n,
                    "t_s": round(t - base, 9),
                    "args": _jsonable(a),
                }
                for n, t, a in sp.events
            ],
        }
        rid = getattr(sp, "rid", None)
        if rid is not None:
            rec["rid"] = rid
        yield rec
    for name, t, args in getattr(tracer, "instants", ()):
        yield {
            "type": "instant",
            "name": name,
            "t_s": round(t - base, 9),
            "args": _jsonable(args),
        }
    if metrics is not None:
        for name, value in sorted(metrics.counters().items()):
            yield {"type": "counter", "name": name, "value": _jsonable(value)}
        for name, value in sorted(metrics.gauges().items()):
            yield {"type": "gauge", "name": name, "value": _jsonable(value)}
    dropped = getattr(tracer, "dropped_spans", 0)
    if dropped:
        yield {"type": "dropped_spans", "value": dropped}


def _atomic_write(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def write_chrome_trace(path: str, tracer, metrics=None) -> str:
    _atomic_write(path, json.dumps(chrome_trace(tracer, metrics)))
    return path


def write_jsonl(path: str, tracer, metrics=None) -> str:
    lines = [json.dumps(r) for r in jsonl_records(tracer, metrics)]
    _atomic_write(path, "\n".join(lines) + ("\n" if lines else ""))
    return path


def write(path: str, tracer, metrics=None) -> str:
    """Format by extension: ``.jsonl`` -> JSONL, else Chrome trace."""
    if path.endswith(".jsonl"):
        return write_jsonl(path, tracer, metrics)
    return write_chrome_trace(path, tracer, metrics)


def span_summary(tracer, top: Optional[int] = 10) -> list:
    """Aggregate finished spans by name: (name, count, total seconds),
    sorted by total wall descending — the ``--metrics-summary`` body."""
    agg: dict = {}
    for sp in tracer.snapshot_spans():
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        c, t = agg.get(sp.name, (0, 0.0))
        agg[sp.name] = (c + 1, t + max(0.0, t1 - sp.t0))
    rows = sorted(
        ((name, c, round(t, 6)) for name, (c, t) in agg.items()),
        key=lambda r: -r[2],
    )
    return rows[:top] if top else rows
