"""Device-timeline profiling: measure what the device DID, not what the
host inferred.

Every wall the obs stack reports (``pull_overlap_ratio``,
``cellcc_pull_core_s``, the ``spill.level`` spans) is host-side: a span
covers the dispatch call, and device execution hides behind jax's async
dispatch. GPU DBSCAN papers justify their decompositions with per-kernel
DEVICE time (arXiv:2103.05162 reports per-phase device timings;
arXiv:1506.02226 attributes wall to individual CUDA kernels); this
module adds the two legs that get us the same ground truth, sharing the
PR-2 trace schema:

**Sampled capture window** (``DBSCAN_PROFILE_WINDOW=<n>``): a
``jax.profiler`` trace spanning the next ``n`` tracked dispatches
(``obs/compile.tracked_call`` is the funnel), written to
``DBSCAN_PROFILE_DIR``. One window per process (a latch — profiling is
a sampling tool, not an always-on cost), opened at the first tracked
dispatch and closed after the n-th; an atexit guard stops a window the
process abandoned so no profiler session ever leaks. The profiler's own
per-device tracks (``*.trace.json[.gz]`` under the log dir, where the
jaxlib version emits them) convert into our Chrome-trace format via
:func:`convert_profile`, and the converted file merges with host-side
shards through ``obs.analyze --merge``.

**Ready-sync fallback** (``DBSCAN_DEVTIME=1`` or :func:`enable` — the
always-available leg, no profiler needed): every tracked dispatch is
bracketed with a ``block_until_ready`` delta —

- ``devtime.dispatch_s`` — host wall of the dispatch call itself
  (trace/lower + enqueue);
- ``devtime.sync_s`` — the residual wait until the dispatch's outputs
  were actually ready (a LOWER bound on device work still running when
  the host moved on);
- ``devtime.device_s`` — the full issue->ready window (an UPPER bound
  on the dispatch's device occupancy), also emitted per family as a
  ``devtime.<family>`` span so the trace carries a device-time track
  per compile family — coverage follows ``obs.schema.COMPILE_FAMILIES``
  exactly, so the PR-8 ``spill.level*`` families and the device cellcc
  finalize (``cellcc.unpack`` / ``cellcc.cc``) appear the moment their
  dispatches run; ``device_busy_frac`` therefore credits the on-device
  finalize the way it credits the sweeps.

The sync point serializes the dispatch tail, so this leg is for
instrumented runs (bench enables it around its timed reps the way it
enables the graftshape checker) and the profiler window is the
low-bias path. ``obs.analyze`` turns the counters+spans into the
device-busy/host-busy rollup and a measured cross-check of
``pull_overlap_ratio`` (do the pull windows really overlap device
work?); bench stamps ``devtime.device_s / wall`` as
``device_busy_frac``.

Disabled path: one module-global truthiness check per hook, matching
the obs/tsan/shapecheck discipline.
"""

from __future__ import annotations

import atexit
import glob
import gzip
import json
import logging
import os
import time
from typing import List, Optional

import dbscan_tpu.obs as obs
from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan

logger = logging.getLogger(__name__)

# ready-sync bracket switch: explicit enable/disable wins; ensure_env
# applies DBSCAN_DEVTIME at the pipeline entry points
_on = False
_env_applied: Optional[bool] = None

# profiler-window state (one window per process; reset() for tests)
_lock = _tsan.lock("obs.devtime")
_win = {
    "target": 0,  # dispatches the window spans (0 = off)
    "seen": 0,  # dispatches completed since the window opened
    "active": False,
    "done": False,
    "dir": None,
}


def enabled() -> bool:
    return _on


def enable() -> None:
    """Turn the ready-sync brackets on (idempotent)."""
    global _on
    _on = True


def disable() -> None:
    global _on
    _on = False


def ensure_env() -> None:
    """Apply ``DBSCAN_DEVTIME`` / ``DBSCAN_PROFILE_WINDOW`` — called at
    the pipeline entry points alongside ``obs.ensure_env``. The env
    value is latched per distinct value, so steady-state updates pay
    two env reads, not state churn; an explicit :func:`enable` is never
    un-done by the env (same precedence as ``obs.enable`` vs
    ``DBSCAN_TRACE``)."""
    global _on, _env_applied
    env_on = bool(config.env("DBSCAN_DEVTIME"))
    with _lock:
        _tsan.access("obs.devtime")
        # latch update under the module lock: ensure_env runs at EVERY
        # pipeline entry, which now includes the serve ingest thread
        # (dbscan_tpu/serve) concurrently with main-thread trains — an
        # unlocked check-then-write here could lose a toggle
        if env_on != _env_applied:
            _env_applied = env_on
            if env_on:
                _on = True
        if not _win["done"] and not _win["active"]:
            _win["target"] = int(config.env("DBSCAN_PROFILE_WINDOW"))


def reset() -> None:
    """Tests: drop the window latch and the bracket switch (a leaked
    live profiler session is stopped first)."""
    global _on, _env_applied
    _stop_window(at_exit=False)
    with _lock:
        _tsan.access("obs.devtime")
        _win.update(target=0, seen=0, active=False, done=False, dir=None)
    _on = False
    _env_applied = None


def window_state() -> dict:
    with _lock:
        _tsan.access("obs.devtime", write=False)
        return dict(_win)


# --- profiler capture window ------------------------------------------


def _profile_dir() -> str:
    return str(config.env("DBSCAN_PROFILE_DIR"))


def _start_window() -> None:
    d = _profile_dir()
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        logger.warning("profiler window failed to open (%s): %s", d, e)
        with _lock:
            _tsan.access("obs.devtime")
            _win["done"] = True
            _win["active"] = False
        return
    with _lock:
        _tsan.access("obs.devtime")
        _win["active"] = True
        _win["dir"] = d
        _win["seen"] = 0
    obs.event("profile.window_open", dir=d, dispatches=_win["target"])
    logger.info(
        "profiler window open: %d dispatch(es) -> %s", _win["target"], d
    )


def _stop_window(at_exit: bool = False) -> None:
    with _lock:
        _tsan.access("obs.devtime")
        if not _win["active"]:
            return
        _win["active"] = False
        _win["done"] = True
        d, seen = _win["dir"], _win["seen"]
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001 — closing must never raise
        logger.warning("profiler window failed to close: %s", e)
        return
    obs.event(
        "profile.window_close",
        dir=d,
        dispatches=int(seen),
        at_exit=bool(at_exit),
    )
    obs.count("profile.windows")
    logger.info(
        "profiler window closed after %d dispatch(es): %s", seen, d
    )


# a window the process abandons mid-capture (crash between dispatches,
# a run shorter than the window) must still close: a leaked session
# breaks every later start_trace in the process
atexit.register(_stop_window, at_exit=True)


def dispatch_begin(family: str) -> None:
    """Pre-dispatch hook from ``tracked_call``: opens the profiler
    window at the first tracked dispatch after ``DBSCAN_PROFILE_WINDOW``
    was set. One dict read on the (default) no-window path."""
    if _win["done"] or _win["active"]:
        return
    if _win["target"] <= 0:
        return
    _start_window()


def dispatch_end(family: str, out, t0: float, t1: float) -> None:
    """Post-dispatch hook from ``tracked_call``: counts the dispatch
    against an open profiler window and, when the ready-sync brackets
    are enabled, blocks on ``out`` and emits the devtime telemetry."""
    if _win["active"]:
        with _lock:
            _tsan.access("obs.devtime")
            _win["seen"] += 1
            close = _win["seen"] >= _win["target"]
        if close:
            _stop_window()
    if not _on:
        return
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — a bad handle must not kill the run
        pass
    t2 = time.perf_counter()
    obs.count("devtime.samples")
    obs.count("devtime.dispatch_s", t1 - t0)
    obs.count("devtime.sync_s", t2 - t1)
    obs.count("devtime.device_s", t2 - t0)
    obs.add_span(
        f"devtime.{family}",
        t0,
        t2,
        family=family,
        host_s=round(t1 - t0, 9),
        sync_s=round(t2 - t1, 9),
    )


# --- profiler-output conversion ---------------------------------------


def profile_trace_files(logdir: str) -> List[str]:
    """The profiler-emitted Chrome traces under ``logdir`` (the
    TensorBoard layout: ``plugins/profile/<run>/<host>.trace.json.gz``;
    some jaxlib versions emit only ``*.xplane.pb``, which has no stdlib
    decoder — those runs still carry the ready-sync fallback)."""
    out: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        out.extend(glob.glob(os.path.join(logdir, pat), recursive=True))
    return sorted(set(out))


def convert_profile(logdir: str, out_path: Optional[str] = None):
    """Convert the profiler's own trace files into ONE trace in our
    Chrome format (per-device tracks preserved), suitable for
    ``obs.analyze`` / ``--merge`` next to the host-side shards. Returns
    the written path (or the trace dict when ``out_path`` is None);
    None when the log dir holds no decodable trace."""
    events: list = []
    files = profile_trace_files(logdir)
    for path in files:
        try:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt") as f:
                obj = json.load(f)
        except Exception as e:  # noqa: BLE001 — skip undecodable files
            logger.warning("cannot decode profiler trace %s: %s", path, e)
            continue
        events.extend(obj.get("traceEvents") or [])
    if not events:
        return None
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "jax.profiler",
            "profile_dir": logdir,
            "files": [os.path.basename(p) for p in files],
        },
    }
    if out_path is None:
        return trace
    from dbscan_tpu.obs import export as export_mod

    export_mod._atomic_write(out_path, json.dumps(trace))
    return out_path
