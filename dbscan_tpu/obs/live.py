"""Live sliding-window telemetry: the while-it's-running aggregation
plane for the serving fleet.

The post-mortem stack (obs/trace -> obs/export -> obs/analyze) answers
"where did the time go" AFTER a run; a serving fleet needs the same
answers WHILE it runs — a load-shed decision cannot wait for a trace
flush. This module keeps mergeable log-bucketed sliding-window
histograms and windowed counter rates, surfaced three ways:

- extended ``service.health()`` / ``router.health()`` dicts;
- a Prometheus-style text exposition file (``DBSCAN_OBS_EXPO=path``,
  atomic tmp+rename rewrite, throttled by ``DBSCAN_OBS_EXPO_PERIOD_S``);
- ``python -m dbscan_tpu.obs.live`` — a top-style console polling the
  exposition file (``--once`` for scripts/tests).

Design constraints (pinned by tests/test_obs_live.py):

- STRICT NO-OP WHEN DISABLED: ``DBSCAN_OBS_LIVE=0`` drops the state;
  every hook is then one module-global truthiness check (<1% overhead
  on the serving hot path, pinned) — the flight.py latch pattern.
- BOUNDED MEMORY, declared: each histogram series is exactly
  ``n_slices`` slices x :data:`NBUCKETS` int64 buckets (plus one
  count/sum per slice); each rate series is ``n_slices`` float slices.
  Series names are DECLARED in obs/schema.py (:data:`LIVE_HISTOGRAMS`
  / :data:`LIVE_RATES`) and undeclared names are rejected, so the
  total footprint is a compile-time constant of the schema —
  ``bytes_bound()`` reports it.
- MERGEABLE: every histogram shares one fixed bucket geometry
  (growth :data:`GROWTH` per bucket), so windows merge by plain
  bucket-count addition — across slices here, across shards by any
  downstream scraper of the exposition files.
- LOCK-CHEAP + TSAN-CERTIFIED: one registered lock guards the whole
  state; the critical section of an observe is a few int adds (no
  allocation after the first touch of a series). The DBSCAN_TSAN=1
  serving drill runs with these aggregators hot.
- QUANTILE ERROR DECLARED: a reported quantile is the geometric
  midpoint of its bucket, so its relative error is bounded by
  ``sqrt(GROWTH) - 1`` (~9.1% at the fixed 2**(1/4) growth) — the
  figure PARITY.md's SLO contract declares and the live-vs-offline
  agreement test budgets.

Timekeeping: slices are stamped with their absolute slice epoch
``int(now / slice_s)`` and zeroed lazily when an observe or a read
touches a slice whose epoch moved on — expiry costs no timer thread
and no per-observation timestamps.
"""

from __future__ import annotations

import argparse
import math
import os
import sys
import time
from typing import Optional

from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import schema

# --- fixed histogram geometry (shared by every series: mergeable) -----

#: per-bucket growth factor; quantile relative error <= sqrt(GROWTH)-1
GROWTH = 2.0 ** 0.25
_LOG_G = math.log(GROWTH)
#: upper edge of bucket 0 in milliseconds (1 microsecond)
LO_MS = 1e-3
#: buckets per slice; covers LO_MS * GROWTH**(NBUCKETS-1) ~ 3.7e6 ms
#: (~1 hour) before clamping to the top bucket
NBUCKETS = 128

#: declared relative quantile error bound of the geometry (PARITY.md
#: "SLO contract"): a bucket spans [edge, edge*GROWTH) and we report
#: its geometric midpoint edge*sqrt(GROWTH).
QUANTILE_REL_ERROR = math.sqrt(GROWTH) - 1.0


def bucket_of(value_ms: float) -> int:
    """Bucket index of a millisecond observation (clamped to range)."""
    if value_ms <= LO_MS:
        return 0
    i = int(math.log(value_ms / LO_MS) / _LOG_G) + 1
    return i if i < NBUCKETS else NBUCKETS - 1


def bucket_mid_ms(i: int) -> float:
    """Geometric midpoint of bucket ``i`` — the reported quantile
    value (relative error <= :data:`QUANTILE_REL_ERROR`)."""
    if i <= 0:
        return LO_MS / 2.0
    return LO_MS * GROWTH ** (i - 1) * math.sqrt(GROWTH)


class _HistWindow:
    """One histogram series: a ring of epoch-stamped slices of bucket
    counts. All access under the LiveState lock."""

    __slots__ = ("epochs", "buckets", "counts", "sums", "t_created")

    def __init__(self, n_slices: int, now: float):
        self.epochs = [-1] * n_slices
        self.buckets = [None] * n_slices  # lazily-allocated count lists
        self.counts = [0] * n_slices
        self.sums = [0.0] * n_slices
        self.t_created = now

    def _slot(self, epoch: int) -> int:
        n = len(self.epochs)
        i = epoch % n
        if self.epochs[i] != epoch:
            self.epochs[i] = epoch
            b = self.buckets[i]
            if b is None:
                self.buckets[i] = [0] * NBUCKETS
            else:
                for j in range(NBUCKETS):
                    b[j] = 0
            self.counts[i] = 0
            self.sums[i] = 0.0
        return i

    def observe(self, value_ms: float, epoch: int) -> None:
        i = self._slot(epoch)
        self.buckets[i][bucket_of(value_ms)] += 1
        self.counts[i] += 1
        self.sums[i] += value_ms

    def _live_slots(self, epoch: int) -> list:
        """Slot indices whose epoch is within the window ending at
        ``epoch`` (stale slices excluded without zeroing them)."""
        lo = epoch - len(self.epochs) + 1
        return [
            i
            for i, e in enumerate(self.epochs)
            if lo <= e <= epoch and self.buckets[i] is not None
        ]

    def merged(self, epoch: int):
        """(total_count, total_sum, merged bucket counts) over the
        live window — plain bucket addition, the mergeability the
        fixed geometry buys."""
        total = 0
        s = 0.0
        merged = [0] * NBUCKETS
        for i in self._live_slots(epoch):
            total += self.counts[i]
            s += self.sums[i]
            b = self.buckets[i]
            for j in range(NBUCKETS):
                merged[j] += b[j]
        return total, s, merged

    def quantile(self, q: float, epoch: int) -> Optional[float]:
        total, _, merged = self.merged(epoch)
        if total == 0:
            return None
        rank = min(total - 1, int(q * total))
        seen = 0
        for j in range(NBUCKETS):
            seen += merged[j]
            if seen > rank:
                return bucket_mid_ms(j)
        return bucket_mid_ms(NBUCKETS - 1)

    def frac_above(self, bound_ms: float, epoch: int) -> Optional[float]:
        """Fraction of windowed observations in buckets strictly above
        ``bound_ms``'s bucket — the SLO engine's bad-event fraction
        (quantized to the declared bucket error, like every readback)."""
        total, _, merged = self.merged(epoch)
        if total == 0:
            return None
        jb = bucket_of(bound_ms)
        above = sum(merged[jb + 1:])
        return above / total


class _RateWindow:
    """One windowed counter series: a ring of epoch-stamped slice sums."""

    __slots__ = ("epochs", "sums", "t_created")

    def __init__(self, n_slices: int, now: float):
        self.epochs = [-1] * n_slices
        self.sums = [0.0] * n_slices
        self.t_created = now

    def bump(self, value: float, epoch: int) -> None:
        n = len(self.epochs)
        i = epoch % n
        if self.epochs[i] != epoch:
            self.epochs[i] = epoch
            self.sums[i] = 0.0
        self.sums[i] += value

    def total(self, epoch: int) -> float:
        lo = epoch - len(self.epochs) + 1
        return sum(
            s for e, s in zip(self.epochs, self.sums) if lo <= e <= epoch
        )


class LiveState:
    """The process-global live-aggregation state: every declared
    series' window, one lock, the expo write throttle."""

    __slots__ = (
        "window_s",
        "n_slices",
        "slice_s",
        "t0",
        "_hists",
        "_rates",
        "_lock",
        "_expo_t_last",
        "_last_seen",
    )

    def __init__(self, window_s: float, n_slices: int):
        self.window_s = max(1e-3, float(window_s))
        self.n_slices = max(2, int(n_slices))
        self.slice_s = self.window_s / self.n_slices
        self.t0 = time.monotonic()
        self._hists = {}
        self._rates = {}
        self._lock = _tsan.lock("obs.live")
        self._expo_t_last = 0.0
        # last wall-clock an event landed on each rate series — the
        # staleness SLO's freshness source (0.0 = never)
        self._last_seen = {}

    # -- recording ------------------------------------------------------

    def _epoch(self, now: float) -> int:
        return int(now / self.slice_s)

    def observe(self, name: str, value_ms: float) -> None:
        if name not in schema.LIVE_HISTOGRAMS:
            raise ValueError(
                f"live histogram {name!r} not declared in "
                "obs.schema.LIVE_HISTOGRAMS"
            )
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live")
            w = self._hists.get(name)
            if w is None:
                w = self._hists[name] = _HistWindow(self.n_slices, now)
            w.observe(float(value_ms), self._epoch(now))

    def bump(self, name: str, value: float = 1.0) -> None:
        if name not in schema.LIVE_RATES:
            raise ValueError(
                f"live rate {name!r} not declared in "
                "obs.schema.LIVE_RATES"
            )
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live")
            w = self._rates.get(name)
            if w is None:
                w = self._rates[name] = _RateWindow(self.n_slices, now)
            w.bump(float(value), self._epoch(now))
            self._last_seen[name] = now

    # -- readback -------------------------------------------------------

    def _elapsed(self, t_created: float, now: float) -> float:
        """Effective window denominator: the full window once the
        series has lived that long, the series' age before (so early
        rates are not diluted by empty future slices)."""
        return max(self.slice_s, min(self.window_s, now - t_created))

    def quantile(self, name: str, q: float) -> Optional[float]:
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live", write=False)
            w = self._hists.get(name)
            if w is None:
                return None
            return w.quantile(q, self._epoch(now))

    def frac_above(self, name: str, bound_ms: float) -> Optional[float]:
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live", write=False)
            w = self._hists.get(name)
            if w is None:
                return None
            return w.frac_above(bound_ms, self._epoch(now))

    def window_count(self, name: str) -> int:
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live", write=False)
            w = self._hists.get(name)
            if w is None:
                return 0
            total, _, _ = w.merged(self._epoch(now))
            return total

    def rate(self, name: str) -> float:
        """Windowed events/second of a rate series (0.0 when unseen)."""
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live", write=False)
            w = self._rates.get(name)
            if w is None:
                return 0.0
            return w.total(self._epoch(now)) / self._elapsed(
                w.t_created, now
            )

    def window_total(self, name: str) -> float:
        now = time.monotonic()
        with self._lock:
            _tsan.access("obs.live", write=False)
            w = self._rates.get(name)
            if w is None:
                return 0.0
            return w.total(self._epoch(now))

    def seconds_since(self, name: str) -> Optional[float]:
        """Seconds since the last bump of ``name`` (None = never) —
        the staleness SLO's freshness read."""
        with self._lock:
            _tsan.access("obs.live", write=False)
            t = self._last_seen.get(name)
        if t is None:
            return None
        return max(0.0, time.monotonic() - t)

    def snapshot(self) -> dict:
        """One coherent read of every live series — the body of the
        exposition file and the console."""
        now = time.monotonic()
        epoch = self._epoch(now)
        out = {
            "window_s": self.window_s,
            "slices": self.n_slices,
            "hists": {},
            "rates": {},
        }
        with self._lock:
            _tsan.access("obs.live", write=False)
            for name, w in sorted(self._hists.items()):
                total, s, merged = w.merged(epoch)
                ent = {"count": total}
                ent["rate"] = total / self._elapsed(w.t_created, now)
                if total:
                    ent["mean_ms"] = s / total
                    for q, key in (
                        (0.5, "p50_ms"),
                        (0.9, "p90_ms"),
                        (0.99, "p99_ms"),
                    ):
                        rank = min(total - 1, int(q * total))
                        seen = 0
                        for j in range(NBUCKETS):
                            seen += merged[j]
                            if seen > rank:
                                ent[key] = bucket_mid_ms(j)
                                break
                out["hists"][name] = ent
            for name, w in sorted(self._rates.items()):
                total = w.total(epoch)
                out["rates"][name] = {
                    "total": total,
                    "rate": total / self._elapsed(w.t_created, now),
                }
        return out

    def bytes_bound(self) -> int:
        """Declared upper bound on this state's series storage: every
        schema-declared series at full allocation (8 bytes per bucket
        count / slice sum — CPython ints and floats are boxed, so this
        is the payload figure the docstring contract declares, not an
        allocator measurement)."""
        per_hist = self.n_slices * (NBUCKETS + 2) * 8
        per_rate = self.n_slices * 2 * 8
        return (
            len(schema.LIVE_HISTOGRAMS) * per_hist
            + len(schema.LIVE_RATES) * per_rate
        )


# --- process-global latch (the flight.py pattern) ---------------------

_state: Optional[LiveState] = None
_configured = None  # (on, window_s, n_slices) last applied
_lock = _tsan.lock("obs.live_state")


def ensure_env() -> None:
    """(Re)apply the env knobs; latches, so steady-state calls are one
    tuple compare. Called from obs.ensure_env() at the pipeline entry
    points and from the serving constructors."""
    global _state, _configured
    on = bool(config.env("DBSCAN_OBS_LIVE"))
    window_s = float(config.env("DBSCAN_OBS_WINDOW_S"))
    n_slices = int(config.env("DBSCAN_OBS_SLICES"))
    conf = (on, window_s, n_slices)
    if conf == _configured:
        return
    with _lock:
        _tsan.access("obs.live_state")
        if conf == _configured:
            return
        _state = LiveState(window_s, n_slices) if on else None
        _configured = conf


def reset() -> None:
    """Drop the state and the latch (tests + bench rung isolation); the
    next ensure_env() rebuilds fresh windows."""
    global _state, _configured
    with _lock:
        _tsan.access("obs.live_state")
        _state = None
        _configured = None


def state() -> Optional[LiveState]:
    return _state


def active() -> bool:
    return _state is not None


# --- hot hooks (strict no-op when disabled) ---------------------------


def observe(name: str, value_ms: float) -> None:
    """Record one ms observation into a declared histogram window;
    a single module-global check when the live plane is off."""
    st = _state
    if st is None:
        return
    st.observe(name, value_ms)


def bump(name: str, value: float = 1.0) -> None:
    """Add to a declared windowed rate series; no-op when off."""
    st = _state
    if st is None:
        return
    st.bump(name, value)


def quantile(name: str, q: float) -> Optional[float]:
    """Windowed quantile of a histogram series (None when the plane is
    off or the window is empty) — the read shed decisions take."""
    st = _state
    if st is None:
        return None
    return st.quantile(name, q)


def frac_above(name: str, bound_ms: float) -> Optional[float]:
    st = _state
    if st is None:
        return None
    return st.frac_above(name, bound_ms)


def rate(name: str) -> float:
    st = _state
    if st is None:
        return 0.0
    return st.rate(name)


def window_total(name: str) -> float:
    st = _state
    if st is None:
        return 0.0
    return st.window_total(name)


def seconds_since(name: str) -> Optional[float]:
    st = _state
    if st is None:
        return None
    return st.seconds_since(name)


def snapshot() -> Optional[dict]:
    st = _state
    if st is None:
        return None
    return st.snapshot()


# --- exposition file --------------------------------------------------


def expo_path() -> Optional[str]:
    """The configured exposition path (shard-suffixed for multi-
    process runs, like every artifact path), or None."""
    path = config.env("DBSCAN_OBS_EXPO")
    if not path:
        return None
    from dbscan_tpu.obs import export as export_mod

    return str(path) + export_mod.shard_suffix()


def render_expo(snap: dict) -> str:
    """Prometheus-style text exposition of a snapshot: one metric
    family per live statistic, series names as the ``name`` label."""
    lines = [
        "# HELP dbscan_live_window_seconds sliding-window width",
        "# TYPE dbscan_live_window_seconds gauge",
        f"dbscan_live_window_seconds {snap['window_s']:g}",
    ]
    stats = (
        ("count", "windowed observation count", "%d"),
        ("rate", "windowed events per second", "%g"),
        ("mean_ms", "windowed mean milliseconds", "%g"),
        ("p50_ms", "windowed p50 milliseconds", "%g"),
        ("p90_ms", "windowed p90 milliseconds", "%g"),
        ("p99_ms", "windowed p99 milliseconds", "%g"),
    )
    for key, help_, fmt in stats:
        fam = f"dbscan_live_{key}"
        rows = []
        for name, ent in snap["hists"].items():
            if key in ent:
                rows.append((name, ent[key]))
        if key in ("count", "rate"):
            for name, ent in snap["rates"].items():
                rows.append((name, ent["total" if key == "count" else key]))
        if not rows:
            continue
        lines.append(f"# HELP {fam} {help_}")
        lines.append(f"# TYPE {fam} gauge")
        for name, v in sorted(rows):
            lines.append(f'{fam}{{name="{name}"}} ' + fmt % v)
    return "\n".join(lines) + "\n"


def parse_expo(text: str) -> dict:
    """Inverse of :func:`render_expo` (the console's reader): returns
    ``{"window_s": ..., "series": {name: {stat: value}}}``."""
    out = {"window_s": None, "series": {}}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        if head == "dbscan_live_window_seconds":
            out["window_s"] = float(val)
            continue
        if not head.startswith("dbscan_live_") or '{name="' not in head:
            continue
        fam, _, label = head.partition("{")
        stat = fam[len("dbscan_live_"):]
        name = label[len('name="'):].rstrip('"}')
        out["series"].setdefault(name, {})[stat] = float(val)
    return out


def write_expo(path: Optional[str] = None) -> Optional[str]:
    """Atomically rewrite the exposition file from the current
    windows; returns the path written (None when the plane is off or
    no path is configured)."""
    st = _state
    if st is None:
        return None
    path = path or expo_path()
    if not path:
        return None
    from dbscan_tpu.obs import export as export_mod

    export_mod._atomic_write(path, render_expo(st.snapshot()))
    return path


def maybe_write_expo() -> Optional[str]:
    """Throttled :func:`write_expo` for hot health/record paths: at
    most one rewrite per DBSCAN_OBS_EXPO_PERIOD_S."""
    st = _state
    if st is None:
        return None
    path = expo_path()
    if not path:
        return None
    period = float(config.env("DBSCAN_OBS_EXPO_PERIOD_S"))
    now = time.monotonic()
    with st._lock:
        _tsan.access("obs.live")
        if now - st._expo_t_last < period:
            return None
        st._expo_t_last = now
    return write_expo(path)


# --- the top-style console --------------------------------------------


def render_console(parsed: dict, source: str) -> str:
    """One console frame from a parsed exposition snapshot."""
    lines = [
        f"dbscan live — {source}  "
        f"(window {parsed['window_s'] or 0:g}s)",
        "",
        f"{'series':<28}{'count':>9}{'rate/s':>10}"
        f"{'p50 ms':>10}{'p90 ms':>10}{'p99 ms':>10}",
    ]
    for name in sorted(parsed["series"]):
        ent = parsed["series"][name]
        def col(key, fmt="%.3g"):
            return (fmt % ent[key]) if key in ent else "-"
        lines.append(
            f"{name:<28}{col('count', '%.0f'):>9}{col('rate'):>10}"
            f"{col('p50_ms'):>10}{col('p90_ms'):>10}{col('p99_ms'):>10}"
        )
    if not parsed["series"]:
        lines.append("(no live series yet)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.obs.live",
        description="Top-style console over the live-telemetry "
        "exposition file (DBSCAN_OBS_EXPO).",
    )
    p.add_argument(
        "path",
        nargs="?",
        help="exposition file to poll (default: $DBSCAN_OBS_EXPO, or "
        "this process's own live windows when it has any)",
    )
    p.add_argument(
        "--interval", type=float, default=2.0,
        help="poll period in seconds (default 2)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (scripts/tests)",
    )
    args = p.parse_args(argv)

    path = args.path or expo_path()
    if not path:
        print(
            "obs.live: no exposition file (set DBSCAN_OBS_EXPO=path "
            "on the serving process, or pass the path)",
            file=sys.stderr,
        )
        return 2

    while True:
        try:
            with open(path, "r", encoding="utf-8") as f:
                parsed = parse_expo(f.read())
        except OSError as e:
            print(f"obs.live: cannot read {path}: {e}", file=sys.stderr)
            if args.once:
                return 2
            time.sleep(args.interval)
            continue
        frame = render_console(parsed, os.path.basename(path))
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame: a plain-terminal top
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
