"""Always-on flight recorder: a bounded in-memory ring of the last
spans/events/counter state, dumped as JSON exactly when a run dies.

Every number the obs stack reports today exists only while somebody
remembered to enable tracing — but the runs that NEED a postmortem
(a preempted TPU worker mid-campaign, a wedged pull engine, a retries-
exhausted abort) are precisely the ones nobody instrumented in advance.
The reference's answer was driver-side println taps (DBSCAN.scala:139,
202); ours is the black-box pattern every serving system carries: an
always-on (``DBSCAN_FLIGHTREC``, default ON), bounded, per-thread-
tracked ring of the most recent telemetry, flushed atomically to
``DBSCAN_FLIGHTREC_PATH`` when it matters:

- **fatal fault** — ``faults.supervised`` dumps right where it raises
  :class:`~dbscan_tpu.faults.FatalDeviceFault`, and the driver's abort
  guard dumps next to ``checkpoint.note_abort`` (so async pull faults
  that never pass through the supervised site are covered too); the
  dump carries the abort site/ordinal and the last spans leading up
  to it;
- **SIGTERM** — the preemption/teardown signal a streaming service
  receives: dump, then chain to the previous disposition so the
  process still dies;
- **SIGUSR1** — dump and keep running (poke a live, wedged process);
- **on demand** — :func:`dump` from any harness or debugger.

Mechanics: the ring reuses the PR-2 span machinery — a private
:class:`~dbscan_tpu.obs.trace.Tracer` (span cap = 2x the configured
ring, so after the tracer's drop-oldest-half trim the TAIL always
holds >= ``DBSCAN_FLIGHTREC_EVENTS`` spans) plus a private
:class:`~dbscan_tpu.obs.metrics.MetricsRegistry`. The ``dbscan_tpu.obs``
module-level hooks route here ONLY while full observability is
disabled — an obs-enabled run records once, into the live registries,
and :func:`dump` then reads ITS tail instead. Spans carry their
thread id, so the dump is a per-thread timeline (the pull-engine
worker's wedged ``pull.chunk`` is distinguishable from the main
thread's dispatch stall).

Overhead contract (pinned by ``tests/test_flight.py``): with the
recorder ON and observability OFF — the default production state —
a hook costs one extra module-global truthiness check plus a bounded
ring append; the dense bench shape stays within 1% of a build with
the recorder disabled. ``DBSCAN_FLIGHTREC=0`` restores the PR-2
strict no-op path bit-for-bit.

Multi-process runs shard the dump path exactly like ``DBSCAN_TRACE``
(``<path>.<process_index>``, via :func:`obs.export.shard_suffix`), so
every worker of a ROADMAP-item-1 job leaves its own postmortem.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Optional

from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import export as export_mod
from dbscan_tpu.obs.metrics import MetricsRegistry
from dbscan_tpu.obs.trace import Tracer


class _RingTracer(Tracer):
    """A Tracer whose process-level instants are bounded like its spans
    (the base class bounds only ``spans``; a recorder that runs for the
    process lifetime must not grow EITHER list without bound)."""

    def instant(self, name: str, args: dict) -> None:
        super().instant(name, args)
        with self._lock:
            if len(self.instants) > self.max_spans:
                del self.instants[: len(self.instants) // 2]


class FlightState:
    """The live recorder: one ring tracer + one metrics registry."""

    __slots__ = ("tracer", "metrics", "capacity")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.tracer = _RingTracer(device_sync=False)
        # drop-oldest-half trim => the surviving tail is always >= cap
        self.tracer.max_spans = 2 * self.capacity
        self.metrics = MetricsRegistry()


#: the one value the obs hooks truth-check on their disabled path
_state: Optional[FlightState] = None
_configured: Optional[bool] = None  # last DBSCAN_FLIGHTREC value applied
_lock = _tsan.lock("obs.flight")
_signals_installed = False
_prev_handlers: dict = {}


def state() -> Optional[FlightState]:
    return _state


def active() -> bool:
    return _state is not None


def capacity() -> int:
    """Ring size: the dump's span/instant tail bound (floor 64 — the
    acceptance contract promises at least the last 64 spans)."""
    return max(64, int(config.env("DBSCAN_FLIGHTREC_EVENTS")))


def ensure_env() -> None:
    """(Re)apply ``DBSCAN_FLIGHTREC`` — called at the pipeline entry
    points alongside ``obs.ensure_env``. One env read per call; the
    recorder is built/dropped only when the knob value CHANGED, so a
    long-lived stream pays a latch check per update, not a rebuild.
    Rings survive across runs by design: the recorder's whole point is
    holding the tail of whatever happened most recently."""
    global _state, _configured
    on = bool(config.env("DBSCAN_FLIGHTREC"))
    if on == _configured:
        return
    with _lock:
        _tsan.access("obs.flight")
        if on == _configured:
            return
        _state = FlightState(capacity()) if on else None
        _configured = on
    if on:
        _install_signal_handlers()


def reset() -> None:
    """Drop the recorder and its env latch (tests): the next
    :func:`ensure_env` re-reads the knob into a FRESH ring."""
    global _state, _configured
    with _lock:
        _tsan.access("obs.flight")
        _state = None
        _configured = None


# --- dumping ----------------------------------------------------------


def _default_path() -> str:
    """``DBSCAN_FLIGHTREC_PATH`` with the multi-process shard suffix
    (``<path>.<process_index>``) — same sharding rule as DBSCAN_TRACE.
    Unconfigured runs dump to a run-scoped file under the system tmp
    dir: an always-on recorder must never litter whatever directory
    the dying process happened to be cwd'd into (a tier-1 test run
    leaves no ``flightrec.json`` in the repo root — pinned)."""
    path = config.env("DBSCAN_FLIGHTREC_PATH")
    if not path:
        import tempfile

        path = os.path.join(
            tempfile.gettempdir(), f"dbscan-flightrec.{os.getpid()}.json"
        )
    return str(path) + export_mod.shard_suffix()


def _span_records(spans: list, base: float, cap: int) -> list:
    out = []
    for sp in spans[-cap:]:
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        rec = {
            "name": sp.name,
            "t0_s": round(sp.t0 - base, 9),
            "dur_s": round(max(0.0, t1 - sp.t0), 9),
            "depth": sp.depth,
            "tid": sp.tid,
            "args": export_mod._jsonable(sp.args),
            "events": [
                {
                    "name": n,
                    "t_s": round(t - base, 9),
                    "args": export_mod._jsonable(a),
                }
                for n, t, a in sp.events
            ],
        }
        rid = getattr(sp, "rid", None)
        if rid is not None:
            rec["rid"] = rid
        out.append(rec)
    return out


def dump(
    path: Optional[str] = None,
    reason: str = "manual",
    _signal_safe: bool = False,
    **note,
) -> Optional[str]:
    """Write the flight ring as one JSON postmortem; returns the path,
    or None when neither the recorder nor observability is live.

    Source registries: a run with full observability enabled records
    once (into the obs registries), so the dump reads THEIR tail; the
    always-on ring covers every other run. ``note`` fields (abort
    site/ordinal/error) land under ``"note"`` — the first thing a
    postmortem reader wants next to the last spans. Best-effort by
    contract: a dump must never mask the fault that triggered it, so
    callers wrap it in try/except (the module's own signal handlers
    do).

    ``_signal_safe``: the signal handlers set it — a CPython signal
    handler runs ON the main thread between bytecodes, so the
    interrupted frame may already HOLD the (non-reentrant) tracer/
    metrics locks, and a dump that tried to acquire them would
    deadlock the dying process. In that mode the dump skips its own
    telemetry emission and snapshots the registries WITHOUT locking —
    CPython list/dict copies are safe against concurrent mutation,
    and the worst case is one in-flight record missing from the tail,
    which beats no postmortem at all."""
    import dbscan_tpu.obs as obs

    st = obs.state()
    if st is not None:
        tracer, metrics, source = st.tracer, st.metrics, "obs"
    else:
        fs = _state
        if fs is None:
            return None
        tracer, metrics, source = fs.tracer, fs.metrics, "flightrec"
    if not _signal_safe:
        # the dump records itself first, so the ring's final instant
        # says why this file exists (and a trace flushed later carries
        # it). Skipped on the signal path: these take the locks.
        obs.event("flightrec.dump", reason=reason, **note)
        obs.count("flightrec.dumps")
        spans = tracer.snapshot_spans()
        counters = metrics.counters()
        gauges = metrics.gauges()
    else:
        spans = list(tracer.spans)
        counters = dict(metrics._counters)
        gauges = dict(metrics._gauges)
    cap = capacity()
    base = tracer.t0
    payload = {
        "flightrec": 1,
        "reason": reason,
        "note": export_mod._jsonable(note),
        "source": source,
        "time": time.time(),
        "epoch0": tracer.epoch0,
        "pid": os.getpid(),
        "shard": export_mod.shard_index(),
        "capacity": cap,
        "dropped_spans": getattr(tracer, "dropped_spans", 0),
        "spans": _span_records(spans, base, cap),
        "instants": [
            {
                "name": n,
                "t_s": round(t - base, 9),
                "args": export_mod._jsonable(a),
            }
            for n, t, a in list(tracer.instants)[-cap:]
        ],
        "counters": export_mod._jsonable(counters),
        "gauges": export_mod._jsonable(gauges),
    }
    out = path or _default_path()
    export_mod._atomic_write(out, json.dumps(payload))
    return out


def dump_on_fault(site: str, ordinal: int, error: str) -> Optional[str]:
    """The fatal-fault dump (``faults.supervised`` exhausting retries,
    the driver's abort guard): best-effort, never raises — the original
    device fault must always win."""
    try:
        return dump(
            reason="fatal_fault",
            site=site,
            ordinal=int(ordinal),
            error=str(error)[:200],
        )
    except Exception:  # noqa: BLE001 — postmortem must not mask the fault
        return None


def load(path: str) -> dict:
    """Read a dump back (tests, tooling)."""
    with open(path) as f:
        return json.load(f)


# --- signal wiring (the serving system's teardown path) ---------------

#: cooperative SIGTERM hooks (dbscan_tpu/serve's checkpoint-on-preempt):
#: run AFTER the dump, BEFORE the chain to the previous disposition —
#: the documented "dump, then <your teardown>, then die" order. A
#: service that instead installed its own raw ``signal.signal`` handler
#: either replaced this module's (losing the dump) or chained back into
#: it (double-dumping); :func:`on_sigterm` is the composition API that
#: has neither problem.
_sigterm_hooks: list = []
#: re-entrancy guard WITHIN one signal delivery: a foreign handler that
#: chains back into :func:`_on_sigterm` (the pre-hook composition style)
#: must not dump or run the hooks a second time
_sigterm_active = False


def sigterm_armed() -> bool:
    """True when this module's SIGTERM handler is actually installed —
    the precondition for :func:`on_sigterm` hooks ever running. False
    when the recorder was never enabled (``DBSCAN_FLIGHTREC=0`` from
    process start) or the first :func:`ensure_env` ran off the main
    thread (the signal API's own constraint). Callers that REQUIRE
    their teardown hook (the serving layer's checkpoint-on-preempt)
    check this and warn, instead of discovering an inert preemption
    path at the first real SIGTERM."""
    return _signals_installed


def on_sigterm(hook):
    """Register a zero-arg teardown hook on the recorder's SIGTERM path
    (dump -> hooks in registration order -> chain). Returns an
    unregister callable. Hooks are best-effort: an exception in one is
    swallowed (teardown must still tear down) and later hooks still
    run. Signal-context caveats apply: the hook runs on the main thread
    between bytecodes, so it must not acquire locks an interrupted
    frame may hold (write files, read published snapshots)."""
    _sigterm_hooks.append(hook)

    def _remove() -> None:
        try:
            _sigterm_hooks.remove(hook)
        except ValueError:
            pass

    return _remove


def _on_sigusr1(signum, frame):
    try:
        dump(reason="SIGUSR1", _signal_safe=True)
    except Exception:  # noqa: BLE001 — a poke must never kill the process
        pass
    prev = _prev_handlers.get(signal.SIGUSR1)
    if callable(prev):
        prev(signum, frame)


def _chain_sigterm(signum, frame):
    """The termination tail: hand off to the disposition that was live
    before this module installed itself."""
    prev = _prev_handlers.get(signal.SIGTERM)
    if callable(prev):
        prev(signum, frame)
        return
    if prev is signal.SIG_IGN:
        # the prior disposition IGNORED SIGTERM: honor it (the process
        # survives) and KEEP this handler installed, so every later
        # SIGTERM still dumps — uninstalling here would silently end
        # the always-on contract after the first signal
        return
    # default disposition: restore it and re-raise so the process still
    # terminates with the standard SIGTERM exit status
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def _on_sigterm(signum, frame):
    global _sigterm_active
    if _sigterm_active:
        # re-entered through a foreign handler chaining back into this
        # one mid-delivery: the dump and the hooks already ran — go
        # straight to the termination tail instead of double-dumping
        _chain_sigterm(signum, frame)
        return
    _sigterm_active = True
    try:
        try:
            dump(reason="SIGTERM", _signal_safe=True)
        except Exception:  # noqa: BLE001 — teardown must still tear down
            pass
        for hook in list(_sigterm_hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 — best-effort by contract
                pass
        _chain_sigterm(signum, frame)
    finally:
        # reached when the chain did not terminate the process (SIG_IGN
        # disposition, or a harness handler that returns): the next
        # delivery dumps again
        _sigterm_active = False


def _install_signal_handlers() -> None:
    """SIGTERM (preemption: dump then die) + SIGUSR1 (dump and keep
    running). Installed once per process, main thread only (the signal
    API's own constraint); previous handlers are chained, so a harness
    with its own SIGTERM hook keeps it."""
    global _signals_installed
    if _signals_installed:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        _prev_handlers[signal.SIGUSR1] = signal.signal(
            signal.SIGUSR1, _on_sigusr1
        )
        _prev_handlers[signal.SIGTERM] = signal.signal(
            signal.SIGTERM, _on_sigterm
        )
        _signals_installed = True
    except (ValueError, OSError, AttributeError):
        # non-main thread or a platform without these signals: the
        # fault/dump() triggers still work, only the signal leg is off
        pass
