"""Device-memory (HBM) watermark sampling for the observability layer.

`faults.py` reacts to RESOURCE_EXHAUSTED blindly: it halves the dispatch
budget without ever recording HOW FULL the chip actually was when the
allocator gave up — so a capture showing repeated halvings cannot say
whether the run was genuinely at the 16 GB ceiling or a fragmentation /
transient-pileup artifact the inflight-window budget should have
prevented. This module closes that gap: :func:`sample` reads
``device.memory_stats()`` (the PJRT allocator's live view — populated on
TPU/GPU, ``None`` on CPU backends) and records ``memory.*`` gauges, and
the driver/spill/fault call sites invoke it at the moments that move HBM
(dispatch fan-outs, the resident payload upload, a RESOURCE_EXHAUSTED
halving).

Contract (same as every obs hook, pinned by tests/test_obs.py):

- DISABLED path is a strict no-op — one truthiness check of the
  process-global obs state, no device call is ever made;
- backends without allocator stats (CPU) degrade to a no-op AFTER the
  state check: one ``memory_stats()`` probe per device per process
  decides availability, then the sampler short-circuits for the process
  lifetime (``_AVAILABLE`` latch) so hot paths never re-probe.

Gauges written per sample (set-last-wins; the PEAK ones are made
monotone here, since the registry's gauges have no max semantics):

- ``memory.bytes_in_use`` — summed live allocator bytes across devices;
- ``memory.peak_bytes_in_use`` — high-water mark: max of the
  allocator's own ``peak_bytes_in_use`` and every sample this process
  took (monotone per process; :func:`reset_peak` for tests);
- ``memory.bytes_limit`` — summed allocator capacity, when reported;
- ``memory.at.<site>`` — bytes_in_use at the last sample taken at that
  call site (``dispatch.dense``, ``dispatch.banded``,
  ``spill.payload_upload``, ``fault.resource_exhausted``, ...): the
  span-boundary occupancy the analyzer's watermark table reads.
"""

from __future__ import annotations

import dbscan_tpu.obs as obs
from dbscan_tpu.lint import tsan as _tsan

# availability latch: None = not probed yet; False = no device reports
# allocator stats (CPU backend) — sampler short-circuits forever;
# True = at least one device reports stats.
_AVAILABLE = None
_peak_seen = 0
_lock = _tsan.lock("obs.memory")


def device_memory_stats() -> dict:
    """Live per-device allocator stats: ``{"tpu:0": {...}, ...}`` for
    every device whose ``memory_stats()`` reports (TPU/GPU PJRT
    backends); ``{}`` where unavailable (CPU) or before jax loads."""
    try:
        import jax

        devices = jax.devices()
    except Exception:  # noqa: BLE001 — sampler must never raise
        return {}
    out = {}
    for d in devices:
        try:
            st = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API
            st = None
        if st:
            out[f"{d.platform}:{d.id}"] = st
    return out


def available() -> bool:
    """True when some device reports allocator stats (probed once).
    The probe latch is written under ``_lock``: the sampler runs from
    supervised retries on the pull-engine worker too, and the unguarded
    latch write was a worker-slice race finding (graftcheck
    race-unlocked-shared, PR 6). Settled fast path: one plain read."""
    global _AVAILABLE
    latched = _AVAILABLE
    if latched is None:
        probed = bool(device_memory_stats())
        with _lock:
            _tsan.access("obs.memory")
            if _AVAILABLE is None:
                _AVAILABLE = probed
            latched = _AVAILABLE
    return latched


def sample(site: str):
    """Record the ``memory.*`` gauges from the live allocator state;
    returns summed bytes_in_use, or None when obs is disabled or no
    device reports stats (CPU). One obs-state truthiness check when
    disabled; one latched boolean when stats are unavailable."""
    st = obs.state()
    if st is None:
        return None
    if not available():
        return None
    stats = device_memory_stats()
    if not stats:
        return None
    in_use = sum(int(s.get("bytes_in_use", 0)) for s in stats.values())
    peak_rep = sum(
        int(s.get("peak_bytes_in_use", 0)) for s in stats.values()
    )
    limit = sum(int(s.get("bytes_limit", 0)) for s in stats.values())
    global _peak_seen
    with _lock:
        _tsan.access("obs.memory")
        _peak_seen = max(_peak_seen, peak_rep, in_use)
        peak = _peak_seen
    st.metrics.gauge("memory.bytes_in_use", in_use)
    st.metrics.gauge("memory.peak_bytes_in_use", peak)
    if limit:
        st.metrics.gauge("memory.bytes_limit", limit)
    st.metrics.gauge(f"memory.at.{site}", in_use)
    st.metrics.count("memory.samples")
    return in_use


def reset_peak() -> None:
    """Drop the process high-water mark AND re-probe availability on
    the next sample (tests swap fake backends in and out)."""
    global _peak_seen, _AVAILABLE
    with _lock:
        _tsan.access("obs.memory")
        _peak_seen = 0
        _AVAILABLE = None
