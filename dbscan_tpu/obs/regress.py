"""Bench regression gate: compare a fresh capture against the history.

The ROADMAP's "as fast as the hardware allows" was un-checkable: a PR
that doubled ``anchor_seconds`` would sail through CI because nothing
compared captures across rounds. This gate closes the loop::

    python -m dbscan_tpu.obs.regress --capture fresh.json \
        [--history bench/history.jsonl] [--threshold 0.25]
    python -m dbscan_tpu.obs.regress --check-schema

Exit codes: 0 = no regression, 1 = regression detected, 2 = usage /
schema / IO error — so CI and a local ``python bench.py && python -m
dbscan_tpu.obs.regress --capture ...`` both gate on it directly.

Noise-aware threshold: for each comparable metric the gate matches
history records on (metric, backend, resident_hot) — hot and cold
resident-cache walls are DIFFERENT populations (PR 2's tag; a cold
cosine rep legitimately runs ~10x the hot wall, and mixing them would
either mask real regressions or flag every cold rep) — and computes the
history's median and relative spread ((max-min)/median). The effective
threshold is ``max(--threshold, spread)``: a metric whose history
already swings 3x across captures (the tunnel-latency lottery) cannot
flag at 25%, while a stable metric flags at the requested bound.
Direction comes from the metric name: ``*_seconds``/``*_s`` regress
UP, ``*_mpts``/``*_vs_baseline``/throughput headline regress DOWN;
metrics with no known direction are skipped (reported, not gated).

One exception to the noise-aware scheme: ``*_pred_ratio`` (graftshape's
observed-HBM-peak / statically-predicted-peak containment figure) is a
HARD CAP at 1.0 with no history needed — it is a contract ("the static
model bounds the observed peak"), not a perf direction, so widening its
threshold to the noise spread would defeat it.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from typing import List, Optional

from dbscan_tpu.obs import bench_history, schema

LOWER_BETTER = "lower"
HIGHER_BETTER = "higher"


def direction(metric: str, unit: Optional[str] = None) -> Optional[str]:
    """Which way ``metric`` regresses: walls regress up, throughputs
    (and the pull-pipeline overlap ratio) regress down, everything else
    is not gate-able."""
    if metric.endswith("_overlap_ratio"):
        # overlap lost = pulls back on the critical path: regresses DOWN
        return HIGHER_BETTER
    if metric.endswith("_busy_frac"):
        # device utilization lost = work moved back to the host/link
        # (devtime's measured device-busy share): regresses DOWN
        return HIGHER_BETTER
    if metric.endswith("_spill_levels"):
        # level-build rounds = fused dispatches = tree depth: a deeper
        # tree pays more round-trips, so the count regresses UP
        return LOWER_BETTER
    if metric.endswith("_cc_iters"):
        # device cellcc CC sweeps: each is a full [C, 25] gather pass,
        # so a propagation-count blowup regresses UP like a wall
        return LOWER_BETTER
    if metric.endswith("_prop_sweeps"):
        # shared window_cc-family sweep count (ops/propagation.py):
        # the figure DBSCAN_PROP_UNIONFIND exists to collapse —
        # regresses UP like _cc_iters
        return LOWER_BETTER
    if metric.endswith("_replay_frac"):
        # campaign restart overhead (replayed wall / total work wall,
        # dbscan_tpu/campaign.py): more of the campaign's wall spent
        # recomputing stolen/killed leases regresses UP like a wall
        return LOWER_BETTER
    if metric.endswith("_jobs_s"):
        # serve tenancy throughput (jobs PER second): a rate, so it
        # regresses DOWN — and it must be matched BEFORE the "_s"
        # seconds rule below catches the suffix
        return HIGHER_BETTER
    if metric.endswith("_shed_frac"):
        # router load-shed fraction (shed / (shed + routed),
        # serve/router.py): capacity the fleet turned away — more
        # shedding at the same offered load regresses UP like a wall
        return LOWER_BETTER
    if metric.endswith("_qps"):
        # serving query rate under concurrent ingest: regresses DOWN
        return HIGHER_BETTER
    if metric.endswith("_boruvka_rounds"):
        # density-engine Borůvka MST contraction rounds: each is a full
        # [n_pad, n_pad] mutual-reachability scan + a synchronous pull,
        # bounded by ceil(log2 n) + 2 — a round-count blowup regresses
        # UP like _spill_levels (labels are count-independent)
        return LOWER_BETTER
    if metric.endswith("_ms"):
        # serve query latency percentiles: walls, regress UP
        return LOWER_BETTER
    if metric.endswith("_ari"):
        # clustering accuracy (embed subsampled mode's declared floor,
        # and every row's construction ARI): regresses DOWN like a
        # throughput — an accuracy collapse must flag, not hide in the
        # raw capture
        return HIGHER_BETTER
    if metric.endswith(("_seconds", "_s")) or metric == "seconds":
        return LOWER_BETTER
    if metric.endswith(("_mpts", "_vs_baseline", "_throughput")) or metric in (
        "vs_baseline",
    ):
        return HIGHER_BETTER
    if unit in ("Mpoints/s",):
        return HIGHER_BETTER
    return None


def compare(
    fresh: List[dict],
    history: List[dict],
    threshold: float = 0.25,
    min_samples: int = 2,
) -> dict:
    """Gate ``fresh`` records against ``history``; returns
    ``{"regressions": [...], "ok": [...], "skipped": [...]}`` where each
    entry carries the metric, values, and the effective threshold."""
    regressions, ok, skipped = [], [], []
    for rec in fresh:
        metric = rec["metric"]
        if metric.endswith("_vs_default_speedup"):
            # autotuner contract, not a perf direction: a committed
            # profile must BEAT (or tie) the defaults it replaces, so
            # the ratio is hard-FLOORED at 1.0 with no history needed —
            # the mirror image of the _pred_ratio hard cap below (and
            # immune to noise widening for the same reason)
            value = rec["value"]
            entry = {
                "metric": metric,
                "value": value,
                "median": 1.0,
                "n": 0,
                "direction": "floor",
                "delta": round(1.0 - value, 4),
                "threshold": 0.0,
                "resident_hot": rec.get("resident_hot"),
                "backend": rec.get("backend"),
            }
            (regressions if value < 1.0 else ok).append(entry)
            continue
        if metric.endswith("_pred_ratio"):
            # graftshape containment contract, not a perf direction:
            # the static model must BOUND the observed HBM peak, so a
            # ratio above 1.0 fails with no history needed (the only
            # hard-capped metric — noise widening would defeat it)
            value = rec["value"]
            entry = {
                "metric": metric,
                "value": value,
                "median": 1.0,
                "n": 0,
                "direction": "cap",
                "delta": round(value - 1.0, 4),
                "threshold": 0.0,
                "resident_hot": rec.get("resident_hot"),
                "backend": rec.get("backend"),
            }
            (regressions if value > 1.0 else ok).append(entry)
            continue
        dirn = direction(metric, rec.get("unit"))
        if dirn is None:
            skipped.append({"metric": metric, "reason": "no_direction"})
            continue
        base = [
            h["value"]
            for h in history
            if h.get("metric") == metric
            and h.get("backend") == rec.get("backend")
            and h.get("resident_hot") == rec.get("resident_hot")
            and h.get("source") != rec.get("source")
        ]
        if len(base) < min_samples:
            skipped.append(
                {
                    "metric": metric,
                    "reason": f"history_n={len(base)}<{min_samples}",
                }
            )
            continue
        med = statistics.median(base)
        if med <= 0:
            skipped.append({"metric": metric, "reason": "median<=0"})
            continue
        spread = (max(base) - min(base)) / med
        eff = max(threshold, spread)
        value = rec["value"]
        if dirn == LOWER_BETTER:
            bad = value > med * (1.0 + eff)
            delta = value / med - 1.0
        else:
            bad = value < med / (1.0 + eff)
            delta = med / max(value, 1e-300) - 1.0
        entry = {
            "metric": metric,
            "value": value,
            "median": round(med, 6),
            "n": len(base),
            "direction": dirn,
            "delta": round(delta, 4),
            "threshold": round(eff, 4),
            "resident_hot": rec.get("resident_hot"),
            "backend": rec.get("backend"),
        }
        (regressions if bad else ok).append(entry)
    return {"regressions": regressions, "ok": ok, "skipped": skipped}


def format_regression(e: dict) -> str:
    """One regression entry as a human line — the ONE rendering of a
    verdict, shared with bench.py's BENCH_HISTORY gate so the formats
    (and the 'allowed' effective-threshold figure) cannot drift."""
    return (
        f"REGRESSION {e['metric']}: {e['value']} vs median "
        f"{e['median']} (n={e['n']}, {e['delta']:+.1%} worse, "
        f"allowed {e['threshold']:.1%}"
        + (
            f", resident_hot={e['resident_hot']}"
            if e["resident_hot"] is not None
            else ""
        )
        + ")"
    )


def _render(result: dict) -> str:
    lines = []
    for e in result["regressions"]:
        lines.append(format_regression(e))
    for e in result["ok"]:
        lines.append(
            f"ok         {e['metric']}: {e['value']} vs median "
            f"{e['median']} (n={e['n']}, allowed {e['threshold']:.1%})"
        )
    for e in result["skipped"]:
        lines.append(f"skip       {e['metric']}: {e['reason']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.obs.regress",
        description="Noise-aware bench regression gate over the "
        "normalized capture history.",
    )
    p.add_argument(
        "--history", default=bench_history.DEFAULT_HISTORY,
        help="history file (default bench/history.jsonl)",
    )
    p.add_argument(
        "--capture",
        help="fresh capture to gate (any historical BENCH_* shape, or "
        "a bench.py output record)",
    )
    p.add_argument(
        "--threshold", type=float, default=0.25,
        help="minimum relative regression to flag (default 0.25; "
        "raised per metric to the history's own spread)",
    )
    p.add_argument(
        "--min-samples", type=int, default=2,
        help="history samples needed before a metric gates (default 2)",
    )
    p.add_argument(
        "--check-schema", action="store_true",
        help="validate the history file's record schema and exit",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the comparison result as JSON",
    )
    args = p.parse_args(argv)

    try:
        history = bench_history.load_history(args.history)
    except (OSError, ValueError) as e:
        print(f"regress: cannot read {args.history}: {e}", file=sys.stderr)
        return 2

    if args.check_schema:
        # the declared telemetry registry is part of the gated contract:
        # a malformed obs/schema.py edit fails the same CI command that
        # validates the bench history
        schema_errors = schema.self_check()
        if schema_errors:
            for err in schema_errors[:20]:
                print(f"regress: obs schema: {err}", file=sys.stderr)
            return 2
        if not history:
            print(
                f"regress: no history at {args.history} (ingest captures "
                "with python -m dbscan_tpu.obs.bench_history first)",
                file=sys.stderr,
            )
            return 2
        errors = bench_history.check_schema(history)
        if errors:
            for err in errors[:20]:
                print(f"regress: schema: {err}", file=sys.stderr)
            return 2
        print(
            f"regress: schema ok — {len(history)} record(s), "
            f"{len({r['metric'] for r in history})} metric(s) in "
            f"{args.history}"
        )
        return 0

    if not args.capture:
        p.error("--capture is required (or use --check-schema)")
    try:
        fresh = bench_history.parse_capture_file(args.capture)
    except (OSError, ValueError) as e:
        print(f"regress: cannot read {args.capture}: {e}", file=sys.stderr)
        return 2
    if not fresh:
        print(
            f"regress: no perf records found in {args.capture}",
            file=sys.stderr,
        )
        return 2

    result = compare(
        fresh, history,
        threshold=args.threshold,
        min_samples=args.min_samples,
    )
    if args.json:
        print(json.dumps(result))
    else:
        print(_render(result))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
