"""Bench-capture history: one normalized, append-only record stream.

The repo root has accumulated 20+ ``BENCH_*.json`` / ``MULTICHIP_*.json``
captures in at least four ad-hoc shapes (flat metric objects, prefixed
row objects, ``{"tail": ...}`` driver wrappers whose JSON lines live
inside a log string, probe records with ``rows``), and the only way to
see the perf trajectory across rounds was to eyeball them. This module
ingests every shape into ONE schema, appended to ``bench/history.jsonl``::

    python -m dbscan_tpu.obs.bench_history BENCH_*.json MULTICHIP_*.json

Record schema (one JSON object per line; append-only — re-ingesting a
file skips records already present)::

    {"metric": str,          # e.g. "anchor_seconds", "value"
     "value": float,
     "unit": str | null,     # "s", "Mpoints/s", ... when known
     "backend": str,         # "tpu" / "cpu" / "multichip" / "unknown"
     "resident_hot": bool | null,  # PR-2 hot/cold tag when the capture
                              # carried it — hot and cold walls are
                              # different populations (PARITY.md) and
                              # the regress gate never mixes them
     "rev": str,             # git rev at ingest time ("unknown" ok)
     "source": str}          # capture filename the record came from

Which numeric keys become records: ``value`` (named by the capture's
own ``metric`` string), plus scalar keys ending in ``_seconds`` /
``_s`` / ``_mpts`` / ``_vs_baseline`` / ``_ari`` (and bare
``seconds`` / ``vs_baseline``) — the walls, throughputs, and accuracy
scores the regress gate knows a better-direction for (``_ari``
promoted since the embed engine's subsampled-edge mode made accuracy
a tunable: its declared floor gates regress-down like a throughput).
Cluster counts and shape diagnostics stay in the raw captures; the
history is the PERF + accuracy trajectory.

The regress gate (:mod:`dbscan_tpu.obs.regress`) compares a fresh
capture against this history with a noise-aware threshold.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Iterable, List, Optional, Tuple

DEFAULT_HISTORY = os.path.join("bench", "history.jsonl")

from dbscan_tpu.obs import schema

# scalar keys promoted to history records: exact names + suffixes
# (_overlap_ratio: the pull-pipeline's overlapped/total pull share —
# a throughput-like health figure that regresses DOWN; _pred_ratio:
# graftshape's observed-HBM-peak / predicted-peak containment figure,
# hard-capped at 1.0 by obs/regress.py; _spill_levels: the level-
# synchronous spill build's round count — a depth/dispatch figure that
# regresses UP like a wall; _busy_frac: devtime's measured device-busy
# share of the rep wall — device utilization lost = work moved back to
# the host/link, so it regresses DOWN like the overlap ratio;
# _cc_iters: the device cellcc finalize's CC sweep count — a
# propagation-depth figure that regresses UP like the spill levels;
# _replay_frac: the campaign driver's priced restart overhead —
# replayed wall / total work wall — which regresses UP like a wall;
# _qps: the serving layer's sustained query rate — a throughput that
# regresses DOWN; _ms: serve query latency percentiles — walls in
# milliseconds, regress UP; _ari: clustering-accuracy scores — the
# embed engine's subsampled-edge mode made accuracy a TUNABLE, so its
# declared floor must trend and gate like a throughput (regress DOWN);
# every row's ARI rides the same suffix, so an accuracy collapse on
# any engine now flags instead of hiding in the raw captures. NOTE the
# ordering trap the serve keys introduce: tenancy_jobs_s ENDS in "_s"
# but is a jobs-per-second THROUGHPUT — obs/regress.direction and
# _unit_for both special-case the "_jobs_s" suffix BEFORE the seconds
# rule)
# _prop_sweeps: the shared window_cc-family sweep count (ops/
# propagation.py) — a propagation-depth figure that regresses UP like
# _cc_iters (and trends the DBSCAN_PROP_UNIONFIND collapse);
# _vs_default_speedup: the autotuner's tuned-vs-default ratio
# (python -m dbscan_tpu.bench --tune) — HARD-FLOORED at 1.0 by
# obs/regress.py (a committed profile that loses to defaults is a red
# gate, the same contract shape as _pred_ratio's hard cap);
# _boruvka_rounds: the density engine's MST contraction round count
# (dbscan_tpu/density/boruvka.py) — a dispatch-depth figure bounded by
# ceil(log2 n) + 2 that regresses UP like _spill_levels
_EXACT_KEYS = ("value", "seconds", "vs_baseline")
_SUFFIXES = (
    "_seconds", "_s", "_mpts", "_vs_baseline", "_overlap_ratio",
    "_pred_ratio", "_spill_levels", "_busy_frac", "_cc_iters",
    "_replay_frac", "_qps", "_ms", "_ari", "_prop_sweeps",
    "_vs_default_speedup", "_shed_frac", "_boruvka_rounds",
)
# numeric-but-not-perf keys the suffix rule would otherwise catch —
# declared with the telemetry schema (the keys are fault-counter
# deltas riding bench rows, so the exclusion must track the schema)
_EXCLUDE = schema.BENCH_EXCLUDE_SUFFIXES

REQUIRED_KEYS = ("metric", "value", "source")


def git_rev(cwd: Optional[str] = None) -> str:
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=cwd,
                capture_output=True,
                timeout=10,
            )
            .stdout.decode()
            .strip()
            or "unknown"
        )
    except Exception:  # noqa: BLE001 — rev is best-effort metadata
        return "unknown"


def _unit_for(metric: str, obj: dict) -> Optional[str]:
    if metric == "value":
        return obj.get("unit")
    if metric.endswith(
        (
            "_overlap_ratio",
            "_pred_ratio",
            "_busy_frac",
            "_replay_frac",
            "_shed_frac",
        )
    ):
        return "ratio"
    if metric.endswith("_spill_levels"):
        return "levels"
    if metric.endswith("_boruvka_rounds"):
        return "rounds"
    if metric.endswith("_cc_iters"):
        return "iters"
    if metric.endswith("_prop_sweeps"):
        return "iters"
    if metric.endswith("_vs_default_speedup"):
        return "ratio"
    if metric.endswith("_jobs_s"):
        # jobs PER second (serve tenancy throughput), not a wall —
        # must beat the "_s" rule below
        return "jobs/s"
    if metric.endswith("_qps"):
        return "queries/s"
    if metric.endswith("_ms"):
        return "ms"
    if metric.endswith("_ari"):
        return "ari"
    if metric.endswith(("_seconds", "_s")) or metric == "seconds":
        return "s"
    if metric.endswith("_mpts"):
        return "Mpoints/s"
    return None


def _resident_tag(metric: str, obj: dict):
    """The hot/cold tag covering ``metric``, when the capture carries
    one. Every ``{prefix}_resident_hot`` key in the capture tags ALL of
    that row's metrics (``{prefix}_seconds``, ``{prefix}_mpts``,
    ``{prefix}_vs_baseline``, ``{prefix}_compute_s``, ...) — a
    vs_baseline derived from a hot/cold wall is just as bimodal as the
    wall itself; headline ``seconds``/``value``/``vs_baseline`` read the
    unprefixed tag. False (a COLD rep) is a tag, not a missing tag:
    every check below is ``is not None``, never truthiness — dropping
    False would gate cold walls against the untagged population."""
    for key, v in obj.items():
        if v is None or not key.endswith("_resident_hot"):
            continue
        prefix = key[: -len("_resident_hot")]
        if metric == prefix or metric.startswith(prefix + "_"):
            return bool(v)
    if metric in ("seconds", "value", "vs_baseline"):
        tag = obj.get("resident_hot")
        if tag is None:
            tag = obj.get("_resident_hot")
        return bool(tag) if tag is not None else None
    return None


def _is_perf_key(key: str, value) -> bool:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return False
    if key in _EXCLUDE or key.endswith(_EXCLUDE):
        return False
    return key in _EXACT_KEYS or key.endswith(_SUFFIXES)


def _records_from_metric_obj(obj: dict, source: str, rev: str) -> list:
    backend = obj.get("backend", "unknown")
    out = []
    for key in sorted(obj.keys()):
        value = obj[key]
        if not _is_perf_key(key, value):
            continue
        metric = obj["metric"] if key == "value" and "metric" in obj else key
        out.append(
            {
                "metric": metric,
                "value": float(value),
                "unit": _unit_for(key, obj),
                "backend": backend,
                "resident_hot": _resident_tag(key, obj),
                "rev": rev,
                "source": source,
            }
        )
    return out


def _objects_in_text(text: str) -> list:
    """Every JSON object found in free text (driver ``tail`` strings):
    one per line that parses as a dict."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            o = json.loads(line)
        except ValueError:
            continue
        if isinstance(o, dict):
            out.append(o)
    return out


def normalize_capture(obj: dict, source: str, rev: str = "unknown") -> list:
    """One capture object (any of the historical shapes) -> normalized
    records. Dict-shape dispatch:

    - driver wrapper (``tail``/``parsed``): recurse into the parsed
      record and every JSON line embedded in the tail;
    - multichip capture (``n_devices``+``ok``): the ``multichip_ok``
      record, PLUS — since the harness became a real capture (PR 12)
      rather than a correctness dryrun — every flat perf key the
      capture carries (``multichip_mpts``, ``multichip_seconds``,
      per-shard ``_busy_frac`` / ``_overlap_ratio`` figures) under the
      ``multichip<n>`` backend, so sharded throughput trends and gates
      like every other row; legacy dryruns have no such keys and
      ingest exactly as before;
    - probe record (``rows`` list of dicts): each row's perf keys;
    - anything else: the perf keys of the object itself.
    """
    records: list = []
    if "n_devices" in obj and "ok" in obj:
        # multichip captures also carry a `tail` log: this branch must
        # win over the wrapper branch
        backend = f"multichip{obj.get('n_devices', 0)}"
        records = [
            {
                "metric": "multichip_ok",
                "value": 1.0 if obj.get("ok") else 0.0,
                "unit": None,
                "backend": backend,
                "resident_hot": None,
                "rev": rev,
                "source": source,
            }
        ]
        sub = dict(obj)
        sub["backend"] = backend
        records += _records_from_metric_obj(sub, source, rev)
        return records
    if "tail" in obj and isinstance(obj.get("tail"), str):
        parsed = obj.get("parsed")
        seen_texts = set()
        if isinstance(parsed, dict):
            records += normalize_capture(parsed, source, rev)
            seen_texts.add(json.dumps(parsed, sort_keys=True))
        for sub in _objects_in_text(obj["tail"]):
            key = json.dumps(sub, sort_keys=True)
            if key in seen_texts:
                continue
            seen_texts.add(key)
            records += normalize_capture(sub, source, rev)
        return records
    rows = obj.get("rows") or obj.get("runs")
    if isinstance(rows, list) and rows and isinstance(rows[0], dict):
        for row in rows:
            records += _records_from_metric_obj(
                {**{k: v for k, v in obj.items() if k != "rows"}, **row},
                source,
                rev,
            )
        return records
    return _records_from_metric_obj(obj, source, rev)


def parse_capture_file(path: str, rev: str = "unknown") -> list:
    """All normalized records from one capture file: whole-file JSON if
    it parses (including pretty-printed objects), else per-line JSON."""
    with open(path) as f:
        text = f.read()
    source = os.path.basename(path)
    try:
        obj = json.loads(text)
        objs = [obj] if isinstance(obj, dict) else []
    except ValueError:
        objs = _objects_in_text(text)
    records: list = []
    seen = set()
    for o in objs:
        for r in normalize_capture(o, source, rev):
            # a capture file may carry the same figure twice (bench.py
            # prints the full record AND the compact summary line);
            # one history record per distinct figure
            k = _dedup_key(r)
            if k not in seen:
                seen.add(k)
                records.append(r)
    return records


def _dedup_key(r: dict) -> Tuple:
    return (
        r.get("source"),
        r.get("metric"),
        r.get("value"),
        r.get("resident_hot"),
        r.get("backend"),
    )


def load_history(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def append_records(records: Iterable[dict], path: str) -> Tuple[int, int]:
    """Append records not already present (by source+metric+value+tag);
    returns (added, skipped). Append-only by design: history lines are
    never rewritten, so concurrent benches can only ever add."""
    existing = {_dedup_key(r) for r in load_history(path)}
    added = skipped = 0
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        for r in records:
            if _dedup_key(r) in existing:
                skipped += 1
                continue
            existing.add(_dedup_key(r))
            f.write(json.dumps(r) + "\n")
            added += 1
    return added, skipped


def ingest(
    paths: Iterable[str],
    out_path: str = DEFAULT_HISTORY,
    rev: Optional[str] = None,
) -> Tuple[int, int]:
    """Parse every capture file and append its records to the history;
    returns (added, skipped)."""
    if rev is None:
        rev = git_rev()
    records: list = []
    for p in paths:
        records += parse_capture_file(p, rev)
    return append_records(records, out_path)


def append_capture(
    obj: dict, path: str, source: str, rev: Optional[str] = None
) -> int:
    """Normalize one in-memory capture (bench.py's ``out`` dict) and
    append it; returns records added. The bench harness calls this when
    ``BENCH_HISTORY`` is set, so every local capture lands in the same
    trend the regress gate reads."""
    if rev is None:
        rev = git_rev()
    added, _ = append_records(normalize_capture(obj, source, rev), path)
    return added


def check_schema(records: List[dict]) -> List[str]:
    """Validate history records; returns error strings (empty = ok)."""
    errors = []
    for i, r in enumerate(records):
        for k in REQUIRED_KEYS:
            if k not in r:
                errors.append(f"record {i}: missing key {k!r}")
        if "value" in r and (
            isinstance(r["value"], bool)
            or not isinstance(r["value"], (int, float))
        ):
            errors.append(
                f"record {i}: value must be a number, got "
                f"{type(r['value']).__name__}"
            )
        if "metric" in r and not isinstance(r["metric"], str):
            errors.append(f"record {i}: metric must be a string")
    return errors


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m dbscan_tpu.obs.bench_history",
        description="Ingest BENCH_*/MULTICHIP_* captures into the "
        "normalized append-only bench history.",
    )
    p.add_argument("captures", nargs="+", help="capture JSON files")
    p.add_argument(
        "--out", default=DEFAULT_HISTORY,
        help=f"history file to append to (default {DEFAULT_HISTORY})",
    )
    p.add_argument("--rev", help="git rev to stamp (default: ask git)")
    args = p.parse_args(argv)
    try:
        added, skipped = ingest(args.captures, args.out, rev=args.rev)
    except (OSError, ValueError) as e:
        print(f"bench_history: {e}", file=sys.stderr)
        return 2
    print(
        f"bench_history: {added} record(s) appended to {args.out}"
        + (f" ({skipped} already present)" if skipped else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
