"""jit/retrace accounting: make recompile storms measurable.

The banded dispatch's whole shape discipline — the ~1.5x width ladder
(`binning._ladder_width`), the streaming shape ratchet
(`binning._ratchet`), the module-level `functools.lru_cache` around the
jit builders (`driver._compiled_block` et al.) — exists to keep XLA
compiles rare: a fresh jit signature per micro-batch turns a 50 ms
steady-state step into a seconds-scale recompile forever. But nothing
MEASURED it: a regression that quietly re-traced every dispatch (a
cache-key bug, a data-dependent shape sneaking past the ladder) was
invisible until someone eyeballed walls. This module wraps the hot
jitted entry points so cache misses become counters and spans:

- :func:`tracked_call` runs one call of a jitted function and, when the
  function's trace-cache grew (``fn._cache_size()``), records the call
  as a compile: ``compiles.total`` / ``compiles.<family>`` /
  ``compiles.wall_s`` counters plus a retroactive ``compile.<family>``
  span over the call (on a cache miss the trace+lower+compile wall IS
  the call wall up to the async dispatch tail — documented
  approximation);
- :func:`warn_on_recompile_storm` logs (once per family per process)
  when one dispatch family compiles more than
  ``DBSCAN_COMPILE_STORM_THRESHOLD`` times (default 12) — the failure
  mode the shape ratchet is designed to prevent, now visible the moment
  it regresses.

Contract: the DISABLED path costs one truthiness check and calls the
function straight through — no cache-size probe, no counter. Jits
without ``_cache_size`` (older/exotic wrappers) degrade to
pass-through. Per-family counts are process-global; :func:`reset` for
tests.
"""

from __future__ import annotations

import logging
import sys
import time

import dbscan_tpu.obs as obs
from dbscan_tpu import config
from dbscan_tpu.lint import shapecheck as _shapecheck
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import devtime as _devtime

logger = logging.getLogger(__name__)

_lock = _tsan.lock("obs.compile")
_family_compiles: dict = {}
_family_sites: dict = {}  # family -> "file:line" of the last miss call
_storm_warned: set = set()
_static_sites: dict = None  # lazy lint.callgraph metadata


def storm_threshold() -> int:
    """Compiles per family past which :func:`warn_on_recompile_storm`
    fires (``DBSCAN_COMPILE_STORM_THRESHOLD``; <=0 disables). Default
    12: a batch run legitimately compiles each family a handful of
    times (one per ladder rung in the data), a storm compiles per
    dispatch."""
    return int(config.env("DBSCAN_COMPILE_STORM_THRESHOLD"))


def _cache_size(fn):
    try:
        return fn._cache_size()
    except Exception:  # noqa: BLE001 — wrapper without the API
        return None


def tracked_call(family: str, fn, *args):
    """Call ``fn(*args)`` with compile accounting (see module doc) and
    the per-dispatch hooks of the independently-enabled runtime
    checkers: the graftshape cross-check (``DBSCAN_SHAPECHECK=1``,
    lint/shapecheck.py — observed shapes must instantiate the static
    family model, allocator growth within the static prediction) and
    the device-timeline hooks (obs/devtime.py — the
    ``DBSCAN_PROFILE_WINDOW`` profiler capture opens/closes here, and
    ``DBSCAN_DEVTIME=1`` brackets the dispatch with a ready-sync delta
    per family). Strict pass-through when everything is disabled (one
    extra truthiness check per optional hook)."""
    sc = _shapecheck.runtime()
    handle = sc.observe_call(family, args) if sc is not None else None
    _devtime.dispatch_begin(family)
    st = obs.state()
    if st is None:
        t0 = time.perf_counter()
        out = fn(*args)
        _devtime.dispatch_end(family, out, t0, time.perf_counter())
        if handle is not None:
            sc.settle_call(handle)
        return out
    before = _cache_size(fn)
    t0 = time.perf_counter()
    out = fn(*args)
    t1 = time.perf_counter()
    if before is not None:
        after = _cache_size(fn)
        if after is not None and after > before:
            # only on a detected miss (cold path by definition): capture
            # the dispatch call site so a storm warning can say WHERE
            # the signatures are being minted, not just which family
            frame = sys._getframe(1)
            site = f"{frame.f_code.co_filename}:{frame.f_lineno}"
            note_compile(family, t0, t1, site=site)
    _devtime.dispatch_end(family, out, t0, t1)
    if handle is not None:
        sc.settle_call(handle)
    return out


def _known_sites(family: str) -> str:
    """Call-site attribution for ``family``: the runtime-observed site
    of the last miss when one exists, else the static
    ``lint.callgraph.tracked_call_sites`` metadata (decorated/wrapped
    dispatches that route through :func:`note_compile` directly)."""
    with _lock:
        _tsan.access("obs.compile", write=False)
        site = _family_sites.get(family)
    if site:
        return site
    global _static_sites
    if _static_sites is None:
        # build OUTSIDE the lock (it walks the source tree), publish
        # under it: tracked_call_sites is deterministic, so a racing
        # duplicate build is wasted work, not wrong data — but the
        # unguarded global write was a worker-slice race finding
        # (graftcheck race-unlocked-shared, PR 6)
        try:
            from dbscan_tpu.lint.callgraph import tracked_call_sites

            built = tracked_call_sites()
        except Exception:  # noqa: BLE001 — metadata is best-effort
            built = {}
        with _lock:
            _tsan.access("obs.compile")
            if _static_sites is None:
                _static_sites = built
    sites = _static_sites.get(family)
    if sites:
        return ", ".join(f"{f}:{ln}" for f, ln in sites[:3])
    return "unknown call site"


def note_compile(
    family: str, t0: float = None, t1: float = None, site: str = None
) -> None:
    """Record one compile of ``family`` (counters + compile-wall span
    when bounds are given) and run the storm check."""
    obs.count("compiles.total")
    obs.count(f"compiles.{family}")
    if t0 is not None and t1 is not None:
        obs.count("compiles.wall_s", t1 - t0)
        obs.add_span(f"compile.{family}", t0, t1, family=family)
    with _lock:
        _tsan.access("obs.compile")
        n = _family_compiles.get(family, 0) + 1
        _family_compiles[family] = n
        if site:
            _family_sites[family] = site
    warn_on_recompile_storm(family, n)


def warn_on_recompile_storm(family: str, n: int = None) -> bool:
    """Log (once per family per process) when ``family`` has compiled
    more than the storm threshold; returns True when the family is in
    storm. The warning carries the ratchet-raise count so a streaming
    storm points straight at the shape that kept moving."""
    if n is None:
        with _lock:
            n = _family_compiles.get(family, 0)
    thr = storm_threshold()
    if thr <= 0 or n <= thr:
        return False
    with _lock:
        if family in _storm_warned:
            return True
        _storm_warned.add(family)
    counters = obs.counters()
    site = _known_sites(family)
    obs.event(
        "compiles.storm",
        family=family,
        compiles=n,
        threshold=thr,
        call_site=site,
    )
    logger.warning(
        "recompile storm: dispatch family %r compiled %d times this "
        "process (threshold %d) at %s — a data-dependent shape is "
        "defeating the width ladder / shape ratchet (%s ratchet raises "
        "observed); steady state should reuse cached signatures",
        family,
        n,
        thr,
        site,
        counters.get("compiles.ratchet_raises", 0),
    )
    return True


def family_compiles() -> dict:
    """Snapshot of per-family compile counts (process-global)."""
    with _lock:
        return dict(_family_compiles)


def reset() -> None:
    """Drop per-family counts, sites, and storm-warned latches (tests)."""
    with _lock:
        _family_compiles.clear()
        _family_sites.clear()
        _storm_warned.clear()
