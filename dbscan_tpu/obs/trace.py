"""Nested wall-clock span tracing for the distributed pipeline.

The reference's only observability is driver-side println taps it ships
commented-in (DBSCAN.scala:139,202 — they collect the whole dataset to
the driver); Spark's real story is the event-log UI. Our analog is a
process-global span registry that the export layer (obs/export.py)
writes as JSONL or a Chrome-trace file (chrome://tracing / Perfetto),
built for the question VERDICT r5 could not answer: *where did the
time go* when the same capture swings 5-60 s (resident-payload upload
hot/cold) or a 100M leg dies mid-device-phase.

Design constraints (enforced by tests/test_obs.py):

- The DISABLED path is a strict no-op: ``obs.span(...)`` returns one
  shared :data:`NOOP_SPAN` after a single truthiness check, nothing is
  appended anywhere, no file is ever touched. Tracing must be safe to
  leave wired through every hot call site.
- Spans nest by thread-local stack (``depth`` is recorded at entry);
  phases that already measure themselves (driver ``timings``) register
  RETROACTIVE spans via :meth:`Tracer.add_span` so the trace and the
  stats dict can never disagree about a phase's wall.
- Optional device-sync boundaries reuse the ``DBSCAN_TIME_DEVICE=1``
  convention (bench.py's MFU instrumentation): when enabled, a span
  that registered device outputs via :meth:`Span.sync` blocks on them
  at exit, so the span covers device execution instead of the async
  dispatch. Off by default — blocking sacrifices pack/compute overlap,
  exactly like the driver's ``banded_p1_sync_s`` instrumentation.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from typing import Optional

from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan

# --- request-scoped trace context -------------------------------------
#
# A request id minted at the serving ingress (QueryRouter.query) rides
# a ContextVar so every span/event/fault the request touches — across
# the router thread, the replica dispatch, the sharded cut read, the
# service ingest thread, and the PullEngine workers — is stamped with
# it at construction time. ContextVars do NOT flow into threads that
# already exist (the ingest loop and pull workers are long-lived), so
# queue hops capture the id explicitly at submit time and restore it
# around the work (serve/service.py, parallel/pipeline.py).

_request_ctx: "contextvars.ContextVar[Optional[str]]" = (
    contextvars.ContextVar("dbscan_obs_request_id", default=None)
)
# next() on itertools.count is a single bytecode under the GIL — ids
# stay unique without a lock even when many router threads mint at once
_rid_counter = itertools.count(1)


def mint_request_id() -> str:
    """A fresh process-unique request id (``r<pid:hex>-<seq>``): the
    pid component keeps ids from multi-process shard traces distinct
    when merged by ``obs.analyze --merge``."""
    return f"r{os.getpid():x}-{next(_rid_counter)}"


def current_request() -> Optional[str]:
    """The request id bound in this context, or None outside any
    request scope — a plain ContextVar read, safe on every hot path."""
    return _request_ctx.get()


def set_request(rid: Optional[str]):
    """Bind ``rid`` in the current context; returns the reset token.
    Prefer :class:`request_scope` — this low-level pair exists for
    callers that cannot use a with-block (generator-shaped code)."""
    return _request_ctx.set(rid)


def reset_request(token) -> None:
    _request_ctx.reset(token)


class request_scope:
    """Context manager binding a request id for the dynamic extent of a
    block: ``with request_scope(rid): ...`` — every span/event created
    inside (on this thread's context) carries ``rid``. Re-entrant and
    exception-safe; ``request_scope(None)`` is a valid no-request
    scope (used by queue consumers restoring a possibly-absent id)."""

    __slots__ = ("rid", "_token")

    def __init__(self, rid: Optional[str]):
        self.rid = rid
        self._token = None

    def __enter__(self) -> Optional[str]:
        self._token = _request_ctx.set(self.rid)
        return self.rid

    def __exit__(self, exc_type, exc, tb) -> None:
        _request_ctx.reset(self._token)


class Span:
    """One wall-clock span: context manager AND the finished record.

    ``events`` holds (name, t, args) instants attached while the span
    was open — the bridge carrying fault retries/degradations
    (dbscan_tpu/faults.py) into the trace as visible marks.
    """

    __slots__ = (
        "name", "t0", "t1", "depth", "tid", "rid", "args", "events",
        "_tracer", "_sync",
    )

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self.name = name
        self.args = args
        self.t0 = time.perf_counter()
        self.t1 = None
        self.depth = 0
        self.tid = threading.get_ident()
        self.rid = _request_ctx.get()
        self.events: list = []
        self._tracer = tracer
        self._sync = None

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end()

    def event(self, name: str, **args) -> None:
        """Attach an instant event (fault retry, budget halving, cache
        decision) to this span at the current time."""
        self.events.append((name, time.perf_counter(), args))

    def sync(self, value) -> None:
        """Register device outputs to block on at span end — only when
        the tracer runs with device-sync boundaries (DBSCAN_TIME_DEVICE
        convention); a plain async span otherwise."""
        self._sync = value

    def end(self) -> None:
        if self.t1 is not None:
            return  # idempotent: with-block exit after an explicit end()
        # drop the sync handle unconditionally: finished spans live in
        # the registry, and a retained reference would pin the device
        # buffers (the ~1 GB resident payload!) for the process lifetime
        sync, self._sync = self._sync, None
        if sync is not None and self._tracer.device_sync:
            import jax

            jax.block_until_ready(sync)
        self.t1 = time.perf_counter()
        self._tracer._finish(self)


class _NoopSpan:
    """The shared disabled-path span: every method a no-op, one
    instance for the whole process (no allocation per call site)."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None

    def event(self, name: str, **args) -> None:
        return None

    def sync(self, value) -> None:
        return None

    def end(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Process-global span registry.

    Finished spans accumulate in :attr:`spans` (appended at END time —
    the export layer orders by start time); open spans live only on the
    per-thread stack, so an abandoned span (exception unwound past a
    manual ``end()``) costs a stack entry, never a torn record.
    """

    def __init__(self, device_sync: bool = False):
        self.device_sync = bool(device_sync)
        self.spans: list = []
        self.instants: list = []  # (name, t, args) outside any span
        # retention bound: a long-lived traced stream (one train() per
        # batch, forever) must not grow memory or flush cost without
        # bound — past the cap the OLDEST half is dropped (the tail of
        # the trace is the interesting part of a live process) and the
        # drop is surfaced via `dropped_spans` in the export
        self.max_spans = max(
            1024, int(config.env("DBSCAN_TRACE_MAX_SPANS"))
        )
        self.dropped_spans = 0
        self._lock = _tsan.lock("obs.trace")
        self._tls = threading.local()
        # time bases for export: perf_counter deltas are the durations,
        # epoch0 anchors them to wall-clock time for cross-process reads
        self.t0 = time.perf_counter()
        self.epoch0 = time.time()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def span(self, name: str, args: dict) -> Span:
        sp = Span(self, name, args)
        st = self._stack()
        sp.depth = len(st)
        st.append(sp)
        return sp

    def _finish(self, sp: Span) -> None:
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:  # out-of-order end (exception unwound children)
            st.remove(sp)
        with self._lock:
            _tsan.access("obs.trace")
            self.spans.append(sp)
            self._trim_locked()

    def _trim_locked(self) -> None:
        if len(self.spans) > self.max_spans:
            cut = len(self.spans) // 2  # amortized O(1) per append
            self.dropped_spans += cut
            del self.spans[:cut]

    def add_span(
        self,
        name: str,
        t0: float,
        t1: float,
        args: Optional[dict] = None,
        events: Optional[list] = None,
        rid: Optional[str] = None,
    ) -> Span:
        """Register a RETROACTIVE span from explicit perf_counter
        bounds — the bridge for phases that already time themselves
        (driver ``timings``): the trace records the exact same window
        the stats dict reports. ``rid`` overrides the ambient request
        id for emitters reporting on behalf of another context (the
        PullEngine worker stamping a job's captured id)."""
        sp = Span(self, name, args or {})
        if rid is not None:
            sp.rid = rid
        sp.t0 = float(t0)
        sp.t1 = float(t1)
        sp.depth = len(self._stack())
        if events:
            sp.events.extend(events)
        with self._lock:
            _tsan.access("obs.trace")
            self.spans.append(sp)
            self._trim_locked()
        return sp

    def instant(self, name: str, args: dict) -> None:
        """A free-standing instant event: attaches to the innermost open
        span when one exists, else to the process-level list."""
        st = self._stack()
        if st:
            # the enclosing span already carries the request id
            st[-1].event(name, **args)
        else:
            rid = _request_ctx.get()
            if rid is not None and "rid" not in args:
                # orphan instants keep the (name, t, args) tuple shape
                # every consumer pins; the request id rides the args
                args = dict(args, rid=rid)
            with self._lock:
                _tsan.access("obs.trace")
                self.instants.append((name, time.perf_counter(), args))

    def snapshot_spans(self) -> list:
        with self._lock:
            _tsan.access("obs.trace", write=False)
            return list(self.spans)
