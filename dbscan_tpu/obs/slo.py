"""Declared SLOs with multi-window burn-rate evaluation.

The serving fleet's health questions ("are we meeting the latency
objective", "how much capacity are we turning away", "is the snapshot
stale") were answerable only post-mortem. This module declares them as
SLO objects over the live windows (obs/live.py) and evaluates the
classic multi-window burn-rate rules on the serving hot paths — no
dedicated thread, no timer:

- burn rate = bad-event fraction / error budget (budget =
  1 - DBSCAN_SLO_OBJECTIVE). Burn 1.0 = exactly consuming budget at
  the sustainable rate; DBSCAN_SLO_BURN_PAGE (default 8) and
  DBSCAN_SLO_BURN_TICKET (default 2) are the alert thresholds.
- two windows: the FAST window is the live plane's sliding window
  (DBSCAN_OBS_WINDOW_S); the SLOW window is a :data:`SLOW_MULT` x
  wider exponential moving average of the fast figure. An alert needs
  BOTH past the threshold — the fast window makes alerts prompt, the
  slow window keeps a single spike from paging.
- alerts are DECLARED obs events: ``slo.burn`` (severity page/ticket,
  slo key, both burns, bound attached) on the upward transition,
  ``slo.recover`` when an alerting SLO drops back under the ticket
  line. Page severity also writes an on-demand flight-recorder dump —
  the postmortem arrives WHILE the incident runs, not after the
  process dies.

The declared SLOs (each enabled by its bound knob, 0 = undeclared):

==============  ======================  ================================
key             knob                    bad-event definition
==============  ======================  ================================
``query_p99``   DBSCAN_SLO_QUERY_P99_MS windowed serve.query_ms
                                        observations over the bound
``shed_frac``   DBSCAN_SLO_SHED_FRAC    windowed shed/(shed+routed)
                                        over the bound (ratio SLO:
                                        burn = frac / bound)
``staleness``   DBSCAN_SLO_STALENESS_S  seconds since the last
                                        serve.epoch_publish over the
                                        bound (burn = staleness/bound)
``fault_rate``  DBSCAN_SLO_FAULT_RATE   windowed faults.events per
                                        second over the bound
                                        (burn = rate / bound)
==============  ======================  ================================

STRICT NO-OP WHEN DISABLED: with the live plane off, or no SLO bound
declared, :func:`maybe_evaluate` is one module-global check.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional

from dbscan_tpu import config
from dbscan_tpu.lint import tsan as _tsan
from dbscan_tpu.obs import live

#: slow-window width as a multiple of the fast (live) window — the
#: classic 1h/6h shape scaled to our default 60 s fast window.
SLOW_MULT = 6.0

#: canonical SLO keys (mirrors obs.schema.SLO_KEYS; the event/gauge
#: names are generated there)
QUERY_P99 = "query_p99"
SHED_FRAC = "shed_frac"
STALENESS = "staleness"
FAULT_RATE = "fault_rate"


class SLO(NamedTuple):
    """One declared objective: ``key`` names it everywhere (events,
    gauges, PARITY table); ``bound`` defines a bad event; ``budget``
    is the error budget the burn rate divides by (None for ratio-style
    SLOs whose burn is measured/bound directly)."""

    key: str
    bound: float
    budget: Optional[float]


def declared_slos() -> list:
    """The SLOs the env declares right now (bound knobs > 0)."""
    budget = max(1e-6, 1.0 - float(config.env("DBSCAN_SLO_OBJECTIVE")))
    out = []
    p99 = float(config.env("DBSCAN_SLO_QUERY_P99_MS"))
    if p99 > 0:
        out.append(SLO(QUERY_P99, p99, budget))
    shed = float(config.env("DBSCAN_SLO_SHED_FRAC"))
    if shed > 0:
        out.append(SLO(SHED_FRAC, shed, None))
    stale = float(config.env("DBSCAN_SLO_STALENESS_S"))
    if stale > 0:
        out.append(SLO(STALENESS, stale, None))
    faults = float(config.env("DBSCAN_SLO_FAULT_RATE"))
    if faults > 0:
        out.append(SLO(FAULT_RATE, faults, None))
    return out


def fast_burn(slo: SLO) -> Optional[float]:
    """The SLO's fast-window burn rate from the live windows (None =
    no data yet: an empty window neither burns nor recovers)."""
    if slo.key == QUERY_P99:
        bad = live.frac_above("serve.query_ms", slo.bound)
        if bad is None:
            return None
        return bad / slo.budget
    if slo.key == SHED_FRAC:
        shed = live.window_total("serve.router.shed")
        routed = live.window_total("serve.router.routed")
        if shed + routed <= 0:
            return None
        return (shed / (shed + routed)) / slo.bound
    if slo.key == STALENESS:
        age = live.seconds_since("serve.epoch_publish")
        if age is None:
            return None
        return age / slo.bound
    if slo.key == FAULT_RATE:
        return live.rate("faults.events") / slo.bound
    raise ValueError(f"unknown SLO key {slo.key!r}")


class SLOEngine:
    """Evaluates the declared SLOs against the live windows; keeps the
    slow-window EMAs and the per-SLO alerting latch. One per process
    (see :func:`get_engine`); all state under one registered lock."""

    __slots__ = ("_lock", "_t_last", "_slow", "_alerting", "window_s")

    def __init__(self, window_s: Optional[float] = None):
        self._lock = _tsan.lock("obs.slo")
        self._t_last = None
        self._slow = {}  # key -> slow-window EMA of the fast burn
        self._alerting = {}  # key -> "page" | "ticket" (absent = quiet)
        self.window_s = (
            float(config.env("DBSCAN_OBS_WINDOW_S"))
            if window_s is None
            else float(window_s)
        )

    def evaluate(self) -> list:
        """One evaluation pass: returns the per-SLO verdict dicts and
        emits the transition events/gauges. Cheap when quiet — a few
        window reads per declared SLO."""
        import dbscan_tpu.obs as obs

        slos = declared_slos()
        if not slos:
            return []
        now = time.monotonic()
        page = float(config.env("DBSCAN_SLO_BURN_PAGE"))
        ticket = float(config.env("DBSCAN_SLO_BURN_TICKET"))
        slow_w = SLOW_MULT * self.window_s
        out = []
        with self._lock:
            _tsan.access("obs.slo")
            dt = (
                self.window_s / 4.0
                if self._t_last is None
                else max(1e-6, now - self._t_last)
            )
            self._t_last = now
            alpha = min(1.0, dt / slow_w)
            for slo in slos:
                fast = fast_burn(slo)
                if fast is None:
                    out.append(
                        {"slo": slo.key, "fast": None, "slow": None,
                         "severity": self._alerting.get(slo.key)}
                    )
                    continue
                slow = self._slow.get(slo.key, 0.0)
                slow += alpha * (fast - slow)
                self._slow[slo.key] = slow
                obs.gauge(f"slo.burn.{slo.key}", fast)
                severity = None
                if fast >= page and slow >= page:
                    severity = "page"
                elif fast >= ticket and slow >= ticket:
                    severity = "ticket"
                prev = self._alerting.get(slo.key)
                if severity and severity != prev:
                    # upward transition (or page escalation): one
                    # event per state change, never per evaluation
                    if prev != "page":  # page never demotes to ticket
                        self._alerting[slo.key] = severity
                        obs.event(
                            "slo.burn",
                            slo=slo.key,
                            severity=severity,
                            fast_burn=round(fast, 3),
                            slow_burn=round(slow, 3),
                            bound=slo.bound,
                        )
                        if severity == "page":
                            obs.count("slo.pages")
                            from dbscan_tpu.obs import flight

                            flight.dump(
                                reason="slo_burn",
                                slo=slo.key,
                                fast_burn=round(fast, 3),
                            )
                        else:
                            obs.count("slo.tickets")
                elif prev and fast < ticket and slow < ticket:
                    del self._alerting[slo.key]
                    obs.event(
                        "slo.recover",
                        slo=slo.key,
                        fast_burn=round(fast, 3),
                        slow_burn=round(slow, 3),
                    )
                out.append(
                    {"slo": slo.key, "fast": fast, "slow": slow,
                     "severity": self._alerting.get(slo.key)}
                )
        return out

    def alerting(self) -> dict:
        """Current alert latch: {slo key: severity} (health() view)."""
        with self._lock:
            _tsan.access("obs.slo", write=False)
            return dict(self._alerting)


_engine: Optional[SLOEngine] = None
_engine_lock = _tsan.lock("obs.slo_engine")
_eval_t_last = 0.0


def get_engine() -> SLOEngine:
    global _engine
    st = _engine
    if st is not None:
        return st
    with _engine_lock:
        _tsan.access("obs.slo_engine")
        if _engine is None:
            _engine = SLOEngine()
        return _engine


def reset_engine() -> None:
    """Drop the engine (tests): the next evaluation builds fresh
    slow windows and a quiet alert latch."""
    global _engine, _eval_t_last
    with _engine_lock:
        _tsan.access("obs.slo_engine")
        _engine = None
        _eval_t_last = 0.0


def windowed_health() -> dict:
    """The live plane's health() extension, shared by the router and
    the services: windowed p99/qps/shed-frac plus the SLO alert latch
    ({} with DBSCAN_OBS_LIVE=0 — health dicts stay backward-shaped).
    Emits the matching serve.windowed_* gauges and gives the throttled
    expo writer its poll."""
    import dbscan_tpu.obs as obs

    if not live.active():
        return {}
    p99 = live.quantile("serve.query_ms", 0.99)
    shed = live.window_total("serve.router.shed")
    routed = live.window_total("serve.router.routed")
    win = {
        "window_s": live.state().window_s,
        "windowed_p99_ms": p99,
        "windowed_qps": live.rate("serve.router.routed")
        + live.rate("serve.queries"),
        "windowed_shed_frac": (
            shed / (shed + routed) if (shed + routed) > 0 else 0.0
        ),
        "slo_alerting": get_engine().alerting(),
    }
    if p99 is not None:
        obs.gauge("serve.windowed_p99_ms", p99)
    obs.gauge("serve.windowed_qps", win["windowed_qps"])
    obs.gauge("serve.windowed_shed_frac", win["windowed_shed_frac"])
    expo = live.expo_path()
    if expo:
        win["expo"] = expo
        live.maybe_write_expo()
    maybe_evaluate()
    return {"windowed": win}


def maybe_evaluate() -> Optional[list]:
    """Throttled evaluation for the serving hot paths (router record,
    snapshot publish, health polls): at most one pass per
    DBSCAN_SLO_EVAL_PERIOD_S, and a single module-global check when
    the live plane is off."""
    global _eval_t_last
    if live._state is None:
        return None
    now = time.monotonic()
    period = float(config.env("DBSCAN_SLO_EVAL_PERIOD_S"))
    if now - _eval_t_last < period:
        return None
    with _engine_lock:
        _tsan.access("obs.slo_engine")
        if now - _eval_t_last < period:
            return None
        _eval_t_last = now
    return get_engine().evaluate()
