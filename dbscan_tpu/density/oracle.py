"""Exact pure-NumPy host HDBSCAN*/OPTICS oracle.

The parity reference for the device density engine
(``dbscan_tpu/density``) and the degradation target for persistent
device faults — the same role ``embed/oracle.py`` plays for the cosine
engine. Everything here is f64 host math over the full pairwise
matrix, so it is O(n^2) memory and capped at
``DBSCAN_DENSITY_ORACLE_MAX`` rows by the callers.

Semantics (Campello/Moulavi/Sander HDBSCAN*, the scikit-learn-contrib
``hdbscan`` reference implementation):

- core distance ``core(p)`` = distance to the ``min_pts``-th nearest
  neighbor, SELF-INCLUSIVE (``min_pts = 1`` makes every core distance
  0);
- mutual reachability ``mr(a, b) = max(core(a), core(b), d(a, b))``;
- the MST of the mutual-reachability graph under the TOTAL edge order
  ``(w, min(u, v), max(u, v))`` — the lexicographic tie-break makes
  the MST unique, which is what lets the device Borůvka pass and this
  Kruskal pass agree edge-for-edge (PARITY.md "Variable-density
  contract");
- single-linkage dendrogram from the MST edges sorted under the same
  total order, condensed with ``min_cluster_size`` pruning, and
  excess-of-mass (EOM) stability selection with
  ``allow_single_cluster=False`` (the root is never a cluster);
- labels renumbered by the canonical min-member-row contract from
  PR 8 (``embed.oracle.canonical_ids``): clusters are 1..K ordered by
  smallest member row, noise is 0.

OPTICS is defined here (and in PARITY.md) as the Prim traversal of the
mutual-reachability MST from row 0 with the same ``(w, min, max)``
tie-break: because the MST is unique under the total order, Prim on
the MST visits vertices in the same order as Prim on the full graph,
and the attaching edge weight IS the point's reachability distance
(inf for the start row). That gives the reachability plot the device
pass reproduces exactly from its own sorted-MST output.

Cross-check: when the scikit-learn-contrib ``hdbscan`` package is
importable, tests/test_density.py compares this oracle's labels
against it (skip-marked otherwise — no new hard dependency).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Tuple

import numpy as np

from dbscan_tpu.embed.oracle import canonical_ids  # noqa: F401 (re-export)

#: host-oracle cap fallback (the callers consult the
#: ``DBSCAN_DENSITY_ORACLE_MAX`` knob; this mirrors its default so the
#: oracle is usable standalone)
ORACLE_MAX_POINTS = 100_000


def pairwise_dists(x: np.ndarray, metric: str) -> np.ndarray:
    """Full [n, n] f64 distance matrix with an exact-zero diagonal.

    ``euclidean``: plain L2 over all columns. ``cosine``: chord-style
    ``1 - <u, v>`` over L2-normalized rows (zero rows stay at
    similarity 0 — distance 1 — to everything, the embed engine's
    convention). The diagonal is forced to exactly 0 either way so the
    self-inclusive core-distance rank never depends on rounding."""
    x = np.asarray(x, dtype=np.float64)
    if metric == "euclidean":
        sq = np.einsum("ij,ij->i", x, x)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
        d = np.sqrt(np.maximum(d2, 0.0))
    elif metric == "cosine":
        norms = np.sqrt(np.einsum("ij,ij->i", x, x))
        inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-300), 0.0)
        unit = x * inv[:, None]
        d = 1.0 - unit @ unit.T
        np.clip(d, 0.0, None, out=d)
    else:
        raise ValueError(f"unknown metric {metric!r}")
    np.fill_diagonal(d, 0.0)
    return d


def core_distances(dists: np.ndarray, min_pts: int) -> np.ndarray:
    """Self-inclusive k-th-NN core distance per row (k = min_pts)."""
    n = len(dists)
    k = min(int(min_pts), n)
    if k <= 1:
        return np.zeros(n, dtype=np.float64)
    part = np.partition(dists, k - 1, axis=1)
    return part[:, k - 1].copy()


def mutual_reachability(dists: np.ndarray, core: np.ndarray) -> np.ndarray:
    """``mr(a, b) = max(core(a), core(b), d(a, b))`` with a 0 diagonal
    (self-reachability never participates in the MST)."""
    mr = np.maximum(dists, np.maximum(core[:, None], core[None, :]))
    np.fill_diagonal(mr, 0.0)
    return mr


def mst_edges(mr: np.ndarray) -> np.ndarray:
    """The unique MST of the full mutual-reachability graph under the
    ``(w, min(u, v), max(u, v))`` total order.

    Kruskal over all n*(n-1)/2 undirected edges lexsorted by that key;
    returns an [n-1, 3] f64 array of ``(u, v, w)`` rows, themselves in
    the total order (u < v per row). O(n^2 log n) host work — oracle
    territory, cap enforced by callers."""
    n = len(mr)
    if n <= 1:
        return np.empty((0, 3), dtype=np.float64)
    iu, iv = np.triu_indices(n, k=1)
    w = mr[iu, iv]
    order = np.lexsort((iv, iu, w))
    iu, iv, w = iu[order], iv[order], w[order]
    parent = np.arange(n, dtype=np.int64)

    def find(a: int) -> int:
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    out = np.empty((n - 1, 3), dtype=np.float64)
    got = 0
    for u, v, wt in zip(iu, iv, w):
        ru, rv = find(int(u)), find(int(v))
        if ru == rv:
            continue
        parent[rv] = ru
        out[got] = (u, v, wt)
        got += 1
        if got == n - 1:
            break
    assert got == n - 1, "mutual-reachability graph must be connected"
    return out


def single_linkage(
    edges: np.ndarray, n: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Dendrogram from MST edges ALREADY in the total order.

    Returns ``(left, right, weight, size)``: internal node ``n + t``
    merges dendrogram nodes ``left[t]`` and ``right[t]`` at distance
    ``weight[t]``; ``size[node]`` counts leaves under any node id. The
    merge ORDER is the sorted-edge order, so equal-weight merges are
    deterministic — the device condense pass sorts with the same key
    and builds the identical tree."""
    left = np.empty(max(n - 1, 0), dtype=np.int64)
    right = np.empty(max(n - 1, 0), dtype=np.int64)
    weight = np.empty(max(n - 1, 0), dtype=np.float64)
    size = np.ones(2 * n - 1 if n else 0, dtype=np.int64)
    parent = np.arange(n, dtype=np.int64)
    node_of = np.arange(n, dtype=np.int64)

    def find(a: int) -> int:
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    for t in range(len(edges)):
        u, v, wt = int(edges[t, 0]), int(edges[t, 1]), float(edges[t, 2])
        ru, rv = find(u), find(v)
        node = n + t
        left[t] = node_of[ru]
        right[t] = node_of[rv]
        weight[t] = wt
        size[node] = size[node_of[ru]] + size[node_of[rv]]
        parent[rv] = ru
        node_of[ru] = node
    return left, right, weight, size


def condense_tree(
    left: np.ndarray,
    right: np.ndarray,
    weight: np.ndarray,
    size: np.ndarray,
    n: int,
    min_cluster_size: int,
) -> List[Tuple[int, int, float, int]]:
    """Condensed tree: rows ``(parent, child, lambda, child_size)``.

    Points keep ids 0..n-1; condensed clusters number from ``n`` (the
    root) upward in discovery order — the scikit-learn-contrib
    reference algorithm verbatim: a split where both sides reach
    ``min_cluster_size`` creates two new clusters; a side below it
    sheds its points at the split's lambda while the big side keeps
    the parent's identity."""
    if n == 0:
        return []
    if n == 1:
        return []
    root = 2 * n - 2
    mcs = max(int(min_cluster_size), 2)

    def children(node: int) -> Tuple[int, int]:
        t = node - n
        return int(left[t]), int(right[t])

    def bfs(node: int) -> List[int]:
        out, frontier = [], [node]
        while frontier:
            out.extend(frontier)
            frontier = [
                c
                for f in frontier
                if f >= n
                for c in children(f)
            ]
        return out

    relabel: Dict[int, int] = {root: n}
    next_label = n + 1
    ignore = np.zeros(2 * n - 1, dtype=bool)
    rows: List[Tuple[int, int, float, int]] = []
    for node in bfs(root):
        if node < n or ignore[node]:
            continue
        lnode, rnode = children(node)
        dist = float(weight[node - n])
        lam = 1.0 / dist if dist > 0.0 else np.inf
        lc, rc = int(size[lnode]), int(size[rnode])
        lab = relabel[node]
        if lc >= mcs and rc >= mcs:
            relabel[lnode] = next_label
            rows.append((lab, next_label, lam, lc))
            next_label += 1
            relabel[rnode] = next_label
            rows.append((lab, next_label, lam, rc))
            next_label += 1
        elif lc < mcs and rc < mcs:
            for sub in bfs(lnode):
                if sub < n:
                    rows.append((lab, sub, lam, 1))
                ignore[sub] = True
            for sub in bfs(rnode):
                if sub < n:
                    rows.append((lab, sub, lam, 1))
                ignore[sub] = True
        elif lc < mcs:
            relabel[rnode] = lab
            for sub in bfs(lnode):
                if sub < n:
                    rows.append((lab, sub, lam, 1))
                ignore[sub] = True
        else:
            relabel[lnode] = lab
            for sub in bfs(rnode):
                if sub < n:
                    rows.append((lab, sub, lam, 1))
                ignore[sub] = True
    return rows


def eom_select(
    rows: List[Tuple[int, int, float, int]], n: int
) -> Tuple[set, Dict[int, int]]:
    """Excess-of-mass cluster selection (``allow_single_cluster=False``).

    Returns ``(selected cluster ids, child -> parent over condensed
    CLUSTERS)``. Stability(c) = sum over c's condensed rows of
    ``(lambda_row - lambda_birth(c)) * child_size``; processing
    clusters bottom-up, a cluster beats its children when its own
    stability is >= the sum of theirs, in which case its whole
    descendant subtree is deselected. The root (id ``n``) is excluded
    outright."""
    if not rows:
        return set(), {}
    birth: Dict[int, float] = {}
    stability: Dict[int, float] = {}
    cluster_parent: Dict[int, int] = {}
    cluster_children: Dict[int, List[int]] = {}
    for parent, child, lam, _sz in rows:
        if child >= n:
            birth[child] = lam
            cluster_parent[child] = parent
            cluster_children.setdefault(parent, []).append(child)
    birth[n] = 0.0
    for parent, _child, lam, sz in rows:
        b = birth[parent]
        contrib = (lam - b) * sz if np.isfinite(lam) else 0.0
        stability[parent] = stability.get(parent, 0.0) + contrib
    for c in birth:
        stability.setdefault(c, 0.0)
    is_cluster = {c: True for c in birth if c != n}
    for node in sorted(is_cluster, reverse=True):
        kids = cluster_children.get(node, [])
        child_sum = sum(stability[k] for k in kids)
        if stability[node] < child_sum and kids:
            is_cluster[node] = False
            stability[node] = child_sum
        else:
            # node wins: deselect every descendant cluster
            frontier = list(kids)
            while frontier:
                k = frontier.pop()
                is_cluster[k] = False
                frontier.extend(cluster_children.get(k, []))
    selected = {c for c, keep in is_cluster.items() if keep}
    return selected, cluster_parent


def labels_from_tree(
    rows: List[Tuple[int, int, float, int]], n: int
) -> np.ndarray:
    """Point labels via EOM selection: each point maps to the nearest
    selected ancestor of the condensed cluster it fell out of, else
    noise. Returns RAW selected-cluster ids (>= n) with -1 noise; the
    callers canonicalize."""
    out = np.full(n, -1, dtype=np.int64)
    if not rows:
        return out
    selected, cluster_parent = eom_select(rows, n)
    resolve: Dict[int, int] = {}

    def nearest_selected(c: int) -> int:
        chain = []
        cur = c
        while cur not in resolve:
            if cur in selected:
                resolve[cur] = cur
                break
            if cur == n or cur not in cluster_parent:
                resolve[cur] = -1
                break
            chain.append(cur)
            cur = cluster_parent[cur]
        got = resolve[cur] if cur in resolve else -1
        for link in chain:
            resolve[link] = got
        return got

    for parent, child, _lam, _sz in rows:
        if child < n:
            out[child] = nearest_selected(parent)
    return out


def hdbscan_labels(
    pts: np.ndarray,
    min_pts: int,
    min_cluster_size: int,
    metric: str = "euclidean",
) -> np.ndarray:
    """Canonical HDBSCAN* labels: [n] int32, clusters 1..K by smallest
    member row, 0 noise — the full oracle pipeline in one call."""
    pts = np.asarray(pts, dtype=np.float64)
    n = len(pts)
    if n == 0:
        return np.empty(0, dtype=np.int32)
    if n == 1:
        return np.zeros(1, dtype=np.int32)
    d = pairwise_dists(pts, metric)
    core = core_distances(d, min_pts)
    mr = mutual_reachability(d, core)
    edges = mst_edges(mr)
    raw = labels_from_mst(edges, n, min_cluster_size)
    return canonical_raw(raw)


def labels_from_mst(
    edges: np.ndarray, n: int, min_cluster_size: int
) -> np.ndarray:
    """RAW labels (selected-cluster ids, -1 noise) from total-ordered
    MST edges — the shared back half of :func:`hdbscan_labels`, also
    used by tests to process device-produced MSTs through the oracle's
    condense machinery."""
    left, right, weight, size = single_linkage(edges, n)
    rows = condense_tree(left, right, weight, size, n, min_cluster_size)
    return labels_from_tree(rows, n)


def canonical_raw(raw: np.ndarray) -> np.ndarray:
    """Canonical renumbering of raw labels (-1 noise): clusters become
    1..K ordered by smallest member row, noise 0 — the PR 8 contract
    (same renumbering ``embed.oracle.canonical_ids`` applies to seed
    labels)."""
    n = len(raw)
    out = np.zeros(n, dtype=np.int32)
    seen: Dict[int, int] = {}
    nxt = 1
    for i in range(n):
        r = int(raw[i])
        if r < 0:
            continue
        if r not in seen:
            seen[r] = nxt
            nxt += 1
        out[i] = seen[r]
    return out


def optics_order(
    edges: np.ndarray, n: int
) -> Tuple[np.ndarray, np.ndarray]:
    """OPTICS ordering + reachability from total-ordered MST edges.

    Prim traversal of the (unique) mutual-reachability MST starting at
    row 0, frontier keyed by ``(w, min(u, v), max(u, v))`` — the same
    total order everywhere. Returns ``(order [n] int64, reach [n]
    f64)`` with ``reach[order[0]] = inf``. Both the oracle and the
    device engine derive OPTICS through this function, so parity is
    structural; its INPUT edges are what the two sides must agree on."""
    order = np.empty(n, dtype=np.int64)
    reach = np.full(n, np.inf, dtype=np.float64)
    if n == 0:
        return order, reach
    adj: Dict[int, List[Tuple[int, float]]] = {i: [] for i in range(n)}
    for u, v, w in edges:
        adj[int(u)].append((int(v), float(w)))
        adj[int(v)].append((int(u), float(w)))
    visited = np.zeros(n, dtype=bool)
    heap: List[Tuple[float, int, int, int]] = [(-np.inf, -1, -1, 0)]
    got = 0
    while heap:
        w, _a, _b, node = heapq.heappop(heap)
        if visited[node]:
            continue
        visited[node] = True
        order[got] = node
        reach[node] = np.inf if got == 0 else w
        got += 1
        for nbr, wt in adj[node]:
            if not visited[nbr]:
                heapq.heappush(
                    heap, (wt, min(node, nbr), max(node, nbr), nbr)
                )
    assert got == n, "MST must span all rows"
    return order, reach


def optics_oracle(
    pts: np.ndarray, min_pts: int, metric: str = "euclidean"
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Full OPTICS oracle: ``(order, reach, core)`` f64 host arrays."""
    pts = np.asarray(pts, dtype=np.float64)
    n = len(pts)
    if n == 0:
        return (
            np.empty(0, np.int64),
            np.empty(0, np.float64),
            np.empty(0, np.float64),
        )
    d = pairwise_dists(pts, metric)
    core = core_distances(d, min_pts)
    if n == 1:
        return np.zeros(1, np.int64), np.full(1, np.inf), core
    edges = mst_edges(mutual_reachability(d, core))
    order, reach = optics_order(edges, n)
    return order, reach, core
