"""Device core distances (stage 1 of the density engine) and the
k-distance statistics they yield.

``core(p)`` = distance to the ``min_pts``-th nearest neighbor,
self-inclusive — the mutual-reachability ingredient. The payload lives
on device once ([n_pad, d] f32, ladder-padded) and the packing window
walks it in fixed-size chunks: one ``density.core`` dispatch per chunk
(``DBSCAN_DENSITY_CHUNK`` rows), each a [chunk, n_pad] blocked
distance slab + ``lax.top_k`` k-th-smallest reduction, supervised at
the ``density_core`` fault site. The chunk start rides as a TRACED
0-d int32 so every chunk — and every same-shaped later run — reuses
one compiled kernel (the zero-retrace pin).

Metric legs mirror the package's two exact engines:

- ``euclidean`` (the 2-D banded leg): unrolled per-coordinate
  difference form ``sum_j (x_ij - x_kj)^2`` then sqrt — elementwise
  f32, which makes the numpy host fallback BITWISE identical, so a
  ``density_core`` persistent fault on the 2-D leg cannot move a
  label;
- ``cosine`` (the embed leg): ``1 - rows @ x.T`` over pre-normalized
  rows, the embed neighbor slab's similarity form (f32 matmul — the
  host fallback agrees to f32 matmul rounding, documented in
  PARITY.md).

Self-distance is forced to exactly 0 on both legs (diagonal mask), so
the self-inclusive rank never depends on rounding.

:func:`auto_eps` is the satellite consumer: the per-partition
``eps="auto"`` probe for plain DBSCAN — a capped deterministic
subsample split into coordinate strips (the partition proxy), each
strip's sorted k-distance curve kneed by max chord distance, eps =
the median strip knee. The per-strip statistics are stamped into the
caller's ``stats`` for the ROADMAP item-3 planner probe.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.obs import compile as obs_compile

#: metrics the density engine accepts (the two exact device legs)
METRICS = ("euclidean", "cosine")


def chunk_rows(n_pad: int) -> int:
    """The packing-window chunk width: ``DBSCAN_DENSITY_CHUNK``
    clamped to the padded payload (a short payload is one chunk)."""
    c = int(config.env("DBSCAN_DENSITY_CHUNK"))
    return max(1, min(c, n_pad))


@functools.lru_cache(maxsize=32)
def _core_fn(n_pad: int, d: int, c: int, k: int, metric: str):
    """One compiled chunk kernel per (n_pad, d, chunk, k, metric):
    f32 [c, n_pad] distance slab -> k-th-smallest per row."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def fn(x, mask, start):
        rows = lax.dynamic_slice(x, (start, jnp.int32(0)), (c, d))
        if metric == "euclidean":
            d2 = jnp.zeros((c, n_pad), dtype=jnp.float32)
            for j in range(d):
                diff = rows[:, j][:, None] - x[:, j][None, :]
                d2 = d2 + diff * diff
            dist = jnp.sqrt(d2)
        else:
            dist = jnp.float32(1.0) - rows @ x.T
            dist = jnp.maximum(dist, jnp.float32(0.0))
        col = jnp.arange(n_pad, dtype=jnp.int32)
        ridx = start + jnp.arange(c, dtype=jnp.int32)
        dist = jnp.where(
            col[None, :] == ridx[:, None], jnp.float32(0.0), dist
        )
        dist = jnp.where(mask[None, :], dist, jnp.float32(jnp.inf))
        kth = -lax.top_k(-dist, k)[0][:, k - 1]
        rmask = lax.dynamic_slice(mask, (start,), (c,))
        return jnp.where(rmask, kth, jnp.float32(0.0))

    return fn


def _host_chunk(
    x: np.ndarray, mask: np.ndarray, start: int, c: int, k: int, metric: str
) -> np.ndarray:
    """Numpy mirror of one chunk — the ``density_core`` persistent-
    fault degradation. Same f32 expression order as the kernel: on the
    euclidean leg the result is bitwise identical; on the cosine leg
    it agrees to f32-matmul rounding."""
    n_pad = len(x)
    rows = x[start : start + c]
    if metric == "euclidean":
        d2 = np.zeros((c, n_pad), dtype=np.float32)
        for j in range(x.shape[1]):
            diff = rows[:, j][:, None] - x[:, j][None, :]
            d2 += diff * diff
        dist = np.sqrt(d2)
    else:
        dist = np.float32(1.0) - rows @ x.T
        np.maximum(dist, np.float32(0.0), out=dist)
    col = np.arange(n_pad, dtype=np.int32)
    ridx = start + np.arange(c, dtype=np.int32)
    dist[col[None, :] == ridx[:, None]] = np.float32(0.0)
    dist = np.where(mask[None, :], dist, np.float32(np.inf))
    kth = np.partition(dist, k - 1, axis=1)[:, k - 1]
    return np.where(mask[start : start + c], kth, np.float32(0.0)).astype(
        np.float32
    )


def device_core(
    x_dev,
    mask_dev,
    x_host: np.ndarray,
    mask_host: np.ndarray,
    min_pts: int,
    metric: str,
    pull_pipe=None,
    oracle_fallback: bool = True,
) -> np.ndarray:
    """Core distances over a device-resident padded payload.

    ``x_dev``/``mask_dev``: the [n_pad, d] f32 / [n_pad] bool device
    arrays (put once by the engine); ``x_host``/``mask_host``: their
    host twins, consumed only by the per-chunk fault fallback. Returns
    the [n_pad] f32 host core-distance vector (0 at padding rows).
    One supervised ``density.core`` dispatch per chunk; chunk pulls
    ride the PullEngine when live so D2H overlaps later chunks."""
    import jax
    import jax.numpy as jnp

    n_pad, d = x_host.shape
    n_live = int(mask_host.sum())
    k = max(1, min(int(min_pts), max(n_live, 1)))
    c = chunk_rows(n_pad)
    fn = _core_fn(n_pad, d, c, k, metric)
    out = np.zeros(n_pad, dtype=np.float32)
    starts = list(range(0, n_pad, c))
    if starts and starts[-1] + c > n_pad:
        starts[-1] = n_pad - c

    def _land(start: int, res) -> None:
        if isinstance(res, np.ndarray):
            chunk = res  # host-fallback path
        else:
            chunk = np.asarray(jax.device_get(res))
            obs.count("transfer.d2h_bytes", int(chunk.nbytes))
        out[start : start + c] = chunk

    jobs = []
    try:
        for start in starts:
            obs.count("density.core_dispatches")
            fallback = (
                functools.partial(
                    _host_chunk, x_host, mask_host, start, c, k, metric
                )
                if oracle_fallback
                else None
            )
            with obs.span("density.core_chunk", start=start, c=c):
                res = faults.supervised(
                    faults.SITE_DENSITY_CORE,
                    lambda _budget: obs_compile.tracked_call(
                        "density.core",
                        fn,
                        x_dev,
                        mask_dev,
                        jnp.int32(start),
                    ),
                    fallback=fallback,
                    label=f"chunk@{start}",
                )
            if pull_pipe is not None:
                work = functools.partial(_land, start, res)
                jobs.append((pull_pipe.submit(
                    work, bytes_hint=c * 4, label=f"core@{start}"
                ), work))
            else:
                _land(start, res)
    except BaseException:
        # orphan-drain (the embed/spill discipline): submitted pulls
        # must not outlive a failing dispatch loop — they write into
        # `out`, which this frame is about to drop
        for job, _work in jobs:
            try:
                pull_pipe.wait(job)
            except Exception:  # noqa: BLE001 — already failing
                pass
        raise
    for job, work in jobs:
        pull_pipe.settle(job, work)
    return out


# --- eps="auto" probe (plain-DBSCAN satellite) -------------------------


def knee_index(curve: np.ndarray) -> int:
    """Knee of an ascending curve by max distance to the chord from
    its first to its last sample (the classic k-distance elbow pick,
    deterministic; flat curves knee at their midpoint)."""
    m = len(curve)
    if m <= 2:
        return m - 1 if m else 0
    y = np.asarray(curve, dtype=np.float64)
    x = np.arange(m, dtype=np.float64)
    dx, dy = x[-1] - x[0], y[-1] - y[0]
    norm = float(np.hypot(dx, dy))
    if norm == 0.0:
        return (m - 1) // 2
    # perpendicular distance from each sample to the chord
    dist = np.abs(dy * (x - x[0]) - dx * (y - y[0])) / norm
    return int(np.argmax(dist))


def auto_eps(
    pts: np.ndarray,
    min_pts: int,
    stats_out: Optional[dict] = None,
) -> float:
    """Per-partition eps auto-select for plain 2-D DBSCAN.

    A deterministic evenly-strided subsample (cap
    ``DBSCAN_DENSITY_AUTO_SAMPLE``) is split into
    ``DBSCAN_DENSITY_AUTO_PARTS`` x-sorted strips — the probe's
    stand-in for the driver's spatial partitions — and each strip's
    sorted core-distance curve (the k-distance curve, k = min_pts,
    via the SAME ``density.core`` dispatches) is kneed; eps is the
    median strip knee. Stamps per-strip statistics into ``stats_out``
    under ``eps_auto`` for the planner probe."""
    from dbscan_tpu.parallel.binning import _ladder_width
    from dbscan_tpu.parallel import pipeline as pipe_mod

    pts = np.asarray(pts, dtype=np.float64)[:, :2]
    n = len(pts)
    if n < 2:
        raise ValueError(f"eps='auto' needs >= 2 points, got {n}")
    cap = max(int(config.env("DBSCAN_DENSITY_AUTO_SAMPLE")), 2)
    stride = max(1, int(np.ceil(n / cap)))
    sample = pts[::stride]
    parts = max(1, int(config.env("DBSCAN_DENSITY_AUTO_PARTS")))
    parts = min(parts, max(1, len(sample) // max(2, int(min_pts))))
    order = np.argsort(sample[:, 0], kind="stable")
    strips = np.array_split(order, parts)
    pull_pipe = pipe_mod.get_engine()
    knees = []
    sizes = []
    with obs.span("density.auto_eps", n=int(n), parts=int(parts)):
        import jax.numpy as jnp

        for strip in strips:
            sub = sample[strip]
            m = len(sub)
            if m < 2:
                continue
            n_pad = _ladder_width(m, 128)
            xh = np.zeros((n_pad, 2), dtype=np.float32)
            xh[:m] = sub
            maskh = np.zeros(n_pad, dtype=bool)
            maskh[:m] = True
            obs.count("transfer.h2d_bytes", int(xh.nbytes + maskh.nbytes))
            core = device_core(
                jnp.asarray(xh), jnp.asarray(maskh), xh, maskh,
                min_pts, "euclidean", pull_pipe,
            )[:m]
            curve = np.sort(core.astype(np.float64))
            knees.append(float(curve[knee_index(curve)]))
            sizes.append(m)
    if not knees:
        raise ValueError("eps='auto' probe produced no strips")
    eps = float(np.median(knees))
    if eps <= 0.0:
        # degenerate strips (all-duplicate rows): fall back to the
        # largest strip knee, and ultimately a tiny positive floor so
        # the driver's eps > 0 validation holds
        eps = max(max(knees), 1e-12)
    obs.gauge("density.eps_auto", eps)
    if stats_out is not None:
        stats_out["eps_auto"] = {
            "eps": eps,
            "k": int(min_pts),
            "sample": int(len(sample)),
            "strips": int(len(knees)),
            "strip_sizes": [int(s) for s in sizes],
            "strip_knees": [round(float(v), 9) for v in knees],
        }
    return eps
