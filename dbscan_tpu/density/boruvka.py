"""Device Borůvka MST over mutual-reachability edges (stage 2).

One ``density.boruvka`` dispatch per round, each a single compiled
kernel reused every round (and every same-shaped later run — the
zero-retrace pin): blocked [128, n_pad] mutual-reachability slabs pick
each point's cheapest OUTGOING candidate, a three-stage scatter-min
reduces candidates to one edge per live component, and the contraction
is the shared union-find propagation
(:func:`dbscan_tpu.ops.propagation.min_label_fixed_point` — the PR 15
single-pass structure) over the selected-edge graph of component
roots. Rounds are bounded by ceil(log2 n): every live component
selects an outgoing edge (the mutual-reachability graph is complete),
so components at least halve per round.

Edge uniqueness is the load-bearing invariant: candidates are ordered
by the TOTAL key ``(w, min(u, v), max(u, v))`` — within a row the
lowest-j argmin realizes it, across a component the three scatter-min
stages (min w, then min(u, v) among w-ties, then max(u, v)) finish
it — so the union of per-round selections IS the unique MST the host
oracle's Kruskal finds under the same order, and Borůvka's
data-dependent ROUND count can never move a label (PARITY.md
"Variable-density contract").

Per-round pulls are thin (the selected-edge vectors + two scalars)
and synchronous — the live-component count decides termination.
Components may pairwise-select the same undirected edge; the host
dedupes per round by (min, max) pair. The ``density_boruvka`` fault
site supervises every round; with no per-round fallback (the MST is
global state), a persistent fault raises
:class:`dbscan_tpu.faults.FatalDeviceFault` and the engine degrades
the WHOLE run to the host oracle — labels intact, the drill
tests/test_density.py pins.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import numpy as np

from dbscan_tpu import faults, obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.ops.labels import SEED_NONE
from dbscan_tpu.ops.propagation import min_label_fixed_point

#: row-block edge of the candidate scan (divides every ladder width)
BLK = 128


@functools.lru_cache(maxsize=32)
def _round_fn(n_pad: int, d: int, metric: str, mode: str):
    """One compiled Borůvka round per (n_pad, d, metric, prop-mode)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    nb = n_pad // BLK
    big = jnp.int32(n_pad)
    none = jnp.int32(SEED_NONE)
    inf = jnp.float32(jnp.inf)

    @jax.jit
    def fn(x, mask, core, comp):
        idx = jnp.arange(n_pad, dtype=jnp.int32)

        def block(bi):
            s = bi * jnp.int32(BLK)
            rows = lax.dynamic_slice(x, (s, jnp.int32(0)), (BLK, d))
            rcore = lax.dynamic_slice(core, (s,), (BLK,))
            rcomp = lax.dynamic_slice(comp, (s,), (BLK,))
            rmask = lax.dynamic_slice(mask, (s,), (BLK,))
            if metric == "euclidean":
                d2 = jnp.zeros((BLK, n_pad), dtype=jnp.float32)
                for j in range(d):
                    diff = rows[:, j][:, None] - x[:, j][None, :]
                    d2 = d2 + diff * diff
                dist = jnp.sqrt(d2)
            else:
                dist = jnp.float32(1.0) - rows @ x.T
                dist = jnp.maximum(dist, jnp.float32(0.0))
            mr = jnp.maximum(dist, jnp.maximum(rcore[:, None], core[None, :]))
            out_ok = (
                mask[None, :]
                & rmask[:, None]
                & (comp[None, :] != rcomp[:, None])
            )
            val = jnp.where(out_ok, mr, inf)
            # first-match argmin = lowest j among w-ties, which realizes
            # the (w, min(u,v), max(u,v)) total key within the row
            return jnp.min(val, axis=1), jnp.argmin(val, axis=1).astype(
                jnp.int32
            )

        w, j = lax.map(block, jnp.arange(nb, dtype=jnp.int32))
        w = w.reshape(n_pad)
        j = j.reshape(n_pad)
        validp = mask & jnp.isfinite(w)

        # three-stage scatter-min per component root: min w, then
        # min(u, v) among w-ties, then max(u, v) — the total order
        # without 64-bit key packing
        r = jnp.clip(comp, 0, n_pad - 1)
        a = jnp.minimum(idx, j)
        b = jnp.maximum(idx, j)
        best_w = jnp.full(n_pad, inf).at[r].min(jnp.where(validp, w, inf))
        tie1 = validp & (w == best_w[r])
        best_a = jnp.full(n_pad, big).at[r].min(jnp.where(tie1, a, big))
        tie2 = tie1 & (a == best_a[r])
        best_b = jnp.full(n_pad, big).at[r].min(jnp.where(tie2, b, big))
        tie3 = tie2 & (b == best_b[r])
        best_i = jnp.full(n_pad, big).at[r].min(jnp.where(tie3, idx, big))
        has = jnp.isfinite(best_w)
        safe_i = jnp.clip(best_i, 0, n_pad - 1)
        sel_j = j[safe_i]
        eu = jnp.where(has, safe_i, jnp.int32(-1))
        ev = jnp.where(has, sel_j, jnp.int32(-1))
        ew = jnp.where(has, best_w, jnp.float32(0.0))

        # contraction: selected edges link root slots; the shared
        # union-find propagation collapses each linked group to its
        # min root in a handful of pull+push+jump sweeps
        partner = comp[jnp.clip(sel_j, 0, n_pad - 1)]

        def neighbor_min(lab):
            # SYMMETRIC relaxation: pull the partner's label AND
            # scatter-min own labels onto partners. The selected-edge
            # graph is a pseudoforest (out-degree 1), so a pull-only
            # sweep would strand a group minimum sitting at a leaf —
            # nobody pulls FROM a leaf — splitting the group and
            # re-selecting its edges next round.
            pull = jnp.where(has, lab[jnp.clip(partner, 0, n_pad - 1)], none)
            push = (
                jnp.full(n_pad, none)
                .at[jnp.where(has, partner, big)]
                .min(jnp.where(has, lab, none), mode="drop")
            )
            return jnp.minimum(pull, push)

        def scatter_relax(lab):
            return lab.at[jnp.where(has, partner, big)].min(lab, mode="drop")

        root_map, iters = min_label_fixed_point(
            idx,
            neighbor_min,
            with_iters=True,
            mode=mode,
            scatter_relax=scatter_relax if mode == "unionfind" else None,
        )
        comp_new = root_map[r]
        n_live = jnp.sum(
            (mask & (comp_new == idx)).astype(jnp.int32), dtype=jnp.int32
        )
        return comp_new, eu, ev, ew, has, n_live, iters

    return fn


def boruvka_mst(
    x_dev,
    mask_dev,
    core_dev,
    n_pad: int,
    d: int,
    n: int,
    metric: str,
    mode: str,
    stats: Optional[dict] = None,
) -> Tuple[np.ndarray, int]:
    """The full device MST: [n-1, 3] f64 ``(u, v, w)`` edge rows in
    selection order (unsorted — stage 3 sorts), plus the round count.

    Raises :class:`faults.FatalDeviceFault` when a round persistently
    fails (the engine's whole-run oracle degrade) and RuntimeError if
    the rounds bound trips without convergence (a kernel bug, not a
    data condition — the mutual-reachability graph is complete)."""
    import jax
    import jax.numpy as jnp

    if n <= 1:
        if stats is not None:
            stats["boruvka_rounds"] = 0
        return np.empty((0, 3), dtype=np.float64), 0
    fn = _round_fn(n_pad, d, metric, mode)
    comp = jnp.arange(n_pad, dtype=jnp.int32)
    max_rounds = int(math.ceil(math.log2(max(n, 2)))) + 2
    ea: list = []
    eb: list = []
    ew_all: list = []
    rounds = 0
    sweeps = 0
    while rounds < max_rounds:
        obs.count("density.boruvka_dispatches")
        with obs.span("density.round", r=rounds):
            out = faults.supervised(
                faults.SITE_DENSITY_BORUVKA,
                lambda _budget: obs_compile.tracked_call(
                    "density.boruvka", fn, x_dev, mask_dev, core_dev, comp
                ),
                label=f"round{rounds}",
            )
        comp, eu, ev, ewv, has, n_live, iters = out
        rounds += 1
        obs.count("density.rounds")
        eu_h, ev_h, ew_h, has_h, live, it = jax.device_get(
            (eu, ev, ewv, has, n_live, iters)
        )
        obs.count(
            "transfer.d2h_bytes",
            int(
                np.asarray(eu_h).nbytes
                + np.asarray(ev_h).nbytes
                + np.asarray(ew_h).nbytes
                + np.asarray(has_h).nbytes
            ),
        )
        sweeps += int(it)
        sel = np.flatnonzero(np.asarray(has_h))
        if len(sel):
            a = np.minimum(eu_h[sel], ev_h[sel]).astype(np.int64)
            b = np.maximum(eu_h[sel], ev_h[sel]).astype(np.int64)
            # two components may select the same undirected edge
            # (the classic Borůvka 2-cycle): dedupe by (min, max) pair
            pair = a * np.int64(n_pad) + b
            _, first = np.unique(pair, return_index=True)
            ea.append(a[first])
            eb.append(b[first])
            ew_all.append(np.asarray(ew_h)[sel][first].astype(np.float64))
        if int(live) <= 1:
            break
    else:
        raise RuntimeError(
            f"boruvka failed to converge in {max_rounds} rounds "
            f"(n={n}) — component selection must halve per round"
        )
    if sweeps:
        from dbscan_tpu.ops import propagation as prop

        prop.note_sweeps(sweeps, mode)
    edges = np.empty((0, 3), dtype=np.float64)
    if ea:
        edges = np.column_stack(
            [np.concatenate(ea), np.concatenate(eb), np.concatenate(ew_all)]
        ).astype(np.float64)
    if len(edges) != n - 1:
        raise RuntimeError(
            f"boruvka produced {len(edges)} edges for n={n} "
            "(expected n-1: the mutual-reachability graph is complete)"
        )
    obs.count("density.edges", int(len(edges)))
    if stats is not None:
        stats["boruvka_rounds"] = rounds
        stats["boruvka_sweeps"] = sweeps
    return edges, rounds
