"""Condensed-tree extraction (stage 3): device edge sort + lambda
prefix, one thin PullEngine pull, and a single-sweep host build.

The device ``density.condense`` dispatch lexsorts the MST edges by the
total key ``(w, min(u, v), max(u, v))`` — the SAME order the oracle's
Kruskal consumed, so merge order is pinned even among equal-weight
edges — computes the lambda transform ``1/w`` and the valid-edge
prefix count (the compaction), all on the padded edge ladder. The
sorted arrays come back through ONE PullEngine pull (the final-labels
ride) and the host finishes with a single ascending sweep.

The sweep is the bottom-up dual of the reference top-down condense
(scikit-learn-contrib ``hdbscan`` ``_condense_tree`` +
``compute_stability`` + EOM ``get_clusters``), one union-find pass
over the sorted merges:

- a component below ``min_cluster_size`` keeps its points PENDING;
- when a pending component reaches the threshold (or merges into a
  component that already has), every pending point sheds at the
  current merge's lambda into that component's cluster entity — the
  condensed tree's point rows;
- when two at-threshold entities merge, both CLOSE as children of a
  fresh parent entity (the condensed tree's cluster rows) and their
  excess-of-mass stability settles as
  ``sum(lambda_row * size) - lambda_close * sum(size)``;
- EOM selection then runs leaves-up over the entity tree (root
  excluded, ``allow_single_cluster=False``), and each point labels to
  the nearest selected ancestor of its shed entity, else noise.

``dbscan_tpu/density/oracle.py`` implements the same semantics
top-down (dendrogram, then condense, then select) — two independent
constructions whose label-for-label agreement tests/test_density.py
pins, with the ``hdbscan``-library cross-check on top when that
package is importable.

OPTICS falls out of the same pass: the sorted MST edges feed the
shared Prim traversal (:func:`dbscan_tpu.density.oracle.optics_order`)
— ordering parity with the oracle is then structural in the edge set.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from dbscan_tpu import obs
from dbscan_tpu.obs import compile as obs_compile
from dbscan_tpu.parallel.binning import _ladder_width


@functools.lru_cache(maxsize=32)
def _sort_fn(e_pad: int):
    """One compiled sort/compact kernel per edge-ladder width."""
    import jax
    import jax.numpy as jnp

    big = jnp.int32(2**30)
    inf = jnp.float32(jnp.inf)

    @jax.jit
    def fn(eu, ev, ew, valid):
        a = jnp.minimum(eu, ev)
        b = jnp.maximum(eu, ev)
        wkey = jnp.where(valid, ew, inf)
        akey = jnp.where(valid, a, big)
        bkey = jnp.where(valid, b, big)
        # lexsort: LAST key is primary -> (w, min(u,v), max(u,v)),
        # invalid (padding) rows sort to the tail
        perm = jnp.lexsort((bkey, akey, wkey))
        sw = ew[perm]
        lam = jnp.where(
            sw > jnp.float32(0.0), jnp.float32(1.0) / sw, inf
        )
        n_valid = jnp.sum(valid.astype(jnp.int32), dtype=jnp.int32)
        return a[perm], b[perm], sw, lam, valid[perm], n_valid

    return fn


def sorted_edges_device(
    edges: np.ndarray, pull_pipe=None
) -> Tuple[np.ndarray, np.ndarray]:
    """Sort [E, 3] MST edge rows on device under the total order.

    Returns ``(sorted [E, 3] f64 (u, v, w) rows, lam [E] f64)``; the
    pull rides the PullEngine when live. Padding to the 128-step edge
    ladder keeps the jit cache keyed by recurring widths."""
    import jax
    import jax.numpy as jnp

    e = len(edges)
    if e == 0:
        return np.empty((0, 3), dtype=np.float64), np.empty(0, np.float64)
    e_pad = _ladder_width(e, 128)
    eu = np.zeros(e_pad, dtype=np.int32)
    ev = np.zeros(e_pad, dtype=np.int32)
    ew = np.zeros(e_pad, dtype=np.float32)
    valid = np.zeros(e_pad, dtype=bool)
    eu[:e] = edges[:, 0].astype(np.int32)
    ev[:e] = edges[:, 1].astype(np.int32)
    ew[:e] = edges[:, 2].astype(np.float32)
    valid[:e] = True
    obs.count(
        "transfer.h2d_bytes",
        int(eu.nbytes + ev.nbytes + ew.nbytes + valid.nbytes),
    )
    fn = _sort_fn(e_pad)
    obs.count("density.condense_dispatches")
    with obs.span("density.condense", e=e):
        out = obs_compile.tracked_call(
            "density.condense",
            fn,
            jnp.asarray(eu),
            jnp.asarray(ev),
            jnp.asarray(ew),
            jnp.asarray(valid),
        )
    landed: dict = {}

    def _land() -> None:
        su, sv, sw, lam, sval, n_valid = jax.device_get(out)
        obs.count(
            "transfer.d2h_bytes",
            int(sum(np.asarray(v).nbytes for v in (su, sv, sw, lam, sval))),
        )
        landed["rows"] = (su, sv, sw, lam, sval, int(n_valid))

    if pull_pipe is not None:
        with obs.span("density.condense_pull", e=e):
            job = pull_pipe.submit(
                _land, bytes_hint=e_pad * 17, label="density.condense"
            )
            pull_pipe.settle(job, _land)
    else:
        _land()
    su, sv, sw, lam, sval, n_valid = landed["rows"]
    if n_valid != e:
        raise RuntimeError(
            f"condense compaction lost edges: {n_valid} valid of {e}"
        )
    out_rows = np.column_stack(
        [
            su[:e].astype(np.float64),
            sv[:e].astype(np.float64),
            sw[:e].astype(np.float64),
        ]
    )
    return out_rows, lam[:e].astype(np.float64)


class _Entity:
    """One condensed-tree cluster entity of the single-sweep build."""

    __slots__ = (
        "eid", "point_rows", "child_rows", "sum_ls", "sum_s",
        "stability", "parent", "children", "closed",
    )

    def __init__(self, eid: int):
        self.eid = eid
        self.point_rows: List[Tuple[int, float]] = []  # (point, lam)
        self.child_rows: List[Tuple[int, float, int]] = []
        self.sum_ls = 0.0  # sum(lam * size) over finite-lam rows
        self.sum_s = 0  # sum(size) over finite-lam rows
        self.stability = 0.0
        self.parent: Optional[int] = None
        self.children: List[int] = []
        self.closed = False

    def add_point(self, p: int, lam: float) -> None:
        self.point_rows.append((p, lam))
        if np.isfinite(lam):
            self.sum_ls += lam
            self.sum_s += 1

    def add_child(self, child: int, lam: float, size: int) -> None:
        self.child_rows.append((child, lam, size))
        if np.isfinite(lam):
            self.sum_ls += lam * size
            self.sum_s += size

    def close(self, birth_lam: float) -> None:
        self.stability = self.sum_ls - birth_lam * self.sum_s
        self.closed = True


def condense_labels(
    sorted_edges: np.ndarray,
    lam: np.ndarray,
    n: int,
    min_cluster_size: int,
) -> np.ndarray:
    """Single-sweep condensed-tree build + EOM labels over MST edges
    ALREADY in the total order. Returns RAW labels (entity ids, -1
    noise) — callers canonicalize (the PR 8 min-member-row contract)."""
    out = np.full(n, -1, dtype=np.int64)
    if n <= 1 or len(sorted_edges) == 0:
        return out
    mcs = max(int(min_cluster_size), 2)
    parent_uf = np.arange(n, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent_uf[root] != root:
            root = parent_uf[root]
        while parent_uf[x] != root:
            parent_uf[x], x = root, parent_uf[x]
        return root

    size = np.ones(n, dtype=np.int64)
    # per-root state: pending points (not yet shed) or a live entity
    pending: Dict[int, List[int]] = {i: [i] for i in range(n)}
    entity_of: Dict[int, int] = {}
    entities: Dict[int, _Entity] = {}
    close_order: List[int] = []
    next_eid = n

    def new_entity() -> _Entity:
        nonlocal next_eid
        ent = _Entity(next_eid)
        entities[next_eid] = ent
        next_eid += 1
        return ent

    for t in range(len(sorted_edges)):
        u, v = int(sorted_edges[t, 0]), int(sorted_edges[t, 1])
        lv = float(lam[t])
        ru, rv = find(u), find(v)
        su, sv = int(size[ru]), int(size[rv])
        eu, ev = entity_of.get(ru), entity_of.get(rv)
        # union (rv into ru), then settle the merged root's state
        parent_uf[rv] = ru
        size[ru] = su + sv
        if eu is not None and ev is not None:
            # cluster-cluster merge: both entities CLOSE as children
            # of a fresh parent born (bottom-up) at this lambda
            par = new_entity()
            for ent in (entities[eu], entities[ev]):
                ent.close(lv)
                ent.parent = par.eid
                par.children.append(ent.eid)
                close_order.append(ent.eid)
            par.add_child(eu, lv, su)
            par.add_child(ev, lv, sv)
            entity_of[ru] = par.eid
            entity_of.pop(rv, None)
        elif eu is not None or ev is not None:
            # one side already a cluster: the small pending side sheds
            # every point at this lambda into the continuing entity
            keep = eu if eu is not None else ev
            ent = entities[keep]
            small_root = rv if eu is not None else ru
            for p in pending.pop(small_root, []):
                ent.add_point(p, lv)
            entity_of[ru] = keep
            entity_of.pop(rv, None)
        else:
            merged = pending.pop(ru, []) + pending.pop(rv, [])
            if su + sv >= mcs:
                # the component reaches min_cluster_size: its entity
                # begins, and every pending point sheds HERE — the
                # top-down "children both too small" case
                ent = new_entity()
                for p in merged:
                    ent.add_point(p, lv)
                entity_of[ru] = ent.eid
            else:
                pending[ru] = merged
    # the final entity is the condensed root: close it with birth 0
    # (EOM excludes it regardless — allow_single_cluster=False)
    root_root = find(0)
    root_eid = entity_of.get(root_root)
    if root_eid is None:
        return out  # n < mcs: everything stayed pending -> all noise
    entities[root_eid].close(0.0)
    close_order.append(root_eid)

    # EOM selection leaves-up (closing order IS child-before-parent)
    wins: Dict[int, bool] = {}
    subtree: Dict[int, float] = {}
    for eid in close_order:
        ent = entities[eid]
        child_sum = sum(subtree[c] for c in ent.children)
        if eid == root_eid:
            wins[eid] = False
            subtree[eid] = child_sum
        elif ent.children and ent.stability < child_sum:
            wins[eid] = False
            subtree[eid] = child_sum
        else:
            wins[eid] = True
            subtree[eid] = ent.stability
    # final set: winners with no winning ancestor (top-down emit;
    # iterative — entity chains can be as deep as the merge count)
    selected: Dict[int, int] = {}
    stack = [(root_eid, -1)]
    while stack:
        eid, above = stack.pop()
        mine = above
        if wins[eid] and above < 0 and eid != root_eid:
            selected[eid] = eid
            mine = eid
        for c in entities[eid].children:
            stack.append((c, mine))

    # label each shed point to the nearest selected ancestor
    label_of: Dict[int, int] = {}

    def entity_label(eid: int) -> int:
        chain = []
        cur: Optional[int] = eid
        while cur is not None and cur not in label_of:
            if cur in selected:
                label_of[cur] = cur
                break
            chain.append(cur)
            cur = entities[cur].parent
        got = label_of.get(cur, -1) if cur is not None else -1
        for link in chain:
            label_of[link] = got
        return got

    for eid, ent in entities.items():
        lab = entity_label(eid)
        if lab < 0:
            continue
        for p, _plam in ent.point_rows:
            out[p] = lab
    return out
