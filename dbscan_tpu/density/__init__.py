"""dbscan_tpu/density — variable-density clustering (HDBSCAN*/OPTICS)
on the shared kernel stack.

Single-eps DBSCAN cannot label mixed-density payloads: any eps that
resolves the dense tenants dissolves the sparse ones into noise.
HDBSCAN* replaces the one global threshold with the full density
hierarchy — and every primitive it needs already exists in this repo,
which is what this engine family rides (ROADMAP item 4):

1. ``density.core`` (``density/core.py``): k-th-neighbor core
   distances, one dispatch per packing-window chunk over the
   device-resident payload — the 2-D euclidean leg mirrors the banded
   neighbor math, the cosine leg the embed similarity slabs;
2. ``density.boruvka`` (``density/boruvka.py``): device Borůvka MST
   over mutual-reachability edges — scatter-min cheapest-edge
   selection + union-find contraction via ``ops/propagation.py``, one
   dispatch per round, rounds <= ceil(log2 n);
3. ``density.condense`` (``density/condense.py``): device sort of the
   MST under the total edge order + lambda prefix, one thin PullEngine
   pull, then the single-sweep condensed-tree build with
   ``min_cluster_size`` pruning and excess-of-mass selection; OPTICS
   reachability ordering falls out of the same sorted-MST pass;
4. ``density/oracle.py``: the exact pure-NumPy host reference — the
   parity bar AND the persistent-fault degradation target.

Citizenship: the three dispatch families live in
``obs/schema.COMPILE_FAMILIES`` and ``lint/shapes.FAMILY_MODELS``
(shapecheck-validated live); the ``density_core``/``density_boruvka``
fault sites heal transients and degrade persistents (per-chunk host
fallback, whole-run oracle); ``DBSCAN_DENSITY_*`` knobs are declared
in ``config.ENV_VARS``; ``bench.py --hdbscan`` commits the gated
capture. Labels follow the canonical min-member-row contract from
PR 8: clusters 1..K by smallest member row, noise 0 — and match the
host oracle exactly (tests/test_density.py).
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Tuple

import numpy as np

from dbscan_tpu import config, faults, obs
from dbscan_tpu.density import boruvka as boruvka_mod
from dbscan_tpu.density import condense as condense_mod
from dbscan_tpu.density import core as core_mod
from dbscan_tpu.density import oracle as oracle_mod
from dbscan_tpu.parallel.binning import _ladder_width, _ratchet

logger = logging.getLogger(__name__)

__all__ = ["hdbscan", "optics", "auto_eps"]

#: monotone shape floors for the node/edge ladders — repeated runs of
#: nearby sizes reuse the SAME padded shapes (zero steady-state
#: compiles, the streaming ratchet discipline)
_SHAPE_FLOORS: dict = {}

#: re-export: the per-partition eps probe plain DBSCAN's ``eps="auto"``
#: rides (models/dbscan.py)
auto_eps = core_mod.auto_eps


def _oracle_cap() -> int:
    return int(config.env("DBSCAN_DENSITY_ORACLE_MAX"))


def _validate(pts, min_pts: int, metric: str) -> np.ndarray:
    if metric not in core_mod.METRICS:
        raise ValueError(
            f"unknown metric {metric!r}: one of {core_mod.METRICS}"
        )
    if int(min_pts) < 1:
        raise ValueError(f"min_pts must be >= 1, got {min_pts}")
    pts = np.asarray(pts)
    if pts.ndim != 2:
        raise ValueError(f"expected [N, D] points, got shape {pts.shape}")
    return pts


def _unit_payload(pts: np.ndarray, metric: str) -> np.ndarray:
    """The f32 device payload: raw coordinates (euclidean) or
    L2-normalized rows (cosine — zero rows stay zero, similarity 0 to
    everything, the embed convention the oracle mirrors)."""
    x32 = np.asarray(pts, dtype=np.float32)
    if metric == "euclidean":
        return x32
    norms = np.sqrt(np.einsum("ij,ij->i", x32, x32, dtype=np.float64))
    inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-30), 0.0)
    return x32 * inv.astype(np.float32)[:, None]


def _padded(unit32: np.ndarray, metric: str):
    """Ratcheted node ladder + (cosine) lane-padded width."""
    n, d = unit32.shape
    d_pad = d if metric == "euclidean" else _ladder_width(d, 8)
    n_pad = _ratchet(
        _SHAPE_FLOORS,
        ("n", metric, d_pad),
        _ladder_width(n, 128),
    )
    xh = np.zeros((n_pad, d_pad), dtype=np.float32)
    xh[:n, :d] = unit32
    maskh = np.zeros(n_pad, dtype=bool)
    maskh[:n] = True
    return xh, maskh


def _device_mst(
    unit32: np.ndarray, min_pts: int, metric: str, stats: dict
) -> Tuple[np.ndarray, np.ndarray]:
    """Stages 1+2 on device: returns ``(mst edges [n-1, 3] f64 in
    selection order, core [n] f32)``. Raises FatalDeviceFault on a
    persistent un-degradable fault for the caller's whole-run oracle
    degrade."""
    import jax.numpy as jnp

    from dbscan_tpu.ops.propagation import prop_mode
    from dbscan_tpu.parallel import pipeline as pipe_mod

    n = len(unit32)
    xh, maskh = _padded(unit32, metric)
    n_pad, d_pad = xh.shape
    x_dev = jnp.asarray(xh)
    mask_dev = jnp.asarray(maskh)
    obs.count("transfer.h2d_bytes", int(xh.nbytes + maskh.nbytes))
    pull_pipe = pipe_mod.get_engine()
    t0 = time.perf_counter()
    core = core_mod.device_core(
        x_dev, mask_dev, xh, maskh, min_pts, metric, pull_pipe,
        oracle_fallback=stats.get("_oracle_fallback", True),
    )
    stats["core_s"] = round(time.perf_counter() - t0, 6)
    stats["core_chunks"] = -(-n_pad // core_mod.chunk_rows(n_pad))
    t1 = time.perf_counter()
    core_dev = jnp.asarray(core)
    obs.count("transfer.h2d_bytes", int(core.nbytes))
    mode = prop_mode()
    edges, rounds = boruvka_mod.boruvka_mst(
        x_dev, mask_dev, core_dev, n_pad, d_pad, n, metric, mode, stats
    )
    stats["mst_s"] = round(time.perf_counter() - t1, 6)
    return edges, core[:n]


def hdbscan(
    pts: np.ndarray,
    min_pts: int = 5,
    min_cluster_size: Optional[int] = None,
    metric: str = "euclidean",
    stats_out: Optional[dict] = None,
    oracle_fallback: bool = True,
) -> np.ndarray:
    """HDBSCAN* labels over ``[N, D]`` points: [N] int32, clusters
    1..K by smallest member row (the canonical PR 8 contract), 0
    noise.

    ``min_pts`` sets the core-distance rank (self-inclusive);
    ``min_cluster_size`` (default ``min_pts``) prunes the condensed
    tree; ``metric`` is ``"euclidean"`` (the 2-D banded leg) or
    ``"cosine"`` (the embed leg, rows L2-normalized internally);
    ``oracle_fallback`` controls the persistent-fault degradations
    (per-chunk for ``density_core``, whole-run for
    ``density_boruvka``); ``stats_out`` receives run diagnostics
    (``boruvka_rounds``, ``core_chunks``, timings)."""
    pts = _validate(pts, min_pts, metric)
    mcs = int(min_cluster_size) if min_cluster_size is not None else int(
        min_pts
    )
    if mcs < 2:
        raise ValueError(f"min_cluster_size must be >= 2, got {mcs}")
    obs.ensure_env()
    n = len(pts)
    if n == 0:
        return np.empty(0, dtype=np.int32)
    if n == 1:
        return np.zeros(1, dtype=np.int32)
    obs.count("density.points", int(n))
    stats: dict = {"_oracle_fallback": oracle_fallback}
    t0 = time.perf_counter()
    unit32 = _unit_payload(pts, metric)
    with obs.span("density.run", n=int(n), metric=metric, kind="hdbscan"):
        try:
            edges, _core = _device_mst(unit32, min_pts, metric, stats)
        except faults.FatalDeviceFault:
            if not oracle_fallback or n > _oracle_cap():
                raise
            return _whole_run_oracle(
                unit32, min_pts, mcs, metric, stats_out, t0
            )
        t2 = time.perf_counter()
        from dbscan_tpu.parallel import pipeline as pipe_mod

        sorted_rows, lam = condense_mod.sorted_edges_device(
            edges, pipe_mod.get_engine()
        )
        raw = condense_mod.condense_labels(sorted_rows, lam, n, mcs)
        labels = oracle_mod.canonical_raw(raw)
        stats["condense_s"] = round(time.perf_counter() - t2, 6)
    _finish_stats(stats_out, stats, n, metric, t0)
    return labels


def optics(
    pts: np.ndarray,
    min_pts: int = 5,
    metric: str = "euclidean",
    stats_out: Optional[dict] = None,
    oracle_fallback: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """OPTICS over the mutual-reachability MST: ``(order [N] int64,
    reach [N] f64, core [N] f64)``.

    The ordering is the Prim traversal of the (unique, total-ordered)
    MST from row 0 — reachability is the attaching edge weight, inf at
    the start row (PARITY.md "Variable-density contract"). Exactly the
    oracle's definition, so order parity is structural in the edge
    set."""
    pts = _validate(pts, min_pts, metric)
    obs.ensure_env()
    n = len(pts)
    if n == 0:
        return (
            np.empty(0, np.int64),
            np.empty(0, np.float64),
            np.empty(0, np.float64),
        )
    obs.count("density.points", int(n))
    stats: dict = {"_oracle_fallback": oracle_fallback}
    t0 = time.perf_counter()
    unit32 = _unit_payload(pts, metric)
    if n == 1:
        core = np.zeros(1, np.float64)
        return np.zeros(1, np.int64), np.full(1, np.inf), core
    with obs.span("density.run", n=int(n), metric=metric, kind="optics"):
        try:
            edges, core32 = _device_mst(unit32, min_pts, metric, stats)
        except faults.FatalDeviceFault:
            if not oracle_fallback or n > _oracle_cap():
                raise
            obs.count("density.oracle_fallbacks")
            logger.warning(
                "density: device MST persistently failing; degrading "
                "the whole OPTICS run to the host oracle (%d points)", n
            )
            order, reach, core = oracle_mod.optics_oracle(
                np.asarray(unit32, dtype=np.float64), min_pts, metric
            )
            if stats_out is not None:
                stats_out.update(density_degraded="oracle")
            return order, reach, core
        t2 = time.perf_counter()
        from dbscan_tpu.parallel import pipeline as pipe_mod

        sorted_rows, _lam = condense_mod.sorted_edges_device(
            edges, pipe_mod.get_engine()
        )
        order, reach = oracle_mod.optics_order(sorted_rows, n)
        stats["condense_s"] = round(time.perf_counter() - t2, 6)
    _finish_stats(stats_out, stats, n, metric, t0)
    return order, reach, core32.astype(np.float64)


def _whole_run_oracle(unit32, min_pts, mcs, metric, stats_out, t0):
    """The ``density_boruvka`` persistent-fault degradation: the exact
    host oracle over the whole (capped) run — labels intact."""
    obs.count("density.oracle_fallbacks")
    logger.warning(
        "density: boruvka round persistently failing; degrading the "
        "whole run to the host oracle (%d points)", len(unit32)
    )
    labels = oracle_mod.hdbscan_labels(
        np.asarray(unit32, dtype=np.float64), min_pts, mcs, metric
    )
    if stats_out is not None:
        stats_out.update(
            density_degraded="oracle",
            timings={"total_s": round(time.perf_counter() - t0, 6)},
        )
    return labels


def _finish_stats(stats_out, stats, n, metric, t0):
    if stats_out is None:
        return
    stats.pop("_oracle_fallback", None)
    timings = {
        k: stats.pop(k)
        for k in ("core_s", "mst_s", "condense_s")
        if k in stats
    }
    timings["total_s"] = round(time.perf_counter() - t0, 6)
    stats_out.update(stats)
    stats_out.update(n=int(n), metric=metric, timings=timings)
