"""Shared min-label fixed-point harness for connected components.

Both local-engine backends (materialized XLA adjacency and streaming Pallas
sweeps) find connected components by the same iteration: masked neighbor-min
propagation plus one pointer jump per step inside ``lax.while_loop``. Only
the neighbor-min computation differs, so the convergence harness lives here
once. Invariants: labels only decrease; a core row's label is always a core
row index inside its own component and <= its own index; the fixed point is
the component minimum — the "seed index" (the fold index of the point that
would have seeded the cluster in the reference's sequential scan,
LocalDBSCANNaive.scala:45-64).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from dbscan_tpu.ops.labels import SEED_NONE

# Pointer jumps per neighbor-min sweep. A 1-D arbitrary-index gather on
# TPU runs at ~40M elements/s (scalar-loop lowering) — ~a third of a full
# neighbor-min sweep at bench densities — so extra jumps per sweep COST
# more than the sweeps they save (4 unrolled jumps: +64% device time;
# jump-to-convergence inner loop: +21%; both measured on v5e at 10M
# points). One jump (the classic pointer-doubling step) is the optimum.
_COMPRESS_JUMPS = 1


def min_label_fixed_point(
    init: jnp.ndarray,
    neighbor_min: Callable[[jnp.ndarray], jnp.ndarray],
    pos_of_label: jnp.ndarray | None = None,
    with_iters: bool = False,
) -> jnp.ndarray:
    """Iterate ``labels -> min(labels, neighbor_min(labels), hop)`` to a fixed
    point.

    init: [N] int32 starting labels (row index on active rows, SEED_NONE
      elsewhere).
    neighbor_min: labels -> [N] int32 per-row min of neighbor labels
      (SEED_NONE where no neighbor qualifies).
    pos_of_label: optional [N] int32 mapping a LABEL VALUE to the array
      position that carries it — for engines whose label values are not array
      positions (the banded engine labels by original fold index while its
      arrays live in cell-sorted order). None means values ARE positions.
    with_iters: also return the number of neighbor-min sweeps the loop ran
      (an int32 scalar, data-dependent) — the convergence-depth figure the
      device cellcc finalize reports as ``cellcc.cc_iters``.

    Each step runs one neighbor-min sweep (the expensive part — the
    backends recompute their masked distance tests inside it) followed by
    ``_COMPRESS_JUMPS`` pointer jumps (chain-collapsing ``new[new]``
    gathers), keeping iteration count O(log diameter) instead of
    O(diameter) for chain-shaped clusters — see the constant's comment for
    why more jumps per sweep do not pay on TPU.

    The loop is hard-capped at n iterations: labels strictly decrease while
    unconverged, so n steps always suffice — and the cap guarantees the
    on-device loop terminates even if a backend miscompiles the
    neighbor-min (an unbounded device loop wedges the whole chip for every
    client).
    """
    n = init.shape[0]
    none = jnp.int32(SEED_NONE)

    def pos(labels):
        safe = jnp.clip(labels, 0, n - 1)
        return pos_of_label[safe] if pos_of_label is not None else safe

    def compress(labels):
        for _ in range(_COMPRESS_JUMPS):
            hop = jnp.where(labels == none, none, labels[pos(labels)])
            labels = jnp.minimum(labels, hop)
        return labels

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def body(state):
        labels, _, it = state
        new = compress(jnp.minimum(labels, neighbor_min(labels)))
        return new, jnp.any(new != labels), it + 1

    # One unrolled body step first: the while_loop carry must be
    # data-derived ("varying") for shard_map, and a constant True init is
    # not; semantically free since body is idempotent at the fixed point.
    state = body((init, jnp.bool_(True), jnp.int32(0)))
    labels, _, iters = lax.while_loop(cond, body, state)
    if with_iters:
        return labels, iters
    return labels


def window_cc(
    adj_mask: jnp.ndarray,
    neighbor_tab: jnp.ndarray,
) -> tuple:
    """Connected components of a windowed adjacency table, on device.

    adj_mask: [N, W] bool — row i is adjacent to ``neighbor_tab[i, j]``
      where ``adj_mask[i, j]`` (the banded engine's per-cell OR of its
      core rows' 5x5-window bitmasks; callers must supply a SYMMETRIC
      relation — core-core eps-adjacency is, see ops/banded.py).
    neighbor_tab: [N, W] int32 neighbor index per window slot (junk at
      masked-off slots is fine; gathers are clipped, values masked).

    Returns ``(comp [N] int32, iters int32)``: per-row component-minimum
    row index (the same component sets scipy's connected_components
    finds on the host — component NUMBERING differs, the min-index
    representative does not) and the sweep count. This is the shared CC
    kernel of the device cellcc finalize (cellgraph.finalize_device);
    streaming micro-batches reuse it through the same driver path.
    """
    n = adj_mask.shape[0]
    none = jnp.int32(SEED_NONE)
    tab = jnp.clip(neighbor_tab, 0, n - 1)

    def neighbor_min(labels):
        return jnp.min(jnp.where(adj_mask, labels[tab], none), axis=1)

    return min_label_fixed_point(
        jnp.arange(n, dtype=jnp.int32), neighbor_min, with_iters=True
    )
