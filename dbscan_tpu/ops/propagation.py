"""Shared min-label fixed-point harness for connected components.

Both local-engine backends (materialized XLA adjacency and streaming Pallas
sweeps) find connected components by the same iteration: masked neighbor-min
propagation plus pointer jumping inside ``lax.while_loop``. Only the
neighbor-min computation differs, so the convergence harness lives here
once. Invariants: labels only decrease; a core row's label is always a core
row index inside its own component and <= its own index; the fixed point is
the component minimum — the "seed index" (the fold index of the point that
would have seeded the cluster in the reference's sequential scan,
LocalDBSCANNaive.scala:45-64).

Two propagation modes share the harness (``DBSCAN_PROP_UNIONFIND``):

- **iterated** (the original path, the parity oracle): one neighbor-min
  sweep + ONE pointer jump per step — O(log diameter) steps, each paying
  a full sweep (the expensive part: backends recompute their masked
  distance tests inside it).
- **unionfind** (default via ``auto``): the single-pass lock-free
  union-find structure of "Theoretically-Efficient and Practical
  Parallel DBSCAN" (arXiv:1912.06255) mapped onto the same monotone
  min-label lattice — each step runs the neighbor-min EDGE RELAXATION,
  a scatter-min push of the freshly relaxed labels back along the edges
  (pull-then-push = two hops per sweep on the symmetric relation), and
  ``_UF_JUMPS`` aggressive pointer-doubling jumps. Chains that cost the
  iterated path ~log2(diameter) sweeps collapse to a small constant.

The two modes reach the SAME fixed point — labels are a monotone
decreasing sequence bounded below by the component minimum, and any
label above it still has a decreasing edge/jump — so final labels are
byte-identical; only the gated sweep counts (``prop.sweeps``,
``cellcc.cc_iters``, ``halo.rounds``) move. PARITY.md "Propagation
contract" is the written form of this invariant.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax

from dbscan_tpu import config
from dbscan_tpu.ops.labels import SEED_NONE

# Pointer jumps per neighbor-min sweep on the ITERATED path. A 1-D
# arbitrary-index gather on TPU runs at ~40M elements/s (scalar-loop
# lowering) — ~a third of a full neighbor-min sweep at bench densities —
# so extra jumps per sweep COST more than the sweeps they save (4
# unrolled jumps: +64% device time; jump-to-convergence inner loop:
# +21%; both measured on v5e at 10M points). One jump (the classic
# pointer-doubling step) is the optimum for the POINT-graph engines that
# measurement covered.
_COMPRESS_JUMPS = 1

# Pointer jumps per sweep on the UNION-FIND path. The consumers that
# ride it (cell graph, halo node graph, embed window tables) are one to
# two orders of magnitude smaller than the point graphs the
# _COMPRESS_JUMPS measurement covered, so the jump gathers are cheap
# relative to the sweep they amortize — and each ELIMINATED sweep saves
# a full [N, W] relaxation pass. 4 jumps compress 16-hop chains per
# sweep; combined with the pull+push double hop, sweep counts collapse
# to a small constant (the arXiv:1912.06255 observation).
_UF_JUMPS = 4


def prop_mode(raw: Optional[str] = None) -> str:
    """Resolve ``DBSCAN_PROP_UNIONFIND`` (or an explicit ``raw``
    override) to ``"unionfind"`` | ``"iterated"``. ``auto`` routes to
    union-find: the sweep collapse is structural (it is what the gated
    ``*_prop_sweeps`` counts prove on any backend), and the iterated
    path stays one knob away as the parity oracle."""
    if raw is None:
        raw = str(config.env("DBSCAN_PROP_UNIONFIND") or "auto")
    raw = raw.strip().lower()
    if raw in ("0", "false", "off", "no", "iterated"):
        return "iterated"
    return "unionfind"


def note_sweeps(sweeps: int, mode: Optional[str] = None) -> None:
    """Host-side telemetry for one settled ``window_cc``-family fixed
    point: accumulate the data-dependent sweep count and publish the
    resolved mode (gauge 1.0 = unionfind, 0.0 = iterated) — the shared
    emission every consumer (cellcc finalize, halo merge, embed
    buckets) funnels its pulled iteration counts through, so leg-1's
    win is measured everywhere ``window_cc`` runs."""
    from dbscan_tpu import obs

    obs.count("prop.sweeps", int(sweeps))
    obs.gauge(
        "prop.mode",
        1.0 if (mode or prop_mode()) == "unionfind" else 0.0,
    )


def min_label_fixed_point(
    init: jnp.ndarray,
    neighbor_min: Callable[[jnp.ndarray], jnp.ndarray],
    pos_of_label: jnp.ndarray | None = None,
    with_iters: bool = False,
    mode: Optional[str] = None,
    scatter_relax: Optional[Callable] = None,
) -> jnp.ndarray:
    """Iterate ``labels -> min(labels, neighbor_min(labels), hop)`` to a fixed
    point.

    init: [N] int32 starting labels (row index on active rows, SEED_NONE
      elsewhere).
    neighbor_min: labels -> [N] int32 per-row min of neighbor labels
      (SEED_NONE where no neighbor qualifies).
    pos_of_label: optional [N] int32 mapping a LABEL VALUE to the array
      position that carries it — for engines whose label values are not array
      positions (the banded engine labels by original fold index while its
      arrays live in cell-sorted order). None means values ARE positions.
    with_iters: also return the number of neighbor-min sweeps the loop ran
      (an int32 scalar, data-dependent) — the convergence-depth figure the
      device cellcc finalize reports as ``cellcc.cc_iters`` and every
      consumer funnels into ``prop.sweeps``.
    mode: "unionfind" | "iterated" | None (resolve the knob at trace
      time). Builders that lru-cache their jits must resolve the mode
      BEFORE their cache key (cellcc/embed/halo do), since a traced
      function latches whatever mode it was traced under.
    scatter_relax: optional labels -> labels scatter-min push (the
      union-find edge relaxation's other direction); only invoked in
      unionfind mode. Consumers with an explicit edge/window table
      supply it (``window_cc``); pull-only consumers (dense adjacency)
      leave it None and still get the aggressive jumps.

    Each step runs one neighbor-min sweep (the expensive part) followed
    by the mode's pointer jumps — ``_COMPRESS_JUMPS`` chain-collapsing
    ``new[new]`` gathers on the iterated path, pull+push relaxation plus
    ``_UF_JUMPS`` jumps on the union-find path (see the constants).

    The loop is hard-capped at n iterations: labels strictly decrease while
    unconverged, so n steps always suffice — and the cap guarantees the
    on-device loop terminates even if a backend miscompiles the
    neighbor-min (an unbounded device loop wedges the whole chip for every
    client).
    """
    n = init.shape[0]
    none = jnp.int32(SEED_NONE)
    mode = prop_mode(mode)
    jumps = _UF_JUMPS if mode == "unionfind" else _COMPRESS_JUMPS

    def pos(labels):
        safe = jnp.clip(labels, 0, n - 1)
        return pos_of_label[safe] if pos_of_label is not None else safe

    def compress(labels):
        for _ in range(jumps):
            hop = jnp.where(labels == none, none, labels[pos(labels)])
            labels = jnp.minimum(labels, hop)
        return labels

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def body(state):
        labels, _, it = state
        new = jnp.minimum(labels, neighbor_min(labels))
        if mode == "unionfind" and scatter_relax is not None:
            # push the freshly pulled labels back along the edges
            # (scatter-min): with the pull above this makes each sweep a
            # two-hop relaxation — new already carries the pulled
            # minima, so the push forwards them another hop
            new = jnp.minimum(new, scatter_relax(new))
        new = compress(new)
        return new, jnp.any(new != labels), it + 1

    # One unrolled body step first: the while_loop carry must be
    # data-derived ("varying") for shard_map, and a constant True init is
    # not; semantically free since body is idempotent at the fixed point.
    state = body((init, jnp.bool_(True), jnp.int32(0)))
    labels, _, iters = lax.while_loop(cond, body, state)
    if with_iters:
        return labels, iters
    return labels


def window_cc(
    adj_mask: jnp.ndarray,
    neighbor_tab: jnp.ndarray,
    mode: Optional[str] = None,
    init: jnp.ndarray | None = None,
) -> tuple:
    """Connected components of a windowed adjacency table, on device.

    adj_mask: [N, W] bool — row i is adjacent to ``neighbor_tab[i, j]``
      where ``adj_mask[i, j]`` (the banded engine's per-cell OR of its
      core rows' 5x5-window bitmasks; callers must supply a SYMMETRIC
      relation — core-core eps-adjacency is, see ops/banded.py).
    neighbor_tab: [N, W] int32 neighbor index per window slot (junk at
      masked-off slots is fine; gathers are clipped, values masked).
    mode: propagation mode ("unionfind"/"iterated"/None = resolve the
      knob at trace time; cached builders pass it explicitly so their
      jit keys carry it).
    init: optional [N] int32 warm-start labels — already-relaxed
      partials (the fused Pallas unpack folds the FIRST sweep per
      chunk, ops/pallas_banded.py); the identity labels are min-merged
      in, so any monotone partial is a valid warm start and the fixed
      point is unchanged.

    Returns ``(comp [N] int32, iters int32)``: per-row component-minimum
    row index (the same component sets scipy's connected_components
    finds on the host — component NUMBERING differs, the min-index
    representative does not) and the sweep count. This is the shared CC
    kernel of the device cellcc finalize (cellgraph.finalize_device);
    streaming micro-batches and the embed buckets reuse it through the
    same driver paths.
    """
    n = adj_mask.shape[0]
    none = jnp.int32(SEED_NONE)
    tab = jnp.clip(neighbor_tab, 0, n - 1)
    mode = prop_mode(mode)

    def neighbor_min(labels):
        return jnp.min(jnp.where(adj_mask, labels[tab], none), axis=1)

    scatter_relax = None
    if mode == "unionfind":
        # masked-off slots scatter out of range and drop: the push is
        # exactly the edge set the pull reads, no phantom adjacency
        push_tab = jnp.where(adj_mask, tab, jnp.int32(n))

        def scatter_relax(labels):
            return labels.at[push_tab].min(
                jnp.broadcast_to(labels[:, None], push_tab.shape),
                mode="drop",
            )

    start = jnp.arange(n, dtype=jnp.int32)
    if init is not None:
        start = jnp.minimum(start, init)
    return min_label_fixed_point(
        start,
        neighbor_min,
        with_iters=True,
        mode=mode,
        scatter_relax=scatter_relax,
    )
