"""Sparse cosine DBSCAN: TF-IDF-style CSR input on the MXU.

The reference has no sparse support (its only metric is 2-D Euclidean,
DBSCANPoint.scala:26-30); this implements BASELINE.json configs[3]
("TF-IDF 20-Newsgroups sparse vectors") TPU-first:

1. only the nonzeros travel to the device — (row, col, val) triples sorted
   by feature column, sliced into feature blocks, padded to one static
   shape (tens of MB for ~2M nnz vs tens of GB densified);
2. a ``lax.scan`` over feature blocks scatter-densifies each [N, F_block]
   slab on device and accumulates the gram matrix with one MXU matmul per
   block — rows are L2-normalized on the host first, so the gram IS the
   cosine similarity;
3. cosine distance = 1 - gram; thresholding yields the [N, N] adjacency,
   and the shared engine tail (ops.local_dbscan.cluster_from_adjacency)
   produces labels/flags.

Memory is bounded by the largest gram, not by the vocabulary size: D
only affects how many feature blocks the scan walks. A single [N, N]
gram serves the 20-Newsgroups-scale config directly; past the
single-gram cap, ``max_points_per_partition`` routes the run through
metric spill partitioning (parallel/spill.py — CSR rows are unit
vectors, so pivot chords come from sparse-dense products) with per-leaf
grams bounded at the partition size and the driver's shared
instance-table merge.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dbscan_tpu.ops.labels import NOISE
from dbscan_tpu.ops.local_dbscan import LocalResult, cluster_from_adjacency

FEATURE_BLOCK = 4096


class _PackedCSR(NamedTuple):
    rows: np.ndarray  # [n_blocks, max_nnz] int32 row index per nnz
    cols: np.ndarray  # [n_blocks, max_nnz] int32 col index WITHIN its block
    vals: np.ndarray  # [n_blocks, max_nnz] f32; 0 on padding
    n_rows: int
    n_blocks: int


def _pack_csr(x_csr, feature_block: int) -> _PackedCSR:
    """Sort nnz by feature column and slice into equal-width feature blocks,
    padded to the max per-block nnz count (one static scan shape)."""
    coo = x_csr.tocoo()
    rows = np.asarray(coo.row, dtype=np.int64)
    cols = np.asarray(coo.col, dtype=np.int64)
    vals = np.asarray(coo.data, dtype=np.float32)
    n, d = x_csr.shape
    n_blocks = max(1, math.ceil(d / feature_block))

    order = np.argsort(cols, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    block_of = cols // feature_block
    starts = np.searchsorted(block_of, np.arange(n_blocks))
    ends = np.r_[starts[1:], len(cols)]
    max_nnz = int((ends - starts).max()) if len(cols) else 1
    # round the padded nnz width up a geometric ladder: the raw max is
    # data-dependent per call, and jax.jit keys on traced shapes — the
    # spill path grams hundreds of partitions, which would otherwise
    # recompile the scan kernel for nearly every one
    from dbscan_tpu.parallel.binning import _ladder_width

    max_nnz = _ladder_width(max_nnz, 128)
    # pad slot: row 0 / col 0 / val 0 — scatters +0.0, a no-op
    r = np.zeros((n_blocks, max_nnz), dtype=np.int32)
    c = np.zeros((n_blocks, max_nnz), dtype=np.int32)
    v = np.zeros((n_blocks, max_nnz), dtype=np.float32)
    for b in range(n_blocks):
        s, e = starts[b], ends[b]
        r[b, : e - s] = rows[s:e]
        c[b, : e - s] = cols[s:e] - b * feature_block
        v[b, : e - s] = vals[s:e]
    return _PackedCSR(r, c, v, n, n_blocks)


def _gram_scan(rows, cols, vals, n_rows: int, feature_block: int,
               varying_axes: tuple = ()):
    """Accumulate X @ X.T over feature blocks: scatter-densify each
    [N, F_block] slab, one MXU matmul per block. ``varying_axes``: set
    to the mesh axis names when tracing inside shard_map — the scan
    carry's zero init must be marked device-varying to match the
    varying inputs (jax >= 0.9 shard_map type discipline; a no-op on
    older jax, mesh.pvary)."""

    def step(gram, triple):
        r, c, v = triple
        slab = jnp.zeros((n_rows, feature_block), dtype=jnp.float32)
        slab = slab.at[r, c].add(v)
        gram = gram + jnp.dot(
            slab, slab.T, preferred_element_type=jnp.float32
        )
        return gram, None

    init = jnp.zeros((n_rows, n_rows), dtype=jnp.float32)
    if varying_axes:
        from dbscan_tpu.parallel import mesh as mesh_mod

        init = mesh_mod.pvary(init, tuple(varying_axes))
    gram, _ = jax.lax.scan(step, init, (rows, cols, vals))
    return gram


@functools.partial(jax.jit, static_argnames=("n_rows", "feature_block"))
def _gram_from_packed(rows, cols, vals, n_rows: int, feature_block: int):
    return _gram_scan(rows, cols, vals, n_rows, feature_block)


@functools.partial(jax.jit, donate_argnums=(0,))
def _stash(buf, vals, offset):
    """Write a leaf's padded result into the run-wide device accumulator
    at a TRACED offset: one compiled kernel per (buffer len, leaf width)
    pair — not per offset — and the buffer is donated, so the device
    keeps one copy. This is how per-leaf results coalesce into a single
    end-of-run pull instead of one ~0.5 s tunnel pull per leaf."""
    return jax.lax.dynamic_update_slice(buf, vals, (offset,))


def _padded_leaf(x, rows_p, w: int):
    """One leaf's CSR slice padded to its ladder width with zero rows
    (masked downstream). Shared by the sequential stash loop and the
    mesh batch dispatch: the two paths' bit-for-bit parity depends on
    packing IDENTICAL leaf matrices."""
    import scipy.sparse as sp

    xp = x[rows_p]
    if w > len(rows_p):
        xp = sp.vstack(
            [xp, sp.csr_matrix((w - len(rows_p), x.shape[1]))]
        ).tocsr()
    return xp


def _normalize_rows(x_csr):
    """(L2-normalized f64 CSR copy, row norms); zero-norm rows stay zero."""
    import scipy.sparse as sp

    x = sp.csr_matrix(x_csr, dtype=np.float64)
    norms = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
    inv = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-300), 0.0)
    return (sp.diags(inv) @ x).tocsr(), norms


def _gram_unit(x_unit_csr, feature_block: int) -> jnp.ndarray:
    """Gram of ALREADY-normalized rows (= cosine similarity), on device."""
    packed = _pack_csr(x_unit_csr, feature_block)
    return _gram_from_packed(
        jnp.asarray(packed.rows),
        jnp.asarray(packed.cols),
        jnp.asarray(packed.vals),
        packed.n_rows,
        feature_block,
    )


def sparse_cosine_gram(x_csr, feature_block: int = FEATURE_BLOCK) -> jnp.ndarray:
    """Cosine-similarity gram matrix of a scipy CSR matrix, on device.

    Rows are L2-normalized on the host (zero rows stay zero). Returns the
    [N, N] f32 similarity.
    """
    return _gram_unit(_normalize_rows(x_csr)[0], feature_block)


def _cluster_gram_body(
    gram, eps, mask, min_points: int, engine: str, mode: str = None
) -> LocalResult:
    n = gram.shape[0]
    dist = 1.0 - gram
    adj = dist <= eps
    adj = adj | jnp.eye(n, dtype=bool)  # self-inclusive regardless of eps
    adj = adj & (mask[None, :] & mask[:, None])  # padding rows inert
    return cluster_from_adjacency(adj, mask, min_points, engine, mode)


def _cluster_gram(gram, eps, mask, min_points: int, engine: str) -> LocalResult:
    # propagation mode resolved BEFORE the jit key (ops/propagation.py
    # contract for cached builders): an in-process knob flip re-traces
    from dbscan_tpu.ops.propagation import prop_mode

    return _cluster_gram_jit(gram, eps, mask, min_points, engine, prop_mode())


@functools.partial(jax.jit, static_argnames=("min_points", "engine", "mode"))
def _cluster_gram_jit(
    gram, eps, mask, min_points: int, engine: str, mode: str
) -> LocalResult:
    return _cluster_gram_body(gram, eps, mask, min_points, engine, mode)


def _compiled_leaf_batch(
    w: int, feature_block: int, min_points: int, engine: str, mesh
):
    from dbscan_tpu.ops.propagation import prop_mode

    return _compiled_leaf_batch_cached(
        w, feature_block, min_points, engine, mesh, prop_mode()
    )


@functools.lru_cache(maxsize=64)
def _compiled_leaf_batch_cached(
    w: int, feature_block: int, min_points: int, engine: str, mesh,
    mode: str,
):
    """Jitted mesh-sharded executor for a batch of SAME-WIDTH sparse
    leaves: [K, nb, mn] packed-CSR scan inputs -> per-leaf gram ->
    cluster, with the leaf axis sharded over the 'parts' mesh axis (one
    leaf per device per batch) — the sparse analog of the dense driver's
    _compiled_block (parallel/driver.py). Cached per (width, engine,
    mesh); jit re-specializes on the ladder-quantized nnz width."""
    from jax import lax
    from jax.sharding import PartitionSpec

    from dbscan_tpu.ops.labels import CORE
    from dbscan_tpu.parallel import mesh as mesh_mod

    axes = mesh_mod.parts_axes(mesh)

    def block(rows, cols, vals, mask, eps):
        def one(args):
            r, c, v, m = args
            gram = _gram_scan(
                r, c, v, w, feature_block, varying_axes=axes
            )
            res = _cluster_gram_body(gram, eps, m, min_points, engine, mode)
            return res.seed_labels, res.flags

        seeds, flags = lax.map(one, (rows, cols, vals, mask))
        # global core count all-reduce: keeps one real ICI collective in
        # the sparse production program, mirroring _compiled_block — so
        # multichip dryruns validate the communication path for sparse
        ncore = jnp.sum(flags == CORE, dtype=jnp.int32)
        ncore = lax.psum(ncore, axes)
        return seeds, flags, ncore

    assert mesh is not None  # only the multi-device dispatch builds this
    spec = mesh_mod.parts_spec(mesh)
    return jax.jit(
        mesh_mod.shard_map(
            block,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec, PartitionSpec()),
            out_specs=(spec, spec, PartitionSpec()),
        )
    )


def sparse_cosine_dbscan(
    x_csr,
    eps: float,
    min_points: int,
    engine: str = "archery",
    feature_block: int = FEATURE_BLOCK,
    max_points_per_partition: int = None,
    stats_out: dict = None,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """DBSCAN over sparse rows with cosine distance (1 - similarity) <= eps.

    Returns (clusters [N] int32 with 0 = noise, flags [N] int8) in the
    package's standard label conventions. Zero rows (empty documents) have
    similarity 0 to everything — they cluster only if eps >= 1.

    ``max_points_per_partition``, when set and exceeded by N, routes the
    run through metric spill partitioning (parallel/spill.py — the CSR
    rows ARE unit vectors, so pivot chords come from sparse-dense
    products): per-leaf grams bounded at the partition size instead of
    one [N, N] gram, merged by the driver's shared instance-table merge
    (parallel/driver.py::finalize_merge). This lifts the single-gram cap
    (~46k rows in 8 GiB) to arbitrary N for clusterable data.

    ``stats_out``, when given, is filled with run diagnostics
    (n_partitions, duplication_factor).
    """
    from dbscan_tpu.ops.labels import seed_to_local_ids

    x, norms = _normalize_rows(x_csr)
    n = x.shape[0]
    if max_points_per_partition is None or n <= max_points_per_partition:
        if stats_out is not None:
            stats_out.update(n_partitions=1, duplication_factor=1.0)
        gram = _gram_unit(x, feature_block)
        res: LocalResult = _cluster_gram(
            gram,
            jnp.float32(eps),
            jnp.ones(n, dtype=bool),
            min_points,
            engine,
        )
        clusters = seed_to_local_ids(np.asarray(res.seed_labels))
        return clusters, np.asarray(res.flags)

    # Zero-norm rows (empty documents, or all-explicit-zero rows) are
    # sim-0 to EVERYTHING: inside the spill partitioner each would be
    # equidistant (chord sqrt(2)) to all pivots and get copied into every
    # cell at every level, inflating duplication until nothing splits.
    # For eps < 1 they are deterministically noise — strip them before
    # partitioning and leave their output rows at (cluster 0, NOISE).
    nz_rows = np.flatnonzero(norms > 0)
    if eps < 1.0 and len(nz_rows) < n:
        clusters = np.zeros(n, dtype=np.int32)
        flags = np.full(n, NOISE, dtype=np.int8)
        if len(nz_rows):
            sub_c, sub_f = _spill_sparse(
                x[nz_rows], eps, min_points, engine, feature_block,
                max_points_per_partition, stats_out, mesh=mesh,
            )
            clusters[nz_rows] = sub_c
            flags[nz_rows] = sub_f
            if stats_out is not None and "duplication_factor" in stats_out:
                # sub-run stats describe the nonzero subset; rescale the
                # instance ratio to the full N (same convention as the
                # dense driver's zero-norm strip, parallel/driver.py)
                stats_out["duplication_factor"] = float(
                    stats_out["duplication_factor"] * len(nz_rows) / n
                )
        elif stats_out is not None:
            stats_out.update(n_partitions=0, duplication_factor=0.0)
        if stats_out is not None:
            stats_out["n_zero_norm_noise"] = int(n - len(nz_rows))
        return clusters, flags
    return _spill_sparse(
        x, eps, min_points, engine, feature_block,
        max_points_per_partition, stats_out, mesh=mesh,
    )


def _spill_sparse(
    x,
    eps: float,
    min_points: int,
    engine: str,
    feature_block: int,
    max_points_per_partition: int,
    stats_out: dict = None,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Spill-partitioned sparse cosine run over PRE-NORMALIZED rows.

    With a multi-device ``mesh``, same-width leaves dispatch in batches
    of mesh-size through the shard_map'd leaf-batch kernel (one leaf per
    device per batch) instead of the sequential stash loop — the sparse
    route's scale-out story, matching the dense driver's partition-axis
    sharding."""
    import scipy.sparse as sp

    from dbscan_tpu.parallel.binning import _ladder_width
    from dbscan_tpu.parallel.driver import _check_dense_width, finalize_merge
    from dbscan_tpu.parallel.spill import (
        band_membership,
        chord_halo,
        spill_partition,
    )

    import time as _time

    t_start = _time.perf_counter()
    n = x.shape[0]
    if n <= max_points_per_partition:
        # reachable via the zero-row strip shrinking N under the cap
        gram = _gram_unit(x, feature_block)
        res = _cluster_gram(
            gram, jnp.float32(eps), jnp.ones(n, dtype=bool), min_points,
            engine,
        )
        from dbscan_tpu.ops.labels import seed_to_local_ids

        if stats_out is not None:
            stats_out.update(n_partitions=1, duplication_factor=1.0)
        return (
            seed_to_local_ids(np.asarray(res.seed_labels)),
            np.asarray(res.flags),
        )

    # the gram's f32 scatter-accumulate rounds with the
    # nnz-per-feature-block count; 1e-4 covers blocks to ~2^14
    # accumulated terms with margin
    # the f32 chord error scales with the terms actually accumulated per
    # row-pair dot — bounded by the max row nnz, NOT the vocabulary width
    max_row_nnz = int(max(1, x.getnnz(axis=1).max())) if x.shape[0] else 1
    halo = chord_halo(eps, 1e-4, dim=max_row_nnz)
    spill_info: dict = {}
    part_ids, point_idx, n_parts, home_of = spill_partition(
        x.astype(np.float32), max_points_per_partition, halo,
        info_out=spill_info,
    )
    t_spill = _time.perf_counter()
    # leaf layout straight from the partitioner (partition-major
    # instances, counts per leaf) — no re-derivation; the ladder pad
    # below is the DISPATCH shape, applied once per leaf here
    counts = spill_info.get("counts")
    if counts is None:
        counts = np.bincount(part_ids, minlength=n_parts)
    offsets = np.r_[0, np.cumsum(counts)]
    widths = [_ladder_width(int(c), 128) for c in counts]
    if widths:
        _check_dense_width(max(widths), int(counts.max()))
    if stats_out is not None:
        stats_out.update(
            n_partitions=n_parts,
            duplication_factor=float(len(part_ids)) / max(1, n),
            spill_levels=int(spill_info.get("levels", 0)),
        )

    # Per-leaf gram+cluster dispatch with NO per-leaf pull: each leaf's
    # padded result is stashed into one run-wide device buffer
    # (dynamic_update_slice at a traced offset) and the host moves
    # straight to packing the next leaf. Everything comes back in ONE
    # pull at the end — the per-leaf np.asarray barrier this replaces
    # serialized host pack and device compute AND paid the tunnel's
    # ~0.5 s pull latency once per leaf. Leaf kernels keep their exact
    # ladder shapes (jit cache) and per-leaf iteration counts.
    slot_off = np.r_[0, np.cumsum(widths)].astype(np.int64)
    total = _ladder_width(int(slot_off[-1]), 128)
    max_b = max(widths)
    from dbscan_tpu.parallel.mesh import mesh_size as _mesh_size

    if mesh is not None and _mesh_size(mesh) > 1:
        # scale-out route: same-width leaves dispatch in mesh-size
        # batches through the shard_map'd leaf-batch kernel, one leaf
        # per device per batch (results pulled per batch)
        seeds_all, flags_all = _mesh_leaf_dispatch(
            x, point_idx, offsets, counts, widths, slot_off, total,
            eps, min_points, engine, feature_block, mesh,
        )
        t_leaves = _time.perf_counter()
        t_pull = t_leaves
    else:
        seed_buf = jnp.zeros(total, dtype=jnp.int32)
        flag_buf = jnp.zeros(total, dtype=jnp.int8)
        for p in range(n_parts):
            # instances are partition-major: O(1) slices, no per-leaf scan
            rows_p = point_idx[offsets[p] : offsets[p + 1]]
            w = widths[p]
            gram = _gram_unit(_padded_leaf(x, rows_p, w), feature_block)
            res = _cluster_gram(
                gram,
                jnp.float32(eps),
                jnp.arange(w) < len(rows_p),
                min_points,
                engine,
            )
            seed_buf = _stash(seed_buf, res.seed_labels, int(slot_off[p]))
            flag_buf = _stash(flag_buf, res.flags, int(slot_off[p]))
        t_leaves = _time.perf_counter()

        # the single pull, then reassembly in partition-major instance
        # order for the shared merge (each leaf's true size is counts[p])
        seeds_all = np.asarray(seed_buf)
        flags_all = np.asarray(flag_buf)
        t_pull = _time.perf_counter()
    inst_seed = np.concatenate(
        [
            seeds_all[slot_off[p] : slot_off[p] + counts[p]]
            for p in range(n_parts)
        ]
    )
    inst_flag = np.concatenate(
        [
            flags_all[slot_off[p] : slot_off[p] + counts[p]]
            for p in range(n_parts)
        ]
    )
    cand, inst_inner = band_membership(part_ids, point_idx, home_of, n)
    # canonical ids (min-member-row numbering): the spill layout depends
    # on pivot choice, so rank-ordered gids would differ between equally
    # valid trees — canonical numbering makes the labels a function of
    # the DATA alone (finalize_merge docstring)
    clusters, flags, _ = finalize_merge(
        part_ids, point_idx, inst_seed, inst_flag, cand, inst_inner,
        n, n_parts, max_b, canonical=True, mesh=mesh,
    )
    if stats_out is not None:
        # phase split in the driver's timings idiom: where the wall goes
        # (spill tree / host gram packing + leaf dispatch / the single
        # result pull / host merge) so a slow row is attributable
        stats_out["timings"] = {
            "spill_partition_s": round(t_spill - t_start, 6),
            "leaf_pack_dispatch_s": round(t_leaves - t_spill, 6),
            "pull_s": round(t_pull - t_leaves, 6),
            "merge_s": round(_time.perf_counter() - t_pull, 6),
            "total_s": round(_time.perf_counter() - t_start, 6),
        }
    return clusters, flags


def _mesh_leaf_dispatch(
    x, point_idx, offsets, counts, widths, slot_off, total,
    eps, min_points, engine, feature_block, mesh,
):
    """Pack and dispatch same-width leaves in mesh-size batches through
    :func:`_compiled_leaf_batch`; returns slot-packed host seed/flag
    arrays (the same layout the sequential stash loop produces).

    Dispatch is ASYNC: every batch is enqueued before any result is
    pulled, so host packing of batch i+1 overlaps device compute of
    batch i (the property the sequential path's device stash exists
    for) and the pulls at the end see already-finished work. With the
    pull engine live (parallel/pipeline.py; single-process only — these
    pulls are collectives under a multi-process mesh) each batch's pull
    + slot scatter additionally runs on the worker WHILE later batches
    are still being packed, instead of all serially at the end."""
    from collections import defaultdict

    from dbscan_tpu.parallel import mesh as mesh_mod
    from dbscan_tpu.parallel import pipeline as pipe_mod

    m = mesh_mod.mesh_size(mesh)
    seeds_all = np.zeros(total, dtype=np.int32)
    flags_all = np.zeros(total, dtype=np.int8)
    by_w = defaultdict(list)
    for p, w in enumerate(widths):
        by_w[int(w)].append(p)
    # replicated scalar, NOT a locally-committed jnp value: in a multi-
    # process mesh the batch inputs are global arrays, and a device-
    # committed eps would clash at jit time (see mesh.replicate_host_array)
    ej = mesh_mod.replicate_host_array(np.float32(eps))
    pull_pipe = pipe_mod.get_engine()

    def _land(batch, w, seeds_dev, flags_dev):
        """Pull one leaf batch and scatter it into its slots (disjoint
        across batches, so worker-side writes never race)."""
        seeds = mesh_mod.pull_to_host(seeds_dev)
        flags = mesh_mod.pull_to_host(flags_dev)
        for i, p in enumerate(batch):
            seeds_all[slot_off[p] : slot_off[p] + w] = seeds[i]
            flags_all[slot_off[p] : slot_off[p] + w] = flags[i]

    jobs = []
    inflight = []  # (batch leaf ids, width, seeds_dev, flags_dev)
    for w, plist in sorted(by_w.items()):
        fn = _compiled_leaf_batch(w, feature_block, min_points, engine, mesh)
        for s0 in range(0, len(plist), m):
            batch = plist[s0 : s0 + m]
            packs, masks = [], []
            for p in batch:
                rows_p = point_idx[offsets[p] : offsets[p + 1]]
                packs.append(
                    _pack_csr(_padded_leaf(x, rows_p, w), feature_block)
                )
                masks.append(np.arange(w) < len(rows_p))
            nb = packs[0].n_blocks
            mn = max(pk.rows.shape[1] for pk in packs)
            # short batches pad with empty leaves (all-False mask) so the
            # leading axis always equals the mesh size — one jit shape
            rows_b = np.zeros((m, nb, mn), dtype=np.int32)
            cols_b = np.zeros((m, nb, mn), dtype=np.int32)
            vals_b = np.zeros((m, nb, mn), dtype=np.float32)
            mask_b = np.zeros((m, w), dtype=bool)
            for i, pk in enumerate(packs):
                rows_b[i, :, : pk.rows.shape[1]] = pk.rows
                cols_b[i, :, : pk.cols.shape[1]] = pk.cols
                vals_b[i, :, : pk.vals.shape[1]] = pk.vals
                mask_b[i] = masks[i]
            seeds_dev, flags_dev, _ = fn(
                mesh_mod.shard_host_array(mesh, rows_b),
                mesh_mod.shard_host_array(mesh, cols_b),
                mesh_mod.shard_host_array(mesh, vals_b),
                mesh_mod.shard_host_array(mesh, mask_b),
                ej,
            )
            if pull_pipe is not None:
                jobs.append(
                    (
                        pull_pipe.submit(
                            functools.partial(
                                _land, batch, w, seeds_dev, flags_dev
                            ),
                            bytes_hint=int(
                                getattr(seeds_dev, "nbytes", 0)
                            )
                            + int(getattr(flags_dev, "nbytes", 0)),
                            label=f"leafbatch{len(jobs)}",
                        ),
                        (batch, w, seeds_dev, flags_dev),
                    )
                )
            else:
                inflight.append((batch, w, seeds_dev, flags_dev))
    for job, args in jobs:
        # settle = wait + brake-on-fault + serial _land for a job a
        # concurrent abort cancelled (its buffers are untouched)
        pull_pipe.settle(job, functools.partial(_land, *args))
    for batch, w, seeds_dev, flags_dev in inflight:
        _land(batch, w, seeds_dev, flags_dev)
    return seeds_all, flags_all
